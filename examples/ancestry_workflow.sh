#!/usr/bin/env bash
# End-to-end walkthrough of the framework's cohort workflow, runnable
# anywhere the package is installed (CPU or TPU; a few minutes on CPU).
#
#   1. simulate a reference panel and a new cohort at shared sites
#   2. per-sample and cross-cohort QC (sample-stats, cross-kinship)
#   3. one-pass ETL into the 2-bit packed store with QC + LD pruning
#   4. fit PCoA on the panel, persist the embedding model
#   5. project the new cohort into the panel's coordinate space
#
# Every step prints what it produced; all outputs land in ./workflow_out.
set -euo pipefail

RUN="python -m spark_examples_tpu"
OUT=workflow_out
mkdir -p "$OUT"

echo "== 1. simulate cohorts (shared variant set) =="
python - "$OUT" <<'EOF'
import sys

import numpy as np

from spark_examples_tpu.ingest.plink import write_plink

out = sys.argv[1]
rng = np.random.default_rng(0)
n_panel, n_new, v, pops = 120, 12, 20_000, 3
labels = rng.integers(0, pops, n_panel + n_new)
p = (0.05 + 0.9 * rng.random((pops, v)))[labels]
g = ((rng.random((len(labels), v)) < p).astype(np.int8)
     + (rng.random((len(labels), v)) < p).astype(np.int8))
g[rng.random(g.shape) < 0.01] = -1
write_plink(f"{out}/panel", g[:n_panel])
write_plink(f"{out}/newcohort", g[n_panel:])
np.save(f"{out}/labels.npy", labels)
print(f"panel {n_panel} samples, new cohort {n_new}, {v} shared variants")
EOF

echo "== 2a. per-sample QC =="
$RUN sample-stats --source plink --path "$OUT/panel" \
    --output-path "$OUT/panel_sample_stats.tsv" | head -3

echo "== 2b. cross-cohort relatedness screen =="
$RUN cross-kinship --source plink --path "$OUT/newcohort" \
    --ref-source plink --ref-path "$OUT/panel" \
    --output-path "$OUT/cross_phi.tsv" | head -3

echo "== 3. ETL: QC + LD-prune the panel into a packed store =="
$RUN pack --source plink --path "$OUT/panel" \
    --maf 0.01 --max-missing 0.1 --ld-prune-r2 0.5 \
    --output-path "$OUT/panel_store"

echo "== 4. fit PCoA on the QC+pruned panel store (panel-only coords) =="
$RUN pcoa --source packed --path "$OUT/panel_store" --num-pc 4 \
    --output-path "$OUT/panel_coords.tsv" | head -2

echo "== 5. fit a projectable model + project the new cohort. The model"
echo "      and the projection must see the SAME variant set, so the"
echo "      projectable fit runs on the unpruned panel (on real data you"
echo "      would subset the new cohort to the store's kept sites and"
echo "      fit/project on that store instead) =="
$RUN pcoa --source plink --path "$OUT/panel" --num-pc 4 \
    --save-model "$OUT/panel_model.npz" \
    --output-path "$OUT/panel_coords_full.tsv" | head -2
$RUN project --source plink --path "$OUT/newcohort" \
    --ref-source plink --ref-path "$OUT/panel" \
    --model "$OUT/panel_model.npz" \
    --output-path "$OUT/new_coords.tsv" | head -2

echo "== done; outputs in $OUT =="
ls "$OUT"

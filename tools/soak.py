"""Chaos soak: a seeded randomized fault schedule over every subsystem.

Single-fault tests prove each recovery path once; the soak proves they
*compose*: N iterations, each arming one randomized fault spec
(site x kind x after/max/params drawn from a seeded RNG) against a real
job — the streamed gram pipeline over a store-backed source (retry,
readahead, heal, checkpoint sites) or the projection server (request
faults) — and checking the invariants after every round:

- **Bit-identity.** The round's result equals the clean baseline
  exactly (integer accumulations: there is no tolerance to hide
  behind). For serve ``io_error`` rounds, exactly the injected
  requests fail — explicitly, with the injected error — and every
  other response is bit-identical.
- **No deadlock.** The round completes inside a watchdog budget
  (supervised subprocess rounds inherit the real watchdog;
  in-process rounds are wall-clock-checked).
- **No leaks.** Every pool/worker thread the round started is gone
  again afterwards (readahead pools, serve workers, heartbeats), and
  the decode cache sits within its byte bound.
- **Consistent heal bookkeeping.** A round that corrupted a chunk on
  disk must leave the store healed: ``store.healed`` advanced and the
  quarantine ledger empty (the soak's store records its origin, so
  every corruption is repairable).

Any violation emits ONE repro line —
``SOAK-REPRO seed=<s> iter=<i> spec=<site:kind:...> job=<kind>`` —
which re-runs that exact round deterministically.

``include_kill`` adds supervised subprocess rounds: the same job run
via the CLI under ``--supervise`` with an injected ``kill`` at a
randomized block, restarting from checkpoints; the output file must
equal the clean run's bytes.

Entry points: ``run_soak`` (library), ``bench.py --chaos-soak``
(25 fixed-seed iterations in the bench headline), and the tier-1
``soak``-marked smoke in tests/test_soak.py (in-process scenarios
only, seconds not minutes).
"""

from __future__ import annotations

import gc
import json
import os
import random
import subprocess
import sys
import threading
import time
import warnings
from dataclasses import dataclass, field

import numpy as np

from spark_examples_tpu.core import faults, telemetry
from spark_examples_tpu.core.config import (
    ComputeConfig,
    IngestConfig,
    JobConfig,
)
from spark_examples_tpu.pipelines import runner
from spark_examples_tpu.store import quarantine as qledger
from spark_examples_tpu.store.heal import origin_from_ingest
from spark_examples_tpu.store.writer import compact

# Thread-name prefixes the leak accounting covers: any of these still
# alive after a round (and a GC + settle window) is a leak. This table
# is also the graftlint thread-hygiene contract — EVERY named thread in
# the production tree carries one of these prefixes, so a new thread
# family that can leak must add itself here to pass tier-1.
_SUSPECT_THREADS = ("store-readahead", "projection-serve-worker",
                    "fleet-serve-worker", "fleet-controller",
                    "supervisor-heartbeat", "telemetry-flusher",
                    "prefetch-producer", "partitioned-reader",
                    "projection-http", "live-telemetry-http",
                    "supervisor-live-proxy", "loadgen-client",
                    "fleet-metrics-http")

# The in-process schedule: (job, site, kind, param ranges). `after` is
# drawn per-round from its range so the fault lands at a different hit
# each time; `max` bounds fires under the job's retry budget so the
# documented contract is full recovery.
SCENARIOS: tuple = (
    ("gram", "ingest.block_read", "io_error",
     dict(after=(0, 6), max=(1, 2))),
    ("gram", "ingest.block_read", "delay",
     dict(after=(0, 6), max=(1, 3), delay=0.01)),
    ("gram", "store.read", "io_error", dict(after=(0, 3), max=(1, 2))),
    # On-disk corruption: the chunk is truncated against its content
    # address and must be HEALED from the recorded origin, in place,
    # with the stream completing bit-identically.
    ("gram", "store.read", "truncate", dict(after=(0, 3), max=(1, 1),
                                            keep=8)),
    ("gram", "store.readahead.decode", "io_error",
     dict(after=(0, 2), max=(1, 1))),
    # Same site, DENSE transport: the readahead warm runs the native
    # decode-to-slab entry (inflate + unpack of the compressed chunk
    # in one C call — store/codec.py), so the held-and-re-raised error
    # contract is proven on the native path, not just the Python one.
    ("gram-dense", "store.readahead.decode", "io_error",
     dict(after=(0, 2), max=(1, 1))),
    ("gram", "device.put", "delay", dict(after=(0, 6), max=(1, 2),
                                         delay=0.01)),
    ("gram", "multihost.consensus", "delay",
     dict(after=(0, 2), max=(1, 2), delay=0.01)),
    ("gram", "checkpoint.tile_write", "truncate",
     dict(after=(0, 7), max=(1, 1), keep=8)),
    # Neighbor rounds: the combined minhash+exact-eval job over the
    # store source; an io_error at the candidate-evaluation site is
    # recomputed wholesale inside the retry boundary, so the sparse
    # top-k must come out bit-identical to the clean baseline.
    ("neighbors", "neighbors.candidates", "io_error",
     dict(after=(0, 6), max=(1, 2))),
    ("serve", "serve.request", "io_error", dict(after=(0, 5), max=(1, 1))),
    ("serve", "serve.request", "delay", dict(after=(0, 5), max=(1, 2),
                                             delay=0.02)),
    # Fleet rounds: a 2-route fleet under a one-panel budget, so the
    # round-robin traffic churns LRU eviction + re-stage through the
    # fleet.stage site — an io_error fails exactly the requests
    # waiting on that stage (the rest stay bit-identical), a delay is
    # a slow cold tier (latency, never correctness).
    ("fleet", "fleet.stage", "io_error", dict(after=(0, 4), max=(1, 2))),
    ("fleet", "fleet.stage", "delay", dict(after=(0, 4), max=(1, 2),
                                           delay=0.01)),
    # Shard-staged fleet rounds: ONE route whose panel exceeds the pool
    # budget, so every request streams the panel as a multi-shard
    # sequence through the same fleet.stage site (after >= 1 lands the
    # fault MID-panel, between shards). An io_error fails exactly its
    # own request — explicitly — a delay is pure latency, and after the
    # armed window closes a full post-heal sweep must be bit-identical
    # to the warm-pool fleet baseline (sharding is an accounting
    # strategy, never an answer change).
    ("fleet-sharded", "fleet.stage", "io_error",
     dict(after=(1, 4), max=(1, 2))),
    ("fleet-sharded", "fleet.stage", "delay",
     dict(after=(1, 4), max=(1, 2), delay=0.01)),
    # Every gram round runs a periodic live-telemetry flusher; a flush
    # that fails must be absorbed (warned + counted) with the job —
    # and every published snapshot — intact.
    ("gram", "telemetry.flush", "io_error", dict(after=(0, 8), max=(1, 2))),
    # Controller rounds (fleet/controller.py): a 2-replica fleet under
    # the control loop, each round ALSO running the deterministic
    # chaos sequence (replica kill mid-hedged-burst -> respawn within
    # the backoff budget with zero admitted requests lost, then a
    # preemption storm draining every replica in turn) plus the armed
    # site: a scrape blackhole (last-good-marked-stale until the slot
    # is declared lost), a spawn-failure cascade (backoff, never a
    # spawn loop), or a stage failure while a respawned replica warms
    # its assigned panels. Bit-identity of served coordinates is
    # pinned across every recovery.
    ("controller", "controller.scrape", "io_error",
     dict(after=(0, 2), max=(1, 2))),
    ("controller", "controller.spawn", "io_error",
     dict(after=(0, 1), max=(1, 1))),
    ("controller", "fleet.stage", "io_error",
     dict(after=(0, 2), max=(1, 1))),
    # The flight tape under fire: the controller's timeline ring
    # (fleet/timeline.py) takes the armed trace.export fault on its
    # appends/compactions — an io_error is absorbed (counted, never
    # killing the control loop), a truncate tears the ring's tail
    # mid-line and read_timeline must still return every complete
    # record before it (the last-good-tape contract).
    ("controller", "trace.export", "io_error",
     dict(after=(0, 3), max=(1, 2))),
    ("controller", "trace.export", "truncate",
     dict(after=(0, 3), max=(1, 1), keep=8)),
)

KILL_SCENARIOS: tuple = (
    ("cli", "ingest.block_read", "kill", dict(after=(2, 6), max=(1, 1))),
    ("cli", "store.read", "kill", dict(after=(1, 3), max=(1, 1))),
    # Kill MID-FLUSH: the tmp+rename protocol must leave the last-good
    # snapshot readable (checked by _snapshots_readable after every
    # supervised round), and the restarted attempt completes
    # bit-identically.
    ("cli", "telemetry.flush", "kill", dict(after=(1, 4), max=(1, 1))),
)


@dataclass
class SoakConfig:
    workdir: str
    iterations: int = 25
    seed: int = 0
    include_kill: bool = True
    n_samples: int = 16
    n_variants: int = 1024
    chunk_variants: int = 256
    block_variants: int = 128
    round_budget_s: float = 60.0  # in-process deadlock watchdog
    kill_budget_s: float = 300.0  # supervised subprocess rounds


@dataclass
class SoakReport:
    iterations: int = 0
    rounds: list = field(default_factory=list)
    violations: list = field(default_factory=list)
    healed: int = 0
    retries: int = 0
    restarts: int = 0
    faults_fired: int = 0

    @property
    def ok(self) -> bool:
        return not self.violations

    def to_json(self) -> dict:
        return {
            "iterations": self.iterations,
            "ok": self.ok,
            "violations": self.violations,
            "healed": self.healed,
            "retries": self.retries,
            "restarts": self.restarts,
            "faults_fired": self.faults_fired,
            "rounds": self.rounds,
        }


def _spec_str(site: str, kind: str, rng: random.Random,
              params: dict) -> str:
    """One randomized spec drawn from the scenario's ranges."""
    after = rng.randint(*params["after"])
    max_fires = rng.randint(*params["max"])
    spec = f"{site}:{kind}:after={after}:max={max_fires}"
    if "delay" in params:
        spec += f":delay={params['delay']}"
    if "keep" in params:
        spec += f":keep={params['keep']}"
    return spec


def _suspect_counts() -> dict[str, int]:
    counts: dict[str, int] = {}
    for t in threading.enumerate():
        if not t.is_alive():
            continue
        for prefix in _SUSPECT_THREADS:
            if t.name.startswith(prefix):
                counts[prefix] = counts.get(prefix, 0) + 1
    return counts


def _leaked_threads(baseline: dict[str, int],
                    settle_s: float = 5.0) -> list[str]:
    """Suspect-thread prefixes whose live count exceeds the fixture
    baseline after a settle window (pool threads wind down
    asynchronously after their executor is released — poll, don't
    snapshot). The baseline covers long-lived fixture plumbing (the
    serve engine's panel source); a round must not ADD to it."""
    deadline = time.monotonic() + settle_s
    while True:
        gc.collect()
        over = [f"{k} x{v} (baseline {baseline.get(k, 0)})"
                for k, v in _suspect_counts().items()
                if v > baseline.get(k, 0)]
        if not over or time.monotonic() > deadline:
            return over
        time.sleep(0.05)


class _Fixture:
    """Everything the rounds share: the origin-recorded store, the
    clean baselines, and (for serve rounds) a warmed engine."""

    def __init__(self, cfg: SoakConfig):
        self.cfg = cfg
        self.store_dir = os.path.join(cfg.workdir, "store")
        self.ingest_cfg = IngestConfig(
            source="synthetic", n_samples=cfg.n_samples,
            n_variants=cfg.n_variants, seed=7,
            block_variants=cfg.block_variants,
        )
        src = runner.build_source(self.ingest_cfg)
        # Default codec (zlib): every store round in the soak exercises
        # compressed chunks — incl. truncate -> origin-heal, which must
        # re-compress byte-identically to clear the ledger.
        compact(self.store_dir, src, chunk_variants=cfg.chunk_variants,
                origin=origin_from_ingest(self.ingest_cfg,
                                          cfg.chunk_variants))
        # Clean gram baselines over the store transport (the exact jobs
        # the rounds run, no faults armed): packed (ibs) and dense
        # (dot — the transport whose readahead warms run the native
        # decode-to-slab entry).
        faults.disarm()
        self.baseline_sim = self._gram_job(None).similarity
        self.baseline_sim_dense = self._gram_job(None,
                                                 metric="dot").similarity
        # Clean neighbors baseline (minhash + LSH + exact sparse eval
        # over the same store transport the faulted rounds run).
        nb = self._neighbors_job()
        self.baseline_neighbors = (nb.ids.copy(), nb.sims.copy())
        # Serve fixture: model fit over the same panel + warmed engine.
        from spark_examples_tpu.pipelines.jobs import pcoa_job
        from spark_examples_tpu.serve import ProjectionEngine

        self.model_path = os.path.join(cfg.workdir, "model.npz")
        fit_job = JobConfig(
            ingest=IngestConfig(block_variants=cfg.block_variants),
            compute=ComputeConfig(metric="ibs", num_pc=3),
            model_path=self.model_path,
        )
        panel = runner.build_source(
            IngestConfig(source="store", path=self.store_dir,
                         block_variants=cfg.block_variants))
        pcoa_job(fit_job, source=panel)
        self._close_source(panel)
        # Panel staged without readahead: the engine keeps its source
        # (for restage), and a fixture-lifetime pool would sit in every
        # round's thread accounting.
        self.engine = ProjectionEngine(
            self.model_path,
            runner.build_source(
                IngestConfig(source="store", path=self.store_dir,
                             block_variants=cfg.block_variants,
                             readahead_chunks=0)),
            block_variants=cfg.block_variants, max_batch=4)
        pool_rng = np.random.default_rng(11)
        self.query_pool = pool_rng.integers(
            0, 3, size=(6, cfg.n_variants)).astype(np.int8)
        self.baseline_coords = [
            self.engine.project_batch(q[None, :])
            for q in self.query_pool
        ]
        # Fleet fixture: a SECOND model (PCA) on the same store panel,
        # plus clean per-route baselines from an unfaulted fleet — the
        # fleet rounds churn eviction/re-stage between the two routes
        # under a one-panel budget.
        from spark_examples_tpu.pipelines.jobs import variants_pca_job

        self.pca_model_path = os.path.join(cfg.workdir, "model_pca.npz")
        pca_panel = runner.build_source(
            IngestConfig(source="store", path=self.store_dir,
                         block_variants=cfg.block_variants))
        variants_pca_job(
            JobConfig(
                ingest=IngestConfig(block_variants=cfg.block_variants),
                compute=ComputeConfig(num_pc=3),
                model_path=self.pca_model_path,
            ),
            source=pca_panel)
        self._close_source(pca_panel)
        self.fleet_baseline: dict[str, list] = {}
        fleet = self.make_fleet()
        try:
            fleet.start()
            for route in ("ibs", "pca"):
                self.fleet_baseline[route] = [
                    fleet.project(route, q, timeout=60.0)
                    for q in self.query_pool
                ]
        finally:
            fleet.close()
        self.thread_baseline = _suspect_counts()

    def make_fleet(self):
        """A fresh 2-route fleet over the soak store: budget sized for
        ONE staged panel, so alternating-route traffic must evict and
        re-stage through fleet.stage every switch."""
        from spark_examples_tpu.core.config import ServeConfig
        from spark_examples_tpu.serve import FleetManifest, build_fleet

        panel_bytes = self.cfg.n_samples * self.cfg.n_variants
        manifest = FleetManifest.parse({
            "budget_mb": panel_bytes * 1.5 / 1e6,
            "routes": [
                {"name": "ibs", "model": self.model_path,
                 "source": f"store:{self.store_dir}"},
                {"name": "pca", "model": self.pca_model_path,
                 "source": f"store:{self.store_dir}"},
            ],
        })
        return build_fleet(
            manifest, ServeConfig(cache_entries=0),
            ingest_defaults=IngestConfig(
                block_variants=self.cfg.block_variants,
                readahead_chunks=2, store_cache_mb=4),
        )

    def make_sharded_fleet(self):
        """A fresh 1-route fleet whose panel EXCEEDS the pool budget
        (budget = 0.4 panels), so every request serves shard-staged:
        ~3 budget-sized shards streamed from the store per request
        through the fleet.stage site, transient pool charges only."""
        from spark_examples_tpu.core.config import ServeConfig
        from spark_examples_tpu.serve import FleetManifest, build_fleet

        panel_bytes = self.cfg.n_samples * self.cfg.n_variants
        manifest = FleetManifest.parse({
            "budget_mb": panel_bytes * 0.4 / 1e6,
            "routes": [
                {"name": "ibs", "model": self.model_path,
                 "source": f"store:{self.store_dir}"},
            ],
        })
        return build_fleet(
            manifest, ServeConfig(cache_entries=0),
            ingest_defaults=IngestConfig(
                block_variants=self.cfg.block_variants,
                readahead_chunks=2, store_cache_mb=4),
        )

    @staticmethod
    def _close_source(src) -> None:
        for obj in (src, getattr(src, "inner", None)):
            close = getattr(obj, "close", None)
            if close is not None:
                close()

    def _gram_job(self, ckpt_dir: str | None, metric: str = "ibs"):
        job = JobConfig(
            ingest=IngestConfig(
                source="store", path=self.store_dir,
                block_variants=self.cfg.block_variants,
                io_retries=3, io_retry_backoff_s=0.001,
                readahead_chunks=2, store_cache_mb=4,
            ),
            compute=ComputeConfig(
                metric=metric, checkpoint_dir=ckpt_dir,
                checkpoint_every_blocks=2 if ckpt_dir else 0,
            ),
        )
        src = runner.build_source(job.ingest)
        try:
            return runner.run_similarity(job, source=src)
        finally:
            self._close_source(src)

    def _neighbors_job(self):
        from spark_examples_tpu.neighbors.engine import neighbors_job

        job = JobConfig(
            ingest=IngestConfig(
                source="store", path=self.store_dir,
                block_variants=self.cfg.block_variants,
                io_retries=3, io_retry_backoff_s=0.001,
                readahead_chunks=2, store_cache_mb=4,
            ),
            compute=ComputeConfig(metric="ibs", minhash_hashes=32,
                                  minhash_bands=8, neighbors_k=5),
        )
        src = runner.build_source(job.ingest)
        try:
            return neighbors_job(job, source=src)
        finally:
            self._close_source(src)

    def store_consistent(self) -> str | None:
        """Post-round store invariant: quarantine ledger empty and
        every chunk file byte-verifiable. A reason string on violation."""
        entries = qledger.load(self.store_dir)
        if entries:
            return (f"quarantine ledger not empty after the round "
                    f"({len(entries)} entries — heal should have "
                    "cleared them)")
        from spark_examples_tpu.core import hashing
        from spark_examples_tpu.store.manifest import StoreManifest

        manifest = StoreManifest.load(self.store_dir)
        for rec in manifest.chunks:
            path = os.path.join(self.store_dir, rec.filename())
            try:
                if hashing.sha256_file(path) != rec.digest:
                    return f"chunk {rec.digest[:16]}... corrupt on disk"
            except OSError as e:
                return f"chunk {rec.digest[:16]}... unreadable ({e})"
        return None


def _snapshots_readable(tel_dir: str) -> str | None:
    """Post-round live-snapshot invariant: every published
    metrics.json / live_trace.jsonl under the round's telemetry dir
    must parse — a flush that failed or a kill mid-write must have
    left the LAST-GOOD file, never a torn one. A reason on violation."""
    for root, _dirs, files in os.walk(tel_dir):
        for name in files:
            path = os.path.join(root, name)
            try:
                if name == "metrics.json":
                    with open(path) as f:
                        json.load(f)
                elif name.endswith(".jsonl"):
                    with open(path) as f:
                        for line in f:
                            if line.strip():
                                json.loads(line)
            except (OSError, ValueError) as e:
                return (f"published snapshot {os.path.relpath(path, tel_dir)}"
                        f" is not readable ({e}) — the atomic-write "
                        "contract is broken")
    return None


def _run_gram_round(fx: _Fixture, i: int, spec: str,
                    round_seed: int, metric: str = "ibs") -> list[str]:
    """One in-process gram round under `spec`, with the periodic
    live-telemetry flusher publishing snapshots throughout (the
    telemetry.flush site fires inside it); returns violations."""
    problems: list[str] = []
    ckpt = os.path.join(fx.cfg.workdir, f"ck{i}")
    tel = os.path.join(fx.cfg.workdir, f"ltel{i}")
    flusher = telemetry.PeriodicFlusher(tel, interval_s=0.02)
    with faults.armed([spec], seed=round_seed):
        with warnings.catch_warnings():
            warnings.simplefilter("ignore", RuntimeWarning)
            flusher.start()
            try:
                res = fx._gram_job(ckpt, metric=metric)
            finally:
                flusher.stop()
    baseline = (fx.baseline_sim_dense if metric == "dot"
                else fx.baseline_sim)
    if not np.array_equal(res.similarity, baseline):
        problems.append("gram result differs from clean baseline")
    reason = _snapshots_readable(tel)
    if reason:
        problems.append(reason)
    return problems


def _run_neighbors_round(fx: _Fixture, spec: str,
                         round_seed: int) -> list[str]:
    """One in-process neighbors round under `spec`: the injected
    io_error in the candidate-evaluation loop is retried by recomputing
    the block wholesale, so the sparse top-k (ids AND similarities)
    must equal the clean baseline exactly."""
    problems: list[str] = []
    with faults.armed([spec], seed=round_seed):
        with warnings.catch_warnings():
            warnings.simplefilter("ignore", RuntimeWarning)
            res = fx._neighbors_job()
    ids0, sims0 = fx.baseline_neighbors
    if not np.array_equal(res.ids, ids0):
        problems.append("neighbor ids differ from clean baseline")
    if not np.array_equal(res.sims, sims0):
        problems.append("neighbor similarities differ from clean "
                        "baseline")
    return problems


def _run_serve_round(fx: _Fixture, spec: str,
                     round_seed: int) -> list[str]:
    """One in-process serve round: sequential queries through a fresh
    server over the shared engine. Injected io_errors must fail exactly
    their own request with the injected error; everything else must be
    bit-identical; the drain must be clean."""
    from spark_examples_tpu.serve import ProjectionServer

    problems: list[str] = []
    server = ProjectionServer(fx.engine, cache_entries=0,
                              max_linger_s=0.001).start()
    injected = 0
    try:
        with faults.armed([spec], seed=round_seed) as inj:
            for qi, q in enumerate(fx.query_pool):
                try:
                    got = server.project(q, timeout=30.0)
                except faults.InjectedFault:
                    injected += 1
                    continue
                if not np.array_equal(got, fx.baseline_coords[qi]):
                    problems.append(
                        f"served coords for query {qi} differ from "
                        "baseline")
            fired = inj.fire_count("serve.request")
        if injected != (fired if "io_error" in spec else 0):
            problems.append(
                f"{injected} requests failed with the injected error "
                f"but {fired} io_error fault(s) fired")
        if not server.drain(timeout=30.0):
            problems.append("serve drain was not clean")
    finally:
        server.close()
    return problems


def _run_fleet_round(fx: _Fixture, spec: str,
                     round_seed: int) -> list[str]:
    """One in-process fleet round: a fresh 2-route fleet under a
    one-panel budget, alternating-route traffic so every route switch
    is an eviction + fleet.stage re-stage. Injected stage io_errors
    must fail exactly their own waiting request (explicitly — either
    the injected error, or PanelUnavailable if they tripped the route
    breaker); every other answer must be bit-identical to the clean
    fleet baseline; the drain must be clean."""
    from spark_examples_tpu.serve import PanelUnavailable

    problems: list[str] = []
    fleet = fx.make_fleet()
    injected = 0
    try:
        fleet.start()
        with faults.armed([spec], seed=round_seed) as inj:
            with warnings.catch_warnings():
                warnings.simplefilter("ignore", RuntimeWarning)
                for _sweep in range(2):
                    for route in ("ibs", "pca"):
                        for qi, q in enumerate(fx.query_pool):
                            try:
                                got = fleet.project(route, q,
                                                    timeout=30.0)
                            except (faults.InjectedFault,
                                    PanelUnavailable):
                                injected += 1
                                continue
                            if not np.array_equal(
                                    got, fx.fleet_baseline[route][qi]):
                                problems.append(
                                    f"fleet coords for {route}[{qi}] "
                                    "differ from baseline")
            fired = inj.fire_count("fleet.stage")
        if "io_error" in spec and injected < fired:
            problems.append(
                f"{fired} fleet.stage io_error(s) fired but only "
                f"{injected} request(s) failed with the injected "
                "error — a stage failure was swallowed")
        if "delay" in spec and injected:
            problems.append(
                f"{injected} request(s) failed under a delay-only "
                "spec — a slow cold tier must cost latency, never "
                "correctness")
        if fleet.pool.resident_bytes() > fleet.pool.budget_bytes:
            problems.append("fleet pool over its configured budget")
        if not fleet.drain(timeout=30.0):
            problems.append("fleet drain was not clean")
    finally:
        fleet.close()
    return problems


def _run_sharded_fleet_round(fx: _Fixture, spec: str,
                             round_seed: int) -> list[str]:
    """One in-process shard-staged fleet round: a 1-route fleet whose
    panel exceeds the pool budget, so every request streams ~3 shards
    through the armed fleet.stage site — the fault lands MID-panel,
    between shards of a live request. Injected io_errors must fail
    exactly their own request (explicitly — the injected error, or
    PanelUnavailable if the route breaker tripped); delays are pure
    latency; and once the armed window closes, a full post-heal sweep
    must be bit-identical to the warm-pool fleet baseline, with the
    pool back to zero transient residency."""
    from spark_examples_tpu.serve import PanelUnavailable

    problems: list[str] = []
    fleet = fx.make_sharded_fleet()
    injected = 0
    stages0 = telemetry.counter_value("fleet.shard_stages")
    try:
        fleet.start()
        with faults.armed([spec], seed=round_seed) as inj:
            with warnings.catch_warnings():
                warnings.simplefilter("ignore", RuntimeWarning)
                for _sweep in range(2):
                    for qi, q in enumerate(fx.query_pool):
                        try:
                            got = fleet.project("ibs", q, timeout=30.0)
                        except (faults.InjectedFault,
                                PanelUnavailable):
                            injected += 1
                            continue
                        if not np.array_equal(
                                got, fx.fleet_baseline["ibs"][qi]):
                            problems.append(
                                f"sharded fleet coords for [{qi}] "
                                "differ from the warm-pool baseline")
            fired = inj.fire_count("fleet.stage")
        if "io_error" in spec and injected < fired:
            problems.append(
                f"{fired} fleet.stage io_error(s) fired mid-panel but "
                f"only {injected} request(s) failed with the injected "
                "error — a shard-stage failure was swallowed")
        if "delay" in spec and injected:
            problems.append(
                f"{injected} request(s) failed under a delay-only "
                "spec — a slow shard stream must cost latency, never "
                "correctness")
        # Post-heal: the site is disarmed; every answer must come back
        # bit-identical (the breaker, if tripped, never wedges the
        # route past the armed window — failures here are violations).
        for qi, q in enumerate(fx.query_pool):
            try:
                got = fleet.project("ibs", q, timeout=30.0)
            except Exception as e:
                problems.append(
                    f"post-heal sharded request [{qi}] failed ({e!r}) "
                    "— the route did not heal after the fault window")
                continue
            if not np.array_equal(got, fx.fleet_baseline["ibs"][qi]):
                problems.append(
                    f"post-heal sharded coords for [{qi}] differ from "
                    "the warm-pool baseline")
        if telemetry.counter_value("fleet.shard_stages") - stages0 < 2:
            problems.append(
                "fewer than 2 shard stages observed — the round never "
                "actually served shard-staged")
        st = fleet.pool.stats()
        if st["transient_bytes"]:
            problems.append(
                f"{st['transient_bytes']} transient pool bytes still "
                "charged after the round — a shard charge leaked")
        if not fleet.drain(timeout=30.0):
            problems.append("sharded fleet drain was not clean")
    finally:
        fleet.close()
    return problems


def _make_controller(fx: _Fixture, ledger_path: str):
    """A 2-replica controller over LocalReplica fleets sharing the
    soak store as their cold tier — every replica can serve every
    route; the warm split comes from the controller's placement."""
    from spark_examples_tpu.fleet import (
        ControllerConfig,
        FleetController,
        LocalReplica,
    )

    panel_bytes = fx.cfg.n_samples * fx.cfg.n_variants
    budget = int(panel_bytes * 1.5)

    def factory(name, generation):
        return LocalReplica(name, lambda: fx.make_fleet().start(),
                            budget_bytes=budget, generation=generation)

    cfg = ControllerConfig(
        min_replicas=2, max_replicas=3,
        idle_rounds=10_000,  # retire is not this round's subject
        stale_scrapes=2, hang_heartbeat_s=60.0,
        backoff_initial_s=0.01, backoff_max_s=0.5,
        flap_window_s=60.0, flap_max_respawns=20,
        drain_timeout_s=30.0, ledger_path=ledger_path,
    )
    return FleetController(factory, {"ibs": panel_bytes,
                                     "pca": panel_bytes}, cfg)


def _run_controller_round(fx: _Fixture, i: int, spec: str,
                          round_seed: int) -> list[str]:
    """One in-process controller round: the armed site (scrape
    blackhole / spawn cascade / stage failure) plus the deterministic
    chaos sequence every round runs — a replica kill mid-hedged-burst
    (zero admitted requests lost, respawn within the backoff budget)
    and a preemption storm — with served coordinates bit-identical to
    the clean fleet baseline after every recovery, and the atomic
    controller.json ledger readable with the story in it."""
    from spark_examples_tpu.serve import PanelUnavailable, run_hedged_loadgen

    problems: list[str] = []
    ledger = os.path.join(fx.cfg.workdir, f"controller{i}.json")
    ctrl = _make_controller(fx, ledger)
    heal_budget_s = 15.0  # >> the 0.5s backoff ceiling

    def _heal(why: str) -> bool:
        # Always step at least once: a freshly killed replica stays
        # "up" until a watch round notices the corpse.
        deadline = time.monotonic() + heal_budget_s
        while time.monotonic() < deadline:
            ctrl.step()
            reps = ctrl.replicas()
            if len(reps) >= 2 and all(r.alive() for r in reps):
                return True
            time.sleep(0.02)
        problems.append(
            f"controller did not heal back to 2 live replicas within "
            f"{heal_budget_s:.0f}s ({why}) — backoff budget blown "
            f"or flap breaker mis-tripped: "
            f"{[s.state for s in ctrl.slots]}")
        return False

    try:
        ctrl.start()
        with faults.armed([spec], seed=round_seed):
            with warnings.catch_warnings():
                warnings.simplefilter("ignore", RuntimeWarning)
                # Watch rounds under the armed site: a blackholed
                # scrape serves last-good-marked-stale until the slot
                # is declared lost; spawn/stage failures must back
                # off and heal — never spawn-loop, never wedge.
                for _ in range(6):
                    ctrl.step()
                if not _heal("under the armed fault"):
                    return problems
                # Chaos 1: kill the primary mid-hedged-burst. The
                # hedge partner + ServerClosed failover must answer
                # every admitted request — a replica loss costs
                # latency, never an answer.
                routers = [r.router for r in ctrl.replicas()]
                box: dict = {}

                def _drive() -> None:
                    box["report"] = run_hedged_loadgen(
                        routers, fx.query_pool, clients=2,
                        requests_per_client=8, route="ibs",
                        hedge_floor_s=0.005, result_timeout_s=30.0,
                        seed=round_seed)

                driver = threading.Thread(
                    target=_drive, name="loadgen-client-driver",
                    daemon=True)
                driver.start()
                time.sleep(0.05)
                ctrl.replicas()[0].kill()
                driver.join(timeout=60.0)
                report = box.get("report")
                if report is None:
                    problems.append(
                        "hedged burst did not complete after the "
                        "replica kill (driver hung)")
                    return problems
                if report["errors"]:
                    # The armed site may land its fire on the
                    # survivor's serving path mid-burst (a stage fault
                    # opens the route breaker): those legs fail
                    # LOUDLY and attributably — the same explicit-
                    # failure tolerance as the bit-identity sweep
                    # below. Only silent losses (timeouts, swallowed
                    # legs) break the zero-loss contract.
                    injected = sum(
                        1 for r in report["error_records"]
                        if "InjectedFault" in r.get("error", "")
                        or "PanelUnavailable" in r.get("error", ""))
                    if report["errors"] > injected:
                        problems.append(
                            f"{report['errors'] - injected} request(s) "
                            f"lost to the replica kill (failovers="
                            f"{report['failovers']}, injected-fault "
                            f"errors={injected}) — the zero-loss "
                            "contract is broken")
                if not _heal("after the mid-burst kill"):
                    return problems
                # Chaos 2: preemption storm — every replica drained
                # and respawned in turn, gracefully.
                for slot_name in [r.name for r in ctrl.replicas()]:
                    if not ctrl.preempt(slot_name):
                        problems.append(
                            f"preempt({slot_name!r}) refused — slot "
                            "not up when the storm reached it")
                if not _heal("after the preemption storm"):
                    return problems
                # Bit-identity across every recovery: each surviving
                # replica serves both routes exactly as the clean
                # fleet baseline did (stage faults still armed fail
                # explicitly, like the fleet rounds).
                for replica in ctrl.replicas():
                    for route in ("ibs", "pca"):
                        for qi, q in enumerate(fx.query_pool):
                            try:
                                got = replica.router.project(
                                    route, q, timeout=30.0)
                            except (faults.InjectedFault,
                                    PanelUnavailable):
                                continue
                            if not np.array_equal(
                                    got, fx.fleet_baseline[route][qi]):
                                problems.append(
                                    f"{replica.name} served {route}"
                                    f"[{qi}] differs from the clean "
                                    "baseline after recovery")
        # Evidence: the atomic ledger must be readable and carry the
        # round's story.
        try:
            with open(ledger) as f:
                led = json.load(f)
        except (OSError, ValueError) as e:
            problems.append(f"controller ledger unreadable ({e}) — "
                            "the atomic-write contract is broken")
        else:
            acts = {d["action"] for d in led["decisions"]}
            kinds = {x["kind"] for x in led["incidents"]}
            if "respawn" not in acts or "preempt" not in acts:
                problems.append(
                    f"ledger is missing the round's decisions "
                    f"(actions={sorted(acts)})")
            if "crash" not in kinds:
                problems.append(
                    f"ledger has no crash incident for the mid-burst "
                    f"kill (kinds={sorted(kinds)})")
        # The timeline ring beside the ledger must stay readable even
        # when trace.export faults tore or failed appends: every
        # complete record before a torn tail survives, and the round's
        # story (control rounds + the crash marker) is on the tape.
        from spark_examples_tpu.fleet.timeline import read_timeline
        tape = read_timeline(
            os.path.join(os.path.dirname(ledger) or ".",
                         "timeline.jsonl"))
        if not any(r.get("type") == "round" for r in tape):
            problems.append(
                "timeline ring has no round records after the round — "
                "the last-good-tape contract is broken")
        if not any(r.get("type") == "marker" and r.get("kind") == "crash"
                   for r in tape):
            problems.append(
                "timeline ring has no crash marker for the mid-burst "
                "kill")
    finally:
        ctrl.close()
    return problems


def _run_kill_round(fx: _Fixture, i: int, spec: str, round_seed: int,
                    baseline_tsv: bytes) -> tuple[list[str], int]:
    """One supervised subprocess round: the CLI job with an injected
    kill, restarted by --supervise, output bytes vs the clean run —
    with the periodic flusher live in every attempt, so a kill landing
    mid-flush (the telemetry.flush scenario) must still leave each
    attempt's last-good snapshot readable.
    Returns (violations, supervised restarts observed)."""
    cfg = fx.cfg
    out = os.path.join(cfg.workdir, f"kill{i}.tsv")
    ckpt = os.path.join(cfg.workdir, f"killck{i}")
    tel = os.path.join(cfg.workdir, f"killtel{i}")
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        **{faults.ENV_SPECS: spec,
           faults.ENV_SEED: str(round_seed)},
    )
    cmd = _cli_gram_cmd(fx, out, ckpt, tel) + ["--supervise"]
    try:
        p = subprocess.run(cmd, env=env, capture_output=True, text=True,
                           timeout=cfg.kill_budget_s)
    except subprocess.TimeoutExpired:
        return [f"supervised round exceeded the {cfg.kill_budget_s:.0f}s "
                "watchdog budget (deadlock?)"], 0
    restarts = p.stderr.count("supervisor: attempt")
    if p.returncode != 0:
        return [f"supervised run exited {p.returncode}: "
                f"{p.stderr[-500:]}"], restarts
    problems = []
    reason = _snapshots_readable(tel)
    if reason:
        problems.append(reason)
    with open(out, "rb") as f:
        got = f.read()
    if got != baseline_tsv:
        problems.append("supervised kill-resume output differs from the "
                        "clean run's bytes")
    return problems, restarts


def _cli_gram_cmd(fx: _Fixture, out: str, ckpt: str,
                  tel: str | None = None) -> list[str]:
    cfg = fx.cfg
    cmd = [
        sys.executable, "-m", "spark_examples_tpu", "similarity",
        "--source", f"store:{fx.store_dir}",
        "--block-variants", str(cfg.block_variants),
        "--metric", "ibs", "--io-retries", "3",
        "--checkpoint-dir", ckpt, "--checkpoint-every-blocks", "2",
        "--output-path", out,
    ]
    if tel is not None:
        cmd += ["--telemetry-dir", tel, "--telemetry-flush-s", "0.02"]
    return cmd


def run_soak(cfg: SoakConfig) -> SoakReport:
    """The harness. Deterministic for a given (SoakConfig.seed,
    iterations, include_kill): the schedule, every spec's parameters,
    and every injector seed derive from one ``random.Random``."""
    os.makedirs(cfg.workdir, exist_ok=True)
    rng = random.Random(cfg.seed)
    report = SoakReport()
    fx = _Fixture(cfg)

    # Schedule: a seeded shuffle of the scenario table, repeated to
    # `iterations` — randomized order/params with guaranteed site
    # coverage once iterations >= the table size.
    table = list(SCENARIOS) + (list(KILL_SCENARIOS) if cfg.include_kill
                               else [])
    schedule = []
    while len(schedule) < cfg.iterations:
        chunk = list(table)
        rng.shuffle(chunk)
        schedule.extend(chunk)
    schedule = schedule[:cfg.iterations]

    baseline_tsv = None
    if cfg.include_kill and any(j == "cli" for j, *_ in schedule):
        out = os.path.join(cfg.workdir, "clean.tsv")
        p = subprocess.run(
            _cli_gram_cmd(fx, out, os.path.join(cfg.workdir, "cleanck")),
            env=dict(os.environ, JAX_PLATFORMS="cpu"),
            capture_output=True, text=True, timeout=cfg.kill_budget_s,
        )
        if p.returncode != 0:
            raise RuntimeError(
                f"clean CLI baseline failed: {p.stderr[-500:]}")
        with open(out, "rb") as f:
            baseline_tsv = f.read()

    healed0 = telemetry.counter_value("store.healed")
    retries0 = telemetry.counter_value("ingest.retries")
    fired0 = telemetry.counter_value("faults.fired")

    for i, (jobkind, site, kind, params) in enumerate(schedule):
        round_seed = rng.randint(0, 2**31 - 1)
        spec = _spec_str(site, kind, rng, params)
        t0 = time.monotonic()
        try:
            if jobkind == "gram":
                problems = _run_gram_round(fx, i, spec, round_seed)
            elif jobkind == "gram-dense":
                problems = _run_gram_round(fx, i, spec, round_seed,
                                           metric="dot")
            elif jobkind == "neighbors":
                problems = _run_neighbors_round(fx, spec, round_seed)
            elif jobkind == "serve":
                problems = _run_serve_round(fx, spec, round_seed)
            elif jobkind == "fleet":
                problems = _run_fleet_round(fx, spec, round_seed)
            elif jobkind == "fleet-sharded":
                problems = _run_sharded_fleet_round(fx, spec, round_seed)
            elif jobkind == "controller":
                problems = _run_controller_round(fx, i, spec, round_seed)
            else:
                problems, restarts = _run_kill_round(
                    fx, i, spec, round_seed, baseline_tsv)
                report.restarts += restarts
        except BaseException as e:
            problems = [f"round raised {e!r}"]
        dt = time.monotonic() - t0
        if jobkind != "cli" and dt > cfg.round_budget_s:
            problems.append(
                f"round took {dt:.1f}s (> {cfg.round_budget_s:.0f}s "
                "budget — stall/deadlock)")
        leaks = _leaked_threads(fx.thread_baseline)
        if leaks:
            problems.append(f"leaked threads: {leaks}")
        reason = fx.store_consistent()
        if reason:
            problems.append(f"store bookkeeping: {reason}")
        report.rounds.append({
            "iter": i, "job": jobkind, "spec": spec,
            "seed": round_seed, "s": round(dt, 2),
            "ok": not problems,
        })
        for prob in problems:
            report.violations.append(
                f"SOAK-REPRO seed={cfg.seed} iter={i} spec={spec!r} "
                f"job={jobkind}: {prob}")
        report.iterations += 1
        if problems:
            break  # first violation stops the soak: the repro line is
            # the deliverable, and later rounds run on a possibly
            # damaged fixture
    report.healed = int(telemetry.counter_value("store.healed") - healed0)
    report.retries = int(telemetry.counter_value("ingest.retries")
                         - retries0)
    report.faults_fired = int(telemetry.counter_value("faults.fired")
                              - fired0)
    return report


def main(argv=None) -> int:  # pragma: no cover - thin CLI
    import argparse
    import tempfile

    ap = argparse.ArgumentParser(description="chaos soak harness")
    ap.add_argument("--iterations", type=int, default=25)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--workdir", default=None,
                    help="fixture dir (default: a fresh tmp dir, "
                    "removed on a clean soak, kept on violation so the "
                    "SOAK-REPRO line has its fixture)")
    ap.add_argument("--no-kill", action="store_true")
    args = ap.parse_args(argv)
    own_workdir = args.workdir is None
    workdir = args.workdir or tempfile.mkdtemp(prefix="soak-")
    report = run_soak(SoakConfig(
        workdir=workdir, iterations=args.iterations, seed=args.seed,
        include_kill=not args.no_kill))
    print(json.dumps(report.to_json(), indent=1))
    if report.ok and own_workdir:
        import shutil

        shutil.rmtree(workdir, ignore_errors=True)
    return 0 if report.ok else 1


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())

"""Noise-aware perf-regression tracking over bench headline history.

BENCH_r01–r05 each printed a headline JSON and nobody diffed them: a
perf regression only surfaced if a human compared files by hand. This
module is the mechanical replacement, three pieces:

- **Substrate.** :func:`append_history` appends one run's headline to
  the append-only ``BENCH_HISTORY.jsonl`` — one JSON object per line,
  ``{"t_unix", "run": {git sha, argv, platform...}, "metrics": {...}}``
  — and :func:`ingest_bench_files` backfills it from the repo's
  archived ``BENCH_r*.json`` round records (their ``parsed`` headline).
  ``bench.py`` appends every run unconditionally, so the history exists
  from day one.
- **Direction registry.** Every headline metric has a *better*
  direction — throughput up, latency down, relerr down, ``*_ok`` stays
  true. :func:`metric_direction` resolves it from an explicit map plus
  suffix rules; metrics with no known direction (free-form strings,
  environment numbers like the dev tunnel rate) are not gated.
- **Noise band.** A metric's recent history (trailing window) gives a
  median and a MAD; the candidate regresses only when it is worse than
  ``median ± max(mad_k·1.4826·MAD, rel_floor·|median|)`` in the bad
  direction. Run-to-run jitter (the MAD) widens the band per metric, so
  a noisy metric needs a big move to fire while a stable one is gated
  tightly — and the relative floor keeps a zero-MAD history from
  flagging 1% wiggles.

Wired as ``bench.py --trend`` (append + check + nonzero exit on
regression) and runnable standalone::

    python tools/trend.py ingest --history BENCH_HISTORY.jsonl BENCH_r*.json
    python tools/trend.py check  --history BENCH_HISTORY.jsonl
    python tools/trend.py check  --history BENCH_HISTORY.jsonl --candidate headline.json

Exit codes: 0 clean, 1 regression, 2 usage.
"""

from __future__ import annotations

import json
import os
import statistics
import subprocess
import sys
import time

HISTORY_FILE = "BENCH_HISTORY.jsonl"

# Defaults of the noise band. mad_k=4 on a consistency-scaled MAD
# (1.4826·MAD estimates sigma for normal noise) keeps ordinary jitter
# quiet; rel_floor guarantees a ±5% dead zone even on a constant
# history (MAD 0), so sub-noise wiggles can never fire.
WINDOW = 8
MAD_K = 4.0
REL_FLOOR = 0.05
MIN_HISTORY = 3

HIGHER_IS_BETTER = +1
LOWER_IS_BETTER = -1
BOOL_MUST_HOLD = 0

# Explicit directions first — names whose suffix rules would guess
# wrong, plus the cross-round headline anchors. None = tracked in the
# history but never gated (environment numbers that measure the dev
# tunnel / session, not the code).
_EXPLICIT: dict[str, int | None] = {
    "value": LOWER_IS_BETTER,  # headline seconds
    "vs_baseline": HIGHER_IS_BETTER,
    "streamed_vs_baseline": HIGHER_IS_BETTER,
    # serve_vcf_s - serve_store_s: the cold-start time SAVED by staging
    # from the store — a gain, despite the "_s" suffix.
    "store_serve_cold_start_delta_s": HIGHER_IS_BETTER,
    "tunnel_mb_s": None,  # session link rate: environment, not code
    "store_link_mb_s": None,  # the SIMULATED link rate: a knob, not a result
    # measured link-bound wall / ideal link wall: 1.0 = decode fully
    # hidden behind the link, the feed-saturation contract.
    "store_link_decode_overhead": LOWER_IS_BETTER,
    "cpu_baseline_s": None,  # the oracle's speed is not ours to gate
    # graftlint finding count (bench headline): 0 on a clean tree; any
    # rise is a regression regardless of perf. The companion lint_ok
    # boolean rides the *_ok must-hold gate.
    "lint_findings": LOWER_IS_BETTER,
    "chaos_soak_iterations": None,
    "chaos_soak_healed": None,
    "chaos_soak_faults_fired": None,
    # Fleet bench (bench --fleet): the route count is workload shape,
    # the eviction count is the budget-forced churn the bench INTENDS
    # (a "regression" to fewer evictions would just mean the mix
    # changed), and the hedge win fraction measures the injected-delay
    # demo's asymmetry, not code quality — the p99s/QPS/ok gate
    # through the ordinary suffix rules.
    "fleet_routes": None,
    "fleet_evictions": None,
    "fleet_hedge_win_frac": None,
    # Controller bench (bench --controller): the shed fraction has no
    # suffix rule ("_rate" is ambiguous between throughput and loss) —
    # here it is dropped requests, so it must go DOWN; the final
    # replica count is the workload's equilibrium, not a quality axis.
    # scale_up_s / p99_loss_s gate through the "_s" suffix rule and
    # controller_ok through the *_ok must-hold gate.
    "controller_burst_shed_rate": LOWER_IS_BETTER,
    "controller_replicas": None,
    # Tracing tax (bench --fleet): traced-vs-untraced loadgen wall
    # overhead as a fraction — "_frac" has no suffix rule, and this
    # one must go DOWN (the flight recorder budget: <= 2% is the PR
    # gate). slo_fast_burn_ok rides the *_ok must-hold gate.
    "trace_overhead_frac": LOWER_IS_BETTER,
    # Neighbor engine (bench --neighbors): recall@k has no suffix rule
    # and must go UP (lost relatives are the failure mode), as must
    # the fraction of pairs the LSH filter avoided evaluating — the
    # "_frac" here is a gain, unlike the stall/overhead fractions.
    # neighbors_sparse_speedup_vs_dense rides the "_vs_" rule,
    # neighbors_p99_ms the "_ms" suffix, neighbors_ok the *_ok gate.
    "neighbors_recall_at_k": HIGHER_IS_BETTER,
    "neighbors_filter_frac": HIGHER_IS_BETTER,
    # Servable sketch models (bench --sketch-serve): how many budgets'
    # worth of panel the shard-staged route streams per request is a
    # workload DESCRIPTOR (set by cohort size vs configured budget),
    # not a quality axis — tracked, never gated. stage_s/p99_ms ride
    # the time suffixes, sketch_serve_ok the *_ok must-hold gate.
    "sketch_serve_panel_over_budget_x": None,
    # Fused packed gram lowering (bench --kernels): the worst
    # per-kernel fused-vs-reference gram speedup. "speedup" alone
    # matches no suffix rule, and this one must go UP — the whole
    # point of decoding the 2-bit codes in-register is beating the
    # unpack-then-matmul reference. kernel_fused_ok (parity + column
    # presence, plus chip-only speedup floor) rides the *_ok gate.
    "kernel_fused_min_speedup": HIGHER_IS_BETTER,
}

# (match kind, token, direction) — first hit wins, checked in order:
# throughput tokens before the bare "_s" time suffix ("_mb_s" ends
# with "_s" too), relerr before "_vs_" ("relerr_vs_exact" is an error,
# not a speedup ratio), stall/compression rules before the generic
# suffixes (a feed-stall FRACTION must go down, a compression RATIO
# up — store PR contract). The kernel-sweep metrics
# (kernel_<name>_mb_s / kernel_<name>_gflops / kernel_sweep_min_gflops
# from bench --kernels) ride the _mb_s and flops throughput rules,
# kernel_sweep_ok the *_ok gate — pinned by tests/test_trend.py.
_RULES: tuple[tuple[str, str, int], ...] = (
    ("contains", "relerr", LOWER_IS_BETTER),
    ("contains", "stall_frac", LOWER_IS_BETTER),
    # 1 - gather_wait/compute of the measured multi-chip gram (bench
    # --multichip): more of the block collective hidden behind the MXU
    # is strictly better — and it must outrank the generic "_frac"-less
    # suffix rules below (multichip_overlap_frac has no other token).
    ("contains", "overlap_frac", HIGHER_IS_BETTER),
    ("contains", "compress_ratio", HIGHER_IS_BETTER),
    ("contains", "_mb_s", HIGHER_IS_BETTER),
    ("contains", "qps", HIGHER_IS_BETTER),
    ("contains", "flops", HIGHER_IS_BETTER),
    ("contains", "_vs_", HIGHER_IS_BETTER),
    ("contains", "scaling", HIGHER_IS_BETTER),
    ("contains", "separation", HIGHER_IS_BETTER),
    ("suffix", "_peak_mb", LOWER_IS_BETTER),
    ("suffix", "_bytes", LOWER_IS_BETTER),
    ("suffix", "_ms", LOWER_IS_BETTER),
    ("suffix", "_s", LOWER_IS_BETTER),
)


def metric_direction(name: str) -> int | None:
    """+1 higher-is-better, -1 lower-is-better, 0 boolean gate, None =
    untracked."""
    if name in _EXPLICIT:
        return _EXPLICIT[name]
    if name.endswith("_ok"):
        return BOOL_MUST_HOLD
    for kind, token, direction in _RULES:
        if kind == "suffix" and name.endswith(token):
            return direction
        if kind == "contains" and token in name:
            return direction
    return None


def _scalar_metrics(headline: dict) -> dict:
    """The gateable subset of a headline: top-level ints/floats/bools
    (strings, nested dicts like the telemetry digest, and repro lines
    stay in the raw record but are not trended)."""
    out = {}
    for k, v in headline.items():
        if isinstance(v, bool):
            out[k] = v
        elif isinstance(v, (int, float)):
            out[k] = float(v)
    return out


# ---------------------------------------------------------------------------
# Substrate: the append-only history.


def load_history(path: str) -> list[dict]:
    """Parse the JSONL history; torn/garbage lines are skipped (the
    file is append-only across crashes — a half-written tail must not
    invalidate years of records)."""
    records = []
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if isinstance(rec, dict) and isinstance(
                        rec.get("metrics"), dict):
                    records.append(rec)
    except OSError:
        pass
    return records


def run_metadata(extra: dict | None = None) -> dict:
    """Who/where/what produced this run: git sha, platform, python —
    the provenance a regression hunt needs first."""
    meta = {
        "platform": sys.platform,
        "python": ".".join(map(str, sys.version_info[:3])),
    }
    try:
        meta["git_sha"] = subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"],
            cwd=os.path.dirname(os.path.abspath(__file__)),
            capture_output=True, text=True, timeout=10,
        ).stdout.strip() or None
    except (OSError, subprocess.SubprocessError):
        meta["git_sha"] = None
    if extra:
        meta.update(extra)
    return meta


def append_history(path: str, headline: dict,
                   run_meta: dict | None = None) -> dict:
    """Append one run's headline to the history; returns the record."""
    record = {
        "t_unix": time.time(),
        "run": run_metadata(run_meta),
        "metrics": _scalar_metrics(headline),
    }
    with open(path, "a") as f:
        f.write(json.dumps(record, sort_keys=True) + "\n")
    return record


def ingest_bench_files(paths: list[str],
                       backend: str = "tpu") -> list[dict]:
    """Backfill records from archived round files: ``BENCH_r*.json``
    round records (their ``parsed`` headline) or bare headline JSON.
    The archived rounds all ran on the chip, so they are tagged
    ``backend="tpu"`` by default — the backend tag is what keeps a
    stray CPU bench run from gating against (or polluting) the chip
    history (see :func:`check_trend`)."""
    records = []
    for p in sorted(paths):
        with open(p) as f:
            doc = json.load(f)
        headline = doc.get("parsed", doc)
        if not isinstance(headline, dict):
            continue
        metrics = _scalar_metrics(headline)
        if not metrics:
            continue
        records.append({
            "t_unix": os.path.getmtime(p),
            "run": {"source": os.path.basename(p),
                    "round": doc.get("n"),
                    "backend": backend},
            "metrics": metrics,
        })
    return records


# ---------------------------------------------------------------------------
# The check.


def check_trend(history: list[dict], candidate: dict,
                window: int = WINDOW, mad_k: float = MAD_K,
                rel_floor: float = REL_FLOOR,
                min_history: int = MIN_HISTORY,
                backend: str | None = None) -> dict:
    """Gate ``candidate`` (a metrics dict or a history record) against
    the trailing ``window`` of ``history``. Returns the report:
    ``ok`` (False iff any regression), ``regressions`` /
    ``improvements`` / ``skipped`` per-metric details.

    ``backend`` (e.g. ``"tpu"``) restricts the history window to runs
    recorded with the same ``run.backend`` — seconds on a CPU dev box
    and seconds on the chip are different quantities, and comparing
    across them would both fire spurious regressions and widen the
    MAD band enough to mask real ones. None = no filtering (fixture
    histories and same-environment workflows)."""
    if backend is not None:
        history = [h for h in history
                   if h.get("run", {}).get("backend") == backend]
    cand = candidate.get("metrics", candidate)
    report: dict = {"checked": 0, "regressions": [], "improvements": [],
                    "skipped": []}
    for name in sorted(cand):
        direction = metric_direction(name)
        value = cand[name]
        if direction is None or not isinstance(value, (bool, int, float)):
            report["skipped"].append({"metric": name, "why": "untracked"})
            continue
        series = [h["metrics"][name] for h in history
                  if name in h.get("metrics", {})][-window:]
        if direction == BOOL_MUST_HOLD:
            report["checked"] += 1
            if not value and any(series):
                report["regressions"].append({
                    "metric": name, "candidate": value,
                    "why": "boolean gate was previously true",
                })
            continue
        if len(series) < min_history:
            report["skipped"].append({
                "metric": name,
                "why": f"history too short ({len(series)} < "
                       f"{min_history})",
            })
            continue
        med = statistics.median(series)
        mad = statistics.median(abs(x - med) for x in series)
        band = max(mad_k * 1.4826 * mad, rel_floor * abs(med))
        delta = float(value) - med
        report["checked"] += 1
        entry = {
            "metric": name,
            "candidate": float(value),
            "median": med,
            "band": round(band, 6),
            "delta": round(delta, 6),
            "direction": ("higher_is_better" if direction > 0
                          else "lower_is_better"),
            "window": len(series),
        }
        # Direction-aware: only a move PAST the band edge in the bad
        # direction regresses; the same move the other way is an
        # improvement (reported, never fatal).
        if delta * direction < -band:
            report["regressions"].append(entry)
        elif delta * direction > band:
            report["improvements"].append(entry)
    report["ok"] = not report["regressions"]
    return report


def check_and_count(history_path: str, candidate: dict | None = None,
                    backend: str | None = None, **kw) -> dict:
    """bench.py's entry: check the candidate (default: the history's
    last record) against the records before it, mirroring the verdict
    into the ``trend.*`` telemetry counters. When the candidate is a
    history record carrying ``run.backend`` and no explicit
    ``backend`` is given, the window filters to that backend."""
    history = load_history(history_path)
    if candidate is None:
        if not history:
            return {"ok": True, "checked": 0, "regressions": [],
                    "improvements": [], "skipped": [],
                    "note": "empty history"}
        candidate, history = history[-1], history[:-1]
    if backend is None:
        backend = candidate.get("run", {}).get("backend") \
            if isinstance(candidate.get("run"), dict) else None
    report = check_trend(history, candidate, backend=backend, **kw)
    try:
        from spark_examples_tpu.core import telemetry

        telemetry.count("trend.metrics_checked", report["checked"])
        if report["regressions"]:
            telemetry.count("trend.regressions",
                            len(report["regressions"]))
    except Exception:
        pass  # the checker must run even without the package on path
    return report


def regression_lines(report: dict) -> list[str]:
    """Human-readable one-liners for a report's regressions — THE
    shared rendering, so bench.py's gate and this module's CLI cannot
    drift apart on wording."""
    return [
        f"trend: REGRESSION {r['metric']}: {r.get('candidate')} vs "
        f"median {r.get('median')} (band ±{r.get('band', 0)}, "
        f"window {r.get('window', 0)})"
        for r in report.get("regressions", [])
    ]


# ---------------------------------------------------------------------------
# CLI.


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        description="noise-aware bench trend tracking")
    sub = ap.add_subparsers(dest="verb", required=True)
    p_in = sub.add_parser("ingest", help="backfill history from "
                          "BENCH_r*.json / headline files")
    p_in.add_argument("files", nargs="+")
    p_in.add_argument("--history", default=HISTORY_FILE)
    p_in.add_argument("--backend", default="tpu",
                      help="run.backend tag stamped on the ingested "
                      "records (default tpu — the archived rounds ran "
                      "on the chip); pass cpu when backfilling dev-box "
                      "headlines so they never gate the chip history")
    p_ck = sub.add_parser("check", help="gate the newest record (or "
                          "--candidate) against the trailing history")
    p_ck.add_argument("--history", default=HISTORY_FILE)
    p_ck.add_argument("--candidate", default=None,
                      help="headline JSON file to gate (default: the "
                      "history's own last record)")
    p_ck.add_argument("--window", type=int, default=WINDOW)
    p_ck.add_argument("--mad-k", type=float, default=MAD_K)
    p_ck.add_argument("--rel-floor", type=float, default=REL_FLOOR)
    p_ck.add_argument("--backend", default=None,
                      help="gate only against history runs recorded "
                      "with this run.backend (e.g. tpu); default: the "
                      "candidate record's own backend when it has one")
    args = ap.parse_args(argv)

    if args.verb == "ingest":
        records = ingest_bench_files(args.files, backend=args.backend)
        with open(args.history, "a") as f:
            for rec in records:
                f.write(json.dumps(rec, sort_keys=True) + "\n")
        print(f"ingested {len(records)} record(s) -> {args.history}")
        return 0

    candidate = None
    if args.candidate:
        with open(args.candidate) as f:
            doc = json.load(f)
        candidate = doc.get("parsed", doc)
    report = check_and_count(args.history, candidate,
                             backend=args.backend,
                             window=args.window, mad_k=args.mad_k,
                             rel_floor=args.rel_floor)
    print(json.dumps(report, sort_keys=True))
    for line in regression_lines(report):
        print(line, file=sys.stderr)
    return 0 if report["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())

"""The graftlint engine: files, findings, suppressions, rule registry.

Design contract (tests/test_graftlint.py pins all of it):

- A **Finding** is ``file:line:col`` (line 1-based as in ``ast``, col
  1-based — ``ast.col_offset + 1``, the gcc/clang editor convention),
  a rule id, a one-line message, and the rule's fix hint. ``data``
  carries rule-specific structured fields (e.g. the undeclared
  telemetry name) so downstream tests/tools need not re-parse messages.
- **Suppression** is the inline comment
  ``# graftlint: disable=<rule>[,<rule>...]  # <reason>`` — on the
  finding's own line, or standing alone on the line directly above it.
  The reason (a second ``#`` chunk) is REQUIRED: a reasonless disable
  still suppresses (so the fix is to add the reason, not to face a
  double report) but emits a ``suppression-reason`` finding of its own,
  which is not itself suppressible.
- Fixture files may carry ``# graftlint: module=<dotted>`` to claim a
  module identity (the jax-import-purity rule checks contracts keyed by
  module path; fixtures live outside the package).
- ``run()`` with no paths walks the production tree —
  ``spark_examples_tpu/``, ``tools/``, ``bench.py`` — never ``tests/``
  (tests legitimately write bad patterns on purpose; the fixture corpus
  lives there). Repo-level checks that only make sense over the full
  tree (e.g. dead fault-site registry entries) run only in that mode.
"""

from __future__ import annotations

import ast
import io
import json
import pathlib
import re
import tokenize
from dataclasses import dataclass, field

REPO = pathlib.Path(__file__).resolve().parent.parent.parent
PACKAGE = "spark_examples_tpu"

_SUPPRESS = re.compile(
    r"#\s*graftlint:\s*disable=([A-Za-z0-9_,-]+)\s*(?:#\s*(\S.*))?$"
)
_MODULE_PRAGMA = re.compile(r"#\s*graftlint:\s*module=([A-Za-z0-9_.]+)")


@dataclass(frozen=True)
class Finding:
    """One violation at a precise location."""

    path: str  # repo-relative posix path
    line: int  # 1-based
    col: int  # 1-based (ast.col_offset + 1)
    rule: str
    message: str
    hint: str = ""
    data: dict = field(default_factory=dict, compare=False)

    def render(self) -> str:
        text = f"{self.path}:{self.line}:{self.col}: {self.rule}: " \
               f"{self.message}"
        if self.hint:
            text += f" (fix: {self.hint})"
        return text


@dataclass
class Suppression:
    line: int  # the line the comment sits on
    rules: frozenset[str]
    reason: str
    col: int
    standalone: bool  # comment-only line -> applies to the next line


class SourceFile:
    """A parsed target: text, AST, suppressions, module identity."""

    def __init__(self, path: pathlib.Path, root: pathlib.Path):
        self.path = path
        self.root = root
        self.rel = path.resolve().relative_to(root).as_posix() \
            if path.resolve().is_relative_to(root) else path.as_posix()
        self.text = path.read_text()
        self.lines = self.text.splitlines()
        self.tree: ast.Module | None = None
        self.parse_error: SyntaxError | None = None
        try:
            self.tree = ast.parse(self.text)
        except SyntaxError as e:
            self.parse_error = e
        self.suppressions = self._parse_suppressions()
        self.module = self._module_name()

    def _comments(self) -> list[tuple[int, int, str]]:
        """(line, col, text) of every real COMMENT token — pragmas and
        suppressions are resolved from the token stream, NOT raw-line
        regexes, so a docstring that merely *mentions* the pragma
        grammar (this engine's own docs do) can never arm it."""
        cached = getattr(self, "_comment_cache", None)
        if cached is not None:
            return cached
        out: list[tuple[int, int, str]] = []
        try:
            for tok in tokenize.generate_tokens(
                    io.StringIO(self.text).readline):
                if tok.type == tokenize.COMMENT:
                    out.append((tok.start[0], tok.start[1], tok.string))
        except (tokenize.TokenError, IndentationError, SyntaxError):
            pass  # unparseable tail: the parse-error finding covers it
        self._comment_cache = out
        return out

    def _parse_suppressions(self) -> list[Suppression]:
        out = []
        for line_no, col0, comment in self._comments():
            m = _SUPPRESS.search(comment)
            if not m:
                continue
            reason = (m.group(2) or "").strip()
            line_text = self.lines[line_no - 1] \
                if line_no - 1 < len(self.lines) else ""
            out.append(Suppression(
                line=line_no,
                rules=frozenset(
                    r.strip() for r in m.group(1).split(",") if r.strip()),
                reason=reason,
                col=col0 + m.start() + 1,
                standalone=line_text[:col0].strip() == "",
            ))
        return out

    def _module_name(self) -> str | None:
        for _line, _col, comment in self._comments():
            m = _MODULE_PRAGMA.search(comment)
            if m:
                return m.group(1)
        rel = pathlib.PurePosixPath(self.rel)
        if rel.parts and rel.parts[0] in (PACKAGE, "tools"):
            parts = list(rel.parts)
            if parts[-1] == "__init__.py":
                parts = parts[:-1]
            else:
                parts[-1] = parts[-1][: -len(".py")]
            return ".".join(parts)
        return None

    def segment(self, node: ast.AST) -> str:
        return ast.get_source_segment(self.text, node) or ""

    def suppressed(self, rule: str, line: int) -> bool:
        for s in self.suppressions:
            if rule not in s.rules:
                continue
            if s.line == line or (s.standalone and s.line == line - 1):
                return True
        return False


class Context:
    """Per-run shared state: the file set, lazily imported registries,
    the package module index for import-graph walks, and a scratch
    ``data`` dict rules use to aggregate across files (e.g. the set of
    fault sites actually fired, consumed by ``finalize``)."""

    def __init__(self, files: list[SourceFile], root: pathlib.Path,
                 full_repo: bool):
        self.files = files
        self.root = root
        # True only for the default (whole-production-tree) walk: repo-
        # level finalize checks (dead registry entries) would misfire on
        # a partial file list.
        self.full_repo = full_repo
        self.data: dict = {}
        self._module_files: dict[str, pathlib.Path] | None = None

    # -- live registries (imported lazily; all jax-free by contract) --

    def kernel_names(self) -> frozenset[str]:
        from spark_examples_tpu import kernels

        return frozenset(kernels.names())

    def telemetry(self):
        from spark_examples_tpu.core import telemetry

        return telemetry

    def faults(self):
        from spark_examples_tpu.core import faults

        return faults

    def config_enums(self) -> dict[str, tuple[tuple[str, ...], str]]:
        """family label -> (values, defining module)."""
        from spark_examples_tpu.core import config as C

        mod = "spark_examples_tpu.core.config"
        return {
            "solver ladder": (tuple(C.SOLVER_LADDER), mod),
            "store codec": (tuple(C.STORE_CODEC_SPECS), mod),
            "tile2d transport": (tuple(C.TILE2D_TRANSPORTS), mod),
            "gram mode": (tuple(C.GRAM_MODES), mod),
            "eigh mode": (tuple(C.EIGH_MODES), mod),
            "braycurtis method": (tuple(C.BRAYCURTIS_METHODS), mod),
            "backend": (tuple(C.BACKENDS), mod),
            "pack stream": (tuple(C.PACK_STREAMS), mod),
            "priority class": (tuple(C.PRIORITY_CLASSES), mod),
        }

    # -- package module index (for the import-graph rule) --

    def module_file(self, dotted: str) -> pathlib.Path | None:
        if self._module_files is None:
            index: dict[str, pathlib.Path] = {}
            pkg = self.root / PACKAGE
            for p in pkg.rglob("*.py"):
                rel = p.relative_to(self.root)
                parts = list(rel.parts)
                if parts[-1] == "__init__.py":
                    parts = parts[:-1]
                else:
                    parts[-1] = parts[-1][: -len(".py")]
                index[".".join(parts)] = p
            self._module_files = index
        return self._module_files.get(dotted)


class Rule:
    """Base analyzer. Subclasses set ``id``/``invariant``/``hint`` and
    implement ``check`` (per file) and optionally ``finalize`` (once per
    run, full-repo mode only — for aggregate invariants)."""

    id: str = ""
    invariant: str = ""
    hint: str = ""

    def check(self, src: SourceFile, ctx: Context):
        return ()

    def finalize(self, ctx: Context):
        return ()

    def finding(self, src: SourceFile, node: ast.AST, message: str,
                hint: str | None = None, **data) -> Finding:
        return Finding(
            path=src.rel,
            line=getattr(node, "lineno", 1),
            col=getattr(node, "col_offset", 0) + 1,
            rule=self.id,
            message=message,
            hint=self.hint if hint is None else hint,
            data=data,
        )


_REGISTRY: dict[str, Rule] = {}


def register(rule_cls: type[Rule]) -> type[Rule]:
    rule = rule_cls()
    if not rule.id:
        raise ValueError(f"rule {rule_cls.__name__} has no id")
    if rule.id in _REGISTRY:
        raise ValueError(f"duplicate rule id {rule.id!r}")
    _REGISTRY[rule.id] = rule
    return rule_cls


def all_rules() -> dict[str, Rule]:
    return dict(_REGISTRY)


# Meta rule ids emitted by the engine itself (not registered analyzers,
# not suppressible).
SUPPRESSION_RULE = "suppression-reason"
PARSE_RULE = "parse-error"

_SUPPRESSION_HINT = (
    "append the reason as a second comment chunk: "
    "# graftlint: disable=<rule>  # <why this site is a deliberate "
    "exception>"
)


def default_targets(root: pathlib.Path = REPO) -> list[pathlib.Path]:
    """The production tree: the package, tools/, bench.py. Tests and
    the fixture corpus are excluded by design — they hold bad patterns
    on purpose."""
    targets = sorted((root / PACKAGE).rglob("*.py"))
    targets += sorted((root / "tools").rglob("*.py"))
    bench = root / "bench.py"
    if bench.exists():
        targets.append(bench)
    return targets


def _expand(paths, root: pathlib.Path) -> list[pathlib.Path]:
    out: list[pathlib.Path] = []
    for p in paths:
        p = pathlib.Path(p)
        if not p.is_absolute():
            p = root / p
        if p.is_dir():
            out.extend(sorted(p.rglob("*.py")))
        else:
            out.append(p)
    return out


def run(paths=None, rules=None, root: pathlib.Path = REPO) -> list[Finding]:
    """Run the suite; returns findings sorted by location.

    ``paths``: files/dirs (default: the whole production tree — which
    additionally arms the repo-level finalize checks). ``rules``: rule
    id allowlist (default: all registered).
    """
    full_repo = paths is None
    files = [SourceFile(p, root)
             for p in (default_targets(root) if full_repo
                       else _expand(paths, root))]
    active = all_rules()
    if rules is not None:
        unknown = sorted(set(rules) - set(active))
        if unknown:
            raise ValueError(
                f"unknown rule id(s) {', '.join(unknown)} — known: "
                f"{', '.join(sorted(active))}")
        active = {rid: r for rid, r in active.items() if rid in rules}
    ctx = Context(files, root, full_repo=full_repo)

    findings: list[Finding] = []
    for src in files:
        if src.parse_error is not None:
            e = src.parse_error
            findings.append(Finding(
                path=src.rel, line=e.lineno or 1, col=(e.offset or 1),
                rule=PARSE_RULE, message=f"file does not parse: {e.msg}",
                hint="fix the syntax error"))
            continue
        for rule in active.values():
            for f in rule.check(src, ctx):
                if not src.suppressed(f.rule, f.line):
                    findings.append(f)
        # A suppression without a reason is itself a finding — whether
        # or not it suppressed anything this run (a stale reasonless
        # disable is still an unauditable exception).
        for s in src.suppressions:
            if not s.reason:
                findings.append(Finding(
                    path=src.rel, line=s.line, col=s.col,
                    rule=SUPPRESSION_RULE,
                    message="suppression without a reason: "
                            f"disable={','.join(sorted(s.rules))}",
                    hint=_SUPPRESSION_HINT,
                    data={"rules": sorted(s.rules)}))
    if full_repo:
        for rule in active.values():
            findings.extend(rule.finalize(ctx))
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings


def format_findings(findings: list[Finding], fmt: str = "text") -> str:
    if fmt == "json":
        return json.dumps(
            {
                "findings": [
                    {"path": f.path, "line": f.line, "col": f.col,
                     "rule": f.rule, "message": f.message, "hint": f.hint}
                    for f in findings
                ],
                "count": len(findings),
                "ok": not findings,
            },
            sort_keys=True, indent=2)
    lines = [f.render() for f in findings]
    lines.append(f"graftlint: {len(findings)} finding(s)"
                 if findings else "graftlint: clean")
    return "\n".join(lines)


def collect_string_constants(paths, root: pathlib.Path = REPO) -> list[str]:
    """Every string constant in the given files/dirs, via the AST —
    including the literal fragments of f-strings. The armed-fault-site
    lint (tests/test_telemetry_names.py) searches these for
    ``site:kind`` specs instead of regexing raw text."""
    out: list[str] = []
    for p in _expand(paths, root):
        try:
            tree = ast.parse(p.read_text())
        except (OSError, SyntaxError):
            continue
        for node in ast.walk(tree):
            if isinstance(node, ast.Constant) and isinstance(node.value, str):
                out.append(node.value)
    return out

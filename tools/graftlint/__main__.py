"""``python -m tools.graftlint`` — run the invariant suite.

Exit codes: 0 clean, 1 findings, 2 usage. ``--format json`` emits one
machine-readable document; the default text format is one
``file:line:col: rule: message`` line per finding.
"""

from __future__ import annotations

import argparse
import sys

from tools.graftlint import engine as E
from tools.graftlint import rules as _rules  # noqa: F401  (registers)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="graftlint",
        description="AST-based invariant analyzers distilled from this "
                    "repo's bug history (see README 'Static analysis')",
    )
    ap.add_argument("paths", nargs="*",
                    help="files/directories to lint (default: the "
                    "production tree — spark_examples_tpu/, tools/, "
                    "bench.py; tests and fixtures are excluded by "
                    "design)")
    ap.add_argument("--rules", default=None, metavar="ID[,ID...]",
                    help="run only these rule ids (default: all; see "
                    "--list-rules)")
    ap.add_argument("--format", default="text", choices=["text", "json"])
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule table (id + invariant) and exit")
    args = ap.parse_args(argv)

    if args.list_rules:
        for rid, rule in sorted(E.all_rules().items()):
            print(f"{rid}: {rule.invariant}")
        return 0

    rules = None
    if args.rules:
        rules = [r.strip() for r in args.rules.split(",") if r.strip()]
    try:
        findings = E.run(paths=args.paths or None, rules=rules)
    except ValueError as e:  # unknown rule id
        ap.error(str(e))
    except OSError as e:
        ap.error(f"cannot read target: {e}")
    print(E.format_findings(findings, args.format))
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())

"""donation-safety — donated jit buffers that cannot or must not be
donated.

Historical bug (PR 12): the sharded PCoA finalize jits donated int32
``pieces`` leaves and scalar counters into float32 outputs. XLA aliases
donated buffers by dtype/shape, so those donations bought nothing but a
"Some donated buffers were not usable" warning on every multi-chip run
— and a donation that DID take effect on a buffer the caller still
reads would return garbage silently.

Two lexical checks, function-scope, best-effort precise:

- **read-after-donate**: a name passed in a donated position of a
  known-donating callable is loaded again later in the same scope
  without being reassigned first (the canonical safe shape is
  ``acc = update(acc, block)`` — the rebind makes the old buffer
  unreachable).
- **non-alias-able leaf**: the donated argument is statically a scalar
  literal or an integer/bool-dtyped array constructor
  (``jnp.zeros(..., dtype=jnp.int32)``, ``np.int32(...)``, ...), which
  XLA cannot alias into a float output.

A "known-donating callable" is one defined in the same module via
``jax.jit(f, donate_argnums=...)``,
``partial(jax.jit, donate_argnums=...)(f)``, or the decorator form.
"""

from __future__ import annotations

import ast

from tools.graftlint.engine import Context, Rule, SourceFile, register
from tools.graftlint.astutil import dotted, walk_scopes

_INT_DTYPES = ("int8", "int16", "int32", "int64", "uint8", "uint16",
               "uint32", "uint64", "bool_", "bool")
_CTORS = ("zeros", "ones", "full", "empty", "asarray", "array",
          "zeros_like", "ones_like", "full_like", "arange")


def _donate_positions(call: ast.Call) -> frozenset[int] | None:
    """Donated positional indices from a ``jax.jit``-shaped call's
    ``donate_argnums=`` keyword, else None."""
    if dotted(call.func) not in ("jax.jit", "jit"):
        return None
    for kw in call.keywords:
        if kw.arg == "donate_argnums":
            v = kw.value
            if isinstance(v, ast.Constant) and isinstance(v.value, int):
                return frozenset((v.value,))
            if isinstance(v, (ast.Tuple, ast.List)):
                idx = [e.value for e in v.elts
                       if isinstance(e, ast.Constant)
                       and isinstance(e.value, int)]
                if len(idx) == len(v.elts):
                    return frozenset(idx)
            return None  # dynamic spec: not statically analyzable
    return None


def _jit_factory_positions(node: ast.AST) -> frozenset[int] | None:
    """Donated positions for either ``jax.jit(f, donate_argnums=...)``
    or ``partial(jax.jit, donate_argnums=...)(f)`` / the same as a
    decorator."""
    if not isinstance(node, ast.Call):
        return None
    direct = _donate_positions(node)
    if direct:
        return direct
    # partial(jax.jit, ...) used as a factory or a decorator
    f = node.func
    if isinstance(f, ast.Call) and dotted(f.func) in (
            "partial", "functools.partial"):
        if f.args and dotted(f.args[0]) in ("jax.jit", "jit"):
            return _donate_positions(
                ast.Call(func=f.args[0], args=[], keywords=f.keywords))
    if dotted(f) in ("partial", "functools.partial"):
        # the decorator form: @partial(jax.jit, donate_argnums=...)
        if node.args and dotted(node.args[0]) in ("jax.jit", "jit"):
            return _donate_positions(
                ast.Call(func=node.args[0], args=[],
                         keywords=node.keywords))
    return None


def _is_nonaliasable(expr: ast.AST) -> str | None:
    """Why this expression's value cannot alias into a float output:
    'a scalar literal' / 'an <dtype>-dtyped array', else None."""
    if isinstance(expr, ast.Constant) and isinstance(
            expr.value, (int, float, bool)):
        return "a scalar literal"
    if not isinstance(expr, ast.Call):
        return None
    d = dotted(expr.func) or ""
    leaf = d.rsplit(".", 1)[-1]
    if leaf in _INT_DTYPES:
        return f"an {leaf}-dtyped scalar/array"
    if leaf in _CTORS:
        for kw in expr.keywords:
            if kw.arg == "dtype":
                dt = dotted(kw.value) or (
                    kw.value.value if isinstance(kw.value, ast.Constant)
                    else "")
                dleaf = str(dt).rsplit(".", 1)[-1]
                if dleaf in _INT_DTYPES:
                    return f"an {dleaf}-dtyped array"
    return None


def _assigned_names(stmt: ast.stmt) -> set[str]:
    out: set[str] = set()
    targets: list[ast.AST] = []
    if isinstance(stmt, ast.Assign):
        targets = list(stmt.targets)
    elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
        targets = [stmt.target]
    elif isinstance(stmt, ast.For):
        targets = [stmt.target]
    elif isinstance(stmt, ast.With):
        targets = [i.optional_vars for i in stmt.items if i.optional_vars]
    for t in targets:
        for n in ast.walk(t):
            if isinstance(n, ast.Name):
                out.add(n.id)
    return out


def _position(node: ast.AST) -> tuple[int, int]:
    return (getattr(node, "end_lineno", node.lineno),
            getattr(node, "end_col_offset", 0))


@register
class DonationSafetyRule(Rule):
    id = "donation-safety"
    invariant = ("donated jit arguments are alias-able float leaves and "
                 "are never read after the donating call")
    hint = ("rebind the result over the donated name "
            "(acc = update(acc, ...)), and donate only float-dtyped "
            "array leaves — split int32/scalar leaves out of "
            "donate_argnums (the PR 12 fix)")

    def check(self, src: SourceFile, ctx: Context):
        if src.tree is None:
            return
        donors: dict[str, frozenset[int]] = {}
        for node in ast.walk(src.tree):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                pos = _jit_factory_positions(node.value)
                if pos:
                    donors[node.targets[0].id] = pos
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    pos = _jit_factory_positions(dec)
                    if pos:
                        donors[node.name] = pos
        if not donors:
            return

        for scope, _body in walk_scopes(src.tree):
            yield from self._check_scope(src, scope, donors)

    def _scope_nodes(self, scope: ast.AST):
        """All nodes lexically in this scope, excluding nested function
        bodies (they run at another time, against other bindings)."""
        stack = [scope]
        first = True
        while stack:
            node = stack.pop()
            if not first and isinstance(
                    node, (ast.FunctionDef, ast.AsyncFunctionDef,
                           ast.Lambda)):
                continue
            first = False
            yield node
            stack.extend(ast.iter_child_nodes(node))

    def _check_scope(self, src: SourceFile, scope: ast.AST,
                     donors: dict[str, frozenset[int]]):
        nodes = list(self._scope_nodes(scope))
        # Latest visible constant-ish assignment per name, in source
        # order — the dtype evidence for donated Name arguments.
        assigns: list[tuple[tuple[int, int], str, ast.AST]] = []
        loads: list[tuple[tuple[int, int], ast.Name]] = []
        stores: list[tuple[tuple[int, int], str]] = []
        calls: list[ast.Call] = []
        stmt_of: dict[int, ast.stmt] = {}
        for n in nodes:
            # Map expressions to their innermost SIMPLE statement only
            # (simple statements contain no other statements), so a
            # call inside `for b in ...: acc = f(acc, b)` resolves to
            # the Assign, not the For.
            if isinstance(n, (ast.Assign, ast.AugAssign, ast.AnnAssign,
                              ast.Expr, ast.Return)):
                for sub in ast.walk(n):
                    if not isinstance(sub, ast.stmt):
                        stmt_of[id(sub)] = n
            if isinstance(n, ast.Assign) and len(n.targets) == 1 and \
                    isinstance(n.targets[0], ast.Name):
                assigns.append(((n.lineno, n.col_offset),
                                n.targets[0].id, n.value))
            if isinstance(n, ast.Name):
                if isinstance(n.ctx, ast.Load):
                    loads.append(((n.lineno, n.col_offset), n))
                elif isinstance(n.ctx, ast.Store):
                    stores.append(((n.lineno, n.col_offset), n.id))
            if isinstance(n, ast.Call) and isinstance(n.func, ast.Name) \
                    and n.func.id in donors:
                calls.append(n)

        for call in calls:
            positions = donors[call.func.id]
            end = _position(call)
            # Does the statement containing this call rebind names (the
            # `acc = update(acc, ...)` shape)? Those rebinds take
            # effect immediately after the call for our purposes.
            container = stmt_of.get(id(call))
            rebound = _assigned_names(container) if container is not None \
                else set()
            for i in sorted(positions):
                if i >= len(call.args):
                    continue
                arg = call.args[i]
                evidence = arg
                if isinstance(arg, ast.Name):
                    before = [(p, v) for p, name, v in assigns
                              if name == arg.id
                              and p < (arg.lineno, arg.col_offset)]
                    if before:
                        evidence = max(before)[1]
                why = _is_nonaliasable(evidence)
                if why:
                    yield self.finding(
                        src, arg,
                        f"argument {i} of {call.func.id}() is donated "
                        f"but is {why} — XLA aliases by dtype/shape, "
                        "so this donation is unusable against float "
                        "outputs (PR 12's 'donated buffers were not "
                        "usable' class)",
                        kind="non-aliasable", callee=call.func.id)
                if not isinstance(arg, ast.Name) or arg.id in rebound:
                    continue
                # read-after-donate: a later Load wins unless a Store
                # rebinds the name first.
                later_loads = [p for p, n in loads
                               if n.id == arg.id and p > end]
                if not later_loads:
                    continue
                first_load = min(later_loads)
                rebind = [p for p, name in stores
                          if name == arg.id and end < p < first_load]
                if not rebind:
                    load_node = next(n for p, n in loads
                                     if n.id == arg.id and p == first_load)
                    yield self.finding(
                        src, load_node,
                        f"{arg.id!r} was donated to {call.func.id}() at "
                        f"line {call.lineno} and is read again here — a "
                        "donated buffer's contents are undefined after "
                        "the call",
                        kind="read-after-donate", callee=call.func.id)

"""thread-hygiene — threads the soak harness can account for.

Historical contract (PR 6): the chaos soak's per-round leak accounting
compares live threads against a fixture baseline BY NAME PREFIX
(``tools/soak.py`` ``_SUSPECT_THREADS``). A ``threading.Thread``
created without ``daemon=`` blocks interpreter exit on a crash path,
and one without a ``name`` (or with a prefix the accounting table does
not cover) is a leak the soak structurally cannot see — it rots exactly
like untested code because it IS unaccounted code.

Checks every ``threading.Thread(...)`` call (and
``ThreadPoolExecutor``'s ``thread_name_prefix``): ``daemon=`` must be
explicit, ``name=`` must be present, and a statically-known name
prefix must be covered by ``_SUSPECT_THREADS`` (parsed from
``tools/soak.py``'s AST — no import, so the rule stays jax-free).
Dynamic prefixes (``thread_name_prefix=name``) are left to review.
"""

from __future__ import annotations

import ast

from tools.graftlint.engine import Context, Rule, SourceFile, register
from tools.graftlint.astutil import dotted, import_aliases


def _suspect_prefixes(ctx: Context) -> tuple[str, ...]:
    key = "soak_thread_prefixes"
    if key in ctx.data:
        return ctx.data[key]
    prefixes: tuple[str, ...] = ()
    soak = ctx.root / "tools" / "soak.py"
    try:
        tree = ast.parse(soak.read_text())
    except (OSError, SyntaxError):
        ctx.data[key] = prefixes
        return prefixes
    for node in tree.body:
        if isinstance(node, ast.Assign) and any(
                isinstance(t, ast.Name) and t.id == "_SUSPECT_THREADS"
                for t in node.targets):
            if isinstance(node.value, (ast.Tuple, ast.List)):
                prefixes = tuple(
                    e.value for e in node.value.elts
                    if isinstance(e, ast.Constant)
                    and isinstance(e.value, str))
    ctx.data[key] = prefixes
    return prefixes


def _static_prefix(expr: ast.AST) -> str | None:
    """The statically-known leading part of a thread name: a literal,
    or an f-string's leading constant fragment. None = fully dynamic."""
    if isinstance(expr, ast.Constant) and isinstance(expr.value, str):
        return expr.value
    if isinstance(expr, ast.JoinedStr) and expr.values:
        head = expr.values[0]
        if isinstance(head, ast.Constant) and isinstance(head.value, str):
            return head.value
    return None


@register
class ThreadHygieneRule(Rule):
    id = "thread-hygiene"
    invariant = ("threads carry daemon= and a name whose prefix the "
                 "soak leak accounting (_SUSPECT_THREADS) covers")
    hint = ("pass daemon= and name='<prefix>-...' where <prefix> is in "
            "tools/soak.py _SUSPECT_THREADS (extend the table for a "
            "new long-lived thread family)")

    def check(self, src: SourceFile, ctx: Context):
        if src.tree is None:
            return
        thread_names = {"threading.Thread"} | import_aliases(
            src.tree, "threading.Thread")
        pool_names = {"concurrent.futures.ThreadPoolExecutor",
                      "futures.ThreadPoolExecutor"} | import_aliases(
            src.tree, "concurrent.futures.ThreadPoolExecutor")
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Call):
                continue
            d = dotted(node.func)
            if d in thread_names:
                yield from self._check_thread(src, ctx, node)
            elif d in pool_names:
                yield from self._check_pool(src, ctx, node)

    def _check_thread(self, src, ctx, call: ast.Call):
        kwargs = {kw.arg: kw.value for kw in call.keywords if kw.arg}
        if "daemon" not in kwargs:
            yield self.finding(
                src, call,
                "threading.Thread without daemon= — an implicit "
                "non-daemon thread blocks interpreter exit on every "
                "crash path")
        if "name" not in kwargs:
            yield self.finding(
                src, call,
                "threading.Thread without name= — the soak harness's "
                "leak accounting tracks threads by name prefix; an "
                "anonymous thread is a leak it cannot see")
            return
        yield from self._check_prefix(src, ctx, kwargs["name"],
                                      "thread name")

    def _check_pool(self, src, ctx, call: ast.Call):
        kwargs = {kw.arg: kw.value for kw in call.keywords if kw.arg}
        if "thread_name_prefix" not in kwargs:
            yield self.finding(
                src, call,
                "ThreadPoolExecutor without thread_name_prefix= — its "
                "anonymous workers are invisible to the soak leak "
                "accounting")
            return
        yield from self._check_prefix(src, ctx,
                                      kwargs["thread_name_prefix"],
                                      "thread_name_prefix")

    def _check_prefix(self, src, ctx, name_expr, what):
        prefix = _static_prefix(name_expr)
        if prefix is None:
            return  # fully dynamic: review-time, not lint-time
        covered = any(prefix.startswith(p)
                      for p in _suspect_prefixes(ctx))
        if not covered:
            yield self.finding(
                src, name_expr,
                f"{what} {prefix!r} is outside the soak leak "
                "accounting (tools/soak.py _SUSPECT_THREADS covers "
                "none of it)")

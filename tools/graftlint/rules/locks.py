"""blocking-under-lock — slow or re-entrant-hostile work inside a lock.

Historical bug (PR 6): the SIGTERM drain path flushed telemetry while
the module lock was already held by the thread the signal interrupted —
the flush needed the same lock and the process deadlocked inside its
own shutdown handler. The general class: file I/O, sleeps, subprocess
or socket calls, or a jax dispatch lexically inside a ``with <lock>:``
body (or between ``lock.acquire()``/``lock.release()``) turns every
other contender — including signal handlers and watchdog threads — into
a hostage of that I/O's latency or failure.

Lexical and deliberately shallow: a call that *leads to* I/O through
another function is not flagged (that function's own lock usage is).
Deliberate short-critical-section writes (e.g. the quarantine ledger's
serialized tmp+rename) carry a reasoned suppression.
"""

from __future__ import annotations

import ast

from tools.graftlint.engine import Context, Rule, SourceFile, register
from tools.graftlint.astutil import dotted


_LOCK_WORDS = frozenset(("lock", "locks", "rlock", "mutex"))


def _lock_named(identifier: str) -> bool:
    """'lock' as a whole underscore-separated word — self._lock, _LOCK,
    stats.lock, _lock_for, _locks_guard — but NOT the substring inside
    this codebase's 'block*' vocabulary (block_reader, blocks, ...)."""
    return any(part in _LOCK_WORDS
               for part in identifier.lower().split("_"))


def _is_lockish(expr: ast.AST) -> bool:
    """The with-item / receiver smells like a lock."""
    if isinstance(expr, ast.Call):
        return _is_lockish(expr.func)
    if isinstance(expr, ast.Attribute):
        return _lock_named(expr.attr)
    if isinstance(expr, ast.Name):
        return _lock_named(expr.id)
    return False


_BLOCKING_PREFIXES = ("subprocess.", "socket.", "shutil.")
_BLOCKING_EXACT = ("time.sleep", "os.fsync", "open", "device_put")
_BLOCKING_METHODS = ("write_text", "read_text", "write_bytes",
                     "read_bytes", "block_until_ready", "recv", "send",
                     "sendall", "accept", "connect", "device_put")


def _blocking_reason(call: ast.Call) -> str | None:
    d = dotted(call.func)
    if d:
        if d in _BLOCKING_EXACT:
            return f"{d}()"
        for p in _BLOCKING_PREFIXES:
            if d.startswith(p):
                return f"{d}()"
        if d.startswith("jax.") or d.startswith("jnp."):
            return f"jax dispatch {d}()"
    if isinstance(call.func, ast.Attribute) and \
            call.func.attr in _BLOCKING_METHODS:
        return f".{call.func.attr}()"
    return None


def _calls_in(node: ast.AST, *, skip_nested_defs: bool = True):
    """Calls lexically under ``node``, excluding nested function/lambda
    bodies (deferred execution does not run under the lock)."""
    stack = list(ast.iter_child_nodes(node))
    while stack:
        n = stack.pop()
        if skip_nested_defs and isinstance(
                n, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            continue
        if isinstance(n, ast.Call):
            yield n
        stack.extend(ast.iter_child_nodes(n))


@register
class BlockingUnderLockRule(Rule):
    id = "blocking-under-lock"
    invariant = ("no file I/O, sleeps, subprocess/socket calls, or jax "
                 "dispatch inside a lock's critical section")
    hint = ("move the blocking work outside the critical section "
            "(snapshot under the lock, write after releasing), or "
            "suppress with the reason the section must stay atomic")

    def check(self, src: SourceFile, ctx: Context):
        if src.tree is None:
            return
        seen: set[int] = set()
        for node in ast.walk(src.tree):
            if isinstance(node, ast.With) and any(
                    _is_lockish(item.context_expr)
                    for item in node.items):
                lock_txt = src.segment(node.items[0].context_expr)
                for call in _calls_in(node):
                    if id(call) in seen:
                        continue
                    reason = _blocking_reason(call)
                    if reason:
                        seen.add(id(call))
                        yield self.finding(
                            src, call,
                            f"{reason} inside `with {lock_txt}:` — "
                            "every contender (including signal/"
                            "shutdown paths) blocks on this call (the "
                            "PR 6 SIGTERM-flush deadlock class)",
                            op=reason)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                   ast.Module)):
                yield from self._acquire_release(src, node, seen)

    def _acquire_release(self, src: SourceFile, scope: ast.AST,
                         seen: set[int]):
        """Explicit acquire()/release() pairs on a lock-named receiver:
        blocking calls positioned between them are inside the critical
        section (try/finally shapes included — the scan is positional,
        matching how the code actually executes on the happy path)."""
        calls = sorted(
            _calls_in(scope),
            key=lambda c: (c.lineno, c.col_offset))
        open_at: dict[str, tuple[int, int]] = {}
        regions: list[tuple[str, tuple[int, int], tuple[int, int]]] = []
        for c in calls:
            if isinstance(c.func, ast.Attribute) and _is_lockish(
                    c.func.value):
                recv = src.segment(c.func.value)
                if c.func.attr == "acquire":
                    open_at[recv] = (c.lineno, c.col_offset)
                elif c.func.attr == "release" and recv in open_at:
                    regions.append((recv, open_at.pop(recv),
                                    (c.lineno, c.col_offset)))
        for c in calls:
            if id(c) in seen:
                continue
            reason = _blocking_reason(c)
            if not reason:
                continue
            pos = (c.lineno, c.col_offset)
            for recv, lo, hi in regions:
                if lo < pos < hi:
                    seen.add(id(c))
                    yield self.finding(
                        src, c,
                        f"{reason} between {recv}.acquire() (line "
                        f"{lo[0]}) and {recv}.release() (line {hi[0]}) "
                        "— the critical section spans this blocking "
                        "call (the PR 6 SIGTERM-flush deadlock class)",
                        op=reason)
                    break

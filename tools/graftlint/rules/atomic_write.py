"""atomic-write — durable artifacts must land via tmp + rename.

Historical bug (PR 8): telemetry's ``metrics.json`` / trace exports
were written in place; a kill mid-write left a torn, unparseable
snapshot exactly when the post-mortem needed it. The repo-wide
discipline since: every durable artifact (manifest, checkpoint,
heartbeat, quarantine ledger, telemetry snapshot, supervisor incident
ledger, bench history) is written to a tmp name and published with one
atomic ``os.replace``.

The rule flags ``open(path, "w"/"wb")`` and ``Path.write_text/_bytes``
where the path expression's source text names a durable-artifact token
but not a tmp staging name, and the enclosing function performs no
``os.replace``/``os.rename`` (i.e. it is not itself the atomic-publish
helper).
"""

from __future__ import annotations

import ast

from tools.graftlint.engine import Context, Rule, SourceFile, register
from tools.graftlint.astutil import dotted

_DURABLE_TOKENS = ("manifest", "checkpoint", "heartbeat", "quarantine",
                   "metrics", "trace", "supervisor", "history",
                   "ledger", "snapshot", "telemetry")


def _scope_calls(scope: ast.AST):
    stack = list(ast.iter_child_nodes(scope))
    while stack:
        n = stack.pop()
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.Lambda)):
            continue
        if isinstance(n, ast.Call):
            yield n
        stack.extend(ast.iter_child_nodes(n))


def _write_mode(call: ast.Call) -> bool:
    """open(...) in a truncating write mode."""
    mode = None
    if len(call.args) >= 2:
        mode = call.args[1]
    for kw in call.keywords:
        if kw.arg == "mode":
            mode = kw.value
    return (isinstance(mode, ast.Constant)
            and isinstance(mode.value, str)
            and mode.value.startswith("w"))


@register
class AtomicWriteRule(Rule):
    id = "atomic-write"
    invariant = ("durable artifacts are published tmp + os.replace, "
                 "never written in place")
    hint = ("write to a tmp sibling and os.replace() it into place "
            "(see telemetry._atomic_write / store.writer), so a kill "
            "mid-write leaves the last-good file readable")

    def check(self, src: SourceFile, ctx: Context):
        if src.tree is None:
            return
        scopes = [src.tree] + [
            n for n in ast.walk(src.tree)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
        for scope in scopes:
            calls = list(_scope_calls(scope))
            atomic_scope = any(
                dotted(c.func) in ("os.replace", "os.rename")
                for c in calls)
            if atomic_scope:
                # The scope stages a tmp file and publishes atomically;
                # its raw writes are the staging half of the protocol.
                continue
            for call in calls:
                d = dotted(call.func)
                path_expr = None
                via = None
                if d == "open" and call.args and _write_mode(call):
                    path_expr, via = call.args[0], "open(..., 'w')"
                elif isinstance(call.func, ast.Attribute) and \
                        call.func.attr in ("write_text", "write_bytes"):
                    path_expr = call.func.value
                    via = f".{call.func.attr}()"
                if path_expr is None:
                    continue
                text = src.segment(path_expr).lower()
                if "tmp" in text:
                    continue
                token = next((t for t in _DURABLE_TOKENS if t in text),
                             None)
                if token is None:
                    continue
                yield self.finding(
                    src, call,
                    f"raw {via} to a durable artifact path "
                    f"({src.segment(path_expr)!r} names {token!r}) — a "
                    "kill mid-write tears it (the PR 8 torn-snapshot "
                    "class)",
                    token=token)

"""telemetry-name / fault-site — canonical-name discipline, on the AST.

Historical contract (PRs 2/4/6): every telemetry name used at a call
site must be declared in ``telemetry.NAMES`` (a typo silently forks a
metric series), and every ``faults.fire`` site must be declared in
``faults.SITES`` (an undeclared site is unarm-able from the env
grammar — a recovery path the chaos harness can never reach). The old
regex lints (tests/test_telemetry_names.py) enforced this for
single-line literal call sites only; these AST rules also see through

- **aliasing**: ``from spark_examples_tpu.core import telemetry as t``
  (and ``import spark_examples_tpu.core.telemetry as tm``),
- **concatenation**: ``telemetry.count("store." + "healed")`` and
  module-level ``NAME = "..."`` constants,
- **multi-line calls**: the regexes anchored on one line.

Telemetry names that are genuinely dynamic (a variable argument, e.g.
``PhaseTimer``'s ``"phase." + name``) remain the runtime registry
check's job — but an f-string at a call site is a finding (literal
sites must stay literal), and fault SITES must be static strings
outright: a site is a greppable constant or the harness docs cannot
reference it.

The fault-site rule's finalize (full-repo runs only) also reports
**dead registry entries**: a declared site nothing fires is a
documented injection point the harness can't hit.
"""

from __future__ import annotations

import ast

from tools.graftlint.engine import Context, Rule, SourceFile, register
from tools.graftlint.astutil import (
    DYNAMIC,
    call_roots,
    dotted,
    fold_string,
    module_string_env,
)

TELEMETRY_MOD = "spark_examples_tpu.core.telemetry"
FAULTS_MOD = "spark_examples_tpu.core.faults"
TELEMETRY_APIS = ("count", "observe", "gauge_set", "event", "begin",
                  "span", "traced", "counter_value")


def _has_fstring_hole(node: ast.AST) -> bool:
    return any(isinstance(n, ast.FormattedValue)
               for n in ast.walk(node))


def _api_calls(src: SourceFile, module: str, apis):
    roots = call_roots(src.tree, module)
    if not roots:
        return
    for node in ast.walk(src.tree):
        if not isinstance(node, ast.Call):
            continue
        d = dotted(node.func)
        if d is None or "." not in d:
            continue
        root, _, leaf = d.rpartition(".")
        if root in roots and leaf in apis:
            yield node, leaf


@register
class TelemetryNameRule(Rule):
    id = "telemetry-name"
    invariant = ("every telemetry name at a call site is declared in "
                 "telemetry.NAMES; literal sites stay literal")
    hint = ("add the name to telemetry.NAMES (core/telemetry.py) — or "
            "for a dynamic family, declare the 'family.*' entry and "
            "pass the dynamic part as attrs, not an f-string")

    def check(self, src: SourceFile, ctx: Context):
        if src.tree is None:
            return
        env = None
        for call, api in _api_calls(src, TELEMETRY_MOD, TELEMETRY_APIS):
            if not call.args:
                continue
            if env is None:
                env = module_string_env(src.tree)
            name_expr = call.args[0]
            folded = fold_string(name_expr, env)
            if isinstance(folded, str):
                if not ctx.telemetry().is_declared(folded):
                    yield self.finding(
                        src, name_expr,
                        f"telemetry.{api}({folded!r}): name not "
                        "declared in telemetry.NAMES — an undeclared "
                        "name forks a metric series nobody joins back",
                        name=folded, api=api, dynamic=False)
            elif folded is DYNAMIC and _has_fstring_hole(name_expr):
                yield self.finding(
                    src, name_expr,
                    f"telemetry.{api}(f\"...\"): an f-string name "
                    "cannot be statically checked — literal sites must "
                    "stay literal (use attrs for the dynamic part)",
                    api=api, dynamic=True)


@register
class FaultSiteRule(Rule):
    id = "fault-site"
    invariant = ("every faults.fire site is a literal declared in "
                 "faults.SITES, and every declared site is fired "
                 "somewhere")
    hint = ("declare the site in faults.SITES (core/faults.py) so "
            "specs can arm it; sites must be static strings — the "
            "harness docs and chaos specs reference them by grep")

    def check(self, src: SourceFile, ctx: Context):
        if src.tree is None:
            return
        env = None
        fired = ctx.data.setdefault("fired_fault_sites", set())
        for call, _api in _api_calls(src, FAULTS_MOD, ("fire",)):
            if not call.args:
                continue
            if env is None:
                env = module_string_env(src.tree)
            site_expr = call.args[0]
            folded = fold_string(site_expr, env)
            if isinstance(folded, str):
                fired.add(folded)
                if folded not in ctx.faults().SITES:
                    yield self.finding(
                        src, site_expr,
                        f"faults.fire({folded!r}): site not declared "
                        "in faults.SITES — an undeclared site is "
                        "unarm-able from the env grammar",
                        site=folded, dynamic=False)
            else:
                yield self.finding(
                    src, site_expr,
                    "faults.fire with a non-literal site — sites must "
                    "be greppable constants for the harness's docs and "
                    "specs to reference",
                    dynamic=True)

    def finalize(self, ctx: Context):
        fired = ctx.data.get("fired_fault_sites", set())
        dead = sorted(set(ctx.faults().SITES) - fired)
        if not dead:
            return
        # Anchor at the SITES assignment in core/faults.py.
        src = next((f for f in ctx.files
                    if f.module == FAULTS_MOD), None)
        path, line, col = FAULTS_MOD.replace(".", "/") + ".py", 1, 1
        if src is not None:
            path = src.rel
            if src.tree is not None:
                for node in src.tree.body:
                    if isinstance(node, ast.Assign) and any(
                            isinstance(t, ast.Name) and t.id == "SITES"
                            for t in node.targets):
                        line, col = node.lineno, node.col_offset + 1
                        break
        from tools.graftlint.engine import Finding

        yield Finding(
            path=path, line=line, col=col, rule=self.id,
            message=f"declared fault sites never fired in code: {dead} "
                    "— a dead registry entry documents an injection "
                    "point the harness can't hit",
            hint="fire the site in the recovery path it documents, or "
                 "drop the registry entry",
            data={"dead": dead})

"""jax-import-purity — the contractually device-free modules stay that
way, transitively.

Historical contract (PRs 6/8): the supervised-CLI parent must never
hold a device — it imports ``cli.main``'s module surface (config,
kernels, supervisor, faults, telemetry) BEFORE re-invoking the child,
and a module-level ``import jax`` anywhere in that closure silently
puts a jax runtime (and on TPU, the chip lock) into the watchdog
process. The same purity is what lets config-time validation and the
kernel registry run in the parent and in graftlint itself.

The rule walks the module-level import graph (function-level imports
are lazy by construction and excluded; ``if TYPE_CHECKING:`` blocks
too) from each contract root and reports the import statement that
begins a chain reaching ``jax``/``jaxlib``.

Fixtures claim a contract identity with ``# graftlint: module=...``.
"""

from __future__ import annotations

import ast
import pathlib

from tools.graftlint.engine import Context, Rule, SourceFile, register

# Module paths that must be importable without jax: the kernel registry
# (config + CLI parent consume it), config-time validation, the fault
# registry, the supervisor parent path, and the CLI module surface the
# parent imports before any child exists.
CONTRACT = (
    "spark_examples_tpu.kernels",
    "spark_examples_tpu.core.config",
    "spark_examples_tpu.core.faults",
    "spark_examples_tpu.core.telemetry",
    "spark_examples_tpu.core.supervisor",
    "spark_examples_tpu.cli.main",
)

_JAX_ROOTS = ("jax", "jaxlib")
PACKAGE = "spark_examples_tpu"


def _module_level_imports(tree: ast.Module):
    """(node, dotted targets) for imports that execute at import time:
    module body, class bodies, module-level try/if — but not function
    bodies and not ``if TYPE_CHECKING:`` blocks."""
    stack: list[ast.AST] = [tree]
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        if isinstance(node, ast.If):
            t = node.test
            name = t.attr if isinstance(t, ast.Attribute) else (
                t.id if isinstance(t, ast.Name) else "")
            if name == "TYPE_CHECKING":
                continue
        if isinstance(node, ast.Import):
            yield node, [a.name for a in node.names]
        elif isinstance(node, ast.ImportFrom):
            if node.level or not node.module:
                continue  # the repo uses absolute imports throughout
            if node.module == "__future__":
                continue
            targets = []
            for a in node.names:
                # `from a.b import c` is module a.b.c when c is a
                # module, else an attribute of a.b — try both.
                targets.append(f"{node.module}.{a.name}")
            targets.append(node.module)
            yield node, targets
        stack.extend(ast.iter_child_nodes(node))


def _ancestors(dotted: str):
    parts = dotted.split(".")
    for i in range(1, len(parts)):
        yield ".".join(parts[:i])


@register
class JaxImportPurityRule(Rule):
    id = "jax-import-purity"
    invariant = ("kernels/, core/config, core/faults, core/telemetry, "
                 "core/supervisor, and cli/main import no jax at module "
                 "level, transitively")
    hint = ("move the jax import inside the function that needs it — "
            "the supervised parent and config-time validation must run "
            "device-free")

    def _chain(self, ctx: Context, dotted: str,
               cache: dict, visiting: set) -> list[str] | None:
        """The module chain from ``dotted`` to a jax import, or None.
        Only package-internal modules are walked; external deps other
        than jax are leaves."""
        root = dotted.split(".", 1)[0]
        if root in _JAX_ROOTS:
            return [dotted]
        if root != PACKAGE:
            return None
        if dotted in cache:
            return cache[dotted]
        if dotted in visiting:
            return None  # import cycle: resolved by the other branch
        path = ctx.module_file(dotted)
        if path is None:
            return None
        visiting.add(dotted)
        chain = None
        try:
            tree = ast.parse(path.read_text())
        except (OSError, SyntaxError):
            visiting.discard(dotted)
            cache[dotted] = None
            return None
        for _node, targets in _module_level_imports(tree):
            for target in targets:
                sub = self._resolve(ctx, target, cache, visiting)
                if sub:
                    chain = [dotted] + sub
                    break
            if chain:
                break
        visiting.discard(dotted)
        cache[dotted] = chain
        return chain

    def _resolve(self, ctx: Context, target: str, cache, visiting):
        """Chain for an import target, including the ancestor package
        __init__ executions a dotted import implies."""
        for anc in _ancestors(target):
            if ctx.module_file(anc) is not None:
                sub = self._chain(ctx, anc, cache, visiting)
                if sub:
                    return sub
        if target.split(".", 1)[0] == PACKAGE and \
                ctx.module_file(target) is None:
            return None  # `from mod import attr` where attr is no module
        return self._chain(ctx, target, cache, visiting)

    def check(self, src: SourceFile, ctx: Context):
        if src.tree is None or src.module is None:
            return
        if not any(src.module == c or src.module.startswith(c + ".")
                   for c in CONTRACT):
            return
        cache = ctx.data.setdefault("jax_purity_cache", {})
        for node, targets in _module_level_imports(src.tree):
            for target in targets:
                chain = self._resolve(ctx, target, cache, set())
                if chain:
                    arrow = " -> ".join([src.module] + chain)
                    yield self.finding(
                        src, node,
                        f"module-level import reaches jax ({arrow}) — "
                        f"{src.module} is contractually jax-free at "
                        "import (the supervised parent / config-time "
                        "path must never hold a device)",
                        chain=chain)
                    break

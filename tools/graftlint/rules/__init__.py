"""Importing this package registers every analyzer with the engine.

One module per invariant family; each rule's docstring names the
historical regression it distills (the fixture under
``tests/fixtures/graftlint/`` replays it)."""

from tools.graftlint.rules import (  # noqa: F401
    atomic_write,
    donation,
    jax_purity,
    locks,
    names,
    registry_literal,
    threads,
)

"""registry-literal — hand-enumerated registry values drift.

Historical bug (PR 11): the CLI shipped with a hard-coded ``--metric``
``choices`` list, so the freshly registered Jaccard kernel was
unreachable from the command line until a verify drive noticed. The
same failure mode exists for every enum family that has a single
source of truth: a literal collection re-listing its members goes
silently stale the day the registry grows.

The rule flags any list/tuple/set literal of >= 2 distinct strings
drawn entirely from one registry family — kernel names (the live
``spark_examples_tpu.kernels`` registry) or one of the config enum
tuples (solver ladder, store codecs, tile2d transports, gram modes,
eigh modes, braycurtis methods, backends, pack streams) — anywhere
outside the family's defining module. Consumers must derive from the
registry (``list(kernels.names())``, ``config.SOLVER_LADDER``, ...).
"""

from __future__ import annotations

import ast

from tools.graftlint.engine import Context, Rule, SourceFile, register


@register
class RegistryLiteralRule(Rule):
    id = "registry-literal"
    invariant = ("enum collections are derived from their registry, "
                 "never re-listed as literals")
    hint = ("derive from the registry: list(kernels.names()), "
            "config.SOLVER_LADDER, config.STORE_CODEC_SPECS, "
            "config.TILE2D_TRANSPORTS, ...")

    def _families(self, ctx: Context):
        fams = [("kernel", ctx.kernel_names(),
                 "spark_examples_tpu.kernels")]
        for label, (values, mod) in ctx.config_enums().items():
            fams.append((label, frozenset(values), mod))
        return fams

    def check(self, src: SourceFile, ctx: Context):
        if src.tree is None:
            return
        families = self._families(ctx)
        for node in ast.walk(src.tree):
            if not isinstance(node, (ast.List, ast.Tuple, ast.Set)):
                continue
            if len(node.elts) < 2:
                continue
            values = [e.value for e in node.elts
                      if isinstance(e, ast.Constant)
                      and isinstance(e.value, str)]
            if len(values) != len(node.elts):
                continue  # a non-string element: not an enum listing
            distinct = set(values)
            if len(distinct) < 2:
                continue
            for label, members, defining in families:
                if distinct <= members:
                    if src.module and (
                            src.module == defining
                            or src.module.startswith(defining + ".")):
                        break  # the registry defining itself
                    yield self.finding(
                        src, node,
                        f"literal collection of {label} registry values "
                        f"{sorted(distinct)} outside {defining} — it "
                        "goes stale when the registry grows (the PR 11 "
                        "unreachable-Jaccard class)",
                        family=label, values=sorted(distinct))
                    break

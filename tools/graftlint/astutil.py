"""Shared AST helpers for graftlint rules: dotted-name rendering,
static string folding, and import-alias tracking — the pieces that let
AST rules see through the aliasing/concatenation/multi-line shapes the
old regex lints missed."""

from __future__ import annotations

import ast


def dotted(node: ast.AST) -> str | None:
    """``a.b.c`` for a Name/Attribute chain, else None."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


DYNAMIC = object()  # sentinel: expression has a runtime-dependent part


def fold_string(node: ast.AST, env: dict[str, str] | None = None):
    """Statically evaluate a string expression.

    Returns the folded ``str``, ``DYNAMIC`` when any part is runtime-
    dependent (f-string holes, calls, unknown names), or ``None`` when
    the expression is not string-shaped at all. ``env`` maps plain
    names to known constant strings (module-level ``NAME = "..."``
    aliases)."""
    if isinstance(node, ast.Constant):
        return node.value if isinstance(node.value, str) else None
    if isinstance(node, ast.JoinedStr):
        parts = []
        for v in node.values:
            if isinstance(v, ast.Constant) and isinstance(v.value, str):
                parts.append(v.value)
            else:
                return DYNAMIC
        return "".join(parts)  # f-string with no holes
    if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Add):
        left = fold_string(node.left, env)
        right = fold_string(node.right, env)
        if left is None or right is None:
            return None
        if left is DYNAMIC or right is DYNAMIC:
            return DYNAMIC
        return left + right
    if isinstance(node, ast.Name) and env is not None:
        if node.id in env:
            return env[node.id]
        return DYNAMIC
    if isinstance(node, (ast.Name, ast.Attribute, ast.Call,
                         ast.Subscript)):
        return DYNAMIC
    return None


def module_string_env(tree: ast.Module) -> dict[str, str]:
    """Module-level ``NAME = "literal"`` bindings (single-target,
    assigned exactly once) — the alias table ``fold_string`` resolves
    plain names against."""
    env: dict[str, str] = {}
    seen: set[str] = set()
    for node in tree.body:
        if (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and isinstance(node.value, ast.Constant)
                and isinstance(node.value.value, str)):
            name = node.targets[0].id
            if name in seen:
                env.pop(name, None)
            else:
                env[name] = node.value.value
                seen.add(name)
    return env


def import_aliases(tree: ast.Module, module: str) -> set[str]:
    """Local names bound to ``module`` (a dotted path) by any import
    form: ``import a.b.c as x``, ``from a.b import c [as x]``. The
    bare ``import a.b.c`` (no alias) binds the root ``a`` — attribute
    chains through it are matched by callers via :func:`dotted`."""
    names: set[str] = set()
    parent, _, leaf = module.rpartition(".")
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name == module and a.asname:
                    names.add(a.asname)
        elif isinstance(node, ast.ImportFrom) and node.level == 0:
            if node.module == parent:
                for a in node.names:
                    if a.name == leaf:
                        names.add(a.asname or a.name)
    return names


def call_roots(tree: ast.Module, module: str) -> set[str]:
    """All dotted prefixes through which ``module``'s attributes are
    reachable in this file: the import aliases plus the full dotted
    path when ``import a.b.c`` appears bare."""
    roots = set(import_aliases(tree, module))
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name == module and not a.asname:
                    roots.add(module)
    return roots


def walk_scopes(tree: ast.Module):
    """Yield (scope_node, body) for the module and every function —
    the unit of the linear read-after-call analyses."""
    yield tree, tree.body
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node, node.body

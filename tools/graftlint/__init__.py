"""graftlint — AST-based invariant analyzers grown from the repo's own
bug history.

Every rule in ``tools/graftlint/rules`` is a distilled regression from a
shipped PR (the motivating bug is named in each rule's docstring and in
README "Static analysis"): the hard-coded CLI ``--metric`` choices list
that made the freshly registered Jaccard kernel unreachable, donated jit
buffers that XLA could never alias (or that the caller read back after
the call), blocking I/O inside lock bodies that later deadlocked the
SIGTERM flush path, raw ``open(path, "w")`` writes to durable artifacts
that tore under kill, ``import jax`` leaking into the modules the
supervised-CLI parent must import device-free, telemetry/fault-site
name drift past the old regex lints, and unnamed/non-daemon threads the
soak harness's leak accounting cannot see.

Stdlib-only and jax-free at import: the whole suite is ``ast`` +
``pathlib`` and may be run by the supervised CLI parent, CI, or bench
without initializing any accelerator backend. Registries it validates
against (kernel names, ``telemetry.NAMES``, ``faults.SITES``, the
config enum tuples) are imported lazily at *check* time from modules
that are themselves contractually jax-free — and the
``jax-import-purity`` rule is what keeps that contract honest.

Usage::

    python -m tools.graftlint                    # whole repo, exit 1 on findings
    python -m tools.graftlint --rules donation-safety,atomic-write
    python -m tools.graftlint --format json path/to/file.py
    python -m spark_examples_tpu lint            # same thing, CLI verb

Suppressions are inline, per line, and MUST carry a reason::

    with self._lock:
        data = f.read()  # graftlint: disable=blocking-under-lock  # <why this one is safe>

A reasonless suppression is itself a finding (``suppression-reason``):
an exception nobody can re-evaluate is just a latent bug with a
comment.
"""

from tools.graftlint.engine import (  # noqa: F401
    Finding,
    all_rules,
    collect_string_constants,
    format_findings,
    run,
)
from tools.graftlint import rules as _rules  # noqa: F401  (registers)

"""Operational tooling that is not part of the library API (the chaos
soak harness). Importable as ``tools.*`` from the repo root — bench.py
and the test suite both run with the repo on ``sys.path``."""

"""Parallel ingest engine (ingest/parallel.py), store readahead
(store/readahead.py), and the K-deep staged device feed
(ingest/prefetch.py): ordered-reassembly determinism — N-worker parses,
compactions, and readahead streams must be byte/bit-identical to the
serial path, including when faults fire inside a pool worker — plus the
config-time knob validation that keeps nonsense values out of worker
threads."""

import os
import warnings

import numpy as np
import pytest

from spark_examples_tpu.core import faults
from spark_examples_tpu.core.config import IngestConfig
from spark_examples_tpu.ingest import bitpack
from spark_examples_tpu.ingest.parallel import (
    parallel_blocks,
    parallel_map_ordered,
    vcf_byte_shards,
)
from spark_examples_tpu.ingest.prefetch import stream_to_device
from spark_examples_tpu.ingest.resilient import (
    IngestExhaustedError,
    RetryingSource,
    RetryPolicy,
)
from spark_examples_tpu.ingest.source import ArraySource
from spark_examples_tpu.ingest.synthetic import SyntheticSource
from spark_examples_tpu.ingest.vcf import VcfSource, write_vcf
from spark_examples_tpu.store import StoreCorruptError, compact, open_store
from tests.conftest import random_genotypes


def _materialize(source, block_variants, start=0):
    blocks = [b for b, _ in source.blocks(block_variants, start)]
    return np.concatenate(blocks, axis=1) if blocks else None


def _metas(stream):
    return [(m.index, m.start, m.stop, m.contig) for _b, m in stream]


@pytest.fixture
def multi_vcf(tmp_path, rng):
    """A two-contig VCF (chr1 x 53 + chr2 x 19) with tiny forced shards
    so even the toy file exercises multi-shard reassembly."""
    import spark_examples_tpu.ingest.parallel as par

    g1 = random_genotypes(rng, 11, 53, 0.1)
    g2 = random_genotypes(rng, 11, 19, 0.1)
    p1, p2 = str(tmp_path / "a.vcf"), str(tmp_path / "b.vcf")
    write_vcf(p1, g1, contig="chr1", start_pos=100)
    write_vcf(p2, g2, contig="chr2", start_pos=500)
    header = [ln for ln in open(p1) if ln.startswith("#")]
    records = [ln for p in (p1, p2) for ln in open(p)
               if not ln.startswith("#")]
    multi = str(tmp_path / "multi.vcf")
    open(multi, "w").writelines(header + records)
    old = par.VCF_SHARD_BYTES
    par.VCF_SHARD_BYTES = 1024
    yield multi, np.concatenate([g1, g2], axis=1)
    par.VCF_SHARD_BYTES = old


# ---------------------------------------------------------------------------
# The ordered reassembly primitive.


def test_parallel_map_ordered_preserves_order():
    out = list(parallel_map_ordered(range(64), lambda x: x * x, 5))
    assert out == [x * x for x in range(64)]


def test_parallel_map_ordered_propagates_error_in_order():
    seen = []

    def fn(x):
        if x == 7:
            raise RuntimeError("worker died")
        return x

    with pytest.raises(RuntimeError, match="worker died"):
        for v in parallel_map_ordered(range(32), fn, 4):
            seen.append(v)
    # Every in-order predecessor was delivered before the failure.
    assert seen == list(range(7))


def test_parallel_map_ordered_single_worker_is_plain_map():
    assert list(parallel_map_ordered(range(5), str, 1)) == list("01234")


# ---------------------------------------------------------------------------
# Parallel parse determinism.


def test_vcf_byte_shards_cover_exactly(multi_vcf):
    path, _g = multi_vcf
    shards = vcf_byte_shards(path, target_bytes=512)
    assert len(shards) > 2
    # Contiguous, non-overlapping, ending at EOF.
    for (a, b), (c, _d) in zip(shards, shards[1:]):
        assert b == c and b > a
    assert shards[-1][1] == os.path.getsize(path)


def test_parallel_vcf_blocks_bit_identical(multi_vcf):
    path, want = multi_vcf
    serial = list(VcfSource(path).blocks(16))
    par = list(parallel_blocks(VcfSource(path), 16, 4))
    assert _metas(serial) == _metas(par)
    for (b1, m1), (b2, m2) in zip(serial, par):
        np.testing.assert_array_equal(b1, b2)
        np.testing.assert_array_equal(m1.positions, m2.positions)
    np.testing.assert_array_equal(
        np.concatenate([b for b, _ in par], axis=1), want)


def test_parallel_blocks_stripe_mode_bit_identical():
    src = SyntheticSource(n_samples=9, n_variants=700, seed=3)
    serial = list(src.blocks(64))
    par = list(parallel_blocks(src, 64, 4))
    assert _metas(serial) == _metas(par)
    for (b1, _), (b2, _) in zip(serial, par):
        np.testing.assert_array_equal(b1, b2)


def test_parallel_blocks_serial_fallback_for_unshardable(multi_vcf, tmp_path):
    # gzip VCF cannot seek -> byte-range sharding must decline, stream
    # still correct through the serial fallback.
    import gzip
    import shutil

    path, want = multi_vcf
    gz = str(tmp_path / "m.vcf.gz")
    with open(path, "rb") as f_in, gzip.open(gz, "wb") as f_out:
        shutil.copyfileobj(f_in, f_out)
    got = np.concatenate(
        [b for b, _ in parallel_blocks(VcfSource(gz), 16, 4)], axis=1)
    np.testing.assert_array_equal(got, want)


def test_batch_parser_pinned_to_python_on_adversarial_records():
    """The native batch parser (vcf_parse_block) against the Python
    record parser on every skip/edge case in one buffer: header lines,
    short fields, no-GT FORMAT, short sample columns, CRLF, half-calls,
    multi-allelic dosage capping, missing subfields, contig changes."""
    from spark_examples_tpu import native
    from spark_examples_tpu.ingest.parallel import (
        _parse_vcf_range_py,
    )

    if native.load() is None:
        pytest.skip("native codec unavailable")
    n = 3
    buf = b"".join([
        b"##meta\n",
        b"#CHROM\tPOS\tID\tREF\tALT\tQUAL\tFILTER\tINFO\tFORMAT\tA\tB\tC\n",
        b"chr1\t100\t.\tA\tC\t.\t.\t.\tGT\t0/1\t1|1\t./.\n",
        b"chr1\t101\t.\tA\tC\t.\t.\t.\tDP:GT\t3:1/2\t4:0/.\t5\n",  # GT 2nd; C missing subfield
        b"chr1\t102\t.\tA\tC\t.\t.\t.\tDP\t3\t4\t5\n",  # no GT -> skip
        b"chr1\t103\tshort\n",  # <10 fields -> skip
        b"chr1\t104\t.\tA\tC\t.\t.\t.\tGT\t0/0\t1/1\n",  # short columns
        b"chr2\t50\t.\tA\tC\t.\t.\t.\tGT\t1/1/1\t.\t0|1\r\n",  # CRLF, capped
    ])
    with warnings.catch_warnings():
        warnings.simplefilter("ignore", RuntimeWarning)
        py = _parse_vcf_range_py(buf, "x.vcf", n, None)
        nat = native.vcf_parse_block(buf, n)
    rows, pos, contigs, n_short = nat
    assert n_short == 1  # the 104 record
    want_cols = np.concatenate([c for c, _p, _ in py], axis=1)
    np.testing.assert_array_equal(rows.T, want_cols)
    np.testing.assert_array_equal(
        pos, np.concatenate([p for _c, p, _ in py]))
    # py pieces are per-contig-run; native contigs are per-record.
    want_contigs = [c for _b, p, c in py for _ in range(len(p))]
    assert contigs == want_contigs


# ---------------------------------------------------------------------------
# Parallel compaction determinism (the satellite's core claim).


def _store_bytes(d):
    with open(os.path.join(d, "manifest.json"), "rb") as f:
        manifest = f.read()
    chunks = {}
    for name in sorted(os.listdir(os.path.join(d, "chunks"))):
        with open(os.path.join(d, "chunks", name), "rb") as f:
            chunks[name] = f.read()
    return manifest, chunks


def test_compact_workers_byte_identical_vcf(multi_vcf, tmp_path):
    path, _want = multi_vcf
    d1, d4 = str(tmp_path / "w1"), str(tmp_path / "w4")
    compact(d1, VcfSource(path), chunk_variants=16, workers=1)
    compact(d4, VcfSource(path), chunk_variants=16, workers=4)
    assert _store_bytes(d1) == _store_bytes(d4)


def test_compact_workers_byte_identical_synthetic(tmp_path):
    d1, d4 = str(tmp_path / "w1"), str(tmp_path / "w4")
    compact(d1, SyntheticSource(n_samples=7, n_variants=333, seed=5),
            chunk_variants=32, workers=1)
    compact(d4, SyntheticSource(n_samples=7, n_variants=333, seed=5),
            chunk_variants=32, workers=4)
    assert _store_bytes(d1) == _store_bytes(d4)


def test_compact_workers_pcoa_bit_identical(multi_vcf, tmp_path):
    """The acceptance-shaped check at test scale: coords through a
    4-worker-compacted store == coords through the 1-worker one."""
    from spark_examples_tpu.core.config import (
        ComputeConfig, IngestConfig, JobConfig,
    )
    from spark_examples_tpu.pipelines.jobs import pcoa_job

    path, _want = multi_vcf
    d1, d4 = str(tmp_path / "w1"), str(tmp_path / "w4")
    compact(d1, VcfSource(path), chunk_variants=16, workers=1)
    compact(d4, VcfSource(path), chunk_variants=16, workers=4)

    def job(d):
        return JobConfig(
            ingest=IngestConfig(source="store", path=d, block_variants=16),
            compute=ComputeConfig(metric="ibs", num_pc=3),
        )

    c1 = pcoa_job(job(d1)).coords
    c4 = pcoa_job(job(d4)).coords
    np.testing.assert_array_equal(c1, c4)


def test_compact_parallel_recovers_injected_worker_fault(multi_vcf, tmp_path):
    """An io_error fired inside a parse shard worker is retried by the
    worker under the wrapping retry policy — the compacted store is
    byte-identical to a clean run."""
    path, _want = multi_vcf
    clean, faulty = str(tmp_path / "clean"), str(tmp_path / "faulty")
    compact(clean, VcfSource(path), chunk_variants=16, workers=4)
    src = RetryingSource(
        VcfSource(path),
        policy=RetryPolicy(max_retries=3, backoff_s=0.001),
    )
    with faults.armed(["ingest.block_read:io_error:after=1:max=2"]), \
            warnings.catch_warnings():
        warnings.simplefilter("ignore", RuntimeWarning)
        compact(faulty, src, chunk_variants=16, workers=4)
    assert _store_bytes(clean) == _store_bytes(faulty)


def test_compact_parallel_exhaustion_names_inorder_cursor(multi_vcf, tmp_path):
    """A worker whose retry budget runs out surfaces as
    IngestExhaustedError with the in-order resume cursor stamped at the
    reassembly point — never a silent partial store."""
    path, _want = multi_vcf
    d = str(tmp_path / "dead")
    src = RetryingSource(
        VcfSource(path), policy=RetryPolicy(max_retries=1, backoff_s=0.001),
    )
    with faults.armed(["ingest.block_read:io_error:max=0"]), \
            warnings.catch_warnings():
        warnings.simplefilter("ignore", RuntimeWarning)
        with pytest.raises(IngestExhaustedError) as ei:
            compact(d, src, chunk_variants=16, workers=4)
    assert ei.value.cursor >= 0
    assert "start_variant" in str(ei.value)
    assert not os.path.exists(os.path.join(d, "manifest.json"))


# ---------------------------------------------------------------------------
# Store readahead.


@pytest.fixture
def store_dir(tmp_path, genotypes):
    src = ArraySource(genotypes, contig="chr9",
                      positions=np.arange(1000, 1211, dtype=np.int64))
    d = str(tmp_path / "store")
    compact(d, src, chunk_variants=32)
    return d


def test_readahead_stream_bit_identical(store_dir, genotypes):
    plain = open_store(store_dir)
    ra = open_store(store_dir, readahead_chunks=3)
    try:
        for bv in (16, 32, 50, 128):
            np.testing.assert_array_equal(
                _materialize(plain, bv), _materialize(ra, bv))
            np.testing.assert_array_equal(
                _materialize(ra, bv), genotypes)
    finally:
        ra.close()


def test_readahead_packed_transport_bit_identical(store_dir, genotypes):
    ra = open_store(store_dir, readahead_chunks=2)
    try:
        cols = []
        for pb, m in ra.packed_blocks(32):
            cols.append(bitpack.unpack_dosages_np(pb)[:, :m.stop - m.start])
        np.testing.assert_array_equal(
            np.concatenate(cols, axis=1), genotypes)
    finally:
        ra.close()


def test_readahead_warms_cache_ahead(store_dir):
    st = open_store(store_dir, readahead_chunks=4)
    try:
        stream = st.blocks(32)
        next(stream)  # first block consumed -> warms are in flight
        # Drain the stream; by the end every chunk went through the
        # cache exactly once and the pool reported activity.
        for _ in stream:
            pass
        from spark_examples_tpu.core import telemetry

        assert telemetry.counter_value("store.readahead.scheduled") > 0
    finally:
        st.close()


def test_readahead_worker_ioerror_rides_retry_boundary(store_dir, genotypes):
    """An injected store.read io_error that fires inside a READAHEAD
    worker is re-raised at the consumer's cursor and recovered by the
    ordinary retry/reopen boundary — stream bit-identical."""
    src = RetryingSource(
        open_store(store_dir, readahead_chunks=3),
        policy=RetryPolicy(max_retries=3, backoff_s=0.001),
        reopen=lambda: open_store(store_dir, readahead_chunks=3),
    )
    with faults.armed(["store.read:io_error:after=2:max=2"]), \
            warnings.catch_warnings():
        warnings.simplefilter("ignore", RuntimeWarning)
        got = _materialize(src, 32)
    np.testing.assert_array_equal(got, genotypes)


def test_readahead_worker_corruption_fails_fast_with_cursor(store_dir):
    """A truncate fault landing in a readahead worker quarantines and
    fails the CONSUMER fast at that chunk with the resume cursor — the
    background pool cannot swallow damage."""
    st = open_store(store_dir, readahead_chunks=3)
    try:
        with faults.armed(["store.read:truncate:after=3:max=1:keep=4"]):
            with pytest.raises(StoreCorruptError) as ei:
                _materialize(st, 32)
        assert ei.value.cursor % 32 == 0  # a chunk-start resume cursor
        assert os.path.exists(os.path.join(store_dir, "quarantine.json"))
    finally:
        st.close()


# ---------------------------------------------------------------------------
# Serve staging from a store (the readahead + /stats satellite).


def test_serve_stages_panel_through_readahead_and_exposes_cache_stats(
        rng, tmp_path):
    """The serve cold-start satellite: a panel staged from store:<dir>
    rides the readahead pool, serves bit-identically to an ArraySource
    panel, and GET /stats reports the DecodeCache accounting."""
    import json
    import urllib.request

    from spark_examples_tpu.core import telemetry
    from spark_examples_tpu.core.config import (
        ComputeConfig, IngestConfig, JobConfig,
    )
    from spark_examples_tpu.pipelines.jobs import pcoa_job
    from spark_examples_tpu.serve import ProjectionEngine, ProjectionServer
    from spark_examples_tpu.serve.http import start_http_server

    telemetry.reset()
    g_ref = random_genotypes(rng, n=16, v=256, missing_rate=0.1)
    model = str(tmp_path / "model.npz")
    pcoa_job(
        JobConfig(ingest=IngestConfig(block_variants=64),
                  compute=ComputeConfig(metric="ibs", num_pc=3),
                  model_path=model),
        source=ArraySource(g_ref),
    )
    d = str(tmp_path / "panel_store")
    compact(d, ArraySource(g_ref), chunk_variants=64)

    plain = ProjectionEngine(model, ArraySource(g_ref), block_variants=64)
    assert plain.store_cache_stats() is None  # non-store panels: absent

    scheduled_before = telemetry.counter_value("store.readahead.scheduled")
    engine = ProjectionEngine(model, open_store(d, readahead_chunks=2),
                              block_variants=64)
    assert telemetry.counter_value(
        "store.readahead.scheduled") > scheduled_before
    stats = engine.store_cache_stats()
    assert stats is not None and {"hits", "misses", "evictions"} <= set(stats)

    q = random_genotypes(rng, n=1, v=256, missing_rate=0.1)[0]
    np.testing.assert_array_equal(
        plain.project_batch(q[None, :]), engine.project_batch(q[None, :]))

    server = ProjectionServer(engine, max_linger_s=0.001).start()
    http = start_http_server(server, port=0)
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{http.port}/stats", timeout=30) as r:
            payload = json.load(r)
        assert "store_cache" in payload
        assert payload["store_cache"]["misses"] >= 1
    finally:
        http.shutdown()
        server.close()
        telemetry.reset()


# ---------------------------------------------------------------------------
# K-deep staged device feed.


def test_staged_device_feed_bit_identical(genotypes):
    src = ArraySource(genotypes)
    got = []
    for dev, m in stream_to_device(src, 64, prefetch=3):
        block = np.asarray(dev)
        assert block.shape[1] == 64  # shape-stable padding survived
        got.append(block[:, : m.stop - m.start])
    np.testing.assert_array_equal(np.concatenate(got, axis=1), genotypes)


def test_staged_device_feed_packed_bit_identical(genotypes):
    got = []
    for dev, m in stream_to_device(ArraySource(genotypes), 64,
                                   prefetch=2, pack=True):
        dense = bitpack.unpack_dosages_np(np.asarray(dev))
        got.append(dense[:, : m.stop - m.start])
    np.testing.assert_array_equal(np.concatenate(got, axis=1), genotypes)


def test_staging_ring_recycles_and_pads_correctly():
    """The staging producer at ring level: slabs recycle through the
    bounded pool, every staged block carries ITS variants (tail padded
    with MISSING), and releasing a slab unblocks the producer."""
    from spark_examples_tpu.ingest.prefetch import _produce_host_blocks

    src = SyntheticSource(n_samples=8, n_variants=1000, seed=9)
    want = _materialize(src, 128)
    got = []
    slabs = set()
    for host, slot, meta in _produce_host_blocks(
        src, 128, 0, 2, 1, False, None, staging=True,
    ):
        assert slot is not None and host is slot.buf
        assert host.shape[1] == 128
        w = meta.stop - meta.start
        got.append(host[:, :w].copy())  # consume before recycling
        assert (host[:, w:] == -1).all()  # MISSING tail pad
        slabs.add(id(slot.buf))
        slot.release()
    np.testing.assert_array_equal(np.concatenate(got, axis=1), want)
    # Bounded ring: far fewer slabs than blocks => recycling happened.
    assert len(slabs) < len(got)


def test_staging_disabled_on_cpu_targets(genotypes):
    """CPU device_put is zero-copy (the returned array aliases the host
    buffer), so the device feed must run UNSTAGED there — holding every
    yielded block while the stream advances stays corruption-free."""
    src = SyntheticSource(n_samples=8, n_variants=2048, seed=9)
    want = _materialize(src, 128)
    held = list(stream_to_device(src, 128, prefetch=2))
    got = np.concatenate(
        [np.asarray(b)[:, : m.stop - m.start] for b, m in held], axis=1)
    np.testing.assert_array_equal(got, want)


def test_staged_feed_abandonment_stops_producer(genotypes):
    it = stream_to_device(ArraySource(genotypes), 32, prefetch=2)
    next(it)
    it.close()  # must not hang or leak a blocked producer


# ---------------------------------------------------------------------------
# Config-time knob validation (the friendly-errors satellite).


@pytest.mark.parametrize("field, value", [
    ("prefetch_blocks", 0),
    ("prefetch_blocks", -1),
    ("prefetch_blocks", 1 << 20),
    ("ingest_workers", 0),
    ("ingest_workers", -4),
    ("ingest_workers", 100_000),
    ("readahead_chunks", -1),
    ("store_cache_mb", -1),
    ("block_variants", 0),
    ("splits_per_contig", 0),
    ("io_retries", -1),
])
def test_ingest_knobs_rejected_at_config_time(field, value):
    with pytest.raises(ValueError, match=field):
        IngestConfig(**{field: value})


def test_ingest_knob_zero_means_off_where_documented():
    cfg = IngestConfig(readahead_chunks=0, store_cache_mb=0, io_retries=0)
    assert cfg.readahead_chunks == 0


def test_compact_rejects_nonpositive_workers(tmp_path, genotypes):
    with pytest.raises(ValueError, match="workers"):
        compact(str(tmp_path / "s"), ArraySource(genotypes),
                chunk_variants=32, workers=0)


# ---------------------------------------------------------------------------
# Shard-aware feed (the multi-chip PR): column-window spans + decode-
# direct host blocks + the multi-host feeder's double-buffered assembly.


def test_store_range_source_spans_match_blocks(tmp_path, genotypes):
    """StoreRangeSource's column-window read path: block_spans +
    decode_range_into (local coordinates) reproduce blocks() bit-
    identically — the contract the multi-host per-process feed drives."""
    d = str(tmp_path / "s")
    compact(d, ArraySource(genotypes), chunk_variants=32)
    store = open_store(d)
    rng_src = store.variant_range(48, 176)  # chunk-misaligned bounds
    assert hasattr(rng_src, "block_spans")
    spans = list(rng_src.block_spans(40))
    via_blocks = list(rng_src.blocks(40))
    assert len(spans) == len(via_blocks)
    for (lo, hi, meta), (blk, bmeta) in zip(spans, via_blocks):
        assert (meta.start, meta.stop) == (bmeta.start, bmeta.stop)
        out = np.full((store.n_samples, hi - lo), -9, np.int8)
        rng_src.decode_range_into(lo, hi, out)
        np.testing.assert_array_equal(out, blk)
    with pytest.raises(ValueError, match="out of bounds"):
        rng_src.decode_range_into(0, 1000, np.empty((store.n_samples, 1000), np.int8))


def test_window_over_retrying_store_forwards_decode_direct(tmp_path, genotypes):
    """The multi-host partition chain — WindowSource over RetryingSource
    over StoreSource — keeps the decode-straight-into-buffer capability
    end to end, and stream_host_blocks' direct drive yields blocks
    bit-identical to the ordinary path (same metas, same padding)."""
    from spark_examples_tpu.ingest.prefetch import (
        pad_block, stream_host_blocks,
    )
    from spark_examples_tpu.ingest.source import WindowSource

    d = str(tmp_path / "s")
    compact(d, ArraySource(genotypes), chunk_variants=32)

    def _open():
        return open_store(d)

    retrying = RetryingSource(_open(), policy=RetryPolicy(max_retries=2),
                              reopen=_open)
    win = WindowSource(retrying, 48, 200)
    assert hasattr(win, "block_spans") and hasattr(win, "decode_range_into")
    got = list(stream_host_blocks(win, 48))  # direct decode drive
    want = [
        (pad_block(b, 48), m)
        for b, m in WindowSource(_open(), 48, 200).blocks(48)
    ]
    assert len(got) == len(want)
    for (gb, gm), (wb, wm) in zip(got, want):
        np.testing.assert_array_equal(gb, wb)
        assert (gm.start, gm.stop) == (wm.start, wm.stop)
    # a window over a capability-less source does NOT advertise the path
    plain = WindowSource(ArraySource(genotypes), 48, 200)
    assert not hasattr(plain, "block_spans")
    # the window's decode is bounds-checked against the WINDOW: an
    # over-long span must error, never silently decode a neighboring
    # partition's variants (double-counting in a multi-host job)
    with pytest.raises(ValueError, match="out of bounds"):
        win.decode_range_into(
            0, win.n_variants + 8,
            np.empty((win.n_samples, win.n_variants + 8), np.int8))


def test_stream_global_blocks_double_buffer_and_feed_bytes(genotypes):
    """Single-process run of the multi-host feeder: the one-block-ahead
    assembly pipeline must preserve block order/content and count
    multihost.shard_feed_bytes for exactly the real (non-padding)
    slabs this process fed."""
    import jax

    from spark_examples_tpu.core import telemetry
    from spark_examples_tpu.parallel import gram_sharded, multihost as mh
    from spark_examples_tpu.core import meshes

    mesh = meshes.make_mesh()
    plan = gram_sharded.GramPlan(mesh, "variant")
    src = ArraySource(genotypes)  # 37 x 211
    before = telemetry.counter_value("multihost.shard_feed_bytes")
    got = list(mh.stream_global_blocks(src, 64, 0, plan, pack=False))
    fed = telemetry.counter_value("multihost.shard_feed_bytes") - before
    # ceil(211/64) = 4 blocks, each padded to a multiple of 8 devices
    assert len(got) == 4
    w = 64  # 64 % 8 == 0 -> padded width = block width
    assert fed == 4 * genotypes.shape[0] * w
    whole = np.concatenate(
        [np.asarray(g)[:, :m.stop - m.start] for g, m in got], axis=1
    )
    np.testing.assert_array_equal(whole, genotypes)
    for g, _m in got:
        assert isinstance(g, jax.Array) and g.sharding == plan.block_sharding

"""Noise-aware perf regression tracking (tools/trend.py).

Tier-1 acceptance: a synthetic 20% regression on a fixture history is
flagged (nonzero exit through the CLI), and candidates inside the noise
band stay quiet — in BOTH directions (latency-like metrics regress
upward, throughput-like downward), with booleans gated and unknown
metrics left alone.
"""

import json
import subprocess
import sys
import os

import pytest

from tools import trend

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _hist(name, values):
    return [{"t_unix": 1000.0 + i, "run": {"round": i},
             "metrics": {name: v}} for i, v in enumerate(values)]


# ------------------------------------------------------------------ directions


def test_metric_directions_resolve_sensibly():
    d = trend.metric_direction
    assert d("value") == trend.LOWER_IS_BETTER  # headline seconds
    assert d("streamed_s") == trend.LOWER_IS_BETTER
    assert d("serve_p99_ms") == trend.LOWER_IS_BETTER
    assert d("sketch_relerr_vs_exact_2500") == trend.LOWER_IS_BETTER
    assert d("sketch_peak_mb") == trend.LOWER_IS_BETTER
    assert d("serve_sustained_qps") == trend.HIGHER_IS_BETTER
    assert d("gram_tflops_staged") == trend.HIGHER_IS_BETTER
    assert d("ingest_mb_s_packed") == trend.HIGHER_IS_BETTER
    assert d("store_hit_vs_cold_parse") == trend.HIGHER_IS_BETTER
    assert d("store_compact_scaling_w4_vs_w1") == trend.HIGHER_IS_BETTER
    assert d("vs_baseline") == trend.HIGHER_IS_BETTER
    assert d("store_ok") == trend.BOOL_MUST_HOLD
    assert d("tunnel_mb_s") is None  # environment, never gated
    assert d("metric") is None  # free-form string name
    # Kernel-sweep metrics (the similarity-kernel registry PR):
    # per-kernel throughputs go up, the sweep completeness gate holds.
    assert d("kernel_jaccard_mb_s") == trend.HIGHER_IS_BETTER
    assert d("kernel_jaccard_gflops") == trend.HIGHER_IS_BETTER
    assert d("kernel_king_mb_s") == trend.HIGHER_IS_BETTER
    assert d("kernel_king_gflops") == trend.HIGHER_IS_BETTER
    assert d("kernel_sweep_min_gflops") == trend.HIGHER_IS_BETTER
    assert d("kernel_sweep_ok") == trend.BOOL_MUST_HOLD
    # Fused packed lowering (the fused-kernels PR): the worst
    # fused-vs-reference gram speedup must go UP ("speedup" matches no
    # suffix rule — pinned explicitly), and the parity-plus-presence
    # gate holds like every *_ok.
    assert d("kernel_fused_min_speedup") == trend.HIGHER_IS_BETTER
    assert d("kernel_fused_ok") == trend.BOOL_MUST_HOLD
    # Multi-chip row (bench --multichip): throughput, the d8-vs-d1
    # wall-clock scaling, and the gather-hidden-behind-compute fraction
    # all go up; the solve-stage seconds go down; the ring-identity +
    # scaling gate holds.
    assert d("multichip_gram_mb_s") == trend.HIGHER_IS_BETTER
    assert d("multichip_scaling_d8_vs_d1") == trend.HIGHER_IS_BETTER
    assert d("multichip_overlap_frac") == trend.HIGHER_IS_BETTER
    assert d("multichip_solve_n100k_s") == trend.LOWER_IS_BETTER
    assert d("multichip_ok") == trend.BOOL_MUST_HOLD
    # Fleet serving (bench --fleet): the per-class p99s fall, QPS
    # rises, the composite gate holds; route count / forced-eviction
    # churn / the injected-delay hedge demo's win fraction are
    # workload shape, never gated.
    assert d("fleet_p99_interactive_s") == trend.LOWER_IS_BETTER
    assert d("fleet_p99_batch_s") == trend.LOWER_IS_BETTER
    assert d("fleet_sustained_qps") == trend.HIGHER_IS_BETTER
    assert d("fleet_ok") == trend.BOOL_MUST_HOLD
    assert d("fleet_routes") is None
    assert d("fleet_evictions") is None
    assert d("fleet_hedge_win_frac") is None
    # Static-analysis gate (bench headline, the graftlint PR): the
    # suite must stay clean — lint_ok HOLDS, and the finding count can
    # only fall. A tree that got faster but picked up an invariant
    # violation is a regression.
    assert d("lint_ok") == trend.BOOL_MUST_HOLD
    assert d("lint_findings") == trend.LOWER_IS_BETTER
    # Fleet control plane (bench --controller): scaling up faster,
    # shedding less of the burst, and a flatter p99 across a replica
    # loss are all improvements; the chaos gate (zero admitted
    # requests lost + respawn + scale-up observed) must hold; the
    # equilibrium replica count is workload shape, never gated.
    assert d("controller_scale_up_s") == trend.LOWER_IS_BETTER
    assert d("controller_burst_shed_rate") == trend.LOWER_IS_BETTER
    assert d("controller_p99_loss_s") == trend.LOWER_IS_BETTER
    assert d("controller_ok") == trend.BOOL_MUST_HOLD
    assert d("controller_replicas") is None
    # Flight recorder (bench --fleet): the tracing tax must trend
    # DOWN (and stay under the ~2% budget); the synthetic fast-burn
    # SLO trip is a must-hold boolean via the *_ok suffix.
    assert d("trace_overhead_frac") == trend.LOWER_IS_BETTER
    assert d("slo_fast_burn_ok") == trend.BOOL_MUST_HOLD
    # Neighbor engine (bench --neighbors): recall and the avoided-pair
    # fraction go UP, the served p99 goes DOWN, the sparse-vs-dense
    # wall ratio is a speedup (up), and the composite acceptance gate
    # (<= 10% evaluated, recall >= 0.95, served == offline) must hold.
    assert d("neighbors_recall_at_k") == trend.HIGHER_IS_BETTER
    assert d("neighbors_filter_frac") == trend.HIGHER_IS_BETTER
    assert d("neighbors_p99_ms") == trend.LOWER_IS_BETTER
    assert d("neighbors_sparse_speedup_vs_dense") == trend.HIGHER_IS_BETTER
    assert d("neighbors_ok") == trend.BOOL_MUST_HOLD
    # Servable sketch models (bench --sketch-serve): the first shard-
    # streamed serve and the steady p99 go DOWN via the time suffixes,
    # the over-budget ratio is a workload descriptor (tracked, never
    # gated), and the composite gate (bit-identity, rung in the
    # fingerprint, >= 2 shards/request, transient charges released)
    # must hold.
    assert d("sketch_serve_stage_s") == trend.LOWER_IS_BETTER
    assert d("sketch_serve_p99_ms") == trend.LOWER_IS_BETTER
    assert d("sketch_serve_panel_over_budget_x") is None
    assert d("sketch_serve_ok") == trend.BOOL_MUST_HOLD


# ------------------------------------------------------------------ the band


def test_twenty_percent_regression_is_flagged_and_noise_is_not():
    """THE acceptance pair: ~2% jitter history; +20% slower fires,
    +2% stays inside the band."""
    history = _hist("streamed_s", [1.00, 1.02, 0.99, 1.01, 0.98, 1.00])
    bad = trend.check_trend(history, {"streamed_s": 1.20})
    assert not bad["ok"]
    assert bad["regressions"][0]["metric"] == "streamed_s"
    quiet = trend.check_trend(history, {"streamed_s": 1.02})
    assert quiet["ok"] and not quiet["regressions"]
    # a 20% IMPROVEMENT is reported, never fatal
    better = trend.check_trend(history, {"streamed_s": 0.80})
    assert better["ok"]
    assert better["improvements"][0]["metric"] == "streamed_s"


def test_direction_awareness_for_throughput():
    """qps DROPPING 20% regresses; qps rising 20% improves."""
    history = _hist("serve_sustained_qps", [100, 102, 99, 101, 98, 100])
    drop = trend.check_trend(history, {"serve_sustained_qps": 80.0})
    assert not drop["ok"]
    rise = trend.check_trend(history, {"serve_sustained_qps": 120.0})
    assert rise["ok"] and rise["improvements"]


def test_noisy_metric_gets_a_wider_band():
    """Run-to-run jitter widens the band: a swing that would fire on a
    stable metric stays quiet on one whose history already moves that
    much (the dev-tunnel lesson from rounds 3-4)."""
    noisy = _hist("streamed_s", [1.0, 1.8, 0.9, 1.7, 1.1, 1.6])
    r = trend.check_trend(noisy, {"streamed_s": 2.0})
    assert r["ok"], r["regressions"]
    stable = _hist("streamed_s", [1.0, 1.01, 0.99, 1.0, 1.0, 1.01])
    r2 = trend.check_trend(stable, {"streamed_s": 2.0})
    assert not r2["ok"]


def test_boolean_gate_and_short_history():
    history = _hist("store_ok", [True, True, True])
    assert not trend.check_trend(history, {"store_ok": False})["ok"]
    assert trend.check_trend(history, {"store_ok": True})["ok"]
    # too-short numeric history: skipped, never guessed
    short = _hist("streamed_s", [1.0, 1.0])
    r = trend.check_trend(short, {"streamed_s": 9.0})
    assert r["ok"]
    assert any("history too short" in s["why"] for s in r["skipped"])


def test_backend_filter_keeps_environments_apart():
    """A CPU dev-box run must neither gate against the chip history
    (spurious regression) nor pollute the window a later chip run is
    gated against (MAD inflation masking real regressions)."""
    tpu = [{"t_unix": float(i), "run": {"backend": "tpu"},
            "metrics": {"streamed_s": v}}
           for i, v in enumerate([1.0, 1.01, 0.99, 1.0])]
    cpu_value = 400.0  # same metric name, different physical quantity
    # the CPU candidate against mixed history: with its backend
    # honored there is no CPU history yet -> skipped, not a regression
    cand = {"run": {"backend": "cpu"}, "metrics": {"streamed_s": cpu_value}}
    r = trend.check_trend(tpu, cand, backend="cpu")
    assert r["ok"] and any("history too short" in s["why"]
                           for s in r["skipped"])
    # a chip candidate ignores an interleaved CPU outlier record
    mixed = tpu + [{"t_unix": 9.0, "run": {"backend": "cpu"},
                    "metrics": {"streamed_s": cpu_value}}]
    bad_chip = trend.check_trend(mixed, {"streamed_s": 1.2},
                                 backend="tpu")
    assert not bad_chip["ok"]  # the 20% chip regression still fires


def test_check_and_count_defaults_to_candidate_backend(tmp_path):
    path = str(tmp_path / "hist.jsonl")
    with open(path, "w") as f:
        for i, v in enumerate([1.0, 1.0, 1.0, 1.0]):
            f.write(json.dumps({"t_unix": float(i),
                                "run": {"backend": "tpu"},
                                "metrics": {"streamed_s": v}}) + "\n")
        f.write(json.dumps({"t_unix": 9.0, "run": {"backend": "cpu"},
                            "metrics": {"streamed_s": 400.0}}) + "\n")
    # newest record is the CPU run: gated only against CPU history
    # (none) -> clean skip, no spurious regression
    report = trend.check_and_count(path)
    assert report["ok"]


def test_new_and_untracked_metrics_never_gate():
    history = _hist("streamed_s", [1.0] * 5)
    r = trend.check_trend(history, {"brand_new_s": 5.0,
                                    "tunnel_mb_s": 3.0,
                                    "note_string": "hi"})
    assert r["ok"]


# ---------------------------------------------------------------- substrate


def test_append_load_round_trip_and_torn_tail(tmp_path):
    path = str(tmp_path / "hist.jsonl")
    rec = trend.append_history(path, {"streamed_s": 1.5, "store_ok": True,
                                      "metric": "a_string"},
                               run_meta={"argv": ["--store"]})
    assert rec["metrics"] == {"streamed_s": 1.5, "store_ok": True}
    assert rec["run"]["argv"] == ["--store"]
    assert "platform" in rec["run"] and "git_sha" in rec["run"]
    with open(path, "a") as f:
        f.write('{"torn": ')  # crashed writer mid-line
    loaded = trend.load_history(path)
    assert len(loaded) == 1 and loaded[0]["metrics"]["streamed_s"] == 1.5


def test_ingest_bench_round_files():
    """The repo's own archived rounds are the backfill source; r05's
    clipped (null) headline is skipped, not crashed on."""
    files = [os.path.join(REPO, f"BENCH_r0{i}.json") for i in range(1, 6)]
    records = trend.ingest_bench_files(files)
    assert len(records) == 4  # r05's parsed headline was clipped to null
    assert all("value" in r["metrics"] for r in records)
    assert records[0]["run"]["source"] == "BENCH_r01.json"


def test_repo_history_is_seeded_and_clean():
    """BENCH_HISTORY.jsonl ships seeded from the archived rounds and
    the newest record passes the gate against its own past."""
    path = os.path.join(REPO, "BENCH_HISTORY.jsonl")
    history = trend.load_history(path)
    assert len(history) >= 4
    report = trend.check_and_count(path)
    assert report["ok"], report["regressions"]


# ----------------------------------------------------------------------- CLI


def test_cli_check_exits_nonzero_on_regression(tmp_path):
    path = str(tmp_path / "hist.jsonl")
    with open(path, "w") as f:
        for rec in _hist("streamed_s", [1.0, 1.01, 0.99, 1.02, 1.0]):
            f.write(json.dumps(rec) + "\n")
    cand = tmp_path / "cand.json"

    def run(value):
        cand.write_text(json.dumps({"streamed_s": value}))
        return subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "trend.py"),
             "check", "--history", path, "--candidate", str(cand)],
            capture_output=True, text=True, timeout=60)

    ok = run(1.0)
    assert ok.returncode == 0, ok.stderr
    bad = run(1.2)
    assert bad.returncode == 1
    assert "REGRESSION streamed_s" in bad.stderr
    report = json.loads(bad.stdout)
    assert report["regressions"][0]["direction"] == "lower_is_better"


def test_cli_ingest_appends(tmp_path):
    path = str(tmp_path / "hist.jsonl")
    p = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "trend.py"),
         "ingest", "--history", path,
         os.path.join(REPO, "BENCH_r02.json"),
         os.path.join(REPO, "BENCH_r03.json")],
        capture_output=True, text=True, timeout=60)
    assert p.returncode == 0, p.stderr
    assert len(trend.load_history(path)) == 2


# ------------------------------------------------------------- telemetry tie


def test_check_and_count_mirrors_into_telemetry(tmp_path):
    from spark_examples_tpu.core import telemetry

    telemetry.reset()
    path = str(tmp_path / "hist.jsonl")
    with open(path, "w") as f:
        for rec in _hist("streamed_s", [1.0, 1.0, 1.0, 1.0]):
            f.write(json.dumps(rec) + "\n")
    report = trend.check_and_count(path, {"streamed_s": 2.0})
    assert not report["ok"]
    assert telemetry.counter_value("trend.metrics_checked") == 1
    assert telemetry.counter_value("trend.regressions") == 1
    telemetry.reset()

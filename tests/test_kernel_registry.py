"""Similarity-kernel registry (spark_examples_tpu/kernels): contract
lints, registry-route bit-identity for every pre-existing metric, the
jaccard workload (golden values, conventions, packed/dense and
multi-device parity, end-to-end eigensolve + serve), and the
dual-sketch ladder for ratio metrics.

The registry lints mirror the fault-site and telemetry-glossary lints:
every registered kernel must declare a FLOPs model, carry a README
"Similarity kernels" table row, and appear in at least one end-to-end
test — a kernel that is registered but undocumented or untested is a
lint failure, not a style nit.
"""

import pathlib
import re

import numpy as np
import pytest

from spark_examples_tpu import kernels
from spark_examples_tpu.core import telemetry
from spark_examples_tpu.core.config import (
    ComputeConfig,
    DUAL_SKETCH_METRICS,
    IngestConfig,
    JobConfig,
    SKETCH_METRICS,
)
from spark_examples_tpu.ingest.source import ArraySource
from spark_examples_tpu.ops import distances, gram
from spark_examples_tpu.pipelines import runner
from spark_examples_tpu.pipelines.jobs import pcoa_job
from spark_examples_tpu.utils import oracle
from tests.conftest import random_genotypes

REPO = pathlib.Path(__file__).resolve().parent.parent

GRAM_METRICS = kernels.gram_names()
ALL_METRICS = kernels.names()


# ------------------------------------------------------------ registry


def test_builtin_registrations_complete():
    """The seven pre-existing metrics, jaccard, and the braycurtis
    table kernel are all registered; capability groups are derived, not
    hand-listed."""
    assert set(ALL_METRICS) == {
        "ibs", "ibs2", "shared-alt", "euclidean", "dot", "king",
        "jaccard", "pc-invariant", "grm", "braycurtis",
    }
    assert set(GRAM_METRICS) == set(ALL_METRICS) - {"braycurtis"}
    assert set(SKETCH_METRICS) == {"shared-alt", "grm", "dot", "euclidean"}
    assert set(DUAL_SKETCH_METRICS) == {"ibs", "jaccard"}
    assert set(kernels.unsketchable_names()) == {"ibs2", "king",
                                                 "pc-invariant"}
    # Consumers' tables are registry-derived.
    assert set(gram.GRAM_METRICS) == set(GRAM_METRICS)
    assert set(gram.DOSAGE_METRICS) == {
        k.name for k in kernels.all_kernels() if k.is_gram and k.pack_auto
    }
    assert gram.MAX_INCREMENT == {
        k.name: k.max_increment for k in kernels.all_kernels()
        if k.max_increment is not None
    }


def test_register_rejects_half_declared_kernels():
    """A half-declared kernel dies at registration, not as a KeyError
    deep inside a streaming job."""
    base = dict(name="_test_tmp", summary="x", family="count",
                pieces=("t1t1",), stats=("s",), finalize=lambda s: s,
                np_finalize=lambda s: s, max_increment=1,
                flops=lambda n, v: 2.0 * n * n * v)
    try:
        with pytest.raises(ValueError, match="already registered"):
            kernels.register(kernels.Kernel(**{**base, "name": "ibs"}))
        with pytest.raises(ValueError, match="family"):
            kernels.register(kernels.Kernel(**{**base, "family": "nope"}))
        with pytest.raises(ValueError, match="FLOPs"):
            kernels.register(kernels.Kernel(**{**base, "flops": None}))
        with pytest.raises(ValueError, match="missing"):
            kernels.register(kernels.Kernel(**{**base, "finalize": None}))
        with pytest.raises(ValueError, match="missing"):
            kernels.register(kernels.Kernel(**{**base,
                                               "max_increment": None}))
        with pytest.raises(ValueError, match="table_runner"):
            kernels.register(kernels.Kernel(
                name="_test_tmp", summary="x", family="table",
                flops=lambda n, v: 1.0))
        ops = lambda b: {}  # noqa: E731
        ops.operand_names = ("a",)
        with pytest.raises(ValueError, match="never declares"):
            kernels.register(kernels.Kernel(
                **{**base, "sketch": kernels.DualSketch(
                    operands=ops, num_terms=(("a", "b", 1.0),),
                    den_terms=(("a", "a", 1.0),))}))
    finally:
        kernels.unregister("_test_tmp")


def test_late_registered_kernel_routes_through_gram(genotypes):
    """A kernel registered AFTER ops/gram imported still routes through
    init/update/combine/finalize — dispatch reads the live registry,
    not the import-time snapshot dicts (which exist for introspection
    only). This is the 'adding a kernel is one registration' contract
    actually held to."""
    import jax.numpy as jnp

    def _fin(stats):
        s = stats["s"].astype(jnp.float32)
        return {"similarity": s,
                "distance": distances.similarity_to_distance(s)}

    def _np_fin(acc):
        d = oracle.cpu_finalize({"s": acc["s"]}, "shared-alt")
        return d

    kernels.register(kernels.Kernel(
        name="_late_test", summary="late registration smoke",
        family="count", pieces=("t1t1",), stats=("s",),
        finalize=_fin, np_finalize=_np_fin, pack_auto=True,
        max_increment=1, flops=lambda n, v: 2.0 * n * n * v,
    ))
    try:
        acc = gram.init(genotypes.shape[0], "_late_test")
        acc = gram.update(acc, genotypes, "_late_test")
        out = distances.finalize(acc, "_late_test")
        want = distances.finalize(
            gram.update(gram.init(genotypes.shape[0], "shared-alt"),
                        genotypes, "shared-alt"), "shared-alt")
        np.testing.assert_array_equal(np.asarray(out["similarity"]),
                                      np.asarray(want["similarity"]))
    finally:
        kernels.unregister("_late_test")


def test_unknown_metric_error_names_registered_kernels():
    """Config-time rejection lists the registry, never a stale string."""
    with pytest.raises(ValueError) as e:
        ComputeConfig(metric="cosine")
    for name in ("jaccard", "ibs", "braycurtis"):
        assert name in str(e.value)


def test_unsketchable_error_names_every_streamability_group():
    msg = kernels.unsketchable_metric_error("king", "sketch")
    for name in ("shared-alt", "grm", "ibs", "jaccard", "ibs2", "king"):
        assert name in msg
    assert "dual sketch" in msg
    assert "--solver exact" in msg


def test_every_kernel_declares_a_positive_flops_model():
    for kern in kernels.all_kernels():
        assert kern.flops is not None, kern.name
        assert kern.flops(64, 128) > 0, kern.name


def test_every_kernel_has_a_readme_row():
    """The README 'Similarity kernels' table documents every registered
    kernel (and no ghost kernels) — the docs half of the registry
    contract."""
    text = (REPO / "README.md").read_text()
    rows = set(re.findall(r"^\| `([\w-]+)`", text, re.MULTILINE))
    missing = set(ALL_METRICS) - rows
    assert not missing, (
        f"kernels registered but missing a README table row: {missing}")


def test_every_kernel_is_a_cli_choice(tmp_path, capsys):
    """The CLI's --metric choices come from the registry — a registered
    kernel must be reachable from the command line without a cli/main.py
    edit (the gap the first jaccard CLI drive actually hit)."""
    from spark_examples_tpu.cli.main import main

    with pytest.raises(SystemExit):
        main(["similarity", "--metric", "not-a-kernel"])
    capsys.readouterr()
    out = str(tmp_path / "sim.tsv")
    rc = main(["similarity", "--metric", "jaccard", "--n-samples", "12",
               "--n-variants", "512", "--block-variants", "256",
               "--output-path", out])
    assert rc == 0
    assert "similarity[jaccard]" in capsys.readouterr().out
    # Every registered name parses as a valid choice (--help exits 0
    # after choice validation; an unknown choice exits 2).
    for name in ALL_METRICS:
        with pytest.raises(SystemExit) as e:
            main(["similarity", "--metric", name, "--help"])
        assert e.value.code == 0, f"{name} rejected by the CLI parser"
    capsys.readouterr()


def test_every_kernel_appears_in_an_end_to_end_test():
    """Every registered kernel name is exercised by at least one test
    that names it as a metric — a registered-but-untested kernel is a
    lint failure."""
    corpus = "\n".join(
        p.read_text() for p in (REPO / "tests").glob("test_*.py"))
    untested = [
        name for name in ALL_METRICS
        if f'"{name}"' not in corpus and f"'{name}'" not in corpus
    ]
    assert not untested, f"kernels never exercised by tests: {untested}"


# ------------------------------------------ registry-route bit-identity


def _dense_acc(g, metric):
    """Stream g through the registry's dense gram route (unsharded)."""
    acc = gram.init(g.shape[0], metric)
    for s in range(0, g.shape[1], 64):
        acc = gram.update(acc, g[:, s:s + 64], metric)
    return acc


@pytest.mark.parametrize("metric", GRAM_METRICS)
def test_jax_and_numpy_finalize_twins_agree(genotypes, metric):
    """Each kernel's jax finalize and its registration-adjacent NumPy
    oracle mirror produce the same similarity/distance from the same
    accumulated statistics — the two conventions can never drift."""
    acc = _dense_acc(genotypes, metric)
    got = {k: np.asarray(v)
           for k, v in distances.finalize(acc, metric).items()}
    stats = {k: np.asarray(v) for k, v in gram.combine(acc, metric).items()}
    want = oracle.cpu_finalize(stats, metric)
    np.testing.assert_allclose(got["similarity"], want["similarity"],
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(got["distance"], want["distance"],
                               rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("lowering", ["reference", "fused"])
@pytest.mark.parametrize("metric",
                         [m for m in GRAM_METRICS
                          if kernels.get(m).pack_auto])
def test_packed_vs_dense_bit_identity(rng, metric, lowering):
    """--pack-stream packed and dense produce BIT-identical results
    through the registry route for every 2-bit-packable kernel — and
    the packed leg must stay bit-identical when it runs the fused
    Pallas lowering (interpret mode on CPU) instead of the reference
    unpack-then-matmul path. The dense leg is always the pinned
    reference oracle. The fused rows pin gram_mode to replicated: the
    auto plan at this N is multi-device variant mode, which cannot
    split a pallas_call across chips (the sharded fused coverage lives
    in the tile2d suites)."""
    if lowering == "fused" and metric not in kernels.fused_names():
        pytest.skip("no fused lowering registered (float-family "
                    "pack_auto kernel)")
    g = random_genotypes(rng, n=24, v=384, missing_rate=0.15)
    mode = "replicated" if lowering == "fused" else "auto"
    out = {}
    for pack in ("dense", "packed"):
        out[pack] = runner.run_similarity(
            JobConfig(
                ingest=IngestConfig(block_variants=128),
                compute=ComputeConfig(
                    metric=metric, pack_stream=pack, gram_mode=mode,
                    gram_lowering=(lowering if pack == "packed"
                                   else "reference"),
                ),
            ),
            source=ArraySource(g),
        )
    np.testing.assert_array_equal(out["dense"].similarity,
                                  out["packed"].similarity)
    np.testing.assert_array_equal(out["dense"].distance,
                                  out["packed"].distance)


@pytest.mark.parametrize("lowering", ["reference", "fused"])
@pytest.mark.parametrize("metric",
                         ["ibs", "ibs2", "king", "jaccard",
                          "pc-invariant"])
def test_tile2d_multi_device_matches_replicated(rng, metric, lowering):
    """Counting kernels are integer-exact, so the tile2d plan over the
    8 virtual devices must match the replicated single-accumulator plan
    BIT-identically — the registry's sharding declarations ride the
    same machinery for old and new kernels alike, and the tile2d leg
    must agree whether its per-device contraction runs the reference
    tile body or the fused packed Pallas kernel."""
    g = random_genotypes(rng, n=48, v=512, missing_rate=0.1)
    out = {}
    for mode in ("replicated", "tile2d"):
        out[mode] = runner.run_similarity(
            JobConfig(
                ingest=IngestConfig(block_variants=128),
                compute=ComputeConfig(
                    metric=metric, gram_mode=mode,
                    gram_lowering=(lowering if mode == "tile2d"
                                   else "reference"),
                ),
            ),
            source=ArraySource(g),
        )
    np.testing.assert_array_equal(out["replicated"].similarity,
                                  out["tile2d"].similarity)


def test_grm_tile2d_matches_replicated(rng):
    """The float-family kernel's declared tile body under the tile2d
    plan agrees with the replicated route (f32 accumulation: same
    per-block order, so identical up to layout — pinned allclose)."""
    g = random_genotypes(rng, n=48, v=512, missing_rate=0.1)
    out = {}
    for mode in ("replicated", "tile2d"):
        out[mode] = runner.run_similarity(
            JobConfig(
                ingest=IngestConfig(block_variants=128),
                compute=ComputeConfig(metric="grm", gram_mode=mode),
            ),
            source=ArraySource(g),
        )
    np.testing.assert_allclose(out["replicated"].similarity,
                               out["tile2d"].similarity,
                               rtol=1e-5, atol=1e-6)


# ------------------------------------------------- fused lowering seam


def test_fused_names_are_the_packable_count_family():
    """The fused set is derived from the registry, not hand-listed:
    exactly the pack_auto count kernels declare a fused_body (the 2-bit
    packed transport is what the fused Pallas kernel decodes)."""
    assert set(kernels.fused_names()) == {
        m for m in GRAM_METRICS
        if kernels.get(m).family == "count" and kernels.get(m).pack_auto
    }
    assert "grm" not in kernels.fused_names()
    assert "dot" not in kernels.fused_names()


def test_fused_tile_products_matches_reference_on_ragged_tiles(rng):
    """Direct parity of the Pallas kernel (interpret mode) against
    genotype.tile_products on shapes that exercise every pad path:
    sample counts off the 128 tile, byte widths off the 512 tile, and
    asymmetric row/col operands."""
    from spark_examples_tpu.ingest import bitpack
    from spark_examples_tpu.ops import genotype
    from spark_examples_tpu.ops.pallas import packed_gram

    rows = random_genotypes(rng, n=37, v=204, missing_rate=0.2)
    cols = random_genotypes(rng, n=21, v=204, missing_rate=0.2)
    prow, pcol = bitpack.pack_dosages(rows), bitpack.pack_dosages(cols)
    for metric in kernels.fused_names():
        pieces = kernels.get(metric).pieces
        fused = packed_gram.fused_tile_products(prow, pcol, pieces)
        ref = genotype.tile_products(bitpack.unpack_dosages(prow),
                                     bitpack.unpack_dosages(pcol),
                                     pieces)
        for p in pieces:
            got = np.asarray(fused[p])
            assert got.dtype == np.int32
            np.testing.assert_array_equal(got, np.asarray(ref[p]),
                                          err_msg=f"{metric}/{p}")


def test_fused_rejects_undecodable_pieces():
    """Only operands decodable from a 2-bit code can feed the fused
    kernel — the centered/weighted operands (grm's z, the dual
    sketches' q) have no packed representation."""
    from spark_examples_tpu.ops.pallas import packed_gram

    with pytest.raises(ValueError, match="qc"):
        packed_gram.check_fusable(("t1t1", "qc"))


def test_resolve_lowering_is_the_shared_auto_helper():
    """One helper owns every backend-conditional lowering pick: the
    gram family's auto choice AND braycurtis's pallas/exact method ride
    the same function, so 'fused on TPU, reference elsewhere' can never
    drift between subsystems."""
    assert kernels.resolve_lowering(
        "auto", "tpu", "fused", "reference") == "fused"
    assert kernels.resolve_lowering(
        "auto", "cpu", "fused", "reference") == "reference"
    # explicit choices pass through untouched on any backend
    assert kernels.resolve_lowering(
        "fused", "cpu", "fused", "reference") == "fused"
    assert kernels.resolve_lowering(
        "reference", "tpu", "fused", "reference") == "reference"
    # the braycurtis fold: same helper, its own option names
    assert kernels.resolve_lowering(
        "auto", "tpu", "pallas", "exact") == "pallas"
    assert kernels.resolve_lowering(
        "exact", "tpu", "pallas", "exact") == "exact"


def test_resolve_gram_lowering_downgrades_and_gates():
    """auto resolves to fused only where fused can run (TPU platform,
    fused-capable kernel, packed stream, and a plan whose per-device
    update can host a pallas_call); forced fused raises with the flags
    named instead of silently downgrading."""
    assert gram.resolve_gram_lowering(
        "auto", "ibs", True, platform="tpu") == "fused"
    assert gram.resolve_gram_lowering(
        "auto", "ibs", True, platform="cpu") == "reference"
    assert gram.resolve_gram_lowering(
        "auto", "grm", False, platform="tpu") == "reference"
    # a multi-device variant-mode plan partitions ONE jitted update
    # across chips — XLA cannot split the pallas_call, so auto
    # downgrades and forced fused refuses, naming the tile2d fix.
    assert gram.resolve_gram_lowering(
        "auto", "ibs", True, n_devices=8, plan_mode="variant",
        platform="tpu") == "reference"
    with pytest.raises(ValueError, match="tile2d"):
        gram.resolve_gram_lowering(
            "fused", "ibs", True, n_devices=8, plan_mode="variant")
    # forced fused on a capable single-device plan holds anywhere
    # (CPU runs the Pallas interpreter)
    assert gram.resolve_gram_lowering("fused", "ibs", True) == "fused"


def test_check_fused_lowering_names_flags():
    with pytest.raises(ValueError, match=r"--gram-lowering fused"):
        kernels.check_fused_lowering("grm", True)
    with pytest.raises(ValueError, match=r"--pack-stream"):
        kernels.check_fused_lowering("ibs", False)
    kernels.check_fused_lowering("ibs", True)  # capable combo passes


def test_config_validates_gram_lowering():
    """Config-time gate: the same check_fused_lowering text fires from
    ComputeConfig.__post_init__, so an impossible --gram-lowering fused
    job dies at argparse time, not after ingest starts."""
    with pytest.raises(ValueError, match=r"--gram-lowering"):
        ComputeConfig(gram_lowering="mosaic")
    with pytest.raises(ValueError, match=r"--gram-lowering fused"):
        ComputeConfig(metric="grm", gram_lowering="fused")
    with pytest.raises(ValueError, match=r"--pack-stream"):
        ComputeConfig(metric="ibs", pack_stream="dense",
                      gram_lowering="fused")
    # pack_stream auto resolves packed for a pack_auto count kernel
    ComputeConfig(metric="ibs", gram_lowering="fused")


def test_register_rejects_fused_body_outside_count_family():
    """The registry seam's own contract: a fused_body on anything but
    a pack_auto count kernel is a registration error — the fused
    lowering decodes 2-bit dosage codes, which only that family
    streams."""
    import dataclasses

    bad = dataclasses.replace(
        kernels.get("grm"), name="grm-fused-test",
        fused_body=lambda rows, cols: {})
    with pytest.raises(ValueError, match="pack_auto count"):
        kernels.register(bad)
    assert "grm-fused-test" not in kernels.names()


# ------------------------------------------------------------- jaccard


def test_jaccard_matches_naive_oracle(genotypes):
    """Golden values: the registry's matmul reformulation of carrier-set
    Jaccard equals the deliberately-independent per-pair set-algebra
    oracle; symmetry, exact unit diagonal, [0, 1] range, and the Gower
    distance relation all hold."""
    out = distances.finalize(_dense_acc(genotypes, "jaccard"), "jaccard")
    sim = np.asarray(out["similarity"])
    want = oracle.naive_jaccard(genotypes)
    np.testing.assert_allclose(sim, want, rtol=1e-6, atol=1e-7)
    np.testing.assert_allclose(sim, sim.T, atol=1e-7)
    np.testing.assert_allclose(np.diag(sim), 1.0, atol=1e-7)
    assert (sim >= 0).all() and (sim <= 1 + 1e-7).all()
    d = np.asarray(out["distance"])
    np.testing.assert_allclose(d * d, np.maximum(2.0 - 2.0 * sim, 0.0),
                               rtol=1e-4, atol=1e-5)


def test_jaccard_empty_union_convention():
    """Pairs with an empty carrier union cannot be distinguished from
    identical -> similarity 1 (the ibs zero-overlap convention's
    spirit), including the all-hom-ref sample's diagonal."""
    g = np.zeros((3, 50), np.int8)
    g[2, ::2] = 1  # one real carrier
    sim = np.asarray(
        distances.finalize(_dense_acc(g, "jaccard"), "jaccard")["similarity"]
    )
    assert sim[0, 1] == 1.0 and sim[1, 0] == 1.0
    assert sim[0, 0] == 1.0
    assert sim[0, 2] == 0.0  # empty vs carrier: empty intersection


def test_jaccard_duplicate_detection():
    """The scenario surface the kernel ships for: an exact duplicate
    pair pins similarity 1 even through missingness; unrelated random
    carriers sit well below."""
    rng = np.random.default_rng(7)
    g = random_genotypes(rng, n=10, v=600, missing_rate=0.05)
    g[5] = g[0]  # plant a duplicate
    sim = np.asarray(
        distances.finalize(_dense_acc(g, "jaccard"), "jaccard")["similarity"]
    )
    assert sim[0, 5] == 1.0
    others = sim[0, [j for j in range(1, 10) if j != 5]]
    assert others.max() < 0.95


# -------------------------------------------------------- pc-invariant


def _naive_pc_invariant(g: np.ndarray) -> np.ndarray:
    """Deliberately-independent oracle: apply the kernel's 3x3
    piecewise-constant table W(a, b) directly, per pair, per variant —
    no matmuls, no pieces algebra, nothing shared with the production
    route (the arXiv:2404.07183 definition applied literally)."""
    w = np.array([[1.0, 0.0, -1.0],
                  [0.0, 1.0, 0.0],
                  [-1.0, 0.0, 1.0]])
    n = g.shape[0]
    sim = np.ones((n, n))
    for i in range(n):
        for j in range(n):
            both = (g[i] >= 0) & (g[j] >= 0)
            m = int(both.sum())
            if m:
                sim[i, j] = w[g[i][both], g[j][both]].sum() / m
    return sim


def test_pc_invariant_matches_table_oracle(genotypes):
    """Golden values: the registry's pieces/stats recombination of the
    piecewise-constant invariant table equals the direct per-pair
    table application; symmetry, exact unit diagonal, [-1, 1] range,
    and the [0, 1] distance transform all hold."""
    out = distances.finalize(_dense_acc(genotypes, "pc-invariant"),
                             "pc-invariant")
    sim = np.asarray(out["similarity"])
    np.testing.assert_allclose(sim, _naive_pc_invariant(genotypes),
                               rtol=1e-6, atol=1e-6)
    np.testing.assert_allclose(sim, sim.T, atol=1e-7)
    np.testing.assert_allclose(np.diag(sim), 1.0, atol=1e-7)
    assert (sim >= -1 - 1e-6).all() and (sim <= 1 + 1e-6).all()
    d = np.asarray(out["distance"])
    np.testing.assert_allclose(d, (1.0 - sim) / 2.0, atol=1e-6)
    assert (d >= 0).all() and (d <= 1 + 1e-6).all()


def test_pc_invariant_table_semantics():
    """The table's three plateaus, pinned directly: identical
    genotypes +1, opposite homozygotes -1, everything else 0 — and
    pairs sharing no complete variants read 1 (indistinguishable from
    identical, the ibs/jaccard convention), keeping self-distance
    exactly 0."""
    g = np.array([
        [0, 0, 0, 0],    # hom-ref
        [2, 2, 2, 2],    # opposite homozygote of row 0
        [1, 1, 1, 1],    # het: 0 against both
        [0, 0, 2, 2],    # half match / half opposite vs row 0
        [-1, -1, -1, -1],  # all-missing: no complete pairs
    ], np.int8)
    out = distances.finalize(_dense_acc(g, "pc-invariant"),
                             "pc-invariant")
    sim = np.asarray(out["similarity"])
    assert sim[0, 0] == 1.0
    assert sim[0, 1] == -1.0 and sim[1, 0] == -1.0
    assert sim[0, 2] == 0.0 and sim[1, 2] == 0.0
    assert sim[0, 3] == 0.0  # (+1 +1 -1 -1) / 4
    assert sim[0, 4] == 1.0 and sim[4, 4] == 1.0  # empty-overlap
    d = np.asarray(out["distance"])
    assert d[0, 1] == 1.0 and d[4, 4] == 0.0


def test_pc_invariant_exact_rung_only():
    """The indefinite table has no sketch form; the registry-derived
    rejection names it with the exact-rung fix."""
    with pytest.raises(ValueError, match="--solver exact"):
        ComputeConfig(metric="pc-invariant", solver="sketch")


def test_jaccard_end_to_end_eigensolve_serve(rng, tmp_path):
    """Acceptance: --metric jaccard runs end-to-end — exact eigensolve
    with a saved model, offline projection of the training panel
    reproducing the fitted coordinates, and the serving engine
    bit-identical to the offline path."""
    from spark_examples_tpu.pipelines.project import pcoa_project_job
    from spark_examples_tpu.serve import ProjectionEngine

    g_ref = random_genotypes(rng, n=16, v=256, missing_rate=0.1)
    model = str(tmp_path / "jaccard.npz")
    job = JobConfig(
        ingest=IngestConfig(block_variants=64),
        compute=ComputeConfig(metric="jaccard", num_pc=4),
        model_path=model,
    )
    fit = pcoa_job(job, source=ArraySource(g_ref))
    # Offline projection of the panel's own rows reproduces the fitted
    # coordinates (jaccard's distance IS the Gower transform and its
    # self-similarity is exactly 1, so the extension is consistent).
    proj = pcoa_project_job(
        job.replace(model_path=None), model_path=model,
        source_new=ArraySource(g_ref), source_ref=ArraySource(g_ref),
    )
    np.testing.assert_allclose(proj.coords, fit.coords,
                               rtol=1e-3, atol=1e-4)
    # Serving: bit-identical to the offline projection path.
    engine = ProjectionEngine(model, ArraySource(g_ref),
                              block_variants=64, max_batch=4)
    query = random_genotypes(rng, n=3, v=256, missing_rate=0.1)
    served = engine.project_batch(query)
    for i in range(query.shape[0]):
        offline = pcoa_project_job(
            job.replace(model_path=None), model_path=model,
            source_new=ArraySource(query[i:i + 1]),
            source_ref=ArraySource(g_ref),
        ).coords
        np.testing.assert_array_equal(served[i:i + 1], offline)


# ---------------------------------------------------- dual-sketch rungs


def _dense_dual_target(g, metric, block=256):
    """The dual rungs' declared target operator, built densely in
    NumPy from the kernel's own spec: B = J diag(1/a) NUM diag(1/a) J
    with a = sqrt(diag(DEN)) — solver error is measured against THIS
    (the denominator's rank-1 defect vs the exact route is reported
    separately by solver.dual_den_defect)."""
    import jax.numpy as jnp

    spec = kernels.get(metric).sketch
    n = g.shape[0]
    num = np.zeros((n, n))
    den_diag = np.zeros(n)
    for s in range(0, g.shape[1], block):
        ops = {k: np.asarray(v, np.float64) for k, v in
               spec.operands(jnp.asarray(g[:, s:s + block])).items()}
        for (left, right, w) in spec.num_terms:
            num += w * ops[left] @ ops[right].T
        for (left, right, w) in spec.den_terms:
            den_diag += w * (ops[left] * ops[right]).sum(axis=1)
    a = np.sqrt(np.maximum(den_diag, 1e-30))
    st = num / np.outer(a, a)
    j = np.eye(n) - 1.0 / n
    return np.linalg.eigvalsh(j @ st @ j)[::-1]


@pytest.mark.parametrize("metric", ["ibs", "jaccard"])
def test_dual_sketch_corrected_within_ladder_bound(metric):
    """Acceptance: ratio metrics complete --solver corrected through
    the dual sketch with solver relerr inside the PR-7 ladder bound
    (structure < 1e-2 after 2 extra passes), and the dual telemetry
    gauges record the construction."""
    n, v, k = 96, 4096, 6
    job = JobConfig(
        ingest=IngestConfig(source="synthetic", n_samples=n, n_variants=v,
                            block_variants=512, seed=3),
        compute=ComputeConfig(metric=metric, num_pc=k, solver="corrected",
                              sketch_rank=40, sketch_iters=2),
    )
    src = runner.build_source(job.ingest)
    g = np.concatenate([b for b, _ in src.blocks(512)], axis=1)
    want = _dense_dual_target(g, metric)[:k]
    telemetry.reset()
    got = pcoa_job(job)
    ev = np.asarray(got.eigenvalues)
    rel = np.abs(ev[:4] - want[:4]) / np.maximum(np.abs(want[:4]), 1e-12)
    assert rel.max() < 1e-2, rel
    gauges = telemetry.metrics_snapshot()["gauges"]
    assert gauges["solver.dual"]["last"] == 1.0
    defect = gauges["solver.dual_den_defect"]["last"]
    assert 0.0 <= defect < 0.5
    if metric == "ibs":
        # ibs pair counts are near rank-1 (missingness only).
        assert defect < 0.05
    assert got.coords.shape == (n, k)


def test_dual_sketch_rung_runs_and_orders_structure():
    """The single-pass rung is available for PSD dual numerators
    (num_psd) — coarser than corrected by design, but it completes and
    keeps the structure/bulk split of its target operator."""
    job = JobConfig(
        ingest=IngestConfig(source="synthetic", n_samples=96,
                            n_variants=4096, block_variants=512, seed=3),
        compute=ComputeConfig(metric="jaccard", num_pc=6, solver="sketch",
                              sketch_rank=40),
    )
    telemetry.reset()
    out = pcoa_job(job)
    ev = np.asarray(out.eigenvalues)
    assert np.isfinite(ev).all() and (ev >= 0).all()
    assert ev[0] > 1.2 * ev[4]  # 4 planted dims separate from bulk
    assert telemetry.metrics_snapshot()["gauges"]["solver.rung"]["last"] == 0.0


def test_dual_sketch_seeded_determinism():
    def run(seed):
        return pcoa_job(JobConfig(
            ingest=IngestConfig(source="synthetic", n_samples=64,
                                n_variants=1024, block_variants=256, seed=5),
            compute=ComputeConfig(metric="jaccard", num_pc=4,
                                  solver="corrected", sketch_rank=24,
                                  sketch_iters=1, sketch_seed=seed),
        ))
    a, b, c = run(11), run(11), run(12)
    np.testing.assert_array_equal(a.coords, b.coords)
    assert not np.array_equal(a.coords, c.coords)


def test_dual_sketch_checkpointed_run_bit_identical(tmp_path):
    """The dual state rides the ordinary checkpoint machinery: a run
    that checkpoints every block (and re-runs resuming from its own
    final mid-pass state) matches the uncheckpointed run exactly."""
    def run(ckpt_dir):
        return pcoa_job(JobConfig(
            ingest=IngestConfig(source="synthetic", n_samples=64,
                                n_variants=1024, block_variants=256, seed=5),
            compute=ComputeConfig(metric="ibs", num_pc=4,
                                  solver="corrected", sketch_rank=24,
                                  sketch_iters=1,
                                  checkpoint_dir=ckpt_dir,
                                  checkpoint_every_blocks=1 if ckpt_dir
                                  else 0),
        ))
    plain = run(None)
    ck = run(str(tmp_path / "dual_ck"))
    np.testing.assert_array_equal(plain.coords, ck.coords)

import numpy as np
import pytest

from spark_examples_tpu.core.config import ComputeConfig, IngestConfig, JobConfig
from spark_examples_tpu.ingest import ArraySource
from spark_examples_tpu.pipelines import jobs, runner
from spark_examples_tpu.pipelines.examples import genotype_histogram
from spark_examples_tpu.utils import oracle
from tests.conftest import random_genotypes


def _job(**kw):
    ingest = IngestConfig(
        source="synthetic", n_samples=40, n_variants=2000,
        block_variants=512, seed=5, n_populations=3,
    )
    compute = ComputeConfig(**kw)
    return JobConfig(ingest=ingest, compute=compute)


def test_similarity_tpu_vs_cpu_backend_agree():
    """The --backend gate: both backends produce the same matrices."""
    tpu = runner.run_similarity(_job(metric="ibs", backend="jax-tpu"))
    cpu = runner.run_similarity(_job(metric="ibs", backend="cpu-reference"))
    np.testing.assert_allclose(tpu.distance, cpu.distance, rtol=1e-5, atol=1e-6)
    assert tpu.sample_ids == cpu.sample_ids


def test_packed_vs_dense_transport_agree():
    """pack_stream=packed (the default via auto) is bit-identical to the
    dense int8 transport, in both replicated and variant-sharded modes."""
    for mode in ("replicated", "variant"):
        packed = runner.run_similarity(
            _job(metric="ibs", pack_stream="packed", gram_mode=mode)
        )
        dense = runner.run_similarity(
            _job(metric="ibs", pack_stream="dense", gram_mode=mode)
        )
        np.testing.assert_array_equal(packed.distance, dense.distance)


def test_auto_pack_keeps_nondosage_metrics_dense(rng):
    """auto must not route arbitrary int8 tables through the 2-bit codec,
    and dot over a count table must be the TRUE dot product — raw-value
    operands, not the dosage thresholds (which would clip at 2)."""
    x = rng.integers(0, 7, size=(12, 300)).astype(np.int8)  # counts, not dosages
    job = _job(metric="dot")
    res = runner.run_similarity(job, source=ArraySource(x))
    np.testing.assert_allclose(
        res.similarity, x.astype(np.float64) @ x.astype(np.float64).T,
        rtol=1e-6,
    )


def test_euclidean_exact_on_count_table(rng):
    """euclidean over arbitrary int8 values (beyond the dosage domain)
    must equal the true pairwise euclidean distance."""
    x = rng.integers(0, 50, size=(10, 200)).astype(np.int8)
    res = runner.run_similarity(
        _job(metric="euclidean"), source=ArraySource(x)
    )
    xf = x.astype(np.float64)
    d2 = ((xf[:, None, :] - xf[None, :, :]) ** 2).sum(-1)
    np.testing.assert_allclose(res.distance, np.sqrt(d2), rtol=1e-6, atol=1e-6)


def test_int32_budget_warning(rng):
    """A stream whose worst-case increment budget is exceeded warns."""
    import warnings

    from spark_examples_tpu.pipelines.runner import _check_int32_budget

    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        _check_int32_budget("dot", n_variants=2**18, max_value=127)  # 127^2 * 2^18 > 2^31
        assert len(w) == 1 and issubclass(w[0].category, RuntimeWarning)
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        _check_int32_budget("ibs", n_variants=2**29, max_value=2)  # 2 * 2^29 = 2^30 ok
        _check_int32_budget("grm", n_variants=2**40, max_value=2)  # f32 path exempt
        assert not w
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        _check_int32_budget("ibs", n_variants=2**30, max_value=2)  # at the edge
        assert len(w) == 1


def test_pcoa_job_end_to_end_recovers_structure():
    job = _job(metric="ibs", num_pc=4)
    out = jobs.pcoa_job(job)
    assert out.coords.shape == (40, 4)
    # planted 3-population structure: PC1/2 separate clusters
    from spark_examples_tpu.pipelines.runner import build_source

    src = build_source(job.ingest)
    pops = src.populations
    coords = out.coords[:, :2]
    cents = np.stack([coords[pops == k].mean(0) for k in range(3)])
    within = np.mean(
        [np.linalg.norm(coords[i] - cents[pops[i]]) for i in range(40)]
    )
    between = np.mean(
        [np.linalg.norm(cents[a] - cents[b]) for a in range(3) for b in range(a + 1, 3)]
    )
    assert between / within > 3.0


def test_variants_pca_job_matches_mllib_route():
    out_tpu = jobs.variants_pca_job(_job(backend="jax-tpu", num_pc=3))
    out_cpu = jobs.variants_pca_job(_job(backend="cpu-reference", num_pc=3))
    # the CPU route must report a real spectrum, matching the TPU one
    assert not np.allclose(out_cpu.eigenvalues, 0.0)
    np.testing.assert_allclose(
        out_cpu.eigenvalues, out_tpu.eigenvalues,
        rtol=1e-3, atol=1e-3 * np.abs(out_tpu.eigenvalues).max(),
    )
    for c in range(3):
        a, b = out_tpu.coords[:, c], out_cpu.coords[:, c]
        assert np.allclose(a, b, atol=1e-2 * np.abs(a).max()) or np.allclose(
            a, -b, atol=1e-2 * np.abs(a).max()
        )


def test_braycurtis_pipeline(rng):
    g = np.abs(random_genotypes(rng, 20, 300, missing_rate=0.2))
    src = ArraySource(g)
    res = runner.run_similarity(
        JobConfig(ingest=IngestConfig(block_variants=128),
                  compute=ComputeConfig(metric="braycurtis")),
        source=src,
    )
    want = oracle.cpu_braycurtis(np.maximum(g, 0))
    np.testing.assert_allclose(res.distance, want, rtol=1e-5, atol=1e-6)


def test_checkpoint_resume(tmp_path, rng):
    """Kill-and-resume: second run continues from the cursor and matches
    an uninterrupted run."""
    g = random_genotypes(rng, 16, 1024, missing_rate=0.1)
    src = ArraySource(g)
    ckpt_dir = str(tmp_path / "ck")
    job = JobConfig(
        ingest=IngestConfig(block_variants=128),
        compute=ComputeConfig(
            metric="ibs", checkpoint_dir=ckpt_dir, checkpoint_every_blocks=2
        ),
    )

    # simulate a crash: a source that dies after 4 blocks
    class Dying(ArraySource):
        def blocks(self, bv, start_variant=0):
            for i, (b, m) in enumerate(super().blocks(bv, start_variant)):
                if i == 4:
                    raise RuntimeError("simulated preemption")
                yield b, m

    with pytest.raises(RuntimeError, match="preemption"):
        runner.run_similarity(job, source=Dying(g))

    resumed = runner.run_similarity(job, source=src)
    clean = runner.run_similarity(
        JobConfig(ingest=IngestConfig(block_variants=128),
                  compute=ComputeConfig(metric="ibs")),
        source=src,
    )
    np.testing.assert_allclose(resumed.distance, clean.distance,
                               rtol=1e-6, atol=1e-7)


def test_checkpoint_tile2d_sharded(tmp_path, rng):
    """VERDICT r3 #2: the tile2d regime can checkpoint without defeating
    the tiling — save writes one tile-shaped file per addressable shard
    (never a full N x N leaf), load re-places each tile onto its device,
    kill/resume matches clean bit-for-bit, and a tile-grid mismatch is
    rejected."""
    import dataclasses
    import glob
    import os

    from spark_examples_tpu.core import checkpoint as ckpt
    from spark_examples_tpu.core.profiling import PhaseTimer
    from spark_examples_tpu.ops import gram
    from spark_examples_tpu.parallel import gram_sharded
    from spark_examples_tpu.parallel.pcoa_sharded import assert_tiled

    g = random_genotypes(rng, 16, 1024, missing_rate=0.1)
    ckpt_dir = str(tmp_path / "ck")
    job = JobConfig(
        ingest=IngestConfig(block_variants=128),
        compute=ComputeConfig(metric="ibs", gram_mode="tile2d",
                              checkpoint_dir=ckpt_dir,
                              checkpoint_every_blocks=2),
    )

    class Dying(ArraySource):
        def blocks(self, bv, start_variant=0):
            for i, (b, m) in enumerate(super().blocks(bv, start_variant)):
                if i == 4:
                    raise RuntimeError("simulated preemption")
                yield b, m

    with pytest.raises(RuntimeError, match="preemption"):
        runner.run_gram(job, Dying(g), PhaseTimer())

    # On disk: one file per tile per leaf, each exactly tile-shaped —
    # the full N x N leaf never materialized on any host or device.
    plan = runner.plan_for_job(job, ArraySource(g))
    ni, nj = plan.mesh.devices.shape
    pieces = gram.PIECES_FOR_METRIC["ibs"]
    tile_files = glob.glob(os.path.join(ckpt_dir, "*.t*_*.npy"))
    assert len(tile_files) == ni * nj * len(pieces), tile_files
    for f in tile_files:
        assert np.load(f).shape == (16 // ni, 16 // nj), f
    full_files = [
        f for f in glob.glob(os.path.join(ckpt_dir, "*.npy"))
        if f not in tile_files
    ]
    assert not full_files, full_files

    # Resume under the SAME tile grid: every restored leaf is genuinely
    # tiled, and the resumed accumulation equals the clean one exactly
    # (integer counts).
    resumed = runner.run_gram(job, ArraySource(g), PhaseTimer())
    for k, v in resumed.acc.items():
        assert_tiled(v, resumed.plan, f"restored accumulator {k}")
    clean_job = job.replace(
        compute=dataclasses.replace(job.compute, checkpoint_dir=None)
    )
    clean = runner.run_gram(clean_job, ArraySource(g), PhaseTimer())
    for k in clean.acc:
        np.testing.assert_array_equal(
            np.asarray(resumed.acc[k]), np.asarray(clean.acc[k])
        )

    # Tile-grid mismatch: resuming the tiled checkpoint under a
    # different plan must refuse, not silently re-tile.
    other_plan = gram_sharded.GramPlan(plan.mesh, "variant")
    with pytest.raises(ValueError, match="tile grid"):
        ckpt.load(ckpt_dir, "ibs", ArraySource(g).sample_ids,
                  block_variants=128, plan=other_plan)
    with pytest.raises(ValueError, match="tiled leaf|tile grid"):
        ckpt.load(ckpt_dir, "ibs", ArraySource(g).sample_ids,
                  block_variants=128)


def test_checkpoint_rejects_wrong_cohort(tmp_path, rng):
    from spark_examples_tpu.core import checkpoint as ckpt

    g = random_genotypes(rng, 8, 64)
    ckpt.save(str(tmp_path / "c"), {"m": np.zeros((8, 8))}, 64, "ibs", 64,
              [f"s{i}" for i in range(8)])
    with pytest.raises(ValueError, match="different cohort"):
        ckpt.load(str(tmp_path / "c"), "ibs", [f"other{i}" for i in range(8)])
    with pytest.raises(ValueError, match="metric"):
        ckpt.load(str(tmp_path / "c"), "grm", [f"s{i}" for i in range(8)])


def test_genotype_histogram(rng):
    g = random_genotypes(rng, 30, 100, missing_rate=0.2)
    src = ArraySource(g)
    counts = genotype_histogram(src, block_variants=32)
    assert len(counts) == 100
    for j in (0, 57, 99):
        c = counts[j]
        col = g[:, j]
        assert c.hom_ref == (col == 0).sum()
        assert c.het == (col == 1).sum()
        assert c.hom_alt == (col == 2).sum()
        assert c.missing == (col == -1).sum()
    sel = genotype_histogram(src, block_variants=32, positions={5, 7})
    assert [c.position for c in sel] == [5, 7]
    # an EMPTY position set matches nothing (None means full scan) —
    # truthiness would silently flip it into a complete scan
    assert genotype_histogram(src, block_variants=32, positions=set()) == []


def test_sample_stats(rng):
    from spark_examples_tpu.pipelines.examples import sample_stats

    g = random_genotypes(rng, 12, 200, missing_rate=0.25)
    stats = sample_stats(ArraySource(g), block_variants=64)
    assert len(stats) == 12
    for i, s in enumerate(stats):
        row = g[i]
        assert s.n_variants == 200
        assert s.n_called == (row >= 0).sum()
        assert s.n_het == (row == 1).sum()
        assert s.n_hom_alt == (row == 2).sum()
        assert s.call_rate == pytest.approx(s.n_called / 200)
        assert s.het_rate == pytest.approx(
            s.n_het / s.n_called if s.n_called else 0.0
        )


def test_pcoa_job_reports_true_inertia_proportion(rng):
    """CoordsOutput.proportion must be the trace-based share of TOTAL
    inertia (oracle parity), not a normalized top-k fraction that
    always sums to 1."""
    from spark_examples_tpu.core.config import (
        ComputeConfig, IngestConfig, JobConfig,
    )
    from spark_examples_tpu.pipelines.jobs import pcoa_job
    from spark_examples_tpu.utils import oracle

    g = random_genotypes(rng, 20, 400, missing_rate=0.1)
    job = JobConfig(ingest=IngestConfig(block_variants=128),
                    compute=ComputeConfig(metric="ibs", num_pc=3))
    out = pcoa_job(job, source=ArraySource(g))
    assert out.proportion is not None and out.proportion.shape == (3,)
    from spark_examples_tpu.ops import distances, gram

    acc = gram.update(gram.init(20, "ibs"), g, "ibs")
    dist = np.asarray(distances.finalize(acc, "ibs")["distance"])
    _, _, want = oracle.pcoa(dist, k=3)
    np.testing.assert_allclose(out.proportion, want, atol=1e-4)
    assert out.proportion.sum() < 0.999  # top-3 of 20 can't be all inertia

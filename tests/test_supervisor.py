"""Supervisor layer (core/supervisor.py): heartbeat writer, watchdog
crash/hang/stall detection, restart semantics, and the fault site.

The children here are tiny jax-free python scripts, so the whole suite
runs in seconds — the jax-shaped end-to-end supervision story (kill +
checkpoint resume, bit-identity) lives in tests/test_kill_matrix.py.
"""

import json
import os
import subprocess
import sys
import time

import pytest

from spark_examples_tpu.core import faults, supervisor, telemetry

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

FAST = supervisor.SupervisorPolicy(
    max_restarts=2, heartbeat_timeout_s=1.0, stall_timeout_s=1.0,
    stall_blocks=0.0, startup_timeout_s=5.0, poll_s=0.02, grace_s=1.0,
)


def _env(**extra):
    env = dict(
        os.environ,
        PYTHONPATH=REPO + os.pathsep + os.environ.get("PYTHONPATH", ""),
        # Fast beats: the watchdog budgets in these tests are sub-second,
        # and the default 0.5 s interval leaves too little scheduling
        # margin on a loaded CI box.
        **{supervisor.ENV_HEARTBEAT_INTERVAL: "0.1"},
    )
    env.update(extra)
    return env


def _run(script: str, policy=FAST, tmp_path=None, **kw):
    hb = str(tmp_path / "hb.json") if tmp_path is not None else None
    return supervisor.supervise(
        [sys.executable, "-c", script], policy=policy,
        heartbeat_path=hb, stdout=subprocess.DEVNULL,
        stderr=subprocess.DEVNULL, **kw)


# --------------------------------------------------------------- child side


def test_heartbeat_writer_and_progress_token(tmp_path):
    telemetry.reset()
    hb = str(tmp_path / "beat.json")
    w = supervisor.HeartbeatWriter(hb, interval_s=0.05).start()
    try:
        payload = json.load(open(hb))
        assert payload["pid"] == os.getpid()
        p0 = payload["progress"]
        telemetry.count("faults.fired")  # any instrumented work
        deadline = time.time() + 5
        while time.time() < deadline:
            if json.load(open(hb))["progress"] > p0:
                break
            time.sleep(0.02)
        assert json.load(open(hb))["progress"] > p0
        assert telemetry.counter_value("supervisor.heartbeats") >= 1
    finally:
        w.stop()
    telemetry.reset()


def test_maybe_start_heartbeat_from_env(tmp_path):
    assert supervisor.maybe_start_heartbeat({}) is None
    hb = str(tmp_path / "env.json")
    w = supervisor.maybe_start_heartbeat({supervisor.ENV_HEARTBEAT: hb})
    try:
        assert w is not None and os.path.exists(hb)
    finally:
        w.stop()


def test_heartbeat_write_failure_is_tolerated(tmp_path):
    """An injected io_error at the supervisor.heartbeat site fails one
    write with a warning — the writer (and the job it reports on)
    keeps running."""
    hb = str(tmp_path / "faulty.json")
    with faults.armed(["supervisor.heartbeat:io_error:after=0:max=1"]):
        with pytest.warns(RuntimeWarning, match="heartbeat write"):
            w = supervisor.HeartbeatWriter(hb, interval_s=0.02).start()
        try:
            deadline = time.time() + 5
            while time.time() < deadline and not os.path.exists(hb):
                time.sleep(0.02)
            assert os.path.exists(hb)  # later beats landed
        finally:
            w.stop()


# ------------------------------------------------------------- parent side


def test_clean_child_passes_through(tmp_path):
    run = _run("import sys; sys.exit(0)", tmp_path=tmp_path)
    assert run.ok and run.restarts == 0 and run.incidents == []


def test_crash_restarts_until_success(tmp_path):
    """Child crashes on the first attempt (marker file tracks attempts),
    succeeds on the second — the supervisor hides the crash."""
    marker = tmp_path / "attempt"
    script = (
        "import os, sys\n"
        f"m = {str(marker)!r}\n"
        "if not os.path.exists(m):\n"
        "    open(m, 'w').close(); sys.exit(17)\n"
        "sys.exit(0)\n"
    )
    with pytest.warns(RuntimeWarning, match="child crash"):
        run = _run(script, tmp_path=tmp_path)
    assert run.ok and run.restarts == 1
    assert "exit code 17" in run.incidents[0]


def test_usage_error_exit_is_not_retried(tmp_path):
    """Exit code 2 (argparse usage error) fails identically every
    attempt — the supervisor must report it once, not burn the restart
    budget re-printing it."""
    run = _run("import sys; sys.exit(2)", tmp_path=tmp_path)
    assert not run.ok and run.returncode == 2 and run.restarts == 0
    assert "non-retryable" in run.incidents[-1]


def test_restart_budget_exhausts_with_last_code(tmp_path):
    with pytest.warns(RuntimeWarning):
        run = _run("import sys; sys.exit(9)", tmp_path=tmp_path)
    assert not run.ok and run.returncode == 9
    assert run.restarts == FAST.max_restarts
    assert "budget" in run.incidents[-1]


def test_hang_without_heartbeat_is_killed(tmp_path):
    """A child that never heartbeats and never exits is killed at the
    startup budget and restarted; the restart completes."""
    marker = tmp_path / "attempt"
    script = (
        "import os, sys, time\n"
        f"m = {str(marker)!r}\n"
        "if not os.path.exists(m):\n"
        "    open(m, 'w').close(); time.sleep(600)\n"
        "sys.exit(0)\n"
    )
    policy = supervisor.SupervisorPolicy(
        max_restarts=1, startup_timeout_s=1.0, poll_s=0.02, grace_s=0.5)
    with pytest.warns(RuntimeWarning, match="child hang"):
        run = _run(script, policy=policy, tmp_path=tmp_path)
    assert run.ok and run.watchdog_kills == 1 and run.restarts == 1
    assert "startup budget" in run.incidents[0]


def test_stall_frozen_progress_is_killed(tmp_path):
    """Heartbeats keep arriving but the progress token never moves:
    the watchdog must call it a stall (naming the queue gauges) and
    restart."""
    marker = tmp_path / "attempt"
    script = (
        "import os, sys, time\n"
        "from spark_examples_tpu.core import supervisor\n"
        "w = supervisor.maybe_start_heartbeat()\n"
        f"m = {str(marker)!r}\n"
        "if not os.path.exists(m):\n"
        "    open(m, 'w').close(); time.sleep(600)\n"  # alive, no progress
        "w.stop(); sys.exit(0)\n"
    )
    with pytest.warns(RuntimeWarning, match="child stall"):
        run = _run(script, env=_env(), tmp_path=tmp_path)
    assert run.ok and run.watchdog_kills == 1 and run.restarts == 1
    assert "progress frozen" in run.incidents[0]
    assert "prefetch_queue_depth" in run.incidents[0]  # gauge diagnosis


def test_stalled_heartbeat_thread_is_a_hang(tmp_path):
    """The supervisor.heartbeat fault site, end to end: a delay spec
    freezes the child's heartbeat thread (the job could even be fine —
    from outside they are indistinguishable), the watchdog kills at the
    heartbeat budget, and the restarted child (faults stripped) runs
    clean."""
    script = (
        "import sys, time\n"
        "from spark_examples_tpu.core import supervisor\n"
        "w = supervisor.maybe_start_heartbeat()\n"
        "time.sleep(2.5)\n"
        "sys.exit(0)\n"
    )
    env = _env(**{
        faults.ENV_SPECS: "supervisor.heartbeat:delay:delay=600:max=1",
    })
    policy = supervisor.SupervisorPolicy(
        max_restarts=1, heartbeat_timeout_s=0.8, stall_timeout_s=30.0,
        startup_timeout_s=5.0, poll_s=0.02, grace_s=0.5)
    with pytest.warns(RuntimeWarning, match="hang"):
        run = _run(script, policy=policy, env=env, tmp_path=tmp_path)
    assert run.ok and run.watchdog_kills == 1
    # The restarted child ran with the fault env stripped (else the
    # delay would re-freeze the first beat and the budget would burn).
    assert run.restarts == 1


def test_idle_server_is_not_stall_killed(tmp_path):
    """A serving child reporting zero in-flight requests is IDLE, not
    stalled: its progress token may freeze indefinitely between
    requests and the watchdog must leave it alone (a batch job with
    the same frozen token IS killed — test_stall_frozen_progress)."""
    script = (
        "import sys, time\n"
        "from spark_examples_tpu.core import supervisor, telemetry\n"
        "telemetry.gauge_set('serve.in_flight', 0)\n"
        "w = supervisor.maybe_start_heartbeat()\n"
        "time.sleep(2.5)\n"  # >> FAST.stall_timeout_s, token frozen
        "w.stop(); sys.exit(0)\n"
    )
    run = _run(script, env=_env(), tmp_path=tmp_path)
    assert run.ok and run.watchdog_kills == 0 and run.restarts == 0


# ------------------------------------------------------------------ CLI glue


def test_strip_supervise_flags():
    argv = ["similarity", "--supervise", "--metric", "ibs",
            "--supervise-max-restarts", "5",
            "--supervise-stall-timeout=9.5", "--output-path", "o.tsv"]
    assert supervisor.strip_supervise_flags(argv) == [
        "similarity", "--metric", "ibs", "--output-path", "o.tsv"]


def test_kill_exit_code_counts_as_crash(tmp_path):
    """The fault harness's os._exit(113) is an ordinary crash to the
    supervisor (restart + resume), distinguishable in incidents."""
    marker = tmp_path / "attempt"
    script = (
        "import os, sys\n"
        f"m = {str(marker)!r}\n"
        "if not os.path.exists(m):\n"
        "    open(m, 'w').close(); os._exit(113)\n"
        "sys.exit(0)\n"
    )
    with pytest.warns(RuntimeWarning, match="exit code 113"):
        run = _run(script, tmp_path=tmp_path)
    assert run.ok and run.restarts == 1

"""Kill-resume bit-identity matrix: {streaming gram, store compaction,
serve hot-reload, streaming sketch solve, minhash neighbors} x 3 seeded
kill points each, every run supervised (core/supervisor.py) so the
kill -> restart -> resume cycle is the REAL production path, and every
resumed output compared bit-for-bit against an uninterrupted run."""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from spark_examples_tpu.core import faults, supervisor
from tests.conftest import random_genotypes

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

GRAM_KILL_POINTS = (1, 3, 5)     # ingest.block_read hit the kill lands on
COMPACT_KILL_POINTS = (0, 1, 2)
SERVE_KILL_POINTS = (0, 2, 4)    # serve.request hit
SKETCH_KILL_POINTS = (1, 4, 9)   # pass 0 early, pass 0 late, pass 1
NEIGHBORS_KILL_POINTS = (1, 4, 9)  # minhash early/late, exact-eval pass


_CACHE_DIR = None  # session-scoped jax compile cache for the children


@pytest.fixture(scope="session", autouse=True)
def _session_compile_cache(tmp_path_factory):
    # Isolate the children's persistent jax compile cache from the
    # user-level ~/.cache one: an executable cached there by some
    # OTHER run (different session, different shapes) can carry a
    # different reduction order at the same shape, and a clean-vs-
    # resumed comparison then fails on float LSBs for reasons that
    # have nothing to do with resume correctness. One shared dir per
    # test session keeps the matrix fast (children reuse each other's
    # compiles) and hermetic — and pytest's tmp_path_factory retires
    # it, unlike a bare mkdtemp.
    global _CACHE_DIR
    _CACHE_DIR = str(tmp_path_factory.mktemp("killmatrix-jax-cache"))
    yield
    _CACHE_DIR = None


def _env(**extra):
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        PYTHONPATH=REPO + os.pathsep + os.environ.get("PYTHONPATH", ""),
        SPARK_EXAMPLES_TPU_CACHE=_CACHE_DIR,
    )
    env.update(extra)
    return env


@pytest.fixture(scope="module")
def packed_store(tmp_path_factory):
    """One 16 x 1024 packed cohort shared by every matrix surface."""
    from spark_examples_tpu.ingest.packed import save_packed

    rng = np.random.default_rng(1234)
    g = np.abs(random_genotypes(rng, 16, 1024, missing_rate=0.1))
    store = str(tmp_path_factory.mktemp("cohort") / "packed")
    save_packed(store, g, bits=2)
    return store, g


# ------------------------------------------------------- streaming gram


def _gram_cmd(store, out, ckpt):
    return [sys.executable, "-m", "spark_examples_tpu", "similarity",
            "--source", "packed", "--path", store,
            "--block-variants", "128", "--metric", "ibs",
            "--checkpoint-dir", ckpt, "--checkpoint-every-blocks", "2",
            "--output-path", out]


@pytest.fixture(scope="module")
def gram_clean(packed_store, tmp_path_factory):
    store, _g = packed_store
    d = tmp_path_factory.mktemp("gram_clean")
    out = str(d / "clean.tsv")
    p = subprocess.run(_gram_cmd(store, out, str(d / "ck")),
                       env=_env(), capture_output=True, text=True,
                       timeout=240)
    assert p.returncode == 0, p.stderr[-2000:]
    with open(out, "rb") as f:
        return f.read()


@pytest.mark.parametrize("kill_after", GRAM_KILL_POINTS)
def test_gram_kill_resume_bit_identical(packed_store, gram_clean,
                                        tmp_path, kill_after):
    """Supervised streaming-gram run killed at the Nth block read:
    the supervisor restarts it, the checkpoint resumes it, and the
    output bytes equal the uninterrupted run's."""
    store, _g = packed_store
    out = str(tmp_path / "sim.tsv")
    env = _env(**{
        faults.ENV_SPECS:
            f"ingest.block_read:kill:after={kill_after}:max=1",
    })
    cmd = _gram_cmd(store, out, str(tmp_path / "ck")) + ["--supervise"]
    p = subprocess.run(cmd, env=env, capture_output=True, text=True,
                       timeout=420)
    assert p.returncode == 0, p.stderr[-2000:]
    assert "supervisor: attempt 0: crash: exit code 113" in p.stderr
    with open(out, "rb") as f:
        assert f.read() == gram_clean


# -------------------------------------------------- streaming sketch solve


def _sketch_cmd(store, out, ckpt):
    return [sys.executable, "-m", "spark_examples_tpu", "pcoa",
            "--source", "packed", "--path", store,
            "--block-variants", "128", "--metric", "grm",
            "--solver", "corrected", "--sketch-rank", "12",
            "--sketch-iters", "1", "--num-pc", "3",
            "--checkpoint-dir", ckpt, "--checkpoint-every-blocks", "2",
            "--output-path", out]


@pytest.fixture(scope="module")
def sketch_clean(packed_store, tmp_path_factory):
    store, _g = packed_store
    d = tmp_path_factory.mktemp("sketch_clean")
    out = str(d / "clean.tsv")
    p = subprocess.run(_sketch_cmd(store, out, str(d / "ck")),
                       env=_env(), capture_output=True, text=True,
                       timeout=240)
    assert p.returncode == 0, p.stderr[-2000:]
    with open(out, "rb") as f:
        return f.read()


@pytest.mark.parametrize("kill_after", SKETCH_KILL_POINTS)
def test_sketch_kill_resume_bit_identical(packed_store, sketch_clean,
                                          tmp_path, kill_after):
    """Supervised sketch-solver run (corrected rung: 2 streamed passes
    over 8 blocks each) killed at the Nth block read — mid-pass-0, late
    pass-0, or inside the power-iteration pass — restarts under the
    supervisor, resumes from the checkpointed (N, r) sketch state (probe
    seed re-derived, cursor + pass index from the manifest), and the
    coordinate bytes equal the uninterrupted run's."""
    store, _g = packed_store
    out = str(tmp_path / "coords.tsv")
    env = _env(**{
        faults.ENV_SPECS:
            f"ingest.block_read:kill:after={kill_after}:max=1",
    })
    cmd = _sketch_cmd(store, out, str(tmp_path / "ck")) + ["--supervise"]
    p = subprocess.run(cmd, env=env, capture_output=True, text=True,
                       timeout=420)
    assert p.returncode == 0, p.stderr[-2000:]
    assert "supervisor: attempt 0: crash: exit code 113" in p.stderr
    with open(out, "rb") as f:
        assert f.read() == sketch_clean


# --------------------------------------- sketch-saved model artifact


def _model_cmd(store, out, model, ckpt):
    # The dual corrected rung — the one whose centering stats + scale
    # diagonal ride the SAME streamed passes the kill lands in and are
    # saved into the FactorizedModel artifact.
    return [sys.executable, "-m", "spark_examples_tpu", "pcoa",
            "--source", "packed", "--path", store,
            "--block-variants", "128", "--metric", "ibs",
            "--solver", "corrected", "--sketch-rank", "12",
            "--sketch-iters", "1", "--num-pc", "3",
            "--save-model", model,
            "--checkpoint-dir", ckpt, "--checkpoint-every-blocks", "2",
            "--output-path", out]


@pytest.fixture(scope="module")
def model_clean(packed_store, tmp_path_factory):
    store, _g = packed_store
    d = tmp_path_factory.mktemp("model_clean")
    out, model = str(d / "clean.tsv"), str(d / "clean_model.npz")
    p = subprocess.run(_model_cmd(store, out, model, str(d / "ck")),
                       env=_env(), capture_output=True, text=True,
                       timeout=240)
    assert p.returncode == 0, p.stderr[-2000:]
    with open(model, "rb") as f:
        model_bytes = f.read()
    with open(out, "rb") as f:
        return model_bytes, f.read()


@pytest.mark.parametrize("kill_after", SKETCH_KILL_POINTS)
def test_saved_model_kill_resume_byte_identical(packed_store,
                                                model_clean, tmp_path,
                                                kill_after):
    """Supervised --save-model corrected run killed at the Nth block
    read — the centering stats and dual scale diagonal are folded by
    the same streamed passes the kill interrupts — restarts, resumes
    from the solver checkpoint, and the saved FactorizedModel .npz
    BYTES equal the uninterrupted run's (np.savez is byte-deterministic
    here: fixed-header arrays, no timestamps), as do the coordinates."""
    store, _g = packed_store
    out = str(tmp_path / "coords.tsv")
    model = str(tmp_path / "model.npz")
    env = _env(**{
        faults.ENV_SPECS:
            f"ingest.block_read:kill:after={kill_after}:max=1",
    })
    cmd = _model_cmd(store, out, model, str(tmp_path / "ck")) + [
        "--supervise"]
    p = subprocess.run(cmd, env=env, capture_output=True, text=True,
                       timeout=420)
    assert p.returncode == 0, p.stderr[-2000:]
    assert "supervisor: attempt 0: crash: exit code 113" in p.stderr
    want_model, want_coords = model_clean
    with open(model, "rb") as f:
        assert f.read() == want_model
    with open(out, "rb") as f:
        assert f.read() == want_coords


# ------------------------------------------------- minhash neighbors job


def _neighbors_cmd(store, out, ckpt):
    return [sys.executable, "-m", "spark_examples_tpu", "neighbors",
            "--source", "packed", "--path", store,
            "--block-variants", "128", "--metric", "ibs",
            "--minhash-hashes", "32", "--minhash-bands", "8",
            "--neighbors-k", "5",
            "--checkpoint-dir", ckpt, "--checkpoint-every-blocks", "2",
            "--output-path", out]


@pytest.fixture(scope="module")
def neighbors_clean(packed_store, tmp_path_factory):
    store, _g = packed_store
    d = tmp_path_factory.mktemp("neighbors_clean")
    out = str(d / "clean.topk")
    p = subprocess.run(_neighbors_cmd(store, out, str(d / "ck")),
                       env=_env(), capture_output=True, text=True,
                       timeout=240)
    assert p.returncode == 0, p.stderr[-2000:]
    with open(out, "rb") as f:
        return f.read()


@pytest.mark.parametrize("kill_after", NEIGHBORS_KILL_POINTS)
def test_neighbors_kill_resume_bit_identical(packed_store,
                                             neighbors_clean, tmp_path,
                                             kill_after):
    """Supervised combined minhash+exact-eval neighbors run killed at
    the Nth block read — early or late in the streamed signature pass
    (which resumes from its solver:minhash checkpoint), or inside the
    candidate-evaluation pass (deterministically re-run) — restarts
    under the supervisor and writes a top-k file byte-identical to the
    uninterrupted run's."""
    store, _g = packed_store
    out = str(tmp_path / "sim.topk")
    env = _env(**{
        faults.ENV_SPECS:
            f"ingest.block_read:kill:after={kill_after}:max=1",
    })
    cmd = _neighbors_cmd(store, out, str(tmp_path / "ck")) + [
        "--supervise"]
    p = subprocess.run(cmd, env=env, capture_output=True, text=True,
                       timeout=420)
    assert p.returncode == 0, p.stderr[-2000:]
    assert "supervisor: attempt 0: crash: exit code 113" in p.stderr
    with open(out, "rb") as f:
        assert f.read() == neighbors_clean


# ------------------------------------------------------ store compaction


def _ingest_cmd(src_store, out_store):
    return [sys.executable, "-m", "spark_examples_tpu", "ingest",
            "--source", "packed", "--path", src_store,
            "--block-variants", "128", "--chunk-variants", "256",
            "--ingest-workers", "2", "--output-path", out_store]


@pytest.fixture(scope="module")
def compact_clean(packed_store, tmp_path_factory):
    store, _g = packed_store
    out = str(tmp_path_factory.mktemp("compact_clean") / "store")
    p = subprocess.run(_ingest_cmd(store, out), env=_env(),
                       capture_output=True, text=True, timeout=240)
    assert p.returncode == 0, p.stderr[-2000:]
    with open(os.path.join(out, "manifest.json"), "rb") as f:
        manifest = f.read()
    chunks = sorted(os.listdir(os.path.join(out, "chunks")))
    return manifest, chunks


@pytest.mark.parametrize("kill_after", COMPACT_KILL_POINTS)
def test_compact_kill_resume_byte_identical(packed_store, compact_clean,
                                            tmp_path, kill_after):
    """Supervised compaction killed mid-stream: the crashed attempt
    leaves chunks but NO manifest (the commit point), the restart
    re-compacts idempotently (content-addressed dedupe + wrong-size
    healing), and manifest + chunk set are byte-identical to a clean
    compaction."""
    store, _g = packed_store
    out = str(tmp_path / "store")
    env = _env(**{
        faults.ENV_SPECS:
            f"ingest.block_read:kill:after={kill_after}:max=1",
    })
    cmd = _ingest_cmd(store, out) + ["--supervise"]
    p = subprocess.run(cmd, env=env, capture_output=True, text=True,
                       timeout=420)
    assert p.returncode == 0, p.stderr[-2000:]
    assert "exit code 113" in p.stderr  # the kill really happened
    want_manifest, want_chunks = compact_clean
    with open(os.path.join(out, "manifest.json"), "rb") as f:
        assert f.read() == want_manifest
    assert sorted(os.listdir(os.path.join(out, "chunks"))) == want_chunks


# ------------------------------------------------------ serve hot-reload


_SERVE_SCRIPT = r"""
import sys
import numpy as np
from spark_examples_tpu.core.virtual import force_virtual_cpu
force_virtual_cpu(2)
from spark_examples_tpu.ingest.packed import load_packed
from spark_examples_tpu.serve import ProjectionEngine, ProjectionServer

model3, model5, panel, out = sys.argv[1:5]
engine = ProjectionEngine(model3, load_packed(panel),
                          block_variants=128, max_batch=2)
server = ProjectionServer(engine, cache_entries=0).start()
rng = np.random.default_rng(5)
queries = rng.integers(0, 3, size=(3, engine.n_variants)).astype(np.int8)
before = [server.project(q, timeout=60) for q in queries]
server.reload_model(model5)   # the hot-reload under test
after = [server.project(q, timeout=60) for q in queries]
assert server.drain(timeout=60)
server.close()
np.savez(out, before=np.concatenate(before), after=np.concatenate(after))
"""


@pytest.fixture(scope="module")
def serve_models(packed_store, tmp_path_factory):
    """Two models on the same panel (k=3 and k=5) fitted once, plus the
    clean (uninterrupted) serve-reload-serve outputs."""
    from spark_examples_tpu.core.config import (
        ComputeConfig, IngestConfig, JobConfig,
    )
    from spark_examples_tpu.pipelines.jobs import pcoa_job

    store, _g = packed_store
    d = tmp_path_factory.mktemp("serve_models")
    models = {}
    for k in (3, 5):
        models[k] = str(d / f"m{k}.npz")
        pcoa_job(JobConfig(
            ingest=IngestConfig(source="packed", path=store,
                                block_variants=128),
            compute=ComputeConfig(metric="ibs", num_pc=k),
            model_path=models[k],
        ))
    clean_out = str(d / "clean.npz")
    p = subprocess.run(
        [sys.executable, "-c", _SERVE_SCRIPT, models[3], models[5],
         store, clean_out],
        env=_env(), capture_output=True, text=True, timeout=240,
    )
    assert p.returncode == 0, p.stderr[-2000:]
    return models, np.load(clean_out)


@pytest.mark.parametrize("kill_after", SERVE_KILL_POINTS)
def test_serve_hot_reload_kill_resume_bit_identical(
        packed_store, serve_models, tmp_path, kill_after):
    """The serving process killed at the Nth admitted request — before,
    during, or after the hot-reload — then restarted by the supervisor:
    the restarted server (same panel staging, same reload) produces
    coordinates bit-identical to the uninterrupted run."""
    store, _g = packed_store
    models, clean = serve_models
    out = str(tmp_path / "coords.npz")
    env = _env(**{
        faults.ENV_SPECS: f"serve.request:kill:after={kill_after}:max=1",
    })
    run = supervisor.supervise(
        [sys.executable, "-c", _SERVE_SCRIPT, models[3], models[5],
         store, out],
        policy=supervisor.SupervisorPolicy(max_restarts=2,
                                           startup_timeout_s=240.0),
        env=env, heartbeat_path=str(tmp_path / "hb"),
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
    )
    assert run.ok, run.incidents
    assert run.restarts == 1  # the kill really happened, once
    assert "exit code 113" in run.incidents[0]
    got = np.load(out)
    np.testing.assert_array_equal(got["before"], clean["before"])
    np.testing.assert_array_equal(got["after"], clean["after"])

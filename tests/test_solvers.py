"""Streaming sketch solver (spark_examples_tpu/solvers): accuracy vs
the exact dense route, ladder monotonicity, seeded determinism, the
no-N-x-N structural guarantee, config-time knob validation, and
checkpoint/resume compatibility. The supervised kill/resume bit-identity
row lives in tests/test_kill_matrix.py."""

import numpy as np
import pytest

from spark_examples_tpu.core import telemetry
from spark_examples_tpu.core.config import (
    ComputeConfig,
    IngestConfig,
    JobConfig,
)
from spark_examples_tpu.pipelines.jobs import pcoa_job, variants_pca_job

N, V, BV = 96, 4096, 512
K = 6
RANK = 40


def _job(metric, solver, tmp=None, **kw):
    kw.setdefault("sketch_rank", RANK)
    return JobConfig(
        ingest=IngestConfig(source="synthetic", n_samples=N, n_variants=V,
                            block_variants=BV, seed=3),
        compute=ComputeConfig(metric=metric, num_pc=K, solver=solver, **kw),
    )


def _relerr(got, want):
    return np.abs(np.asarray(got) - want) / np.maximum(np.abs(want), 1e-12)


@pytest.fixture(scope="module")
def grm_ladder():
    """Exact + every sketch rung on one cohort, computed once."""
    exact = pcoa_job(_job("grm", "exact"))
    sketch = pcoa_job(_job("grm", "sketch"))
    corrected1 = pcoa_job(_job("grm", "corrected", sketch_iters=1))
    corrected3 = pcoa_job(_job("grm", "corrected", sketch_iters=3))
    return {"exact": exact, "sketch": sketch, "corrected1": corrected1,
            "corrected3": corrected3}


def test_sketch_accuracy_vs_exact_dense(grm_ladder):
    """The accuracy contract at seed scale: the corrected rung's
    STRUCTURE eigenvalues (the n_populations-1 planted ancestry
    dimensions) match the exact dense route to ~1e-2, and the full
    top-k (bulk included — quasi-degenerate sampling noise, the slow
    part for every randomized solver) stays bounded; the single-pass
    sketch rung is coarser but still recovers the structure ordering."""
    ev = np.asarray(grm_ladder["exact"].eigenvalues)
    assert ev[0] > 2.0 * ev[K - 1]  # the cohort really has structure
    rel_c = _relerr(grm_ladder["corrected3"].eigenvalues, ev)
    assert rel_c[:4].max() < 1e-2, rel_c
    assert rel_c.max() < 0.15, rel_c
    rel_s = _relerr(grm_ladder["sketch"].eigenvalues, ev)
    assert rel_s.max() < 0.5, rel_s
    # Eigenvalues descending, PSD-clamped, coordinates well-formed.
    sk = grm_ladder["sketch"]
    assert (np.diff(np.asarray(sk.eigenvalues)) <= 1e-6).all()
    assert (np.asarray(sk.eigenvalues) >= 0).all()
    assert sk.coords.shape == (N, K)


def test_ladder_monotonicity(grm_ladder):
    """Climbing the ladder must not lose accuracy: each extra streamed
    power-iteration pass contracts the subspace error, so
    sketch -> corrected(1) -> corrected(3) relerr is non-increasing."""
    ev = np.asarray(grm_ladder["exact"].eigenvalues)
    r_sketch = _relerr(grm_ladder["sketch"].eigenvalues, ev).max()
    r_c1 = _relerr(grm_ladder["corrected1"].eigenvalues, ev).max()
    r_c3 = _relerr(grm_ladder["corrected3"].eigenvalues, ev).max()
    assert r_c1 < r_sketch, (r_c1, r_sketch)
    # Tiny slack: the bulk is quasi-degenerate, so an extra pass may
    # reshuffle which noise direction wins by epsilon.
    assert r_c3 <= r_c1 * 1.05 + 1e-6, (r_c3, r_c1)


def test_proportion_explained_tracks_exact(grm_ladder):
    """The streamed trace accumulator gives an honest total-inertia
    denominator: proportions approximate the exact route's."""
    want = np.asarray(grm_ladder["exact"].proportion)
    got = np.asarray(grm_ladder["corrected3"].proportion)
    assert got.shape == want.shape
    np.testing.assert_allclose(got[:4], want[:4], rtol=0.05)


def test_seeded_determinism():
    """Same seed -> bit-identical coordinates; different probe seed ->
    a genuinely different random subspace (sketch rung)."""
    a = pcoa_job(_job("shared-alt", "sketch", sketch_seed=7))
    b = pcoa_job(_job("shared-alt", "sketch", sketch_seed=7))
    np.testing.assert_array_equal(a.coords, b.coords)
    np.testing.assert_array_equal(
        np.asarray(a.eigenvalues), np.asarray(b.eigenvalues))
    c = pcoa_job(_job("shared-alt", "sketch", sketch_seed=8))
    assert not np.array_equal(a.coords, c.coords)


def test_no_nxn_on_the_sketch_path(monkeypatch):
    """THE memory claim, asserted structurally: every N x N allocation
    site of the dense route (gram accumulator init, the finalize that
    consumes it) is rigged to explode, and the sketch job still
    completes — no N x N array is ever allocated on this path — while
    telemetry records the avoided allocation."""
    from spark_examples_tpu.ops import distances, gram
    from spark_examples_tpu.parallel import gram_sharded

    def boom(*a, **k):
        raise AssertionError("N x N allocated on the sketch path")

    monkeypatch.setattr(gram_sharded, "init_sharded", boom)
    monkeypatch.setattr(gram, "init", boom)
    monkeypatch.setattr(distances, "finalize", boom)
    telemetry.reset()
    out = pcoa_job(_job("dot", "sketch"))
    assert out.coords.shape == (N, K)
    gauges = telemetry.metrics_snapshot()["gauges"]
    state = gauges["solver.state_bytes"]["last"]
    avoided = gauges["solver.nxn_bytes_avoided"]["last"]
    # y + qc leaves plus the (N,) streamed column-mass vector the
    # model artifact's centering stats fold from.
    assert state == (2 * N * RANK + N) * 4
    assert avoided == 4 * N * N  # one int32 "yy" piece for dot
    assert state < avoided
    assert gauges["solver.rung"]["last"] == 0.0


def test_pca_sketch_matches_exact_structure():
    """The flagship PCA driver through the ladder: corrected-rung
    structure eigenvalues match the exact centered-similarity eigh."""
    exact = variants_pca_job(_job(None, "exact"))
    got = variants_pca_job(_job(None, "corrected", sketch_iters=3))
    ev = np.asarray(exact.eigenvalues)
    rel = _relerr(got.eigenvalues, ev)
    assert rel[:4].max() < 1e-2, rel
    # PCA convention: coords = lambda * v — column norms equal lambda.
    norms = np.linalg.norm(got.coords, axis=0)
    np.testing.assert_allclose(norms[:4], np.asarray(got.eigenvalues)[:4],
                               rtol=1e-4)
    assert telemetry.metrics_snapshot()["gauges"]["solver.rung"]["last"] == 1.0


def test_knob_validation_names_the_flags():
    """Config-time validation, IngestConfig-convention error messages."""
    with pytest.raises(ValueError, match="--solver"):
        ComputeConfig(solver="nystrom")
    with pytest.raises(ValueError, match="--sketch-rank"):
        ComputeConfig(solver="sketch", metric="grm", sketch_rank=0)
    with pytest.raises(ValueError, match="--sketch-rank.*--num-pc"):
        ComputeConfig(solver="sketch", metric="grm", num_pc=32,
                      sketch_rank=16)
    with pytest.raises(ValueError, match="--sketch-iters"):
        ComputeConfig(solver="corrected", metric="grm", sketch_iters=0)
    # king declares no sketch form (indefinite numerator, far-from-
    # rank-1 denominator) — rejected with the registry-derived text.
    with pytest.raises(ValueError, match="--metric king"):
        ComputeConfig(solver="sketch", metric="king")
    with pytest.raises(ValueError, match="--metric ibs2"):
        ComputeConfig(solver="sketch", metric="ibs2")
    # Ratio metrics declaring a dual sketch are sketchable now.
    ComputeConfig(solver="sketch", metric="ibs")
    ComputeConfig(solver="corrected", metric="jaccard")
    # The exact rung constrains nothing new.
    ComputeConfig(solver="exact", metric="ibs")


def test_unsketchable_metric_rejected_at_job_level():
    """The runtime gate (shared with config-time validation — one
    registry-derived builder, no drift) still rejects kernels declaring
    no sketch form, naming every streamability group."""
    from spark_examples_tpu.solvers import sketch as sk

    with pytest.raises(ValueError, match="king.*--solver exact"):
        sk.check_sketchable("king", "sketch")
    with pytest.raises(ValueError, match="dual sketch"):
        sk.check_sketchable("ibs2", "corrected")
    with pytest.raises(ValueError, match="king"):
        pcoa_job(_job("king", "sketch"))


def test_sketch_guards():
    """Routes that cannot honor the sketch contract refuse loudly."""
    with pytest.raises(ValueError, match="cpu-reference|CPU"):
        pcoa_job(_job("grm", "sketch", backend="cpu-reference"))
    # --save-model on a rung/metric that cannot center is now rejected
    # when the CONFIG is built (replace re-runs __post_init__), before
    # any pass streams.
    with pytest.raises(ValueError, match="save-model|centering"):
        _job("grm", "sketch").replace(model_path="/tmp/nope.npz")
    with pytest.raises(ValueError, match="stream"):
        from spark_examples_tpu.pipelines.streaming import (
            incremental_pcoa_job,
        )

        incremental_pcoa_job(_job("grm", "sketch",
                                  stream_refresh_blocks=2))


def test_cli_rejects_solver_on_non_eig_commands():
    from spark_examples_tpu.cli.main import main

    with pytest.raises(SystemExit) as e:
        main(["similarity", "--solver", "sketch", "--metric", "grm"])
    assert e.value.code == 2


def test_checkpoint_resume_and_compat(tmp_path):
    """A re-run over an existing sketch checkpoint resumes (and matches
    the uninterrupted run bit-for-bit); resuming under different probe
    settings is rejected, never silently mixed."""
    ck = str(tmp_path / "ck")
    base = dict(sketch_iters=1, sketch_seed=5, checkpoint_dir=ck,
                checkpoint_every_blocks=2)
    clean = pcoa_job(_job("grm", "corrected", **base))
    # The final every-K checkpoint is still on disk: a second run
    # resumes from it mid-stream and must land on identical output.
    resumed = pcoa_job(_job("grm", "corrected", **base))
    np.testing.assert_array_equal(clean.coords, resumed.coords)
    # Different probe seed: the checkpointed subspace is from another
    # random draw — refuse.
    with pytest.raises(ValueError, match="seed|sketch"):
        pcoa_job(_job("grm", "corrected", **{**base, "sketch_seed": 6}))
    with pytest.raises(ValueError, match="rank|sketch"):
        pcoa_job(_job("grm", "corrected",
                      **{**base, "sketch_rank": RANK // 2}))


def test_model_artifact_records_solver_rung(tmp_path):
    """Exact-rung models carry their ladder rung; older files without
    the field load as exact."""
    from spark_examples_tpu.pipelines.project import load_model

    path = str(tmp_path / "m.npz")
    job = _job("grm", "exact").replace(model_path=path)
    pcoa_job(job)
    mdl = load_model(path)
    assert mdl.solver == "exact"


def test_euclidean_sketch_no_missing():
    """Euclidean PCoA: the sketch Gram identity B = (JY)(JY)^T is exact
    when no calls are missing — pin it against the exact route."""
    from spark_examples_tpu.ingest.synthetic import SyntheticSource

    cfg = IngestConfig(source="synthetic", n_samples=48, n_variants=1024,
                       block_variants=256, seed=9)

    def src():
        return SyntheticSource(n_samples=48, n_variants=1024, seed=9,
                               missing_rate=0.0)

    exact = pcoa_job(JobConfig(ingest=cfg, compute=ComputeConfig(
        metric="euclidean", num_pc=4, solver="exact")), source=src())
    got = pcoa_job(JobConfig(ingest=cfg, compute=ComputeConfig(
        metric="euclidean", num_pc=4, solver="corrected", sketch_rank=24,
        sketch_iters=3)), source=src())
    rel = _relerr(got.eigenvalues, np.asarray(exact.eigenvalues))
    assert rel[:3].max() < 1e-2, rel


def test_stage_runtimes_measures_all_stages():
    """The multi-chip bench's solve-stage entry (solvers/solve.
    stage_runtimes): every row-sharded stage is measured, positive, and
    runs on both a mesh plan and the single-device (None) plan — the
    same jits production solves use, so a measured row here is the real
    path, not a proxy."""
    from spark_examples_tpu.core import meshes
    from spark_examples_tpu.parallel.gram_sharded import GramPlan
    from spark_examples_tpu.solvers.solve import stage_runtimes

    plan = GramPlan(meshes.make_mesh(), "tile2d")
    for p in (None, plan):
        times = stage_runtimes(256, 16, p, k=4, repeats=1)
        assert set(times) == {"cholqr2_s", "nystrom_s", "rayleigh_s"}
        assert all(v > 0 for v in times.values()), times

"""Out-of-sample PCoA projection: exactness on the training cohort,
ancestry placement of held-out samples, and stream-mismatch guards."""

import dataclasses

import numpy as np
import pytest

from spark_examples_tpu.core.config import (
    ComputeConfig, IngestConfig, JobConfig,
)
from spark_examples_tpu.ingest.source import ArraySource
from spark_examples_tpu.pipelines.jobs import pcoa_job
from spark_examples_tpu.pipelines.project import pcoa_project_job
from tests.conftest import random_genotypes


def _cohort(rng, n, v, pops=3):
    labels = rng.integers(0, pops, n)
    p = (0.05 + 0.9 * rng.random((pops, v)))[labels]
    g = (
        (rng.random((n, v)) < p).astype(np.int8)
        + (rng.random((n, v)) < p).astype(np.int8)
    )
    return g, labels


def test_project_training_samples_is_exact(rng, tmp_path):
    """B V = V diag(lambda): pushing the reference's own samples through
    the projection path reproduces their fitted coordinates."""
    g = random_genotypes(rng, n=20, v=500, missing_rate=0.1)
    model = str(tmp_path / "m.npz")
    job = JobConfig(
        ingest=IngestConfig(block_variants=128),
        compute=ComputeConfig(metric="ibs", num_pc=5),
        model_path=model,
    )
    fitted = pcoa_job(job, source=ArraySource(g))
    out = pcoa_project_job(
        job.replace(model_path=None),
        model_path=model,
        source_new=ArraySource(g),
        source_ref=ArraySource(g),
    )
    k = out.coords.shape[1]  # lambda<=0 components dropped by the model
    np.testing.assert_allclose(
        out.coords, fitted.coords[:, :k], atol=1e-3
    )


def test_project_places_heldout_by_ancestry(rng, tmp_path):
    """Held-out samples project near their own population's centroid."""
    g, labels = _cohort(rng, n=90, v=4000)
    ref, new = g[:60], g[60:]
    lr, ln = labels[:60], labels[60:]
    model = str(tmp_path / "m.npz")
    job = JobConfig(
        ingest=IngestConfig(block_variants=512),
        compute=ComputeConfig(metric="ibs", num_pc=4),
        model_path=model,
    )
    fitted = pcoa_job(job, source=ArraySource(ref))
    out = pcoa_project_job(
        job.replace(model_path=None), model_path=model,
        source_new=ArraySource(new), source_ref=ArraySource(ref),
    )
    cents = np.stack(
        [fitted.coords[lr == c, :2].mean(0) for c in range(3)]
    )
    for i in range(len(ln)):
        d = np.linalg.norm(out.coords[i, :2] - cents, axis=1)
        assert d.argmin() == ln[i]


def test_project_rejects_mismatched_streams(rng, tmp_path):
    g = random_genotypes(rng, n=10, v=256)
    model = str(tmp_path / "m.npz")
    job = JobConfig(
        ingest=IngestConfig(block_variants=64),
        compute=ComputeConfig(metric="ibs", num_pc=3),
        model_path=model,
    )
    pcoa_job(job, source=ArraySource(g))
    with pytest.raises(ValueError, match="diverged|ended first"):
        pcoa_project_job(
            job.replace(model_path=None), model_path=model,
            source_new=ArraySource(g[:, :200]),  # fewer variants
            source_ref=ArraySource(g),
        )
    with pytest.raises(ValueError, match="fitted on"):
        pcoa_project_job(
            job.replace(model_path=None), model_path=model,
            source_new=ArraySource(g),
            source_ref=ArraySource(g[:6]),  # wrong panel size
        )
    with pytest.raises(ValueError, match="fitted on"):
        pcoa_project_job(
            job.replace(model_path=None), model_path=model,
            source_new=ArraySource(g),
            # same size, different cohort: ids must not match either
            source_ref=ArraySource(
                g, ids=[f"OTHER{i}" for i in range(10)]
            ),
        )


def test_pca_project_training_samples_is_exact(rng, tmp_path):
    """The flagship PCA driver's projection: c_row @ V = lambda v_row,
    so pushing the panel's own samples through reproduces their fitted
    PC coordinates."""
    from spark_examples_tpu.pipelines.jobs import variants_pca_job

    g = random_genotypes(rng, n=20, v=500, missing_rate=0.1)
    model = str(tmp_path / "m.npz")
    job = JobConfig(
        ingest=IngestConfig(block_variants=128),
        compute=ComputeConfig(num_pc=4),
        model_path=model,
    )
    fitted = variants_pca_job(job, source=ArraySource(g))
    out = pcoa_project_job(
        job.replace(model_path=None), model_path=model,
        source_new=ArraySource(g), source_ref=ArraySource(g),
    )
    k = out.coords.shape[1]
    np.testing.assert_allclose(
        out.coords, fitted.coords[:, :k], atol=2e-2
    )


def test_pca_project_places_heldout_by_ancestry(rng, tmp_path):
    from spark_examples_tpu.pipelines.jobs import variants_pca_job

    g, labels = _cohort(rng, n=90, v=4000)
    ref, new = g[:60], g[60:]
    lr, ln = labels[:60], labels[60:]
    model = str(tmp_path / "m.npz")
    job = JobConfig(
        ingest=IngestConfig(block_variants=512),
        compute=ComputeConfig(num_pc=3),
        model_path=model,
    )
    fitted = variants_pca_job(job, source=ArraySource(ref))
    out = pcoa_project_job(
        job.replace(model_path=None), model_path=model,
        source_new=ArraySource(new), source_ref=ArraySource(ref),
    )
    cents = np.stack(
        [fitted.coords[lr == c, :2].mean(0) for c in range(3)]
    )
    for i in range(len(ln)):
        d = np.linalg.norm(out.coords[i, :2] - cents, axis=1)
        assert d.argmin() == ln[i]


def test_shared_alt_pcoa_model_is_rejected_up_front(rng, tmp_path):
    """A shared-alt PCoA model is valid to FIT but not projectable; the
    gate must key on (kind, metric) and fail before streaming — metric
    alone would pass it and crash after the expensive cross pass."""
    g = random_genotypes(rng, n=10, v=256)
    model = str(tmp_path / "m.npz")
    job = JobConfig(
        ingest=IngestConfig(block_variants=64),
        compute=ComputeConfig(metric="shared-alt", num_pc=3),
        model_path=model,
    )
    pcoa_job(job, source=ArraySource(g))
    with pytest.raises(ValueError, match="not.*projectable"):
        pcoa_project_job(
            job.replace(model_path=None), model_path=model,
            source_new=ArraySource(g), source_ref=ArraySource(g),
        )


def test_qc_pack_fit_project_chain(rng, tmp_path, capsys):
    """The documented panel-QC workflow (the project command's own
    recommendation): pack --maf into a filtered store, fit on it, then
    project from the same store — self-projection reproduces the fitted
    coordinates."""
    from spark_examples_tpu.cli.main import main
    from spark_examples_tpu.ingest.vcf import write_vcf

    g = random_genotypes(rng, n=12, v=500, missing_rate=0.2)
    vcf = str(tmp_path / "c.vcf")
    write_vcf(vcf, g)
    store = str(tmp_path / "store")
    model = str(tmp_path / "m.npz")
    fit_tsv, proj_tsv = str(tmp_path / "f.tsv"), str(tmp_path / "p.tsv")
    assert main(["pack", "--source", "vcf", "--path", vcf, "--maf", "0.1",
                 "--max-missing", "0.2", "--output-path", store,
                 "--block-variants", "64"]) == 0
    assert main(["pcoa", "--source", "packed", "--path", store,
                 "--num-pc", "3", "--block-variants", "64",
                 "--save-model", model, "--output-path", fit_tsv]) == 0
    assert main(["project", "--source", "packed", "--path", store,
                 "--ref-source", "packed", "--ref-path", store,
                 "--model", model, "--block-variants", "64",
                 "--output-path", proj_tsv]) == 0
    fit = np.loadtxt(fit_tsv, skiprows=1, usecols=(1, 2, 3))
    proj = np.loadtxt(proj_tsv, skiprows=1, usecols=(1, 2, 3))
    np.testing.assert_allclose(proj, fit, atol=5e-3)
    capsys.readouterr()


def test_project_cli_flow(rng, tmp_path, capsys):
    """pcoa --save-model then project, through the real CLI."""
    from spark_examples_tpu.cli.main import main
    from spark_examples_tpu.ingest.plink import write_plink

    g, labels = _cohort(rng, n=40, v=1500)
    ref, new = g[:30], g[30:]
    refp, newp = str(tmp_path / "ref"), str(tmp_path / "new")
    write_plink(refp, ref)
    write_plink(newp, new)
    model = str(tmp_path / "m.npz")
    coords = str(tmp_path / "proj.tsv")
    assert main(["pcoa", "--source", "plink", "--path", refp,
                 "--block-variants", "256", "--num-pc", "3",
                 "--save-model", model]) == 0
    assert main(["project", "--source", "plink", "--path", newp,
                 "--ref-source", "plink", "--ref-path", refp,
                 "--block-variants", "256", "--model", model,
                 "--output-path", coords]) == 0
    got = np.loadtxt(coords, skiprows=1, usecols=(1, 2, 3))
    assert got.shape == (10, 3)
    capsys.readouterr()


def test_allele_flip_detected(rng, tmp_path):
    """Swapped REF/ALT coding in one cohort (dosage g -> 2-g) must warn
    loudly — it silently corrupts projection/kinship otherwise."""
    import warnings

    g = random_genotypes(rng, n=16, v=600, missing_rate=0.05)
    model = str(tmp_path / "m.npz")
    job = JobConfig(
        ingest=IngestConfig(block_variants=128),
        compute=ComputeConfig(metric="ibs", num_pc=3),
        model_path=model,
    )
    pcoa_job(job, source=ArraySource(g))
    flipped = np.where(g >= 0, 2 - g, -1).astype(np.int8)
    with pytest.warns(RuntimeWarning, match="allele-frequency"):
        pcoa_project_job(
            job.replace(model_path=None), model_path=model,
            source_new=ArraySource(flipped), source_ref=ArraySource(g),
        )
    # concordant cohorts stay silent
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        pcoa_project_job(
            job.replace(model_path=None), model_path=model,
            source_new=ArraySource(g), source_ref=ArraySource(g),
        )
    assert not [x for x in w if "allele-frequency" in str(x.message)]


def test_single_sample_projection_does_not_warn(rng, tmp_path):
    """A one-sample new cohort has very noisy per-variant AFs (r tops
    out ~0.3-0.5 vs the panel even with identical coding); the
    concordance check must not cry wolf on this flagship use case —
    only a NEGATIVE correlation (true flips) warns at small sizes."""
    import warnings

    g, _ = _cohort(rng, n=40, v=3000)
    ref, one = g[:39], g[39:]
    model = str(tmp_path / "m.npz")
    job = JobConfig(
        ingest=IngestConfig(block_variants=512),
        compute=ComputeConfig(metric="ibs", num_pc=3),
        model_path=model,
    )
    pcoa_job(job, source=ArraySource(ref))
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        pcoa_project_job(
            job.replace(model_path=None), model_path=model,
            source_new=ArraySource(one), source_ref=ArraySource(ref),
        )
    assert not [x for x in w if "allele-frequency" in str(x.message)]
    # but a FLIPPED single sample still warns (negative correlation)
    flipped = np.where(one >= 0, 2 - one, -1).astype(np.int8)
    with pytest.warns(RuntimeWarning, match="swapped"):
        pcoa_project_job(
            job.replace(model_path=None), model_path=model,
            source_new=ArraySource(flipped), source_ref=ArraySource(ref),
        )


def test_model_schema_version_and_friendly_errors(rng, tmp_path):
    """Satellite: saved models carry schema_version; load_model refuses
    pre-versioning / future / truncated / field-missing files with a
    friendly error naming the cause — never a raw KeyError/BadZipFile
    (the serving layer hot-reloads models and must be able to diagnose
    a bad file from the exception alone)."""
    from spark_examples_tpu.pipelines.project import (
        SCHEMA_VERSION, ModelFormatError, load_model,
    )

    g = random_genotypes(rng, n=10, v=256)
    model = str(tmp_path / "m.npz")
    job = JobConfig(
        ingest=IngestConfig(block_variants=64),
        compute=ComputeConfig(metric="ibs", num_pc=3),
        model_path=model,
    )
    pcoa_job(job, source=ArraySource(g))
    with np.load(model) as mdl:
        assert int(mdl["schema_version"]) == SCHEMA_VERSION
        payload = {k: mdl[k] for k in mdl.files}
    loaded = load_model(model)
    assert loaded.kind == "pcoa" and loaded.metric == "ibs"
    assert loaded.n_ref == 10
    assert loaded.digest() == load_model(model).digest()

    # pre-versioning file -> error naming schema_version + the remedy
    legacy = str(tmp_path / "legacy.npz")
    np.savez(legacy, **{k: v for k, v in payload.items()
                        if k != "schema_version"})
    with pytest.raises(ModelFormatError, match="schema_version"):
        load_model(legacy)

    # missing required field -> error NAMES the field
    broken = str(tmp_path / "broken.npz")
    np.savez(broken, **{k: v for k, v in payload.items()
                        if k != "d2_colmean"})
    with pytest.raises(ModelFormatError, match="d2_colmean"):
        load_model(broken)

    # a model from a newer build is refused, not misread
    future = str(tmp_path / "future.npz")
    np.savez(future, **{**payload,
                        "schema_version": np.int64(SCHEMA_VERSION + 1)})
    with pytest.raises(ModelFormatError, match="newer"):
        load_model(future)

    # truncated archive (the formerly opaque failure) -> friendly error
    trunc = str(tmp_path / "trunc.npz")
    raw = open(model, "rb").read()
    with open(trunc, "wb") as f:
        f.write(raw[: len(raw) // 3])
    with pytest.raises(ModelFormatError, match="truncated or corrupt"):
        load_model(trunc)
    # ... including through the job surface
    with pytest.raises(ModelFormatError):
        pcoa_project_job(
            job.replace(model_path=None), model_path=trunc,
            source_new=ArraySource(g), source_ref=ArraySource(g),
        )
    # pca models carry the version too
    pca_model = str(tmp_path / "pca.npz")
    from spark_examples_tpu.pipelines.jobs import variants_pca_job

    variants_pca_job(
        JobConfig(ingest=IngestConfig(block_variants=64),
                  compute=ComputeConfig(num_pc=3), model_path=pca_model),
        source=ArraySource(g),
    )
    assert load_model(pca_model).kind == "pca"


def test_cross_update_cache_is_explicit_and_clearable(rng, monkeypatch):
    """Satellite: the tiled cross-update builder's compiled-closure memo
    is explicit, LRU-bounded, and clear_caches() empties it — a
    hot-reload loop cannot grow it unboundedly (the old module-level
    lru_cache pinned stale mesh/sharding objects for the process
    lifetime)."""
    from spark_examples_tpu.core import meshes
    from spark_examples_tpu.pipelines import project as P

    mesh = meshes.make_mesh()
    P.clear_caches()
    assert len(P._CROSS_UPDATE_CACHE) == 0
    plan = P.CrossPlan(mesh, "tile2d")

    # same key -> one entry, the cached builder is returned
    fn1 = P._cross_update_tiled(plan, ("m", "d1"))
    fn2 = P._cross_update_tiled(plan, ("m", "d1"))
    assert fn1 is fn2
    assert len(P._CROSS_UPDATE_CACHE) == 1

    # the LRU bound holds under key churn (capacity shrunk for the test)
    monkeypatch.setattr(P, "_CROSS_UPDATE_CAPACITY", 2)
    for stats in (("m",), ("d1",), ("s",), ("m", "d1")):
        P._cross_update_tiled(plan, stats)
        assert len(P._CROSS_UPDATE_CACHE) <= 2

    # a reload loop stays flat: build -> clear, N times
    for _ in range(5):
        P._cross_update_tiled(plan, ("m", "d1"))
        assert len(P._CROSS_UPDATE_CACHE) >= 1
        P.clear_caches()
        assert len(P._CROSS_UPDATE_CACHE) == 0


def test_cross_accumulate_tile2d_matches_replicated(rng):
    """VERDICT r4 weak #5: the cross-cohort accumulation under a tile2d
    plan (new rows over i, ref rows over j, no full (A, N_ref) leaf on
    any device) must equal the replicated path bit for bit."""
    import jax

    from spark_examples_tpu.core import meshes
    from spark_examples_tpu.core.profiling import PhaseTimer
    from spark_examples_tpu.parallel.pcoa_sharded import assert_tiled
    from spark_examples_tpu.pipelines.project import (
        CrossPlan, _accumulate_cross, cross_plan_for,
    )

    g_new = random_genotypes(rng, n=16, v=768, missing_rate=0.1)
    g_ref = random_genotypes(rng, n=32, v=768, missing_rate=0.1)
    job = JobConfig(ingest=IngestConfig(block_variants=256),
                    compute=ComputeConfig(metric="ibs"))
    mesh = meshes.make_mesh()
    stats = ("m", "d1")

    def run(mode):
        plan = CrossPlan(mesh, mode)
        acc, nv, _ = _accumulate_cross(
            job, ArraySource(g_new), ArraySource(g_ref), stats,
            PhaseTimer(), plan=plan,
        )
        assert nv == 768
        return plan, acc

    plan_t, tiled = run("tile2d")
    for k, v in tiled.items():
        assert_tiled(v, plan_t, k)  # every shard a proper (8, 8) tile
    _, repl = run("replicated")
    for k in stats:
        np.testing.assert_array_equal(
            np.asarray(tiled[k]), np.asarray(repl[k]), err_msg=k
        )

    # auto mode: small shapes stay replicated; forced tile2d with a
    # non-divisible axis is rejected loudly.
    assert cross_plan_for(mesh, 16, 32, 2, "auto").mode == "replicated"
    # --gram-mode variant (a valid symmetric-path choice carried in the
    # same job config) maps to the replicated cross path, not an error.
    assert cross_plan_for(mesh, 16, 32, 2, "variant").mode == "replicated"
    with pytest.raises(ValueError, match="divisible"):
        cross_plan_for(mesh, 17, 32, 2, "tile2d")


def test_cross_kinship_and_projection_tile2d_end_to_end(rng, tmp_path):
    """Jobs route through the tiled cross path when gram_mode forces it
    and produce the same outputs as the default path."""
    from spark_examples_tpu.pipelines.project import cross_kinship_job

    g, _labels = _cohort(rng, n=48, v=1024)
    ref, new = g[:32], g[32:]
    model = str(tmp_path / "m.npz")
    base = JobConfig(
        ingest=IngestConfig(block_variants=256),
        compute=ComputeConfig(metric="ibs", num_pc=4),
        model_path=model,
    )
    pcoa_job(base, source=ArraySource(ref))

    def project(mode):
        job = base.replace(
            model_path=None,
            compute=dataclasses.replace(base.compute, gram_mode=mode),
        )
        return pcoa_project_job(
            job, model_path=model, source_new=ArraySource(new),
            source_ref=ArraySource(ref),
        ).coords

    np.testing.assert_allclose(
        project("tile2d"), project("auto"), atol=1e-4
    )

    def kinship(mode):
        job = base.replace(
            model_path=None,
            compute=dataclasses.replace(base.compute, gram_mode=mode),
        )
        return cross_kinship_job(
            job, ArraySource(new), ArraySource(ref)
        ).similarity

    np.testing.assert_array_equal(kinship("tile2d"), kinship("auto"))

"""Sharded-gram parity on the 8-virtual-device CPU mesh.

The reference's distributed story was tested via Spark `local[*]`
(SURVEY.md §4); this is the analogue: the same sharded code paths
(mesh, sharding annotations, XLA-inserted collectives) run across 8
virtual CPU devices and must agree exactly with the single-device path.
"""

import jax
import numpy as np
import pytest

from spark_examples_tpu.core import meshes
from spark_examples_tpu.ops import distances, gram
from spark_examples_tpu.parallel import gram_sharded
from tests.conftest import random_genotypes


@pytest.fixture(scope="module")
def mesh():
    assert len(jax.devices()) == 8, "conftest must force 8 CPU devices"
    return meshes.make_mesh()


def _single_device_reference(g, metric, block=64):
    acc = gram.init(g.shape[0], metric)
    for s in range(0, g.shape[1], block):
        acc = gram.update(acc, g[:, s : s + block], metric)
    return {k: np.asarray(v) for k, v in acc.items()}


@pytest.mark.parametrize("mode", ["variant", "tile2d", "replicated"])
@pytest.mark.parametrize("metric", ["ibs", "shared-alt", "grm"])
def test_sharded_modes_match_single_device(rng, mesh, mode, metric):
    g = random_genotypes(rng, n=32, v=512, missing_rate=0.12)
    plan = gram_sharded.GramPlan(mesh, mode)
    acc = gram_sharded.init_sharded(plan, 32, metric)
    update = gram_sharded.make_update(plan, metric)
    for s in range(0, 512, 64):
        acc = update(acc, g[:, s : s + 64])
    want = _single_device_reference(g, metric)
    for k in want:
        np.testing.assert_allclose(
            np.asarray(acc[k]), want[k], rtol=1e-5, atol=1e-5,
            err_msg=f"{mode}/{metric}/{k}",
        )


def test_mesh_autofactor(mesh):
    assert mesh.devices.shape == (2, 4)
    assert mesh.axis_names == ("i", "j")


def test_plan_auto_selection(mesh):
    assert gram_sharded.plan_for(mesh, 100, "ibs").mode == "variant"
    big_n = 80_000  # 2 pieces * 4B * N^2 >> budget -> tiled
    assert gram_sharded.plan_for(mesh, big_n, "ibs").mode == "tile2d"
    one = meshes.make_mesh(jax.devices()[:1])
    assert gram_sharded.plan_for(one, 100, "ibs").mode == "replicated"


def test_hard_sync_forces_every_shard(mesh, monkeypatch):
    """hard_sync must BLOCK on a value that depends on EVERY shard —
    forcing only the (0, 0) tile would leave the other devices' chains
    unforced and make mesh timings dishonest (VERDICT r2 weak #2). The
    barrier is a jitted checksum with ONE D2H fetch (instead of one per
    leaf); the spy asserts the fetch happens and that its value is the
    sum over ALL elements of all device leaves — the proof that every
    shard's data entered the round-tripped reduction, so no device's
    chain can be skipped and removing the fetch breaks the test."""
    from spark_examples_tpu.core import profiling

    x = jax.device_put(np.arange(64.0).reshape(8, 8), meshes.tile2d(mesh))
    z = jax.numpy.arange(3.0)

    fetched = []

    class NpSpy:
        @staticmethod
        def asarray(a, *args, **kw):
            fetched.append(np.asarray(a, *args, **kw))
            return fetched[-1]

    monkeypatch.setattr(profiling, "np", NpSpy)
    out = profiling.hard_sync({"a": x, "z": z, "host": np.ones(2)})
    assert out["a"] is x and out["z"] is z
    # exactly one D2H round-trip, and its value covers every shard of
    # every device leaf (2016 from the 8-tile x, 3 from z; the host
    # numpy leaf is excluded)
    assert len(fetched) == 1
    assert float(fetched[0]) == float(np.arange(64.0).sum() + 3.0)

    # Unregistered-dataclass results (PCoAResult etc.) are opaque leaves
    # to tree_util — hard_sync must expand them or it barriers on
    # NOTHING (the bug that made a dense eigh "finish" in 2 ms while its
    # 371 ms drained into the next phase).
    import dataclasses

    @dataclasses.dataclass
    class Res:
        coords: object
        note: str = "x"

    fetched.clear()
    res = Res(coords=jax.numpy.arange(5.0))
    assert profiling.hard_sync(res) is res
    assert len(fetched) == 1
    assert float(fetched[0]) == 10.0  # the coords really entered the sum

    # containers INSIDE dataclass fields expand too (GramRun.acc is a
    # dict of device arrays)
    fetched.clear()
    res = Res(coords={"a": jax.numpy.arange(3.0),
                      "b": [jax.numpy.ones(2), "meta"]})
    profiling.hard_sync(res)
    assert len(fetched) == 1
    assert float(fetched[0]) == 5.0  # 0+1+2 from a, 1+1 from b


def test_tile2d_sharded_solve_matches_dense(rng, mesh):
    """The config-4 route: finalize -> center -> randomized eigh with
    every N x N stage tile2d-sharded must agree with the dense path, and
    the tile contract must hold at each stage boundary (the built-in
    assert_tiled checks raise on any full-size leaf)."""
    from spark_examples_tpu.models.pcoa import fit_pcoa
    from spark_examples_tpu.parallel import pcoa_sharded

    n = 64
    g = random_genotypes(rng, n=n, v=480, missing_rate=0.1)
    plan = gram_sharded.GramPlan(mesh, "tile2d")
    acc = gram_sharded.init_sharded(plan, n, "ibs")
    update = gram_sharded.make_update(plan, "ibs")
    for s in range(0, 480, 96):
        acc = update(acc, g[:, s : s + 96])

    res = pcoa_sharded.pcoa_coords_sharded(plan, acc, "ibs", k=4)

    ref_acc = _single_device_reference(g, "ibs", block=96)
    ref_dist = np.asarray(
        distances.finalize(
            {k: np.asarray(v) for k, v in ref_acc.items()}, "ibs"
        )["distance"]
    )
    # Dense route with the same randomized solver, same key and params.
    ref = fit_pcoa(ref_dist.astype(np.float32), k=4, method="randomized")
    np.testing.assert_allclose(
        np.asarray(res.eigenvalues), np.asarray(ref.eigenvalues),
        rtol=1e-3, atol=1e-4,
    )
    np.testing.assert_allclose(
        np.abs(np.asarray(res.coords)), np.abs(np.asarray(ref.coords)),
        rtol=1e-2, atol=1e-3,
    )
    # And the randomized solve itself must track the exact dense eigh.
    exact = fit_pcoa(ref_dist.astype(np.float32), k=4, method="dense")
    np.testing.assert_allclose(
        np.asarray(res.eigenvalues), np.asarray(exact.eigenvalues),
        rtol=1e-2, atol=1e-3,
    )


def test_assert_tiled_rejects_replicated(mesh):
    from spark_examples_tpu.parallel import pcoa_sharded

    plan = gram_sharded.GramPlan(mesh, "tile2d")
    full = jax.device_put(np.zeros((16, 16)), meshes.replicated(mesh))
    with pytest.raises(AssertionError, match="full-size leaf"):
        pcoa_sharded.assert_tiled(full, plan, "test")


def test_pcoa_job_tile2d_route_matches_variant_route(rng):
    """pcoa_job with gram_mode=tile2d takes the fully-sharded solve and
    must produce the same coordinates as the variant-mode dense route."""
    from spark_examples_tpu.core.config import (
        ComputeConfig, IngestConfig, JobConfig,
    )
    from spark_examples_tpu.pipelines import jobs

    def run(mode, eigh_mode):
        job = JobConfig(
            ingest=IngestConfig(source="synthetic", n_samples=48,
                                n_variants=1500, block_variants=512, seed=9),
            compute=ComputeConfig(metric="ibs", num_pc=3, gram_mode=mode,
                                  eigh_mode=eigh_mode),
        )
        return jobs.pcoa_job(job)

    tiled = run("tile2d", "randomized")
    dense = run("variant", "randomized")
    np.testing.assert_allclose(
        tiled.eigenvalues, dense.eigenvalues, rtol=1e-3, atol=1e-4
    )
    np.testing.assert_allclose(
        np.abs(tiled.coords), np.abs(dense.coords), rtol=1e-2, atol=1e-3
    )
    # the sharded route records the same phase structure
    assert "eigh" in tiled.timer.phases and "gram" in tiled.timer.phases


def test_pca_sharded_matches_dense(rng, mesh):
    """The flagship PCA at the tile2d regime: finalize -> center ->
    top-|lambda| eig fully sharded must match models/pca.fit_pca, with
    the tile contract asserted at every N x N stage boundary."""
    from spark_examples_tpu.models.pca import fit_pca
    from spark_examples_tpu.ops import distances
    from spark_examples_tpu.parallel import pcoa_sharded

    n = 64
    g = random_genotypes(rng, n=n, v=600, missing_rate=0.1)
    plan = gram_sharded.GramPlan(mesh, "tile2d")
    acc = gram_sharded.init_sharded(plan, n, "shared-alt")
    update = gram_sharded.make_update(plan, "shared-alt")
    for s in range(0, 600, 120):
        acc = update(acc, g[:, s : s + 120])
    res = pcoa_sharded.pca_coords_sharded(plan, acc, "shared-alt", k=3,
                                          iters=12, check_shardings=True)

    dense_acc = gram.update(gram.init(n, "shared-alt"), g, "shared-alt")
    sim = distances.finalize(dense_acc, "shared-alt")["similarity"]
    want = fit_pca(np.asarray(sim), k=3)
    # Eigenvalues agree to sub-percent (this cohort is unstructured so
    # the spectrum is clustered — the hard case for subspace iteration);
    # eigenVECTORS may rotate within a near-degenerate cluster, so the
    # gap-independent correctness criterion is the residual: each
    # returned (lambda, v) must be a genuine eigenpair of the DENSE
    # centered matrix.
    np.testing.assert_allclose(
        np.asarray(res.eigenvalues), np.asarray(want.eigenvalues),
        rtol=5e-3,
    )
    from spark_examples_tpu.ops.centering import center_matrix

    c = np.asarray(center_matrix(np.asarray(sim, np.float32)))
    c = 0.5 * (c + c.T)
    vals = np.asarray(res.eigenvalues)
    vecs = np.asarray(res.coords) / vals[None, :]  # coords = v * lambda
    resid = np.linalg.norm(c @ vecs - vecs * vals[None, :], axis=0)
    assert (resid / np.abs(vals) < 2e-2).all(), resid / np.abs(vals)


def test_pca_job_tile2d_route_matches_variant_route(rng):
    """variants_pca_job with gram_mode=tile2d takes the fully-sharded
    PCA solve and must agree with the variant-mode dense route."""
    from spark_examples_tpu.core.config import (
        ComputeConfig, IngestConfig, JobConfig,
    )
    from spark_examples_tpu.pipelines import jobs

    def run(mode):
        job = JobConfig(
            ingest=IngestConfig(source="synthetic", n_samples=48,
                                n_variants=1500, block_variants=512, seed=9),
            compute=ComputeConfig(num_pc=3, gram_mode=mode),
        )
        return jobs.variants_pca_job(job)

    tiled = run("tile2d")
    dense = run("variant")
    np.testing.assert_allclose(
        tiled.eigenvalues, dense.eigenvalues, rtol=5e-3
    )
    # atol-dominant: randomized-vs-dense coords agree to ~1 unit on
    # components of magnitude ~140; near-zero entries make rtol alone
    # meaningless
    np.testing.assert_allclose(
        np.abs(tiled.coords), np.abs(dense.coords), rtol=2e-2, atol=1.0
    )


def test_sharded_end_to_end_pcoa(rng, mesh):
    """Sharded accumulate -> finalize -> PCoA equals unsharded run."""
    from spark_examples_tpu.models.pcoa import fit_pcoa

    g = random_genotypes(rng, n=24, v=300, missing_rate=0.05)
    plan = gram_sharded.GramPlan(mesh, "variant")
    acc = gram_sharded.init_sharded(plan, 24, "ibs")
    update = gram_sharded.make_update(plan, "ibs")
    for s in range(0, 300, 100):
        acc = update(acc, g[:, s : s + 100])
    dist = distances.finalize(acc, "ibs")["distance"]
    res = fit_pcoa(dist, k=3)

    ref_acc = _single_device_reference(g, "ibs", block=100)
    ref_stats = {
        k: np.asarray(v) for k, v in gram.combine(ref_acc, "ibs").items()
    }
    ref_dist = np.where(
        ref_stats["m"] > 0, ref_stats["d1"] / (2 * ref_stats["m"]), 0.0
    )
    ref = fit_pcoa(ref_dist.astype(np.float32), k=3)
    np.testing.assert_allclose(
        np.asarray(res.eigenvalues), np.asarray(ref.eigenvalues),
        rtol=1e-4, atol=1e-5,
    )
    np.testing.assert_allclose(
        np.abs(np.asarray(res.coords)), np.abs(np.asarray(ref.coords)),
        rtol=1e-3, atol=1e-4,
    )


@pytest.mark.parametrize("metric", ["ibs", "grm"])
def test_tile2d_replicated_block_layout_matches(rng, mesh, metric):
    """The staged/on-device transport: replicated blocks into a tile2d
    accumulation produce the same result as the sharded transport."""
    g = random_genotypes(rng, n=32, v=256, missing_rate=0.1)
    plan = gram_sharded.GramPlan(mesh, "tile2d")
    acc = gram_sharded.init_sharded(plan, 32, metric)
    update = gram_sharded.make_update(plan, metric,
                                      block_layout="replicated")
    for s in range(0, 256, 64):
        acc = update(acc, g[:, s : s + 64])
    want = _single_device_reference(g, metric)
    for k in want:
        np.testing.assert_allclose(
            np.asarray(acc[k]), want[k], rtol=1e-5, atol=1e-5, err_msg=k
        )


def test_tile2d_replicated_layout_compiles_without_collectives(mesh):
    """The config-4 projection's premise, compile-checked: with blocks
    already resident on every device (block_layout="replicated"), the
    tile2d hot-loop update lowers to purely local slicing + matmuls —
    no all-gather / all-to-all / collective-permute anywhere. (The
    default "sharded" transport, by contrast, all-gathers each block
    over ICI — asserted below so the documented trade-off tracks the
    code. An all-REDUCE never belongs in either tile2d lowering: tiles
    are disjoint, nothing sums across devices — and left to the SPMD
    partitioner's own choice it DID pick a partial-tile all-reduce,
    tile_area x 4 B x pieces of traffic per block, which is why both
    transports are explicit shard_maps.)"""
    from spark_examples_tpu.parallel.gram_sharded import (
        _acc_shardings, _jitted_update,
    )

    plan = gram_sharded.GramPlan(mesh, "tile2d")
    n, v = 32, 64
    acc_spec = {
        k: jax.ShapeDtypeStruct((n, n), np.int32)
        for k in gram.PIECES_FOR_METRIC["ibs"]
    }
    blk_spec = jax.ShapeDtypeStruct((n, v), np.int8)

    def hlo(layout):
        jitted = _jitted_update(plan, "ibs", False, False, layout)
        return jitted.lower(acc_spec, blk_spec).compile().as_text()

    collectives = ("all-gather", "all-to-all", "collective-permute",
                   "all-reduce")
    replicated = hlo("replicated")
    assert not any(c in replicated for c in collectives), (
        "replicated-layout tile2d update must have no collectives in "
        "the hot loop"
    )
    sharded = hlo("sharded")
    assert "all-gather" in sharded, (
        "sharded-layout tile2d update is expected to all-gather the "
        "block over ICI (the documented host-link trade-off)"
    )
    assert "all-reduce" not in sharded, (
        "a partial-tile all-reduce crept back into the sharded tile2d "
        "update — that is tile_area x 4 B x pieces of ICI traffic per "
        "block instead of one block gather"
    )


def test_replicated_block_layout_rejected_for_variant_mode(mesh):
    plan = gram_sharded.GramPlan(mesh, "variant")
    with pytest.raises(ValueError, match="redundantly"):
        gram_sharded.make_update(plan, "ibs", block_layout="replicated")


# ------------------------------------------------------- ring transport


@pytest.mark.parametrize("lowering", ["reference", "fused"])
@pytest.mark.parametrize("packed", [False, True])
@pytest.mark.parametrize(
    "metric", ["ibs", "ibs2", "king", "jaccard", "grm"]
)
def test_ring_transport_matches_gather(rng, mesh, metric, packed,
                                       lowering):
    """The tentpole contract: the ppermute ring schedule produces the
    SAME accumulators as the bulk all_gather — BIT-identical for every
    int32-accumulating kernel (integer sums are exact under the ring's
    per-shard reordering), allclose for grm's f32. Every device starts
    at a different ring offset (device d contracts shards d, d+1, ...,
    d-1 in that order), so one pass covers all 8 offsets; the final
    ragged block additionally exercises the pad path on both
    transports. The fused axis reruns both transports with the packed
    Pallas tile body (interpret mode on CPU) and additionally pins them
    to the reference-lowering gather run — the checkpointed accumulator
    contract extends across lowerings, not just transports."""
    from spark_examples_tpu.ingest import bitpack

    if lowering == "fused" and not (packed and metric != "grm"):
        pytest.skip("fused lowering decodes the 2-bit packed stream "
                    "(count family only)")

    g = random_genotypes(rng, n=32, v=288, missing_rate=0.12)
    plan = gram_sharded.GramPlan(mesh, "tile2d")

    def _stream(transport, lw):
        acc = gram_sharded.init_sharded(plan, 32, metric)
        update = gram_sharded.make_update(plan, metric, packed=packed,
                                          transport=transport,
                                          lowering=lw)
        for s in range(0, 288, 96):  # final block ragged after padding
            blk = g[:, s:s + 96]
            if packed:
                blk = bitpack.pack_dosages(blk)
            acc = update(acc, blk)
        return {k: np.asarray(v) for k, v in acc.items()}

    accs = {t: _stream(t, lowering) for t in ("gather", "ring")}
    if lowering == "fused":
        # the cross-lowering oracle: fused rings/gathers must equal the
        # reference lowering bit-exactly (int32 sums are reorder-exact)
        accs["reference"] = _stream("gather", "reference")
    for k in accs["gather"]:
        for other in [t for t in accs if t != "gather"]:
            if metric == "grm" and k == "zz":
                np.testing.assert_allclose(
                    accs["gather"][k], accs[other][k],
                    rtol=1e-5, atol=1e-4, err_msg=f"{metric}/{k}")
            else:
                np.testing.assert_array_equal(
                    accs["gather"][k], accs[other][k],
                    err_msg=f"{other} diverged from gather on "
                            f"{metric}/{k} (packed={packed}, "
                            f"lowering={lowering})")


def test_ring_lowering_is_permute_only(mesh):
    """Compile check of the overlapped schedule: the ring transport's
    hot loop lowers to collective-permutes ONLY — no bulk all-gather
    serializing in front of the contraction, and no partial-tile
    all-reduce (the pathological SPMD lowering both explicit shard_maps
    exist to prevent)."""
    from spark_examples_tpu.parallel.gram_sharded import _jitted_update

    plan = gram_sharded.GramPlan(mesh, "tile2d")
    n, v = 32, 64
    acc_spec = {
        k: jax.ShapeDtypeStruct((n, n), np.int32)
        for k in gram.PIECES_FOR_METRIC["ibs"]
    }
    blk_spec = jax.ShapeDtypeStruct((n, v), np.int8)
    jitted = _jitted_update(plan, "ibs", False, False, "sharded", "ring")
    hlo = jitted.lower(acc_spec, blk_spec).compile().as_text()
    assert "collective-permute" in hlo, (
        "ring transport must move shards via collective-permute"
    )
    assert "all-gather" not in hlo and "all-reduce" not in hlo, (
        "a bulk collective crept into the ring transport's hot loop"
    )


def test_transport_auto_resolution(mesh):
    """The FLOPs-model choice: production shapes (76k x 4096 packed)
    hide a shard hop behind one ring step's contraction -> ring; tiny
    test tiles do not -> gather. Non-tile2d plans have no choice."""
    plan = gram_sharded.GramPlan(mesh, "tile2d")
    assert gram_sharded.resolve_transport(
        plan, "ibs", 76_000, 4096, True) == "ring"
    assert gram_sharded.resolve_transport(
        plan, "ibs", 32, 64, False) == "gather"
    vplan = gram_sharded.GramPlan(mesh, "variant")
    assert gram_sharded.resolve_transport(
        vplan, "ibs", 76_000, 4096, True) == "gather"


def test_ring_divisibility_validated_with_flags_named(mesh):
    """The satellite contract: a block width the shard count cannot
    divide dies with --tile2d-transport/--block-variants named, not as
    a raw shard_map sharding error; and the config-time flag value
    check names the flag too."""
    plan = gram_sharded.GramPlan(mesh, "tile2d")
    with pytest.raises(ValueError, match=r"--tile2d-transport ring"):
        gram_sharded.check_ring_divisible(60, plan, packed=False)
    with pytest.raises(ValueError, match=r"--block-variants"):
        gram_sharded.check_ring_divisible(7, plan, packed=True)
    # divisible widths (what the padded feeds produce) pass silently
    gram_sharded.check_ring_divisible(64, plan, packed=False)

    from spark_examples_tpu.core.config import ComputeConfig

    with pytest.raises(ValueError, match=r"--tile2d-transport"):
        ComputeConfig(tile2d_transport="mesh")


def test_ring_run_gram_checkpoint_resumes_bit_identical(rng, tmp_path):
    """Kill/resume row for ring mode: a ring-transport streamed job
    killed mid-stream resumes from its checkpoint to the SAME
    similarity as the uninterrupted ring run — and both match the
    gather transport bit-exactly (the checkpointed accumulator is
    transport-agnostic by the bit-identity contract)."""
    from spark_examples_tpu.core.config import (
        ComputeConfig, IngestConfig, JobConfig,
    )
    from spark_examples_tpu.ingest import ArraySource
    from spark_examples_tpu.pipelines import runner

    g = random_genotypes(rng, n=16, v=1024, missing_rate=0.1)

    def job(transport, ckpt=None):
        return JobConfig(
            ingest=IngestConfig(block_variants=128),
            compute=ComputeConfig(
                metric="ibs", gram_mode="tile2d",
                tile2d_transport=transport,
                checkpoint_dir=ckpt,
                checkpoint_every_blocks=2 if ckpt else 0,
            ),
        )

    class Dying(ArraySource):
        def blocks(self, bv, start_variant=0):
            for b, m in super().blocks(bv, start_variant):
                if m.start >= 5 * 128:
                    raise RuntimeError("simulated preemption")
                yield b, m

    ckpt = str(tmp_path / "ck")
    with pytest.raises(RuntimeError, match="preemption"):
        runner.run_similarity(job("ring", ckpt), source=Dying(g))
    import os

    assert os.path.isdir(ckpt)  # a mid-stream checkpoint exists
    resumed = runner.run_similarity(job("ring", ckpt), source=ArraySource(g))
    clean_ring = runner.run_similarity(job("ring"), source=ArraySource(g))
    clean_gather = runner.run_similarity(job("gather"), source=ArraySource(g))
    np.testing.assert_array_equal(resumed.similarity,
                                  clean_ring.similarity)
    np.testing.assert_array_equal(resumed.similarity,
                                  clean_gather.similarity)


def test_cross_lowering_checkpoint_resumes_bit_identical(rng, tmp_path):
    """Kill/resume row across the LOWERING axis: a checkpoint written
    while streaming under one gram lowering resumes under the OTHER to
    the same similarity as either uninterrupted run — the accumulator
    on disk is int32 piece counts, identical bit-for-bit whichever
    lowering produced them, so operators can flip --gram-lowering
    mid-incident without invalidating checkpoints."""
    from spark_examples_tpu.core.config import (
        ComputeConfig, IngestConfig, JobConfig,
    )
    from spark_examples_tpu.ingest import ArraySource
    from spark_examples_tpu.pipelines import runner

    g = random_genotypes(rng, n=16, v=1024, missing_rate=0.1)

    def job(lowering, ckpt=None):
        return JobConfig(
            ingest=IngestConfig(block_variants=128),
            compute=ComputeConfig(
                metric="king", gram_mode="tile2d",
                gram_lowering=lowering,
                checkpoint_dir=ckpt,
                checkpoint_every_blocks=2 if ckpt else 0,
            ),
        )

    class Dying(ArraySource):
        def blocks(self, bv, start_variant=0):
            for b, m in super().blocks(bv, start_variant):
                if m.start >= 5 * 128:
                    raise RuntimeError("simulated preemption")
                yield b, m

    clean = {lw: runner.run_similarity(job(lw), source=ArraySource(g))
             for lw in ("reference", "fused")}
    np.testing.assert_array_equal(clean["reference"].similarity,
                                  clean["fused"].similarity)
    for wrote, resumed_under in (("reference", "fused"),
                                 ("fused", "reference")):
        ckpt = str(tmp_path / f"ck-{wrote}")
        with pytest.raises(RuntimeError, match="preemption"):
            runner.run_similarity(job(wrote, ckpt), source=Dying(g))
        out = runner.run_similarity(job(resumed_under, ckpt),
                                    source=ArraySource(g))
        np.testing.assert_array_equal(
            out.similarity, clean[resumed_under].similarity,
            err_msg=f"checkpoint written under {wrote} did not resume "
                    f"bit-identically under {resumed_under}")


def test_ring_update_counts_ring_steps(rng, mesh):
    from spark_examples_tpu.core import telemetry

    plan = gram_sharded.GramPlan(mesh, "tile2d")
    before = telemetry.counter_value("gram.ring_steps")
    update = gram_sharded.make_update(plan, "ibs", transport="ring")
    acc = gram_sharded.init_sharded(plan, 32, "ibs")
    update(acc, random_genotypes(rng, n=32, v=64, missing_rate=0.1))
    assert telemetry.counter_value("gram.ring_steps") - before == 8


def test_fused_update_counts_fused_blocks(rng, mesh):
    from spark_examples_tpu.core import telemetry
    from spark_examples_tpu.ingest import bitpack

    plan = gram_sharded.GramPlan(mesh, "tile2d")
    before = telemetry.counter_value("gram.fused_blocks")
    update = gram_sharded.make_update(plan, "ibs", packed=True,
                                      lowering="fused")
    acc = gram_sharded.init_sharded(plan, 32, "ibs")
    blk = bitpack.pack_dosages(
        random_genotypes(rng, n=32, v=64, missing_rate=0.1))
    update(acc, blk)
    assert telemetry.counter_value("gram.fused_blocks") - before == 1


def test_make_update_validates_lowering(mesh):
    """make_update takes the RESOLVED lowering only — auto must be
    resolved by the caller (gram.resolve_gram_lowering) — and a fused
    request that cannot run dies with the flags named: dense streams
    have nothing to decode, and a multi-device variant-mode plan
    cannot split one pallas_call across chips."""
    plan = gram_sharded.GramPlan(mesh, "tile2d")
    with pytest.raises(ValueError, match="unresolved gram lowering"):
        gram_sharded.make_update(plan, "ibs", packed=True,
                                 lowering="auto")
    with pytest.raises(ValueError, match=r"--pack-stream"):
        gram_sharded.make_update(plan, "ibs", packed=False,
                                 lowering="fused")
    vplan = gram_sharded.GramPlan(mesh, "variant")
    with pytest.raises(ValueError, match="tile2d"):
        gram_sharded.make_update(vplan, "ibs", packed=True,
                                 lowering="fused")


def test_sharded_route_emits_no_unusable_donation_warnings(rng, mesh):
    """The MULTICHIP_r05 satellite: every jit of the tile2d update AND
    the sharded finalize/center/eigh route must donate only buffers the
    executable can actually alias — 'Some donated buffers were not
    usable' in the dryrun tail meant int32 accumulators (and grm's
    scalar) were being donated into f32/replicated outputs for no
    gain. Caches are cleared so lowering (where the warning fires)
    happens inside the catch for every stage."""
    import warnings

    from spark_examples_tpu.parallel import pcoa_sharded
    from spark_examples_tpu.parallel.gram_sharded import _jitted_update

    _jitted_update.cache_clear()
    pcoa_sharded._finalize_field_jit.cache_clear()
    pcoa_sharded._center_jit.cache_clear()
    pcoa_sharded._eigh_jit.cache_clear()

    plan = gram_sharded.GramPlan(mesh, "tile2d")
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        for metric in ("ibs", "grm"):
            acc = gram_sharded.init_sharded(plan, 32, metric)
            for transport in ("gather", "ring"):
                update = gram_sharded.make_update(plan, metric,
                                                  transport=transport)
                acc = update(acc, random_genotypes(rng, 32, 64, 0.1))
            res = pcoa_sharded.pcoa_coords_sharded(plan, acc, metric, k=3)
            jax.block_until_ready(res.coords)
        acc = gram_sharded.init_sharded(plan, 32, "shared-alt")
        update = gram_sharded.make_update(plan, "shared-alt")
        acc = update(acc, random_genotypes(rng, 32, 64, 0.1))
        res = pcoa_sharded.pca_coords_sharded(plan, acc, "shared-alt", k=3)
        jax.block_until_ready(res.coords)
    bad = [str(w.message) for w in caught
           if "donated buffers" in str(w.message)]
    assert not bad, (
        "sharded route emitted unusable-donation warnings:\n"
        + "\n".join(bad)
    )

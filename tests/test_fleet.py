"""Fleet serving (serve/fleet.py + pool.py + router.py): manifest
validation, 3-route bit-identity against the single-model server AND
the offline `project` CLI (including immediately after an LRU eviction
+ re-stage), the HBM-budgeted warm pool, priority-class admission
(interactive preempts batch; per-class sheds and deadlines), the
fleet.stage fault site + route circuit breaker, result-cache namespace
lifecycle on route unload, client-side replica hedging, the fleet HTTP
front, and the `serve --fleet` CLI.

The acceptance test (`test_acceptance_multi_tenant_mix`) is the tier-1
smoke of ISSUE 15's contract: a 3-route fleet under the multi-tenant
loadgen mix serves every route bit-identically while the pool stays
under budget with evictions observed, interactive p99 below batch p99,
and no quarantine entries.
"""

import dataclasses
import json
import threading
import time
import urllib.error
import urllib.request
from types import SimpleNamespace

import numpy as np
import pytest

from spark_examples_tpu.core import faults, telemetry
from spark_examples_tpu.core.config import (
    PRIORITY_CLASSES,
    ComputeConfig,
    IngestConfig,
    JobConfig,
    ServeConfig,
)
from spark_examples_tpu.ingest.source import ArraySource
from spark_examples_tpu.pipelines.jobs import pcoa_job, variants_pca_job
from spark_examples_tpu.pipelines.project import pcoa_project_job
from spark_examples_tpu.serve import (
    CircuitBreaker,
    DeadlineExceeded,
    FleetFormatError,
    FleetManifest,
    PanelPool,
    PanelUnavailable,
    ProjectionEngine,
    ProjectionServer,
    ServerOverloaded,
    UnknownRoute,
    build_fleet,
    run_fleet_loadgen,
    run_hedged_loadgen,
)
from spark_examples_tpu.store import quarantine as qledger
from tests.conftest import random_genotypes

BV = 128  # staging/fit block width for every test panel
N, V = 12, 256
PANEL_BYTES = N * V  # dense int8 staged bytes per test panel

INTERACTIVE, BATCH = PRIORITY_CLASSES


@pytest.fixture(autouse=True)
def _fresh_telemetry():
    telemetry.reset()
    yield
    telemetry.reset()
    telemetry.configure(dir=None)


@pytest.fixture(scope="module")
def fx(tmp_path_factory):
    """Three fitted (model, store-backed panel) routes — ibs PCoA,
    shared-alt PCA, jaccard PCoA — plus their offline ground truths."""
    from spark_examples_tpu.store.writer import compact

    base = tmp_path_factory.mktemp("fleet_fixture")
    rng = np.random.default_rng(42)
    routes = {}
    for i, (name, kind, metric) in enumerate([
        ("r-ibs", "pcoa", "ibs"),
        ("r-pca", "pca", None),
        ("r-jac", "pcoa", "jaccard"),
    ]):
        g = random_genotypes(rng, n=N, v=V, missing_rate=0.1)
        store = str(base / f"store_{i}")
        compact(store, ArraySource(g), chunk_variants=64)
        model = str(base / f"model_{i}.npz")
        job = JobConfig(
            ingest=IngestConfig(block_variants=BV),
            compute=ComputeConfig(metric=metric, num_pc=3),
            model_path=model,
        )
        (pcoa_job if kind == "pcoa" else variants_pca_job)(
            job, source=ArraySource(g))
        routes[name] = SimpleNamespace(
            name=name, genotypes=g, store=store, model=model, job=job)
    return SimpleNamespace(base=base, routes=routes)


def _manifest_doc(fx, **top) -> dict:
    return {
        "routes": [
            {"name": r.name, "model": r.model,
             "source": f"store:{r.store}"}
            for r in fx.routes.values()
        ],
        **top,
    }


def _build(fx, budget_mb=1.0, cfg=None, readahead=0, **manifest_top):
    manifest = FleetManifest.parse(
        _manifest_doc(fx, budget_mb=budget_mb, **manifest_top))
    return build_fleet(
        manifest, cfg or ServeConfig(),
        ingest_defaults=IngestConfig(block_variants=BV,
                                     readahead_chunks=readahead),
    )


def _offline(route, query) -> np.ndarray:
    """The offline single-query `project` path — the serving
    contract's ground truth."""
    return pcoa_project_job(
        route.job.replace(model_path=None), model_path=route.model,
        source_new=ArraySource(
            query[None, :] if query.ndim == 1 else query),
        source_ref=ArraySource(route.genotypes),
    ).coords


# ----------------------------------------------------------- manifest


def test_manifest_validation_names_the_problem(tmp_path):
    with pytest.raises(FleetFormatError, match="routes"):
        FleetManifest.parse({"routes": []})
    with pytest.raises(FleetFormatError, match="'model'"):
        FleetManifest.parse(
            {"routes": [{"name": "a", "source": "synthetic"}]})
    with pytest.raises(FleetFormatError, match="duplicate route"):
        FleetManifest.parse({"routes": [
            {"name": "a", "model": "m.npz", "source": "synthetic"},
            {"name": "a", "model": "m2.npz", "source": "synthetic"},
        ]})
    with pytest.raises(FleetFormatError, match="unknown field"):
        FleetManifest.parse({"routes": [
            {"name": "a", "model": "m.npz", "source": "synthetic",
             "modle": "typo"},
        ]})
    with pytest.raises(FleetFormatError, match="unknown top-level"):
        FleetManifest.parse(_manifest_doc_empty(), )
    p = tmp_path / "broken.json"
    p.write_text("{not json")
    with pytest.raises(FleetFormatError, match="not readable JSON"):
        FleetManifest.load(str(p))
    # Scalar fields are type-checked at parse: a string budget (or a
    # bool, or a sub-1 max_batch) is a named FleetFormatError, never a
    # TypeError from deep inside pool construction.
    good_routes = [{"name": "a", "model": "m", "source": "s"}]
    with pytest.raises(FleetFormatError, match="budget_mb"):
        FleetManifest.parse({"routes": good_routes, "budget_mb": "256"})
    with pytest.raises(FleetFormatError, match="max_batch"):
        FleetManifest.parse({"routes": good_routes, "max_batch": True})
    with pytest.raises(FleetFormatError, match="block_variants"):
        FleetManifest.parse({"routes": [
            {"name": "a", "model": "m", "source": "s",
             "block_variants": "4096"}]})


def _manifest_doc_empty():
    return {"routes": [{"name": "a", "model": "m", "source": "s"}],
            "budget_gb": 1}


# ------------------------------------------------- bit-identity (tier-1)


def test_three_routes_bit_identical_to_single_model_and_offline(fx):
    """Every route's served coordinates equal BOTH its own single-model
    ProjectionServer's and the offline `project` CLI's, bit for bit."""
    fleet = _build(fx, budget_mb=1.0).start()
    rng = np.random.default_rng(7)
    try:
        for route in fx.routes.values():
            q = random_genotypes(rng, n=1, v=V, missing_rate=0.1)[0]
            offline = _offline(route, q)
            got = fleet.project(route.name, q, timeout=60)
            np.testing.assert_array_equal(got, offline)
            engine = ProjectionEngine(
                route.model, ArraySource(route.genotypes),
                block_variants=BV, max_batch=fleet.max_batch)
            with ProjectionServer(engine, cache_entries=0) as single:
                single_coords = single.project(q, timeout=60)
            np.testing.assert_array_equal(got, single_coords)
        assert fleet.drain(timeout=60)
    finally:
        fleet.close()


def test_lru_eviction_restage_stays_bit_identical(fx):
    """Budget of ONE panel: round-robin traffic churns the pool
    (evictions + re-stages counted) and every answer — including the
    first after a route's panel was just evicted and re-staged — stays
    bit-identical to the offline path. The pool never exceeds budget."""
    budget = int(PANEL_BYTES * 1.5)  # fits exactly one staged panel
    fleet = _build(fx, budget_mb=budget / 1e6,
                   cfg=ServeConfig(cache_entries=0)).start()
    rng = np.random.default_rng(11)
    try:
        for sweep in range(3):
            for route in fx.routes.values():
                q = random_genotypes(rng, n=1, v=V, missing_rate=0.1)[0]
                got = fleet.project(route.name, q, timeout=60)
                np.testing.assert_array_equal(got, _offline(route, q))
                assert fleet.pool.resident_bytes() <= budget
                assert fleet.pool.resident_routes() == [route.name]
        assert telemetry.counter_value("fleet.evictions") >= 6
        assert telemetry.counter_value("fleet.restage_total") >= 6
        # The store stayed clean through the churn: re-stages verified
        # every chunk and nothing quarantined.
        for route in fx.routes.values():
            assert qledger.load(route.store) == []
        assert fleet.drain(timeout=60)
    finally:
        fleet.close()


def test_acceptance_multi_tenant_mix(fx):
    """THE ISSUE-15 acceptance smoke: 3 routes under a 2-panel budget
    driven by the multi-tenant mix — all traffic served, pool under
    budget with evictions observed, interactive p99 under batch p99,
    bit-identity spot-checked after the storm, no quarantine."""
    budget = int(PANEL_BYTES * 2.5)  # fits two of the three panels
    fleet = _build(fx, budget_mb=budget / 1e6,
                   cfg=ServeConfig(cache_entries=0)).start()
    rng = np.random.default_rng(13)
    pools = {
        name: random_genotypes(rng, n=24, v=V, missing_rate=0.1)
        for name in fx.routes
    }
    mix = []
    for name in fx.routes:
        mix.append((name, INTERACTIVE, 1))
        mix.append((name, BATCH, 2))
    try:
        report = run_fleet_loadgen(fleet, pools, mix,
                                   requests_per_client=8)
        assert report["errors"] == 0 and report["shed"] == 0
        assert report["completed"] == 9 * 8
        assert report["per_class"][INTERACTIVE]["p99_s"] > 0
        assert (report["per_class"][INTERACTIVE]["p99_s"]
                <= report["per_class"][BATCH]["p99_s"])
        assert fleet.pool.resident_bytes() <= budget
        assert telemetry.counter_value("fleet.evictions") > 0
        assert telemetry.counter_value("fleet.restage_total") > 0
        for route in fx.routes.values():
            q = pools[route.name][0]
            np.testing.assert_array_equal(
                fleet.project(route.name, q, timeout=60),
                _offline(route, q))
            assert qledger.load(route.store) == []
        assert fleet.drain(timeout=60)
    finally:
        fleet.close()


# ------------------------------------------------------------ priority


def test_interactive_preempts_queued_batch(fx):
    """With the worker stalled, batch requests queued FIRST are
    overtaken by a later interactive request (completion order pinned
    via done-callbacks; serve.priority.preemptions counts it)."""
    fleet = _build(fx, cfg=ServeConfig(cache_entries=0,
                                       max_linger_ms=0.0)).start()
    rng = np.random.default_rng(17)
    route = next(iter(fx.routes))
    order: list[str] = []

    def tag(name):
        def cb(_fut):
            order.append(name)
        return cb

    try:
        qs = random_genotypes(rng, n=4, v=V, missing_rate=0.1)
        with faults.armed(["serve.request:delay:delay=0.25:max=1"]):
            stalled = fleet.submit(route, qs[0], priority=BATCH)
            stalled.add_done_callback(tag("b0"))
            time.sleep(0.05)  # the worker picks b0 up and stalls
            b1 = fleet.submit(route, qs[1], priority=BATCH)
            b1.add_done_callback(tag("b1"))
            b2 = fleet.submit(route, qs[2], priority=BATCH)
            b2.add_done_callback(tag("b2"))
            i0 = fleet.submit(route, qs[3], priority=INTERACTIVE)
            i0.add_done_callback(tag("i0"))
            for f in (stalled, b1, b2, i0):
                f.result(timeout=60)
        assert order.index("i0") < order.index("b1")
        assert order.index("i0") < order.index("b2")
        assert telemetry.counter_value("serve.priority.preemptions") >= 1
        assert fleet.drain(timeout=60)
    finally:
        fleet.close()


def test_per_class_shed_thresholds(fx):
    """The batch queue sheds at its own bound while interactive keeps
    admitting — per-class counters prove which class was protected."""
    fleet = _build(fx, cfg=ServeConfig(
        cache_entries=0, max_linger_ms=0.0,
        queue_interactive=8, queue_batch=2)).start()
    rng = np.random.default_rng(19)
    route = next(iter(fx.routes))
    qs = random_genotypes(rng, n=12, v=V, missing_rate=0.1)
    futs, shed_batch = [], 0
    try:
        with faults.armed(["serve.request:delay:delay=0.1:max=1"]):
            futs.append(fleet.submit(route, qs[0], priority=BATCH))
            time.sleep(0.05)  # worker stalled on the first request
            for q in qs[1:8]:
                try:
                    futs.append(fleet.submit(route, q, priority=BATCH))
                except ServerOverloaded:
                    shed_batch += 1
            assert shed_batch > 0
            # The protected class still admits past batch's shedding.
            futs.append(fleet.submit(route, qs[8],
                                     priority=INTERACTIVE))
            for f in futs:
                f.result(timeout=60)
        assert telemetry.counter_value(
            "serve.priority.shed_batch") == shed_batch
        assert telemetry.counter_value(
            "serve.priority.shed_interactive") == 0
        assert fleet.drain(timeout=60)
    finally:
        fleet.close()


def test_per_class_default_deadlines(fx):
    """ServeConfig's per-class deadlines apply by class: the batch
    default expires a queued batch request while the interactive one
    (no deadline) survives the same stall."""
    fleet = _build(fx, cfg=ServeConfig(
        cache_entries=0, max_linger_ms=0.0,
        deadline_batch_ms=60.0)).start()
    rng = np.random.default_rng(23)
    route = next(iter(fx.routes))
    qs = random_genotypes(rng, n=3, v=V, missing_rate=0.1)
    try:
        with faults.armed(["serve.request:delay:delay=0.25:max=1"]):
            stalled = fleet.submit(route, qs[0], priority=INTERACTIVE)
            time.sleep(0.05)
            doomed = fleet.submit(route, qs[1], priority=BATCH)
            safe = fleet.submit(route, qs[2], priority=INTERACTIVE)
            assert stalled.result(timeout=60).shape == (1, 3)
            assert safe.result(timeout=60).shape == (1, 3)
            with pytest.raises(DeadlineExceeded):
                doomed.result(timeout=60)
        assert telemetry.counter_value("serve.deadline_expired") == 1
        assert fleet.drain(timeout=60)
    finally:
        fleet.close()


def test_unknown_route_and_bad_priority(fx):
    fleet = _build(fx).start()
    try:
        q = np.zeros(V, np.int8)
        with pytest.raises(UnknownRoute, match="r-ibs"):
            fleet.submit("nope", q)
        with pytest.raises(ValueError, match="priority"):
            fleet.submit("r-ibs", q, priority="urgent")
        with pytest.raises(ValueError, match="dosage vector"):
            fleet.submit("r-ibs", np.zeros(7, np.int8))
    finally:
        fleet.close()


# ------------------------------------------- fleet.stage chaos + breaker


def test_fleet_stage_fault_feeds_breaker_then_recovers(fx):
    """Injected fleet.stage io_errors fail exactly the waiting requests
    (explicitly), feed the route's breaker to open (later requests fail
    fast with PanelUnavailable, health degrades), and the half-open
    probe re-stages bit-identically once the fault clears."""
    fleet = _build(fx, cfg=ServeConfig(cache_entries=0)).start()
    rng = np.random.default_rng(29)
    route = fx.routes["r-ibs"]
    now = [0.0]  # injected breaker clock: the reset window advances
    # only when the test says so, not with wall time
    fleet.routes[route.name].breaker = CircuitBreaker(
        trip_after=2, reset_s=10.0, clock=lambda: now[0])
    q = random_genotypes(rng, n=1, v=V, missing_rate=0.1)[0]
    try:
        with faults.armed(["fleet.stage:io_error:max=0"]) as inj:
            for _ in range(2):
                with pytest.raises(faults.InjectedFault):
                    fleet.project(route.name, q, timeout=60)
            assert inj.fire_count("fleet.stage") == 2
            # Breaker tripped: the store is no longer touched.
            with pytest.raises(PanelUnavailable):
                fleet.project(route.name, q, timeout=60)
            assert inj.fire_count("fleet.stage") == 2
            assert fleet.health == "degraded"
        # Disarmed, but r-ibs's breaker is still open (the injected
        # clock has not reached the reset window): it keeps failing
        # fast while OTHER routes serve right through the incident.
        with pytest.raises(PanelUnavailable):
            fleet.project(route.name, q, timeout=60)
        other = fx.routes["r-pca"]
        np.testing.assert_array_equal(
            fleet.project(other.name, q, timeout=60),
            _offline(other, q))
        assert fleet.health == "degraded"
        now[0] = 10.1  # reset window -> half-open probe
        np.testing.assert_array_equal(
            fleet.project(route.name, q, timeout=60),
            _offline(route, q))
        assert fleet.routes[route.name].breaker.state == "closed"
        assert fleet.health == "healthy"
        assert fleet.drain(timeout=60)
    finally:
        fleet.close()


def test_fleet_stage_delay_is_absorbed(fx):
    """A slow cold tier (fleet.stage delay) costs latency, never
    correctness."""
    fleet = _build(fx, cfg=ServeConfig(cache_entries=0)).start()
    rng = np.random.default_rng(31)
    route = fx.routes["r-jac"]
    q = random_genotypes(rng, n=1, v=V, missing_rate=0.1)[0]
    try:
        with faults.armed(["fleet.stage:delay:delay=0.05:max=1"]):
            np.testing.assert_array_equal(
                fleet.project(route.name, q, timeout=60),
                _offline(route, q))
        assert fleet.drain(timeout=60)
    finally:
        fleet.close()


# ----------------------------------------- result-cache lifecycle (fix)


def test_route_unload_evicts_cache_namespace_bytes_flat(fx):
    """The lifecycle satellite: a load/serve/unload loop leaves the
    shared result cache's byte accounting exactly where it started —
    an unloaded route's namespace is evicted whole, not stranded in
    the LRU."""
    from spark_examples_tpu.serve.fleet import RouteSpec, build_route

    fleet = _build(fx, cfg=ServeConfig(cache_entries=64)).start()
    rng = np.random.default_rng(37)
    extra = fx.routes["r-jac"]
    spec = RouteSpec(name="tenant-x", model=extra.model,
                     source=f"store:{extra.store}")
    try:
        fleet.unload_route("r-jac")  # keep only two permanent routes
        q0 = random_genotypes(rng, n=1, v=V, missing_rate=0.1)[0]
        fleet.project("r-ibs", q0, timeout=60)  # a resident entry
        baseline = fleet._cache.stats()
        assert baseline["bytes"] > 0
        for cycle in range(3):
            fleet.add_route(build_route(
                spec, IngestConfig(block_variants=BV,
                                   readahead_chunks=0), BV))
            for k in range(4):
                q = random_genotypes(rng, n=1, v=V,
                                     missing_rate=0.1)[0]
                fleet.project("tenant-x", q, timeout=60)
            grown = fleet._cache.stats()
            assert grown["bytes"] > baseline["bytes"]
            assert fleet.unload_route("tenant-x")
            after = fleet._cache.stats()
            assert after == baseline, f"cycle {cycle}: cache leaked"
        assert telemetry.counter_value(
            "fleet.cache_namespace_evictions") == 3 * 4
        # The permanent route's entry survived every eviction cycle.
        before_hits = telemetry.counter_value("serve.cache_hits")
        fleet.project("r-ibs", q0, timeout=60)
        assert telemetry.counter_value("serve.cache_hits") \
            == before_hits + 1
    finally:
        fleet.close()


# ------------------------------------------------------------- hedging


def test_hedging_cuts_tail_on_delay_injected_replica(fx):
    """Two replicas over the SAME stores (the shared cold tier); the
    primary is delay-injected (a long linger holds every batch). The
    hedged run's p99 lands well under the unhedged run's, hedges win,
    and nothing errors — first answer wins, the loser is cancelled."""
    slow_cfg = ServeConfig(cache_entries=0, max_linger_ms=120.0)
    fast_cfg = ServeConfig(cache_entries=0, max_linger_ms=0.0)
    slow = _build(fx, cfg=slow_cfg).start()
    fast = _build(fx, cfg=fast_cfg).start()
    rng = np.random.default_rng(41)
    pool = random_genotypes(rng, n=32, v=V, missing_rate=0.1)
    route = "r-ibs"
    try:
        unhedged = run_hedged_loadgen(
            [slow, slow], pool, clients=2, requests_per_client=6,
            route=route, hedge_floor_s=10.0)  # floor past every
        # request: the hedge never fires — the no-hedge baseline
        # through the same code path.
        assert unhedged["hedge_launched"] == 0
        assert unhedged["errors"] == 0
        hedged = run_hedged_loadgen(
            [slow, fast], pool, clients=2, requests_per_client=6,
            route=route, hedge_floor_s=0.02)
        assert hedged["errors"] == 0
        assert hedged["completed"] == 12
        assert hedged["hedge_launched"] > 0
        assert hedged["hedge_wins"] > 0
        assert hedged["hedge_win_frac"] > 0.5
        assert hedged["p99_s"] < unhedged["p99_s"]
        assert telemetry.counter_value("fleet.hedge_wins") \
            == hedged["hedge_wins"]
    finally:
        slow.close()
        fast.close()


# ------------------------------------------------------- pool semantics


def test_panel_pool_unit_semantics():
    """PanelPool in isolation: LRU order, budget eviction, restage
    accounting, oversize tolerance (warn, serve anyway), and remove()
    forgetting the staged-before history."""
    pool = PanelPool(1000)

    def stage(nbytes):
        return lambda: ([("blocks", None)], 64, nbytes)

    pool.acquire("a", stage(400))
    pool.acquire("b", stage(400))
    assert pool.resident_routes() == ["a", "b"]
    pool.acquire("a", lambda: (_ for _ in ()).throw(
        AssertionError("hit must not re-stage")))
    assert pool.resident_routes() == ["b", "a"]  # LRU refreshed
    pool.acquire("c", stage(400))  # 1200 > 1000: evicts LRU ("b")
    assert pool.resident_routes() == ["a", "c"]
    assert telemetry.counter_value("fleet.evictions") == 1
    pool.acquire("b", stage(400))  # b again: restage counted
    assert telemetry.counter_value("fleet.restage_total") == 1
    with pytest.warns(RuntimeWarning, match="exceed the pool budget"):
        pool.acquire("huge", stage(5000))
    assert pool.is_staged("huge")  # served unevictable, loudly
    pool.remove("huge")
    with pytest.warns(RuntimeWarning, match="exceed the pool budget"):
        pool.acquire("huge", stage(5000))
    # remove() forgot the history: that was a first stage, not a
    # restage.
    assert telemetry.counter_value("fleet.restage_total") == 1


def test_pool_stage_failure_leaves_pool_consistent():
    pool = PanelPool(1000)
    with pytest.raises(RuntimeError, match="boom"):
        pool.acquire("a", lambda: (_ for _ in ()).throw(
            RuntimeError("boom")))
    assert not pool.is_staged("a")
    assert pool.resident_bytes() == 0


# ------------------------------------------------------------ HTTP front


def test_fleet_http_front(fx):
    from spark_examples_tpu.serve.http import start_fleet_http_server

    fleet = _build(fx).start()
    http = start_fleet_http_server(fleet, port=0)
    base = f"http://127.0.0.1:{http.port}"
    rng = np.random.default_rng(43)
    q = random_genotypes(rng, n=1, v=V, missing_rate=0.1)[0]
    try:
        req = urllib.request.Request(
            f"{base}/project",
            data=json.dumps({
                "route": "r-ibs", "priority": BATCH,
                "genotypes": [int(x) for x in q],
            }).encode(),
            method="POST",
        )
        with urllib.request.urlopen(req, timeout=60) as resp:
            out = json.loads(resp.read())
        want = _offline(fx.routes["r-ibs"], q).astype(np.float32)
        np.testing.assert_array_equal(
            np.asarray(out["coords"], np.float32), want)
        # Path-addressed form: POST /project/<route>.
        req2 = urllib.request.Request(
            f"{base}/project/r-pca",
            data=json.dumps(
                {"genotypes": [int(x) for x in q]}).encode(),
            method="POST",
        )
        with urllib.request.urlopen(req2, timeout=60) as resp:
            out2 = json.loads(resp.read())
        np.testing.assert_array_equal(
            np.asarray(out2["coords"], np.float32),
            _offline(fx.routes["r-pca"], q).astype(np.float32))
        with urllib.request.urlopen(f"{base}/healthz", timeout=30) as r:
            health = json.loads(r.read())
        assert health["status"] == "healthy"
        assert set(health["routes"]) == set(fx.routes)
        with urllib.request.urlopen(f"{base}/routes", timeout=30) as r:
            routes = json.loads(r.read())
        assert routes["r-ibs"]["completed"] >= 1
        assert routes["r-ibs"]["staged"] is True
        with urllib.request.urlopen(f"{base}/stats", timeout=30) as r:
            stats = json.loads(r.read())
        assert stats["pool"]["resident_bytes"] > 0
        assert stats["result_cache"]["entries"] >= 1
        # The per-route autoscale series land on the Prometheus plane.
        with urllib.request.urlopen(f"{base}/metrics", timeout=30) as r:
            prom = r.read().decode()
        assert "fleet_routes" in prom
        assert "fleet_pool_bytes" in prom
        assert "fleet_route_r_ibs_queue_depth" in prom
        assert "fleet_route_r_ibs_p99_s" in prom
        assert "serve_priority_depth_interactive" in prom
        # Error surface: unknown route 404, missing route 400.
        bad = urllib.request.Request(
            f"{base}/project",
            data=json.dumps({"route": "nope",
                             "genotypes": [0] * V}).encode(),
            method="POST")
        with pytest.raises(urllib.error.HTTPError) as err:
            urllib.request.urlopen(bad, timeout=30)
        assert err.value.code == 404
        bad2 = urllib.request.Request(
            f"{base}/project",
            data=json.dumps({"genotypes": [0] * V}).encode(),
            method="POST")
        with pytest.raises(urllib.error.HTTPError) as err:
            urllib.request.urlopen(bad2, timeout=30)
        assert err.value.code == 400
    finally:
        http.shutdown()
        fleet.close()


def test_http_front_trace_surfaces(fx, tmp_path):
    """ISSUE 17: the fleet HTTP front echoes the client's X-Trace-Id
    (or mints one), stamps X-Run-Id and a Server-Timing phase
    breakdown on every response, and serves the slowest-K exemplar
    ring at GET /debug/requests."""
    from spark_examples_tpu.serve.http import start_fleet_http_server

    telemetry.configure(dir=str(tmp_path / "tel"), trace_events=True)
    sample0 = telemetry.trace_sample()
    telemetry.set_trace_sample(1.0)
    fleet = _build(fx).start()
    http = start_fleet_http_server(fleet, port=0)
    base = f"http://127.0.0.1:{http.port}"
    rng = np.random.default_rng(47)
    q = random_genotypes(rng, n=1, v=V, missing_rate=0.1)[0]
    body = json.dumps({"genotypes": [int(x) for x in q]}).encode()
    try:
        req = urllib.request.Request(
            f"{base}/project/r-ibs", data=body, method="POST")
        req.add_header("X-Trace-Id", "client-chosen-trace-01")
        with urllib.request.urlopen(req, timeout=60) as resp:
            assert resp.headers["X-Trace-Id"] == "client-chosen-trace-01"
            assert resp.headers["X-Run-Id"] == telemetry.run_id()
            timing = resp.headers["Server-Timing"]
        # The phase breakdown names at least the total and the compute
        # leg (cache/queue appear when those phases happened).
        assert "total;dur=" in timing
        assert "compute;dur=" in timing
        # No header -> the server mints a 16-hex id.
        req2 = urllib.request.Request(
            f"{base}/project/r-ibs", data=body, method="POST")
        with urllib.request.urlopen(req2, timeout=60) as resp:
            minted = resp.headers["X-Trace-Id"]
        assert len(minted) == 16 and int(minted, 16) >= 0
        with urllib.request.urlopen(f"{base}/debug/requests",
                                    timeout=30) as r:
            dbg = json.loads(r.read())
        assert dbg["trace_sample"] == 1.0
        by_tid = {e["trace_id"]: e for e in dbg["exemplars"]}
        assert "client-chosen-trace-01" in by_tid
        ex = by_tid["client-chosen-trace-01"]
        assert ex["route"] == "r-ibs" and ex["status"] == 200
        assert "total" in ex["phases"] and "compute" in ex["phases"]
        # The sampled request also left a trace.request span behind.
        assert telemetry.metrics_snapshot()[
            "histograms"]["trace.request"]["count"] >= 2
    finally:
        telemetry.set_trace_sample(sample0)
        http.shutdown()
        fleet.close()


def test_hedged_legs_share_one_trace_id(fx, tmp_path):
    """Both legs of a hedged request carry ONE trace_id with distinct
    span ids — the waterfall key that joins the client's trace.hedge
    attribution event to the server-side queue/compute spans."""
    telemetry.configure(dir=str(tmp_path / "tel"), trace_events=True)
    sample0 = telemetry.trace_sample()
    telemetry.set_trace_sample(1.0)
    slow = _build(fx, cfg=ServeConfig(cache_entries=0,
                                      max_linger_ms=120.0)).start()
    fast = _build(fx, cfg=ServeConfig(cache_entries=0,
                                      max_linger_ms=0.0)).start()
    rng = np.random.default_rng(48)
    pool = random_genotypes(rng, n=16, v=V, missing_rate=0.1)
    try:
        report = run_hedged_loadgen(
            [slow, fast], pool, clients=2, requests_per_client=6,
            route="r-ibs", hedge_floor_s=0.02)
        assert report["errors"] == 0 and report["hedge_launched"] > 0
        evs = telemetry.recent_events()
        hedge_tids = {e["args"]["trace_id"] for e in evs
                      if e["name"] == "trace.hedge"}
        assert hedge_tids  # every attribution event carries the key
        span_ids = {}  # trace_id -> span ids seen on server spans
        for e in evs:
            if e["name"] in ("trace.queue", "trace.compute"):
                span_ids.setdefault(
                    e["args"]["trace_id"], set()).add(
                        e["args"]["span_id"])
        # Client-side hedge events and server-side spans join on the
        # same trace ids.
        assert hedge_tids & set(span_ids)
        # Two legs submitted under ONE trace id get distinct span ids
        # on their server spans (driven directly, like _leg_trace).
        tid = telemetry.new_trace_id()
        legs = []
        for _ in range(2):
            tr = {"trace_id": tid, "span_id": telemetry.new_span_id(),
                  "sampled": True, "phases": {}}
            legs.append((tr, fast.submit(
                "r-ibs", pool[0], priority=INTERACTIVE, trace=tr)))
        for _tr, fut in legs:
            fut.result(timeout=60.0)
        spans = [e for e in telemetry.recent_events()
                 if e["name"] == "trace.compute"
                 and e["args"]["trace_id"] == tid]
        assert {e["args"]["span_id"] for e in spans} == \
            {tr["span_id"] for tr, _f in legs}
        assert len({tr["span_id"] for tr, _f in legs}) == 2
        # Satellite: client-side error records carry the run id (none
        # fired here — the contract is on the recorder itself).
        assert report["error_records"] == []
    finally:
        telemetry.set_trace_sample(sample0)
        slow.close()
        fast.close()


# ------------------------------------------------------------------ CLI


def test_serve_fleet_cli_loadgen(fx, tmp_path, capsys):
    """`serve --fleet manifest.json --loadgen N` end to end: the
    multi-tenant report (interactive + batch clients per route) prints
    as JSON with per-class percentiles and fleet stats."""
    from spark_examples_tpu.cli.main import main

    manifest_path = tmp_path / "fleet.json"
    manifest_path.write_text(json.dumps(
        _manifest_doc(fx, budget_mb=1.0)))
    rc = main([
        "serve", "--fleet", str(manifest_path),
        "--source", "synthetic", "--n-samples", "4",
        "--block-variants", str(BV), "--readahead-chunks", "0",
        "--max-batch", "4", "--max-linger-ms", "1",
        "--loadgen", "1", "--loadgen-requests", "4",
    ])
    assert rc == 0
    report = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    # 3 routes x 2 classes x 1 client x 4 requests
    assert report["completed"] == 24 and report["errors"] == 0
    assert set(report["per_class"]) == set(PRIORITY_CLASSES)
    assert set(report["per_route"]) == set(fx.routes)
    assert report["stats"]["pool"]["resident_bytes"] > 0


def test_serve_cli_fleet_and_model_are_exclusive(fx, tmp_path):
    from spark_examples_tpu.cli.main import main

    manifest_path = tmp_path / "fleet.json"
    manifest_path.write_text(json.dumps(_manifest_doc(fx)))
    with pytest.raises(SystemExit):
        main(["serve", "--fleet", str(manifest_path),
              "--model", "m.npz"])
    with pytest.raises(SystemExit):
        main(["serve"])  # neither --model nor --fleet

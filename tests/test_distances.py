import numpy as np
import pytest

from spark_examples_tpu.ops import distances, gram
from spark_examples_tpu.utils import oracle
from tests.conftest import random_genotypes


def _finalized(genotypes, metric):
    acc = gram.init(genotypes.shape[0], metric)
    acc = gram.update(acc, genotypes, metric)
    return distances.finalize(acc, metric)


def test_ibs_distance_matches_naive(genotypes):
    got = np.asarray(_finalized(genotypes, "ibs")["distance"])
    want = oracle.naive_ibs_distance(genotypes)
    np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-7)
    # symmetric, zero diagonal
    np.testing.assert_allclose(got, got.T, atol=1e-7)
    np.testing.assert_allclose(np.diag(got), 0.0, atol=1e-7)


def test_ibs_zero_overlap_pair(rng):
    g = random_genotypes(rng, n=6, v=30, missing_rate=0.0)
    g[0, :15] = -1
    g[1, 15:] = -1  # samples 0 and 1 share no valid variant
    out = np.asarray(_finalized(g, "ibs")["distance"])
    assert out[0, 1] == 0.0  # pinned convention (see distances.finalize)


def test_euclidean_matches_naive(genotypes):
    got = np.asarray(_finalized(genotypes, "euclidean")["distance"])
    want = np.sqrt(oracle.naive_pairwise(genotypes)["e2"])
    np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-6)


@pytest.mark.parametrize("shape", [(17, 33), (64, 128), (130, 257)])
def test_braycurtis_matches_naive(rng, shape):
    x = rng.gamma(2.0, 10.0, size=shape) * (rng.random(shape) > 0.3)
    got = np.asarray(distances.braycurtis(x, row_tile=32, feat_tile=32))
    want = oracle.naive_braycurtis(x)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_braycurtis_matches_scipy(rng):
    x = rng.gamma(2.0, 10.0, size=(25, 71))
    got = np.asarray(distances.braycurtis(x, row_tile=16, feat_tile=16))
    want = oracle.cpu_braycurtis(x)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_manhattan_padding_is_neutral(rng):
    x = rng.random((19, 23))
    got = np.asarray(distances.pairwise_manhattan(x, row_tile=8, feat_tile=8))
    want = np.abs(x[:, None, :] - x[None, :, :]).sum(-1)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_similarity_to_distance_gower(rng):
    # For a Gram matrix G = X X^T the Gower distance is euclidean distance.
    x = rng.random((12, 5))
    g = x @ x.T
    got = np.asarray(distances.similarity_to_distance(g))
    want = np.sqrt(((x[:, None, :] - x[None, :, :]) ** 2).sum(-1))
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)

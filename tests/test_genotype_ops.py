"""Parity of the matmul reformulation against the naive oracle.

This is SURVEY.md §7 hard-part #1: the pair-count -> matmul rewrite must
reproduce the reference's reduceByKey counting semantics (including
missing-genotype handling) exactly. The naive oracle defines those
semantics; every gram piece must match it to the integer.
"""

import numpy as np
import pytest

from spark_examples_tpu.ops import genotype, gram
from spark_examples_tpu.utils import oracle
from tests.conftest import random_genotypes

PIECES = ("m", "s", "d1", "ibs2", "dot", "e2")


@pytest.mark.parametrize("missing_rate", [0.0, 0.15, 0.6])
def test_gram_pieces_match_naive(rng, missing_rate):
    g = random_genotypes(rng, n=23, v=157, missing_rate=missing_rate)
    got = {k: np.asarray(v) for k, v in genotype.gram_pieces(g).items()}
    want = oracle.naive_pairwise(g)
    for k in PIECES:
        np.testing.assert_array_equal(got[k], want[k], err_msg=f"piece {k}")


def test_gram_pieces_all_missing_column(rng):
    g = random_genotypes(rng, n=11, v=40, missing_rate=0.1)
    g[:, 7] = -1  # fully missing variant must contribute nothing
    got = {k: np.asarray(v) for k, v in genotype.gram_pieces(g).items()}
    want = oracle.naive_pairwise(g)
    for k in PIECES:
        np.testing.assert_array_equal(got[k], want[k])


def test_blocked_accumulation_equals_single_shot(genotypes):
    """Streaming over variant blocks == one-shot (associativity)."""
    n, v = genotypes.shape
    acc = gram.init(n, "ibs")
    for start in range(0, v, 64):
        acc = gram.update(acc, genotypes[:, start : start + 64], "ibs")
    stats = gram.combine(acc, "ibs")
    whole = genotype.gram_pieces(genotypes)
    np.testing.assert_array_equal(np.asarray(stats["d1"]), np.asarray(whole["d1"]))
    np.testing.assert_array_equal(np.asarray(stats["m"]), np.asarray(whole["m"]))


def test_int32_accumulators_exact_past_f32_mantissa():
    """North-star safety (40M variants): counts keep accumulating exactly
    past 2^24, where f32 accumulators would round every odd increment
    (f32 spacing at 2^24 is 2). int32 is exact to 2^31."""
    import jax.numpy as jnp

    n = 4
    big = 2**24
    acc = {k: jnp.full((n, n), big, jnp.int32)
           for k in gram.PIECES_FOR_METRIC["ibs"]}
    block = np.zeros((n, 3), np.int8)  # 3 valid hom-ref calls per sample
    acc = gram.update(acc, block, "ibs")
    assert acc["cc"].dtype == jnp.int32
    # 2**24 + 3 is NOT representable in f32; int32 holds it exactly
    np.testing.assert_array_equal(np.asarray(acc["cc"]), big + 3)
    stats = gram.combine(acc, "ibs")
    np.testing.assert_array_equal(np.asarray(stats["m"]), big + 3)


def test_cpu_backend_matches_naive(genotypes):
    got = oracle.cpu_gram_pieces(genotypes)
    want = oracle.naive_pairwise(genotypes)
    for k in PIECES:
        np.testing.assert_allclose(got[k], want[k], err_msg=f"piece {k}")


def test_dot_e2_exact_on_arbitrary_int8(rng):
    """dot/e2 use raw-value operands: exact for count tables up to int8
    max, not just the dosage domain (the other pieces are dosage-defined;
    the naive oracle's raw-value dot/e2 are the contract here). Exercises
    the radix-128 int8 split of the squared operand (values > 11 make
    qr = v^2 overflow int8, so the split path is what's under test)."""
    g = rng.integers(-1, 120, size=(9, 83)).astype(np.int8)
    got = {k: np.asarray(v) for k, v in genotype.gram_pieces(g).items()}
    want = oracle.naive_pairwise(g)
    for k in ("dot", "e2", "m"):
        np.testing.assert_array_equal(got[k], want[k], err_msg=f"piece {k}")


def test_grm_precise_flag_tightens_accuracy(genotypes):
    """impl_for(grm, precise) is reachable and f32 accumulation is at
    least as close to the f64 oracle as the bf16 default."""
    n = genotypes.shape[0]
    want = oracle.naive_grm(genotypes)

    def run(precise):
        impl = gram.impl_for("grm", packed=False, grm_precise=precise)
        acc = impl(gram.init(n, "grm"), genotypes)
        return np.asarray(acc["zz"] / np.maximum(np.asarray(acc["nvar"]), 1.0))

    err_bf16 = np.abs(run(False) - want).max()
    err_f32 = np.abs(run(True) - want).max()
    assert err_f32 <= err_bf16
    np.testing.assert_allclose(run(True), want, rtol=1e-4, atol=1e-4)


def test_grm_matches_naive(genotypes):
    acc = gram.init(genotypes.shape[0], "grm")
    acc = gram.update(acc, genotypes, "grm")
    got = np.asarray(acc["zz"] / np.maximum(np.asarray(acc["nvar"]), 1.0))
    want = oracle.naive_grm(genotypes)
    # bf16 standardized dosages: tolerance, not exactness.
    np.testing.assert_allclose(got, want, rtol=0.05, atol=0.05)


def test_tile_products_match_gram_products(genotypes):
    """tile_products on a (rows, cols) split must reproduce the same
    sub-blocks gram_products computes for the full block — the parity
    contract of the replicated-transport tile2d update."""
    from spark_examples_tpu.ops.genotype import gram_products, tile_products

    products = ("cc", "yc", "t1t1", "t2t2", "qc", "yy")
    full = {k: np.asarray(v) for k, v in
            gram_products(genotypes, products).items()}
    rows, cols = genotypes[:16], genotypes[16:]
    tile = tile_products(rows, cols, products)
    for k in products:
        np.testing.assert_array_equal(
            np.asarray(tile[k]), full[k][:16, 16:], err_msg=k
        )
    # Same slice on both sides == the full product's diagonal block.
    sym = tile_products(rows, rows, products)
    for k in products:
        np.testing.assert_array_equal(
            np.asarray(sym[k]), full[k][:16, :16], err_msg=k
        )


@pytest.mark.parametrize("seed", [101, 202, 303])
def test_metric_parity_fuzz(seed):
    """Randomized-shape parity sweep: every gram metric's full
    accumulate→combine→finalize chain must match the naive CPU oracle
    bit-for-bit (int paths) or to float tolerance (grm), across odd
    shapes, block grids, and missing rates — the pair-count→matmul
    reformulation is the framework's core parity risk (SURVEY.md §7
    hard part 1), so it gets adversarial shapes, not just the fixtures.
    """
    from spark_examples_tpu.ops import distances
    from spark_examples_tpu.utils import oracle

    rng = np.random.default_rng(seed)
    n = int(rng.integers(3, 41))
    v = int(rng.integers(7, 400))
    bv = int(rng.integers(3, v + 1))
    miss = float(rng.uniform(0.0, 0.4))
    g = random_genotypes(rng, n=n, v=v, missing_rate=miss)

    for metric in ("ibs", "ibs2", "shared-alt", "euclidean", "dot", "king"):
        acc = gram.init(n, metric)
        for s in range(0, v, bv):
            acc = gram.update(acc, g[:, s:s + bv], metric)
        got = {k: np.asarray(val)
               for k, val in distances.finalize(acc, metric).items()}
        prods = oracle.cpu_gram_products(
            g, gram.PIECES_FOR_METRIC[metric]
        )
        want = oracle.cpu_finalize(
            gram.combine(
                {k: np.asarray(p, np.int64) for k, p in prods.items()},
                metric,
            ),
            metric,
        )
        for field in ("similarity", "distance"):
            np.testing.assert_allclose(
                got[field], np.asarray(want[field], np.float32),
                rtol=1e-5, atol=1e-5,
                err_msg=f"{metric}/{field} n={n} v={v} bv={bv} miss={miss:.2f}",
            )

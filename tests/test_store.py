"""Content-addressed dataset store (spark_examples_tpu/store): round-trip
bit-identity against direct sources, range queries at chunk boundaries,
deterministic resume, the tiered decode cache's accounting, and the
integrity story — corrupt-chunk quarantine under the ``store.read``
fault site, transient-IO recovery through the retry layer."""

import json
import os
import warnings

import numpy as np
import pytest

from spark_examples_tpu.core import faults, telemetry
from spark_examples_tpu.core.config import ReferenceRange
from spark_examples_tpu.ingest import VcfSource, bitpack, write_vcf
from spark_examples_tpu.ingest.resilient import RetryingSource, RetryPolicy
from spark_examples_tpu.ingest.source import ArraySource
from spark_examples_tpu.ingest.synthetic import SyntheticSource
from spark_examples_tpu.store import (
    StoreCorruptError,
    StoreFormatError,
    compact,
    open_store,
)
from tests.conftest import random_genotypes


def _materialize(source, block_variants, start=0):
    blocks = [b for b, _ in source.blocks(block_variants, start)]
    return np.concatenate(blocks, axis=1) if blocks else None


def _materialize_packed(source, block_variants, start=0):
    cols = []
    for pb, m in source.packed_blocks(block_variants, start):
        cols.append(bitpack.unpack_dosages_np(pb)[:, : m.stop - m.start])
    return np.concatenate(cols, axis=1)


@pytest.fixture
def store_dir(tmp_path, genotypes):
    """A compacted store over the shared 37 x 211 cohort, chunk width 32
    (ragged tail chunk included)."""
    src = ArraySource(genotypes, contig="chr9",
                     positions=np.arange(1000, 1000 + 211, dtype=np.int64))
    d = str(tmp_path / "store")
    compact(d, src, chunk_variants=32)
    return d


def _multi_contig_vcf(tmp_path, rng):
    """One VCF holding chr1 (23 variants) + chr2 (10), the contig-
    boundary shape every grid in the store must respect."""
    g1 = random_genotypes(rng, 7, 23, 0.1)
    g2 = random_genotypes(rng, 7, 10, 0.1)
    p1, p2 = str(tmp_path / "a.vcf"), str(tmp_path / "b.vcf")
    write_vcf(p1, g1, contig="chr1", start_pos=100)
    write_vcf(p2, g2, contig="chr2", start_pos=500)
    header = [l for l in open(p1) if l.startswith("#")]
    records = [l for p in (p1, p2) for l in open(p) if not l.startswith("#")]
    multi = str(tmp_path / "multi.vcf")
    open(multi, "w").writelines(header + records)
    return multi, g1, g2


# ---------------------------------------------------------------------------
# Round-trip bit-identity


def test_roundtrip_bit_identity_synthetic(tmp_path):
    src = SyntheticSource(n_samples=13, n_variants=501, seed=11)
    d = str(tmp_path / "s")
    manifest = compact(d, src, chunk_variants=64)
    assert manifest.n_variants == 501 and len(manifest.chunks) == 8
    st = open_store(d)
    want = _materialize(src, 64)
    # widths below/at/above/misaligned-with the chunk grid
    for bv in (32, 64, 100, 256, 501, 1024):
        np.testing.assert_array_equal(_materialize(st, bv), want)
    for bv in (32, 64, 256, 1024):  # packed transport needs bv % 4 == 0
        np.testing.assert_array_equal(_materialize_packed(st, bv), want)


def test_roundtrip_vcf_multi_contig(tmp_path, rng):
    multi, g1, g2 = _multi_contig_vcf(tmp_path, rng)
    vs = VcfSource(multi)
    d = str(tmp_path / "s")
    compact(d, vs, chunk_variants=8)
    st = open_store(d)
    want = np.concatenate([g1, g2], axis=1)
    np.testing.assert_array_equal(_materialize(st, 16), want)
    # contig labels exact, blocks never span the chr1/chr2 boundary
    metas = [m for _b, m in st.blocks(16)]
    assert [m.contig for m in metas] == ["chr1", "chr1", "chr2"]
    assert [(m.start, m.stop) for m in metas] == [(0, 16), (16, 23), (23, 33)]
    # positions preserved through the catalog
    pos = np.concatenate([m.positions for m in metas])
    np.testing.assert_array_equal(
        pos, np.r_[np.arange(100, 123), np.arange(500, 510)])
    # packed transport flushes at the same boundaries
    np.testing.assert_array_equal(_materialize_packed(st, 16), want)
    assert not st.exact_n_variants  # multi-contig declines the claim
    assert open_store(d).manifest.contig_span("chr2") == (23, 33)


def test_compaction_dedupes_identical_chunks(tmp_path):
    g = np.zeros((5, 96), np.int8)  # 3 identical 32-wide chunks
    d = str(tmp_path / "s")
    manifest = compact(d, ArraySource(g), chunk_variants=32)
    assert len(manifest.chunks) == 3
    assert len({c.digest for c in manifest.chunks}) == 1
    files = os.listdir(os.path.join(d, "chunks"))
    assert len(files) == 1  # content addressing = dedupe for free
    np.testing.assert_array_equal(_materialize(open_store(d), 40), g)


def test_recompaction_heals_wrong_sized_chunk(tmp_path, genotypes):
    src = ArraySource(genotypes)
    d = str(tmp_path / "s")
    manifest = compact(d, src, chunk_variants=64)
    victim = os.path.join(d, manifest.chunks[1].filename())
    with open(victim, "r+b") as f:
        f.truncate(5)
    compact(d, src, chunk_variants=64)  # dedupe must not trust the name
    np.testing.assert_array_equal(_materialize(open_store(d), 64), genotypes)


# ---------------------------------------------------------------------------
# Range queries + resume


def test_range_queries_at_chunk_boundaries(store_dir, genotypes):
    st = open_store(store_dir)
    # spans that start/end exactly ON, just inside, and across the
    # 32-wide chunk grid (and the ragged 211 tail)
    for lo, hi in ((0, 32), (31, 33), (32, 64), (15, 97), (96, 211),
                   (210, 211), (207, 211), (0, 211), (64, 64)):
        np.testing.assert_array_equal(
            st.read_range(lo, hi), genotypes[:, lo:hi])
        rs = st.variant_range(lo, hi)
        assert rs.n_variants == hi - lo
        if hi > lo:
            got = _materialize(rs, 13)  # width misaligned with everything
            np.testing.assert_array_equal(got, genotypes[:, lo:hi])
    with pytest.raises(ValueError, match="out of bounds"):
        st.read_range(0, 212)


def test_position_span_and_restrict(store_dir, genotypes):
    st = open_store(store_dir)
    # positions are 1000..1210; [1032, 1064) covers variants [32, 64)
    assert st.position_span("chr9", 1032, 1064) == (32, 64)
    assert st.position_span("chr9", 0, 999) == (1000 - 1000, 0)
    assert st.position_span("chrX", 0, 10**9) == (0, 0)
    sub = st.restrict([ReferenceRange("chr9", 1031, 1065)])
    np.testing.assert_array_equal(_materialize(sub, 16),
                                  genotypes[:, 31:65])
    # two ranges chain in order, like partitioned file ingest
    both = st.restrict([ReferenceRange("chr9", 1000, 1008),
                        ReferenceRange("chr9", 1100, 1104)])
    np.testing.assert_array_equal(
        _materialize(both, 6),
        np.concatenate([genotypes[:, 0:8], genotypes[:, 100:104]], axis=1))
    # a miss everywhere still answers cohort metadata with zero variants
    empty = st.restrict([ReferenceRange("chrX", 0, 10)])
    assert empty.n_variants == 0 and empty.n_samples == st.n_samples


def test_resume_cursors(store_dir, genotypes):
    st = open_store(store_dir)
    full = list(st.blocks(48))
    cursor = full[2][1].stop
    resumed = list(st.blocks(48, start_variant=cursor))
    assert [m.start for _b, m in resumed] == [m.start for _b, m in full[3:]]
    for (a, _), (b, _) in zip(resumed, full[3:]):
        np.testing.assert_array_equal(a, b)
    # packed transport resumes on the same grid
    pk = list(st.packed_blocks(48, start_variant=cursor))
    assert [m.start for _b, m in pk] == [m.start for _b, m in full[3:]]
    # a range source resumes on LOCAL cursors
    rs = st.variant_range(31, 180)
    rfull = list(rs.blocks(40))
    rres = list(rs.blocks(40, start_variant=rfull[1][1].stop))
    np.testing.assert_array_equal(rres[0][0], rfull[2][0])


def test_store_through_runner_bit_identical(tmp_path, genotypes):
    """The drop-in contract: a pcoa job from --source store:<dir> is
    bit-identical to the same job streaming the source directly."""
    from spark_examples_tpu.core.config import (
        ComputeConfig, IngestConfig, JobConfig,
    )
    from spark_examples_tpu.pipelines.jobs import pcoa_job

    src = SyntheticSource(n_samples=16, n_variants=384, seed=2)
    d = str(tmp_path / "s")
    compact(d, src, chunk_variants=64)
    compute = ComputeConfig(metric="ibs", num_pc=3)
    direct = pcoa_job(JobConfig(
        ingest=IngestConfig(source="synthetic", n_samples=16,
                            n_variants=384, seed=2, block_variants=128),
        compute=compute,
    ))
    # the "store:<dir>" spelling normalizes into source/path
    via_store = pcoa_job(JobConfig(
        ingest=IngestConfig(source=f"store:{d}", block_variants=128),
        compute=compute,
    ))
    np.testing.assert_array_equal(direct.coords, via_store.coords)


def test_cli_ingest_then_store_source(tmp_path, capsys):
    """CLI surface: `ingest` compacts, `pcoa --source store:<dir>`
    consumes, coordinates match the straight-from-VCF run."""
    from spark_examples_tpu.cli.main import main

    rng = np.random.default_rng(8)
    g = rng.integers(0, 3, (12, 200)).astype(np.int8)
    vcf = str(tmp_path / "c.vcf")
    write_vcf(vcf, g, contig="chr3", start_pos=700)
    store = str(tmp_path / "store")
    assert main(["ingest", "--source", "vcf", "--path", vcf,
                 "--chunk-variants", "64", "--output-path", store]) == 0
    assert "content-addressed chunks" in capsys.readouterr().out
    a, b = str(tmp_path / "a.tsv"), str(tmp_path / "b.tsv")
    assert main(["pcoa", "--source", f"store:{store}", "--num-pc", "3",
                 "--block-variants", "64", "--output-path", a]) == 0
    assert main(["pcoa", "--source", "vcf", "--path", vcf, "--num-pc",
                 "3", "--block-variants", "64", "--output-path", b]) == 0
    capsys.readouterr()
    ca = np.loadtxt(a, skiprows=1, usecols=(1, 2, 3))
    cb = np.loadtxt(b, skiprows=1, usecols=(1, 2, 3))
    np.testing.assert_array_equal(ca, cb)


# ---------------------------------------------------------------------------
# Tiered decode cache


def test_decode_cache_accounting(store_dir, genotypes):
    st = open_store(store_dir)  # 7 chunks of <= 32 variants
    _materialize(st, 32)  # one decode per chunk
    s1 = st.cache.stats()
    assert s1["misses"] == 7 and s1["entries"] == 7
    _materialize(st, 32)  # second pass: all hits
    s2 = st.cache.stats()
    assert s2["misses"] == 7 and s2["hits"] >= 7
    assert s2["bytes"] == genotypes.nbytes  # dense decodes resident


def test_decode_cache_bounded_eviction(store_dir, genotypes):
    # room for ~2 decoded chunks (37 x 32 = 1184 B each)
    st = open_store(store_dir, cache_bytes=2500)
    np.testing.assert_array_equal(_materialize(st, 32), genotypes)
    np.testing.assert_array_equal(_materialize(st, 32), genotypes)
    s = st.cache.stats()
    assert s["evictions"] > 0 and s["bytes"] <= 2500
    # capacity 0 disables storage, reads stay correct
    st0 = open_store(store_dir, cache_bytes=0)
    np.testing.assert_array_equal(_materialize(st0, 32), genotypes)
    assert st0.cache.stats()["entries"] == 0


# ---------------------------------------------------------------------------
# Integrity: quarantine + fault-harness recovery


def test_truncated_chunk_quarantined(store_dir):
    before = telemetry.counter_value("store.quarantined")
    with faults.armed(["store.read:truncate:after=2:keep=4"]):
        st = open_store(store_dir)
        with pytest.raises(StoreCorruptError) as e:
            _materialize(st, 32)
    assert e.value.cursor == 64  # third chunk's first variant
    assert "start_variant=64" in str(e.value)
    q = json.load(open(os.path.join(store_dir, "quarantine.json")))
    assert len(q) == 1 and q[0]["start"] == 64
    assert telemetry.counter_value("store.quarantined") == before + 1


def test_bitflip_fails_digest_verification(store_dir):
    st = open_store(store_dir)
    victim = os.path.join(store_dir, st.manifest.chunks[0].filename())
    raw = bytearray(open(victim, "rb").read())
    raw[7] ^= 0x40  # same size, different content
    open(victim, "wb").write(bytes(raw))
    with pytest.raises(StoreCorruptError, match="content address"):
        open_store(store_dir).read_range(0, 8)
    # verify=False skips hashing — but a compressed chunk whose stored
    # bytes no longer inflate still fails LOUDLY through the decode
    # path (garbage can't be silently decoded, unlike the raw codec).
    with pytest.raises(StoreCorruptError):
        open_store(store_dir, verify=False).read_range(0, 8)


def test_bitflip_raw_codec_verify_off_is_fast_and_loose(tmp_path, genotypes):
    """The documented fast-and-loose knob on a RAW-codec store: with
    hashing skipped, a same-size bit flip reads back as (wrong) data —
    the pre-compression behavior, preserved for raw chunks."""
    src = ArraySource(genotypes)
    d = str(tmp_path / "raw")
    manifest = compact(d, src, chunk_variants=32, codec="raw")
    victim = os.path.join(d, manifest.chunks[0].filename())
    raw = bytearray(open(victim, "rb").read())
    raw[7] ^= 0x40
    open(victim, "wb").write(bytes(raw))
    with pytest.raises(StoreCorruptError, match="content address"):
        open_store(d).read_range(0, 8)
    open_store(d, verify=False).read_range(0, 8)


def test_missing_chunk_file_quarantined_not_retried(store_dir):
    """A cataloged chunk that does not exist is damage, not weather —
    it must quarantine with recovery guidance, not burn the retry
    layer's reopen budget re-missing the same file."""
    st = open_store(store_dir)
    os.remove(os.path.join(store_dir, st.manifest.chunks[3].filename()))
    before = telemetry.counter_value("ingest.retries")
    rs = RetryingSource(
        open_store(store_dir),
        policy=RetryPolicy(max_retries=3, backoff_s=0.001),
        reopen=lambda: open_store(store_dir),
    )
    with pytest.raises(StoreCorruptError, match="chunk file missing"):
        _materialize(rs, 32)
    assert telemetry.counter_value("ingest.retries") == before


def test_bad_source_specs_are_usage_errors(capsys):
    """`vcf:path` and `store:` must die as argparse usage errors, not
    mid-job tracebacks (other sources take --path)."""
    from spark_examples_tpu.cli.main import main

    for bad in ("vcf:cohort.vcf", "store:", "nonsense"):
        with pytest.raises(SystemExit) as e:
            main(["pcoa", "--source", bad])
        assert e.value.code == 2
        capsys.readouterr()


def test_corrupt_chunk_not_retried(store_dir):
    """Corruption is damage, not weather: the retry boundary must fail
    fast with the cursor named, not burn its budget re-reading it."""
    before = telemetry.counter_value("ingest.retries")
    with faults.armed(["store.read:truncate:after=1:keep=4"]):
        rs = RetryingSource(
            open_store(store_dir),
            policy=RetryPolicy(max_retries=3, backoff_s=0.001),
            reopen=lambda: open_store(store_dir),
        )
        with pytest.raises(StoreCorruptError) as e:
            _materialize(rs, 32)
    assert e.value.cursor == 32
    assert telemetry.counter_value("ingest.retries") == before


def test_transient_io_error_recovered_bit_identically(store_dir, genotypes):
    """An injected store.read IOError rides the RetryingSource reopen
    path (fresh mappings) and the recovered stream is bit-identical."""
    before = telemetry.counter_value("ingest.retries")
    with faults.armed(["store.read:io_error:after=3:max=2"]) as inj:
        rs = RetryingSource(
            open_store(store_dir),
            policy=RetryPolicy(max_retries=2, backoff_s=0.001),
            reopen=lambda: open_store(store_dir),
        )
        with warnings.catch_warnings():
            warnings.simplefilter("ignore", RuntimeWarning)
            got = _materialize(rs, 32)
        assert inj.fire_count("store.read") == 2
    np.testing.assert_array_equal(got, genotypes)
    assert telemetry.counter_value("ingest.retries") == before + 2


# ---------------------------------------------------------------------------
# Manifest format errors (the load_model()-grade treatment)


def _manifest_path(d):
    return os.path.join(d, "manifest.json")


def test_missing_manifest_is_friendly(tmp_path):
    with pytest.raises(StoreFormatError, match="not a dataset store"):
        open_store(str(tmp_path / "nope"))


def test_pre_versioning_manifest_rejected(store_dir):
    m = json.load(open(_manifest_path(store_dir)))
    del m["schema_version"]
    json.dump(m, open(_manifest_path(store_dir), "w"))
    with pytest.raises(StoreFormatError, match="pre-versioning"):
        open_store(store_dir)


def test_future_schema_rejected(store_dir):
    m = json.load(open(_manifest_path(store_dir)))
    m["schema_version"] = 99
    json.dump(m, open(_manifest_path(store_dir), "w"))
    with pytest.raises(StoreFormatError, match="newer than this build"):
        open_store(store_dir)


def test_missing_field_named(store_dir):
    m = json.load(open(_manifest_path(store_dir)))
    del m["chunks"]
    json.dump(m, open(_manifest_path(store_dir), "w"))
    with pytest.raises(StoreFormatError, match="chunks"):
        open_store(store_dir)


def test_truncated_manifest_rejected(store_dir):
    raw = open(_manifest_path(store_dir)).read()
    open(_manifest_path(store_dir), "w").write(raw[: len(raw) // 2])
    with pytest.raises(StoreFormatError, match="unreadable"):
        open_store(store_dir)

"""The fixed shape: jax imports are lazy, contract imports stay in the
jax-free closure."""
# graftlint: module=spark_examples_tpu.core.faults
import os
import time

from spark_examples_tpu.core import telemetry  # jax-free by contract


def run_on_device(x):
    import jax  # lazy: only the process that computes pays for it

    return jax.device_put(x), os.getpid(), time.time()

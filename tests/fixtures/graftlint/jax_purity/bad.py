"""Distilled PR 6 contract break: a module on the supervised parent's
import path pulling jax in at module level (directly AND transitively
through a package whose __init__ re-exports a jax-importing module)."""
# graftlint: module=spark_examples_tpu.core.faults
import jax  # line 5: direct

from spark_examples_tpu.ops import gram  # line 7: transitive via ops

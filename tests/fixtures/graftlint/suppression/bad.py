"""A suppression that names no reason: the finding it silences is
silenced, but the bare disable is itself a finding — an exception
nobody can re-evaluate is a latent bug with a comment."""
import threading
import time

_lock = threading.Lock()


def hold():
    with _lock:
        time.sleep(0.1)  # graftlint: disable=blocking-under-lock

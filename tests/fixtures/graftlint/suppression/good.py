"""Reasoned suppressions: inline on the finding's line, or standing
alone on the line directly above it."""
import threading
import time

_lock = threading.Lock()


def hold():
    with _lock:
        time.sleep(0.01)  # graftlint: disable=blocking-under-lock  # test pacing stub: the sleep IS the critical section under test
        # graftlint: disable=blocking-under-lock  # ditto, standalone form
        time.sleep(0.01)

"""Distilled PR 12 regression: donating int32/scalar leaves XLA cannot
alias into float outputs, and reading a donated buffer after the call."""
from functools import partial

import jax
import jax.numpy as jnp


@partial(jax.jit, donate_argnums=(0, 1))
def finalize(pieces, nvar):
    return pieces / nvar


def unusable_donation(block):
    pieces = jnp.zeros((8, 8), dtype=jnp.int32)
    return finalize(pieces, 3)  # line 15: int32 arg 0, scalar arg 1


_update = jax.jit(lambda acc, b: acc + b, donate_argnums=(0,))


def read_after_donate(blocks):
    acc = jnp.zeros((8, 8), dtype=jnp.float32)
    out = _update(acc, blocks[0])
    return out + acc.sum()  # line 24: acc was donated at line 23

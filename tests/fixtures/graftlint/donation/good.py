"""The safe shapes: float leaves, loop-carried rebinding."""
from functools import partial

import jax
import jax.numpy as jnp


@partial(jax.jit, donate_argnums=(0,))
def update(acc, b):
    return acc + b


def run(blocks):
    acc = jnp.zeros((8, 8), dtype=jnp.float32)
    for b in blocks:
        acc = update(acc, b)  # rebinds: the old buffer is unreachable
    return acc  # reads the LAST result, never a donated buffer

"""Distilled PR 11 regression: the CLI's hard-coded --metric choices
list that made the freshly registered Jaccard kernel unreachable."""
import argparse

parser = argparse.ArgumentParser()
parser.add_argument(
    "--metric",
    default="ibs",
    choices=["ibs", "ibs2", "grm", "shared-alt"],  # line 9: the drift
)
parser.add_argument(
    "--solver",
    choices=("sketch", "corrected", "exact"),  # line 13: config enum too
)

"""The fixed shape: choices derived from the live registries."""
import argparse

from spark_examples_tpu import kernels
from spark_examples_tpu.core import config

parser = argparse.ArgumentParser()
parser.add_argument("--metric", default="ibs",
                    choices=list(kernels.names()))
parser.add_argument("--solver", choices=list(config.SOLVER_LADDER))
# A mixed collection that merely CONTAINS one registry value is not an
# enum listing.
MODES = ["ibs", "something-else"]

"""Distilled PR 6 contract breaks: threads the soak leak accounting
cannot see — anonymous, implicit-daemon, or prefix-uncovered."""
import threading
from concurrent.futures import ThreadPoolExecutor


def start(work):
    t1 = threading.Thread(target=work)  # line 8: no daemon, no name
    t2 = threading.Thread(  # line 9: uncovered prefix
        target=work, name="mystery-worker", daemon=True)
    pool = ThreadPoolExecutor(max_workers=2)  # line 11: anonymous pool
    return t1, t2, pool

"""The accounted shapes: explicit daemon, names whose prefixes the
soak harness's _SUSPECT_THREADS table covers."""
import threading
from concurrent.futures import ThreadPoolExecutor


def start(work, k):
    t = threading.Thread(target=work, name=f"prefetch-producer-{k}",
                         daemon=True)
    pool = ThreadPoolExecutor(max_workers=2,
                              thread_name_prefix="store-readahead")
    return t, pool

"""The fixed shape: snapshot under the lock, I/O after releasing."""
import threading

_lock = threading.Lock()


def flush(path, registry):
    with _lock:
        snapshot = dict(registry)  # cheap copy in the critical section
    with open(path, "w") as f:  # I/O with no lock held
        f.write(str(snapshot))


def helper_call_is_not_lexical(path, registry, writer):
    with _lock:
        writer(path, registry)  # the callee's own lock use is its problem

"""Distilled PR 6 regression: the SIGTERM drain flushed telemetry
(file I/O) while holding the module lock the flush itself needed."""
import subprocess
import threading
import time

_lock = threading.Lock()


def flush(path, snapshot):
    with _lock:
        time.sleep(0.1)  # line 12: sleep under the lock
        with open(path, "w") as f:  # line 13: file I/O under the lock
            f.write(snapshot)


def probe(lock, cmd):
    lock.acquire()
    try:
        subprocess.run(cmd)  # line 20: subprocess inside acquire/release
    finally:
        lock.release()

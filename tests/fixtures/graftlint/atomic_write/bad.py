"""Distilled PR 8 regression: in-place writes to durable artifacts —
a kill mid-write leaves a torn metrics.json / manifest."""
import json
import pathlib


def export(metrics_path, payload):
    with open(metrics_path, "w") as f:  # line 8: raw write, durable path
        json.dump(payload, f)


def save(root, doc):
    manifest = pathlib.Path(root) / "manifest.json"
    manifest.write_text(json.dumps(doc))  # line 14: same class

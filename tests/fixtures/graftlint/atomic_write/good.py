"""The fixed shape: stage to a tmp sibling, publish with os.replace."""
import json
import os


def export(metrics_path, payload):
    tmp = metrics_path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(payload, f)
    os.replace(tmp, metrics_path)  # atomic publish


def ordinary_output(path, rows):
    # Not a durable artifact: plain result tables may write in place.
    with open(path, "w") as f:
        f.writelines(rows)

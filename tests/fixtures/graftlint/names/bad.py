"""Distilled PRs 2/4 contract breaks the regex lints missed: an
undeclared name behind an import ALIAS, an undeclared name built by
CONCATENATION, an f-string name, and an undeclared fault site in a
MULTI-LINE call."""
from spark_examples_tpu.core import faults
from spark_examples_tpu.core import telemetry as t

_PREFIX = "serve."


def handle(request, shard):
    t.count("serve.bogus_requests", 1)  # line 12: undeclared, aliased
    t.count(_PREFIX + "also_bogus", 1)  # line 13: undeclared, concat
    t.observe(f"serve.latency_{shard}", 0.1)  # line 14: f-string name
    faults.fire(  # multi-line call: the site literal is on line 16
        "serve.bogus_site",
        kind="io_error",
    )

"""Declared names through every shape the AST rules see: alias,
concatenation of declared parts, multi-line call, dynamic-but-variable
name (the runtime registry check's job, not lint's)."""
from spark_examples_tpu.core import faults
from spark_examples_tpu.core import telemetry as t

_STORE = "store."


def handle(request, name):
    t.count("serve.requests", 1)
    t.count(_STORE + "healed", 1)  # folds to the declared store.healed
    t.observe(  # multi-line literal call site
        "serve.latency_s",
        0.1,
    )
    t.count(name, 1)  # dynamic variable: runtime-checked, not flagged
    faults.fire("serve.request", kind="io_error")

"""Fleet flight recorder (ISSUE 17): request-scoped trace context and
deterministic sampling, the slowest-K exemplar ring, the controller's
timeline ring (size-bounded, fault-tolerant, compacting), declarative
SLO parsing + burn-rate evaluation, `GET /fleet/metrics`, fleet-wide
stitching (`telemetry stitch --fleet`), the `telemetry timeline` CLI
verb, and the cross-process acceptance: two real ProcessReplica serve
children answering hedged requests that share ONE trace id, the
primary killed mid-burst, stitched onto one waterfall with the
controller's incident markers.
"""

import json
import os
import sys
import threading
import time
import urllib.request

import numpy as np
import pytest

from spark_examples_tpu.core import faults, stitch, telemetry
from spark_examples_tpu.core.config import TelemetryConfig
from spark_examples_tpu.fleet.replica import ReplicaSnapshot
from spark_examples_tpu.fleet.slo import SLOEvaluator, SLOSpec
from spark_examples_tpu.fleet.timeline import (
    FleetTimeline,
    TimelineMetricsServer,
    read_timeline,
)
from spark_examples_tpu.serve import FleetFormatError, FleetManifest


@pytest.fixture(autouse=True)
def _fresh_telemetry():
    sample0 = telemetry.trace_sample()
    telemetry.reset()
    yield
    telemetry.reset()
    telemetry.configure(dir=None)
    telemetry.set_trace_sample(sample0)


# ------------------------------------------------------ trace context


def test_trace_ids_are_hex_tokens():
    tid = telemetry.new_trace_id()
    sid = telemetry.new_span_id()
    assert len(tid) == 16 and int(tid, 16) >= 0
    assert len(sid) == 8 and int(sid, 16) >= 0
    assert telemetry.new_trace_id() != tid


def test_sampling_is_deterministic_on_the_trace_id():
    telemetry.set_trace_sample(1.0)
    assert telemetry.should_sample("anything")
    telemetry.set_trace_sample(0.0)
    assert not telemetry.should_sample("anything")
    telemetry.set_trace_sample(0.5)
    ids = [telemetry.new_trace_id() for _ in range(400)]
    first = [telemetry.should_sample(t) for t in ids]
    # Deterministic: the same id always decides the same way — the
    # property hedge legs and child processes rely on.
    assert [telemetry.should_sample(t) for t in ids] == first
    frac = sum(first) / len(first)
    assert 0.3 < frac < 0.7


def test_trace_sample_flag_validated():
    with pytest.raises(ValueError, match="--trace-sample"):
        TelemetryConfig(trace_sample=1.5)
    with pytest.raises(ValueError, match="--trace-sample"):
        TelemetryConfig(trace_sample=True)
    assert TelemetryConfig(trace_sample=0.25).trace_sample == 0.25


def test_trace_scope_stamps_ids_into_events(tmp_path):
    telemetry.configure(dir=str(tmp_path / "tel"), trace_events=True)
    with telemetry.trace_scope(trace_id="a" * 16, span_id="b" * 8):
        telemetry.event("trace.hedge", winner="primary", loser="none")
    evs = [e for e in telemetry.recent_events()
           if e["name"] == "trace.hedge"]
    assert evs and evs[-1]["args"]["trace_id"] == "a" * 16
    assert evs[-1]["args"]["winner"] == "primary"


def test_span_at_records_retroactive_interval(tmp_path):
    telemetry.configure(dir=str(tmp_path / "tel"), trace_events=True)
    t0 = time.perf_counter() - 0.05
    telemetry.span_at("trace.queue", t0, 0.05, trace_id="t1",
                      span_id="s1", route="r", cls="interactive")
    ev = next(e for e in telemetry.recent_events()
              if e["name"] == "trace.queue")
    assert ev["ph"] == "X"
    assert ev["dur"] == pytest.approx(0.05 * 1e6)
    assert ev["args"]["trace_id"] == "t1"
    # The histogram side: span_at feeds the same latency registry a
    # live span would.
    hists = telemetry.metrics_snapshot()["histograms"]
    assert hists["trace.queue"]["count"] == 1


def test_exemplar_ring_keeps_the_slowest_k():
    for i in range(telemetry.TRACE_EXEMPLARS + 18):
        telemetry.record_request_exemplar(
            f"t{i:04d}", total_s=i / 1e3,
            phases={"total": i / 1e3}, route="r", status=200)
    ex = telemetry.request_exemplars()
    assert len(ex) == telemetry.TRACE_EXEMPLARS
    # Slowest first, and the fast tail was evicted.
    assert ex[0]["trace_id"] == f"t{telemetry.TRACE_EXEMPLARS + 17:04d}"
    assert min(e["total_s"] for e in ex) == pytest.approx(18 / 1e3)
    assert all("phases" in e and "t_unix" in e for e in ex)


# ---------------------------------------------------- timeline ring


def _snap(p99=0.01, shed=0.0, qi=0, qb=0, route="r-a", staged=True,
          stale=False, ready=True):
    return ReplicaSnapshot(
        t=time.monotonic(), ready=ready, health="healthy",
        worker_alive=True, in_flight=0, queue_interactive=qi,
        queue_batch=qb, p99_s=p99, shed_rate=shed, pool_bytes=0.0,
        pool_pressure=0.0, stale=stale,
        routes={route: {"p99_s": p99, "queue_depth": qi + qb,
                        "shed_rate": shed, "staged": staged}})


def test_timeline_roundtrip_markers_and_folds(tmp_path):
    path = str(tmp_path / "timeline.jsonl")
    tl = FleetTimeline(path=path)
    for rd in range(4):
        tl.record_round(rd, {"replica-0": _snap(p99=0.01 * (rd + 1)),
                             "replica-1": None}, 1, 1)
    tl.record_marker(3, "replica-0", "crash", "killed mid-burst")
    recs = read_timeline(path)
    assert [r["type"] for r in recs] == ["round"] * 4 + ["marker"]
    assert recs[0]["slots"]["replica-1"] == {"present": False}
    assert recs[2]["slots"]["replica-0"]["routes"]["r-a"]["p99_s"] == \
        pytest.approx(0.03)
    assert recs[-1]["kind"] == "crash"
    # recent() interleaves rounds and markers on one seq clock.
    assert [r["seq"] for r in tl.recent()] == [1, 2, 3, 4, 5]
    # Folds: the fleet p99 is a real Histogram.merge quantile over the
    # per-slot rounds, published as timeline.* gauges.
    gauges = telemetry.metrics_snapshot()["gauges"]
    assert gauges["timeline.fleet_p99_s"]["last"] > 0.0
    assert gauges["timeline.route.r-a.p99_s"]["last"] > 0.0
    assert tl.route_quantile("r-a", 0.99) >= 0.01
    assert telemetry.counter_value("timeline.rounds") == 4
    assert telemetry.counter_value("timeline.markers") == 1


def test_timeline_merges_quantiles_across_slots():
    tl = FleetTimeline(path=None)  # memory-only mode
    for rd in range(20):
        tl.record_round(rd, {
            "replica-0": _snap(p99=0.010),
            "replica-1": _snap(p99=0.100),
        }, 2, 2)
    # The fleet-wide p99 sees BOTH slots' samples — a max-of-medians
    # would sit at 0.1 only by luck; the merge provably spans both.
    q99 = tl.route_quantile("r-a", 0.99)
    q10 = tl.route_quantile("r-a", 0.10)
    assert q99 >= 0.09
    assert q10 <= 0.02


def test_timeline_compacts_past_the_size_bound(tmp_path):
    path = str(tmp_path / "timeline.jsonl")
    tl = FleetTimeline(path=path, max_bytes=4096, window=6)
    for rd in range(60):
        tl.record_round(rd, {"replica-0": _snap()}, 1, 1)
    assert telemetry.counter_value("timeline.compactions") >= 1
    assert os.path.getsize(path) <= 4096 + 2048  # bound + one window
    recs = read_timeline(path)
    # The survivor set is the in-memory window plus appends since the
    # last rewrite: far fewer records than were ever appended, and the
    # newest round is always the last line on the tape.
    assert recs[-1]["round"] == 59
    assert 6 <= len([r for r in recs if r["type"] == "round"]) < 30


def test_timeline_absorbs_trace_export_io_errors(tmp_path):
    path = str(tmp_path / "timeline.jsonl")
    tl = FleetTimeline(path=path)
    with faults.armed(["trace.export:io_error:after=1:max=2"]):
        for rd in range(5):  # never raises into the control loop
            tl.record_round(rd, {"replica-0": _snap()}, 1, 1)
    assert telemetry.counter_value("timeline.write_errors") == 2
    recs = read_timeline(path)
    assert len(recs) == 3  # the two failed appends are the only holes
    assert recs[-1]["round"] == 4


def test_timeline_truncate_fault_leaves_last_good_tape(tmp_path):
    path = str(tmp_path / "timeline.jsonl")
    tl = FleetTimeline(path=path)
    with faults.armed(["trace.export:truncate:keep=8:after=3:max=1"]):
        for rd in range(8):
            tl.record_round(rd, {"replica-0": _snap()}, 1, 1)
    # The truncate tore the tape down to 8 bytes mid-append — the
    # round being written is lost, every complete record appended
    # afterwards survives, and the reader skips the torn fragment.
    recs = read_timeline(path)
    assert [r["round"] for r in recs] == [4, 5, 6, 7]


def test_timeline_config_validation_names_the_knob():
    with pytest.raises(ValueError, match="--timeline-max-bytes"):
        FleetTimeline(max_bytes=10)
    with pytest.raises(ValueError, match="--timeline-max-bytes"):
        FleetTimeline(max_bytes=True)
    from spark_examples_tpu.fleet import ControllerConfig
    with pytest.raises(ValueError, match="--timeline-max-bytes"):
        ControllerConfig(timeline_max_bytes=1)


def test_fleet_metrics_server_serves_folds_and_timeline(tmp_path):
    tl = FleetTimeline(path=None)
    for rd in range(3):
        tl.record_round(rd, {"replica-0": _snap(p99=0.02, qi=3)}, 1, 1)
    tl.record_marker(2, "r-a", "slo_breach", "p99<=5ms burned")
    srv = TimelineMetricsServer(tl).serve_in_thread()
    base = f"http://127.0.0.1:{srv.port}"
    try:
        with urllib.request.urlopen(f"{base}/fleet/metrics",
                                    timeout=30) as r:
            prom = r.read().decode()
        assert "timeline_fleet_p99_s" in prom
        assert "timeline_fleet_queue_depth" in prom
        assert "timeline_route_r_a_p99_s" in prom
        with urllib.request.urlopen(f"{base}/fleet/timeline",
                                    timeout=30) as r:
            doc = json.loads(r.read())
        assert len(doc["records"]) == 4
        assert doc["records"][-1]["kind"] == "slo_breach"
        with pytest.raises(urllib.error.HTTPError) as err:
            urllib.request.urlopen(f"{base}/nope", timeout=30)
        assert err.value.code == 404
    finally:
        srv.shutdown()


# ------------------------------------------------------------- SLOs


def _manifest(slos):
    return {"routes": [{"name": "r-a", "model": "m.npz",
                        "source": "synthetic"}],
            "slos": slos}


def test_slo_manifest_validation_names_the_entry():
    with pytest.raises(FleetFormatError, match="'slos' must be a list"):
        FleetManifest.parse(_manifest({"route": "r-a"}))
    with pytest.raises(FleetFormatError, match=r"slos\[0\] has unknown"):
        FleetManifest.parse(_manifest([{"route": "r-a", "p99ms": 5}]))
    with pytest.raises(FleetFormatError, match="names no declared route"):
        FleetManifest.parse(_manifest([{"route": "r-b", "p99_ms": 5}]))
    with pytest.raises(FleetFormatError, match="declares no objective"):
        FleetManifest.parse(_manifest([{"route": "r-a"}]))
    with pytest.raises(FleetFormatError, match=r"slos\[0\]\.p99_ms"):
        FleetManifest.parse(_manifest([{"route": "r-a", "p99_ms": -1}]))
    with pytest.raises(FleetFormatError,
                       match=r"slos\[0\]\.availability"):
        FleetManifest.parse(
            _manifest([{"route": "r-a", "availability": 1.5}]))
    with pytest.raises(FleetFormatError, match="slow_window_s"):
        FleetManifest.parse(_manifest([
            {"route": "r-a", "p99_ms": 5, "fast_window_s": 60,
             "slow_window_s": 30}]))
    m = FleetManifest.parse(_manifest([
        {"route": "r-a", "p99_ms": 50, "budget": 0.2},
        {"route": "*", "availability": 0.99},
    ]))
    assert m.slos[0].p99_ms == 50.0 and m.slos[0].budget == 0.2
    assert m.slos[0].key == "r-a"
    assert m.slos[1].key == "fleet"
    assert FleetManifest.parse(_manifest(None) | {"slos": None}).slos == ()


def test_slo_burn_needs_min_rounds_before_claiming():
    tl = FleetTimeline(path=None)
    spec = SLOSpec(route="r-a", p99_ms=5.0, fast_window_s=30.0,
                   slow_window_s=30.0)
    for rd in range(2):  # violating, but too thin a window
        tl.record_round(rd, {"replica-0": _snap(p99=0.2)}, 1, 1)
    assert SLOEvaluator((spec,), tl).evaluate() == []
    gauges = telemetry.metrics_snapshot()["gauges"]
    assert gauges["slo.r-a.fast_burn"]["last"] == 0.0
    assert gauges["slo.ok"]["last"] == 1.0


def test_slo_breach_when_both_windows_burn():
    tl = FleetTimeline(path=None)
    spec = SLOSpec(route="r-a", p99_ms=5.0, fast_window_s=30.0,
                   slow_window_s=30.0)
    for rd in range(6):  # p99 40x over the objective, every round
        tl.record_round(rd, {"replica-0": _snap(p99=0.2)}, 1, 1)
    breaches = SLOEvaluator((spec,), tl).evaluate()
    assert len(breaches) == 1
    b = breaches[0]
    assert b["route"] == "r-a" and "p99<=5" in b["objective"]
    assert b["fast_burn"] >= 1.0 and b["slow_burn"] >= 1.0
    gauges = telemetry.metrics_snapshot()["gauges"]
    assert gauges["slo.r-a.breached"]["last"] == 1.0
    assert gauges["slo.ok"]["last"] == 0.0
    assert telemetry.counter_value("slo.breaches") == 1


def test_slo_availability_objective_reads_shed_rate():
    tl = FleetTimeline(path=None)
    spec = SLOSpec(route="*", availability=0.99, fast_window_s=30.0,
                   slow_window_s=30.0)
    for rd in range(4):
        tl.record_round(rd, {"replica-0": _snap(shed=0.5)}, 1, 1)
    breaches = SLOEvaluator((spec,), tl).evaluate()
    assert breaches and breaches[0]["key"] == "fleet"
    assert "availability>=0.99" in breaches[0]["objective"]
    # Healthy rounds push the violating fraction back under budget.
    tl2 = FleetTimeline(path=None)
    for rd in range(40):
        tl2.record_round(rd, {"replica-0": _snap(shed=0.0)}, 1, 1)
    assert SLOEvaluator((spec,), tl2).evaluate() == []


# ----------------------------------------------------- fleet stitch


def _write_slot_export(base, slot, events, run_id="rid1", epoch=1000.0,
                       live_ring=False):
    d = os.path.join(base, slot, "rank0")
    os.makedirs(d)
    if not live_ring:
        with open(os.path.join(d, "metrics.json"), "w") as f:
            json.dump({"counters": {}, "meta": {
                "rank": 0, "attempt": 0, "run_id": run_id,
                "epoch_unix_s": epoch}}, f)
    name = "live_trace.jsonl" if live_ring else "trace.jsonl"
    with open(os.path.join(d, name), "w") as f:
        for ev in events:
            f.write(json.dumps(ev) + "\n")


def test_stitch_fleet_merges_slots_with_incident_markers(tmp_path):
    base = str(tmp_path / "fleet")
    span = {"name": "trace.compute", "cat": "trace", "ph": "X",
            "dur": 5e3, "tid": 1}
    # The hedged waterfall: both slots carry spans for ONE trace id —
    # slot 0 (killed mid-burst) left only its live ring.
    _write_slot_export(base, "replica-0", [
        {**span, "ts": 10.0, "args": {"trace_id": "tt1",
                                      "span_id": "a1"}}],
        live_ring=True)
    _write_slot_export(base, "replica-1", [
        {**span, "ts": 20.0, "args": {"trace_id": "tt1",
                                      "span_id": "b1"}},
        {**span, "ts": 30.0, "args": {"trace_id": "tt2",
                                      "span_id": "b2"}}])
    with open(os.path.join(base, "controller.json"), "w") as f:
        json.dump({"incidents": [
            {"round": 3, "who": "replica-0", "kind": "crash",
             "detail": "killed mid-burst", "t_unix": 1000.5}]}, f)
    report = stitch.stitch_fleet(base)
    assert report["slots"] == ["replica-0", "replica-1"]
    assert report["events"] == 3
    assert report["incident_markers"] == 1
    lines = [json.loads(line)
             for line in open(report["output"]) if line.strip()]
    legs = [e for e in lines
            if e.get("args", {}).get("trace_id") == "tt1"]
    # One trace id, two slots, two distinct pid tracks (slot stride).
    assert len(legs) == 2
    assert abs(legs[0]["pid"] - legs[1]["pid"]) >= 1_000_000
    assert {e["args"]["span_id"] for e in legs} == {"a1", "b1"}
    marker = next(e for e in lines if e["name"] == "incident: crash")
    assert marker["ph"] == "i" and marker["s"] == "g"
    assert marker["args"]["who"] == "replica-0"
    names = {e["args"].get("name") for e in lines if e.get("ph") == "M"}
    assert {"replica-0 attempt 0 rank 0", "replica-1 attempt 0 rank 0",
            "controller"} <= names


def test_stitch_fleet_rejects_a_non_fleet_dir(tmp_path):
    with pytest.raises(stitch.StitchError, match="fleet workdir"):
        stitch.stitch_fleet(str(tmp_path))


def test_stitch_fleet_reads_rotated_ledger_generation(tmp_path):
    base = str(tmp_path / "fleet")
    _write_slot_export(base, "replica-0", [
        {"name": "trace.request", "ph": "X", "ts": 1.0, "dur": 1.0,
         "tid": 1, "args": {}}])
    inc = {"round": 1, "who": "replica-0", "kind": "crash",
           "detail": "old generation", "t_unix": 1000.1}
    with open(os.path.join(base, "controller.json.old"), "w") as f:
        json.dump({"incidents": [inc]}, f)
    with open(os.path.join(base, "controller.json"), "w") as f:
        # The current ledger still holds the overlap entry — the
        # stitch must dedup it, not double-mark.
        json.dump({"incidents": [inc, {
            "round": 9, "who": "replica-0", "kind": "flap",
            "detail": "new generation", "t_unix": 1001.0}]}, f)
    report = stitch.stitch_fleet(base)
    assert report["incident_markers"] == 2


# ----------------------------------------------------------- CLI


def test_telemetry_timeline_cli_renders_the_tape(tmp_path, capsys):
    from spark_examples_tpu.cli.main import main

    tl = FleetTimeline(path=str(tmp_path / "timeline.jsonl"))
    for rd in range(3):
        tl.record_round(rd, {"replica-0": _snap(p99=0.025, qi=2)}, 1, 1)
    tl.record_marker(2, "r-a", "slo_breach", "p99<=5ms burned: fast 3x")
    rc = main(["telemetry", "timeline", "--path", str(tmp_path)])
    assert rc == 0
    out, err = capsys.readouterr()
    report = json.loads(out.strip().splitlines()[-1])
    assert report["rounds"] == 3 and report["markers"] == 1
    assert report["replicas_last"] == 1
    assert report["routes"]["r-a"]["p99_last_ms"] == pytest.approx(25.0)
    assert report["marker_kinds"] == ["slo_breach"]
    assert "slo_breach" in err and "round" in err


def test_telemetry_timeline_cli_empty_tape_fails_loudly(tmp_path,
                                                        capsys):
    from spark_examples_tpu.cli.main import main

    rc = main(["telemetry", "timeline", "--path", str(tmp_path)])
    assert rc == 1
    assert "no readable records" in capsys.readouterr().err


# --------------------------------------- cross-process acceptance


V_E2E = 64
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def fleet_cmd(tmp_path_factory):
    """A one-route fleet manifest (tiny fitted model + compacted
    store) and the serve child argv that loads it."""
    from spark_examples_tpu.core.config import (
        ComputeConfig, IngestConfig, JobConfig,
    )
    from spark_examples_tpu.ingest.source import ArraySource
    from spark_examples_tpu.pipelines.jobs import pcoa_job
    from spark_examples_tpu.store.writer import compact
    from tests.conftest import random_genotypes

    base = tmp_path_factory.mktemp("trace_e2e")
    rng = np.random.default_rng(7)
    g = random_genotypes(rng, n=8, v=V_E2E, missing_rate=0.1)
    store = str(base / "store")
    compact(store, ArraySource(g), chunk_variants=32)
    model = str(base / "model.npz")
    pcoa_job(JobConfig(
        ingest=IngestConfig(block_variants=32),
        compute=ComputeConfig(metric="ibs", num_pc=3),
        model_path=model,
    ), source=ArraySource(g))
    manifest = str(base / "fleet.json")
    with open(manifest, "w") as f:
        json.dump({"routes": [{
            "name": "r-ibs", "model": model,
            "source": f"store:{store}", "block_variants": 32}]}, f)
    argv = [sys.executable, "-m", "spark_examples_tpu", "serve",
            "--fleet", manifest, "--port", "0"]
    return argv


def _post(port, trace_id=None, timeout=60.0):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/project/r-ibs",
        data=json.dumps({"genotypes": [0] * V_E2E}).encode(),
        method="POST")
    if trace_id:
        req.add_header("X-Trace-Id", trace_id)
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return resp.headers, json.loads(resp.read())


def _wait_port(replica, budget_s=120.0):
    deadline = time.monotonic() + budget_s
    while time.monotonic() < deadline:
        if not replica.alive():
            raise AssertionError(
                f"{replica.name} died during startup")
        if replica.port() is not None:
            return replica.port()
        time.sleep(0.1)
    raise AssertionError(f"{replica.name} never announced a port")


def test_hedged_trace_survives_replica_kill_end_to_end(fleet_cmd,
                                                       tmp_path):
    """ISSUE 17 acceptance: two REAL serve child processes, hedged
    requests sharing one trace id, the primary SIGKILLed mid-burst —
    `stitch_fleet` joins the survivor's spans, the killed replica's
    live-ring spans, and the controller ledger's crash marker onto ONE
    waterfall, all under the parent's run_id (propagated through the
    ProcessReplica environment)."""
    from spark_examples_tpu.fleet.replica import ProcessReplica

    base = str(tmp_path / "fleetdir")
    os.makedirs(base)
    reps = []
    for slot in ("replica-0", "replica-1"):
        slot_dir = os.path.join(base, slot)
        os.makedirs(slot_dir)
        argv = fleet_cmd + ["--telemetry-dir", slot_dir,
                            "--telemetry-flush-s", "0.2"]
        reps.append(ProcessReplica(
            slot, argv, workdir=base, budget_bytes=10_000_000,
            route_names=["r-ibs"],
            env=dict(os.environ, JAX_PLATFORMS="cpu",
                     PYTHONPATH=REPO + os.pathsep
                     + os.environ.get("PYTHONPATH", ""))).start())
    r0, r1 = reps
    try:
        # run_id + sample rate ride the environment into both children.
        assert r0.env[telemetry.ENV_RUN_ID] == telemetry.run_id()
        assert r1.env[telemetry.ENV_RUN_ID] == telemetry.run_id()
        p0, p1 = _wait_port(r0), _wait_port(r1)

        shared = "hedge-e2e-" + telemetry.new_trace_id()
        # Primary leg: the client's X-Trace-Id is echoed back and the
        # response carries the serving run id + phase breakdown.
        headers, out = _post(p0, trace_id=shared, timeout=120.0)
        assert headers["X-Trace-Id"] == shared
        assert headers["X-Run-Id"] == telemetry.run_id()
        assert "total;dur=" in headers["Server-Timing"]
        assert len(out["coords"][0]) == 3
        # A server-minted id for a traceless client is a hex token.
        h2, _ = _post(p0, timeout=120.0)
        assert int(h2["X-Trace-Id"], 16) >= 0
        # Let the periodic flusher publish the live ring, then KILL
        # the primary mid-"burst" — no exit-time export happens.
        time.sleep(0.8)
        r0.kill()
        assert not r0.alive()
        # The hedge leg re-sends the SAME trace id to the survivor.
        h3, out3 = _post(p1, trace_id=shared, timeout=120.0)
        assert h3["X-Trace-Id"] == shared
        np.testing.assert_array_equal(
            np.asarray(out3["coords"], np.float32),
            np.asarray(out["coords"], np.float32))
        # The survivor's exemplar ring serves the request forensics.
        with urllib.request.urlopen(
                f"http://127.0.0.1:{p1}/debug/requests",
                timeout=30) as r:
            dbg = json.loads(r.read())
        assert dbg["trace_sample"] == telemetry.trace_sample()
        assert any(e["trace_id"] == shared for e in dbg["exemplars"])
        # Graceful drain: the survivor's exit-time export lands.
        assert r1.drain(60.0)
    finally:
        for r in reps:
            r.kill()
    with open(os.path.join(base, "controller.json"), "w") as f:
        json.dump({"incidents": [
            {"round": 1, "who": "replica-0", "kind": "crash",
             "detail": "killed mid-hedged-burst",
             "t_unix": time.time()}]}, f)
    report = stitch.stitch_fleet(base)
    assert set(report["slots"]) == {"replica-0", "replica-1"}
    assert report["incident_markers"] == 1
    # ONE logical run across both processes (env-propagated run_id);
    # the killed slot contributes via its live ring (no trace.jsonl).
    assert not report["mixed_run_ids"]
    assert not os.path.exists(
        os.path.join(base, "replica-0", "rank0", "trace.jsonl"))
    lines = [json.loads(line)
             for line in open(report["output"]) if line.strip()]
    legs = [e for e in lines
            if e.get("args", {}).get("trace_id") == shared]
    pids = {e["pid"] for e in legs}
    assert len(legs) >= 2 and len(pids) == 2  # both process tracks
    assert next(e for e in lines
                if e["name"] == "incident: crash")["args"]["who"] == \
        "replica-0"

"""Streaming incremental PCoA (config 5): snapshots during the stream,
final coordinates matching a full recompute."""

import numpy as np
import pytest

from spark_examples_tpu.core.config import ComputeConfig, IngestConfig, JobConfig
from spark_examples_tpu.pipelines import jobs
from spark_examples_tpu.pipelines.streaming import incremental_pcoa_job


def _job(**compute_kw):
    return JobConfig(
        ingest=IngestConfig(source="synthetic", n_samples=48,
                            n_variants=4096, block_variants=256, seed=11,
                            n_populations=3),
        compute=ComputeConfig(metric="ibs", num_pc=4, **compute_kw),
    )


def test_incremental_matches_full_recompute():
    out, snapshots = incremental_pcoa_job(_job(stream_refresh_blocks=4))
    # 4096/256 = 16 blocks -> refreshes at blocks 4, 8, 12, 16
    assert len(snapshots) == 4
    assert snapshots[-1].n_variants == 4096
    assert "stream_refresh" in out.timer.phases

    full = jobs.pcoa_job(_job(eigh_mode="dense"))
    # PC3/4 of the 3-population cohort are small and near-degenerate, so
    # the randomized solve agrees to ~1e-2 there; the dominant pair is
    # much tighter and its coordinates must match columnwise.
    np.testing.assert_allclose(
        out.eigenvalues, full.eigenvalues, rtol=1e-2, atol=1e-4
    )
    np.testing.assert_allclose(
        np.abs(out.coords[:, :2]), np.abs(full.coords[:, :2]),
        rtol=1e-2, atol=1e-3,
    )


def test_snapshots_track_final_solution():
    """Warm subspace tracking: every snapshot must already be a usable
    estimate. IBS distances are normalized by the pairwise-complete
    count, so the eigenvalues of the partial accumulator are directly
    comparable across the stream (no per-variant scaling) — each
    snapshot's top eigenvalue is a sampling estimate of the final one,
    and the mid-stream estimates must stay tight (a divergent subspace
    would send them to garbage)."""
    out, snapshots = incremental_pcoa_job(_job(stream_refresh_blocks=2))
    assert len(snapshots) == 8
    final = out.eigenvalues[0]
    errs = [abs(s.eigenvalues[0] - final) / final for s in snapshots]
    # Every snapshot past the first is within 10% of the final value
    # (the first has seen only 512 variants of 4096 — allow 25%), and
    # the last refresh (same accumulator as the terminal solve, but
    # only one warm power step) is within 2%.
    assert errs[0] < 0.25
    assert all(e < 0.10 for e in errs[1:])
    assert errs[-1] < 0.02


def test_small_cohort_probe_clamp():
    """n_samples < num_pc + oversample must not crash: the probe block
    is clamped to (N, N)."""
    job = JobConfig(
        ingest=IngestConfig(source="synthetic", n_samples=20,
                            n_variants=1024, block_variants=256, seed=3),
        compute=ComputeConfig(metric="ibs", num_pc=10,
                              stream_refresh_blocks=2),
    )
    out, snapshots = incremental_pcoa_job(job)
    assert out.coords.shape == (20, 10)
    assert len(snapshots) == 2


def test_streaming_requires_refresh_and_backend():
    with pytest.raises(ValueError, match="stream_refresh_blocks"):
        incremental_pcoa_job(_job(stream_refresh_blocks=0))
    with pytest.raises(ValueError, match="jax backend"):
        incremental_pcoa_job(
            _job(stream_refresh_blocks=2, backend="cpu-reference")
        )
    with pytest.raises(ValueError, match="dense"):
        incremental_pcoa_job(
            _job(stream_refresh_blocks=2, eigh_mode="dense")
        )


def test_streaming_tile2d_plan():
    """The refresh path respects a tiled accumulator layout (no full
    N x N on one device during refreshes either)."""
    job = _job(stream_refresh_blocks=8, gram_mode="tile2d")
    out, snapshots = incremental_pcoa_job(job)
    assert len(snapshots) == 2
    full = jobs.pcoa_job(_job(eigh_mode="dense"))
    np.testing.assert_allclose(  # see tolerance note in the first test
        out.eigenvalues, full.eigenvalues, rtol=2e-2, atol=1e-4
    )

"""2-bit packed transport: codec roundtrips, device unpack parity, and
the packed streaming path matching the dense one end to end."""

import numpy as np
import pytest

from spark_examples_tpu.ingest import bitpack
from spark_examples_tpu.ingest.packed import load_packed, save_packed
from spark_examples_tpu.ingest.prefetch import pad_packed, stream_to_device
from spark_examples_tpu.ingest.source import ArraySource
from spark_examples_tpu.ops import gram
from tests.conftest import random_genotypes


def test_pack_roundtrip(genotypes):
    p = bitpack.pack_dosages(genotypes)
    assert p.dtype == np.uint8
    assert p.shape == (genotypes.shape[0],
                       bitpack.packed_width(genotypes.shape[1]))
    back = bitpack.unpack_dosages_np(p)
    v = genotypes.shape[1]
    np.testing.assert_array_equal(back[:, :v], genotypes)
    # pad columns decode as missing
    assert (back[:, v:] == -1).all()


def test_pack_rejects_out_of_domain():
    bad = np.array([[0, 1, 3]], np.int8)  # 3 is not a dosage
    with pytest.raises(ValueError, match="2-bit range"):
        bitpack.pack_dosages(bad)
    with pytest.raises(ValueError, match="2-bit range"):
        bitpack.pack_dosages(np.array([[-2]], np.int8))


def test_device_unpack_matches_host(genotypes):
    import jax

    p = bitpack.pack_dosages(genotypes)
    dev = np.asarray(jax.jit(bitpack.unpack_dosages)(p))
    np.testing.assert_array_equal(dev, bitpack.unpack_dosages_np(p))


def test_update_packed_matches_dense(rng):
    g = random_genotypes(rng, n=23, v=160, missing_rate=0.2)
    p = bitpack.pack_dosages(g)
    for metric in ("ibs", "shared-alt", "grm"):
        dense = gram.update(gram.init(23, metric), g, metric)
        packed = gram.update_packed(gram.init(23, metric), p, metric)
        for k in dense:
            np.testing.assert_allclose(
                np.asarray(packed[k]), np.asarray(dense[k]), rtol=1e-6
            )


def test_packed_store_roundtrip(tmp_path, genotypes):
    path = str(tmp_path / "store2bit")
    save_packed(path, genotypes, sample_ids=[f"X{i}" for i in
                range(genotypes.shape[0])], bits=2)
    src = load_packed(path)
    assert src.n_variants == genotypes.shape[1]
    assert src.sample_ids[0] == "X0"
    out = np.concatenate([b for b, _ in src.blocks(64)], axis=1)
    np.testing.assert_array_equal(out, genotypes)
    # zero-copy packed slices agree with packing the dense blocks
    for pblock, meta in src.packed_blocks(64):
        want = bitpack.pack_dosages(genotypes[:, meta.start:meta.stop])
        np.testing.assert_array_equal(pblock, want)


def test_packed_store_resume(genotypes, tmp_path):
    path = str(tmp_path / "store")
    save_packed(path, genotypes, bits=2)
    src = load_packed(path)
    full = list(src.packed_blocks(64))
    resumed = list(src.packed_blocks(64, start_variant=128))
    assert [m.start for _, m in resumed] == [m.start for _, m in full[2:]]
    np.testing.assert_array_equal(resumed[0][0], full[2][0])


def test_pad_packed_decodes_missing():
    p = bitpack.pack_dosages(np.array([[0, 1, 2, 0]], np.int8))
    out = pad_packed(p, 3)
    assert out.shape == (1, 3)
    np.testing.assert_array_equal(
        bitpack.unpack_dosages_np(out[:, 1:]), -np.ones((1, 8), np.int8)
    )


def test_pack_source_roundtrip(rng, tmp_path):
    """One-pass ETL: stream a source into the 2-bit store; reading the
    store back reproduces the cohort (incl. a ragged final block)."""
    from spark_examples_tpu.ingest.packed import pack_source

    g = random_genotypes(rng, n=9, v=203, missing_rate=0.2)
    path = str(tmp_path / "store")
    written = pack_source(path, ArraySource(g), block_variants=64)
    assert written == 203
    src = load_packed(path)
    assert src.n_variants == 203
    out = np.concatenate([b for b, _ in src.blocks(50)], axis=1)
    np.testing.assert_array_equal(out, g)


def test_pack_source_contig_flush_alignment(rng, tmp_path):
    """Chromosome-flush blocks end at arbitrary widths; the packer's
    carry buffer must keep every later variant byte-aligned (packing a
    sub-byte tail early would shift the whole remainder)."""
    from spark_examples_tpu.ingest.packed import pack_source
    from spark_examples_tpu.ingest.plink import PlinkSource, write_plink

    g = random_genotypes(rng, n=6, v=45, missing_rate=0.1)
    prefix = str(tmp_path / "c")
    # contig runs of 7, 13, 25 -> flushes at 7 and 20 (neither % 4 == 0)
    write_plink(prefix, g, chroms=["1"] * 7 + ["2"] * 13 + ["3"] * 25,
                positions=np.arange(45))
    path = str(tmp_path / "store")
    pack_source(path, PlinkSource(prefix), block_variants=16)
    src = load_packed(path)
    blocks = list(src.blocks(16))
    out = np.concatenate([b for b, _ in blocks], axis=1)
    np.testing.assert_array_equal(out, g)
    np.testing.assert_array_equal(src.positions, np.arange(45))
    # chromosome identity round-trips: dense blocks flush at run
    # boundaries with exact contigs, matching the original stream
    assert [(m.start, m.stop, m.contig) for _, m in blocks] == [
        (m.start, m.stop, m.contig)
        for _, m in PlinkSource(prefix).blocks(16)
    ]
    # byte-grid packed blocks may straddle runs: contig is exact when
    # unique, None when spanning
    pmetas = [m for _, m in src.packed_blocks(16)]
    assert pmetas[0].contig is None  # 0..16 spans chr1/chr2
    assert pmetas[2].contig == "3"   # 32..45 inside chr3


@pytest.mark.parametrize("use_store", [False, True])
def test_packed_stream_matches_dense_accumulation(rng, tmp_path, use_store):
    """End to end: streaming packed blocks into update_packed produces the
    same IBS accumulators as the dense stream — including ragged final
    blocks and pad_multiple rounding."""
    g = random_genotypes(rng, n=17, v=500, missing_rate=0.1)
    if use_store:
        path = str(tmp_path / "s")
        save_packed(path, g, bits=2)
        src = load_packed(path)
    else:
        src = ArraySource(g)

    dense_acc = gram.init(17, "ibs")
    for block, _ in stream_to_device(src, 128, pad_multiple=2):
        dense_acc = gram.update(dense_acc, block, "ibs")

    packed_acc = gram.init(17, "ibs")
    n_bytes = 0
    for block, _ in stream_to_device(src, 128, pad_multiple=2, pack=True):
        assert block.dtype == np.uint8
        n_bytes += block.size
        packed_acc = gram.update_packed(packed_acc, block, "ibs")

    for k in dense_acc:
        np.testing.assert_allclose(
            np.asarray(packed_acc[k]), np.asarray(dense_acc[k]), rtol=1e-6
        )
    # the transport really was ~4x smaller
    assert n_bytes <= g.size // 4 + 17 * 4 * len(list(src.blocks(128)))

"""Fleet control plane (fleet/controller.py + placement.py +
replica.py, ISSUE 16): warm-panel bin packing, snapshot transports
(stats payload + Prometheus text), the controller's failure matrix
(crash / hang / stale scrape) with bounded-backoff respawn and the
flap breaker, autoscale up/down with the min/max floor and ceiling,
graceful preemption, the atomic controller.json ledger, and the two
fault sites registered this PR (controller.scrape, controller.spawn).

The satellites ride here too: `/readyz` on both HTTP fronts, the
validated `--drain-timeout-s` / `--loadgen-seed` serve flags, the
zero-admitted-requests-lost contract across a replica kill and a
SIGTERM drain mid-hedged-traffic, and the seeded BurstSchedule /
hedge-delay determinism behind `--loadgen-seed`.
"""

import json
import os
import sys
import threading
import time
from types import SimpleNamespace

import numpy as np
import pytest

from spark_examples_tpu.core import faults, telemetry
from spark_examples_tpu.core.config import (
    PRIORITY_CLASSES,
    ComputeConfig,
    IngestConfig,
    JobConfig,
    ServeConfig,
)
from spark_examples_tpu.fleet import (
    ControllerConfig,
    FleetController,
    LocalReplica,
    ProcessReplica,
    Replica,
    ReplicaSnapshot,
    ScrapeError,
    pack,
    parse_prometheus,
)
from spark_examples_tpu.fleet.controller import LEDGER_KEEP
from spark_examples_tpu.fleet.placement import Placement, rebalance_needed
from spark_examples_tpu.fleet.replica import (
    snapshot_from_prometheus,
    snapshot_from_stats,
)
from spark_examples_tpu.ingest.source import ArraySource
from spark_examples_tpu.pipelines.jobs import pcoa_job, variants_pca_job
from spark_examples_tpu.pipelines.project import pcoa_project_job
from spark_examples_tpu.serve import (
    DRAINING,
    BurstSchedule,
    FleetManifest,
    ServerClosed,
    build_fleet,
    run_hedged_loadgen,
)
from spark_examples_tpu.serve.loadgen import _HedgeDelay
from tests.conftest import random_genotypes

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)  # tools/ is repo tooling, not an installed pkg

BV = 128
N, V = 12, 256
PANEL_BYTES = N * V
INTERACTIVE, BATCH = PRIORITY_CLASSES


@pytest.fixture(autouse=True)
def _fresh_telemetry():
    telemetry.reset()
    yield
    telemetry.reset()
    telemetry.configure(dir=None)


# ---------------------------------------------------------- placement


def test_pack_first_fit_decreasing_and_lookups():
    p = pack({"big": 70, "mid": 40, "small": 20},
             {"r0": 100, "r1": 60})
    assert p.assignments["r0"] == ("big", "small")
    assert p.assignments["r1"] == ("mid",)
    assert p.overflow == ()
    assert p.replica_for("mid") == "r1"
    assert p.replica_for("nope") is None
    assert p.routes_for("r0") == ("big", "small")
    assert p.routes_for("ghost") == ()


def test_pack_overflow_and_determinism():
    p = pack({"a": 80, "b": 80, "c": 80}, {"r0": 100, "r1": 100})
    assert p.overflow == ("c",)  # equal sizes tie-break by name
    # Same inputs -> bit-identical packing (the rebalance no-op rule).
    assert pack({"a": 80, "b": 80, "c": 80},
                {"r0": 100, "r1": 100}) == p
    # Negative/zero sizes clamp instead of corrupting budgets.
    q = pack({"z": -5}, {"r0": 0})
    assert q.assignments["r0"] == ("z",)


def test_rebalance_needed_tracks_membership_and_growth():
    panels = {"a": 60, "b": 30}
    budgets = {"r0": 100}
    current = pack(panels, budgets)
    assert not rebalance_needed(current, panels, budgets)
    assert rebalance_needed(current, panels, {"r0": 100, "r1": 100})
    assert rebalance_needed(current, {"a": 60, "b": 50}, {"r0": 100})
    assert rebalance_needed(Placement(), panels, budgets)


# ----------------------------------------------------------- snapshots


def _stats_payload(qi=0, qb=0, in_flight=0, p99_ms=12.0, admitted=9,
                   shed=1):
    return {
        "health": {"status": "healthy", "worker_alive": True,
                   "in_flight": in_flight},
        "queues": {INTERACTIVE: qi, BATCH: qb},
        "pool": {"budget_bytes": 1000, "resident_bytes": 700,
                 "pressure": 0.7, "staged_routes": ["ibs"]},
        "routes": {
            "ibs": {
                "staged": True, "queue_depth": qi,
                "admitted": admitted, "shed": shed,
                "latency_ms": {
                    INTERACTIVE: {"p99": p99_ms},
                    BATCH: {"p99": p99_ms / 2},
                },
            },
        },
    }


def test_snapshot_from_stats_maps_the_autoscale_signals():
    snap = snapshot_from_stats(_stats_payload(qi=5, qb=2, in_flight=1),
                               t=3.0, ready=True)
    assert snap.ready and snap.worker_alive
    assert snap.queue_interactive == 5 and snap.queue_batch == 2
    assert snap.in_flight == 1
    assert snap.p99_s == pytest.approx(0.012)
    assert snap.shed_rate == pytest.approx(0.1)
    assert snap.pool_bytes == 700.0
    assert snap.pool_pressure == pytest.approx(0.7)
    assert snap.routes["ibs"]["staged"] is True
    assert not snap.idle and not snap.stale
    stale = snap.as_stale()
    assert stale.stale and stale.queue_interactive == 5

    idle = snapshot_from_stats(_stats_payload(qi=0, qb=0, in_flight=0),
                               t=4.0, ready=True)
    assert idle.idle


def test_parse_prometheus_skips_garbage_lines():
    flat = parse_prometheus(
        "# HELP x y\n"
        "# TYPE x gauge\n"
        "fleet_pool_bytes 700\n"
        "serve_in_flight 2\n"
        "not-a-number-line abc\n"
        "  \n"
        "loneword\n"
        "fleet_route_r_ibs_p99_s 0.034\n")
    assert flat == {"fleet_pool_bytes": 700.0, "serve_in_flight": 2.0,
                    "fleet_route_r_ibs_p99_s": 0.034}


def test_snapshot_from_prometheus_unmangles_route_series():
    flat = {
        "serve_in_flight": 1.0,
        "serve_priority_depth_interactive": 4.0,
        "serve_priority_depth_batch": 7.0,
        "fleet_pool_bytes": 600.0,
        "fleet_pool_pressure": 0.6,
        "fleet_route_r_ibs_p99_s": 0.05,
        "fleet_route_r_ibs_shed_rate": 0.25,
        "fleet_route_r_ibs_staged": 1.0,
        "fleet_route_r_ibs_queue_depth": 3.0,
    }
    snap = snapshot_from_prometheus(flat, ["r-ibs"], t=1.0, ready=True)
    assert snap.queue_interactive == 4 and snap.queue_batch == 7
    assert snap.p99_s == pytest.approx(0.05)
    assert snap.shed_rate == pytest.approx(0.25)
    assert snap.routes["r-ibs"]["staged"] is True
    assert snap.routes["r-ibs"]["queue_depth"] == 3
    assert snap.pool_bytes == 600.0


# ------------------------------------------------------ config contract


def test_controller_config_validation_names_the_knob():
    with pytest.raises(ValueError, match="max_replicas"):
        ControllerConfig(min_replicas=4, max_replicas=2)
    with pytest.raises(ValueError, match="interval_s"):
        ControllerConfig(interval_s=0.0)
    with pytest.raises(ValueError, match="backoff_max_s"):
        ControllerConfig(backoff_initial_s=2.0, backoff_max_s=1.0)
    with pytest.raises(ValueError, match="--drain-timeout-s"):
        ControllerConfig(drain_timeout_s=0.0)
    with pytest.raises(ValueError, match="stale_scrapes"):
        ControllerConfig(stale_scrapes=True)  # bools are not numbers


def test_serve_config_drain_and_seed_flags_validated():
    cfg = ServeConfig(drain_timeout_s=5.0, loadgen_seed=42)
    assert cfg.drain_timeout_s == 5.0 and cfg.loadgen_seed == 42
    with pytest.raises(ValueError, match="--drain-timeout-s"):
        ServeConfig(drain_timeout_s=0.0)
    with pytest.raises(ValueError, match="--loadgen-seed"):
        ServeConfig(loadgen_seed=-1)
    with pytest.raises(ValueError, match="--loadgen-seed"):
        ServeConfig(loadgen_seed=1.5)


# --------------------------------------------- the controller, faked out


def _snap(ready=True, qi=0, qb=0, in_flight=0, p99=0.0):
    return ReplicaSnapshot(
        t=0.0, ready=ready, health="healthy", worker_alive=True,
        in_flight=in_flight, queue_interactive=qi, queue_batch=qb,
        p99_s=p99, shed_rate=0.0, pool_bytes=0.0, pool_pressure=0.0)


class FakeReplica(Replica):
    """A scriptable replica: the controller's failure matrix without
    engines, sockets, or clocks."""

    def __init__(self, name, generation=0, budget_bytes=1000):
        self.name = name
        self.generation = generation
        self.budget_bytes = budget_bytes
        self.warm_routes = ()
        self.snap = _snap()
        self.scrape_exc = None
        self.warm_exc = None
        self.hb_age = None
        self.dead = False
        self.killed = False
        self.drain_calls = []
        self.drain_clean = True
        self.warm_calls = []

    def start(self):
        return self

    def alive(self):
        return not self.dead and not self.killed

    def heartbeat_age_s(self):
        return self.hb_age

    def ready(self):
        return self.snap.ready

    def scrape(self):
        if self.scrape_exc is not None:
            raise self.scrape_exc
        return self.snap

    def warm(self, routes):
        if self.warm_exc is not None:
            raise self.warm_exc
        self.warm_routes = tuple(routes)
        self.warm_calls.append(tuple(routes))

    def drain(self, timeout_s):
        self.drain_calls.append(timeout_s)
        self.dead = True
        return self.drain_clean

    def kill(self):
        self.killed = True


class Harness:
    """Controller + injected clock + scripted factory."""

    def __init__(self, ledger=None, **cfg_kw):
        self.clk = [0.0]
        self.made = []
        self.fail_spawns = 0
        self.warm_fail_next = False
        defaults = dict(
            min_replicas=2, max_replicas=3, idle_rounds=10_000,
            pressure_rounds=2, stale_scrapes=2, hang_heartbeat_s=5.0,
            backoff_initial_s=0.5, backoff_max_s=4.0,
            flap_window_s=100.0, flap_max_respawns=3,
            drain_timeout_s=7.0, ledger_path=ledger,
        )
        defaults.update(cfg_kw)
        # Budgets fit exactly one route per replica: a -> slot 0,
        # b -> slot 1 (FFD with 1000-byte budgets).
        self.ctrl = FleetController(
            self._factory, {"a": 600, "b": 500},
            ControllerConfig(**defaults), clock=lambda: self.clk[0])

    def _factory(self, name, generation):
        if self.fail_spawns > 0:
            self.fail_spawns -= 1
            raise RuntimeError("spawn denied by harness")
        r = FakeReplica(name, generation)
        if self.warm_fail_next:
            self.warm_fail_next = False
            r.warm_exc = RuntimeError("warm denied by harness")
        self.made.append(r)
        return r

    def tick(self, dt=1.0):
        self.clk[0] += dt
        return self.ctrl.step()


def test_bootstrap_spawns_min_replicas_with_placement(tmp_path):
    ledger = str(tmp_path / "controller.json")
    h = Harness(ledger=ledger)
    h.ctrl.start()
    assert len(h.ctrl.replicas()) == 2
    # FFD placement handed each replica its warm set at spawn.
    assert h.made[0].warm_routes == ("a",)
    assert h.made[1].warm_routes == ("b",)
    with open(ledger) as f:
        led = json.load(f)
    assert [s["state"] for s in led["slots"]] == ["up", "up"]
    h.ctrl.close()
    with open(ledger) as f:
        led = json.load(f)
    assert [s["state"] for s in led["slots"]] == ["retired", "retired"]
    assert h.made[0].drain_calls == [7.0]  # the configured drain budget


def test_crash_backs_off_then_respawns():
    h = Harness()
    h.ctrl.start()
    h.made[0].dead = True
    h.tick()
    desc = h.ctrl.describe()
    assert desc["slots"][0]["state"] == "backoff"
    assert any(x["kind"] == "crash" for x in desc["incidents"])
    assert len(h.ctrl.replicas()) == 1
    h.tick(0.1)  # inside the 0.5s backoff: still down
    assert h.ctrl.describe()["slots"][0]["state"] == "backoff"
    h.tick(0.5)  # past it: respawned, next generation
    assert h.ctrl.describe()["slots"][0]["state"] == "up"
    assert len(h.ctrl.replicas()) == 2
    assert h.made[-1].generation == 1
    assert h.made[-1].warm_routes == ("a",)  # placement survives death
    assert telemetry.counter_value("controller.respawns") == 1
    assert any(d["action"] == "respawn" for d in
               h.ctrl.describe()["decisions"])


def test_hang_is_killed_then_respawned():
    h = Harness()
    h.ctrl.start()
    h.made[1].hb_age = 9.0  # budget is 5s
    h.tick()
    assert h.made[1].killed  # TERM'd the zombie before respawning
    desc = h.ctrl.describe()
    assert desc["slots"][1]["state"] == "backoff"
    assert any(x["kind"] == "hang" for x in desc["incidents"])
    h.tick(1.0)
    assert len(h.ctrl.replicas()) == 2


def test_stale_scrape_serves_last_good_then_declares_lost():
    h = Harness()  # stale_scrapes=2
    h.ctrl.start()
    h.made[0].snap = _snap(qi=3)
    h.tick()  # a good scrape lands the snapshot
    h.made[0].scrape_exc = ScrapeError("blackholed /metrics")
    h.tick()
    desc = h.ctrl.describe()
    # First failure: still up, acting on last-good-marked-stale.
    assert desc["slots"][0]["state"] == "up"
    assert desc["slots"][0]["stale"] is True
    assert telemetry.counter_value("controller.scrape_stale") == 1
    h.tick()  # second consecutive failure: the budget is spent
    desc = h.ctrl.describe()
    assert desc["slots"][0]["state"] == "backoff"
    assert h.made[0].killed
    assert any(x["kind"] == "stale" for x in desc["incidents"])
    assert telemetry.counter_value("controller.scrapes") >= 1


def test_startup_grace_tolerates_unscrapable_fresh_replica():
    """A process replica binds its scrape port seconds after spawn:
    failed scrapes on a never-scraped replica inside startup_grace_s
    are startup, not loss — but an expired grace declares loss on the
    next round (a replica that never comes up is not grandfathered)."""
    h = Harness(startup_grace_s=10.0)  # stale_scrapes=2
    h.ctrl.start()
    for r in h.made:  # unscrapable from birth (still binding)
        r.scrape_exc = ScrapeError("connection refused")
    h.tick()
    h.tick()  # 2 failures > stale_scrapes, but inside the grace
    desc = h.ctrl.describe()
    assert desc["slots"][0]["state"] == "up"
    assert not h.made[0].killed
    assert telemetry.counter_value("controller.scrape_stale") >= 2
    h.made[0].scrape_exc = None  # slot 0 comes up late but fine
    h.tick()
    assert h.ctrl.describe()["slots"][0]["stale"] is False
    h.tick(11.0)  # slot 1 never answers: grace expired -> lost
    desc = h.ctrl.describe()
    assert desc["slots"][1]["state"] == "backoff"
    assert h.made[1].killed
    assert any(x["kind"] == "stale" for x in desc["incidents"])
    with pytest.raises(ValueError, match="startup_grace_s"):
        ControllerConfig(startup_grace_s=-1.0)


def test_flap_breaker_parks_a_dying_slot_and_resets():
    h = Harness(backoff_initial_s=0.0, flap_max_respawns=3,
                flap_window_s=1000.0)
    h.ctrl.start()
    for _ in range(10):
        if h.ctrl.describe()["slots"][0]["state"] == "parked":
            break
        for r in h.made:
            if r.name == "replica-0":
                r.dead = True
        h.tick()
    desc = h.ctrl.describe()
    assert desc["slots"][0]["state"] == "parked"
    assert any(x["kind"] == "flap_breaker" for x in desc["incidents"])
    assert telemetry._gauges["controller.flap_breaker_open"]["last"] == 1.0
    # Parked stays parked — no spawn loop.
    made_before = len(h.made)
    h.tick()
    h.tick()
    assert len(h.made) == made_before
    # Operator override: reset, next round respawns.
    assert h.ctrl.reset_flap_breaker("replica-0") is True
    assert h.ctrl.reset_flap_breaker("replica-0") is False
    h.tick()
    assert h.ctrl.describe()["slots"][0]["state"] == "up"
    assert len(h.ctrl.replicas()) == 2


def test_scale_up_needs_sustained_pressure_and_respects_ceiling():
    h = Harness()  # pressure_rounds=2, max_replicas=3
    h.ctrl.start()
    for r in h.made:
        r.snap = _snap(qi=10)  # depth/ready = 10 >= trigger 4
    h.tick()
    assert len(h.ctrl.replicas()) == 2  # one round is not sustained
    h.tick()
    assert len(h.ctrl.replicas()) == 3
    assert telemetry.counter_value("controller.scale_ups") == 1
    assert any(d["action"] == "scale_up"
               for d in h.ctrl.describe()["decisions"])
    # Ceiling: pressure continues, no fourth replica.
    for r in h.made:
        r.snap = _snap(qi=10)
    h.tick()
    h.tick()
    h.tick()
    assert len(h.ctrl.replicas()) == 3


def test_idle_retire_drains_lifo_down_to_the_floor():
    h = Harness(min_replicas=1, max_replicas=2, pressure_rounds=1,
                idle_rounds=2)
    h.ctrl.start()  # floor 1: starts one replica
    h.made[0].snap = _snap(qi=10)
    h.tick()  # pressure_rounds=1: scale to 2
    assert len(h.ctrl.replicas()) == 2
    for r in h.made:
        r.snap = _snap(qi=0)
    h.tick()
    assert len(h.ctrl.replicas()) == 2  # idle 1 round of 2
    h.tick()
    assert len(h.ctrl.replicas()) == 1  # newest drained (LIFO)
    retired = h.ctrl.describe()["slots"][1]
    assert retired["state"] == "retired"
    assert h.made[-1].drain_calls == [7.0]
    assert telemetry.counter_value("controller.retires") == 1
    # The floor holds no matter how long the idle stretch runs.
    for _ in range(5):
        h.tick()
    assert len(h.ctrl.replicas()) == 1


def test_preempt_drains_within_budget_and_respawns_immediately():
    h = Harness()
    h.ctrl.start()
    victim = h.made[0]
    assert h.ctrl.preempt("replica-0") is True
    assert victim.drain_calls == [7.0]
    # No backoff: the slot came straight back up, next generation.
    assert len(h.ctrl.replicas()) == 2
    assert h.ctrl.describe()["slots"][0]["state"] == "up"
    assert h.made[-1].generation == 1
    assert telemetry.counter_value("controller.preemptions") == 1
    assert h.ctrl.preempt("replica-99") is False
    # A drain past its budget is an incident, not a hang.
    h.made[-1].drain_clean = False
    assert h.ctrl.preempt("replica-0") is True
    assert any(x["kind"] == "dirty_preempt"
               for x in h.ctrl.describe()["incidents"])


def test_spawn_failure_backs_off_and_tears_down_half_starts():
    h = Harness()
    h.fail_spawns = 1
    h.ctrl.start()  # slot 0's bootstrap spawn fails
    desc = h.ctrl.describe()
    assert desc["slots"][0]["state"] == "backoff"
    assert any(x["kind"] == "spawn_failure" for x in desc["incidents"])
    assert len(h.ctrl.replicas()) == 1
    h.tick(1.0)  # past the backoff: healed
    assert len(h.ctrl.replicas()) == 2
    # A replica that started but failed to warm must not leak its
    # worker: the controller kills the half-start.
    h.warm_fail_next = True
    cur = next(r for r in h.made
               if r.name == "replica-0" and r.alive())
    cur.dead = True
    h.tick()         # crash detected
    h.tick(1.0)      # respawn attempt -> warm fails
    half = h.made[-1]
    assert half.warm_exc is not None and half.killed
    assert h.ctrl.describe()["slots"][0]["state"] == "backoff"
    h.tick(2.0)      # doubled backoff elapsed: healed for real
    assert len(h.ctrl.replicas()) == 2


def test_ledger_is_atomic_json_and_bounded(tmp_path):
    ledger = str(tmp_path / "controller.json")
    h = Harness(ledger=ledger, backoff_initial_s=0.0,
                flap_max_respawns=10_000, flap_window_s=0.5)
    h.ctrl.start()
    # A crash costs two ticks (detect, respawn) — run enough cycles
    # that the incident stream overflows the ledger's retention.
    for _ in range(2 * LEDGER_KEEP + 60):
        h.made[-1].dead = True
        h.tick()
    with open(ledger) as f:
        led = json.load(f)  # parses after every rewrite: atomic
    assert len(led["incidents"]) <= LEDGER_KEEP
    assert len(led["decisions"]) <= LEDGER_KEEP
    assert led["rounds"] == h.ctrl.rounds
    assert telemetry.counter_value("controller.incidents") > LEDGER_KEEP
    h.ctrl.close()


def test_step_survives_a_bad_round_in_run_loop():
    h = Harness(interval_s=0.01)
    h.ctrl.start()
    h.made[0].scrape_exc = RuntimeError("not a ScrapeError")
    h.ctrl.run()
    try:
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            h.clk[0] += 0.01
            if any(x["kind"] == "step_error"
                   for x in h.ctrl.describe()["incidents"]):
                break
            time.sleep(0.005)
    finally:
        h.ctrl.close()
    assert any(x["kind"] == "step_error"
               for x in h.ctrl.describe()["incidents"])


# ----------------------------------------------------- the fault sites


def test_controller_scrape_fault_marks_stale_then_recovers():
    h = Harness(stale_scrapes=3)
    h.ctrl.start()
    h.tick()  # good scrapes land last-good snapshots
    with faults.armed(["controller.scrape:io_error:after=0:max=1"],
                      seed=11) as inj:
        h.tick()
        assert inj.fire_count("controller.scrape") == 1
    desc = h.ctrl.describe()
    assert desc["slots"][0]["stale"] is True
    assert desc["slots"][0]["state"] == "up"  # within the budget
    assert telemetry.counter_value("controller.scrape_stale") == 1
    h.tick()  # disarmed: the next scrape clears the failure streak
    assert h.ctrl.describe()["slots"][0]["stale"] is False
    assert len(h.ctrl.replicas()) == 2


def test_controller_spawn_fault_cascade_backs_off_and_heals():
    h = Harness(backoff_initial_s=0.5)
    with faults.armed(["controller.spawn:io_error:after=0:max=1"],
                      seed=11) as inj:
        h.ctrl.start()  # first spawn eats the injected failure
        assert inj.fire_count("controller.spawn") == 1
        desc = h.ctrl.describe()
        assert desc["slots"][0]["state"] == "backoff"
        assert any(x["kind"] == "spawn_failure"
                   for x in desc["incidents"])
        assert len(h.ctrl.replicas()) == 1
        h.tick(1.0)  # still armed (max=1 spent): respawn succeeds
        assert len(h.ctrl.replicas()) == 2


def test_soak_registers_controller_scenarios_and_thread_prefix():
    """Satellite 6: the soak's scenario table carries the controller
    sites and the thread-hygiene table knows the controller loop's
    thread family (graftlint parses _SUSPECT_THREADS for prefixes)."""
    from tools.soak import _SUSPECT_THREADS, SCENARIOS

    jobs = {j for j, *_ in SCENARIOS}
    assert "controller" in jobs
    sites = {site for j, site, *_ in SCENARIOS if j == "controller"}
    assert sites == {"controller.scrape", "controller.spawn",
                     "fleet.stage", "trace.export"}
    assert "fleet-controller" in _SUSPECT_THREADS
    assert "fleet-metrics-http" in _SUSPECT_THREADS


# --------------------------------------------- real replicas + readiness


@pytest.fixture(scope="module")
def fx(tmp_path_factory):
    """Two fitted routes (ibs PCoA + shared-alt PCA) over one
    compacted store — the controller integration panel."""
    from spark_examples_tpu.store.writer import compact

    base = tmp_path_factory.mktemp("controller_fixture")
    rng = np.random.default_rng(42)
    g = random_genotypes(rng, n=N, v=V, missing_rate=0.1)
    store = str(base / "store")
    compact(store, ArraySource(g), chunk_variants=64)
    routes = {}
    for name, fit, metric in (("r-ibs", pcoa_job, "ibs"),
                              ("r-pca", variants_pca_job, None)):
        model = str(base / f"model_{name}.npz")
        job = JobConfig(
            ingest=IngestConfig(block_variants=BV),
            compute=ComputeConfig(metric=metric, num_pc=3),
            model_path=model,
        )
        fit(job, source=ArraySource(g))
        routes[name] = SimpleNamespace(
            name=name, genotypes=g, store=store, model=model, job=job)
    return SimpleNamespace(base=base, routes=routes, genotypes=g)


def _build(fx, budget_mb=1.0, cfg=None):
    manifest = FleetManifest.parse({
        "budget_mb": budget_mb,
        "routes": [
            {"name": r.name, "model": r.model,
             "source": f"store:{r.store}"}
            for r in fx.routes.values()
        ],
    })
    return build_fleet(
        manifest, cfg or ServeConfig(cache_entries=0),
        ingest_defaults=IngestConfig(block_variants=BV,
                                     readahead_chunks=0))


def _offline(route, query):
    return pcoa_project_job(
        route.job.replace(model_path=None), model_path=route.model,
        source_new=ArraySource(query[None, :]),
        source_ref=ArraySource(route.genotypes),
    ).coords


def test_router_ready_info_transitions(fx):
    fleet = _build(fx)
    info = fleet.ready_info()
    assert info["ready"] is False and info["worker_alive"] is False
    fleet.start()
    assert fleet.ready_info()["ready"] is True
    try:
        fleet.warm_route("r-ibs")
        info = fleet.ready_info()
        assert info["ready"] is True
        assert info["warmed_routes"] == ["r-ibs"]
        assert info["unstaged_routes"] == []
    finally:
        fleet.drain()
        info = fleet.ready_info()
        assert info["ready"] is False and info["draining"] is True
        fleet.close()


def test_http_readyz_and_warm_endpoints(fx):
    import urllib.error
    import urllib.request

    from spark_examples_tpu.serve.http import start_fleet_http_server

    fleet = _build(fx).start()
    http = start_fleet_http_server(fleet, port=0)
    base = f"http://127.0.0.1:{http.port}"
    try:
        with urllib.request.urlopen(f"{base}/readyz", timeout=30) as r:
            body = json.loads(r.read())
        assert r.status == 200 and body["ready"] is True
        # /warm/<route> is the controller's staging hook.
        with urllib.request.urlopen(f"{base}/warm/r-pca",
                                    timeout=60) as r:
            assert json.loads(r.read()) == {"warmed": "r-pca"}
        assert fleet.pool.is_staged("r-pca")
        with pytest.raises(urllib.error.HTTPError) as err:
            urllib.request.urlopen(f"{base}/warm/nope", timeout=30)
        assert err.value.code == 404
        # Draining flips readiness to 503 while /healthz keeps talking.
        fleet.drain()
        with pytest.raises(urllib.error.HTTPError) as err:
            urllib.request.urlopen(f"{base}/readyz", timeout=30)
        assert err.value.code == 503
        assert json.loads(err.value.read())["draining"] is True
        with urllib.request.urlopen(f"{base}/healthz", timeout=30) as r:
            assert json.loads(r.read())["status"] == DRAINING
    finally:
        http.shutdown()
        fleet.close()


def test_single_model_server_readyz(fx):
    from spark_examples_tpu.serve import ProjectionEngine, ProjectionServer

    route = fx.routes["r-ibs"]
    engine = ProjectionEngine(route.model, ArraySource(route.genotypes),
                              block_variants=BV, max_batch=4)
    server = ProjectionServer(engine, drain_timeout_s=5.0)
    assert server.ready_info()["ready"] is False
    server.start()
    try:
        assert server.ready_info()["ready"] is True
    finally:
        server.drain()  # uses the configured 5s budget
        info = server.ready_info()
        assert info["ready"] is False and info["draining"] is True
        server.close()


def _local_replica(fx, name, cfg=None):
    return LocalReplica(
        name, lambda: _build(fx, cfg=cfg).start(),
        budget_bytes=2 * PANEL_BYTES)


def test_zero_admitted_requests_lost_on_replica_kill(fx):
    """The tentpole's chaos proof in miniature: kill the primary under
    hedged load — every admitted request is answered (failovers, never
    errors), and the survivor still serves bit-identical coordinates."""
    r0 = _local_replica(fx, "replica-0").start()
    r1 = _local_replica(fx, "replica-1").start()
    rng = np.random.default_rng(3)
    pool = random_genotypes(rng, n=8, v=V, missing_rate=0.1)
    box = {}

    def _drive():
        box["report"] = run_hedged_loadgen(
            [r0.router, r1.router], pool, clients=2,
            requests_per_client=10, route="r-ibs",
            hedge_floor_s=0.005, result_timeout_s=120.0, seed=5)

    t = threading.Thread(target=_drive, name="loadgen-client-driver",
                         daemon=True)
    t.start()
    time.sleep(0.05)
    r0.kill()
    t.join(timeout=120.0)
    report = box["report"]
    try:
        assert report["errors"] == 0
        assert report["completed"] == 20
        assert report["failovers"] >= 1
        assert not r0.alive() and r1.alive()
        q = pool[0]
        got = r1.router.project("r-ibs", q, timeout=120.0)
        np.testing.assert_array_equal(
            got, _offline(fx.routes["r-ibs"], q).astype(np.float32))
    finally:
        r1.drain(30.0)


def test_sigterm_drain_with_inflight_hedged_requests(fx):
    """Satellite 4: drain a fleet replica mid-hedged-traffic. Every
    admitted request is answered, the drain gauge shows the state, and
    nothing is silently dropped."""
    slow_cfg = ServeConfig(cache_entries=0, max_linger_ms=20.0)
    r0 = _local_replica(fx, "replica-0", cfg=slow_cfg).start()
    r1 = _local_replica(fx, "replica-1", cfg=slow_cfg).start()
    box = {}
    rng = np.random.default_rng(4)
    pool = random_genotypes(rng, n=8, v=V, missing_rate=0.1)

    def _drive():
        box["report"] = run_hedged_loadgen(
            [r0.router, r1.router], pool, clients=2,
            requests_per_client=8, route="r-ibs",
            hedge_floor_s=0.01, result_timeout_s=120.0, seed=6)

    t = threading.Thread(target=_drive, name="loadgen-client-driver",
                         daemon=True)
    t.start()
    time.sleep(0.05)
    clean = r0.drain(30.0)  # the SIGTERM path for a local replica
    t.join(timeout=120.0)
    report = box["report"]
    try:
        assert clean is True
        assert report["errors"] == 0
        assert report["completed"] == 16
        # The drained replica advertised its state on the way down.
        assert telemetry._gauges["serve.health"]["max"] == 2.0  # DRAINING
        assert not r0.alive()
    finally:
        r1.drain(30.0)


def test_drain_reports_requests_abandoned_at_deadline(fx):
    """Satellite 3: requests still queued when the drain deadline
    expires are failed loudly (ServerClosed) and counted as
    serve.drain_abandoned — never a silent drop. A never-started
    worker makes the straggler set exact: every admitted request hits
    the deadline."""
    fleet = _build(fx)  # admission open, worker never started
    rng = np.random.default_rng(8)
    futs = [fleet.submit("r-ibs",
                         random_genotypes(rng, n=1, v=V,
                                          missing_rate=0.1)[0],
                         priority=INTERACTIVE)
            for _ in range(6)]
    assert fleet.drain(timeout=0.0) is False
    for f in futs:
        with pytest.raises(ServerClosed):
            f.result(timeout=120.0)
    assert telemetry.counter_value("serve.drain_abandoned") == 6
    fleet.close()


def test_controller_over_local_replicas_end_to_end(fx, tmp_path):
    """The tentpole integration: bootstrap with placement, kill ->
    respawn within the backoff budget, preempt -> drain + immediate
    respawn, ledger tells the story, and recovered replicas serve
    bit-identically."""
    ledger = str(tmp_path / "controller.json")

    def factory(name, generation):
        return LocalReplica(name, lambda: _build(fx).start(),
                            budget_bytes=2 * PANEL_BYTES,
                            generation=generation)

    ctrl = FleetController(
        factory, {"r-ibs": PANEL_BYTES, "r-pca": PANEL_BYTES},
        ControllerConfig(
            min_replicas=2, max_replicas=3, idle_rounds=10_000,
            stale_scrapes=2, backoff_initial_s=0.01, backoff_max_s=0.5,
            flap_window_s=60.0, flap_max_respawns=10,
            drain_timeout_s=30.0, ledger_path=ledger,
        ))
    try:
        ctrl.start()
        assert len(ctrl.replicas()) == 2
        assert ctrl.ready_count() == 0  # no scrape yet
        ctrl.step()
        assert ctrl.ready_count() == 2
        # Kill -> detect -> respawn within the backoff budget.
        ctrl.replicas()[0].kill()
        deadline = time.monotonic() + 15.0
        while time.monotonic() < deadline:
            ctrl.step()
            reps = ctrl.replicas()
            if len(reps) == 2 and all(r.alive() for r in reps):
                break
            time.sleep(0.02)
        reps = ctrl.replicas()
        assert len(reps) == 2 and all(r.alive() for r in reps)
        # Preempt: drained gracefully, respawned immediately.
        assert ctrl.preempt("replica-1") is True
        assert len(ctrl.replicas()) == 2
        # Bit-identity after both recoveries, on every replica.
        rng = np.random.default_rng(9)
        q = random_genotypes(rng, n=1, v=V, missing_rate=0.1)[0]
        want = _offline(fx.routes["r-ibs"], q).astype(np.float32)
        for replica in ctrl.replicas():
            np.testing.assert_array_equal(
                replica.router.project("r-ibs", q, timeout=120.0), want)
    finally:
        ctrl.close()
    with open(ledger) as f:
        led = json.load(f)
    actions = {d["action"] for d in led["decisions"]}
    assert {"respawn", "preempt"} <= actions
    assert any(x["kind"] == "crash" for x in led["incidents"])


# ------------------------------------------------- ProcessReplica bits


def test_process_replica_plumbing(tmp_path):
    from spark_examples_tpu.core import supervisor

    r = ProcessReplica(
        "replica-0", argv=["true"], workdir=str(tmp_path),
        budget_bytes=1000, route_names=["r-ibs"])
    assert r.argv[-2:] == ["--port-file", r.port_file]
    assert r.env[supervisor.ENV_HEARTBEAT] == r.heartbeat_path
    assert r.port() is None  # nothing announced yet
    assert r.heartbeat_age_s() is None  # startup, not a hang
    assert r.alive() is False
    assert r.drain(1.0) is True  # never started: trivially clean
    # Warm before the port is announced DEFERS (records intent): a
    # spawn warms immediately after Popen, and the serve child stages
    # panels lazily on demand anyway — raising here turned every slow
    # process start into a spawn_failure -> flap-breaker park.
    r.warm(("r-ibs",))
    assert r.warm_routes == ("r-ibs",)
    with open(r.port_file, "w") as f:
        json.dump({"port": 4242}, f)
    assert r.port() == 4242
    with pytest.raises(ScrapeError, match="/metrics"):
        r.scrape()  # nothing listening on the announced port
    with pytest.raises(ScrapeError):
        r.warm(("r-ibs",))  # port known: a failed warm is a failure


# --------------------------------------------- seeded load (satellite 2)


def test_burst_schedule_is_deterministic_and_validated():
    a = BurstSchedule(duration_s=10.0, base_qps=5.0, seed=7)
    b = BurstSchedule(duration_s=10.0, base_qps=5.0, seed=7)
    assert a.bursts == b.bursts
    assert a.arrivals() == b.arrivals()
    c = BurstSchedule(duration_s=10.0, base_qps=5.0, seed=8)
    assert c.arrivals() != a.arrivals()
    assert all(0.0 < t < 10.0 for t in a.arrivals())
    # Inside a burst window the rate is the diurnal rate times the
    # burst factor.
    lo, _hi = a.bursts[0]
    base_rate = 5.0 * (1.0 + 0.3 * np.sin(2.0 * np.pi * lo / 10.0))
    assert a.rate_at(lo) == pytest.approx(base_rate * 6.0)
    with pytest.raises(ValueError, match="bad burst schedule"):
        BurstSchedule(duration_s=0.0, base_qps=5.0)
    with pytest.raises(ValueError, match="burst_factor"):
        BurstSchedule(duration_s=1.0, base_qps=5.0, burst_factor=0.5)


def test_hedge_delay_seed_precharges_the_ring():
    seeded = _HedgeDelay(0.01, seed=42)
    again = _HedgeDelay(0.01, seed=42)
    assert seeded.delay_s() == again.delay_s()
    assert seeded.delay_s() >= 0.01  # floor always holds
    # Unseeded: floor until min_samples arrive (no prior to replay).
    cold = _HedgeDelay(0.01)
    assert cold.delay_s() == 0.01
    # The prior drains out as real samples land: record a slow tail
    # and the p95 takes over.
    for _ in range(256):
        seeded.record(0.5)
    assert seeded.delay_s() == pytest.approx(0.5)


# ------------------------------- flight recorder rides the controller


def _route_snap(p99=0.01, shed=0.0, route="a"):
    return ReplicaSnapshot(
        t=0.0, ready=True, health="healthy", worker_alive=True,
        in_flight=0, queue_interactive=0, queue_batch=0, p99_s=p99,
        shed_rate=shed, pool_bytes=0.0, pool_pressure=0.0,
        routes={route: {"p99_s": p99, "queue_depth": 0,
                        "shed_rate": shed, "staged": True}})


def test_slo_breach_scales_up_within_the_same_round(tmp_path):
    """ISSUE 17 acceptance: an injected latency regression trips the
    fast-burn SLO AND the scale-up inside one control round — the
    breach bypasses the (deliberately unreachable) pressure_rounds
    gate."""
    from spark_examples_tpu.fleet.slo import SLOSpec

    ledger = str(tmp_path / "controller.json")
    h = Harness(ledger=ledger, pressure_rounds=99,
                slos=(SLOSpec(route="a", p99_ms=5.0,
                              fast_window_s=30.0, slow_window_s=30.0),))
    h.ctrl.start()
    for r in h.made:
        r.snap = _route_snap(p99=0.2)  # 40x over the objective
    rounds_to_trip = 0
    while len(h.ctrl.replicas()) < 3 and rounds_to_trip < 10:
        h.tick()
        rounds_to_trip += 1
    led = h.ctrl.describe()
    breach = next(i for i in led["incidents"]
                  if i["kind"] == "slo_breach")
    assert breach["who"] == "a" and "p99<=5" in breach["detail"]
    scale = next(d for d in led["decisions"]
                 if d["action"] == "scale_up")
    assert scale["detail"].startswith("slo breach pressure (this round)")
    # Same round: the breach incident and the scale-up decision carry
    # the SAME round number — the controller did not wait a tick.
    assert scale["round"] == breach["round"]
    assert len(h.ctrl.replicas()) == 3
    # The breach is visible on the metrics surface too.
    gauges = telemetry.metrics_snapshot()["gauges"]
    assert gauges["slo.a.breached"]["last"] == 1.0
    h.ctrl.close()


def test_healthy_fleet_never_trips_slo_pressure(tmp_path):
    from spark_examples_tpu.fleet.slo import SLOSpec

    h = Harness(pressure_rounds=99,
                slos=(SLOSpec(route="a", p99_ms=500.0,
                              fast_window_s=30.0, slow_window_s=30.0),))
    h.ctrl.start()
    for r in h.made:
        r.snap = _route_snap(p99=0.01)
    for _ in range(6):
        h.tick()
    assert len(h.ctrl.replicas()) == 2
    assert not any(i["kind"] == "slo_breach"
                   for i in h.ctrl.describe()["incidents"])
    gauges = telemetry.metrics_snapshot()["gauges"]
    assert gauges["slo.ok"]["last"] == 1.0
    h.ctrl.close()


def test_timeline_ring_lands_beside_the_ledger(tmp_path):
    from spark_examples_tpu.fleet.timeline import read_timeline

    ledger = str(tmp_path / "controller.json")
    h = Harness(ledger=ledger)
    h.ctrl.start()
    for _ in range(3):
        h.tick()
    h.made[0].dead = True
    h.tick()  # crash -> incident -> timeline marker
    recs = read_timeline(str(tmp_path / "timeline.jsonl"))
    rounds = [r for r in recs if r["type"] == "round"]
    assert rounds and rounds[-1]["replicas"] >= 1
    assert "replica-0" in rounds[1]["slots"]
    assert any(r["type"] == "marker" and r["kind"] == "crash"
               for r in recs)
    h.ctrl.close()


def test_ledger_rotates_full_generations_to_old(tmp_path):
    ledger = str(tmp_path / "controller.json")
    h = Harness(ledger=ledger)
    h.ctrl.start()
    for i in range(LEDGER_KEEP + 30):
        h.ctrl._incident("replica-0", "probe", f"synthetic #{i}")
    old = ledger + ".old"
    assert os.path.exists(old)
    with open(old) as f:
        gen0 = json.load(f)  # atomic: parses mid-stream
    # The archived generation holds the FULL deque from just before
    # the first drop — nothing silently discarded.
    assert len(gen0["incidents"]) == LEDGER_KEEP
    assert gen0["incidents"][0]["detail"] == "synthetic #0"
    assert telemetry.counter_value("controller.ledger_rotations") == 1
    # One rotation covers the next LEDGER_KEEP drops: no re-rotation
    # until another full generation has rolled through.
    for i in range(LEDGER_KEEP - 30):
        h.ctrl._incident("replica-0", "probe", f"late #{i}")
    assert telemetry.counter_value("controller.ledger_rotations") == 1
    h.ctrl._incident("replica-0", "probe", "tips the second generation")
    assert telemetry.counter_value("controller.ledger_rotations") == 2
    with open(old) as f:
        gen1 = json.load(f)
    assert gen1["incidents"][-1]["detail"] == "late #169"
    h.ctrl.close()


def test_controller_serves_the_fleet_metrics_surface(tmp_path):
    import urllib.request

    h = Harness(ledger=str(tmp_path / "controller.json"))
    h.ctrl.start()
    for r in h.made:
        r.snap = _route_snap(p99=0.02)
    for _ in range(2):
        h.tick()
    port_file = str(tmp_path / "metrics_port.json")
    srv = h.ctrl.serve_metrics(port_file=port_file)
    assert h.ctrl.serve_metrics() is srv  # idempotent
    with open(port_file) as f:
        port = int(f.read())
    assert port == srv.port
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/fleet/metrics", timeout=30) as r:
        prom = r.read().decode()
    assert "timeline_fleet_p99_s" in prom
    assert "timeline_route_a_p99_s" in prom
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/fleet/timeline", timeout=30) as r:
        doc = json.loads(r.read())
    assert any(rec["type"] == "round" for rec in doc["records"])
    h.ctrl.close()  # close() tears the metrics server down too
    with pytest.raises(OSError):
        urllib.request.urlopen(
            f"http://127.0.0.1:{port}/fleet/metrics", timeout=5)

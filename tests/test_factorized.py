"""Servable sketch models (models/factorized.py + the factorized
projection paths): save/load round-trip and the ModelFormatError
ladder, rung-carrying fingerprints, offline/served bit-identity for
both families, and THE PR-19 acceptance chain — a corrected-rung dual
model fitted AND served with every dense N x N allocation site rigged
to explode, through a fleet route whose panel exceeds the pool budget
(>= 2 staged shards per request), bit-identical to the offline
`project` path including immediately after the sharded route's
transient charges evict a co-resident warm panel.
"""

import dataclasses

import numpy as np
import pytest
from types import SimpleNamespace

from spark_examples_tpu.core import telemetry
from spark_examples_tpu.core.config import (
    ComputeConfig,
    IngestConfig,
    JobConfig,
    ServeConfig,
)
from spark_examples_tpu.ingest.source import ArraySource
from spark_examples_tpu.models.factorized import FactorizedModel
from spark_examples_tpu.pipelines.jobs import pcoa_job, variants_pca_job
from spark_examples_tpu.pipelines.project import (
    ModelFormatError,
    load_model,
    pcoa_project_job,
)
from spark_examples_tpu.serve import (
    FleetManifest,
    ProjectionEngine,
    ProjectionServer,
    build_fleet,
)
from tests.conftest import random_genotypes

N = 48
V_BIG, V_WARM = 2048, 512   # big panel shard-stages; warm panel fits
BV = 256
K, RANK, ITERS = 4, 24, 2
BIG_PANEL = N * V_BIG       # 98304 dense int8 bytes
WARM_PANEL = N * V_WARM     # 24576
BUDGET = 40_000             # warm fits; big needs ceil(98304/36864)=3 shards


@pytest.fixture(autouse=True)
def _fresh_telemetry():
    telemetry.reset()
    yield
    telemetry.reset()
    telemetry.configure(dir=None)


def _boom(*a, **k):
    raise AssertionError("N x N allocated on the factorized path")


def _rig_nxn(mp):
    """Rig every dense N x N allocation site to explode (the idiom of
    test_solvers.test_no_nxn_on_the_sketch_path)."""
    from spark_examples_tpu.ops import distances, gram
    from spark_examples_tpu.parallel import gram_sharded

    mp.setattr(gram_sharded, "init_sharded", _boom)
    mp.setattr(gram, "init", _boom)
    mp.setattr(distances, "finalize", _boom)


@pytest.fixture(scope="module")
def fx(tmp_path_factory):
    """Two factorized fits — a corrected-rung dual (pcoa/ibs) model on
    the big panel, FITTED UNDER THE N x N RIG, and a corrected-rung
    pca-family model on the warm panel — plus compacted stores."""
    from spark_examples_tpu.store.writer import compact

    base = tmp_path_factory.mktemp("factorized_fixture")
    rng = np.random.default_rng(19)
    routes = {}
    specs = [
        ("r-big", "pcoa", "ibs", V_BIG),
        ("r-warm", "pca", None, V_WARM),
    ]
    mp = pytest.MonkeyPatch()
    _rig_nxn(mp)
    try:
        for i, (name, kind, metric, v) in enumerate(specs):
            g = random_genotypes(rng, n=N, v=v, missing_rate=0.1)
            store = str(base / f"store_{i}")
            compact(store, ArraySource(g), chunk_variants=BV)
            model = str(base / f"model_{i}.npz")
            job = JobConfig(
                ingest=IngestConfig(block_variants=BV),
                compute=ComputeConfig(metric=metric, num_pc=K,
                                      solver="corrected",
                                      sketch_rank=RANK,
                                      sketch_iters=ITERS),
                model_path=model,
            )
            out = (pcoa_job if kind == "pcoa" else variants_pca_job)(
                job, source=ArraySource(g))
            routes[name] = SimpleNamespace(
                name=name, genotypes=g, store=store, model=model,
                job=job, coords=np.asarray(out.coords))
    finally:
        mp.undo()
    return SimpleNamespace(base=base, routes=routes)


def _offline(route, query) -> np.ndarray:
    """The offline single-query `project` path — the serving
    contract's ground truth (single row: the same jitted finalize
    shape the server runs)."""
    return pcoa_project_job(
        route.job.replace(model_path=None), model_path=route.model,
        source_new=ArraySource(
            query[None, :] if query.ndim == 1 else query),
        source_ref=ArraySource(route.genotypes),
    ).coords


# --------------------------------------------- artifact round-trip


def test_roundtrip_and_digest_carries_rung(fx):
    """Both families load back as validated FactorizedModels, and the
    fingerprint hashes the RUNG PROVENANCE: two fits differing only in
    solver, rank, or probe seed can never share a digest (and so never
    a serving result-cache namespace)."""
    big = load_model(fx.routes["r-big"].model)
    assert isinstance(big, FactorizedModel)
    assert (big.kind, big.family, big.metric) == (
        "factorized", "pcoa", "ibs")
    assert (big.solver, big.rank) == ("corrected", RANK)
    assert big.n_ref == N and len(big.sample_ids) == N
    assert big.scale is not None and big.scale.shape == (N,)
    assert big.colmean.shape == (N,)
    assert big.eigvecs.shape[0] == N
    assert big.eigvecs.shape[1] == big.eigvals.shape[0] <= K

    warm = load_model(fx.routes["r-warm"].model)
    assert (warm.kind, warm.family) == ("factorized", "pca")
    assert warm.scale is None

    d = big.digest()
    assert len(d) == 16 and set(d) <= set("0123456789abcdef")
    assert dataclasses.replace(big, solver="sketch").digest() != d
    assert dataclasses.replace(big, rank=RANK + 8).digest() != d
    assert dataclasses.replace(big, seed=big.seed + 1).digest() != d
    # Reload is stable: the digest is a pure content fingerprint.
    assert load_model(fx.routes["r-big"].model).digest() == d


def test_model_format_error_ladder(fx, tmp_path):
    """Factorized-specific rungs of load_model's error ladder: unknown
    family and missing required fields (incl. the pcoa-only scale
    diagonal) are named ModelFormatErrors, never raw KeyErrors."""
    with np.load(fx.routes["r-big"].model, allow_pickle=False) as z:
        payload = {k: z[k] for k in z.files}

    bad = str(tmp_path / "family.npz")
    np.savez(bad, **{**payload, "family": np.asarray("zca")})
    with pytest.raises(ModelFormatError, match="unknown factorized family"):
        load_model(bad)

    bad = str(tmp_path / "truncated.npz")
    np.savez(bad, **{k: v for k, v in payload.items() if k != "colmean"})
    with pytest.raises(ModelFormatError,
                       match=r"missing required field\(s\).*colmean"):
        load_model(bad)

    bad = str(tmp_path / "noscale.npz")
    np.savez(bad, **{k: v for k, v in payload.items() if k != "scale"})
    with pytest.raises(ModelFormatError,
                       match=r"missing required field\(s\).*scale"):
        load_model(bad)


def test_pca_sketch_rung_is_savable(fx, tmp_path):
    """The single-pass sketch rung is savable for pca-family metrics
    (no correction pass needed for the factor form) — and the saved
    artifact records that rung."""
    r = fx.routes["r-warm"]
    model = str(tmp_path / "sketch.npz")
    variants_pca_job(
        r.job.replace(
            model_path=model,
            compute=dataclasses.replace(r.job.compute, solver="sketch",
                                        sketch_iters=1)),
        source=ArraySource(r.genotypes))
    mdl = load_model(model)
    assert (mdl.kind, mdl.family, mdl.solver) == (
        "factorized", "pca", "sketch")
    # Different rung over the same cohort: different namespace.
    assert mdl.digest() != load_model(r.model).digest()


# ------------------------------------------- serving bit-identity


def test_single_server_bit_identity_both_families(fx, monkeypatch):
    """Each factorized model served through its own ProjectionServer
    answers bit-identically to the offline single-query `project`
    path — with the N x N sites rigged the whole time."""
    _rig_nxn(monkeypatch)
    rng = np.random.default_rng(23)
    for route in fx.routes.values():
        v = route.genotypes.shape[1]
        q = random_genotypes(rng, n=1, v=v, missing_rate=0.1)[0]
        offline = _offline(route, q)
        engine = ProjectionEngine(
            route.model, ArraySource(route.genotypes),
            block_variants=BV, max_batch=4)
        with ProjectionServer(engine, cache_entries=0) as srv:
            np.testing.assert_array_equal(
                srv.project(q, timeout=60), offline)


def test_acceptance_corrected_model_sharded_fleet(fx, monkeypatch):
    """THE PR-19 acceptance chain: the corrected-rung dual model —
    N x N sites rigged to explode for the entire serving session —
    routes through a fleet whose pool budget is smaller than its panel,
    so every request shard-stages (>= 2 shards observed via the
    fleet.shard_stages counter), answers bit-identical to the offline
    `project` path, the sharded route's transient charges evict the
    co-resident warm panel (whose first post-eviction answer is also
    bit-identical after re-stage), the rung-carrying fingerprint is the
    route's cache namespace, and the transient accounting drains to
    zero."""
    _rig_nxn(monkeypatch)
    big, warm = fx.routes["r-big"], fx.routes["r-warm"]
    manifest = FleetManifest.parse({
        "routes": [{"name": r.name, "model": r.model,
                    "source": f"store:{r.store}"} for r in (big, warm)],
        "budget_mb": BUDGET / 1e6,
    })
    fleet = build_fleet(
        manifest, ServeConfig(cache_entries=0),
        ingest_defaults=IngestConfig(block_variants=BV)).start()
    rng = np.random.default_rng(29)
    try:
        # The router chose sharded serving from the size hint alone.
        route = fleet.routes["r-big"]
        assert route.panel_bytes_hint == BIG_PANEL > BUDGET
        # Rung in the fingerprint/namespace: the cache namespace IS the
        # digest that hashes solver/rank/seed (test_roundtrip proves
        # the digest moves when the rung does).
        mdl = load_model(big.model)
        assert (mdl.solver, mdl.rank) == ("corrected", RANK)
        assert route.cache_ns == mdl.digest()

        # Warm route stages whole (it fits) and stays resident.
        qw = random_genotypes(rng, n=1, v=V_WARM, missing_rate=0.1)[0]
        np.testing.assert_array_equal(
            fleet.project("r-warm", qw, timeout=60), _offline(warm, qw))
        assert fleet.pool.is_staged("r-warm")

        c0 = telemetry.counter_value("fleet.shard_stages")
        qb = random_genotypes(rng, n=1, v=V_BIG, missing_rate=0.1)[0]
        np.testing.assert_array_equal(
            fleet.project("r-big", qb, timeout=60), _offline(big, qb))
        c1 = telemetry.counter_value("fleet.shard_stages")
        assert c1 - c0 >= 2, (c0, c1)
        gx = telemetry.metrics_snapshot()["gauges"][
            "fleet.panel_over_budget_x"]
        assert gx["last"] == pytest.approx(BIG_PANEL / BUDGET)
        assert gx["last"] > 1.0

        # The shards' transient budget charges evicted the warm panel
        # (shards themselves are never eviction candidates)...
        assert not fleet.pool.is_staged("r-big")
        assert not fleet.pool.is_staged("r-warm")
        assert telemetry.counter_value("fleet.evictions") >= 1
        # ... and the warm route's first post-eviction answer is
        # bit-identical after the re-stage.
        qw = random_genotypes(rng, n=1, v=V_WARM, missing_rate=0.1)[0]
        np.testing.assert_array_equal(
            fleet.project("r-warm", qw, timeout=60), _offline(warm, qw))
        assert telemetry.counter_value("fleet.restage_total") >= 1

        # Over-budget panels have no warm tier: a second request
        # re-streams the shard sequence and still answers identically.
        qb = random_genotypes(rng, n=1, v=V_BIG, missing_rate=0.1)[0]
        np.testing.assert_array_equal(
            fleet.project("r-big", qb, timeout=60), _offline(big, qb))
        c2 = telemetry.counter_value("fleet.shard_stages")
        assert c2 - c1 >= 2, (c1, c2)
        assert fleet.routes["r-big"].tally["stages"] >= 2

        st = fleet.pool.stats()
        assert st["transient_bytes"] == 0, st
        assert st["resident_bytes"] <= BUDGET
        assert fleet.drain(timeout=60)
    finally:
        fleet.close()

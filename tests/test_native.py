"""Native C++ codec (native/codec.cpp) == pure-Python semantics.

The shared library is an accelerator for the prefetch producer thread
and the VCF parse loop, never a semantic fork — these tests pin the
native outputs byte-for-byte against the NumPy/Python fallbacks on the
same inputs, including every GT edge case the Python parser defines.
Skipped wholesale when the library can't build (no g++)."""

import numpy as np
import pytest

from spark_examples_tpu import native
from spark_examples_tpu.ingest import bitpack
from spark_examples_tpu.ingest.vcf import VcfSource, _dosage, write_vcf
from tests.conftest import random_genotypes

pytestmark = pytest.mark.skipif(
    native.load() is None, reason="native library unavailable (no g++?)"
)


def _py_pack(g):
    """The NumPy reference path, bypassing the native fast path."""
    n, v = g.shape
    codes = np.where(g < 0, 3, g).astype(np.uint8)
    pad = -v % 4
    if pad:
        codes = np.concatenate(
            [codes, np.full((n, pad), 3, np.uint8)], axis=1
        )
    c = codes.reshape(n, -1, 4)
    return c[..., 0] | (c[..., 1] << 2) | (c[..., 2] << 4) | (c[..., 3] << 6)


@pytest.mark.parametrize("v", [1, 3, 4, 7, 64, 257])
def test_native_pack_matches_numpy(rng, v):
    g = random_genotypes(rng, n=11, v=v, missing_rate=0.2)
    got = native.pack_dosages(g)
    np.testing.assert_array_equal(got, _py_pack(g))


def test_native_pack_rejects_out_of_domain():
    with pytest.raises(ValueError, match="2-bit range"):
        native.pack_dosages(np.array([[0, 3]], np.int8))
    with pytest.raises(ValueError, match="2-bit range"):
        native.pack_dosages(np.array([[-2, 0]], np.int8))


def test_native_pack_declines_wide_dtypes():
    # int32 input must fall back to NumPy (which validates the wide
    # domain) rather than being reinterpreted as int8.
    assert native.pack_dosages(np.array([[0, 1]], np.int32)) is None


def test_native_unpack_roundtrip(rng):
    g = random_genotypes(rng, n=9, v=200, missing_rate=0.3)
    p = bitpack.pack_dosages(g)
    out = native.unpack_dosages(p)
    np.testing.assert_array_equal(out[:, :200], g)
    assert (out[:, 200:] == -1).all()


GT_CASES = [
    b"0/0", b"0/1", b"1/1", b"1/2", b"2/2", b"./.", b".",
    b"0|1", b"1|1", b"./1", b"1/.", b"0/0/1", b"1/1/1", b"", b"0",
]


def test_native_gt_parse_matches_python():
    """One synthetic record exercising every GT edge case, with extra
    FORMAT subfields and GT not in first position."""
    n = len(GT_CASES)
    samples = b"\t".join(b"9:" + gt + b":PASS" for gt in GT_CASES)
    line = (b"chr1\t100\trs1\tA\tC\t.\tPASS\t.\tDP:GT:FT\t" + samples)
    out = np.empty(n, np.int8)
    assert native.vcf_parse_gt(line, 1, n, out)
    want = [_dosage(gt.decode()) for gt in GT_CASES]
    np.testing.assert_array_equal(out, np.asarray(want, np.int8))


def test_gt_subfields_shorter_than_format():
    """VCF permits dropping trailing subfields: FORMAT DP:GT with a bare
    '5' sample column means GT absent -> missing, on BOTH parsers."""
    line = b"chr1\t1\t.\tA\tC\t.\t.\t.\tDP:GT\t5\t7:0/1"
    out = np.empty(2, np.int8)
    assert native.vcf_parse_gt(line, 1, 2, out)
    np.testing.assert_array_equal(out, np.array([-1, 1], np.int8))


def test_vcf_crlf_line_endings(rng, tmp_path, monkeypatch):
    """CRLF files parse identically to LF files on both parsers — binary
    reads see the \\r that text mode's universal newlines used to hide,
    and an unstripped \\r would corrupt the last sample's dosage."""
    g = random_genotypes(rng, n=5, v=40, missing_rate=0.2)
    lf, crlf = str(tmp_path / "lf.vcf"), str(tmp_path / "crlf.vcf")
    write_vcf(lf, g)
    with open(lf, "rb") as f:
        body = f.read().replace(b"\n", b"\r\n")
    with open(crlf, "wb") as f:
        f.write(body)
    for forced_fallback in (False, True):
        if forced_fallback:
            monkeypatch.setattr(native, "_lib", None)
            monkeypatch.setattr(native, "_tried", True)
        out = np.concatenate(
            [b for b, _ in VcfSource(crlf).blocks(16)], axis=1
        )
        np.testing.assert_array_equal(out, g)


def test_truncated_vcf_warns(rng, tmp_path):
    """A record with fewer sample columns than the header (truncated
    file) is skipped with a loud warning, not silently dropped."""
    g = random_genotypes(rng, n=6, v=10, missing_rate=0.0)
    path = str(tmp_path / "t.vcf")
    write_vcf(path, g)
    with open(path) as f:
        lines = f.read().splitlines()
    # cut the last record mid-line (drop 3 sample columns)
    lines[-1] = "\t".join(lines[-1].split("\t")[:-3])
    with open(path, "w") as f:
        f.write("\n".join(lines) + "\n")
    with pytest.warns(RuntimeWarning, match="truncated or malformed"):
        out = np.concatenate(
            [b for b, _ in VcfSource(path).blocks(4)], axis=1
        )
    np.testing.assert_array_equal(out, g[:, :9])  # 9 good records kept


def test_native_gt_parse_short_record():
    out = np.empty(5, np.int8)
    line = b"chr1\t1\t.\tA\tC\t.\t.\t.\tGT\t0/1\t1/1"
    assert not native.vcf_parse_gt(line, 0, 5, out)


def test_vcf_source_native_vs_python_fallback(rng, tmp_path, monkeypatch):
    """Full VcfSource stream: native parser == Python parser on the same
    file (the fallback is forced via SPARK_TPU_NO_NATIVE for a fresh
    subprocess-free comparison by reloading the module state)."""
    g = random_genotypes(rng, n=13, v=300, missing_rate=0.15)
    path = str(tmp_path / "c.vcf")
    write_vcf(path, g)

    native_blocks = np.concatenate(
        [b for b, _ in VcfSource(path).blocks(77)], axis=1
    )
    # Force the Python path without rebuilding: stub the loader.
    monkeypatch.setattr(native, "_lib", None)
    monkeypatch.setattr(native, "_tried", True)
    python_blocks = np.concatenate(
        [b for b, _ in VcfSource(path).blocks(77)], axis=1
    )
    np.testing.assert_array_equal(native_blocks, python_blocks)
    np.testing.assert_array_equal(native_blocks, g)

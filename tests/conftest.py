"""Test bootstrap: force the JAX CPU backend with 8 virtual devices.

Mirrors the reference's `local[*]` testing story (SURVEY.md §4): the same
sharded code paths (mesh, shard_map, collectives) run multi-"device" in
one process, so distributed logic is exercised without TPU hardware.
Must run before the first `import jax` anywhere in the test session.
"""

from spark_examples_tpu.core.virtual import force_virtual_cpu

force_virtual_cpu(8)

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.default_rng(1234)


def random_genotypes(rng, n, v, missing_rate=0.1):
    """Random dosage matrix with missing calls, int8."""
    g = rng.integers(0, 3, size=(n, v), dtype=np.int8)
    miss = rng.random((n, v)) < missing_rate
    g[miss] = -1
    return g


@pytest.fixture
def genotypes(rng):
    return random_genotypes(rng, n=37, v=211, missing_rate=0.15)

"""Test bootstrap: force the JAX CPU backend with 8 virtual devices.

Mirrors the reference's `local[*]` testing story (SURVEY.md §4): the same
sharded code paths (mesh, shard_map, collectives) run multi-"device" in
one process, so distributed logic is exercised without TPU hardware.
Must run before the first `import jax` anywhere in the test session.
"""

import os

# Hard override: the ambient environment pins JAX_PLATFORMS=axon (the
# real TPU) and a sitecustomize.py imports jax at interpreter startup,
# so the env var alone is captured too late — update jax's config too
# (backends initialise lazily, so this still wins if nothing computed).
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.default_rng(1234)


def random_genotypes(rng, n, v, missing_rate=0.1):
    """Random dosage matrix with missing calls, int8."""
    g = rng.integers(0, 3, size=(n, v), dtype=np.int8)
    miss = rng.random((n, v)) < missing_rate
    g[miss] = -1
    return g


@pytest.fixture
def genotypes(rng):
    return random_genotypes(rng, n=37, v=211, missing_rate=0.15)

"""Crash-recovery under deterministic fault injection (core/faults.py).

Every recovery path the fault-tolerance layer claims is executed here
under injected faults and held to the strongest available standard:
bit-identical results against a clean run (the gram accumulators are
integer counts — there is no tolerance to hide behind).
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from spark_examples_tpu.core import faults
from spark_examples_tpu.core.config import (
    ComputeConfig,
    IngestConfig,
    JobConfig,
)
from spark_examples_tpu.ingest import ArraySource
from spark_examples_tpu.ingest.resilient import (
    CorruptBlockError,
    IngestExhaustedError,
    RetryingSource,
    RetryPolicy,
)
from spark_examples_tpu.pipelines import runner
from tests.conftest import random_genotypes

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

FAST_RETRY = RetryPolicy(max_retries=4, backoff_s=0.001, max_backoff_s=0.01)


# ---------------------------------------------------------------- faults core


def test_spec_parse_roundtrip():
    s = faults.FaultSpec.parse("ingest.block_read:io_error:p=0.5:after=3:max=2")
    assert s.site == "ingest.block_read"
    assert s.kind == "io_error"
    assert (s.probability, s.after, s.max_fires) == (0.5, 3, 2)
    with pytest.raises(ValueError, match="unknown fault site"):
        faults.FaultSpec.parse("nonsite:io_error")
    with pytest.raises(ValueError, match="unknown fault kind"):
        faults.FaultSpec.parse("device.put:explode")
    with pytest.raises(ValueError, match="valid keys"):
        faults.FaultSpec.parse("device.put:delay:frequency=2")


def test_injector_after_and_max_are_deterministic():
    with faults.armed(["device.put:io_error:after=2:max=2"]) as inj:
        fired = []
        for _ in range(6):
            try:
                faults.fire("device.put")
                fired.append(False)
            except faults.InjectedFault:
                fired.append(True)
        # hits 0,1 pass; 2,3 fire; exhausted afterwards.
        assert fired == [False, False, True, True, False, False]
        assert inj.fire_count("device.put") == 2
    assert faults.fire_count("device.put") == 0  # disarmed


def test_disarmed_fire_is_noop():
    faults.disarm()
    faults.fire("ingest.block_read")  # must not raise


# ---------------------------------------------------------- retrying ingest


def test_retry_transient_io_error_bit_exact(rng):
    """Injected transient IOErrors at the block-read site are retried
    (re-open + seek to cursor) and the full stream is bit-identical to
    an uninjected read."""
    g = random_genotypes(rng, 12, 700, missing_rate=0.1)
    src = RetryingSource(ArraySource(g), policy=FAST_RETRY)
    clean = [(b.copy(), m) for b, m in ArraySource(g).blocks(128)]
    with faults.armed(["ingest.block_read:io_error:after=2:max=2"]) as inj:
        with pytest.warns(RuntimeWarning, match="transient ingest error"):
            got = [(b.copy(), m) for b, m in src.blocks(128)]
        assert inj.fire_count("ingest.block_read") == 2
    assert len(got) == len(clean)
    for (gb, gm), (cb, cm) in zip(got, clean):
        np.testing.assert_array_equal(gb, cb)
        assert (gm.start, gm.stop, gm.index) == (cm.start, cm.stop, cm.index)


def test_retry_exhaustion_names_cursor(rng):
    g = random_genotypes(rng, 8, 512)
    src = RetryingSource(
        ArraySource(g), policy=RetryPolicy(max_retries=1, backoff_s=0.001)
    )
    # Unlimited fires outlast the 1-retry budget; 2 blocks (256 variants)
    # stream before the first fault, so that boundary is the cursor.
    with faults.armed(["ingest.block_read:io_error:after=2:max=0"]):
        with pytest.raises(IngestExhaustedError, match="cursor 256") as ei:
            with pytest.warns(RuntimeWarning):
                list(src.blocks(128))
    assert ei.value.cursor == 256


def test_retry_budget_resets_on_progress(rng):
    """The retry budget bounds CONSECUTIVE failures (one incident), not
    the stream lifetime: independent recoverable hiccups far apart must
    not accumulate into a job kill."""
    g = random_genotypes(rng, 8, 1024)
    src = RetryingSource(
        ArraySource(g), policy=RetryPolicy(max_retries=1, backoff_s=0.001)
    )
    clean = [(b.copy(), m) for b, m in ArraySource(g).blocks(128)]
    # Four separate single-failure incidents, each with >= 1 block of
    # progress in between — more total failures than max_retries=1 would
    # survive per-stream, recoverable per-incident.
    specs = [f"ingest.block_read:io_error:after={a}:max=1"
             for a in (1, 4, 7, 10)]
    with faults.armed(specs) as inj:
        with pytest.warns(RuntimeWarning, match="transient ingest error"):
            got = [(b.copy(), m) for b, m in src.blocks(128)]
        assert inj.fire_count("ingest.block_read") == 4
    assert len(got) == len(clean)
    for (gb, gm), (cb, cm) in zip(got, clean):
        np.testing.assert_array_equal(gb, cb)
        assert (gm.start, gm.stop, gm.index) == (cm.start, cm.stop, cm.index)


def test_retry_reopen_rebuilds_inner_source(rng):
    """``reopen`` swaps in a FRESH inner source before each retry — the
    recovery path memmap-backed sources (packed store) need, where the
    broken file state lives on the object itself."""
    g = random_genotypes(rng, 8, 512)

    class DeadMapping(ArraySource):
        """Fails every read, like a memmap whose file went away."""

        def blocks(self, block_variants, start_variant=0):
            raise IOError("stale mapping")
            yield  # pragma: no cover

    rebuilt = []

    def reopen():
        rebuilt.append(True)
        return ArraySource(g)

    src = RetryingSource(DeadMapping(g), policy=FAST_RETRY, reopen=reopen)
    clean = [(b.copy(), m) for b, m in ArraySource(g).blocks(128)]
    with pytest.warns(RuntimeWarning, match="transient ingest error"):
        got = [(b.copy(), m) for b, m in src.blocks(128)]
    assert rebuilt  # the factory actually ran
    assert len(got) == len(clean)
    for (gb, gm), (cb, cm) in zip(got, clean):
        np.testing.assert_array_equal(gb, cb)


def test_build_source_packed_reopen_rebuilds_mapping(tmp_path, rng):
    """build_source gives the packed store a reopen factory (its memmap
    cannot recover by re-slicing itself)."""
    from spark_examples_tpu.ingest.packed import save_packed

    g = np.abs(random_genotypes(rng, 8, 256))
    store = str(tmp_path / "store")
    save_packed(store, g, bits=2)
    src = runner.build_source(IngestConfig(source="packed", path=store))
    assert src.reopen is not None
    fresh = src.reopen()
    assert fresh is not src.inner and hasattr(fresh, "packed_blocks")


def test_corrupt_block_fails_fast_with_cursor(rng):
    """A structurally invalid block is never retried: one attempt, an
    actionable error naming the resume cursor."""
    g = random_genotypes(rng, 10, 512)

    class Corrupting(ArraySource):
        def blocks(self, bv, start_variant=0):
            for b, m in super().blocks(bv, start_variant):
                if m.start == 256:  # third block: drop a sample row
                    b = b[:-1]
                yield b, m

    src = RetryingSource(Corrupting(g), policy=FAST_RETRY)
    with pytest.raises(CorruptBlockError, match="cursor 256") as ei:
        list(src.blocks(128))
    assert ei.value.cursor == 256
    assert "start_variant=256" in str(ei.value)


def test_retrying_source_in_similarity_job_bit_exact(rng):
    """The job surface: a similarity run whose ingest suffers transient
    IOErrors AND transfer stalls matches the clean run bit-identically
    (ibs counts are integers — exactness is the only passing grade)."""
    g = random_genotypes(rng, 16, 1024, missing_rate=0.1)
    job = JobConfig(ingest=IngestConfig(block_variants=128),
                    compute=ComputeConfig(metric="ibs"))
    clean = runner.run_similarity(job, source=ArraySource(g))
    chaotic_src = RetryingSource(ArraySource(g), policy=FAST_RETRY)
    with faults.armed([
        "ingest.block_read:io_error:after=3:max=2",
        "device.put:delay:delay=0.01:max=3",
        "multihost.consensus:delay:delay=0.01:max=2",  # inert single-host
    ]) as inj:
        with pytest.warns(RuntimeWarning, match="transient ingest error"):
            chaotic = runner.run_similarity(job, source=chaotic_src)
        assert inj.fire_count("ingest.block_read") == 2
        assert inj.fire_count("device.put") == 3
    np.testing.assert_array_equal(chaotic.similarity, clean.similarity)
    np.testing.assert_array_equal(chaotic.distance, clean.distance)
    assert chaotic.n_variants == clean.n_variants


def test_build_source_wraps_file_sources(tmp_path, rng):
    """build_source applies the retry boundary to file-backed sources
    (and leaves synthetic unwrapped — it does no IO)."""
    from spark_examples_tpu.ingest.packed import save_packed

    g = random_genotypes(rng, 8, 256, missing_rate=0.0)
    g = np.abs(g)  # packed store holds dosages
    store = str(tmp_path / "store")
    save_packed(store, g, bits=2)
    src = runner.build_source(IngestConfig(source="packed", path=store))
    assert isinstance(src, RetryingSource)
    assert src.exact_n_variants  # inner claims pass through
    assert hasattr(src, "packed_blocks")  # packed transport forwarded
    nosrc = runner.build_source(
        IngestConfig(source="packed", path=store, io_retries=0)
    )
    assert not isinstance(nosrc, RetryingSource)
    syn = runner.build_source(IngestConfig(source="synthetic",
                                           n_samples=8, n_variants=256))
    assert not isinstance(syn, RetryingSource)


def test_retrying_packed_transport_bit_exact(tmp_path, rng):
    from spark_examples_tpu.ingest.packed import load_packed, save_packed

    g = np.abs(random_genotypes(rng, 8, 512, missing_rate=0.1))
    store = str(tmp_path / "store")
    save_packed(store, g, bits=2)
    clean = [(b.copy(), m) for b, m in load_packed(store).packed_blocks(128)]
    src = RetryingSource(load_packed(store), policy=FAST_RETRY)
    with faults.armed(["ingest.block_read:io_error:after=1:max=1"]):
        with pytest.warns(RuntimeWarning):
            got = [(b.copy(), m) for b, m in src.packed_blocks(128)]
    assert len(got) == len(clean)
    for (gb, _), (cb, _) in zip(got, clean):
        np.testing.assert_array_equal(gb, cb)


# ------------------------------------------------------ checkpoint integrity


def _ckpt_job(ckpt_dir: str) -> JobConfig:
    return JobConfig(
        ingest=IngestConfig(block_variants=128),
        compute=ComputeConfig(metric="ibs", checkpoint_dir=ckpt_dir,
                              checkpoint_every_blocks=2),
    )


def _run_until(job, g, die_at_block: int):
    """Stream with checkpointing and die (exception) at a given block."""

    class Dying(ArraySource):
        def blocks(self, bv, start_variant=0):
            for i, (b, m) in enumerate(super().blocks(bv, start_variant)):
                if m.start >= die_at_block * bv:
                    raise RuntimeError("simulated preemption")
                yield b, m

    with pytest.raises(RuntimeError, match="preemption"):
        runner.run_similarity(job, source=Dying(g))


def test_checkpoint_manifest_records_sha256(tmp_path, rng):
    g = random_genotypes(rng, 16, 1024)
    ckpt = str(tmp_path / "ck")
    _run_until(_ckpt_job(ckpt), g, die_at_block=4)
    manifest = json.load(open(os.path.join(ckpt, "manifest.json")))
    sums = manifest["sha256"]
    data_files = [f for f in os.listdir(ckpt) if f.endswith(".npy")]
    assert sorted(sums) == sorted(data_files)
    from spark_examples_tpu.core.checkpoint import _sha256_file

    for f, want in sums.items():
        assert _sha256_file(os.path.join(ckpt, f)) == want


def test_truncated_tile_falls_back_to_old_generation(tmp_path, rng):
    """A checkpoint whose latest generation has a truncated tile is
    rejected by checksum and the retained .old generation restores;
    the resumed job still matches the clean run bit-exactly (it simply
    re-streams from the older cursor)."""
    g = random_genotypes(rng, 16, 1024, missing_rate=0.1)
    ckpt = str(tmp_path / "ck")
    job = _ckpt_job(ckpt)
    # Two+ saves happen (8 blocks / every 2); truncate a file of the
    # LATEST generation only.
    _run_until(job, g, die_at_block=6)
    assert os.path.isdir(ckpt) and os.path.isdir(ckpt + ".old")
    victim = sorted(
        f for f in os.listdir(ckpt) if f.endswith(".npy")
    )[0]
    with open(os.path.join(ckpt, victim), "r+b") as f:
        f.truncate(8)
    with pytest.warns(RuntimeWarning, match="sha256 mismatch"):
        resumed = runner.run_similarity(job, source=ArraySource(g))
    clean = runner.run_similarity(
        JobConfig(ingest=IngestConfig(block_variants=128),
                  compute=ComputeConfig(metric="ibs")),
        source=ArraySource(g),
    )
    np.testing.assert_array_equal(resumed.similarity, clean.similarity)


def test_fallback_promotes_old_generation(tmp_path, rng):
    """Resuming from .old must promote it back to the latest slot (the
    corrupt latest set aside as .corrupt) — otherwise the NEXT save's
    rotation would rmtree the only good generation and demote the
    corrupt one into .old, leaving a crash window with zero good
    checkpoints."""
    g = random_genotypes(rng, 16, 1024, missing_rate=0.1)
    ckpt = str(tmp_path / "ck")
    job = _ckpt_job(ckpt)
    _run_until(job, g, die_at_block=6)
    victim = sorted(f for f in os.listdir(ckpt) if f.endswith(".npy"))[0]
    good_cursor = json.load(
        open(os.path.join(ckpt + ".old", "manifest.json")))["cursors"]
    with open(os.path.join(ckpt, victim), "r+b") as f:
        f.truncate(8)
    with pytest.warns(RuntimeWarning, match="sha256 mismatch"):
        resumed = runner.run_similarity(job, source=ArraySource(g))
    # The good generation now sits in the LATEST slot (advanced by the
    # resumed run's own saves past the old cursor), the corrupt one is
    # preserved aside, and the fallback slot is alive again.
    assert os.path.isdir(ckpt + ".corrupt")
    assert json.load(
        open(os.path.join(ckpt, "manifest.json")))["cursors"] != good_cursor
    from spark_examples_tpu.core.checkpoint import load

    assert load(ckpt, "ibs", [f"S{i:06d}" for i in range(16)]) is not None
    clean = runner.run_similarity(
        JobConfig(ingest=IngestConfig(block_variants=128),
                  compute=ComputeConfig(metric="ibs")),
        source=ArraySource(g),
    )
    np.testing.assert_array_equal(resumed.similarity, clean.similarity)


def test_reopen_failure_consumes_retry_budget(rng):
    """A reopen() that itself fails on a still-flaky mount must burn
    the same budget and raise the same cursor-naming exhaustion error
    as a failed read — never escape as a raw OSError."""
    g = random_genotypes(rng, 8, 512)

    def always_dead():
        raise IOError("mount still down")

    class DeadMapping(ArraySource):
        def blocks(self, block_variants, start_variant=0):
            raise IOError("stale mapping")
            yield  # pragma: no cover

    src = RetryingSource(
        DeadMapping(g),
        policy=RetryPolicy(max_retries=2, backoff_s=0.001),
        reopen=always_dead,
    )
    with pytest.raises(IngestExhaustedError, match="cursor 0"):
        with pytest.warns(RuntimeWarning, match="transient ingest error"):
            list(src.blocks(128))


def test_injected_truncation_at_write_site(tmp_path, rng):
    """The same fallback, driven end to end by the injection harness:
    the checkpoint.tile_write site truncates a file AFTER its sha256
    was recorded — exactly a torn write — and load() must reject that
    generation and restore from .old."""
    from spark_examples_tpu.core import checkpoint as ckpt_mod

    g = random_genotypes(rng, 16, 1024, missing_rate=0.1)
    ckpt = str(tmp_path / "ck")
    job = _ckpt_job(ckpt)
    n_files_per_save = 4  # ibs pieces, replicated layout
    with faults.armed([
        # Saves land at blocks 2, 4, 6; corrupt a file of the THIRD
        # (final) save so the latest generation is the bad one and the
        # retained save-2 generation is the .old fallback target.
        f"checkpoint.tile_write:truncate:after={2 * n_files_per_save + 1}:max=1",
    ]) as inj:
        _run_until(job, g, die_at_block=6)
        assert inj.fire_count("checkpoint.tile_write") == 1
    with pytest.warns(RuntimeWarning, match="sha256 mismatch|falling back"):
        restored = ckpt_mod.load(ckpt, "ibs", ArraySource(g).sample_ids,
                                 block_variants=128)
    assert restored is not None
    resumed = runner.run_similarity(job, source=ArraySource(g))
    clean = runner.run_similarity(
        JobConfig(ingest=IngestConfig(block_variants=128),
                  compute=ComputeConfig(metric="ibs")),
        source=ArraySource(g),
    )
    np.testing.assert_array_equal(resumed.similarity, clean.similarity)


def test_all_generations_corrupt_raises(tmp_path, rng):
    from spark_examples_tpu.core import checkpoint as ckpt_mod
    from spark_examples_tpu.core.checkpoint import CheckpointCorruptError

    g = random_genotypes(rng, 16, 1024)
    ckpt = str(tmp_path / "ck")
    _run_until(_ckpt_job(ckpt), g, die_at_block=6)
    for gen in (ckpt, ckpt + ".old"):
        victim = sorted(f for f in os.listdir(gen) if f.endswith(".npy"))[0]
        with open(os.path.join(gen, victim), "r+b") as f:
            f.truncate(4)
    with pytest.raises(CheckpointCorruptError, match="sha256 mismatch"):
        ckpt_mod.load(ckpt, "ibs", ArraySource(g).sample_ids,
                      block_variants=128)


def test_corrupt_manifest_falls_back(tmp_path, rng):
    g = random_genotypes(rng, 16, 1024)
    ckpt = str(tmp_path / "ck")
    _run_until(_ckpt_job(ckpt), g, die_at_block=6)
    with open(os.path.join(ckpt, "manifest.json"), "w") as f:
        f.write('{"truncated": tru')  # torn JSON
    from spark_examples_tpu.core import checkpoint as ckpt_mod

    with pytest.warns(RuntimeWarning, match="manifest unreadable"):
        restored = ckpt_mod.load(ckpt, "ibs", ArraySource(g).sample_ids,
                                 block_variants=128)
    assert restored is not None
    _acc, cursor, _stats = restored
    assert cursor > 0  # a real earlier generation, not a fresh start


def test_legacy_checkpoint_without_checksums_loads(tmp_path, rng):
    """Pre-integrity checkpoints (no sha256 map) must keep loading."""
    from spark_examples_tpu.core import checkpoint as ckpt_mod

    ids = [f"s{i}" for i in range(8)]
    ckpt_mod.save(str(tmp_path / "c"), {"m": np.zeros((8, 8))}, 64, "ibs",
                  64, ids)
    manifest_path = os.path.join(str(tmp_path / "c"), "manifest.json")
    manifest = json.load(open(manifest_path))
    del manifest["sha256"]
    with open(manifest_path, "w") as f:
        json.dump(manifest, f)
    # leaf-schema check needs the real ibs pieces; bypass via direct load
    with pytest.raises(ValueError, match="stale accumulator schema"):
        ckpt_mod.load(str(tmp_path / "c"), "ibs", ids)


# ----------------------------------------------------- kill + resume (subproc)


_KILL_JOB = r"""
import sys
import numpy as np
from spark_examples_tpu.core.virtual import force_virtual_cpu
force_virtual_cpu(2)
from spark_examples_tpu.core.config import (
    ComputeConfig, IngestConfig, JobConfig,
)
from spark_examples_tpu.pipelines import runner

job = JobConfig(
    ingest=IngestConfig(source="packed", path=sys.argv[3],
                        block_variants=128),
    compute=ComputeConfig(metric="ibs", checkpoint_dir=sys.argv[1],
                          checkpoint_every_blocks=2),
)
res = runner.run_similarity(job)
np.save(sys.argv[2], res.similarity)
"""


def test_process_kill_resumes_from_checkpoint(tmp_path, rng):
    """An injected os._exit mid-stream (the 'kill' kind, armed via the
    environment as a real operator would) leaves a checkpoint a second
    invocation resumes from, matching the clean run bit-exactly. Uses a
    packed store: file-backed sources get the retry wrapper whose
    block-read site hosts the injection."""
    from spark_examples_tpu.ingest.packed import save_packed

    g = np.abs(random_genotypes(rng, 16, 1024, missing_rate=0.1))
    store = str(tmp_path / "store")
    save_packed(store, g, bits=2)
    ckpt = str(tmp_path / "ck")
    out = str(tmp_path / "sim.npy")
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        PYTHONPATH=REPO + os.pathsep + os.environ.get("PYTHONPATH", ""),
    )
    # Kill at the 6th block read: checkpoints exist for blocks 2 and 4.
    env[faults.ENV_SPECS] = "ingest.block_read:kill:after=5:max=1"
    p = subprocess.run(
        [sys.executable, "-c", _KILL_JOB, ckpt, out, store],
        env=env, cwd=REPO, capture_output=True, text=True, timeout=240,
    )
    assert p.returncode == faults.KILL_EXIT_CODE, (p.returncode, p.stderr[-2000:])
    assert os.path.exists(os.path.join(ckpt, "manifest.json"))
    assert not os.path.exists(out)

    env.pop(faults.ENV_SPECS)
    p = subprocess.run(
        [sys.executable, "-c", _KILL_JOB, ckpt, out, store],
        env=env, cwd=REPO, capture_output=True, text=True, timeout=240,
    )
    assert p.returncode == 0, p.stderr[-2000:]
    resumed = np.load(out)

    clean_out = str(tmp_path / "clean.npy")
    p = subprocess.run(
        [sys.executable, "-c", _KILL_JOB, str(tmp_path / "nock"), clean_out,
         store],
        env=env, cwd=REPO, capture_output=True, text=True, timeout=240,
    )
    assert p.returncode == 0, p.stderr[-2000:]
    np.testing.assert_array_equal(resumed, np.load(clean_out))

    # The injection site fires INSIDE the retry boundary, so the
    # checkpoint the killed run left holds exactly the blocks it
    # completed — re-verify the cursor is block-grid aligned.
    manifest = json.load(open(os.path.join(ckpt, "manifest.json")))
    assert manifest["next_variant"] % 128 == 0


# --------------------------------------------------- consensus under faults


def test_consensus_delay_straggler_is_absorbed(rng):
    """A straggling control plane (delay faults at the consensus site)
    must slow the stream, not desynchronize or corrupt it. Runs the
    multi-host feeder in its single-process degenerate form — the
    2-process coverage lives in tests/test_distributed.py."""
    from spark_examples_tpu.core import meshes
    from spark_examples_tpu.parallel import gram_sharded, multihost as mh

    g = np.abs(random_genotypes(rng, 8, 512, missing_rate=0.0))
    src = ArraySource(g)
    mesh = meshes.make_mesh()
    plan = gram_sharded.plan_for(mesh, 8, "ibs", "variant")
    stats: dict = {}

    def drain():
        widths = []
        for gblock, meta in mh.stream_global_blocks(
            src, 128, 0, plan, pack=False, stats=stats
        ):
            widths.append((np.asarray(gblock.addressable_data(0)).shape,
                           None if meta is None else meta.stop))
        return widths

    clean = drain()
    with faults.armed(
        ["multihost.consensus:delay:delay=0.02:max=0"]
    ) as inj:
        delayed = drain()
        assert inj.fire_count("multihost.consensus") >= 2
    assert delayed == clean

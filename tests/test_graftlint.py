"""graftlint (tools/graftlint) — the AST invariant suite, tier-1.

Three contracts pinned here:

- **Historical-bug replay.** Every rule catches a distilled replica of
  the regression that motivated it (tests/fixtures/graftlint/<rule>/
  bad.py) at EXACT rule id + line + col, and stays quiet on the fixed
  shape (good.py). The fixtures are the executable changelog of the
  bug classes: PR 11's unreachable-Jaccard choices list, PR 12's
  unusable donations, PR 6's lock-held I/O deadlock, PR 8's torn
  snapshots, the supervised parent's jax-free contract, the telemetry/
  fault-site name registry, and the soak thread accounting.
- **Dogfood.** The whole production tree lints clean — the suite runs
  over the repo as part of tier-1, so a new finding is a test failure
  with a precise location, not a review-round discovery.
- **Suppression discipline.** ``# graftlint: disable=<rule>`` without a
  reason is itself a finding; with a reason it silences exactly its
  line (inline or standalone-above).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

import pytest

from tools import graftlint
from tools.graftlint import engine

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(REPO, "tests", "fixtures", "graftlint")

# The analyzer surface: rule id -> the fixture directory that replays
# its motivating historical bug.
ANALYZERS = {
    "registry-literal": "registry_literal",
    "donation-safety": "donation",
    "blocking-under-lock": "locks",
    "atomic-write": "atomic_write",
    "jax-import-purity": "jax_purity",
    "telemetry-name": "names",
    "fault-site": "names",
    "thread-hygiene": "threads",
}

# (rule, line, col) triples each bad.py must produce, EXACTLY — the
# precise-location contract of the acceptance criteria.
EXPECTED_BAD = {
    "registry_literal": [
        ("registry-literal", 9, 13),
        ("registry-literal", 13, 13),
    ],
    "donation": [
        ("donation-safety", 16, 21),   # int32 accumulator donated
        ("donation-safety", 16, 29),   # scalar donated
        ("donation-safety", 25, 18),   # read-after-donate
    ],
    "locks": [
        ("blocking-under-lock", 12, 9),   # sleep under `with lock`
        ("blocking-under-lock", 13, 14),  # open() under `with lock`
        ("blocking-under-lock", 20, 9),   # subprocess in acquire/release
    ],
    "atomic_write": [
        ("atomic-write", 8, 10),   # open(metrics_path, "w")
        ("atomic-write", 14, 5),   # manifest.write_text(...)
    ],
    "jax_purity": [
        ("jax-import-purity", 5, 1),  # direct `import jax`
        ("jax-import-purity", 7, 1),  # transitive via the ops package
    ],
    "names": [
        ("telemetry-name", 12, 13),  # undeclared, through the alias
        ("telemetry-name", 13, 13),  # undeclared, built by concatenation
        ("telemetry-name", 14, 15),  # f-string name
        ("fault-site", 16, 9),       # undeclared site, multi-line call
    ],
    "threads": [
        ("thread-hygiene", 8, 10),   # no daemon=
        ("thread-hygiene", 8, 10),   # no name=
        ("thread-hygiene", 10, 27),  # prefix outside _SUSPECT_THREADS
        ("thread-hygiene", 11, 12),  # pool without thread_name_prefix
    ],
    "suppression": [
        ("suppression-reason", 12, 26),  # reasonless disable
    ],
}


def _fixture(name, which):
    return os.path.join(FIXTURES, name, which + ".py")


def _triples(findings):
    return [(f.rule, f.line, f.col) for f in findings]


# ------------------------------------------------------- fixture replay


@pytest.mark.parametrize("name", sorted(EXPECTED_BAD))
def test_bad_fixture_findings_pinned(name):
    findings = graftlint.run(paths=[_fixture(name, "bad")])
    assert _triples(findings) == EXPECTED_BAD[name], "\n".join(
        f.render() for f in findings)


@pytest.mark.parametrize("name", sorted(EXPECTED_BAD))
def test_good_fixture_is_clean(name):
    findings = graftlint.run(paths=[_fixture(name, "good")])
    assert not findings, "\n".join(f.render() for f in findings)


def test_every_analyzer_is_registered_and_proven():
    """The ~7-analyzer surface: every registered rule id has a fixture
    that demonstrably catches its historical bug (and vice versa —
    an analyzer without a motivating fixture is an invariant nobody
    distilled)."""
    assert set(graftlint.all_rules()) == set(ANALYZERS)
    for rule_id, fixture in ANALYZERS.items():
        expected = [r for r, _, _ in EXPECTED_BAD[fixture]]
        assert rule_id in expected, (
            f"{rule_id}: fixture {fixture}/bad.py never triggers it")


# ------------------------------------------------------------- dogfood


def test_whole_repo_lints_clean():
    """THE tier-1 gate: the production tree has zero findings — every
    true positive found while building the suite was fixed in this PR
    (core/__init__'s eager jax re-export, hand-listed enum choices,
    unnamed threads) or carries a reasoned suppression."""
    t0 = time.monotonic()
    findings = graftlint.run()
    elapsed = time.monotonic() - t0
    assert not findings, "\n".join(f.render() for f in findings)
    # Acceptance bound: the whole suite inside tier-1 in well under 30s.
    assert elapsed < 30.0, f"graftlint took {elapsed:.1f}s"


def test_repo_suppressions_all_carry_reasons():
    """Mechanical restatement of the suppression ledger: every disable
    comment in the production tree names its rule AND its reason."""
    for path in engine.default_targets():
        src = engine.SourceFile(path, engine.REPO)
        for s in src.suppressions:
            assert s.reason, f"{src.rel}:{s.line}: reasonless suppression"
            assert s.rules <= set(graftlint.all_rules()) | {
                engine.SUPPRESSION_RULE}, (
                f"{src.rel}:{s.line}: unknown rule in {sorted(s.rules)}")


def test_readme_rule_table_names_every_rule():
    """README 'Static analysis' is the invariant ledger (BASELINE.md
    points at it): every registered rule — and the suppression meta
    rule — must have a row/mention, so the docs and the registry move
    together (the glossary-lint convention from PR 8)."""
    text = open(os.path.join(REPO, "README.md")).read()
    start = text.index("## Static analysis")
    section = text[start:text.index("\n## ", start + 1)]
    for rule_id in list(ANALYZERS) + [engine.SUPPRESSION_RULE]:
        assert f"`{rule_id}`" in section, (
            f"README 'Static analysis' has no row for {rule_id}")


# --------------------------------------------------- engine semantics


def test_suppression_reasonless_still_suppresses_but_reports():
    findings = graftlint.run(paths=[_fixture("suppression", "bad")])
    assert [f.rule for f in findings] == ["suppression-reason"]


def test_rules_filter_and_unknown_rule():
    findings = graftlint.run(paths=[_fixture("locks", "bad")],
                             rules=["atomic-write"])
    assert not findings  # the lock findings are outside the filter
    with pytest.raises(ValueError, match="unknown rule id"):
        graftlint.run(paths=[_fixture("locks", "bad")],
                      rules=["no-such-rule"])


def test_docstring_pragma_mentions_are_inert(tmp_path):
    """Pragmas/suppressions are resolved from COMMENT tokens, not raw
    lines: a docstring that merely MENTIONS the grammar (the engine's
    own docs do) must neither suppress findings nor hijack the file's
    module identity (code-review finding on the first engine cut)."""
    p = tmp_path / "doc.py"
    p.write_text(
        '"""Docs only:\n'
        '    x = 1  # graftlint: disable=blocking-under-lock  # mentioned\n'
        '    # graftlint: module=spark_examples_tpu.core.config\n'
        '"""\n'
        "import threading\n"
        "import time\n"
        "_lock = threading.Lock()\n"
        "def f():\n"
        "    with _lock:\n"
        "        time.sleep(0.1)\n")
    src = engine.SourceFile(p, tmp_path)
    assert src.suppressions == []
    assert src.module is None  # the docstring pragma did not bind
    findings = graftlint.run(paths=[str(p)])
    assert [f.rule for f in findings] == ["blocking-under-lock"]


def test_block_vocabulary_is_not_a_lock(tmp_path):
    """'lock' must match as a whole word: this codebase's block_*
    vocabulary (block_reader, blocks) shares the substring, and a
    with-statement over it must not open a phantom critical section
    (code-review finding on the first rule cut)."""
    p = tmp_path / "blocks.py"
    p.write_text(
        "def read(store, path):\n"
        "    with store.block_reader() as r:\n"
        "        data = open(path).read()\n"
        "    blocks = store.blocks\n"
        "    blocks.acquire()\n"
        "    data += open(path).read()\n"
        "    blocks.release()\n"
        "    return data, r\n"
        "def guarded(locks_guard, path):\n"
        "    with locks_guard:\n"
        "        return open(path).read()\n")
    findings = graftlint.run(paths=[str(p)],
                             rules=["blocking-under-lock"])
    # Only the genuinely lock-named with-item fires (line 11).
    assert [(f.rule, f.line) for f in findings] == [
        ("blocking-under-lock", 11)]


def test_parse_error_is_a_finding(tmp_path):
    p = tmp_path / "broken.py"
    p.write_text("def f(:\n")
    findings = graftlint.run(paths=[str(p)])
    assert [f.rule for f in findings] == ["parse-error"]
    assert findings[0].line == 1


def test_finding_render_is_precise():
    f = graftlint.run(paths=[_fixture("atomic_write", "bad")])[0]
    assert f.render().startswith(
        "tests/fixtures/graftlint/atomic_write/bad.py:8:10: atomic-write:")


def test_dead_fault_site_detection_runs_only_on_full_tree(tmp_path):
    """finalize-level checks (dead faults.SITES entries) need the whole
    production tree; a partial run must not fire them spuriously."""
    p = tmp_path / "empty.py"
    p.write_text("x = 1\n")
    findings = graftlint.run(paths=[str(p)], rules=["fault-site"])
    assert not findings


# ------------------------------------------------------------------ CLI


def _cli(*args):
    return subprocess.run(
        [sys.executable, "-m", "tools.graftlint", *args],
        capture_output=True, text=True, cwd=REPO, timeout=120,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})


def test_cli_exit_codes_and_json():
    bad = _cli(os.path.join("tests", "fixtures", "graftlint",
                            "atomic_write", "bad.py"), "--format", "json")
    assert bad.returncode == 1, bad.stderr
    doc = json.loads(bad.stdout)
    assert doc["count"] == 2 and not doc["ok"]
    assert doc["findings"][0]["rule"] == "atomic-write"
    assert doc["findings"][0]["line"] == 8
    assert doc["findings"][0]["col"] == 10

    good = _cli(os.path.join("tests", "fixtures", "graftlint",
                             "atomic_write", "good.py"))
    assert good.returncode == 0, good.stdout + good.stderr
    assert "graftlint: clean" in good.stdout

    usage = _cli("--rules", "no-such-rule")
    assert usage.returncode == 2


def test_cli_list_rules_names_every_analyzer():
    p = _cli("--list-rules")
    assert p.returncode == 0
    for rule_id in ANALYZERS:
        assert rule_id in p.stdout


def test_cli_lint_verb_is_jax_free():
    """`python -m spark_examples_tpu lint` is the thin wrapper — and it
    must run device-free (the whole point of the purity contract)."""
    p = subprocess.run(
        [sys.executable, "-c",
         "import sys\n"
         "from spark_examples_tpu.cli.main import main\n"
         "rc = main(['lint', '--list-rules'])\n"
         "assert 'jax' not in sys.modules, 'lint verb imported jax'\n"
         "sys.exit(rc)"],
        capture_output=True, text=True, cwd=REPO, timeout=120)
    assert p.returncode == 0, p.stdout + p.stderr
    assert "registry-literal" in p.stdout

"""Multi-process (DCN-analogue) test: two `jax.distributed` processes on
localhost run a variant-mode gram update together.

The reference's multi-node story was Spark executors coordinating over
netty; the rebuild's is `jax.distributed` (gRPC coordinator = the DCN
control plane) + XLA collectives across process-spanning meshes
(SURVEY.md §2.2 "Distributed communication backend"). The in-process
virtual-CPU mesh (conftest) cannot exercise that coordinator path, so
this test launches two real OS processes, each owning 2 virtual CPU
devices of a shared 4-device mesh, streams each process its half of the
variant axis, and checks the psum-merged accumulator matches the
single-process oracle bit-for-bit.
"""

import json
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_WORKER = r"""
import json, os, sys
import numpy as np

# Env vars alone lose to this image's sitecustomize (which registers the
# axon TPU plugin at interpreter startup); the jax.config update inside
# force_virtual_cpu is what actually pins the CPU backend — same
# bootstrap as tests/conftest.py, but per-process here.
from spark_examples_tpu.core.virtual import force_virtual_cpu
force_virtual_cpu(2)

import jax

from spark_examples_tpu.core import meshes
from spark_examples_tpu.ops import gram as gram_ops
from spark_examples_tpu.parallel import gram_sharded

meshes.maybe_init_distributed()  # the code path under test
assert jax.process_count() == 2, jax.process_count()
assert len(jax.devices()) == 4, jax.devices()

N, V = 24, 64
METRIC = "ibs"

mesh = meshes.make_mesh()  # global (2, 2) over both processes
plan = gram_sharded.plan_for(mesh, N, METRIC, "variant")
update = gram_sharded.make_update(plan, METRIC, packed=False)

# Same seeded cohort in both processes (the driver replicates metadata;
# the data plane is sharded by the block_sharding placement below).
rng = np.random.default_rng(99)
g = rng.integers(0, 3, size=(N, V), dtype=np.int8)
g[rng.random((N, V)) < 0.15] = -1

acc = jax.jit(
    lambda: gram_ops.init(N, METRIC),
    out_shardings={
        k: plan.acc_sharding for k in gram_ops.PIECES_FOR_METRIC[METRIC]
    },
)()

# Two blocks, each device_put across the process-spanning mesh: each
# process materialises only its addressable variant shards.
for blk in (g[:, : V // 2], g[:, V // 2 :]):
    block = jax.make_array_from_callback(
        blk.shape, plan.block_sharding, lambda idx, b=blk: b[idx]
    )
    acc = update(acc, block)

# Variant mode replicates the accumulator: every process holds the full
# psum-merged matrix in each addressable shard.
got = {k: np.asarray(v.addressable_data(0)) for k, v in acc.items()}
from spark_examples_tpu.utils import oracle
want = oracle.cpu_gram_products(g, gram_ops.PIECES_FOR_METRIC[METRIC])
err = max(
    float(np.abs(got[k] - np.asarray(want[k], np.int64)).max()) for k in got
)
print(json.dumps({"process": jax.process_index(), "max_err": err}))
"""


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_two_process_variant_gram():
    port = _free_port()
    procs = []
    for pid in (0, 1):
        env = dict(os.environ)
        env.update(
            JAX_PLATFORMS="cpu",
            XLA_FLAGS="--xla_force_host_platform_device_count=2",
            JAX_COORDINATOR_ADDRESS=f"127.0.0.1:{port}",
            JAX_NUM_PROCESSES="2",
            JAX_PROCESS_ID=str(pid),
            PYTHONPATH=REPO + os.pathsep + os.environ.get("PYTHONPATH", ""),
        )
        procs.append(
            subprocess.Popen(
                [sys.executable, "-c", _WORKER], env=env, cwd=REPO,
                stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            )
        )
    outs = []
    for p in procs:
        try:
            out, err = p.communicate(timeout=240)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail("distributed worker timed out (coordinator stall)")
        assert p.returncode == 0, f"worker failed:\n{err[-2000:]}"
        outs.append(json.loads(out.strip().splitlines()[-1]))
    assert {o["process"] for o in outs} == {0, 1}
    assert all(o["max_err"] == 0.0 for o in outs), outs

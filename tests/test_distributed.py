"""Multi-process (DCN-analogue) test: two `jax.distributed` processes on
localhost run a variant-mode gram update together.

The reference's multi-node story was Spark executors coordinating over
netty; the rebuild's is `jax.distributed` (gRPC coordinator = the DCN
control plane) + XLA collectives across process-spanning meshes
(SURVEY.md §2.2 "Distributed communication backend"). The in-process
virtual-CPU mesh (conftest) cannot exercise that coordinator path, so
this test launches two real OS processes, each owning 2 virtual CPU
devices of a shared 4-device mesh, streams each process its half of the
variant axis, and checks the psum-merged accumulator matches the
single-process oracle bit-for-bit.
"""

import json
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_WORKER = r"""
import json, os, sys
import numpy as np

# Env vars alone lose to this image's sitecustomize (which registers the
# axon TPU plugin at interpreter startup); the jax.config update inside
# force_virtual_cpu is what actually pins the CPU backend — same
# bootstrap as tests/conftest.py, but per-process here.
from spark_examples_tpu.core.virtual import force_virtual_cpu
force_virtual_cpu(2)

import jax

from spark_examples_tpu.core import meshes
from spark_examples_tpu.ops import gram as gram_ops
from spark_examples_tpu.parallel import gram_sharded

meshes.maybe_init_distributed()  # the code path under test
assert jax.process_count() == 2, jax.process_count()
assert len(jax.devices()) == 4, jax.devices()

N, V = 24, 64
METRIC = "ibs"

mesh = meshes.make_mesh()  # global (2, 2) over both processes
plan = gram_sharded.plan_for(mesh, N, METRIC, "variant")
update = gram_sharded.make_update(plan, METRIC, packed=False)

# Same seeded cohort in both processes (the driver replicates metadata;
# the data plane is sharded by the block_sharding placement below).
rng = np.random.default_rng(99)
g = rng.integers(0, 3, size=(N, V), dtype=np.int8)
g[rng.random((N, V)) < 0.15] = -1

acc = jax.jit(
    lambda: gram_ops.init(N, METRIC),
    out_shardings={
        k: plan.acc_sharding for k in gram_ops.PIECES_FOR_METRIC[METRIC]
    },
)()

# Two blocks, each device_put across the process-spanning mesh: each
# process materialises only its addressable variant shards.
for blk in (g[:, : V // 2], g[:, V // 2 :]):
    block = jax.make_array_from_callback(
        blk.shape, plan.block_sharding, lambda idx, b=blk: b[idx]
    )
    acc = update(acc, block)

# Variant mode replicates the accumulator: every process holds the full
# psum-merged matrix in each addressable shard.
got = {k: np.asarray(v.addressable_data(0)) for k, v in acc.items()}
from spark_examples_tpu.utils import oracle
want = oracle.cpu_gram_products(g, gram_ops.PIECES_FOR_METRIC[METRIC])
err = max(
    float(np.abs(got[k] - np.asarray(want[k], np.int64)).max()) for k in got
)
print(json.dumps({"process": jax.process_index(), "max_err": err}))
"""


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _run_two_process(worker_src: str, extra_env: dict | None = None) -> list[dict]:
    """Launch two coordinated jax.distributed workers on localhost and
    return their parsed JSON outputs (shared harness for every
    multi-process test in this file).

    gloo's TCP transport has a rare preamble-size race under full-suite
    load (`gloo::EnforceNotMet ... op.preamble.length <= op.nbytes`,
    SIGABRT) that is unrelated to the code under test — one bounded
    retry on exactly that signature; any other failure surfaces
    immediately."""
    last_gloo_err = None
    for _attempt in range(2):
        outs, gloo_race = _run_two_process_once(worker_src, extra_env)
        if not gloo_race:
            return outs
        last_gloo_err = gloo_race
    pytest.fail(f"gloo transport race persisted across retry:\n{last_gloo_err}")


def _run_two_process_once(worker_src, extra_env):
    port = _free_port()
    procs = []
    for pid in (0, 1):
        env = dict(os.environ)
        env.update(
            JAX_PLATFORMS="cpu",
            XLA_FLAGS="--xla_force_host_platform_device_count=2",
            JAX_COORDINATOR_ADDRESS=f"127.0.0.1:{port}",
            JAX_NUM_PROCESSES="2",
            JAX_PROCESS_ID=str(pid),
            PYTHONPATH=REPO + os.pathsep + os.environ.get("PYTHONPATH", ""),
        )
        env.update(extra_env or {})
        procs.append(
            subprocess.Popen(
                [sys.executable, "-c", worker_src], env=env, cwd=REPO,
                stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            )
        )
    results = []
    try:
        for p in procs:
            try:
                out, err = p.communicate(timeout=240)
            except subprocess.TimeoutExpired:
                pytest.fail(
                    "distributed worker timed out (coordinator stall)"
                )
            results.append((p.returncode, out, err))
    finally:
        for q in procs:  # reap siblings on any failure path
            if q.poll() is None:
                q.kill()
    if any(rc != 0 for rc, _, _ in results):
        # Classify AFTER collecting both workers: the gloo preamble
        # race may hit either one, and its sibling then dies with only
        # coordination-service heartbeat noise in stderr.
        for rc, _, err in results:
            if rc != 0 and "gloo::EnforceNotMet" in err:
                return [], err[-2000:]
        rc, _, err = next(r for r in results if r[0] != 0)
        pytest.fail(f"worker failed (rc={rc}):\n{err[-2000:]}")
    outs = [json.loads(out.strip().splitlines()[-1]) for _, out, _ in results]
    assert {o["process"] for o in outs} == {0, 1}
    return outs, None


def test_two_process_variant_gram():
    outs = _run_two_process(_WORKER)
    assert all(o["max_err"] == 0.0 for o in outs), outs


_TILE2D_WORKER = r"""
import json, os, sys
import numpy as np

from spark_examples_tpu.core.virtual import force_virtual_cpu
force_virtual_cpu(2)

import jax

from spark_examples_tpu.core import meshes
from spark_examples_tpu.models.pcoa import fit_pcoa
from spark_examples_tpu.ops import distances, gram as gram_ops
from spark_examples_tpu.parallel import gram_sharded
from spark_examples_tpu.parallel.pcoa_sharded import pcoa_coords_sharded

meshes.maybe_init_distributed()
assert jax.process_count() == 2, jax.process_count()

N, V = 32, 96
mesh = meshes.make_mesh()  # (2, 2) spanning both processes
plan = gram_sharded.GramPlan(mesh, "tile2d")
update = gram_sharded.make_update(plan, "ibs")
acc = gram_sharded.init_sharded(plan, N, "ibs")

rng = np.random.default_rng(7)
g = rng.integers(0, 3, size=(N, V), dtype=np.int8)
g[rng.random((N, V)) < 0.1] = -1

for s in range(0, V, 32):
    acc = update(acc, g[:, s : s + 32])

# The config-4 route across PROCESSES: finalize/center/randomized eigh
# all tile2d-sharded over the 2x2 process-spanning mesh; the collectives
# in the sharded matmuls and mesh transposes ride the DCN analogue.
res = pcoa_coords_sharded(plan, acc, "ibs", k=3, check_shardings=True)
coords = np.asarray(res.coords)

# Single-process oracle: dense accumulate + dense-route PCoA.
dense = gram_ops.init(N, "ibs")
for s in range(0, V, 32):
    dense = gram_ops.update(dense, g[:, s : s + 32], "ibs")
dist = distances.finalize(dense, "ibs")["distance"]
want = fit_pcoa(np.asarray(dist), k=3, method="randomized")
err = float(np.max(np.abs(np.abs(coords) - np.abs(np.asarray(want.coords)))))
print(json.dumps({"process": jax.process_index(), "max_err": err}))
"""


def test_two_process_tile2d_sharded_solve():
    """The 76k route's multi-host story: tile2d accumulation AND the
    fully-sharded finalize/center/eigh running across two real
    processes on a shared (2, 2) mesh, matching the dense route."""
    outs = _run_two_process(_TILE2D_WORKER)
    assert all(o["max_err"] < 1e-3 for o in outs), outs


# The JOB surface — pcoa_job end to end, not hand-built arrays: each
# process builds its own range-partitioned source (build_source windows
# it), streams only its share, and the consensus-stepped feeder
# (parallel/multihost.py) assembles global variant-sharded blocks.
# n_variants = 1280 with 256-wide blocks -> 5 blocks: process 0 gets 3,
# process 1 gets 2, so the final consensus step also exercises the
# missing-slab straggler path.
_JOB_WORKER = r"""
import json, os, sys
import numpy as np

from spark_examples_tpu.core.virtual import force_virtual_cpu
force_virtual_cpu(2)

import jax

from spark_examples_tpu.core.config import (
    ComputeConfig, IngestConfig, JobConfig,
)
from spark_examples_tpu.pipelines.jobs import pcoa_job
from spark_examples_tpu.pipelines.runner import build_source

job = JobConfig(
    ingest=IngestConfig(source="synthetic", n_samples=24, n_variants=1280,
                        block_variants=256, seed=5),
    compute=ComputeConfig(gram_mode=os.environ["GRAM_MODE"],
                          eigh_mode="randomized", num_pc=3, metric="ibs"),
)
src = build_source(job.ingest)  # inits jax.distributed, windows the source
assert jax.process_count() == 2, jax.process_count()
out = pcoa_job(job, source=src)
print(json.dumps({
    "process": jax.process_index(),
    "local_n_variants": int(src.n_variants),
    "n_variants": int(out.n_variants),
    "coords": np.abs(out.coords).tolist(),
}))
"""


def _single_process_job_coords(mode: str):
    import numpy as np

    from spark_examples_tpu.core.config import (
        ComputeConfig, IngestConfig, JobConfig,
    )
    from spark_examples_tpu.pipelines.jobs import pcoa_job

    job = JobConfig(
        ingest=IngestConfig(source="synthetic", n_samples=24,
                            n_variants=1280, block_variants=256, seed=5),
        compute=ComputeConfig(gram_mode=mode, eigh_mode="randomized",
                              num_pc=3, metric="ibs"),
    )
    return np.abs(pcoa_job(job).coords)


@pytest.mark.parametrize("mode", ["variant", "tile2d"])
def test_two_process_pcoa_job_end_to_end(mode):
    """VERDICT r3 #1: the real job surface under jax.distributed.

    pcoa_job (ingest -> sharded gram -> solve -> coords) across two
    processes, each demonstrably reading only its window of the input,
    matching the single-process job bit-for-tolerance."""
    outs = _run_two_process(_JOB_WORKER, extra_env={"GRAM_MODE": mode})
    want = _single_process_job_coords(mode)
    locals_ = sorted(o["local_n_variants"] for o in outs)
    assert locals_ == [512, 768], locals_  # partitioned, not replicated
    for o in outs:
        assert o["n_variants"] == 1280, o  # global total re-assembled
        got = np.asarray(o["coords"])
        assert got.shape == want.shape
        assert float(np.max(np.abs(got - want))) < 1e-3


# Feeder control-plane cost (VERDICT r4 weak #6): exact-length sources
# agree on the step count in ONE upfront allgather; unknown-length
# sources fall back to one consensus per consensus_every blocks. The
# worker streams the same 64-block partition both ways and reports the
# round counts plus throughput; the parent asserts the amortization and
# that both modes assemble identical global totals.
_FEEDER_WORKER = r"""
import json, time
import numpy as np

from spark_examples_tpu.core.virtual import force_virtual_cpu
force_virtual_cpu(2)

import jax

from spark_examples_tpu.core import meshes
from spark_examples_tpu.ingest.source import WindowSource, window_for_process
from spark_examples_tpu.ingest.synthetic import SyntheticSource
from spark_examples_tpu.parallel import gram_sharded, multihost as mh

meshes.maybe_init_distributed()
N, V, BV = 16, 16384, 128  # 128 blocks globally, 64 per process
base = SyntheticSource(n_samples=N, n_variants=V, seed=11)
start, stop = window_for_process(V, BV, jax.process_index(),
                                 jax.process_count())
src = WindowSource(base, start, stop)
mesh = meshes.make_mesh()
plan = gram_sharded.plan_for(mesh, N, "ibs", "variant")


class HiddenLength:
    # An unknown-length view of the same partition (exact_n_variants
    # deliberately absent) — forces the group-consensus fallback.
    def __init__(self, inner):
        self._inner = inner

    n_samples = property(lambda self: self._inner.n_samples)
    n_variants = property(lambda self: self._inner.n_variants)
    sample_ids = property(lambda self: self._inner.sample_ids)

    def blocks(self, bv, start=0):
        return self._inner.blocks(bv, start)


def drain(source):
    stats = {}
    t0 = time.perf_counter()
    n_blocks = n_real = width = 0
    for gblock, meta in mh.stream_global_blocks(
        source, BV, 0, plan, pack=False, stats=stats, consensus_every=8
    ):
        n_blocks += 1
        n_real += meta is not None
        width += gblock.shape[1]
    dt = time.perf_counter() - t0
    return {
        "rounds": stats.get("consensus_rounds", 0),
        "blocks": n_blocks, "real": n_real, "global_width": width,
        "blocks_per_s": round(n_blocks / dt, 1),
    }


exact = drain(src)
fallback = drain(HiddenLength(src))

# Partial-group tail: 5 local steps under consensus_every=8 -> ONE
# group of 8 steps (5 real + 3 all-padding), then the terminal gather.
small_start, small_stop = window_for_process(1280, BV, jax.process_index(),
                                             jax.process_count())
small = HiddenLength(WindowSource(
    SyntheticSource(n_samples=N, n_variants=1280, seed=11),
    small_start, small_stop,
))
partial = drain(small)
print(json.dumps({"process": jax.process_index(),
                  "exact": exact, "fallback": fallback,
                  "partial": partial}))
"""


def test_feeder_consensus_amortization():
    outs = _run_two_process(_FEEDER_WORKER)
    for o in outs:
        # 128 blocks / 2 processes = 64 steps; exact mode: one upfront
        # count round + one terminal contract-agreement round (vs 65 in
        # the naive per-block protocol).
        assert o["exact"]["rounds"] == 2, o
        assert o["exact"]["blocks"] == 64, o
        assert o["exact"]["real"] == 64, o
        # Fallback: ceil(64 / 8) has-data rounds + the terminal one,
        # plus the upfront count round that discovered -1.
        assert o["fallback"]["rounds"] == 1 + 64 // 8 + 1, o
        assert o["fallback"]["blocks"] == 64, o
        assert o["fallback"]["global_width"] == o["exact"]["global_width"], o
        # A group that outlives the data pads to the group boundary:
        # 5 real steps -> 8 yielded (3 missing-slab), 3 rounds total
        # (upfront count probe + group has-data + terminal).
        assert o["partial"]["blocks"] == 8, o
        assert o["partial"]["real"] == 5, o
        assert o["partial"]["rounds"] == 3, o


# Collective watchdog (ADVICE r5 finding 4): a broken exact_n_variants
# claim on ONE process must abort EVERY process within one agreement
# round — the old process-local AssertionError left peers parked in
# their next collective until a distributed timeout. Process 1's source
# claims one more block than it produces; both workers must observe the
# contract failure and exit cleanly (no harness timeout).
_CONTRACT_WORKER = r"""
import json
import numpy as np

from spark_examples_tpu.core.virtual import force_virtual_cpu
force_virtual_cpu(2)

import jax

from spark_examples_tpu.core import meshes
from spark_examples_tpu.ingest.source import WindowSource, window_for_process
from spark_examples_tpu.ingest.synthetic import SyntheticSource
from spark_examples_tpu.parallel import gram_sharded, multihost as mh

meshes.maybe_init_distributed()
N, V, BV = 16, 1024, 128
base = SyntheticSource(n_samples=N, n_variants=V, seed=3)
start, stop = window_for_process(V, BV, jax.process_index(),
                                 jax.process_count())
src = WindowSource(base, start, stop)

if jax.process_index() == 1:
    inner = src

    class Lying:
        exact_n_variants = True
        n_samples = inner.n_samples
        n_variants = inner.n_variants + BV  # claims one block it lacks
        sample_ids = inner.sample_ids
        def blocks(self, bv, start=0):
            return inner.blocks(bv, start)
    src = Lying()

mesh = meshes.make_mesh()
plan = gram_sharded.plan_for(mesh, N, "ibs", "variant")
outcome = "completed"
try:
    for _ in mh.stream_global_blocks(src, BV, 0, plan, pack=False):
        pass
except RuntimeError as e:
    outcome = "contract" if "contract is broken" in str(e) else f"wrong: {e}"
print(json.dumps({"process": jax.process_index(), "outcome": outcome}))
"""


def test_two_process_contract_violation_aborts_globally():
    outs = _run_two_process(_CONTRACT_WORKER)
    # BOTH processes — including the honest one — fail in the agreement
    # round instead of one raising locally and the peer hanging.
    assert all(o["outcome"] == "contract" for o in outs), outs


# Straggler injection: process 1's control plane is delayed at every
# consensus round (core/faults.py "delay" kind, armed in-process so the
# fault is asymmetric); the collectives must absorb the skew and the
# job's coordinates must match the single-process run.
_STRAGGLER_WORKER = r"""
import json, os
import numpy as np

from spark_examples_tpu.core.virtual import force_virtual_cpu
force_virtual_cpu(2)

import jax

from spark_examples_tpu.core import faults
from spark_examples_tpu.core.config import (
    ComputeConfig, IngestConfig, JobConfig,
)
from spark_examples_tpu.pipelines.jobs import pcoa_job
from spark_examples_tpu.pipelines.runner import build_source

job = JobConfig(
    ingest=IngestConfig(source="synthetic", n_samples=24, n_variants=1280,
                        block_variants=256, seed=5),
    compute=ComputeConfig(gram_mode="variant", eigh_mode="randomized",
                          num_pc=3, metric="ibs"),
)
src = build_source(job.ingest)
assert jax.process_count() == 2
if jax.process_index() == 1:  # only one process straggles
    faults.arm(["multihost.consensus:delay:delay=0.1:max=0"])
out = pcoa_job(job, source=src)
print(json.dumps({
    "process": jax.process_index(),
    "fires": faults.fire_count("multihost.consensus"),
    "coords": np.abs(out.coords).tolist(),
}))
"""


def test_two_process_straggler_delay_absorbed():
    outs = _run_two_process(_STRAGGLER_WORKER)
    want = _single_process_job_coords("variant")
    for o in outs:
        if o["process"] == 1:
            assert o["fires"] >= 2, o  # upfront + terminal rounds
        got = np.asarray(o["coords"])
        assert float(np.max(np.abs(got - want))) < 1e-3, o


# VERDICT r5 task 6: multi-host checkpoint/resume. Both processes
# stream their partitions with per-block checkpointing into a SHARED
# directory, die together at consensus step 2 (the on_block bomb fires
# at the same step on every process, so the SPMD collectives never
# desynchronize), then resume from per-process cursors and must match
# the single-process oracle bit for bit. Exercises every _barrier /
# cursor-gather / primary-rotation path in core/checkpoint.py under
# process_count=2, in both accumulator layouts (replicated leaves in
# variant mode, per-process tile files in tile2d).
_CKPT_WORKER = r"""
import json, os
import numpy as np

from spark_examples_tpu.core.virtual import force_virtual_cpu
force_virtual_cpu(2)

import jax

from spark_examples_tpu.core.config import (
    ComputeConfig, IngestConfig, JobConfig,
)
from spark_examples_tpu.core.profiling import PhaseTimer
from spark_examples_tpu.ingest.synthetic import SyntheticSource
from spark_examples_tpu.ops import gram as gram_ops
from spark_examples_tpu.pipelines import runner
from spark_examples_tpu.utils import oracle

mode = os.environ["GRAM_MODE"]
ckpt_dir = os.environ["CKPT_DIR"]
job = JobConfig(
    ingest=IngestConfig(source="synthetic", n_samples=24, n_variants=1280,
                        block_variants=256, seed=5),
    compute=ComputeConfig(gram_mode=mode, metric="ibs",
                          checkpoint_dir=ckpt_dir,
                          checkpoint_every_blocks=1),
)
src = runner.build_source(job.ingest)
assert jax.process_count() == 2


def bomb(acc, blocks_done, meta):
    if blocks_done == 2:
        raise RuntimeError("simulated preemption")


died = False
try:
    runner.run_gram(job, src, PhaseTimer(), on_block=bomb)
except RuntimeError as e:
    died = "preemption" in str(e)
assert died, "bomb never fired"
manifest = json.load(open(os.path.join(ckpt_dir, "manifest.json")))
assert manifest["process_count"] == 2, manifest
# Both processes checkpointed after consensus step 1 -> cursor 256 each.
assert manifest["cursors"] == {"0": 256, "1": 256}, manifest
tile_files = [f for f in os.listdir(ckpt_dir) if ".t" in f]
if mode == "tile2d":
    assert tile_files, "tile2d checkpoint wrote no per-tile files"

# Resume: a fresh partition source, cursors from the checkpoint.
grun = runner.run_gram(job, runner.build_source(job.ingest), PhaseTimer())
assert grun.n_variants == 1280, grun.n_variants

# Bit-exact parity with the full-cohort CPU oracle, shard by shard.
full = SyntheticSource(n_samples=24, n_variants=1280, seed=5)
g = np.concatenate([b for b, _ in full.blocks(256)], axis=1)
want = oracle.cpu_gram_products(g, gram_ops.PIECES_FOR_METRIC["ibs"])
err = 0.0
for k, v in grun.acc.items():
    for sh in v.addressable_shards:
        got = np.asarray(sh.data)
        ref = np.asarray(want[k], np.int64)[sh.index]
        err = max(err, float(np.abs(got - ref).max()))
print(json.dumps({"process": jax.process_index(), "max_err": err,
                  "mode": grun.plan.mode}))
"""


@pytest.mark.parametrize("mode", ["variant", "tile2d"])
def test_two_process_checkpoint_resume(tmp_path, mode):
    outs = _run_two_process(
        _CKPT_WORKER,
        extra_env={"GRAM_MODE": mode, "CKPT_DIR": str(tmp_path / "ck")},
    )
    for o in outs:
        assert o["max_err"] == 0.0, o
        assert o["mode"] == mode, o

    # Process-count mismatch rejection: this (single-process) test
    # process must be refused the 2-process checkpoint outright.
    import jax

    from spark_examples_tpu.core import checkpoint as ckpt
    from spark_examples_tpu.ingest.synthetic import SyntheticSource

    assert jax.process_count() == 1
    ids = SyntheticSource(n_samples=24, n_variants=1280, seed=5).sample_ids
    with pytest.raises(ValueError, match="do not transfer"):
        ckpt.load(str(tmp_path / "ck"), "ibs", ids, block_variants=256)


# VERDICT r5 task 9: the streaming incremental-PCoA job across two
# processes — proves the lockstep-refresh contract (streaming.py: every
# process enters the collective refresh jit at the same shared
# blocks_done, even on steps where it fed a padding slab) does not
# deadlock, and the final tightened coordinates match the
# single-process run.
_STREAM_WORKER = r"""
import json, os
import numpy as np

from spark_examples_tpu.core.virtual import force_virtual_cpu
force_virtual_cpu(2)

import jax

from spark_examples_tpu.core.config import (
    ComputeConfig, IngestConfig, JobConfig,
)
from spark_examples_tpu.pipelines.runner import build_source
from spark_examples_tpu.pipelines.streaming import incremental_pcoa_job

job = JobConfig(
    ingest=IngestConfig(source="synthetic", n_samples=24, n_variants=1280,
                        block_variants=256, seed=5),
    compute=ComputeConfig(gram_mode=os.environ["GRAM_MODE"],
                          num_pc=3, metric="ibs",
                          stream_refresh_blocks=2),
)
src = build_source(job.ingest)
assert jax.process_count() == 2
out, snaps = incremental_pcoa_job(job, source=src)
assert snaps, "no mid-stream snapshot was emitted"
for s in snaps:
    assert np.isfinite(np.asarray(s.coords)).all()
print(json.dumps({
    "process": jax.process_index(),
    "n_variants": int(out.n_variants),
    "snapshots": len(snaps),
    "coords": np.abs(out.coords).tolist(),
}))
"""


@pytest.mark.parametrize("mode", ["variant", "tile2d"])
def test_two_process_incremental_pcoa(mode):
    outs = _run_two_process(_STREAM_WORKER, extra_env={"GRAM_MODE": mode})

    import numpy as np

    from spark_examples_tpu.core.config import (
        ComputeConfig, IngestConfig, JobConfig,
    )
    from spark_examples_tpu.pipelines.streaming import incremental_pcoa_job

    job = JobConfig(
        ingest=IngestConfig(source="synthetic", n_samples=24,
                            n_variants=1280, block_variants=256, seed=5),
        compute=ComputeConfig(gram_mode=mode, num_pc=3, metric="ibs",
                              stream_refresh_blocks=2),
    )
    ref, _snaps = incremental_pcoa_job(job)
    want = np.abs(ref.coords)
    for o in outs:
        assert o["n_variants"] == 1280, o
        # 3 consensus steps -> one mid-stream refresh at step 2 (the
        # single-process run sees 5 local blocks, a different cadence —
        # only the final tightened solve must agree).
        assert o["snapshots"] == 1, o
        got = np.asarray(o["coords"])
        assert float(np.max(np.abs(got - want))) < 1e-3, o


# Multi-host cross-cohort jobs: each process accumulates its variant
# partition's (A, N_ref) statistics locally, then one additive
# cross-process merge reproduces the single-host result exactly; the
# unsupported tile2d cross plan refuses up front instead of corrupting.
_CROSS_WORKER = r"""
import json, os, tempfile
import numpy as np

from spark_examples_tpu.core.virtual import force_virtual_cpu
force_virtual_cpu(2)

import jax

from spark_examples_tpu.core.config import (
    ComputeConfig, IngestConfig, JobConfig,
)
from spark_examples_tpu.pipelines.project import cross_kinship_job
from spark_examples_tpu.pipelines.runner import build_source

ingest_new = IngestConfig(source="synthetic", n_samples=8, n_variants=1280,
                          block_variants=256, seed=5)
ingest_ref = IngestConfig(source="synthetic", n_samples=8, n_variants=1280,
                          block_variants=256, seed=5)
job = JobConfig(ingest=ingest_new, compute=ComputeConfig(metric="king"))
src_new = build_source(ingest_new)   # per-process window
src_ref = build_source(ingest_ref)
assert jax.process_count() == 2
res = cross_kinship_job(job, src_new, src_ref)
print(json.dumps({
    "process": jax.process_index(),
    "local_variants": int(src_new.n_variants),
    "n_variants": int(res.n_variants),
    "phi": np.asarray(res.similarity).tolist(),
}))
"""


def test_two_process_cross_kinship_matches_single():
    outs = _run_two_process(_CROSS_WORKER)

    import numpy as np

    from spark_examples_tpu.core.config import (
        ComputeConfig, IngestConfig, JobConfig,
    )
    from spark_examples_tpu.ingest.synthetic import SyntheticSource
    from spark_examples_tpu.pipelines.project import cross_kinship_job

    src = SyntheticSource(n_samples=8, n_variants=1280, seed=5)
    job = JobConfig(ingest=IngestConfig(block_variants=256),
                    compute=ComputeConfig(metric="king"))
    want = cross_kinship_job(job, src,
                             SyntheticSource(n_samples=8, n_variants=1280,
                                             seed=5)).similarity
    locals_ = sorted(o["local_variants"] for o in outs)
    assert locals_ == [512, 768], locals_  # genuinely partitioned
    for o in outs:
        assert o["n_variants"] == 1280, o  # merged global count
        np.testing.assert_array_equal(np.asarray(o["phi"]), want)
    # Same individuals in both cohorts -> diagonal phi ~ 0.5.
    assert (np.diag(want) > 0.45).all()


_CROSS_TILE2D_GUARD = r"""
import json
import numpy as np

from spark_examples_tpu.core.virtual import force_virtual_cpu
force_virtual_cpu(2)

import jax

from spark_examples_tpu.core import meshes
from spark_examples_tpu.core.config import (
    ComputeConfig, IngestConfig, JobConfig,
)
from spark_examples_tpu.core.profiling import PhaseTimer
from spark_examples_tpu.ingest.source import ArraySource
from spark_examples_tpu.pipelines.project import _accumulate_cross

meshes.maybe_init_distributed()
assert jax.process_count() == 2
g = np.zeros((8, 64), np.int8)
job = JobConfig(ingest=IngestConfig(block_variants=32),
                compute=ComputeConfig(metric="ibs", gram_mode="tile2d"))
try:
    _accumulate_cross(job, ArraySource(g), ArraySource(g), ("m", "d1"),
                      PhaseTimer())
    outcome = "ran"
except ValueError as e:
    outcome = "refused" if "single-host" in str(e) else f"wrong: {e}"
print(json.dumps({"process": jax.process_index(), "outcome": outcome}))
"""


def test_cross_tile2d_refuses_multihost():
    outs = _run_two_process(_CROSS_TILE2D_GUARD)
    assert all(o["outcome"] == "refused" for o in outs), outs

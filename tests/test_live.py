"""Live telemetry plane (core/telemetry.py periodic flusher +
core/live.py surfaces + core/stitch.py restart stitching).

The acceptance story (ISSUE 9): a supervised streaming job killed and
restarted mid-run yields (a) a scrapeable /metrics endpoint that stays
live across the restart via the parent proxy and (b) ONE stitched
Perfetto trace spanning both attempts with a restart marker — proven by
subprocess tests at the bottom. The unit layers above them pin the
pieces: snapshot atomicity under concurrent writers, crash-flush /
periodic-flush interaction, the telemetry.flush fault site, Prometheus
rendering, the sidecar, and the proxy's stale-answer behavior.
"""

import json
import os
import subprocess
import sys
import threading
import time
import urllib.request

import pytest

from spark_examples_tpu.core import faults, live, stitch, supervisor, telemetry

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _fresh_telemetry():
    telemetry.reset()
    yield
    telemetry.stop_periodic_flush()
    telemetry.configure(dir=None)
    telemetry.reset()


def _get(url: str, timeout: float = 10.0) -> bytes:
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return r.read()


# ---------------------------------------------------------------- snapshot API


def test_live_snapshot_carries_identity_and_recent_events(tmp_path):
    telemetry.configure(dir=str(tmp_path), trace_events=True)
    telemetry.count("faults.fired")
    for _ in range(3):
        with telemetry.span("checkpoint.save", cat="checkpoint"):
            pass
    snap = telemetry.live_snapshot(recent=2)
    assert snap["counters"]["faults.fired"] == 1
    assert snap["histograms"]["checkpoint.save"]["count"] == 3
    assert len(snap["recent_events"]) == 2  # the rolling ring, not all
    assert snap["meta"]["run_id"] and snap["meta"]["attempt"] == 0
    assert snap["meta"]["epoch_unix_s"] <= snap["meta"]["wrote_unix_s"]


def test_recent_events_ring_excludes_the_flushers_own_spans(tmp_path):
    """During a stall the flusher keeps publishing while the job emits
    nothing — its own live.flush spans must not displace the job events
    the ring preserves for the killed-attempt stitch fallback."""
    telemetry.configure(dir=str(tmp_path), trace_events=True)
    with telemetry.span("gram.block", cat="gram"):
        pass
    for _ in range(telemetry.RECENT_EVENTS + 8):  # > ring capacity
        with telemetry.span("live.flush", cat="live"):
            pass
    names = {ev["name"] for ev in telemetry.recent_events()}
    assert "live.flush" not in names
    assert "gram.block" in names  # the job event survived the flood


def test_progress_token_ignores_live_plane_counters():
    """A flusher publishing (or an operator scraping) every few seconds
    must not make a stalled job look alive to the watchdog — including
    once the trace buffer is full, when every flusher span advances
    telemetry.dropped_events on pure wall-clock."""
    t0 = supervisor.progress_token()
    telemetry.count("live.flushes")
    telemetry.count("live.requests", 5)
    telemetry.observe("live.flush", 0.001)
    telemetry.count("telemetry.dropped_events")  # full-buffer flushes
    assert supervisor.progress_token() == t0
    telemetry.count("faults.fired")  # real instrumented work does move it
    assert supervisor.progress_token() > t0


# ------------------------------------------------------------ periodic flusher


def test_periodic_flusher_publishes_atomic_monotonic_snapshots(tmp_path):
    """Satellite: concurrent observe() during snapshots must never
    produce a torn or non-monotonic export — every read of the
    published metrics.json parses, histogram counts only grow, and
    the final publish holds every sample."""
    telemetry.configure(dir=str(tmp_path), trace_events=True)
    stop = threading.Event()
    wrote = [0]

    def hammer():
        while not stop.is_set():
            telemetry.observe("serve.latency_s", 0.001)
            wrote[0] += 1

    threads = [threading.Thread(target=hammer) for _ in range(4)]
    for t in threads:
        t.start()
    flusher = telemetry.start_periodic_flush(0.005)
    path = tmp_path / "rank0" / "metrics.json"
    last_count = -1
    reads = 0
    deadline = time.time() + 3.0
    try:
        while time.time() < deadline and reads < 40:
            try:
                with open(path) as f:
                    snap = json.load(f)  # atomic: never torn
            except OSError:
                continue  # first flush not landed yet
            h = snap["histograms"].get("serve.latency_s", {"count": 0})
            assert h["count"] >= last_count, "non-monotonic export"
            if h["count"]:
                # internally consistent summary, not a half-recorded one
                assert h["sum"] >= h["count"] * 0.0009
                assert h["min"] <= h["p50"] <= h["max"]
            last_count = h["count"]
            reads += 1
    finally:
        stop.set()
        for t in threads:
            t.join()
    flusher.stop()
    telemetry.stop_periodic_flush()
    assert reads >= 10 and last_count > 0
    with open(path) as f:
        final = json.load(f)
    # stop() publishes one final snapshot: nothing recorded is lost
    assert final["histograms"]["serve.latency_s"]["count"] == wrote[0]
    assert final["counters"]["live.flushes"] >= 1
    # the rolling event ring parses line-by-line too
    with open(tmp_path / "rank0" / "live_trace.jsonl") as f:
        ring = [json.loads(line) for line in f if line.strip()]
    assert len(ring) <= telemetry.RECENT_EVENTS


def test_flush_fault_is_absorbed_and_counted(tmp_path):
    """The telemetry.flush chaos site: an injected io_error fails one
    flush (warned once, counted), later flushes recover, and the
    published snapshot is the last GOOD one."""
    telemetry.configure(dir=str(tmp_path), trace_events=False)
    telemetry.count("faults.fired")
    flusher = telemetry.PeriodicFlusher(str(tmp_path), interval_s=0.01)
    with faults.armed(["telemetry.flush:io_error:after=0:max=1"]):
        with pytest.warns(RuntimeWarning, match="periodic telemetry flush"):
            flusher.flush()  # the injected failure
        flusher.flush()  # recovers
    flusher.stop()
    assert telemetry.counter_value("live.flush_errors") == 1
    assert telemetry.counter_value("live.flushes") >= 1
    with open(tmp_path / "rank0" / "metrics.json") as f:
        snap = json.load(f)
    assert snap["counters"]["faults.fired"] >= 1


def test_kill_mid_flush_leaves_last_good_snapshot_readable(tmp_path):
    """Crash-flush x periodic-flush interaction: a hard kill (os._exit,
    no atexit, no SIGTERM handler) between flushes must leave the last
    periodic snapshot complete and parseable."""
    script = (
        "import os, sys, time\n"
        "from spark_examples_tpu.core import telemetry\n"
        f"telemetry.configure(dir={str(tmp_path / 'tel')!r}, "
        "trace_events=True, flush_s=0.01)\n"
        "for i in range(50):\n"
        "    telemetry.count('faults.fired')\n"
        "    telemetry.observe('serve.latency_s', 0.001)\n"
        "    time.sleep(0.005)\n"
        "os._exit(113)\n"  # preemption: no flush hooks run
    )
    p = subprocess.run(
        [sys.executable, "-c", script],
        env=dict(os.environ, PYTHONPATH=REPO + os.pathsep
                 + os.environ.get("PYTHONPATH", "")),
        capture_output=True, text=True, timeout=120)
    assert p.returncode == 113
    with open(tmp_path / "tel" / "rank0" / "metrics.json") as f:
        snap = json.load(f)  # parses: the atomic-write contract held
    assert snap["counters"]["faults.fired"] > 0
    with open(tmp_path / "tel" / "rank0" / "live_trace.jsonl") as f:
        for line in f:
            if line.strip():
                json.loads(line)


# ------------------------------------------------------- prometheus + sidecar


def test_prometheus_text_renders_every_metric_kind():
    telemetry.count("faults.fired", 3)
    telemetry.gauge_set("serve.in_flight", 2)
    telemetry.observe("serve.latency_s", 0.25)
    telemetry.count("phase.gram", 1.5)
    text = live.prometheus_text()
    assert "# TYPE faults_fired_total counter" in text
    assert "faults_fired_total 3.0" in text
    assert "serve_in_flight 2.0" in text
    assert 'phase_seconds_total{phase="gram"} 1.5' in text
    assert "# TYPE serve_latency_s summary" in text
    assert 'serve_latency_s{quantile="0.5"}' in text
    assert "serve_latency_s_count 1" in text
    assert "telemetry_info{run_id=" in text


def test_sidecar_endpoints_and_port_files(tmp_path):
    telemetry.count("faults.fired")
    port_file = tmp_path / "port"
    announce = tmp_path / "announce"
    server = live.maybe_start_live(environ={
        live.ENV_PORT: "0",
        live.ENV_PORT_FILE: str(port_file),
        live.ENV_ANNOUNCE: str(announce),
    })
    assert server is not None
    try:
        assert int(port_file.read_text()) == server.port
        assert announce.read_text() == f"127.0.0.1:{server.port}"
        base = f"http://127.0.0.1:{server.port}"
        assert b"faults_fired_total" in _get(f"{base}/metrics")
        debug = json.loads(_get(f"{base}/debug/telemetry"))
        assert debug["counters"]["faults.fired"] == 1
        health = json.loads(_get(f"{base}/healthz"))
        assert health["ok"] and health["run_id"]
        assert telemetry.counter_value("live.requests") == 3
    finally:
        server.shutdown()


def test_maybe_start_live_is_opt_in():
    assert live.maybe_start_live(environ={}) is None


# ---------------------------------------------------------------------- proxy


def test_proxy_follows_child_and_serves_stale_when_down(tmp_path):
    """The proxy answers from the live child when it is up, and from
    the last-good cache (marked stale, supervisor series appended)
    when it is down — the scrape that lands mid-restart succeeds."""
    telemetry.count("faults.fired")
    port_file = tmp_path / "child.port"
    child = live.LiveTelemetryServer(port=0, port_file=str(port_file))
    child.serve_in_thread()
    state = {"run_id": "testrun", "attempt": 0, "restarts": 0,
             "watchdog_kills": 0}
    proxy = live.SupervisorLiveProxy(
        "127.0.0.1", 0, str(port_file), lambda: dict(state))
    proxy.serve_in_thread()
    base = f"http://127.0.0.1:{proxy.port}"
    try:
        body = _get(f"{base}/metrics").decode()
        assert "faults_fired_total" in body  # the child's series
        assert "supervisor_restarts 0" in body
        assert "supervisor_scrape_stale 0" in body
        debug = json.loads(_get(f"{base}/debug/telemetry"))
        assert debug["stale"] is False
        assert debug["child"]["counters"]["faults.fired"] == 1

        child.shutdown()  # the restart window
        state["restarts"] = 1
        state["attempt"] = 1
        body = _get(f"{base}/metrics").decode()
        assert "faults_fired_total" in body  # last-good cache
        assert "supervisor_scrape_stale 1" in body
        assert "supervisor_restarts 1" in body
        assert "supervisor_child_up 0" in body
        debug = json.loads(_get(f"{base}/debug/telemetry"))
        assert debug["stale"] is True
        assert debug["supervisor"]["restarts"] == 1
        health = json.loads(_get(f"{base}/healthz"))
        assert health["ok"] and health["child_up"] is False
        assert telemetry.counter_value("live.proxy_stale") >= 2
    finally:
        proxy.shutdown()
        child.shutdown()


# --------------------------------------------------------------------- stitch


def _write_attempt(base, att, rank, epoch, run_id, events):
    d = os.path.join(base, f"attempt{att}", f"rank{rank}")
    os.makedirs(d)
    with open(os.path.join(d, "metrics.json"), "w") as f:
        json.dump({"counters": {}, "meta": {
            "rank": rank, "attempt": att, "run_id": run_id,
            "epoch_unix_s": epoch}}, f)
    with open(os.path.join(d, "trace.jsonl"), "w") as f:
        f.write(json.dumps({"name": "process_name", "ph": "M",
                            "pid": rank, "ts": 0, "args": {}}) + "\n")
        for ev in events:
            f.write(json.dumps(ev) + "\n")


def test_stitch_merges_attempts_on_one_timeline(tmp_path):
    base = str(tmp_path / "tel")
    ev = {"name": "gram.block", "cat": "gram", "ph": "X", "dur": 5.0,
          "tid": 1, "args": {}}
    _write_attempt(base, 0, 0, 1000.0, "rid1", [{**ev, "ts": 10.0}])
    _write_attempt(base, 1, 0, 1002.5, "rid1", [{**ev, "ts": 10.0}])
    with open(os.path.join(base, "supervisor.json"), "w") as f:
        json.dump({"run_id": "rid1", "restarts": 1, "incidents": [
            {"attempt": 0, "kind": "crash", "detail": "exit code 113",
             "returncode": 113, "t_unix": 1002.0}]}, f)
    report = stitch.stitch(base)
    assert report["attempts"] == [0, 1]
    assert report["events"] == 2
    assert report["restart_markers"] == 1
    assert report["run_ids"] == ["rid1"] and not report["mixed_run_ids"]
    lines = [json.loads(line)
             for line in open(report["output"]) if line.strip()]
    spans = [e for e in lines if e.get("name") == "gram.block"]
    # attempt 1's identical local ts lands 2.5 s later on the global
    # timeline, on its own pid track
    assert spans[0]["ts"] == 10.0 and spans[1]["ts"] == 2.5e6 + 10.0
    assert spans[0]["pid"] != spans[1]["pid"]
    marker = next(e for e in lines if e["name"] == "restart: crash")
    assert marker["ph"] == "i" and marker["s"] == "g"
    assert marker["ts"] == pytest.approx(2.0e6)
    names = {e["args"].get("name") for e in lines if e.get("ph") == "M"}
    assert {"attempt 0 rank 0", "attempt 1 rank 0",
            "supervisor"} <= names


def test_stitch_flags_mixed_run_ids_and_flat_layout(tmp_path):
    base = str(tmp_path / "tel")
    os.makedirs(os.path.join(base, "rank0"))
    with open(os.path.join(base, "rank0", "metrics.json"), "w") as f:
        json.dump({"meta": {"rank": 0, "attempt": 0, "run_id": "a",
                            "epoch_unix_s": 5.0}}, f)
    with open(os.path.join(base, "rank0", "trace.jsonl"), "w") as f:
        f.write(json.dumps({"name": "gram.block", "ph": "X", "ts": 1.0,
                            "dur": 1.0, "tid": 0, "args": {}}) + "\n")
    _write_attempt(base, 1, 0, 6.0, "b", [])
    report = stitch.stitch(base)
    assert report["mixed_run_ids"] and report["run_ids"] == ["a", "b"]
    assert report["events"] == 1


def test_stitch_rejects_emptiness(tmp_path):
    with pytest.raises(stitch.StitchError):
        stitch.stitch(str(tmp_path))


def test_stitch_falls_back_to_live_ring_for_killed_attempt(tmp_path):
    """A killed attempt has no exit-time trace.jsonl; its periodic
    live_trace.jsonl ring must still appear in the session trace."""
    base = str(tmp_path / "tel")
    d = os.path.join(base, "attempt0", "rank0")
    os.makedirs(d)
    with open(os.path.join(d, "metrics.json"), "w") as f:
        json.dump({"meta": {"rank": 0, "attempt": 0, "run_id": "r",
                            "epoch_unix_s": 0.0}}, f)
    with open(os.path.join(d, "live_trace.jsonl"), "w") as f:
        f.write(json.dumps({"name": "gram.block", "ph": "X", "ts": 3.0,
                            "dur": 1.0, "tid": 0, "args": {}}) + "\n")
    report = stitch.stitch(base)
    assert report["events"] == 1


# ----------------------------------------------- supervised acceptance (E2E)


def _cli_env(**extra):
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        PYTHONPATH=REPO + os.pathsep + os.environ.get("PYTHONPATH", ""),
        **{supervisor.ENV_HEARTBEAT_INTERVAL: "0.1"},
    )
    env.update(extra)
    return env


def test_supervised_kill_restart_proxy_and_stitch(tmp_path):
    """THE acceptance test: a supervised streaming job is killed
    mid-run by an injected fault and restarted; the parent's /metrics
    proxy answers before, during, and after the restart (the restart
    itself visible in the supervisor series), and `telemetry stitch`
    yields one Perfetto trace spanning both attempts with a restart
    marker."""
    tel = str(tmp_path / "tel")
    announce = tmp_path / "announce"
    env = _cli_env(**{
        # kill at the 4th host->device transfer; a per-block delay
        # widens the scrape window (stripped, like the kill, on the
        # restarted attempt)
        faults.ENV_SPECS: ("device.put:kill:after=3;"
                           "device.put:delay:delay=0.05:max=0"),
        live.ENV_ANNOUNCE: str(announce),
    })
    cmd = [
        sys.executable, "-m", "spark_examples_tpu", "similarity",
        "--n-samples", "16", "--n-variants", "2048",
        "--block-variants", "128",
        "--checkpoint-dir", str(tmp_path / "ck"),
        "--checkpoint-every-blocks", "2",
        "--telemetry-dir", tel, "--telemetry-flush-s", "0.05",
        "--live-port", "0", "--supervise",
        "--output-path", str(tmp_path / "out.tsv"),
    ]
    proc = subprocess.Popen(cmd, env=env, cwd=str(tmp_path),
                            stdout=subprocess.DEVNULL,
                            stderr=subprocess.PIPE, text=True)
    try:
        base = None
        deadline = time.time() + 60
        while base is None and time.time() < deadline:
            try:
                base = "http://" + announce.read_text().strip()
            except OSError:
                time.sleep(0.05)
        assert base, "proxy never announced its endpoint"
        scrapes = restart_seen = child_metric_seen = 0
        while proc.poll() is None and time.time() - deadline < 240:
            try:
                body = _get(f"{base}/metrics", timeout=2).decode()
            except Exception:
                time.sleep(0.05)
                continue  # transient socket teardown, keep polling
            scrapes += 1
            if "supervisor_restarts 1" in body:
                restart_seen += 1
            if "gram_block" in body:
                child_metric_seen += 1
            time.sleep(0.05)
        _, stderr = proc.communicate(timeout=60)
    finally:
        if proc.poll() is None:
            proc.kill()
    assert proc.returncode == 0, stderr[-2000:]
    assert "supervisor: attempt 0: crash: exit code 113" in stderr
    # (a) the endpoint stayed live across the restart: scrapes landed
    # throughout, and the restart itself became visible in the
    # supervisor series while the job kept running
    assert scrapes >= 5
    assert restart_seen >= 1, "restart never visible on /metrics"
    assert child_metric_seen >= 1, "child series never proxied"
    # (b) one stitched trace spanning both attempts + restart marker,
    # via the CLI verb
    p = subprocess.run(
        [sys.executable, "-m", "spark_examples_tpu", "telemetry",
         "stitch", "--path", tel],
        env=_cli_env(), capture_output=True, text=True, timeout=120)
    assert p.returncode == 0, p.stderr
    report = json.loads(p.stdout)
    assert report["attempts"] == [0, 1]
    assert report["restart_markers"] == 1
    assert not report["mixed_run_ids"]  # one run_id across attempts
    lines = [json.loads(line)
             for line in open(report["output"]) if line.strip()]
    pids = {e["pid"] for e in lines if e.get("name") == "gram.block"}
    assert len(pids) == 2, "blocks from both attempts on their tracks"
    assert any(e.get("cat") == "supervisor" for e in lines)


def test_unsupervised_live_port_sidecar_cli(tmp_path):
    """--live-port on a plain batch job: /metrics scrapeable mid-run,
    with job series present."""
    announce = tmp_path / "announce"
    env = _cli_env(**{
        live.ENV_ANNOUNCE: str(announce),
        faults.ENV_SPECS: "device.put:delay:delay=0.05:max=0",
    })
    cmd = [
        sys.executable, "-m", "spark_examples_tpu", "similarity",
        "--n-samples", "16", "--n-variants", "1024",
        "--block-variants", "128", "--live-port", "0",
        "--output-path", str(tmp_path / "out.tsv"),
    ]
    proc = subprocess.Popen(cmd, env=env, cwd=str(tmp_path),
                            stdout=subprocess.DEVNULL,
                            stderr=subprocess.PIPE, text=True)
    try:
        base = None
        deadline = time.time() + 60
        while base is None and time.time() < deadline:
            try:
                base = "http://" + announce.read_text().strip()
            except OSError:
                time.sleep(0.05)
        assert base, "sidecar never announced"
        saw_series = False
        while proc.poll() is None:
            try:
                body = _get(f"{base}/metrics", timeout=2).decode()
                if "ingest_bytes_total" in body:
                    saw_series = True
            except Exception:
                pass
            time.sleep(0.05)
        _, stderr = proc.communicate(timeout=60)
    finally:
        if proc.poll() is None:
            proc.kill()
    assert proc.returncode == 0, stderr[-2000:]
    assert saw_series, "job series never scrapeable mid-run"

import numpy as np

from spark_examples_tpu.cli.main import main
from spark_examples_tpu.core.config import ReferenceRange
from spark_examples_tpu.ingest.reads import Read, SamSource, SyntheticReadsSource
from spark_examples_tpu.pipelines.coverage import coverage


def _naive_depth(reads, ref):
    depth = np.zeros(ref.end - ref.start, np.int64)
    for start, length in reads:
        s = max(start, ref.start) - ref.start
        e = min(start + length, ref.end) - ref.start
        if e > s:
            depth[s:e] += 1
    return depth


def test_coverage_matches_naive():
    ref = ReferenceRange("chr1", 1000, 3000)
    src = SyntheticReadsSource([ref], reads_per_range=500, read_length=100,
                               seed=3)
    got = coverage(src)[0]
    reads = []
    for starts, lengths in src.read_batches(ref):
        reads += list(zip(starts, lengths))
    want = _naive_depth(reads, ref)
    np.testing.assert_array_equal(got.depth.astype(np.int64), want)
    assert got.n_reads == 500
    assert got.mean > 0
    assert got.histogram(20).sum() == 2000


def test_coverage_batching_invariant():
    ref = ReferenceRange("chrX", 0, 5000)
    src = SyntheticReadsSource([ref], reads_per_range=2000, seed=9)
    a = coverage(src, batch=100)[0].depth
    b = coverage(src, batch=100000)[0].depth
    np.testing.assert_array_equal(a, b)


def test_sam_source(tmp_path):
    ref = ReferenceRange("chr7", 0, 500)
    sam = tmp_path / "toy.sam"
    reads = [Read("r1", "chr7", 10, 50), Read("r2", "chr7", 40, 50),
             Read("r3", "chr7", 480, 50), Read("r4", "chr8", 10, 50)]
    with open(sam, "w") as f:
        f.write("@HD\tVN:1.6\n@SQ\tSN:chr7\tLN:500\n@SQ\tSN:chr8\tLN:500\n")
        for r in reads:
            f.write(
                f"{r.name}\t0\t{r.contig}\t{r.start + 1}\t60\t{r.length}M\t"
                f"*\t0\t0\t{'A' * r.length}\t*\n"
            )
    src = SamSource(str(sam), references=[ref])
    res = coverage(src)[0]
    assert res.n_reads == 3  # chr8 read excluded
    want = _naive_depth([(10, 50), (40, 50), (480, 50)], ref)
    np.testing.assert_array_equal(res.depth.astype(np.int64), want)
    # header-derived ranges
    auto = SamSource(str(sam))
    assert [r.contig for r in auto.ranges()] == ["chr7", "chr8"]


def test_cli_coverage(tmp_path, capsys):
    out = str(tmp_path / "depth.tsv")
    rc = main(["coverage", "--references", "chr22:100:1100",
               "--reads-per-range", "300", "--read-length", "50",
               "--output-path", out])
    assert rc == 0
    cap = capsys.readouterr()
    assert "reads=300" in cap.out and "mean_depth=" in cap.out
    rows = open(out).read().strip().splitlines()
    assert len(rows) == 1001  # header + 1000 positions

"""Pallas kernel and MXU-path Bray-Curtis tests (CPU interpret mode)."""

import numpy as np
import pytest

from spark_examples_tpu.ops.distances import braycurtis_matmul
from spark_examples_tpu.ops.pallas.braycurtis_kernel import (
    braycurtis_pallas,
    pairwise_manhattan_pallas,
)
from spark_examples_tpu.utils import oracle


@pytest.fixture
def otu(rng):
    # integer OTU-like counts: sparse, overdispersed
    x = rng.gamma(0.5, 40.0, size=(70, 600)) * (rng.random((70, 600)) > 0.6)
    return x.astype(np.int32).astype(np.float32)


def test_pallas_manhattan_matches_numpy(otu):
    got = np.asarray(pairwise_manhattan_pallas(otu, interpret=True))
    want = np.abs(otu[:, None, :] - otu[None, :, :]).sum(-1)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-2)


def test_pallas_braycurtis_matches_oracle(otu):
    got = np.asarray(braycurtis_pallas(otu, interpret=True))
    want = oracle.cpu_braycurtis(otu)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_braycurtis_matmul_quantization_bound(otu):
    want = oracle.cpu_braycurtis(otu)
    for levels, tol in [(64, 2e-2), (256, 6e-3)]:
        got = np.asarray(braycurtis_matmul(otu, levels=levels))
        err = np.abs(got - want).max()
        assert err < tol, f"levels={levels}: err {err}"
    # higher levels must not be less accurate (monotone refinement)
    e64 = np.abs(np.asarray(braycurtis_matmul(otu, levels=64)) - want).max()
    e512 = np.abs(np.asarray(braycurtis_matmul(otu, levels=512)) - want).max()
    assert e512 <= e64


def test_braycurtis_matmul_exact_for_binary():
    """0/1 presence-absence data lies exactly on the threshold grid."""
    rng = np.random.default_rng(4)
    x = (rng.random((40, 300)) > 0.5).astype(np.float32)
    got = np.asarray(braycurtis_matmul(x, levels=16, precise=True))
    want = oracle.cpu_braycurtis(x)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_braycurtis_matmul_pipeline_option(rng):
    from spark_examples_tpu.core.config import (
        ComputeConfig,
        IngestConfig,
        JobConfig,
    )
    from spark_examples_tpu.ingest import ArraySource
    from spark_examples_tpu.pipelines import runner

    x = np.abs(rng.integers(0, 3, (20, 256), dtype=np.int8))
    res = runner.run_similarity(
        JobConfig(
            ingest=IngestConfig(block_variants=64),
            compute=ComputeConfig(metric="braycurtis",
                                  braycurtis_method="matmul",
                                  braycurtis_levels=8),
        ),
        source=ArraySource(x.astype(np.int8)),
    )
    want = oracle.cpu_braycurtis(x.astype(np.float64))
    np.testing.assert_allclose(res.distance, want, rtol=1e-2, atol=1e-3)


def test_braycurtis_pallas_pipeline_option(rng):
    """`braycurtis_method="pallas"` is user-reachable end-to-end; on the
    CPU test backend the runner auto-selects interpret mode."""
    from spark_examples_tpu.core.config import (
        ComputeConfig,
        IngestConfig,
        JobConfig,
    )
    from spark_examples_tpu.ingest import ArraySource
    from spark_examples_tpu.pipelines import runner

    x = np.abs(rng.integers(0, 3, (20, 256), dtype=np.int8))
    res = runner.run_similarity(
        JobConfig(
            ingest=IngestConfig(block_variants=64),
            compute=ComputeConfig(metric="braycurtis",
                                  braycurtis_method="pallas"),
        ),
        source=ArraySource(x.astype(np.int8)),
    )
    want = oracle.cpu_braycurtis(x.astype(np.float64))
    np.testing.assert_allclose(res.distance, want, rtol=1e-4, atol=1e-5)


def test_braycurtis_unknown_method_rejected(rng):
    from spark_examples_tpu.core.config import (
        ComputeConfig,
        IngestConfig,
        JobConfig,
    )
    from spark_examples_tpu.ingest import ArraySource
    from spark_examples_tpu.pipelines import runner

    x = np.abs(rng.integers(0, 3, (8, 64), dtype=np.int8))
    # Since the graftlint PR the bogus method dies at CONFIG time (the
    # enum families are validated in ComputeConfig.__post_init__, flag
    # named) — before any source/runner machinery exists.
    with pytest.raises(ValueError, match="braycurtis-method"):
        ComputeConfig(metric="braycurtis", braycurtis_method="fused")
    # And a config mutated past validation still dies in the runner.
    cfg = ComputeConfig(metric="braycurtis")
    cfg.braycurtis_method = "fused"
    with pytest.raises(ValueError, match="braycurtis_method"):
        runner.run_similarity(
            JobConfig(
                ingest=IngestConfig(block_variants=64),
                compute=cfg,
            ),
            source=ArraySource(x),
        )

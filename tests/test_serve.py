"""Online projection serving: padded micro-batch bit-identity against
the offline `project` path, admission control / load-shedding,
deadlines, the LRU result cache, fault injection at serve.request,
graceful drain, the closed-loop loadgen, the HTTP front, and the
tier-1 in-process smoke test."""

import json
import urllib.error
import urllib.request

import numpy as np
import pytest

from spark_examples_tpu.core import faults, telemetry
from spark_examples_tpu.core.config import (
    ComputeConfig, IngestConfig, JobConfig,
)
from spark_examples_tpu.ingest.source import ArraySource
from spark_examples_tpu.pipelines.jobs import pcoa_job, variants_pca_job
from spark_examples_tpu.pipelines.project import pcoa_project_job
from spark_examples_tpu.serve import (
    DeadlineExceeded,
    ProjectionEngine,
    ProjectionServer,
    ServerClosed,
    ServerOverloaded,
    run_loadgen,
)
from tests.conftest import random_genotypes

BV = 128  # staging/fit block width for every test panel


@pytest.fixture(autouse=True)
def _fresh_telemetry():
    """Serve tests assert on serve.* counters; isolate them (and leave
    no export directory configured behind)."""
    telemetry.reset()
    yield
    telemetry.reset()
    telemetry.configure(dir=None)


def _fit(tmp_path, rng, kind="pcoa", n=16, v=256, num_pc=4):
    """Fit a tiny reference panel; returns (panel, model_path, job)."""
    g_ref = random_genotypes(rng, n=n, v=v, missing_rate=0.1)
    model = str(tmp_path / f"model_{kind}_{n}x{v}.npz")
    job = JobConfig(
        ingest=IngestConfig(block_variants=BV),
        compute=ComputeConfig(
            metric="ibs" if kind == "pcoa" else None, num_pc=num_pc),
        model_path=model,
    )
    fit = pcoa_job if kind == "pcoa" else variants_pca_job
    fit(job, source=ArraySource(g_ref))
    return g_ref, model, job


def _offline(job, model, g_ref, query):
    """The offline single-query `project` path — the serving contract's
    ground truth."""
    return pcoa_project_job(
        job.replace(model_path=None), model_path=model,
        source_new=ArraySource(
            query[None, :] if query.ndim == 1 else query),
        source_ref=ArraySource(g_ref),
    ).coords


@pytest.mark.parametrize("kind", ["pcoa", "pca"])
def test_batch_padding_equivalence(rng, tmp_path, kind):
    """Satellite: coordinates from padded micro-batches (sizes 1, 3,
    max, and max+1 spilling into two batches) are BIT-identical to the
    single-query offline job, for both projectable model kinds."""
    g_ref, model, job = _fit(tmp_path, rng, kind=kind, n=24, v=384)
    max_batch = 4
    engine = ProjectionEngine(model, ArraySource(g_ref),
                              block_variants=BV, max_batch=max_batch)
    queries = random_genotypes(rng, n=max_batch + 1, v=384,
                               missing_rate=0.1)
    offline = [_offline(job, model, g_ref, q) for q in queries]
    for b in (1, 3, max_batch):
        got = engine.project_batch(queries[:b])
        assert got.shape == (b, engine.n_components)
        for i in range(b):
            np.testing.assert_array_equal(got[i:i + 1], offline[i])
    # max+1 concurrent submissions must spill into a second batch and
    # still match per query.
    server = ProjectionServer(engine, max_linger_s=0.01,
                              cache_entries=0).start()
    try:
        futs = [server.submit(q) for q in queries]
        for fut, want in zip(futs, offline):
            np.testing.assert_array_equal(fut.result(timeout=60), want)
        assert server.stats.snapshot()["batches"] >= 2
    finally:
        server.close()


def test_serve_smoke(rng, tmp_path):
    """Tier-1 smoke: start in-process, one request, clean drain."""
    g_ref, model, job = _fit(tmp_path, rng, n=12, v=256)
    engine = ProjectionEngine(model, ArraySource(g_ref),
                              block_variants=BV, max_batch=2)
    query = random_genotypes(rng, n=1, v=256)[0]
    with ProjectionServer(engine) as server:
        coords = server.project(query, timeout=60)
        assert coords.shape == (1, engine.n_components)
        assert np.isfinite(coords).all()
    assert server.in_flight == 0
    with pytest.raises(ServerClosed):
        server.submit(query)


def test_result_cache_hit_and_lru_eviction(rng, tmp_path):
    g_ref, model, job = _fit(tmp_path, rng, n=12, v=256)
    engine = ProjectionEngine(model, ArraySource(g_ref),
                              block_variants=BV, max_batch=2)
    queries = random_genotypes(rng, n=3, v=256)
    server = ProjectionServer(engine, cache_entries=2).start()
    try:
        first = server.project(queries[0], timeout=60)
        again = server.project(queries[0], timeout=60)
        np.testing.assert_array_equal(first, again)
        assert server.stats.snapshot()["cache_hits"] == 1
        assert telemetry.counter_value("serve.cache_hits") == 1
        # Two more distinct queries evict queries[0] (capacity 2) —
        # resubmitting it is a miss, not a stale hit.
        server.project(queries[1], timeout=60)
        server.project(queries[2], timeout=60)
        server.project(queries[0], timeout=60)
        assert server.stats.snapshot()["cache_hits"] == 1
        assert telemetry.counter_value("serve.cache_misses") == 4
    finally:
        server.close()


def test_overload_sheds_and_drains_under_injected_stall(rng, tmp_path):
    """The acceptance scenario: with a delay fault armed at the new
    serve.request site, the stalled worker backs the bounded queue up,
    admission sheds with explicit ServerOverloaded, every ADMITTED
    request still resolves, and drain is clean — no hang, no deadlock,
    no silent drop."""
    g_ref, model, job = _fit(tmp_path, rng, n=12, v=256)
    engine = ProjectionEngine(model, ArraySource(g_ref),
                              block_variants=BV, max_batch=2)
    server = ProjectionServer(engine, max_linger_s=0.0, max_queue=2,
                              cache_entries=0).start()
    queries = random_genotypes(rng, n=30, v=256)
    futs, shed = [], 0
    try:
        with faults.armed(["serve.request:delay:delay=0.05:max=8"],
                          seed=3) as inj:
            for q in queries:
                try:
                    futs.append(server.submit(q))
                except ServerOverloaded:
                    shed += 1
            assert shed > 0, "bounded queue never filled"
            assert futs, "everything shed — queue bound miswired"
            for fut in futs:  # every admitted request is answered
                assert fut.result(timeout=60).shape[0] == 1
            assert inj.fire_count("serve.request") > 0
        assert server.drain(timeout=60)
    finally:
        server.close()
    assert server.in_flight == 0
    assert telemetry.counter_value("serve.shed") == shed
    assert server.stats.snapshot()["shed"] == shed


def test_deadline_expires_while_queued(rng, tmp_path):
    g_ref, model, job = _fit(tmp_path, rng, n=12, v=256)
    engine = ProjectionEngine(model, ArraySource(g_ref),
                              block_variants=BV, max_batch=2)
    server = ProjectionServer(engine, max_linger_s=0.0,
                              cache_entries=0).start()
    queries = random_genotypes(rng, n=2, v=256)
    try:
        with faults.armed(["serve.request:delay:delay=0.2:max=1"]):
            stalled = server.submit(queries[0])
            doomed = server.submit(queries[1], deadline_s=0.05)
            assert stalled.result(timeout=60).shape == (
                1, engine.n_components)
            with pytest.raises(DeadlineExceeded):
                doomed.result(timeout=60)
        assert telemetry.counter_value("serve.deadline_expired") == 1
        assert server.drain(timeout=60)
    finally:
        server.close()


def test_injected_io_error_fails_exactly_one_request(rng, tmp_path):
    g_ref, model, job = _fit(tmp_path, rng, n=12, v=256)
    engine = ProjectionEngine(model, ArraySource(g_ref),
                              block_variants=BV, max_batch=2)
    server = ProjectionServer(engine, cache_entries=0).start()
    queries = random_genotypes(rng, n=4, v=256)
    try:
        with faults.armed(["serve.request:io_error:max=1"]):
            futs = [server.submit(q) for q in queries]
            outcomes = []
            for fut in futs:
                try:
                    fut.result(timeout=60)
                    outcomes.append("ok")
                except faults.InjectedFault:
                    outcomes.append("fault")
        assert outcomes.count("fault") == 1
        assert outcomes.count("ok") == 3
        assert telemetry.counter_value("serve.errors") == 1
        assert server.drain(timeout=60)
    finally:
        server.close()


def test_cancellation_before_pickup(rng, tmp_path):
    g_ref, model, job = _fit(tmp_path, rng, n=12, v=256)
    engine = ProjectionEngine(model, ArraySource(g_ref),
                              block_variants=BV, max_batch=2)
    server = ProjectionServer(engine, max_linger_s=0.0,
                              cache_entries=0).start()
    queries = random_genotypes(rng, n=2, v=256)
    try:
        with faults.armed(["serve.request:delay:delay=0.2:max=1"]):
            stalled = server.submit(queries[0])
            victim = server.submit(queries[1])
            assert victim.cancel()  # still queued behind the stall
            stalled.result(timeout=60)
        assert server.drain(timeout=60)
        assert server.stats.snapshot()["cancelled"] == 1
    finally:
        server.close()


def test_loadgen_sustained_qps_and_telemetry_export(rng, tmp_path):
    """Acceptance: a sustained concurrent-client loadgen run reports
    nonzero sustained QPS, and latency p50/p99 land in the telemetry
    export (the same registry numbers the report carries)."""
    g_ref, model, job = _fit(tmp_path, rng, n=16, v=256)
    engine = ProjectionEngine(model, ArraySource(g_ref),
                              block_variants=BV, max_batch=4)
    server = ProjectionServer(engine, max_linger_s=0.001, max_queue=32,
                              cache_entries=8).start()
    pool = random_genotypes(rng, n=24, v=256)
    tdir = str(tmp_path / "tel")
    telemetry.configure(dir=tdir, trace_events=False)
    try:
        report = run_loadgen(server, pool, clients=4,
                             requests_per_client=10)
        assert server.drain(timeout=60)
    finally:
        server.close()
        telemetry.export()
        telemetry.configure(dir=None)
    assert report["completed"] == 40
    assert report["errors"] == 0 and report["shed"] == 0
    assert report["sustained_qps"] > 0
    assert report["offered_qps"] >= report["sustained_qps"]
    assert report["latency_p99_ms"] >= report["latency_p50_ms"] > 0
    with open(tmp_path / "tel" / "rank0" / "metrics.json") as f:
        exported = json.load(f)
    lat = exported["histograms"]["serve.latency_s"]
    assert lat["count"] == 40
    assert lat["p99"] >= lat["p50"] > 0
    assert exported["counters"]["serve.requests"] > 0


def test_http_front(rng, tmp_path):
    from spark_examples_tpu.serve.http import start_http_server

    g_ref, model, job = _fit(tmp_path, rng, n=10, v=256)
    engine = ProjectionEngine(model, ArraySource(g_ref),
                              block_variants=BV, max_batch=2)
    server = ProjectionServer(engine).start()
    http = start_http_server(server, port=0)
    base = f"http://127.0.0.1:{http.port}"
    query = random_genotypes(rng, n=1, v=256)[0]
    try:
        req = urllib.request.Request(
            f"{base}/project",
            data=json.dumps(
                {"genotypes": [int(x) for x in query]}).encode(),
            method="POST",
        )
        with urllib.request.urlopen(req, timeout=60) as resp:
            out = json.loads(resp.read())
        got = np.asarray(out["coords"], np.float32)
        want = _offline(job, model, g_ref, query).astype(np.float32)
        np.testing.assert_array_equal(got, want)
        with urllib.request.urlopen(f"{base}/healthz", timeout=30) as r:
            health = json.loads(r.read())
        assert health["status"] == "healthy"
        assert health["panel"] == "staged"
        assert health["worker_alive"] and health["worker_restarts"] == 0
        assert health["n_variants"] == 256
        with urllib.request.urlopen(f"{base}/stats", timeout=30) as r:
            stats = json.loads(r.read())
        assert stats["completed"] >= 1
        # /stats folds the health machine + breaker + worker restarts
        # into one coherent object (the live-telemetry-plane satellite)
        assert stats["health"]["status"] == "healthy"
        assert stats["health"]["worker_restarts"] == 0
        assert stats["health"]["worker_alive"]
        assert stats["health"]["breaker"]["state"] == "closed"
        assert stats["latency_p99_ms"] >= stats["latency_p50_ms"]
        # /metrics: Prometheus text over the live registry, mid-run
        with urllib.request.urlopen(f"{base}/metrics", timeout=30) as r:
            assert r.headers["Content-Type"].startswith("text/plain")
            prom = r.read().decode()
        assert "serve_requests_total" in prom
        assert 'serve_latency_s{quantile="0.99"}' in prom
        assert "telemetry_info{run_id=" in prom
        # /debug/telemetry: the full live snapshot as JSON
        with urllib.request.urlopen(f"{base}/debug/telemetry",
                                    timeout=30) as r:
            debug = json.loads(r.read())
        assert debug["counters"]["serve.requests"] >= 1
        assert debug["meta"]["run_id"]
        assert "recent_events" in debug
        # malformed bodies are 400s, not dropped sockets: wrong type,
        # out-of-int8-range dosages, float dosages
        for body in (b'{"genotypes": "nope"}',
                     json.dumps({"genotypes": [300] * 256}).encode(),
                     json.dumps({"genotypes": [0.7] * 256}).encode()):
            bad = urllib.request.Request(
                f"{base}/project", data=body, method="POST")
            with pytest.raises(urllib.error.HTTPError) as err:
                urllib.request.urlopen(bad, timeout=30)
            assert err.value.code == 400
    finally:
        http.shutdown()
        server.close()


def test_hot_reload_swaps_model_and_rejects_wrong_panel(rng, tmp_path):
    g_ref, model3, _ = _fit(tmp_path, rng, n=16, v=256, num_pc=3)
    # A second model on the SAME panel, different k — legal hot-reload.
    model5 = str(tmp_path / "m5.npz")
    job5 = JobConfig(
        ingest=IngestConfig(block_variants=BV),
        compute=ComputeConfig(metric="ibs", num_pc=5),
        model_path=model5,
    )
    pcoa_job(job5, source=ArraySource(g_ref))
    # A model on a DIFFERENT panel — reload must refuse it.
    other_panel = random_genotypes(rng, n=16, v=256)
    model_other = str(tmp_path / "other.npz")
    pcoa_job(job5.replace(model_path=model_other),
             source=ArraySource(other_panel,
                                ids=[f"OTHER{i}" for i in range(16)]))

    engine = ProjectionEngine(model3, ArraySource(g_ref),
                              block_variants=BV, max_batch=2)
    query = random_genotypes(rng, n=1, v=256)[0]
    server = ProjectionServer(engine, cache_entries=4).start()
    try:
        before = server.project(query, timeout=60)
        server.project(query, timeout=60)  # prime the cache
        server.reload_model(model5)
        after = server.project(query, timeout=60)
        # New model served (more components) and the cache was cleared —
        # the primed entry could not short-circuit the reload.
        assert after.shape[1] > before.shape[1]
        with pytest.raises(ValueError, match="different reference panel"):
            server.reload_model(model_other)
        assert server.drain(timeout=60)
    finally:
        server.close()


def test_engine_rejects_malformed_queries(rng, tmp_path):
    g_ref, model, _ = _fit(tmp_path, rng, n=12, v=256)
    engine = ProjectionEngine(model, ArraySource(g_ref),
                              block_variants=BV, max_batch=2)
    server = ProjectionServer(engine).start()
    try:
        with pytest.raises(ValueError, match="dosage vector"):
            server.submit(np.zeros(100, np.int8))  # wrong variant count
        with pytest.raises(ValueError):
            engine.project_batch(
                np.zeros((3, 256), np.int8))  # over max_batch
        # wrong-panel engine construction fails before staging
        with pytest.raises(ValueError, match="fitted on"):
            ProjectionEngine(model, ArraySource(g_ref[:6]),
                             block_variants=BV)
    finally:
        server.close()


def test_worker_loop_error_recovers_without_dropping(rng, tmp_path,
                                                     monkeypatch):
    """Availability hardening: an unexpected failure in the worker LOOP
    (outside the per-batch backstop) is caught by the supervision net —
    the worker keeps running, admitted requests are answered, and
    health degrades for the cooloff window."""
    import time as _time

    from spark_examples_tpu.serve import health as H

    g_ref, model, job = _fit(tmp_path, rng, n=10, v=256)
    engine = ProjectionEngine(model, ArraySource(g_ref),
                              block_variants=BV, max_batch=2)
    server = ProjectionServer(engine).start()
    try:
        assert server.health == "healthy"
        real_collect = server._collect
        blown = []

        def exploding_collect():
            if not blown:
                blown.append(True)
                raise RuntimeError("synthetic worker-loop failure")
            return real_collect()

        monkeypatch.setattr(server, "_collect", exploding_collect)
        query = random_genotypes(rng, n=1, v=256)[0]
        with pytest.warns(RuntimeWarning, match="worker recovered"):
            got = server.project(query, timeout=60)
        np.testing.assert_array_equal(
            got, _offline(job, model, g_ref, query))
        assert server._worker_restarts == 1
        assert server.health == "degraded"
        info = server.health_info()
        assert info["worker_alive"] and info["worker_restarts"] == 1
        # The cooloff expires -> healthy again (clock nudged, not slept).
        server._last_recovery = _time.monotonic() - H.DEGRADED_COOLOFF_S - 1
        assert server.health == "healthy"
        assert server.drain(timeout=60)
        assert server.health == "draining"
    finally:
        server.close()


def test_in_flight_gauge_published_at_start(rng, tmp_path):
    """The backlog gauge exists (at 0) from start(), BEFORE any
    request: the supervisor's idle-server exemption reads it from the
    heartbeat, so an unpublished gauge would get a pre-first-request
    idle server stall-killed."""
    g_ref, model, _job = _fit(tmp_path, rng, n=10, v=256)
    engine = ProjectionEngine(model, ArraySource(g_ref),
                              block_variants=BV, max_batch=2)
    server = ProjectionServer(engine).start()
    try:
        gauges = telemetry.metrics_snapshot()["gauges"]
        assert gauges["serve.in_flight"]["last"] == 0
        from spark_examples_tpu.core import supervisor

        assert supervisor.heartbeat_payload()["in_flight"] == 0
    finally:
        server.close()


def test_dead_worker_thread_restarted_at_admission(rng, tmp_path):
    """A worker thread that DIED (not just errored) is replaced at the
    next submit without dropping anything already admitted."""
    g_ref, model, job = _fit(tmp_path, rng, n=10, v=256)
    engine = ProjectionEngine(model, ArraySource(g_ref),
                              block_variants=BV, max_batch=2)
    server = ProjectionServer(engine).start()
    try:
        # Simulate an untrappable death: stop the loop, let the thread
        # exit, then re-arm the (still-open) server.
        server._stop.set()
        server._worker.join(timeout=10)
        assert not server._worker.is_alive()
        server._stop.clear()
        query = random_genotypes(rng, n=1, v=256)[0]
        with pytest.warns(RuntimeWarning, match="found dead at admission"):
            got = server.project(query, timeout=60)
        np.testing.assert_array_equal(
            got, _offline(job, model, g_ref, query))
        assert server._worker_restarts == 1
        assert telemetry.counter_value("serve.worker_restarts") == 1
    finally:
        server.close()


def test_store_breaker_trips_to_cached_panel_mode(rng, tmp_path):
    """The store-read circuit breaker: repeated store failures during a
    panel re-stage trip it open; the server keeps serving BIT-IDENTICAL
    results from the cached panel (degraded), and a later successful
    half-open probe closes it again (healthy)."""
    from spark_examples_tpu.core import faults
    from spark_examples_tpu.pipelines import runner as R
    from spark_examples_tpu.core.config import IngestConfig
    from spark_examples_tpu.serve import CircuitBreaker
    from spark_examples_tpu.store.writer import compact

    g_ref, model, job = _fit(tmp_path, rng, n=10, v=256)
    store = str(tmp_path / "panel_store")
    compact(store, ArraySource(g_ref), chunk_variants=64)
    panel_cfg = IngestConfig(source="store", path=store,
                             block_variants=BV, readahead_chunks=0,
                             io_retries=0)
    engine = ProjectionEngine(model, R.build_source(panel_cfg),
                              block_variants=BV, max_batch=2)
    engine.breaker = CircuitBreaker(trip_after=2, reset_s=0.05)
    server = ProjectionServer(engine).start()
    query = random_genotypes(rng, n=1, v=256)[0]
    want = _offline(job, model, g_ref, query)
    try:
        np.testing.assert_array_equal(server.project(query, timeout=60),
                                      want)
        with faults.armed(["store.read:io_error:max=0"]):
            with pytest.warns(RuntimeWarning, match="re-stage failed"):
                assert server.restage_panel(
                    R.build_source(panel_cfg)) is False
            with pytest.warns(RuntimeWarning, match="re-stage failed"):
                assert server.restage_panel(
                    R.build_source(panel_cfg)) is False
            # Tripped: open -> short-circuit, the store is NOT touched.
            assert engine.breaker.state in ("open", "half-open")
            assert engine.panel_mode == "cached-only"
            assert server.health == "degraded"
            assert server.health_info()["panel"] == "cached-only"
            # Cached-panel-only mode still serves, bit-identically.
            np.testing.assert_array_equal(
                server.project(query, timeout=60), want)
        # Store recovered: the half-open probe re-stages and closes.
        import time as _time

        _time.sleep(0.06)
        assert server.restage_panel(R.build_source(panel_cfg)) is True
        assert engine.breaker.state == "closed"
        assert server.health == "healthy"
        np.testing.assert_array_equal(server.project(query, timeout=60),
                                      want)
    finally:
        server.close()


def test_restage_refuses_panel_identity_change(rng, tmp_path):
    """A re-stage streaming a different variant count must be refused
    (fed to the breaker as a failure), never swapped under the model."""
    g_ref, model, _job = _fit(tmp_path, rng, n=10, v=256)
    engine = ProjectionEngine(model, ArraySource(g_ref),
                              block_variants=BV, max_batch=2)
    with pytest.warns(RuntimeWarning, match="re-stage failed"):
        assert engine.restage(ArraySource(g_ref[:, :128])) is False


def test_breaker_state_machine():
    """CircuitBreaker unit semantics with an injected clock."""
    from spark_examples_tpu.serve import CircuitBreaker

    now = [0.0]
    b = CircuitBreaker(trip_after=2, reset_s=10.0, clock=lambda: now[0])
    assert b.state == "closed" and b.allow()
    b.record_failure()
    assert b.state == "closed"  # one failure is weather
    b.record_failure()
    assert b.state == "open" and not b.allow()
    assert telemetry.counter_value("serve.breaker_open") == 1
    now[0] = 10.1  # reset window elapsed -> one probe allowed
    assert b.state == "half-open"
    assert b.allow() and not b.allow()  # single probe at a time
    b.record_failure()  # failed probe re-opens the clock
    assert b.state == "open" and not b.allow()
    now[0] = 20.3
    assert b.allow()
    b.record_success()
    assert b.state == "closed" and b.allow()


def test_serve_cli_loadgen_mode(rng, tmp_path, capsys):
    """The `serve --loadgen` CLI path end to end, with telemetry export:
    pack a panel, fit a model, serve it, and read the report + exported
    serve.* histograms."""
    from spark_examples_tpu.cli.main import main
    from spark_examples_tpu.ingest.packed import save_packed

    g_ref = random_genotypes(rng, n=16, v=256, missing_rate=0.1)
    store = str(tmp_path / "panel_store")
    save_packed(store, g_ref, bits=2)
    model = str(tmp_path / "cli_model.npz")
    tdir = str(tmp_path / "cli_tel")
    assert main(["pcoa", "--source", "packed", "--path", store,
                 "--num-pc", "3", "--block-variants", str(BV),
                 "--save-model", model]) == 0
    telemetry.reset()
    assert main(["serve", "--model", model,
                 "--ref-source", "packed", "--ref-path", store,
                 "--source", "synthetic", "--n-samples", "8",
                 "--block-variants", str(BV),
                 "--max-batch", "4", "--max-linger-ms", "1",
                 "--loadgen", "2", "--loadgen-requests", "6",
                 "--telemetry-dir", tdir]) == 0
    out = capsys.readouterr().out
    report = json.loads(out.strip().splitlines()[-1])
    assert report["completed"] == 12 and report["errors"] == 0
    assert report["sustained_qps"] > 0
    with open(tmp_path / "cli_tel" / "rank0" / "metrics.json") as f:
        exported = json.load(f)
    assert exported["histograms"]["serve.latency_s"]["count"] > 0
    telemetry.configure(dir=None)

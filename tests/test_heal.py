"""Store self-healing (store/heal.py) and the atomic quarantine ledger
(store/quarantine.py): origin re-compaction, replica copy, the inline
heal-on-read path, the `store heal` CLI verb, and concurrent-writer
idempotence."""

import json
import os
import shutil
import threading
import warnings

import numpy as np
import pytest

from spark_examples_tpu.core import faults, telemetry
from spark_examples_tpu.core.config import IngestConfig
from spark_examples_tpu.pipelines import runner
from spark_examples_tpu.store import quarantine as qledger
from spark_examples_tpu.store.heal import (
    HealError,
    build_origin_source,
    heal,
    heal_chunk,
    origin_from_ingest,
)
from spark_examples_tpu.store.manifest import StoreCorruptError, StoreManifest
from spark_examples_tpu.store.reader import open_store
from spark_examples_tpu.store.writer import compact

N, V, CHUNK = 8, 512, 128


@pytest.fixture
def origin_cfg():
    return IngestConfig(source="synthetic", n_samples=N, n_variants=V,
                        seed=3, block_variants=CHUNK)


@pytest.fixture
def healable_store(tmp_path, origin_cfg):
    """A store compacted WITH its origin recorded (schema v2)."""
    store = str(tmp_path / "store")
    compact(store, runner.build_source(origin_cfg), chunk_variants=CHUNK,
            origin=origin_from_ingest(origin_cfg, CHUNK))
    return store


def _clean(store):
    return open_store(store).read_range(0, V).copy()


def _truncate_chunk(store, idx):
    m = StoreManifest.load(store)
    path = os.path.join(store, m.chunks[idx].filename())
    with open(path, "r+b") as f:
        f.truncate(5)
    return m.chunks[idx]


# ----------------------------------------------------------------- origin


def test_origin_roundtrip(origin_cfg):
    rec = origin_from_ingest(origin_cfg, CHUNK)
    assert rec["source"] == "synthetic" and rec["chunk_variants"] == CHUNK
    src = build_origin_source(rec)
    assert (src.n_samples, src.n_variants) == (N, V)


def test_origin_records_absolute_path():
    """A relative --path is absolutized in the origin record: heals
    run from whatever cwd the LATER job has, not the compaction's."""
    cfg = IngestConfig(source="packed", path="rel/cohort")
    rec = origin_from_ingest(cfg, CHUNK)
    assert os.path.isabs(rec["path"])
    assert rec["path"].endswith(os.path.join("rel", "cohort"))


def test_corrupt_chunk_heals_from_origin_in_stream(healable_store):
    """The acceptance path: a chunk truncated on disk is re-compacted
    from the origin span IN PLACE during the read, re-verified, and the
    stream completes bit-identically — no quarantine, no failed run."""
    want = _clean(healable_store)
    _truncate_chunk(healable_store, 1)
    before = telemetry.counter_value("store.healed")
    with pytest.warns(RuntimeWarning, match="healed in place from origin"):
        got = open_store(healable_store).read_range(0, V)
    np.testing.assert_array_equal(got, want)
    assert telemetry.counter_value("store.healed") == before + 1
    # Ledger clean, chunk bytes verifiable again.
    assert qledger.load(healable_store) == []
    rec = StoreManifest.load(healable_store).chunks[1]
    from spark_examples_tpu.core.hashing import sha256_file

    assert sha256_file(
        os.path.join(healable_store, rec.filename())) == rec.digest


def test_injected_truncate_heals_under_fault_harness(healable_store):
    """Same path driven by the chaos harness's store.read truncate —
    exactly what the soak's heal rounds arm."""
    want = _clean(healable_store)
    with faults.armed(["store.read:truncate:after=2:max=1:keep=4"]):
        with pytest.warns(RuntimeWarning, match="healed in place"):
            got = open_store(healable_store).read_range(0, V)
    np.testing.assert_array_equal(got, want)


def test_missing_chunk_heals_from_replica(healable_store, tmp_path):
    """A deleted chunk file is restored by verified copy from a peer
    replica directory (tried before origin re-compaction)."""
    want = _clean(healable_store)
    replica = str(tmp_path / "replica")
    shutil.copytree(healable_store, replica)
    rec = StoreManifest.load(healable_store).chunks[2]
    os.remove(os.path.join(healable_store, rec.filename()))
    with pytest.warns(RuntimeWarning, match="healed in place from replica"):
        got = open_store(healable_store,
                         replicas=(replica,)).read_range(0, V)
    np.testing.assert_array_equal(got, want)


def test_store_replicas_threaded_through_config(healable_store, tmp_path):
    """--store-replicas reaches the reader through IngestConfig →
    build_source → open_store (replicas are tried BEFORE origin)."""
    want = _clean(healable_store)
    replica = str(tmp_path / "rep")
    shutil.copytree(healable_store, replica)
    _truncate_chunk(healable_store, 1)
    cfg = IngestConfig(source="store", path=healable_store,
                       block_variants=CHUNK, store_replicas=[replica])
    src = runner.build_source(cfg)
    with pytest.warns(RuntimeWarning, match="healed in place from replica"):
        got = np.concatenate([b for b, _ in src.blocks(CHUNK)], axis=1)
    np.testing.assert_array_equal(got, want)


def test_no_route_quarantines_as_before(tmp_path, origin_cfg):
    """A store without origin or replicas keeps the PR-4 contract:
    quarantine + StoreCorruptError naming the resume cursor."""
    store = str(tmp_path / "plain")
    compact(store, runner.build_source(origin_cfg), chunk_variants=CHUNK)
    assert StoreManifest.load(store).origin is None
    _truncate_chunk(store, 1)
    with pytest.raises(StoreCorruptError, match="resume"):
        open_store(store).read_range(0, V)
    assert len(qledger.load(store)) == 1


def test_changed_origin_refuses_wrong_bytes(healable_store, tmp_path):
    """Healing must be verifiable: if the origin no longer reproduces
    the chunk's content address (here: the manifest's recorded seed is
    tampered), the heal REFUSES to install different bytes and the
    chunk quarantines."""
    manifest_path = os.path.join(healable_store, "manifest.json")
    raw = json.load(open(manifest_path))
    raw["origin"]["seed"] = 999  # a different cohort entirely
    with open(manifest_path, "w") as f:
        json.dump(raw, f)
    _truncate_chunk(healable_store, 0)
    with pytest.raises(StoreCorruptError, match="heal failed"):
        with warnings.catch_warnings():
            warnings.simplefilter("ignore", RuntimeWarning)
            open_store(healable_store).read_range(0, V)
    assert len(qledger.load(healable_store)) == 1


def test_heal_chunk_no_route_raises(healable_store):
    m = StoreManifest.load(healable_store)
    m.origin = None
    with pytest.raises(HealError, match="no replica"):
        heal_chunk(healable_store, m, m.chunks[0])


# ------------------------------------------------------- offline heal verb


def test_heal_verb_repairs_ledger_and_verify_all(healable_store):
    want = _clean(healable_store)
    # Quarantine one chunk the hard way (auto-heal off), corrupt a
    # second SILENTLY (no ledger entry — only --verify-all finds it).
    _truncate_chunk(healable_store, 1)
    with pytest.raises(StoreCorruptError):
        open_store(healable_store, auto_heal=False).read_range(0, V)
    assert len(qledger.load(healable_store)) == 1
    _truncate_chunk(healable_store, 3)

    report = heal(healable_store, verify_all=True)
    assert report["damaged"] == 2 and not report["failed"]
    assert sorted(h["how"] for h in report["healed"]) == ["origin", "origin"]
    assert qledger.load(healable_store) == []
    np.testing.assert_array_equal(_clean(healable_store), want)


def test_heal_verifies_bytes_before_trusting_ledger(healable_store):
    """The ledger is advisory: a quarantined chunk whose file was
    restored by hand (the recovery path the quarantine error names)
    must verify clean and just clear its entry — not be re-compacted,
    and never reported unhealable."""
    rec = StoreManifest.load(healable_store).chunks[1]
    path = os.path.join(healable_store, rec.filename())
    good = open(path, "rb").read()
    _truncate_chunk(healable_store, 1)
    with pytest.raises(StoreCorruptError):
        open_store(healable_store, auto_heal=False).read_range(0, V)
    assert len(qledger.load(healable_store)) == 1
    with open(path, "wb") as f:  # the operator restores the file
        f.write(good)
    report = heal(healable_store)
    assert report["failed"] == [] and report["damaged"] == 0
    assert [h["how"] for h in report["healed"]] == ["already-intact"]
    assert qledger.load(healable_store) == []


def test_heal_clears_stale_ledger_entries(healable_store):
    """Entries whose digest no longer exists in the manifest (the
    store was re-compacted since the incident) are cleared and counted
    — a phantom chunk must not alarm forever."""
    qledger.record(healable_store, {"digest": "gone" * 16, "chunk": 0})
    report = heal(healable_store)
    assert report["stale_cleared"] == 1 and report["damaged"] == 0
    assert qledger.load(healable_store) == []


def test_store_heal_cli(healable_store, capsys):
    from spark_examples_tpu.cli.main import main

    _truncate_chunk(healable_store, 2)
    with pytest.raises(StoreCorruptError):
        open_store(healable_store, auto_heal=False).read_range(0, V)
    assert main(["store", "heal", "--path", healable_store]) == 0
    report = json.loads(capsys.readouterr().out.strip().splitlines()[0])
    assert report["healed"] and not report["failed"]
    np.testing.assert_array_equal(
        _clean(healable_store),
        open_store(healable_store).read_range(0, V))


def test_store_heal_cli_reports_unhealable(tmp_path, origin_cfg, capsys):
    store = str(tmp_path / "plain")
    compact(store, runner.build_source(origin_cfg), chunk_variants=CHUNK)
    _truncate_chunk(store, 0)
    with pytest.raises(StoreCorruptError):
        open_store(store).read_range(0, V)
    from spark_examples_tpu.cli.main import main

    assert main(["store", "heal", "--path", store]) == 1
    report = json.loads(capsys.readouterr().out.strip().splitlines()[0])
    assert report["failed"] and "no replica" in report["failed"][0]["error"]


# ------------------------------------------------- quarantine ledger (S2)


def test_quarantine_record_is_idempotent_and_atomic(tmp_path):
    root = str(tmp_path)
    entry = {"digest": "d1", "chunk": 0, "reason": "x"}
    assert qledger.record(root, entry) is True
    assert qledger.record(root, entry) is False  # same digest: no dup
    assert qledger.record(root, {"digest": "d2"}) is True
    assert {e["digest"] for e in qledger.load(root)} == {"d1", "d2"}
    assert qledger.remove(root, "d1") is True
    assert qledger.remove(root, "d1") is False
    assert [e["digest"] for e in qledger.load(root)] == ["d2"]
    assert qledger.remove(root, "d2") is True
    # Empty ledger = no file (the healthy state).
    assert not os.path.exists(os.path.join(root, "quarantine.json"))


def test_quarantine_concurrent_writers_lose_nothing(tmp_path):
    """The satellite contract: N readahead workers quarantining
    concurrently — some on the SAME chunk — must produce exactly one
    entry per digest with no lost updates and no torn file."""
    root = str(tmp_path)
    digests = [f"d{i % 4}" for i in range(32)]  # 8 writers per digest

    def write(d):
        qledger.record(root, {"digest": d, "reason": "race"})

    threads = [threading.Thread(target=write, args=(d,)) for d in digests]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    entries = qledger.load(root)
    assert sorted(e["digest"] for e in entries) == ["d0", "d1", "d2", "d3"]


def test_v1_manifest_loads_without_origin(tmp_path, origin_cfg):
    """Schema compatibility: a version-1 manifest (pre-origin) loads
    with origin=None and the store reads normally."""
    store = str(tmp_path / "v1")
    compact(store, runner.build_source(origin_cfg), chunk_variants=CHUNK)
    path = os.path.join(store, "manifest.json")
    raw = json.load(open(path))
    raw["schema_version"] = 1
    del raw["origin"]
    with open(path, "w") as f:
        json.dump(raw, f)
    m = StoreManifest.load(store)
    assert m.schema_version == 1 and m.origin is None
    assert open_store(store).read_range(0, V).shape == (N, V)

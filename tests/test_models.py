import numpy as np

import jax

from spark_examples_tpu.models.pca import fit_pca
from spark_examples_tpu.models.pcoa import fit_pcoa
from spark_examples_tpu.ops import centering, eigh
from spark_examples_tpu.utils import oracle


def _psd(rng, n):
    x = rng.standard_normal((n, n))
    return (x @ x.T).astype(np.float32)


def test_center_matrix_matches_oracle(rng):
    a = rng.random((31, 31)).astype(np.float32)
    np.testing.assert_allclose(
        np.asarray(centering.center_matrix(a)),
        oracle.center_matrix(a),
        rtol=1e-5,
        atol=1e-5,
    )


def test_top_k_eigh_matches_numpy(rng):
    b = _psd(rng, 40)
    vals, vecs = eigh.top_k_eigh(b, 5)
    wv = np.linalg.eigvalsh(b.astype(np.float64))[::-1][:5]
    np.testing.assert_allclose(np.asarray(vals), wv, rtol=1e-4)
    # residual check: B v = lambda v
    res = b @ np.asarray(vecs) - np.asarray(vecs) * np.asarray(vals)
    assert np.abs(res).max() < 1e-2 * np.abs(wv[0])


def test_randomized_eigh_close_to_dense(rng):
    b = _psd(rng, 120)
    k = 6
    dv, _ = eigh.top_k_eigh(b, k)
    rv, rvecs = eigh.randomized_eigh(b, k, jax.random.key(0), oversample=20, iters=6)
    np.testing.assert_allclose(np.asarray(rv), np.asarray(dv), rtol=1e-3)
    res = b @ np.asarray(rvecs) - np.asarray(rvecs) * np.asarray(rv)
    assert np.abs(res).max() < 1e-2 * float(dv[0])


def test_pcoa_matches_oracle(rng):
    # Euclidean distances of random points: PCoA must recover them.
    x = rng.standard_normal((30, 4))
    d = np.sqrt(((x[:, None] - x[None, :]) ** 2).sum(-1)).astype(np.float32)
    res = fit_pcoa(d, k=4)
    coords, vals, prop = oracle.pcoa(d, k=4)
    np.testing.assert_allclose(np.asarray(res.eigenvalues), vals, rtol=1e-3, atol=1e-3)
    # coords match up to per-axis sign
    got, want = np.asarray(res.coords), coords
    for c in range(4):
        assert (
            np.allclose(got[:, c], want[:, c], atol=1e-2)
            or np.allclose(got[:, c], -want[:, c], atol=1e-2)
        )
    # pairwise distances reconstructed from 4 coords == original (exact rank)
    rec = np.sqrt(((got[:, None] - got[None, :]) ** 2).sum(-1))
    np.testing.assert_allclose(rec, d, atol=1e-2)


def test_pca_equivalent_to_mllib_route(rng):
    s = _psd(rng, 35)
    res = fit_pca(s, k=4)
    want = oracle.pca_mllib_route(s, k=4)
    got = np.asarray(res.coords)
    for c in range(4):
        assert (
            np.allclose(got[:, c], want[:, c], atol=1e-2)
            or np.allclose(got[:, c], -want[:, c], atol=1e-2)
        ), f"component {c} mismatch"


def test_fit_pcoa_randomized_knobs(rng):
    """iters/oversample overrides reach the solver: structure
    eigenvalues match dense, and more iterations never worsen the
    worst-case eigenvalue error."""
    from tests.conftest import random_genotypes

    from spark_examples_tpu.models.pcoa import fit_pcoa
    from spark_examples_tpu.ops import distances, gram

    g = random_genotypes(rng, n=64, v=2048, missing_rate=0.05)
    acc = gram.update(gram.init(64, "ibs"), g, "ibs")
    dist = np.asarray(distances.finalize(acc, "ibs")["distance"])
    dense = np.asarray(fit_pcoa(dist, k=6).eigenvalues)

    def err(iters):
        vals = np.asarray(
            fit_pcoa(dist, k=6, method="randomized", iters=iters,
                     oversample=16).eigenvalues
        )
        return np.abs((vals - dense) / np.maximum(np.abs(dense), 1e-12)).max()

    assert err(24) <= err(2) + 1e-6
    # Structure (well-separated) eigenvalues are tight even at few iters.
    vals4 = np.asarray(
        fit_pcoa(dist, k=6, method="randomized", iters=8).eigenvalues
    )
    top = dense > 0.05 * dense[0]
    np.testing.assert_allclose(vals4[top], dense[top], rtol=2e-3)

"""Neighbor engine: MinHash signatures, LSH candidate filtering, exact
sparse evaluation, the self-describing top-k/pairs output format, the
fault-injection recovery boundary, and serve/CLI bit-identity of the
query-vs-panel path."""

import dataclasses
import json
import urllib.request

import numpy as np
import pytest

from spark_examples_tpu.core import faults, telemetry
from spark_examples_tpu.core.config import (
    ComputeConfig,
    IngestConfig,
    JobConfig,
    ServeConfig,
)
from spark_examples_tpu.ingest.source import ArraySource
from spark_examples_tpu.neighbors import (
    NeighborFormatError,
    PairsResult,
    TopKResult,
    load_result,
    save_result,
)
from spark_examples_tpu.neighbors import lsh
from spark_examples_tpu.neighbors.engine import (
    neighbors_job,
    topk_from_pairs,
    topk_rows,
)
from tests.conftest import random_genotypes


@pytest.fixture(autouse=True)
def _fresh_telemetry():
    telemetry.reset()
    yield
    telemetry.reset()
    telemetry.configure(dir=None)


def _job(metric="ibs", **compute):
    return JobConfig(
        ingest=IngestConfig(block_variants=256),
        compute=ComputeConfig(metric=metric, **compute),
    )


def family_cohort(rng, families=8, size=12, v=2048, carrier_rate=0.08,
                  mutation_rate=0.03):
    """Planted-relatives cohort: ``families`` founder carrier sets, each
    cloned into ``size`` members with a few percent of entries
    resampled. Every sample's true nearest neighbors are its family —
    the structure an LSH filter must recover."""
    blocks = []
    for _ in range(families):
        founder = (rng.random(v) < carrier_rate).astype(np.int8) * (
            1 + (rng.random(v) < 0.3).astype(np.int8))
        for _ in range(size):
            g = founder.copy()
            mut = rng.random(v) < mutation_rate
            g[mut] = (rng.random(mut.sum()) < carrier_rate) * (
                1 + (rng.random(mut.sum()) < 0.3)).astype(np.int8)
            blocks.append(g)
    return np.asarray(blocks, np.int8)


# ------------------------------------------------ exact sparse evaluation


def _dense_pair_sims(g, metric):
    """Independent dense oracle: full N x N cross-statistics as int64
    indicator matmuls (different evaluation order from the engine's
    chunked per-pair einsum — integer arithmetic makes the comparison
    exact), finalized through the same f64 PairSpec."""
    from spark_examples_tpu import kernels
    from spark_examples_tpu.ops import genotype

    spec = kernels.get(metric).pair
    ops = {
        "c": (g >= 0).astype(np.int64),
        "t1": (g >= 1).astype(np.int64),
        "t2": (g >= 2).astype(np.int64),
    }
    ops["y"] = ops["t1"] + ops["t2"]
    acc = {}
    for s in spec.stats:
        total = np.zeros((len(g), len(g)), np.int64)
        for (left, right), w in genotype.CROSS_STATS[s]:
            total += w * (ops[left] @ ops[right].T)
        acc[s] = total
    return np.asarray(spec.sim(acc), np.float64)


@pytest.mark.parametrize("metric", ["ibs", "jaccard", "king"])
def test_pair_sims_bitwise_equal_dense(rng, metric):
    """The candidate-pair exact path (host int64 einsum over indicator
    operands, PairSpec f64 finalize) must equal a dense exact oracle
    bit for bit, and agree with the production dense similarity matrix
    to its f32 output precision."""
    from spark_examples_tpu.pipelines.jobs import similarity_matrix_job

    g = random_genotypes(rng, 24, 700, missing_rate=0.1)
    oracle = _dense_pair_sims(g, metric)
    job = _job(metric=metric, minhash_hashes=32, minhash_bands=32,
               neighbors_output="pairs")
    res = neighbors_job(job, source=ArraySource(g))
    assert isinstance(res, PairsResult)
    assert len(res.pairs)  # bands=rows-of-1 proposes plenty
    for (i, j), s in zip(res.pairs, res.sims):
        assert float(s) == float(oracle[i, j]), (metric, i, j)
    # ... and the oracle itself tracks the production dense route to
    # the f32 precision that route emits at.
    dense = similarity_matrix_job(
        _job(metric=metric), source=ArraySource(g)).similarity
    ii, jj = res.pairs[:, 0], res.pairs[:, 1]
    np.testing.assert_allclose(oracle[ii, jj],
                               np.asarray(dense, np.float64)[ii, jj],
                               rtol=1e-6, atol=1e-6)


def test_topk_from_pairs_matches_dense_when_exhaustive(rng):
    """With every pair a candidate, the sparse reduction must equal the
    dense per-row top-k exactly (same ordering, same tie-breaks)."""
    from spark_examples_tpu.pipelines.jobs import similarity_matrix_job

    g = random_genotypes(rng, 18, 512, missing_rate=0.05)
    dense = similarity_matrix_job(
        _job(), source=ArraySource(g)).similarity.copy()
    np.fill_diagonal(dense, -np.inf)  # top-k excludes self by design
    want_ids, want_sims = topk_rows(dense, 5)
    n = len(g)
    pairs = np.array([(i, j) for i in range(n) for j in range(i + 1, n)],
                     np.int64)
    sims = np.array([dense[i, j] for i, j in pairs])
    ids, vals = topk_from_pairs(pairs, sims, n, 5)
    np.testing.assert_array_equal(ids, want_ids)
    np.testing.assert_array_equal(vals, want_sims)


def test_recall_oracle_planted_relatives(rng):
    """The acceptance contract in miniature: on a planted-relatives
    cohort the LSH filter must evaluate a small fraction of all pairs
    yet recover >= 0.95 of the dense exact top-k."""
    from spark_examples_tpu.pipelines.jobs import similarity_matrix_job

    g = family_cohort(rng)
    n, k = len(g), 10
    job = _job(metric="ibs", minhash_hashes=64, minhash_bands=16,
               neighbors_k=k)
    res = neighbors_job(job, source=ArraySource(g))
    assert isinstance(res, TopKResult)

    dense = similarity_matrix_job(
        _job(), source=ArraySource(g)).similarity.copy()
    np.fill_diagonal(dense, -np.inf)
    dense_ids, _ = topk_rows(dense, k)

    hits = sum(
        len(set(res.ids[i][res.ids[i] >= 0].tolist())
            & set(dense_ids[i].tolist()))
        for i in range(n)
    )
    recall = hits / float(n * k)
    evaluated = telemetry.counter_value("neighbors.candidate_pairs")
    frac_evaluated = evaluated / (n * (n - 1) / 2)
    assert recall >= 0.95, (recall, frac_evaluated)
    assert frac_evaluated <= 0.5, frac_evaluated
    # Telemetry contract: the filter fraction gauge and candidate/
    # evaluated counters were published.
    snap = telemetry.metrics_snapshot()
    assert snap["gauges"]["neighbors.filter_frac"]["last"] == (
        pytest.approx(1.0 - frac_evaluated))
    assert telemetry.counter_value("neighbors.evaluated_pairs") == (
        evaluated)


def test_bucket_cap_bounds_candidates_and_counts_overflow():
    """A degenerate cohort (everyone identical => one bucket) must
    truncate at the cap and count what it dropped instead of going
    quadratic."""
    sig = np.zeros((50, 16), np.uint32)  # all-identical signatures
    pairs, n_overflow, _nb = lsh.candidate_pairs(sig, bands=4,
                                                 bucket_cap=10)
    # 10-member buckets -> at most C(10,2) distinct pairs
    assert len(pairs) <= 45
    assert n_overflow == 4 * 40  # 40 dropped per band
    assert lsh.filter_fraction(len(pairs), 50) > 0.9


def test_minhash_bands_must_divide_hashes():
    with pytest.raises(ValueError, match="--minhash-bands"):
        ComputeConfig(minhash_hashes=10, minhash_bands=3)
    with pytest.raises(ValueError, match="--neighbors-output"):
        ComputeConfig(neighbors_output="csv")
    with pytest.raises(ValueError, match="--neighbors-k"):
        ComputeConfig(neighbors_k=0)
    with pytest.raises(ValueError, match="--minhash-bucket-cap"):
        ComputeConfig(minhash_bucket_cap=0)


def test_metric_without_pair_finalize_is_rejected(rng):
    g = random_genotypes(rng, 8, 256)
    with pytest.raises(ValueError, match="pairwise finalize"):
        neighbors_job(_job(metric="braycurtis"), source=ArraySource(g))


def test_signatures_deterministic_across_block_partitions(rng):
    """MinHash signatures hash GLOBAL variant indices, so the block
    partition cannot change them — the property that makes checkpoint
    resume bit-identical by construction."""
    from spark_examples_tpu.core.profiling import PhaseTimer
    from spark_examples_tpu.neighbors.engine import minhash_signatures

    g = random_genotypes(rng, 10, 640, missing_rate=0.1)
    sigs = []
    for bv in (64, 256, 640):
        job = JobConfig(ingest=IngestConfig(block_variants=bv),
                        compute=ComputeConfig(minhash_hashes=32))
        sig, n_variants = minhash_signatures(job, ArraySource(g),
                                             PhaseTimer())
        assert n_variants == 640
        sigs.append(sig)
    np.testing.assert_array_equal(sigs[0], sigs[1])
    np.testing.assert_array_equal(sigs[0], sigs[2])


# ------------------------------------------------------- output format


def _topk_result():
    return TopKResult(
        ids=np.array([[1, 2], [0, -1]], np.int32),
        sims=np.array([[0.9, 0.5], [0.9, 0.0]], np.float64),
        sample_ids=("a", "b"), metric="ibs", k=2, n_variants=77,
    )


def test_topk_roundtrip(tmp_path):
    path = str(tmp_path / "r.topk")
    want = _topk_result()
    save_result(path, want)
    got = load_result(path)
    assert isinstance(got, TopKResult)
    np.testing.assert_array_equal(got.ids, want.ids)
    np.testing.assert_array_equal(got.sims, want.sims)
    assert got.sample_ids == want.sample_ids
    assert (got.metric, got.k, got.n_variants) == ("ibs", 2, 77)


def test_pairs_roundtrip(tmp_path):
    path = str(tmp_path / "r.pairs")
    want = PairsResult(
        pairs=np.array([[0, 1], [1, 2]], np.int64),
        sims=np.array([0.25, 0.75]),
        sample_ids=("a", "b", "c"), metric="jaccard", n_variants=5,
    )
    save_result(path, want)
    got = load_result(path, expect_kind="pairs")
    assert isinstance(got, PairsResult)
    np.testing.assert_array_equal(got.pairs, want.pairs)
    np.testing.assert_array_equal(got.sims, want.sims)


def test_save_is_atomic_and_deterministic(tmp_path):
    a, b = str(tmp_path / "a"), str(tmp_path / "b")
    save_result(a, _topk_result())
    save_result(b, _topk_result())
    with open(a, "rb") as fa, open(b, "rb") as fb:
        assert fa.read() == fb.read()  # no timestamps, no tempfile names
    assert [p.name for p in tmp_path.iterdir()] != []  # no tmp litter
    assert all(not p.name.startswith("tmp")
               for p in tmp_path.iterdir())


def test_format_error_ladder(tmp_path):
    path = str(tmp_path / "r.topk")
    save_result(path, _topk_result())
    with open(path, "rb") as f:
        header, payload = f.read().split(b"\n", 1)
    doc = json.loads(header)

    def write(doc2, body=payload, name="bad"):
        p = str(tmp_path / name)
        with open(p, "wb") as f:
            f.write(json.dumps(doc2).encode() + b"\n" + body)
        return p

    with pytest.raises(NeighborFormatError, match="cannot read"):
        load_result(str(tmp_path / "missing"))
    with pytest.raises(NeighborFormatError, match="format tag"):
        load_result(write(dict(doc, format="something-else")))
    with pytest.raises(NeighborFormatError, match="schema_version"):
        load_result(write(dict(doc, schema_version=99)))
    with pytest.raises(NeighborFormatError, match="missing field"):
        load_result(write({k: v for k, v in doc.items() if k != "k"}))
    with pytest.raises(NeighborFormatError, match="unknown neighbors"):
        load_result(write(dict(doc, kind="heap")))
    with pytest.raises(NeighborFormatError,
                       match="--neighbors-output pairs"):
        load_result(path, expect_kind="pairs")
    bad_arrays = [dict(a, dtype="<f4") for a in doc["arrays"]]
    with pytest.raises(NeighborFormatError, match="schema drift"):
        load_result(write(dict(doc, arrays=bad_arrays)))
    with pytest.raises(NeighborFormatError, match="truncated"):
        load_result(write(doc, body=payload[:-4]))
    with pytest.raises(NeighborFormatError, match="trailing"):
        load_result(write(doc, body=payload + b"xx"))


# ------------------------------------------ fault injection + recovery


def test_neighbors_candidates_io_error_recovers_bit_identically(rng):
    """An injected io_error at the ``neighbors.candidates`` site must
    surface the retry warning and still produce output byte-identical
    to a clean run (the block is recomputed wholesale, never partially
    accumulated)."""
    g = random_genotypes(rng, 20, 768, missing_rate=0.1)
    job = _job(minhash_hashes=32, minhash_bands=16, neighbors_k=5)
    clean = neighbors_job(job, source=ArraySource(g))
    with faults.armed(["neighbors.candidates:io_error:after=1:max=2"]):
        with pytest.warns(RuntimeWarning, match="recomputing"):
            faulted = neighbors_job(job, source=ArraySource(g))
    np.testing.assert_array_equal(faulted.ids, clean.ids)
    np.testing.assert_array_equal(faulted.sims, clean.sims)


def test_neighbors_candidates_io_error_exhausts_budget(rng):
    """Past the retry budget the io_error propagates — fail loudly,
    never emit partial similarities."""
    g = random_genotypes(rng, 12, 512)
    job = JobConfig(
        ingest=IngestConfig(block_variants=256, io_retries=1),
        compute=ComputeConfig(metric="ibs", minhash_hashes=32,
                              minhash_bands=16),
    )
    with faults.armed(["neighbors.candidates:io_error:max=99"]):
        with pytest.warns(RuntimeWarning):
            with pytest.raises(IOError):
                neighbors_job(job, source=ArraySource(g))


# ------------------------------------------------ serving (fleet topk)


@pytest.fixture(scope="module")
def served(tmp_path_factory):
    """A store-backed pcoa model fleet with one topk-capable route,
    plus the raw genotypes."""
    from spark_examples_tpu.pipelines.jobs import pcoa_job
    from spark_examples_tpu.serve.fleet import FleetManifest, build_fleet
    from spark_examples_tpu.store.writer import compact

    rng = np.random.default_rng(77)
    g = random_genotypes(rng, 14, 512, missing_rate=0.05)
    d = tmp_path_factory.mktemp("nbserve")
    store = str(d / "store")
    compact(store, ArraySource(g), chunk_variants=128)
    model = str(d / "m.npz")
    pcoa_job(JobConfig(
        ingest=IngestConfig(block_variants=128),
        compute=ComputeConfig(metric="ibs", num_pc=3),
        model_path=model,
    ), source=ArraySource(g))
    manifest = FleetManifest.parse({
        "budget_mb": 4.0,
        "routes": [{"name": "r", "model": model,
                    "source": f"store:{store}", "topk": True}],
    })
    fleet = build_fleet(manifest, ServeConfig(),
                        ingest_defaults=IngestConfig(block_variants=128))
    fleet.start()
    yield fleet, g, model, store
    fleet.close()


def test_served_topk_bit_identical_to_offline(served):
    """The /neighbors serving path and the offline query-vs-panel
    engine answer from the same padded-batch kernel and the same top-k
    reduction — assert the bit-identity, including immediately after
    the route's panel is evicted and re-staged."""
    from spark_examples_tpu.pipelines import project as P
    from spark_examples_tpu.serve import engine as E

    fleet, g, model, _store = served
    rng = np.random.default_rng(5)
    queries = random_genotypes(rng, 3, g.shape[1], missing_rate=0.05)

    ctx = E.ModelContext(P.load_model(model))
    blocks, n_variants, _nb = E.stage_blocks(ArraySource(g), 128)
    want_ids, want_sims = E.batch_topk(ctx, blocks, queries, 8,
                                       n_variants, 4)

    got = [fleet.topk("r", q.copy(), k=4) for q in queries]
    for i, (ids, sims) in enumerate(got):
        np.testing.assert_array_equal(ids[0], want_ids[i])
        np.testing.assert_array_equal(sims[0], want_sims[i])

    fleet.pool.remove("r")  # evict; next request must re-stage
    ids2, sims2 = fleet.topk("r", queries[0].copy(), k=4)
    np.testing.assert_array_equal(ids2[0], want_ids[0])
    np.testing.assert_array_equal(sims2[0], want_sims[0])


def test_neighbors_http_endpoint_and_stats(served):
    from spark_examples_tpu.serve.http import start_fleet_http_server

    fleet, g, _model, _store = served
    h = start_fleet_http_server(fleet)
    try:
        body = json.dumps({"genotypes": g[2].tolist(), "k": 3}).encode()
        r = urllib.request.urlopen(urllib.request.Request(
            f"http://127.0.0.1:{h.port}/neighbors/r", data=body,
            headers={"Content-Type": "application/json"}))
        doc = json.loads(r.read())
        assert doc["k"] == 3
        assert len(doc["neighbor_ids"][0]) == 3
        assert doc["neighbor_indices"][0] == [
            list(fleet.routes["r"].ctx.model.sample_ids).index(s)
            for s in doc["neighbor_ids"][0]]
        direct = fleet.topk("r", g[2].copy(), k=3)
        assert doc["neighbor_indices"] == [direct[0][0].tolist()]
        assert doc["similarities"] == [direct[1][0].tolist()]
        # Satellite: /stats and the autoscale gauges carry the topk path
        stats = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{h.port}/stats").read())
        assert stats["routes"]["r"]["topk"] is True
        assert stats["routes"]["r"]["topk_requests"] >= 2
        fleet.publish_autoscale()
        snap = telemetry.metrics_snapshot()
        assert "fleet.route.r.topk_requests" in snap["gauges"]
        assert telemetry.counter_value("neighbors.requests") >= 2
    finally:
        h.shutdown()


def test_topk_capability_gated(served):
    """A route without ``"topk": true`` refuses neighbor queries, and a
    manifest declaring topk on a model that cannot honor it dies at
    build time as FleetFormatError."""
    from spark_examples_tpu.pipelines.jobs import variants_pca_job
    from spark_examples_tpu.serve.fleet import (
        FleetFormatError,
        FleetManifest,
        build_fleet,
    )
    from spark_examples_tpu.store.writer import compact

    fleet, g, model, store = served
    no_cap = FleetManifest.parse({
        "budget_mb": 4.0,
        "routes": [{"name": "plain", "model": model,
                    "source": f"store:{store}"}],
    })
    plain = build_fleet(no_cap, ServeConfig(),
                        ingest_defaults=IngestConfig(block_variants=128))
    plain.start()
    try:
        with pytest.raises(ValueError, match="topk"):
            plain.topk("plain", g[0].copy(), k=3)
        plain.project("plain", g[0].copy())  # projection still fine
    finally:
        plain.close()

    import tempfile
    with tempfile.TemporaryDirectory() as d:
        pca_model = f"{d}/pca.npz"
        pca_store = f"{d}/store"
        compact(pca_store, ArraySource(np.abs(g)), chunk_variants=128)
        variants_pca_job(JobConfig(
            ingest=IngestConfig(block_variants=128),
            compute=ComputeConfig(metric="shared-alt", num_pc=3),
            model_path=pca_model,
        ), source=ArraySource(np.abs(g)))
        bad = FleetManifest.parse({
            "budget_mb": 4.0,
            "routes": [{"name": "pca", "model": pca_model,
                        "source": f"store:{pca_store}", "topk": True}],
        })
        with pytest.raises(FleetFormatError, match="cannot honor"):
            build_fleet(bad, ServeConfig(),
                        ingest_defaults=IngestConfig(block_variants=128))


def test_manifest_topk_field_validated():
    from spark_examples_tpu.serve.fleet import (
        FleetFormatError,
        FleetManifest,
    )

    with pytest.raises(FleetFormatError, match="topk"):
        FleetManifest.parse({"routes": [
            {"name": "r", "model": "m.npz", "source": "store:/x",
             "topk": "yes"}]})


# --------------------------------------------------------------- CLI


def test_cli_cohort_mode_writes_loadable_topk(rng, tmp_path, capsys):
    from spark_examples_tpu.cli.main import main

    g = np.abs(random_genotypes(rng, 16, 512, missing_rate=0.1))
    from spark_examples_tpu.ingest.packed import save_packed
    store = str(tmp_path / "packed")
    save_packed(store, g, bits=2)
    out = str(tmp_path / "out.topk")
    rc = main(["neighbors", "--source", "packed", "--path", store,
               "--block-variants", "128", "--metric", "ibs",
               "--minhash-hashes", "32", "--minhash-bands", "8",
               "--neighbors-k", "4", "--output-path", out])
    assert rc == 0
    res = load_result(out, expect_kind="topk")
    assert res.k == 4 and len(res.sample_ids) == 16
    assert "top-4 for 16 samples" in capsys.readouterr().out


def test_cli_rejects_bad_knobs(tmp_path):
    from spark_examples_tpu.cli.main import main

    with pytest.raises(SystemExit):
        main(["neighbors", "--source", "synthetic",
              "--minhash-hashes", "10", "--minhash-bands", "3"])

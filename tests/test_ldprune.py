"""LD pruning (--ld-prune-r2): planted-LD removal, independence
preservation, block/window invariances, contig isolation, resume, and
CLI wiring."""

import numpy as np
import pytest

from spark_examples_tpu.ingest.ldprune import LdPruneSource, _greedy_keep
from spark_examples_tpu.ingest.source import ArraySource
from tests.conftest import random_genotypes


def _materialize(src, bv, start=0):
    blocks = [b for b, _ in src.blocks(bv, start)]
    return (np.concatenate(blocks, axis=1) if blocks
            else np.empty((src.n_samples, 0), np.int8))


def _ld_cohort(rng, n=300, n_indep=40, copies=4, flip_rate=0.02):
    # n large enough that null pairwise r^2 (~chi^2_1/n) stays far
    # below the pruning thresholds used in these tests — at small n,
    # random correlations between i.i.d. columns would prune spuriously
    """Each independent variant followed by near-duplicates (planted
    LD blocks). Returns (g, independent_column_indices)."""
    base = rng.integers(0, 3, (n, n_indep), dtype=np.int8)
    cols, indep_idx = [], []
    for j in range(n_indep):
        indep_idx.append(len(cols))
        cols.append(base[:, j])
        for _ in range(copies - 1):
            c = base[:, j].copy()
            flip = rng.random(n) < flip_rate
            c[flip] = rng.integers(0, 3, flip.sum(), dtype=np.int8)
            cols.append(c)
    return np.stack(cols, axis=1), np.asarray(indep_idx)


def test_greedy_keep_semantics():
    r2 = np.array([
        [1.0, 0.9, 0.1],
        [0.9, 1.0, 0.1],
        [0.1, 0.1, 1.0],
    ])
    keep = _greedy_keep(r2, base=0, thresh=0.2)
    np.testing.assert_array_equal(keep, [True, False, True])
    # carried-in column 0 is immutable; only 1..2 are decided
    keep = _greedy_keep(r2, base=1, thresh=0.2)
    np.testing.assert_array_equal(keep, [False, True])


def test_prune_rejects_bad_params():
    src = ArraySource(np.zeros((4, 8), np.int8))
    with pytest.raises(ValueError, match="carry"):
        LdPruneSource(src, r2=0.2, window=64, carry=0)  # -0 slice trap
    with pytest.raises(ValueError, match="carry"):
        LdPruneSource(src, r2=0.2, window=64, carry=-3)
    with pytest.raises(ValueError, match="carry"):
        LdPruneSource(src, r2=0.2, window=64, carry=64)
    with pytest.raises(ValueError, match="r2"):
        LdPruneSource(src, r2=0.0)


def test_prune_caches_count_after_full_pass(rng):
    g, indep = _ld_cohort(rng, n=200, n_indep=10, copies=3)
    src = LdPruneSource(ArraySource(g), r2=0.2, window=16, carry=4)
    list(src.blocks(8))  # full streaming pass
    assert src._n_variants == len(indep)  # no second prune needed


def test_prune_removes_planted_ld(rng):
    g, indep = _ld_cohort(rng)
    src = LdPruneSource(ArraySource(g), r2=0.2, window=64, carry=16)
    out = _materialize(src, 50)
    # one representative survives per LD block, none of the copies
    assert out.shape[1] == len(indep)
    np.testing.assert_array_equal(out, g[:, indep])


def test_prune_keeps_independent_variants(rng):
    g = rng.integers(0, 3, (80, 300), dtype=np.int8)  # i.i.d. columns
    src = LdPruneSource(ArraySource(g), r2=0.5, window=64, carry=16)
    out = _materialize(src, 100)
    # i.i.d. dosages at N=80: pairwise r^2 concentrates ~1/N << 0.5
    assert out.shape[1] >= 290
    assert src.n_variants == out.shape[1]


def test_prune_block_size_invariance(rng):
    g, _ = _ld_cohort(rng, n=40, n_indep=25, copies=3)
    src = LdPruneSource(ArraySource(g), r2=0.2, window=32, carry=8)
    a = _materialize(src, 16)
    b = _materialize(src, 64)
    np.testing.assert_array_equal(a, b)


def test_prune_carry_checks_window_boundaries(rng):
    """A duplicate pair straddling a window boundary within `carry` is
    still pruned."""
    n = 400
    base = rng.integers(0, 3, (n, 64), dtype=np.int8)
    dup = np.concatenate([base, base[:, -4:]], axis=1)  # cols 64..67
    src = LdPruneSource(ArraySource(dup), r2=0.2, window=64, carry=16)
    out = _materialize(src, 64)
    assert out.shape[1] == 64  # the 4 straddling duplicates pruned


def test_prune_resets_at_contig_boundary(rng, tmp_path):
    """LD context must not cross chromosomes: an identical column on a
    different contig is NOT pruned."""
    from spark_examples_tpu.ingest.plink import PlinkSource, write_plink

    n = 40
    col = rng.integers(0, 3, (n, 1), dtype=np.int8)
    fill1 = rng.integers(0, 3, (n, 19), dtype=np.int8)
    fill2 = rng.integers(0, 3, (n, 19), dtype=np.int8)
    g = np.concatenate([col, fill1, col, fill2], axis=1)  # dup at 0, 20
    prefix = str(tmp_path / "c")
    write_plink(prefix, g, chroms=["1"] * 20 + ["2"] * 20)
    src = LdPruneSource(PlinkSource(prefix), r2=0.2, window=40, carry=8)
    out = _materialize(src, 40)
    # both copies of `col` survive (different chromosomes) unless the
    # random fill happened to correlate (flaky-proof: assert the dup
    # column appears twice)
    matches = (out == col).all(axis=0).sum()
    assert matches >= 2


def test_prune_resume(rng):
    g, _ = _ld_cohort(rng)
    src = LdPruneSource(ArraySource(g), r2=0.2, window=64, carry=16)
    full = list(src.blocks(8))  # 40 kept variants -> 5 blocks
    cursor = full[2][1].stop
    resumed = list(src.blocks(8, cursor))
    assert [m.start for _, m in resumed] == [m.start for _, m in full[3:]]
    np.testing.assert_array_equal(resumed[0][0], full[3][0])


def test_prune_cli_pipeline(rng, tmp_path, capsys):
    from spark_examples_tpu.cli.main import main
    from spark_examples_tpu.ingest.vcf import write_vcf

    g, indep = _ld_cohort(rng, n=200, n_indep=20, copies=3)
    vcf = str(tmp_path / "c.vcf")
    write_vcf(vcf, g)
    assert main(["similarity", "--source", "vcf", "--path", vcf,
                 "--ld-prune-r2", "0.3", "--ld-window", "32",
                 "--ld-carry", "8", "--block-variants", "16"]) == 0
    cap = capsys.readouterr()
    assert f"over {len(indep)} variants" in cap.out

"""Chaos soak (tools/soak.py): the fixed-seed tier-1 smoke, the full
25-iteration soak behind `slow`, and explicit arming tests for the
fault sites registered this PR (readahead worker decode, staging-ring
transfer wait — the supervisor.heartbeat site is armed in
tests/test_supervisor.py)."""

import os
import sys

import numpy as np
import pytest

from spark_examples_tpu.core import faults, telemetry
from spark_examples_tpu.ingest.source import ArraySource
from tests.conftest import random_genotypes

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)  # tools/ is repo tooling, not an installed pkg

from tools.soak import SCENARIOS, SoakConfig, run_soak  # noqa: E402


@pytest.mark.soak
def test_chaos_soak_smoke(tmp_path):
    """Tier-1 smoke: one seeded-shuffled pass over the whole in-process
    scenario table (every registered in-process site, randomized
    after/max/params), invariants checked every round — bit-identity,
    watchdog budget, thread accounting, heal bookkeeping. Seconds, not
    minutes; the kill/supervise rounds live in the slow soak."""
    report = run_soak(SoakConfig(
        workdir=str(tmp_path), iterations=len(SCENARIOS), seed=7,
        include_kill=False, round_budget_s=120.0,
    ))
    assert report.ok, "\n".join(report.violations)
    assert report.iterations == len(SCENARIOS)
    # One shuffled pass = every scenario ran exactly once.
    sites_run = {r["spec"].split(":")[0] for r in report.rounds}
    assert sites_run == {site for _j, site, _k, _p in SCENARIOS}
    assert report.faults_fired > 0
    # The schedule includes the on-disk truncate scenario, so the soak
    # must have exercised a real heal (origin re-compaction).
    assert report.healed >= 1
    assert report.retries >= 1


@pytest.mark.soak
def test_chaos_soak_schedule_is_deterministic(tmp_path):
    """Same seed -> same schedule, specs, and injector seeds (the
    repro-line contract depends on it). Probed via two 3-iteration
    runs: cheap, and any drift in the RNG plumbing breaks it."""
    r1 = run_soak(SoakConfig(workdir=str(tmp_path / "a"), iterations=3,
                             seed=41, include_kill=False))
    r2 = run_soak(SoakConfig(workdir=str(tmp_path / "b"), iterations=3,
                             seed=41, include_kill=False))
    assert [(r["spec"], r["seed"]) for r in r1.rounds] == \
        [(r["spec"], r["seed"]) for r in r2.rounds]


@pytest.mark.slow
@pytest.mark.soak
def test_chaos_soak_full(tmp_path):
    """The acceptance soak: 25 fixed-seed iterations over every
    registered site including supervised kill-resume rounds."""
    report = run_soak(SoakConfig(
        workdir=str(tmp_path), iterations=25, seed=20260803,
        include_kill=True,
    ))
    assert report.ok, "\n".join(report.violations)
    assert report.iterations == 25
    assert report.healed >= 1
    assert report.restarts >= 1  # at least one supervised kill-resume


# ------------------------------------------------ new fault-site arming


def test_readahead_worker_decode_fault_delivered_in_order(tmp_path, rng):
    """store.readahead.decode: an io_error in the background warm
    worker is held and re-raised at the consumer's cursor — and an
    unfaulted re-read is bit-identical (the warm failure poisoned
    nothing)."""
    from spark_examples_tpu.pipelines import runner as R
    from spark_examples_tpu.core.config import IngestConfig
    from spark_examples_tpu.store.writer import compact

    g = np.abs(random_genotypes(rng, 8, 512, missing_rate=0.1))
    store = str(tmp_path / "st")
    compact(store, ArraySource(g), chunk_variants=128)
    cfg = IngestConfig(source="store", path=store, block_variants=128,
                       readahead_chunks=2, io_retries=0)
    clean = [(b.copy(), m) for b, m in R.build_source(cfg).blocks(128)]
    with faults.armed(["store.readahead.decode:io_error:after=1:max=1"]) \
            as inj:
        src = R.build_source(cfg)
        with pytest.raises(faults.InjectedFault):
            list(src.blocks(128))
        assert inj.fire_count("store.readahead.decode") == 1
        src.close()
    got = [(b.copy(), m) for b, m in R.build_source(cfg).blocks(128)]
    for (gb, _), (cb, _) in zip(got, clean):
        np.testing.assert_array_equal(gb, cb)


def test_readahead_worker_fault_recovers_through_retry(tmp_path, rng):
    """Same site, wrapped in the retry boundary (the production
    wiring): the held worker error rides reopen-and-seek and the
    stream completes bit-identically."""
    from spark_examples_tpu.pipelines import runner as R
    from spark_examples_tpu.core.config import IngestConfig
    from spark_examples_tpu.store.writer import compact

    g = np.abs(random_genotypes(rng, 8, 512, missing_rate=0.1))
    store = str(tmp_path / "st")
    compact(store, ArraySource(g), chunk_variants=128)
    cfg = IngestConfig(source="store", path=store, block_variants=128,
                       readahead_chunks=2, io_retries=3,
                       io_retry_backoff_s=0.001)
    clean = np.concatenate(
        [b for b, _ in ArraySource(g).blocks(128)], axis=1)
    with faults.armed(["store.readahead.decode:io_error:after=1:max=1"]):
        src = R.build_source(cfg)
        with pytest.warns(RuntimeWarning, match="transient ingest error"):
            got = np.concatenate([b for b, _ in src.blocks(128)], axis=1)
    np.testing.assert_array_equal(got, clean)


def test_staging_ring_transfer_wait_fault(rng, monkeypatch):
    """prefetch.transfer_wait: fires at slab-retire time in the K-deep
    staged feed. Staging is CPU-gated in production (device_put is
    zero-copy there), so the gate is bypassed to prove the site's
    semantics: a delay is absorbed (the stream completes at full
    length), an io_error propagates to the consumer (the job resumes
    from its checkpoint, like device.put)."""
    from spark_examples_tpu.ingest import prefetch

    monkeypatch.setattr(prefetch, "_can_stage", lambda d, s: True)
    g = random_genotypes(rng, 8, 512, missing_rate=0.1)

    def stream():
        # Metas only: with the CPU zero-copy aliasing the gate exists
        # to prevent, block CONTENTS are undefined here — the test
        # asserts cadence and error delivery, not data.
        return [m.stop for _b, m in prefetch.stream_to_device(
            ArraySource(g), 64, prefetch=2)]

    with faults.armed(["prefetch.transfer_wait:delay:delay=0.01:max=2"]) \
            as inj:
        stops = stream()
        assert inj.fire_count("prefetch.transfer_wait") == 2
    assert stops == list(range(64, 513, 64))
    with faults.armed(["prefetch.transfer_wait:io_error:max=1"]) as inj:
        with pytest.raises(faults.InjectedFault):
            stream()
        assert inj.fire_count("prefetch.transfer_wait") == 1


def test_checkpoint_tile_read_fault_falls_back(tmp_path):
    """checkpoint.tile_read under injection: an io_error during latest-
    generation verification rejects that generation and the retained
    .old generation restores (the read-side twin of the tile_write
    truncate test in test_faults)."""
    from spark_examples_tpu.core import checkpoint as ckpt
    from spark_examples_tpu.ops import gram

    ids = [f"s{i}" for i in range(8)]
    acc = {k: np.zeros((8, 8), np.int32)
           for k in gram.PIECES_FOR_METRIC["ibs"]}
    ckpt.save(str(tmp_path / "c"), acc, 128, "ibs", 128, ids)
    ckpt.save(str(tmp_path / "c"), acc, 256, "ibs", 128, ids)  # rotates
    assert os.path.isdir(str(tmp_path / "c") + ".old")
    with faults.armed(["checkpoint.tile_read:io_error:after=0:max=1"]):
        with pytest.warns(RuntimeWarning, match="falling back"):
            restored = ckpt.load(str(tmp_path / "c"), "ibs", ids,
                                 block_variants=128)
    assert restored is not None
    _acc, cursor, _stats = restored
    assert cursor == 128  # the .old generation's cursor

import numpy as np
import pytest

from spark_examples_tpu.cli.main import main
from spark_examples_tpu.pipelines.io import read_matrix, write_matrix


def _run(capsys, *argv):
    rc = main(list(argv))
    assert rc == 0
    return capsys.readouterr()


BASE = ["--n-samples", "24", "--n-variants", "1500", "--block-variants", "512"]


def test_cli_pcoa_writes_coords(tmp_path, capsys):
    out = str(tmp_path / "coords.tsv")
    cap = _run(capsys, "pcoa", *BASE, "--num-pc", "3", "--output-path", out)
    assert "24 samples x 3 components" in cap.out
    assert "eigenvalues:" in cap.out and "explained:" in cap.out
    rows = open(out).read().strip().splitlines()
    assert rows[0] == "sample\tpc1\tpc2\tpc3"
    assert len(rows) == 25


def test_cli_similarity_then_pcoa_from_matrix(tmp_path, capsys):
    m = str(tmp_path / "sim.tsv")
    _run(capsys, "similarity", *BASE, "--metric", "ibs", "--output-path", m)
    ids, sim, kind = read_matrix(m)
    assert sim.shape == (24, 24)
    assert kind == "similarity"  # self-describing sidecar
    # PCoA consuming the persisted similarity directly: the sidecar tells
    # it to Gower-transform (the naive handoff that used to be degenerate).
    out = str(tmp_path / "coords.tsv")
    cap = _run(capsys, "pcoa", "--matrix-path", m, "--num-pc", "2",
               "--output-path", out)
    assert "2 components" in cap.out
    # explicit distance matrix still accepted
    d = str(tmp_path / "dist.tsv")
    write_matrix(d, ids, 1.0 - sim, kind="distance")
    cap = _run(capsys, "pcoa", "--matrix-path", d, "--num-pc", "2")
    assert "2 components" in cap.out


def test_cli_npy_matrix_keeps_sample_ids(tmp_path, capsys):
    m = str(tmp_path / "sim.npy")
    _run(capsys, "similarity", *BASE, "--metric", "ibs", "--output-path", m)
    ids, sim, kind = read_matrix(m)
    assert kind == "similarity"
    assert ids[0].startswith("P")  # real cohort ids, not fabricated S000000


def test_cli_pca_cpu_backend(tmp_path, capsys):
    cap = _run(capsys, "pca", *BASE, "--backend", "cpu-reference",
               "--num-pc", "2")
    assert "24 samples x 2 components" in cap.out


def test_cli_search_variants(capsys):
    cap = _run(capsys, "search-variants", *BASE, "--positions", "3", "7")
    lines = [l for l in cap.out.splitlines() if l.strip()]
    assert len(lines) == 2
    assert "af=" in lines[0]


def test_cli_search_variants_output_path(tmp_path, capsys):
    out = str(tmp_path / "hist.tsv")
    _run(capsys, "search-variants", "--n-samples", "12", "--n-variants",
         "120", "--block-variants", "64", "--output-path", out)
    rows = open(out).read().strip().splitlines()
    assert rows[0].startswith("contig\tposition")
    assert len(rows) == 121  # full table, not the 50-row console preview


def test_cli_vcf_source(tmp_path, capsys):
    from spark_examples_tpu.ingest import write_vcf

    rng = np.random.default_rng(0)
    g = rng.integers(0, 3, (10, 50)).astype(np.int8)
    path = str(tmp_path / "t.vcf")
    write_vcf(path, g, contig="chr22", start_pos=100)
    cap = _run(capsys, "similarity", "--source", "vcf", "--path", path,
               "--metric", "ibs", "--block-variants", "16")
    assert "10x10 over 50 variants" in cap.out
    cap = _run(capsys, "search-variants", "--source", "vcf", "--path", path,
               "--positions", "100")
    assert cap.out.startswith("chr22:100")


def test_cli_trace_dir_captures_profile(tmp_path, capsys):
    trace_dir = str(tmp_path / "trace")
    _run(capsys, "pcoa", *BASE, "--num-pc", "2", "--trace-dir", trace_dir)
    import os

    found = [
        os.path.join(r, f)
        for r, _, fs in os.walk(trace_dir)
        for f in fs
    ]
    assert found, "no jax.profiler trace files written"


def test_cli_pack_then_pcoa(tmp_path, capsys):
    """The ETL handoff: pack a VCF into the 2-bit store, then run PCoA
    from the store — same coordinates as straight from the VCF."""
    from spark_examples_tpu.ingest import write_vcf

    rng = np.random.default_rng(3)
    g = rng.integers(0, 3, (12, 300)).astype(np.int8)
    vcf = str(tmp_path / "c.vcf")
    write_vcf(vcf, g, contig="chr1", start_pos=500)
    store = str(tmp_path / "store")
    cap = _run(capsys, "pack", "--source", "vcf", "--path", vcf,
               "--block-variants", "64", "--output-path", store)
    assert "packed 12 samples x 300 variants" in cap.out

    from_store = str(tmp_path / "a.tsv")
    from_vcf = str(tmp_path / "b.tsv")
    _run(capsys, "pcoa", "--source", "packed", "--path", store,
         "--block-variants", "64", "--num-pc", "3",
         "--output-path", from_store)
    _run(capsys, "pcoa", "--source", "vcf", "--path", vcf,
         "--block-variants", "64", "--num-pc", "3",
         "--output-path", from_vcf)
    a = np.loadtxt(from_store, skiprows=1, usecols=(1, 2, 3))
    b = np.loadtxt(from_vcf, skiprows=1, usecols=(1, 2, 3))
    np.testing.assert_allclose(np.abs(a), np.abs(b), atol=1e-5)


def test_cli_sample_stats(tmp_path, capsys):
    out = str(tmp_path / "stats.tsv")
    cap = _run(capsys, "sample-stats", *BASE, "--output-path", out)
    assert cap.out.startswith("sample\tn_called")
    rows = open(out).read().strip().splitlines()
    assert len(rows) == 25  # header + 24 samples
    cols = rows[1].split("\t")
    assert len(cols) == 6 and 0.0 <= float(cols[2]) <= 1.0


def test_cli_version(capsys):
    with pytest.raises(SystemExit) as e:
        main(["--version"])
    assert e.value.code == 0
    from spark_examples_tpu.version import __version__

    assert __version__ in capsys.readouterr().out


def test_cli_pack_with_ld_prune(tmp_path, capsys):
    """pack composes with the QC/LD transforms (two passes: the count
    for preallocation, then the stream) and the store holds the pruned
    set."""
    rng = np.random.default_rng(6)
    base = rng.integers(0, 3, (120, 30), dtype=np.int8)
    # interleave each variant with its duplicate (adjacent, well inside
    # the pruning window — pairs farther apart than window+carry are
    # out of reach by design)
    g = np.repeat(base, 2, axis=1)
    from spark_examples_tpu.ingest.vcf import write_vcf

    vcf = str(tmp_path / "c.vcf")
    write_vcf(vcf, g)
    store = str(tmp_path / "store")
    cap = _run(capsys, "pack", "--source", "vcf", "--path", vcf,
               "--ld-prune-r2", "0.3", "--ld-window", "20",
               "--block-variants", "16", "--output-path", store)
    assert "x 30 variants" in cap.out  # every duplicate pruned


def test_cli_eigh_knobs(tmp_path, capsys):
    """--eigh-iters/--eigh-oversample thread into the randomized solver;
    a generous setting still recovers the dense answer."""
    out1 = str(tmp_path / "c1.tsv")
    out2 = str(tmp_path / "c2.tsv")
    _run(capsys, "pcoa", *BASE, "--num-pc", "2", "--eigh-mode", "dense",
         "--output-path", out1)
    _run(capsys, "pcoa", *BASE, "--num-pc", "2",
         "--eigh-mode", "randomized", "--eigh-iters", "16",
         "--eigh-oversample", "16", "--output-path", out2)

    def coords(p):
        rows = [r.split("\t")[1:] for r in
                open(p).read().strip().splitlines()[1:]]
        return np.abs(np.asarray(rows, float))

    np.testing.assert_allclose(coords(out2), coords(out1),
                               rtol=5e-2, atol=1e-3)

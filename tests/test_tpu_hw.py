"""Real-hardware gate: compile the Pallas kernel for the TPU (no interpret).

The suite's conftest pins the whole pytest process to the virtual-CPU
backend (the `local[*]` analogue), so hardware coverage runs in a
subprocess that inherits the ambient environment — in this image
``JAX_PLATFORMS=axon`` (TPU v5 lite via the axon PJRT plugin). If the
platform fails to initialise (no tunnel, plugin unsupported) the test
skips with the subprocess's stderr as the recorded reason rather than
failing: the kernel's correctness is already pinned CPU-side
(test_kernels.py); this test is specifically "Mosaic accepts and runs it
on the real chip".
"""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_SCRIPT = r"""
import json, sys
import numpy as np
import jax

if jax.default_backend() == "cpu":
    print(json.dumps({"skip": "no accelerator platform available"}))
    sys.exit(0)

from spark_examples_tpu.ops.pallas.braycurtis_kernel import braycurtis_pallas
from spark_examples_tpu.utils import oracle

rng = np.random.default_rng(7)
x = (rng.gamma(0.5, 40.0, (96, 640)) * (rng.random((96, 640)) > 0.6))
x = x.astype(np.float32)
got = np.asarray(braycurtis_pallas(x))  # interpret=False: real Mosaic compile
want = oracle.cpu_braycurtis(x)
print(json.dumps({
    "backend": jax.default_backend(),
    "max_err": float(np.abs(got - want).max()),
}))
"""


def _run_on_hw(script: str, timeout: int = 420) -> dict:
    env = dict(os.environ)
    # Undo anything the parent test session forced; let the ambient
    # platform (axon TPU here, CPU elsewhere) win in the child.
    env.pop("XLA_FLAGS", None)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    try:
        proc = subprocess.run(
            [sys.executable, "-c", script], env=env, cwd=REPO,
            capture_output=True, text=True, timeout=timeout,
        )
    except subprocess.TimeoutExpired:
        pytest.skip("hardware subprocess timed out (tunnel stall?)")
    if proc.returncode != 0:
        pytest.skip(
            "TPU platform unavailable/unsupported for this kernel: "
            + proc.stderr.strip()[-800:]
        )
    return json.loads(proc.stdout.strip().splitlines()[-1])


def test_pallas_braycurtis_compiles_on_tpu():
    out = _run_on_hw(_SCRIPT)
    if "skip" in out:
        pytest.skip(out["skip"])
    assert out["backend"] != "cpu"
    assert out["max_err"] < 1e-4, out

"""Real-hardware gate: compile the Pallas kernel for the TPU (no interpret).

The suite's conftest pins the whole pytest process to the virtual-CPU
backend (the `local[*]` analogue), so hardware coverage runs in a
subprocess that inherits the ambient environment — in this image
``JAX_PLATFORMS=axon`` (TPU v5 lite via the axon PJRT plugin). If the
platform fails to initialise (no tunnel, plugin unsupported) the test
skips with the subprocess's stderr as the recorded reason rather than
failing: the kernel's correctness is already pinned CPU-side
(test_kernels.py); this test is specifically "Mosaic accepts and runs it
on the real chip".
"""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_SCRIPT = r"""
import json, sys
import numpy as np
import jax

if jax.default_backend() == "cpu":
    print(json.dumps({"skip": "no accelerator platform available"}))
    sys.exit(0)

from spark_examples_tpu.ops.pallas.braycurtis_kernel import braycurtis_pallas
from spark_examples_tpu.utils import oracle

rng = np.random.default_rng(7)
x = (rng.gamma(0.5, 40.0, (96, 640)) * (rng.random((96, 640)) > 0.6))
x = x.astype(np.float32)
got = np.asarray(braycurtis_pallas(x))  # interpret=False: real Mosaic compile
want = oracle.cpu_braycurtis(x)
print(json.dumps({
    "backend": jax.default_backend(),
    "max_err": float(np.abs(got - want).max()),
}))
"""


def _hw_env() -> dict:
    env = dict(os.environ)
    # Undo anything the parent test session forced; let the ambient
    # platform (axon TPU here, CPU elsewhere) win in the child. This
    # image's sitecustomize happens to override JAX_PLATFORMS anyway,
    # but don't rely on that.
    env.pop("XLA_FLAGS", None)
    env.pop("JAX_PLATFORMS", None)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    return env


_ambient_stalled: bool | None = None
_PROBE_TIMEOUT_S = 180


def _platform_init_stalled() -> bool:
    """One bounded probe per module: does ambient-platform init hang?
    With a dead TPU tunnel the plugin stalls inside backend init, so
    WITHOUT this gate every test here burns its full 420 s subprocess
    timeout (the perf gates retry once — up to ~35 min total) just to
    learn the chip is gone. A healthy platform — real TPU or plain CPU
    — answers this probe in seconds and the tests proceed unchanged."""
    global _ambient_stalled
    if _ambient_stalled is None:
        # 180 s = 3x the documented worst healthy first-init (~60 s for
        # eigh compiles on axon). Raising it further would protect a
        # pathologically slow-but-alive tunnel at the cost of eating the
        # tier-1 wall-clock budget every time the tunnel is genuinely
        # dead; the skip message names the bound so a misclassified
        # slow session is visible rather than silent.
        try:
            subprocess.run(
                [sys.executable, "-c", "import jax; jax.default_backend()"],
                env=_hw_env(), cwd=REPO, capture_output=True,
                timeout=_PROBE_TIMEOUT_S,
            )
            _ambient_stalled = False
        except subprocess.TimeoutExpired:
            _ambient_stalled = True
    return _ambient_stalled


def _run_on_hw(script: str, timeout: int = 420, strict: bool = False) -> dict:
    """``strict``: a nonzero exit from the child is a test FAILURE, not
    a skip — for gates where the crash IS the regression (the script
    must print its own skip JSON for platform-unavailable cases before
    entering the guarded section). Timeouts still skip either way: on a
    tunneled dev chip a stall is ambiguous."""
    if _platform_init_stalled():
        pytest.skip(
            "ambient accelerator platform init exceeded "
            f"{_PROBE_TIMEOUT_S} s (dead tunnel, or a pathologically "
            "slow session — raise _PROBE_TIMEOUT_S if the chip is "
            "known healthy)"
        )
    env = _hw_env()
    try:
        proc = subprocess.run(
            [sys.executable, "-c", script], env=env, cwd=REPO,
            capture_output=True, text=True, timeout=timeout,
        )
    except subprocess.TimeoutExpired:
        pytest.skip("hardware subprocess timed out (tunnel stall?)")
    if proc.returncode != 0:
        if strict:
            pytest.fail(
                "hardware subprocess crashed (the crash IS the "
                "regression for this gate): "
                + proc.stderr.strip()[-800:]
            )
        pytest.skip(
            "TPU platform unavailable/unsupported for this kernel: "
            + proc.stderr.strip()[-800:]
        )
    return json.loads(proc.stdout.strip().splitlines()[-1])


def test_pallas_braycurtis_compiles_on_tpu():
    out = _run_on_hw(_SCRIPT)
    if "skip" in out:
        pytest.skip(out["skip"])
    assert out["backend"] != "cpu"
    assert out["max_err"] < 1e-4, out


_PERF_SCRIPT = r"""
import json, sys, time

# Platform-init guard: anything failing in here is "hardware
# unavailable" (skip); anything failing AFTER it is a real lowering
# regression and must crash the subprocess (strict mode fails the test).
try:
    import jax

    if jax.default_backend() != "tpu":
        # The TFLOP/s floor is calibrated for a TPU MXU; running it
        # on cpu OR another accelerator (a CUDA dev box) would fail
        # spuriously.
        print(json.dumps(
            {"skip": f"backend is {jax.default_backend()!r}, not tpu"}
        ))
        sys.exit(0)
    jax.numpy.zeros(8).block_until_ready()  # platform truly usable
except Exception as e:  # noqa: BLE001 - any init failure = skip
    print(json.dumps({"skip": f"platform init failed: {e!r}"}))
    sys.exit(0)

import jax.numpy as jnp
from spark_examples_tpu.core.profiling import hard_sync
from spark_examples_tpu.ops import gram

# Staged-shaped gram: one compiled scan over data-dependent slices at
# the bench's production block width (bench.py staged_run) — narrower
# blocks are int32-accumulator-bandwidth-bound (measured 61 TFLOP/s at
# 32768 vs 155+ at 131072), which would gate on the wrong regime. The
# 1.3 GB operand is generated on-device; no tunnel traffic.
N, V_BLK, N_BLOCKS = 2504, 131072, 4
pieces = gram.PIECES_FOR_METRIC["ibs"]
g = hard_sync(jax.random.randint(
    jax.random.key(0), (N, V_BLK * N_BLOCKS), -1, 3, jnp.int8
))

@jax.jit
def accumulate(g):
    def body(acc, start):
        blk = jax.lax.dynamic_slice(g, (0, start), (N, V_BLK))
        return gram._update_impl(acc, blk, pieces), None
    acc0 = {k: jnp.zeros((N, N), jnp.int32) for k in pieces}
    acc, _ = jax.lax.scan(body, acc0, jnp.arange(N_BLOCKS) * V_BLK)
    return acc

# Deterministic dtype check on the COMPILED program: the ibs update must
# lower to int8 x int8 -> int32 MXU ops (on TPU XLA emits them as
# s32[...] convolution(...) with s8 fused operands). A silent precision
# downgrade (bf16/f32 operands) changes these dtypes regardless of how
# fast the session happens to be — the failure mode a wall-clock floor
# cannot separate from session variance.
import re
hlo = accumulate.lower(g).compile().as_text()
matmul_ops = re.findall(r"= (\w+)\[[^\]]*\]\S* (?:convolution|dot)\(", hlo)
n_int_matmuls = sum(1 for dt in matmul_ops if dt == "s32")
n_float_matmuls = sum(1 for dt in matmul_ops if dt in ("f32", "bf16", "f16"))

hard_sync(accumulate(g))  # compile+warm
best = 1e9
for _ in range(3):
    t0 = time.perf_counter()
    hard_sync(accumulate(g))
    best = min(best, time.perf_counter() - t0)
flops = gram.flops_per_block(N, V_BLK * N_BLOCKS, "ibs")
print(json.dumps({
    "backend": jax.default_backend(),
    "tflops": flops / best / 1e12,
    "wall_ms": best * 1e3,
    "int_matmuls": n_int_matmuls,
    "float_matmuls": n_float_matmuls,
}))
"""


def test_gram_throughput_floor_on_tpu():
    """Two-part regression gate for the int8 gram lowering (VERDICT r4
    weak #3 — the old 30 TFLOP/s floor could not tell a regression from
    session variance, which was its entire job):

    1. **Deterministic dtype assertion** on the compiled HLO: every
       matmul of the update must be an s32-accumulating integer op and
       none may be bf16/f32 — a silent precision downgrade is caught
       structurally, with zero dependence on how fast the session is.
       (A numeric floor alone cannot do this: a bf16 downgrade at v5e's
       197-TFLOPS bf16 peak lands ~142-154 at typical efficiency,
       inside the observed healthy-session band of 139-285.)
    2. **Throughput floor at 110 TFLOP/s**: catches execution-class
       regressions the dtype check can't see (VPU lowering, layout
       pathologies, scan de-pipelining — all multiples slower), while
       sitting safely under the slowest healthy session observed at
       this shape (139).

    One retry absorbs transient tunnel blips mid-benchmark (observed
    ~1-in-10 during suite soaks); a persistent crash still fails — the
    crash IS the regression."""
    retryable = (Exception, pytest.fail.Exception, pytest.skip.Exception)
    for attempt in (1, 2):
        try:
            out = _run_on_hw(_PERF_SCRIPT, strict=True)
            break
        except retryable:
            if attempt == 2:
                raise
    if "skip" in out:
        pytest.skip(out["skip"])
    assert out["float_matmuls"] == 0, (
        f"precision downgrade: float matmuls in the int8 update HLO — {out}"
    )
    assert out["int_matmuls"] >= 4, (
        f"expected >= 4 s32 matmul ops (one per ibs piece) — {out}"
    )
    assert out["tflops"] > 110.0, out


_BC_PERF_SCRIPT = r"""
import json, sys, time

try:
    import jax

    if jax.default_backend() != "tpu":
        print(json.dumps(
            {"skip": f"backend is {jax.default_backend()!r}, not tpu"}
        ))
        sys.exit(0)
    jax.numpy.zeros(8).block_until_ready()
except Exception as e:  # noqa: BLE001 - any init failure = skip
    print(json.dumps({"skip": f"platform init failed: {e!r}"}))
    sys.exit(0)

import jax.numpy as jnp
from spark_examples_tpu.core.profiling import hard_sync
from spark_examples_tpu.ops.pallas.braycurtis_kernel import braycurtis_pallas

# The config-3 shape exactly (BASELINE.md): 10k-sample OTU table,
# generated on-device so no tunnel traffic pollutes the number.
N, F = 10_000, 4096
k1, k2 = jax.random.split(jax.random.key(7))
x = jnp.where(
    jax.random.uniform(k1, (N, F)) > 0.6,
    jnp.floor(jax.random.exponential(k2, (N, F)) * 20.0),
    0.0,
).astype(jnp.float32)
x = hard_sync(x)

hard_sync(braycurtis_pallas(x))  # compile+warm
best = 1e9
for _ in range(3):
    t0 = time.perf_counter()
    hard_sync(braycurtis_pallas(x))
    best = min(best, time.perf_counter() - t0)
print(json.dumps({"backend": jax.default_backend(), "wall_s": best}))
"""


def test_braycurtis_pallas_floor_on_tpu():
    """Performance gate for the fused-VMEM Bray-Curtis kernel at the
    full config-3 shape: < 1 s at N=10k (measured 0.33 s on v5e; the
    threshold-matmul MXU fallback runs ~1.25 s and the exact VPU
    lowering ~50 s, so a silent fallback to either fails the gate
    while leaving ~3x headroom over session variance)."""
    retryable = (Exception, pytest.fail.Exception, pytest.skip.Exception)
    for attempt in (1, 2):
        try:
            out = _run_on_hw(_BC_PERF_SCRIPT, strict=True)
            break
        except retryable:
            if attempt == 2:
                raise
    if "skip" in out:
        pytest.skip(out["skip"])
    assert out["wall_s"] < 1.0, out

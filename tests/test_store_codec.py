"""Compressed chunk store (store/codec.py + manifest v3): round-trip
bit-identity on both transports against raw stores and direct sources,
v1/v2 back-compat reads, mixed-codec stores, unknown-codec rejection,
corrupt-compressed-chunk quarantine and byte-identical origin healing
(incl. dictionary recovery), deterministic parallel compaction, the
native decode-to-slab entry and its loud Python fallback, and the
cadence-adaptive readahead depth."""

import json
import os
import threading
import time
import warnings
import zlib

import numpy as np
import pytest

from spark_examples_tpu import native
from spark_examples_tpu.core import faults, hashing, telemetry
from spark_examples_tpu.core.config import IngestConfig
from spark_examples_tpu.ingest import bitpack, write_vcf
from spark_examples_tpu.ingest.resilient import RetryingSource, RetryPolicy
from spark_examples_tpu.ingest.source import ArraySource
from spark_examples_tpu.ingest.synthetic import SyntheticSource
from spark_examples_tpu.ingest.vcf import VcfSource
from spark_examples_tpu.store import (
    StoreCorruptError,
    StoreFormatError,
    compact,
    open_store,
    origin_from_ingest,
)
from spark_examples_tpu.store import codec as codecmod
from spark_examples_tpu.store.manifest import StoreManifest
from spark_examples_tpu.store.readahead import ReadaheadPool
from tests.conftest import random_genotypes


def _materialize(source, block_variants, start=0):
    blocks = [b for b, _ in source.blocks(block_variants, start)]
    return np.concatenate(blocks, axis=1) if blocks else None


def _materialize_packed(source, block_variants):
    cols = []
    for pb, m in source.packed_blocks(block_variants):
        cols.append(bitpack.unpack_dosages_np(pb)[:, : m.stop - m.start])
    return np.concatenate(cols, axis=1)


def _force_python_decode(monkeypatch):
    """Pin the pure-Python decode path without rebuilding, the
    test_native idiom: stub the loader state."""
    monkeypatch.setattr(native, "_lib", None)
    monkeypatch.setattr(native, "_tried", True)


@pytest.fixture
def zstore(tmp_path, genotypes):
    """A zlib-compressed store over the shared 37 x 211 cohort with an
    origin recipe (ArraySource cannot be an origin, so synthetic)."""
    cfg = IngestConfig(source="synthetic", n_samples=16, n_variants=384,
                       seed=2)
    from spark_examples_tpu.pipelines.runner import build_source

    src = build_source(cfg)
    d = str(tmp_path / "z")
    compact(d, src, chunk_variants=64, codec="zlib",
            origin=origin_from_ingest(cfg, 64))
    want = _materialize(build_source(cfg), 64)
    return d, want


# ---------------------------------------------------------------------------
# Round-trip bit-identity


@pytest.mark.parametrize("spec", ["zlib", "zlib-dict"])
def test_compressed_roundtrip_synthetic_both_transports(tmp_path, spec):
    src = SyntheticSource(n_samples=13, n_variants=501, seed=11)
    raw_dir = str(tmp_path / "raw")
    cmp_dir = str(tmp_path / "cmp")
    compact(raw_dir, src, chunk_variants=64, codec="raw")
    manifest = compact(cmp_dir, src, chunk_variants=64, codec=spec)
    assert all(c.codec == "zlib" for c in manifest.chunks)
    assert all((c.dict_digest is not None) == (spec == "zlib-dict")
               for c in manifest.chunks)
    want = _materialize(src, 64)
    for bv in (32, 64, 100, 501):
        np.testing.assert_array_equal(_materialize(open_store(cmp_dir), bv),
                                      want)
    for bv in (32, 64, 256):
        np.testing.assert_array_equal(
            _materialize_packed(open_store(cmp_dir), bv), want)
    # the raw store decodes to the same bytes (codecs are transparent)
    np.testing.assert_array_equal(_materialize(open_store(raw_dir), 64),
                                  want)


@pytest.mark.parametrize("spec", ["zlib", "zlib-dict"])
def test_compressed_roundtrip_vcf_multi_contig(tmp_path, rng, spec):
    g1 = random_genotypes(rng, 7, 23, 0.1)
    g2 = random_genotypes(rng, 7, 10, 0.1)
    p1, p2 = str(tmp_path / "a.vcf"), str(tmp_path / "b.vcf")
    write_vcf(p1, g1, contig="chr1", start_pos=100)
    write_vcf(p2, g2, contig="chr2", start_pos=500)
    header = [ln for ln in open(p1) if ln.startswith("#")]
    records = [ln for p in (p1, p2) for ln in open(p)
               if not ln.startswith("#")]
    multi = str(tmp_path / "multi.vcf")
    open(multi, "w").writelines(header + records)
    d = str(tmp_path / "s")
    manifest = compact(d, VcfSource(multi), chunk_variants=8, codec=spec)
    st = open_store(d)
    want = np.concatenate([g1, g2], axis=1)
    np.testing.assert_array_equal(_materialize(st, 16), want)
    np.testing.assert_array_equal(_materialize_packed(open_store(d), 16),
                                  want)
    if spec == "zlib-dict":
        # One dictionary per contig, shared by that contig's chunks.
        by_contig = {}
        for c in manifest.chunks:
            by_contig.setdefault(c.contig, set()).add(c.dict_digest)
        assert all(len(s) == 1 for s in by_contig.values())
        assert by_contig["chr1"] != by_contig["chr2"]


def test_real_genotype_chunks_actually_compress(tmp_path):
    """The tentpole's premise: a realistic MAF spectrum (most variants
    rare, rows dominated by hom-ref zeros — unlike the near-uniform
    synthetic cohort, which deflates only ~1.2x) compresses
    several-fold — and the catalog's size accounting matches the files
    on disk."""
    rng = np.random.default_rng(5)
    maf = rng.uniform(0.001, 0.12, size=4096)
    g = (rng.random((64, 4096)) < maf).astype(np.int8) + (
        rng.random((64, 4096)) < maf).astype(np.int8)
    src = ArraySource(g)
    d = str(tmp_path / "s")
    manifest = compact(d, src, chunk_variants=1024, codec="zlib")
    n = manifest.n_samples
    raw_b = sum(c.payload_size(n) for c in manifest.chunks)
    stored_b = sum(c.disk_size(n) for c in manifest.chunks)
    assert stored_b < raw_b / 1.5  # several-fold on low-entropy data
    for c in manifest.chunks:
        path = os.path.join(d, c.filename())
        assert os.path.getsize(path) == c.stored_size
        assert c.raw_size == c.n_bytes(n)


def test_pcoa_roundtrip_through_compressed_store(tmp_path):
    from spark_examples_tpu.core.config import (
        ComputeConfig, JobConfig,
    )
    from spark_examples_tpu.pipelines.jobs import pcoa_job

    src = SyntheticSource(n_samples=16, n_variants=384, seed=2)
    d = str(tmp_path / "s")
    compact(d, src, chunk_variants=64, codec="zlib-dict")
    compute = ComputeConfig(metric="ibs", num_pc=3)
    direct = pcoa_job(JobConfig(
        ingest=IngestConfig(source="synthetic", n_samples=16,
                            n_variants=384, seed=2, block_variants=128),
        compute=compute,
    ))
    via_store = pcoa_job(JobConfig(
        ingest=IngestConfig(source=f"store:{d}", block_variants=128),
        compute=compute,
    ))
    np.testing.assert_array_equal(direct.coords, via_store.coords)


# ---------------------------------------------------------------------------
# Back-compat: v1/v2 stores read back untouched


def _downgrade_manifest(d, version):
    """Rewrite a raw-codec store's manifest as its v1/v2 ancestor
    (6-element chunk rows, no codec fields)."""
    path = os.path.join(d, "manifest.json")
    m = json.load(open(path))
    m["schema_version"] = version
    m["chunks"] = [row[:6] for row in m["chunks"]]
    if version < 2:
        m.pop("origin", None)
    json.dump(m, open(path, "w"))


@pytest.mark.parametrize("version", [1, 2])
def test_v1_v2_store_reads_untouched(tmp_path, genotypes, version):
    src = ArraySource(genotypes)
    d = str(tmp_path / "s")
    compact(d, src, chunk_variants=32, codec="raw")
    before = sorted(os.listdir(os.path.join(d, "chunks")))
    _downgrade_manifest(d, version)
    st = open_store(d)
    assert st.manifest.schema_version == version
    assert all(c.codec == "raw" and c.stored_size == -1
               for c in st.manifest.chunks)
    np.testing.assert_array_equal(_materialize(st, 32), genotypes)
    np.testing.assert_array_equal(_materialize_packed(open_store(d), 32),
                                  genotypes)
    # reading rewrites nothing
    assert sorted(os.listdir(os.path.join(d, "chunks"))) == before


def test_unknown_codec_rejected_at_load(tmp_path, genotypes):
    d = str(tmp_path / "s")
    compact(d, ArraySource(genotypes), chunk_variants=32, codec="zlib")
    path = os.path.join(d, "manifest.json")
    m = json.load(open(path))
    m["chunks"][1][6] = "lz99"
    json.dump(m, open(path, "w"))
    with pytest.raises(StoreFormatError, match="unknown codec 'lz99'"):
        open_store(d)


def test_mixed_codec_chunks_in_one_store(tmp_path, genotypes):
    """Codecs are a per-chunk property: one chunk converted to raw
    (new stored bytes -> new content address) reads back transparently
    beside its zlib neighbors, on both transports."""
    d = str(tmp_path / "s")
    manifest = compact(d, ArraySource(genotypes), chunk_variants=32,
                       codec="zlib")
    rec = manifest.chunks[2]
    stored = open(os.path.join(d, rec.filename()), "rb").read()
    payload = zlib.decompress(stored)
    new_digest = hashing.sha256_bytes(payload)
    with open(os.path.join(d, "chunks", f"{new_digest}.bin"), "wb") as f:
        f.write(payload)
    path = os.path.join(d, "manifest.json")
    m = json.load(open(path))
    row = m["chunks"][2]
    assert row[3] == rec.digest
    row[3], row[6], row[8] = new_digest, "raw", len(payload)
    json.dump(m, open(path, "w"))
    st = open_store(d)
    assert [c.codec for c in st.manifest.chunks].count("raw") == 1
    np.testing.assert_array_equal(_materialize(st, 32), genotypes)
    np.testing.assert_array_equal(_materialize_packed(open_store(d), 32),
                                  genotypes)


# ---------------------------------------------------------------------------
# Integrity: corrupt compressed chunks quarantine / heal exactly like raw


def test_corrupt_compressed_chunk_quarantined(tmp_path, genotypes):
    d = str(tmp_path / "s")
    manifest = compact(d, ArraySource(genotypes), chunk_variants=32,
                       codec="zlib")  # no origin, no replica: no route
    victim = os.path.join(d, manifest.chunks[2].filename())
    raw = bytearray(open(victim, "rb").read())
    raw[5] ^= 0x10
    open(victim, "wb").write(bytes(raw))
    before = telemetry.counter_value("store.quarantined")
    with pytest.raises(StoreCorruptError, match="content address") as e:
        _materialize(open_store(d), 32)
    assert e.value.cursor == 64
    q = json.load(open(os.path.join(d, "quarantine.json")))
    assert len(q) == 1 and q[0]["start"] == 64
    assert telemetry.counter_value("store.quarantined") == before + 1


def test_truncated_compressed_chunk_caught_by_size(tmp_path, genotypes):
    """Truncation detection no longer falls out of the mmap shape (a
    compressed file's size is per-chunk) — the catalog's stored_size
    must catch it."""
    d = str(tmp_path / "s")
    manifest = compact(d, ArraySource(genotypes), chunk_variants=32,
                       codec="zlib")
    victim = os.path.join(d, manifest.chunks[0].filename())
    with open(victim, "r+b") as f:
        f.truncate(max(manifest.chunks[0].stored_size - 3, 1))
    with pytest.raises(StoreCorruptError, match="catalog says"):
        open_store(d).read_range(0, 8)


def test_compressed_chunk_heals_from_origin_byte_identically(zstore):
    """The acceptance bullet: `store heal` re-compaction reproduces
    compressed chunks BYTE-identically from the recorded origin."""
    d, want = zstore
    manifest = StoreManifest.load(d)
    rec = manifest.chunks[1]
    victim = os.path.join(d, rec.filename())
    original = open(victim, "rb").read()
    raw = bytearray(original)
    raw[7] ^= 0x40
    open(victim, "wb").write(bytes(raw))
    healed0 = telemetry.counter_value("store.healed")
    with warnings.catch_warnings():
        warnings.simplefilter("ignore", RuntimeWarning)
        got = _materialize(open_store(d), 64)
    np.testing.assert_array_equal(got, want)
    assert telemetry.counter_value("store.healed") == healed0 + 1
    assert open(victim, "rb").read() == original  # byte-identical repair
    assert not os.path.exists(os.path.join(d, "quarantine.json"))


def test_store_heal_cli_verb_repairs_compressed_store(zstore, capsys):
    from spark_examples_tpu.cli.main import main

    d, want = zstore
    manifest = StoreManifest.load(d)
    victim = os.path.join(d, manifest.chunks[0].filename())
    original = open(victim, "rb").read()
    open(victim, "wb").write(original[:-2])  # truncate
    assert main(["store", "heal", "--path", d, "--verify-all"]) == 0
    capsys.readouterr()
    assert open(victim, "rb").read() == original
    np.testing.assert_array_equal(_materialize(open_store(d), 64), want)


def test_dict_file_recovered_from_origin(tmp_path):
    """A deleted dicts/<digest>.zdict is re-derived from the origin
    (the dictionary is a pure function of its trainer chunk's raw
    payload) and the stream continues bit-identically."""
    from spark_examples_tpu.pipelines.runner import build_source

    cfg = IngestConfig(source="synthetic", n_samples=16, n_variants=384,
                       seed=2)
    d = str(tmp_path / "s")
    compact(d, build_source(cfg), chunk_variants=64, codec="zlib-dict",
            origin=origin_from_ingest(cfg, 64))
    want = _materialize(build_source(cfg), 64)
    manifest = StoreManifest.load(d)
    dd = manifest.chunks[0].dict_digest
    os.remove(codecmod.dict_path(d, dd))
    np.testing.assert_array_equal(_materialize(open_store(d), 64), want)
    # ... and the file is back, content-addressed.
    assert hashing.sha256_file(codecmod.dict_path(d, dd)) == dd


def test_dict_missing_without_origin_fails_fast(tmp_path, genotypes):
    d = str(tmp_path / "s")
    manifest = compact(d, ArraySource(genotypes), chunk_variants=32,
                       codec="zlib-dict")
    os.remove(codecmod.dict_path(d, manifest.chunks[0].dict_digest))
    with pytest.raises(StoreCorruptError, match="dictionary"):
        _materialize(open_store(d), 32)


def test_injected_io_error_recovers_on_compressed_store(zstore):
    d, want = zstore
    with faults.armed(["store.read:io_error:after=2:max=2"]) as inj:
        rs = RetryingSource(
            open_store(d),
            policy=RetryPolicy(max_retries=2, backoff_s=0.001),
            reopen=lambda: open_store(d),
        )
        with warnings.catch_warnings():
            warnings.simplefilter("ignore", RuntimeWarning)
            got = _materialize(rs, 64)
        assert inj.fire_count("store.read") == 2
    np.testing.assert_array_equal(got, want)


def test_readahead_decode_fault_on_native_dense_path(zstore):
    """store.readahead.decode armed while the dense-transport warms run
    the NATIVE decode-to-slab entry over compressed chunks: the worker
    error is held, re-raised at the consumer's cursor, and the retry
    boundary recovers bit-identically."""
    if not native.has_store_decode():
        pytest.skip("native decode entry unavailable")
    d, want = zstore
    errors0 = telemetry.counter_value("store.readahead.errors")
    with faults.armed(["store.readahead.decode:io_error:after=1:max=1"]):
        rs = RetryingSource(
            open_store(d, readahead_chunks=2),
            policy=RetryPolicy(max_retries=2, backoff_s=0.001),
            reopen=lambda: open_store(d, readahead_chunks=2),
        )
        got = _materialize(rs, 64)
    np.testing.assert_array_equal(got, want)
    assert telemetry.counter_value("store.readahead.errors") == errors0 + 1


# ---------------------------------------------------------------------------
# Determinism


def test_compressed_compaction_deterministic_across_workers(tmp_path):
    src1 = SyntheticSource(n_samples=24, n_variants=700, seed=9)
    src4 = SyntheticSource(n_samples=24, n_variants=700, seed=9)
    d1, d4 = str(tmp_path / "w1"), str(tmp_path / "w4")
    compact(d1, src1, chunk_variants=64, workers=1, codec="zlib-dict")
    compact(d4, src4, chunk_variants=64, workers=4, codec="zlib-dict")
    m1 = open(os.path.join(d1, "manifest.json"), "rb").read()
    m4 = open(os.path.join(d4, "manifest.json"), "rb").read()
    assert m1 == m4
    for sub in ("chunks", "dicts"):
        f1 = sorted(os.listdir(os.path.join(d1, sub)))
        f4 = sorted(os.listdir(os.path.join(d4, sub)))
        assert f1 == f4
        for name in f1:
            a = open(os.path.join(d1, sub, name), "rb").read()
            b = open(os.path.join(d4, sub, name), "rb").read()
            assert a == b


def test_recompaction_dedupes_compressed_chunks(tmp_path, genotypes):
    src = ArraySource(genotypes)
    d = str(tmp_path / "s")
    compact(d, src, chunk_variants=32, codec="zlib")
    files = sorted(os.listdir(os.path.join(d, "chunks")))
    compact(d, src, chunk_variants=32, codec="zlib")  # byte-deterministic
    assert sorted(os.listdir(os.path.join(d, "chunks"))) == files


# ---------------------------------------------------------------------------
# Native decode-to-slab + the loud fallback


def test_packaged_library_exports_decode_symbol():
    """Native build smoke (tier-1): the freshly-built .so must export
    the decode-to-slab entry — a stale binary missing it would silently
    run the slow path if nothing asserted this."""
    if native.load() is None:
        pytest.skip("native library unavailable (no g++?)")
    assert native.has_store_decode()
    assert codecmod.native_decode_available()


def test_stale_binary_selects_python_fallback_loudly(
        tmp_path, genotypes, monkeypatch):
    """A library WITHOUT the symbol (stale build): reads stay correct
    through the Python path, `store.codec.fallback` counts once, and a
    one-line warning fires."""
    d = str(tmp_path / "s")
    compact(d, ArraySource(genotypes), chunk_variants=32, codec="zlib")

    real = native.load()
    if real is None:
        pytest.skip("native library unavailable (no g++?)")

    class _Stale:  # an old build: every symbol EXCEPT the new one
        def __getattr__(self, name):
            if name == "store_decode_chunk":
                raise AttributeError(name)
            return getattr(real, name)

    monkeypatch.setattr(native, "_lib", _Stale())
    monkeypatch.setattr(native, "_tried", True)
    monkeypatch.setattr(codecmod, "_fallback_warned", False)
    telemetry.reset()
    assert not native.has_store_decode()
    with pytest.warns(RuntimeWarning, match="decode-to-slab"):
        got = _materialize(open_store(d), 32)
    np.testing.assert_array_equal(got, genotypes)
    assert telemetry.counter_value("store.codec.fallback") == 1.0
    # once per process, not per chunk
    _materialize(open_store(d), 32)
    assert telemetry.counter_value("store.codec.fallback") == 1.0


@pytest.mark.parametrize("spec", ["raw", "zlib", "zlib-dict"])
def test_python_fallback_bit_identical_to_native(tmp_path, spec,
                                                 monkeypatch):
    if native.load() is None:
        pytest.skip("native library unavailable (no g++?)")
    src = SyntheticSource(n_samples=11, n_variants=333, seed=3)
    d = str(tmp_path / "s")
    compact(d, src, chunk_variants=64, codec=spec)
    native_out = _materialize(open_store(d), 50)
    _force_python_decode(monkeypatch)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore", RuntimeWarning)
        python_out = _materialize(open_store(d), 50)
    np.testing.assert_array_equal(native_out, python_out)


def test_decode_range_into_matches_read_range(zstore):
    d, want = zstore
    st = open_store(d)
    out = np.full((st.n_samples, 90), 7, np.int8)
    st.decode_range_into(30, 110, out, col_off=5)
    np.testing.assert_array_equal(out[:, 5:85], want[:, 30:110])
    assert (out[:, :5] == 7).all() and (out[:, 85:] == 7).all()


def test_prefetch_direct_decode_to_slab_path(zstore):
    """The staged dense feed drives decode_range_into against the
    staging ring (decode straight into the slab): forced on (CPU
    placements normally disable staging) and compared bit-for-bit
    against the unstaged stream, padding included."""
    from spark_examples_tpu.ingest.prefetch import (
        _produce_host_blocks, pad_block,
    )

    d, want = zstore
    st = open_store(d)
    staged = []
    gen = _produce_host_blocks(st, 100, 0, 2, 1, False, None,
                               staging=True)
    for host, slot, meta in gen:
        staged.append((host.copy(), meta))
        if slot is not None:
            slot.release()
    plain = list(open_store(d).blocks(100))
    assert [m.start for _h, m in staged] == [m.start for _b, m in plain]
    for (h, _m), (b, _mm) in zip(staged, plain):
        np.testing.assert_array_equal(h, pad_block(b, 100))


def test_retry_boundary_forwards_decode_to_slab(zstore):
    """The DEFAULT config wraps every store in RetryingSource
    (io_retries=3): the wrapper must forward the decode-direct
    capability — and recover an injected IO error mid-span under its
    own budget — or production jobs would silently demote to the
    materialize-then-copy path."""
    from spark_examples_tpu.ingest.prefetch import (
        _produce_host_blocks, pad_block,
    )

    d, want = zstore
    rs = RetryingSource(
        open_store(d),
        policy=RetryPolicy(max_retries=2, backoff_s=0.001),
        reopen=lambda: open_store(d),
    )
    assert hasattr(rs, "decode_range_into") and hasattr(rs, "block_spans")
    with faults.armed(["store.read:io_error:after=2:max=2"]) as inj:
        staged = []
        with warnings.catch_warnings():
            warnings.simplefilter("ignore", RuntimeWarning)
            for host, slot, _meta in _produce_host_blocks(
                    rs, 100, 0, 2, 1, False, None, staging=True):
                staged.append(host.copy())
                if slot is not None:
                    slot.release()
        assert inj.fire_count("store.read") == 2
    plain = list(open_store(d).blocks(100))
    assert len(staged) == len(plain)
    for h, (b, _m) in zip(staged, plain):
        np.testing.assert_array_equal(h, pad_block(b, 100))


# ---------------------------------------------------------------------------
# Cadence-adaptive readahead


def test_adaptive_depth_policy_curve():
    t = ReadaheadPool._target_depth
    assert t(None, None, 2, 16) == 2           # no samples yet: floor
    assert t(0.001, 0.1, 2, 16) == 2           # consumer slow: floor
    assert t(0.1, 0.01, 2, 16) == 11           # decode 10x cadence: +1
    assert t(10.0, 0.001, 2, 16) == 16         # clamped at the ceiling
    assert t(0.0, 0.1, 1, 8) == 1              # instant decode: floor


def test_adaptive_pool_deepens_and_reports(monkeypatch):
    pool = ReadaheadPool(2, max_depth=16)
    try:
        assert pool.depth == 2
        # Synthetic EWMAs: a fast consumer (1 ms cadence) against a
        # slow decode (50 ms) must deepen the window.
        pool._decode_ewma = 0.05
        t = [0.0]

        def _clock():
            t[0] += 0.001
            return t[0]

        import spark_examples_tpu.store.readahead as ra_mod

        monkeypatch.setattr(ra_mod.time, "perf_counter", _clock)
        pool.note_retire()
        pool.note_retire()
        assert pool.depth == 16  # 1 + ceil(50ms / 1ms) clamped
        # ... and back down when the consumer slows to 1 s/block.
        t[0] += 0.0  # continue the clock
        monkeypatch.setattr(
            ra_mod.time, "perf_counter",
            lambda: t.__setitem__(0, t[0] + 1.0) or t[0])
        for _ in range(40):
            pool.note_retire()
        assert pool.depth == 2
    finally:
        pool.close()


def test_adaptive_depth_normalizes_block_grid_to_chunks(monkeypatch):
    """Retire samples normalize to per-CHUNK cadence: a block grid
    coarser than the chunk grid divides the interval by the chunks it
    retired; a finer grid accumulates until a boundary is crossed —
    without this the target depth is wrong by the chunk/block ratio."""
    import spark_examples_tpu.store.readahead as ra_mod

    pool = ReadaheadPool(2, max_depth=16)
    try:
        t = [0.0]
        monkeypatch.setattr(ra_mod.time, "perf_counter", lambda: t[0])
        # 4 chunks retired by one 4 ms block -> 1 ms/chunk, not 4 ms.
        pool.note_retire(3)
        t[0] += 0.004
        pool.note_retire(7)
        assert pool._retire_ewma == pytest.approx(0.001)
        # blocks WITHIN one chunk accumulate: a sub-block retire at the
        # same index samples nothing...
        t[0] += 0.004
        pool.note_retire(7)
        assert pool._retire_ewma == pytest.approx(0.001)
        # ...and the boundary crossing charges the whole accumulated
        # interval to the one chunk retired.
        t[0] += 0.004
        pool.note_retire(8)
        assert pool._retire_ewma == pytest.approx(
            0.001 + 0.25 * (0.008 - 0.001))
    finally:
        pool.close()


def test_consumer_wait_deepens_window():
    """A consume() that had to block on an unfinished warm deepens the
    window on the next retire even when the EWMA ratio says otherwise —
    a starved consumer's retire interval absorbs the decode wait, which
    would otherwise suppress deepening exactly when it is needed."""
    pool = ReadaheadPool(2, max_depth=16)
    try:
        ev = threading.Event()
        pool.schedule(("dense", 0), ev.wait)
        got = [None]
        th = threading.Thread(
            target=lambda: got.__setitem__(0, pool.consume(("dense", 0))))
        th.start()
        time.sleep(0.02)
        ev.set()
        th.join()
        assert got[0] is True
        pool._decode_ewma = 0.0001  # EWMAs claiming "compute-bound"
        pool._retire_ewma = 1.0     # must not override a real wait
        pool.note_retire()
        assert pool.depth == 3
        # wait-free rounds step back toward the target, one per retire.
        pool.note_retire()
        assert pool.depth == 2
    finally:
        pool.close()


def test_fixed_depth_when_max_disabled():
    pool = ReadaheadPool(3, max_depth=0)
    try:
        pool._decode_ewma = 10.0
        pool._retire_ewma = 0.001
        pool.note_retire()
        assert pool.depth == 3  # max <= floor pins the depth
    finally:
        pool.close()


def test_adaptive_depth_live_in_stream(tmp_path):
    """End to end: a streamed read with floor < max keeps the depth
    inside [floor, max] and exports the gauge."""
    src = SyntheticSource(n_samples=8, n_variants=2048, seed=1)
    d = str(tmp_path / "s")
    compact(d, src, chunk_variants=64, codec="zlib")
    st = open_store(d, readahead_chunks=2, readahead_chunks_max=8)
    try:
        _materialize(st, 64)
        assert 2 <= st._ra.depth <= 8
    finally:
        st.close()


# ---------------------------------------------------------------------------
# Cache accounting: decoded (decompressed) bytes, not on-disk bytes


def test_cache_charges_decoded_not_stored_bytes(tmp_path):
    src = SyntheticSource(n_samples=64, n_variants=1024, seed=5)
    d = str(tmp_path / "s")
    manifest = compact(d, src, chunk_variants=256, codec="zlib")
    n = manifest.n_samples
    stored_b = sum(c.disk_size(n) for c in manifest.chunks)
    st = open_store(d)
    _materialize_packed(st, 256)  # payload-cache entries (inflated)
    payload_b = sum(c.payload_size(n) for c in manifest.chunks)
    assert st.cache.stats()["bytes"] == payload_b
    assert payload_b > stored_b  # the compressed sizes would undercount
    _materialize(st, 256)  # dense entries ride alongside
    dense_b = n * manifest.n_variants
    assert st.cache.stats()["bytes"] == payload_b + dense_b


# ---------------------------------------------------------------------------
# Knob validation + CLI surface


def test_store_codec_knob_validated_at_config_time():
    with pytest.raises(ValueError, match="store_codec='lzma'"):
        IngestConfig(store_codec="lzma")
    with pytest.raises(ValueError, match="readahead_chunks_max"):
        IngestConfig(readahead_chunks=8, readahead_chunks_max=4)
    IngestConfig(readahead_chunks=8, readahead_chunks_max=0)  # pinned ok
    with pytest.raises(ValueError, match="readahead_chunks_max"):
        IngestConfig(readahead_chunks_max=-1)


def test_bad_codec_flags_are_usage_errors(tmp_path, capsys):
    from spark_examples_tpu.cli.main import main

    for argv in (
        ["ingest", "--store-codec", "lzma", "--output-path",
         str(tmp_path / "s")],
        ["pcoa", "--readahead-chunks", "8", "--readahead-chunks-max", "4"],
    ):
        with pytest.raises(SystemExit) as e:
            main(argv)
        assert e.value.code == 2
        capsys.readouterr()


def test_ingest_cli_reports_ratio_and_write_rate(tmp_path, capsys):
    from spark_examples_tpu.cli.main import main

    store = str(tmp_path / "store")
    assert main(["ingest", "--source", "synthetic", "--n-samples", "12",
                 "--n-variants", "512", "--chunk-variants", "128",
                 "--output-path", store]) == 0
    out = capsys.readouterr().out
    assert "x zlib" in out and "MB/s written" in out and "MB stored" in out
    # default codec is zlib: the store really is compressed
    m = StoreManifest.load(store)
    assert all(c.codec == "zlib" for c in m.chunks)

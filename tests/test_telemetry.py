"""Telemetry layer (core/telemetry.py): histogram math vs numpy, the
JSONL trace schema (parse / nest / monotonic), exported-throughput
agreement with PhaseTimer, the resettable hard_sync fallback warning,
and retry-incident surfacing — plus the 2-process per-rank export with
a nonzero consensus-wait histogram (the straggler metric)."""

import json
import os

import numpy as np
import pytest

from spark_examples_tpu.core import faults, telemetry
from spark_examples_tpu.core.telemetry import HIST_GROWTH, Histogram


@pytest.fixture(autouse=True)
def _clean_registry():
    """Telemetry is process-wide: every test starts zeroed and leaves
    the layer unconfigured (no export dir, no event buffering)."""
    telemetry.reset()
    telemetry.configure(dir=None)
    yield
    telemetry.reset()
    telemetry.configure(dir=None)


def _small_job(**compute_kw):
    from spark_examples_tpu.core.config import (
        ComputeConfig, IngestConfig, JobConfig,
    )

    return JobConfig(
        ingest=IngestConfig(source="synthetic", n_samples=24,
                            n_variants=1024, block_variants=256, seed=1),
        compute=ComputeConfig(metric="ibs", num_pc=3,
                              eigh_mode="randomized", **compute_kw),
    )


# ---------------------------------------------------------------------------
# Histogram: log-bucket percentiles against numpy on known samples.


@pytest.mark.parametrize("dist", ["lognormal", "uniform", "exponential"])
def test_histogram_percentiles_match_numpy(dist):
    rng = np.random.default_rng(7)
    samples = {
        "lognormal": rng.lognormal(-5.0, 1.5, 5000),  # ~block times
        "uniform": rng.uniform(1e-4, 2e-1, 5000),
        "exponential": rng.exponential(3e-3, 5000),
    }[dist]
    h = Histogram()
    for s in samples:
        h.record(float(s))
    # Bucket geometry bounds the error: the quantile is read off the
    # geometric bucket midpoint, within sqrt(GROWTH)-1 (~4.4%) of the
    # true value; 6% leaves room for numpy's interpolation.
    tol = max(HIST_GROWTH ** 0.5 - 1.0, 0.044) + 0.016
    for q in (50, 95, 99):
        want = float(np.percentile(samples, q))
        got = h.quantile(q / 100.0)
        assert abs(got - want) / want < tol, (dist, q, got, want)
    assert h.count == len(samples)
    np.testing.assert_allclose(h.sum, samples.sum(), rtol=1e-9)


def test_histogram_exact_edges():
    h = Histogram()
    assert h.quantile(0.5) == 0.0  # empty
    h.record(0.123)
    # Single sample: min/max clamping makes every quantile exact.
    assert h.quantile(0.5) == pytest.approx(0.123)
    assert h.quantile(0.99) == pytest.approx(0.123)
    h2 = Histogram()
    for _ in range(100):
        h2.record(4.2e-3)
    assert h2.quantile(0.95) == pytest.approx(4.2e-3)
    h2.record(-1.0)  # nonpositive -> underflow bucket, no crash
    assert h2.min == -1.0


def test_counters_gauges_reset():
    assert telemetry.count("ingest.retries") == 1.0
    assert telemetry.count("ingest.retries", 2.0) == 3.0
    assert telemetry.counter_value("ingest.retries") == 3.0
    telemetry.gauge_set("prefetch.queue_depth", 2)
    telemetry.gauge_set("prefetch.queue_depth", 0)
    snap = telemetry.metrics_snapshot()
    g = snap["gauges"]["prefetch.queue_depth"]
    assert (g["last"], g["min"], g["max"], g["n"]) == (0.0, 0.0, 2.0, 2)
    telemetry.reset()
    assert telemetry.counter_value("ingest.retries") == 0.0
    assert "prefetch.queue_depth" not in telemetry.metrics_snapshot()["gauges"]


def test_unknown_name_warns_once_and_counts():
    with pytest.warns(RuntimeWarning, match="not declared"):
        telemetry.count("no.such.metric")
    # Second use: counted, no second warning.
    import warnings as _w

    with _w.catch_warnings():
        _w.simplefilter("error")
        telemetry.count("no.such.metric")
    assert telemetry.counter_value("telemetry.unknown_names") == 2.0


# ---------------------------------------------------------------------------
# Trace JSONL schema round-trip on a real (tiny) job.


def _run_traced_job(tmp_path, **compute_kw):
    from spark_examples_tpu.pipelines.jobs import pcoa_job

    telemetry.configure(dir=str(tmp_path / "tel"), trace_events=True)
    out = pcoa_job(_small_job(**compute_kw))
    d = telemetry.export()
    return out, d


def test_trace_jsonl_round_trip(tmp_path):
    out, d = _run_traced_job(tmp_path)
    lines = open(os.path.join(d, "trace.jsonl")).read().splitlines()
    events = [json.loads(line) for line in lines]  # every line parses
    spans = [e for e in events if e["ph"] == "X"]
    assert spans, "no span events recorded"
    for e in spans:
        assert set(e) >= {"name", "cat", "ph", "ts", "dur", "pid", "tid",
                          "args"}, e
        assert e["pid"] == 0
        assert e["dur"] >= 0
    names = {e["name"] for e in spans}
    assert "gram.block" in names
    assert "phase.gram" in names and "phase.eigh" in names
    # The per-block spans carry their attrs.
    blocks = [e for e in spans if e["name"] == "gram.block"]
    assert len(blocks) == 4  # 1024 variants / 256 per block
    assert [b["args"]["index"] for b in blocks] == [1, 2, 3, 4]

    # Monotonic ordering per rank: the exporter sorts by ts.
    ts = [e["ts"] for e in events if e["ph"] in ("X", "i")]
    assert all(a <= b for a, b in zip(ts, ts[1:]))

    # Spans nest: per tid, intervals are properly contained or disjoint
    # (strict LIFO context managers can't produce partial overlap).
    EPS = 0.5  # microseconds of float slack
    by_tid: dict = {}
    for e in spans:
        by_tid.setdefault(e["tid"], []).append(e)
    for evs in by_tid.values():
        evs.sort(key=lambda e: (e["ts"], -e["dur"]))
        stack: list[float] = []
        for e in evs:
            while stack and e["ts"] >= stack[-1] - EPS:
                stack.pop()
            if stack:
                assert e["ts"] + e["dur"] <= stack[-1] + EPS, (
                    "partial overlap within a thread", e)
            stack.append(e["ts"] + e["dur"])


def test_metrics_json_agrees_with_phase_timer(tmp_path):
    out, d = _run_traced_job(tmp_path)
    m = json.load(open(os.path.join(d, "metrics.json")))
    rep = out.timer.report()
    for key in ("gram_gflops_per_s", "ingest_mb_per_s", "eigh_gflops_per_s"):
        assert key in m["derived"], (key, m["derived"])
        assert m["derived"][key] == pytest.approx(rep[key], rel=0.01)
    # Registry subsumes PhaseTimer.counters.
    for cname, value in out.timer.counters.items():
        assert m["counters"][cname] == pytest.approx(value)
    # Prefetch instrumentation fired.
    assert m["histograms"]["prefetch.get_wait_s"]["count"] >= 4
    assert m["gauges"]["prefetch.queue_depth"]["n"] >= 4
    # rank-0 summary table exists and names the rank.
    summary = open(os.path.join(os.path.dirname(d), "summary.txt")).read()
    assert "gram_gflops" in summary and "\n0\t" in summary


def test_no_trace_events_mode_keeps_metrics(tmp_path):
    from spark_examples_tpu.pipelines.jobs import pcoa_job

    telemetry.configure(dir=str(tmp_path / "tel"), trace_events=False)
    pcoa_job(_small_job())
    d = telemetry.export()
    events = [json.loads(line)
              for line in open(os.path.join(d, "trace.jsonl"))]
    assert all(e["ph"] == "M" for e in events)  # metadata only
    m = json.load(open(os.path.join(d, "metrics.json")))
    assert m["histograms"]["gram.block"]["count"] == 4  # spans still measured
    assert "gram_gflops_per_s" in m["derived"]


def test_digest_shape():
    from spark_examples_tpu.pipelines.jobs import pcoa_job

    pcoa_job(_small_job())
    dig = telemetry.digest()
    assert dig["blocks"] == 4
    assert dig["block_p95_s"] >= dig["block_p50_s"] > 0
    assert 0.0 <= dig["prefetch_stall_frac"] <= 1.0
    assert dig["ingest_retries"] == 0
    assert dig["consensus_wait_p95_s"] == 0.0  # single process


# ---------------------------------------------------------------------------
# Satellite: hard_sync per-shard fallback — counter + resettable
# warn-once (the old module-global latch was untestable and invisible
# after the first warning).


def test_hard_sync_fallback_counts_and_rearms(monkeypatch):
    import jax

    from spark_examples_tpu.core import profiling

    def boom(leaf):
        raise RuntimeError("injected checksum failure")

    monkeypatch.setattr(profiling, "_leaf_sum", boom)
    x = jax.numpy.arange(8.0)
    with pytest.warns(RuntimeWarning, match="falling back"):
        profiling.hard_sync(x)
    assert telemetry.counter_value("hard_sync.fallback") == 1.0
    # Second occurrence: counted, NOT re-warned.
    import warnings as _w

    with _w.catch_warnings():
        _w.simplefilter("error")
        profiling.hard_sync(x)
    assert telemetry.counter_value("hard_sync.fallback") == 2.0
    # reset() re-arms the warning — the latch is now testable state.
    telemetry.reset()
    with pytest.warns(RuntimeWarning, match="falling back"):
        profiling.hard_sync(x)
    assert telemetry.counter_value("hard_sync.fallback") == 1.0


# ---------------------------------------------------------------------------
# Satellite: retry incidents surface in run output.


def test_retry_incidents_surface_in_timer_report(tmp_path):
    from spark_examples_tpu.core.profiling import PhaseTimer
    from spark_examples_tpu.ingest.packed import load_packed, pack_source
    from spark_examples_tpu.ingest.resilient import RetryingSource, RetryPolicy
    from spark_examples_tpu.ingest.synthetic import SyntheticSource

    store = str(tmp_path / "store")
    pack_source(store, SyntheticSource(n_samples=8, n_variants=256, seed=3),
                64)
    src = RetryingSource(
        load_packed(store),
        policy=RetryPolicy(max_retries=3, backoff_s=0.001),
        reopen=lambda: load_packed(store),
    )
    timer = PhaseTimer()
    with faults.armed(["ingest.block_read:io_error:after=1:max=2"]):
        with timer.phase("gram"):
            blocks = [b for b, _ in src.blocks(64)]
    assert len(blocks) == 4  # stream completed despite the faults
    assert telemetry.counter_value("ingest.retries") == 2.0
    assert telemetry.counter_value("ingest.reopens") == 2.0
    assert telemetry.counter_value("faults.fired") == 2.0
    rep = timer.report()
    # The silently-retrying run is distinguishable from a clean one.
    assert rep["ingest_retries"] == 2.0
    assert rep["ingest_reopens"] == 2.0
    assert "ingest_corrupt_blocks" not in rep  # zero stays silent

    # A timer constructed AFTER those incidents must not inherit them:
    # incidents are reported as deltas against the construction-time
    # snapshot, not as process-lifetime totals.
    fresh = PhaseTimer()
    with fresh.phase("gram"):
        list(src.blocks(64))
    assert "ingest_retries" not in fresh.report()

    telemetry.reset()
    with timer.phase("gram"):
        list(src.blocks(64))
    assert "ingest_retries" not in timer.report()  # clean run, clean report


# ---------------------------------------------------------------------------
# 2-process: one file set per rank, nonzero consensus-wait histogram.


_TELEMETRY_WORKER = r"""
import json, os
import numpy as np

from spark_examples_tpu.core.virtual import force_virtual_cpu
force_virtual_cpu(2)

import jax

from spark_examples_tpu.core import telemetry
from spark_examples_tpu.core.config import (
    ComputeConfig, IngestConfig, JobConfig,
)
from spark_examples_tpu.pipelines.jobs import pcoa_job
from spark_examples_tpu.pipelines.runner import build_source

telemetry.configure(dir=os.environ["TDIR"], trace_events=True)
job = JobConfig(
    ingest=IngestConfig(source="synthetic", n_samples=24, n_variants=1280,
                        block_variants=256, seed=5),
    compute=ComputeConfig(gram_mode="variant", eigh_mode="randomized",
                          num_pc=3, metric="ibs"),
)
src = build_source(job.ingest)
assert jax.process_count() == 2
out = pcoa_job(job, source=src)
d = telemetry.export()
m = json.load(open(os.path.join(d, "metrics.json")))
wait = m["histograms"].get("multihost.consensus", {"count": 0})
print(json.dumps({
    "process": jax.process_index(),
    "dir": d,
    "consensus_count": wait.get("count", 0),
    "consensus_sum": wait.get("sum", 0.0),
    "blocks": m["histograms"]["gram.block"]["count"],
}))
"""


def test_two_process_per_rank_export_and_consensus_wait(tmp_path):
    from test_distributed import _run_two_process

    tdir = str(tmp_path / "tel")
    outs = _run_two_process(_TELEMETRY_WORKER, extra_env={"TDIR": tdir})
    assert {o["process"] for o in outs} == {0, 1}
    for o in outs:
        rank_dir = os.path.join(tdir, f"rank{o['process']}")
        assert o["dir"] == rank_dir
        # One file set per rank.
        assert os.path.exists(os.path.join(rank_dir, "trace.jsonl"))
        assert os.path.exists(os.path.join(rank_dir, "metrics.json"))
        # The consensus-wait histogram is nonzero: at least the upfront
        # step-count round and the terminal contract round.
        assert o["consensus_count"] >= 2, o
        assert o["consensus_sum"] > 0.0, o
    # 1280 variants / 256 -> 3 consensus steps; the rank with the
    # 512-variant share streams 2 REAL blocks and pads its 3rd step —
    # padding must NOT count as a gram.block sample.
    assert sorted(o["blocks"] for o in outs) == [2, 3], outs
    for o in outs:
        rank_dir = os.path.join(tdir, f"rank{o['process']}")
        events = [json.loads(line)
                  for line in open(os.path.join(rank_dir, "trace.jsonl"))]
        span_names = {e["name"] for e in events if e["ph"] == "X"}
        assert "multihost.consensus" in span_names
        assert all(e["pid"] == o["process"] for e in events)
    # rank 0 wrote the merged summary (best-effort peer merge).
    assert os.path.exists(os.path.join(tdir, "summary.txt"))

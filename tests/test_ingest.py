import numpy as np
import pytest

from spark_examples_tpu.core.config import ReferenceRange
from spark_examples_tpu.ingest import (
    ArraySource,
    ChainSource,
    SyntheticSource,
    VcfSource,
    load_packed,
    partition_ranges,
    save_packed,
    write_vcf,
)
from spark_examples_tpu.ingest.prefetch import pad_block, stream_to_device
from spark_examples_tpu.ingest.vcf import _dosage
from tests.conftest import random_genotypes


def _materialize(source, block_variants, start=0):
    blocks = [b for b, _ in source.blocks(block_variants, start)]
    return np.concatenate(blocks, axis=1) if blocks else None


def test_array_source_roundtrip(genotypes):
    src = ArraySource(genotypes)
    out = _materialize(src, 64)
    np.testing.assert_array_equal(out, genotypes)
    assert src.n_samples == genotypes.shape[0]
    assert len(src.sample_ids) == src.n_samples


def test_synthetic_block_size_invariance():
    src = SyntheticSource(n_samples=20, n_variants=3000, seed=7)
    a = _materialize(src, 512)
    b = _materialize(src, 1536)
    np.testing.assert_array_equal(a, b)
    assert a.shape == (20, 3000)
    assert a.min() >= -1 and a.max() <= 2


def test_synthetic_has_population_structure():
    src = SyntheticSource(n_samples=60, n_variants=4000, n_populations=2,
                          fst=0.3, seed=3)
    g = _materialize(src, 4000).astype(float)
    g[g < 0] = np.nan
    # mean dosage per variant differs between the two planted populations
    pops = src.populations
    d = np.nanmean(g[pops == 0], 0) - np.nanmean(g[pops == 1], 0)
    assert np.nanstd(d) > 0.2  # visible drift


def test_synthetic_resume_matches(genotypes):
    src = SyntheticSource(n_samples=10, n_variants=2048, seed=1)
    full = [m.start for _, m in src.blocks(256)]
    resumed = [m.start for _, m in src.blocks(256, start_variant=1024)]
    assert resumed == full[4:]
    b_full = list(src.blocks(256))[4][0]
    b_res = next(iter(src.blocks(256, start_variant=1024)))[0]
    np.testing.assert_array_equal(b_full, b_res)


def test_vcf_roundtrip(tmp_path, genotypes):
    path = str(tmp_path / "toy.vcf")
    write_vcf(path, genotypes)
    src = VcfSource(path)
    assert src.n_samples == genotypes.shape[0]
    assert src.n_variants == genotypes.shape[1]
    out = _materialize(src, 50)
    np.testing.assert_array_equal(out, genotypes)


def test_vcf_gz_and_region_filter(tmp_path, genotypes):
    path = str(tmp_path / "toy.vcf.gz")
    write_vcf(path, genotypes, contig="chr1", start_pos=100)
    v = genotypes.shape[1]
    src = VcfSource(path, references=[ReferenceRange("chr1", 100, 100 + v // 2)])
    out = _materialize(src, 32)
    np.testing.assert_array_equal(out, genotypes[:, : v // 2])


def test_vcf_blocks_never_span_contigs(tmp_path, rng):
    """A block straddling a contig boundary would mislabel variants."""
    g1 = random_genotypes(rng, 6, 10, 0.0)
    g2 = random_genotypes(rng, 6, 10, 0.0)
    p1, p2 = str(tmp_path / "a.vcf"), str(tmp_path / "b.vcf")
    write_vcf(p1, g1, contig="chr1", start_pos=100)
    write_vcf(p2, g2, contig="chr2", start_pos=100)
    # concatenate records into one multi-contig VCF
    lines1 = [l for l in open(p1) if not l.startswith("#")]
    lines2 = [l for l in open(p2) if not l.startswith("#")]
    header = [l for l in open(p1) if l.startswith("#")]
    multi = str(tmp_path / "multi.vcf")
    open(multi, "w").writelines(header + lines1 + lines2)

    src = VcfSource(multi)
    blocks = list(src.blocks(8))  # 8 does not divide 10: blocks would span
    # boundary flush: block starts/stops partition [0,20) without mixing
    contigs = [m.contig for _b, m in blocks]
    assert contigs == ["chr1", "chr1", "chr2", "chr2"]
    spans = [(m.start, m.stop) for _b, m in blocks]
    assert spans == [(0, 8), (8, 10), (10, 18), (18, 20)]
    out = np.concatenate([b for b, _ in blocks], axis=1)
    np.testing.assert_array_equal(out, np.concatenate([g1, g2], axis=1))
    # record-ordinal resume from an unaligned cursor
    resumed = list(src.blocks(8, start_variant=10))
    assert [m.start for _b, m in resumed] == [10, 18]


def test_checkpoint_survives_crash_window(tmp_path):
    """If the new checkpoint never lands, the .old one must load."""
    import os, shutil

    from spark_examples_tpu.core import checkpoint as ckpt

    ids = [f"s{i}" for i in range(4)]
    path = str(tmp_path / "c")
    acc0 = {k: np.ones((4, 4)) for k in ("cc", "yc", "t1t1", "t2t2")}
    ckpt.save(path, acc0, 64, "ibs", 64, ids)
    # simulate the crash window: old moved aside, new never landed
    os.replace(path, path + ".old")
    acc, cursor, _stats = ckpt.load(path, "ibs", ids, block_variants=64)
    assert cursor == 64
    np.testing.assert_array_equal(np.asarray(acc["cc"]), np.ones((4, 4)))


@pytest.mark.parametrize(
    "gt,want",
    [("0/0", 0), ("0|1", 1), ("1/1", 2), ("./.", -1), (".", -1),
     ("1/.", 1), ("2|1", 2), ("1/2", 2), ("0/2", 1), ("0", 0), ("1", 1)],
)
def test_dosage_semantics(gt, want):
    assert _dosage(gt) == want


def test_packed_roundtrip(tmp_path, genotypes):
    p = str(tmp_path / "packed")
    save_packed(p, genotypes, sample_ids=[f"x{i}" for i in range(genotypes.shape[0])])
    src = load_packed(p)
    np.testing.assert_array_equal(_materialize(src, 33), genotypes)
    assert src.sample_ids[0] == "x0"


def test_chain_source(genotypes):
    a = ArraySource(genotypes[:, :100])
    b = ArraySource(genotypes[:, 100:])
    chain = ChainSource([a, b])
    assert chain.n_variants == genotypes.shape[1]
    np.testing.assert_array_equal(_materialize(chain, 64), genotypes)


def test_partition_ranges():
    ranges = partition_ranges([ReferenceRange("chr1", 0, 1000)], 4)
    assert len(ranges) == 4
    assert ranges[0].start == 0 and ranges[-1].end == 1000
    spans = [(r.end - r.start) for r in ranges]
    assert sum(spans) == 1000


def test_resume_cursor_inside_partial_final_block(genotypes):
    """A cursor at the end of a ragged final block must not re-emit it."""
    g = genotypes[:, :150]  # not a multiple of 64: final block is [128,150)
    src = ArraySource(g)
    metas = [m for _, m in src.blocks(64)]
    assert metas[-1].stop == 150
    assert list(src.blocks(64, start_variant=150)) == []
    # aligned cursor resumes at the partial block exactly once
    resumed = [m.start for _, m in src.blocks(64, start_variant=128)]
    assert resumed == [128]


def test_pad_block_is_missing(genotypes):
    padded = pad_block(genotypes[:, :10], 16)
    assert padded.shape == (genotypes.shape[0], 16)
    assert (padded[:, 10:] == -1).all()


def test_stream_to_device_pads_and_orders(genotypes):
    src = ArraySource(genotypes)
    blocks = list(stream_to_device(src, 64))
    assert all(b.shape == (genotypes.shape[0], 64) for b, _ in blocks)
    assert [m.index for _, m in blocks] == list(range(len(blocks)))
    # padding with MISSING leaves gram counts unchanged
    from spark_examples_tpu.ops import gram

    acc = gram.init(genotypes.shape[0], "ibs")
    for b, _ in blocks:
        acc = gram.update(acc, b, "ibs")
    from spark_examples_tpu.ops.genotype import gram_pieces

    stats = gram.combine(acc, "ibs")
    whole = gram_pieces(genotypes)
    np.testing.assert_array_equal(np.asarray(stats["m"]), np.asarray(whole["m"]))
    np.testing.assert_array_equal(np.asarray(stats["d1"]), np.asarray(whole["d1"]))


def test_stream_to_device_propagates_errors():
    class Bad:
        n_samples = 3
        n_variants = 10
        sample_ids = ["a", "b", "c"]

        def blocks(self, bv, start_variant=0):
            yield np.zeros((3, bv), np.int8), None
            raise RuntimeError("boom")

    it = stream_to_device(Bad(), 4)
    next(it)
    with pytest.raises(RuntimeError, match="boom"):
        list(it)


def test_partitioned_source_matches_chain(genotypes):
    """Concurrent partitioned reads emit the exact sequential stream."""
    from spark_examples_tpu.ingest.partitioned import PartitionedSource

    parts = lambda: [  # noqa: E731
        ArraySource(genotypes[:, :70]),
        ArraySource(genotypes[:, 70:95]),
        ArraySource(genotypes[:, 95:]),
    ]
    chain = ChainSource(parts())
    par = PartitionedSource(parts(), max_workers=2, buffer_blocks=2)
    assert par.n_variants == chain.n_variants
    got = list(par.blocks(32))
    want = list(chain.blocks(32))
    assert len(got) == len(want)
    for (gb, gm), (wb, wm) in zip(got, want):
        np.testing.assert_array_equal(gb, wb)
        assert (gm.index, gm.start, gm.stop) == (wm.index, wm.start, wm.stop)


def test_partitioned_source_resume_mid_stream(genotypes):
    from spark_examples_tpu.ingest.partitioned import PartitionedSource

    parts = lambda: [  # noqa: E731
        ArraySource(genotypes[:, :64]),
        ArraySource(genotypes[:, 64:128]),
        ArraySource(genotypes[:, 128:]),
    ]
    par = PartitionedSource(parts(), max_workers=3)
    full = list(par.blocks(32))
    for cursor in (32, 64, 96, 128, 160):
        resumed = list(PartitionedSource(parts()).blocks(32, cursor))
        want = [(b, m) for b, m in full if m.start >= cursor]
        assert len(resumed) == len(want), cursor
        for (gb, gm), (wb, wm) in zip(resumed, want):
            np.testing.assert_array_equal(gb, wb)
            assert (gm.start, gm.stop) == (wm.start, wm.stop)
    # cursor at/past the end yields nothing
    total = genotypes.shape[1]
    assert list(PartitionedSource(parts()).blocks(32, total)) == []


def test_partitioned_source_propagates_reader_errors(genotypes):
    from spark_examples_tpu.ingest.partitioned import PartitionedSource

    class Broken:
        n_samples = genotypes.shape[0]
        n_variants = 10
        sample_ids = [f"S{i}" for i in range(genotypes.shape[0])]

        def blocks(self, bv, start=0):
            raise RuntimeError("disk on fire")
            yield  # pragma: no cover

    par = PartitionedSource([ArraySource(genotypes[:, :64]), Broken()])
    with pytest.raises(RuntimeError, match="disk on fire"):
        list(par.blocks(32))


def test_partitioned_vcf_pipeline_parity(tmp_path, genotypes):
    """--splits-per-contig routes through PartitionedSource and produces
    the same similarity matrix as the unsplit ingest."""
    from spark_examples_tpu.core.config import (
        ComputeConfig,
        IngestConfig,
        JobConfig,
    )
    from spark_examples_tpu.ingest.vcf import write_vcf
    from spark_examples_tpu.pipelines import runner

    path = str(tmp_path / "c.vcf")
    write_vcf(path, genotypes, contig="chr1", start_pos=1000)
    base = dict(source="vcf", path=path,
                references=[ReferenceRange("chr1", 0, 10_000)],
                block_variants=64)
    r_seq = runner.run_similarity(JobConfig(
        ingest=IngestConfig(**base), compute=ComputeConfig(metric="ibs")))
    r_par = runner.run_similarity(JobConfig(
        ingest=IngestConfig(**base, splits_per_contig=3, ingest_workers=2),
        compute=ComputeConfig(metric="ibs")))
    np.testing.assert_array_equal(r_seq.similarity, r_par.similarity)
    assert r_seq.n_variants == r_par.n_variants


def test_parquet_roundtrip(tmp_path, genotypes):
    """Wide parquet variant table (the BigQuery-export stand-in) round-
    trips exactly, streams in steady blocks, resumes mid-stream, and
    reports an exact length from file metadata alone."""
    from spark_examples_tpu.ingest.parquet import ParquetSource, write_parquet

    path = str(tmp_path / "cohort.parquet")
    write_parquet(path, genotypes, row_group_rows=64)
    src = ParquetSource(path)
    n, v = genotypes.shape
    assert src.n_samples == n
    assert src.exact_n_variants
    assert src.n_variants == v
    got = np.concatenate([b for b, _ in src.blocks(50)], axis=1)
    np.testing.assert_array_equal(got, genotypes)
    metas = [m for _, m in src.blocks(50)]
    assert [m.start for m in metas] == list(range(0, v, 50))
    assert metas[0].contig == "chr22"
    assert metas[0].positions is not None
    # Resume from a produced cursor.
    tail = np.concatenate([b for b, _ in src.blocks(50, metas[1].stop)], axis=1)
    np.testing.assert_array_equal(tail, genotypes[:, metas[1].stop:])


def test_parquet_region_filter_and_job(tmp_path, rng):
    from spark_examples_tpu.core.config import (
        ComputeConfig, IngestConfig, JobConfig,
    )
    from spark_examples_tpu.ingest.parquet import ParquetSource, write_parquet
    from spark_examples_tpu.ingest.source import ArraySource
    from spark_examples_tpu.pipelines.jobs import pcoa_job

    g = random_genotypes(rng, n=12, v=300, missing_rate=0.05)
    path = str(tmp_path / "cohort.parquet")
    write_parquet(path, g, contig="chr1", start_pos=100, row_group_rows=128)
    half = ParquetSource(
        path, references=[ReferenceRange("chr1", 100, 100 + 150)],
    )
    assert not half.exact_n_variants  # filtered: count needs a scan
    assert half.n_variants == 150
    got = np.concatenate([b for b, _ in half.blocks(64)], axis=1)
    np.testing.assert_array_equal(got, g[:, :150])

    # The job surface accepts source="parquet" end to end.
    job = JobConfig(
        ingest=IngestConfig(source="parquet", path=path, block_variants=64),
        compute=ComputeConfig(metric="ibs", num_pc=3),
    )
    out = pcoa_job(job)
    want = pcoa_job(
        JobConfig(ingest=IngestConfig(block_variants=64),
                  compute=ComputeConfig(metric="ibs", num_pc=3)),
        source=ArraySource(g),
    )
    np.testing.assert_allclose(
        np.abs(out.coords), np.abs(want.coords), atol=1e-4
    )


def test_parquet_multi_contig_blocks_never_span(tmp_path, rng):
    import pyarrow as pa
    import pyarrow.parquet as pq

    from spark_examples_tpu.ingest.parquet import ParquetSource

    g = random_genotypes(rng, n=6, v=100, missing_rate=0.0)
    contigs = ["chr1"] * 37 + ["chr2"] * 63
    cols = {"contig": pa.array(contigs),
            "position": pa.array(np.arange(100, dtype=np.int64))}
    for i in range(6):
        cols[f"S{i}"] = pa.array(np.asarray(g[i], np.int8))
    pq.write_table(pa.table(cols), str(tmp_path / "mc.parquet"),
                   row_group_size=40)
    src = ParquetSource(str(tmp_path / "mc.parquet"))
    # Multi-contig: dense blocks flush at the chr1/chr2 boundary, so the
    # steady ceil-count contract cannot be claimed.
    assert not src.exact_n_variants
    blocks = list(src.blocks(25))
    for _, m in blocks:
        assert m.contig in ("chr1", "chr2")
    # The chr1/chr2 boundary at 37 forces a partial flush there.
    stops = [m.stop for _, m in blocks]
    assert 37 in stops
    got = np.concatenate([b for b, _ in blocks], axis=1)
    np.testing.assert_array_equal(got, g)


def test_packed_store_exactness_claim(tmp_path, genotypes):
    """The exact_n_variants contract (steady ceil-count blocks on BOTH
    transports): single-run stores claim it, multi-contig stores must
    decline — their dense blocks flush at each chromosome run, so the
    multi-host feeder cannot precompute their step count."""
    from spark_examples_tpu.ingest.packed import Packed2BitSource, save_packed

    path = str(tmp_path / "store")
    save_packed(path, genotypes)
    from spark_examples_tpu.ingest.packed import load_packed

    single = load_packed(path)
    assert single.exact_n_variants
    multi = Packed2BitSource(
        packed=single.packed, v=single.v,
        contig_runs=[("chr1", 0), ("chr2", 100)],
    )
    assert not multi.exact_n_variants
    # And the feeder helper honors the declination.
    from spark_examples_tpu.parallel.multihost import _exact_local_steps

    assert _exact_local_steps(multi, 64, 0) == -1
    assert _exact_local_steps(single, 64, 0) == -(-single.v // 64)


def test_parquet_schema_errors(tmp_path, rng):
    """Malformed tables fail loudly with the defect named."""
    import pyarrow as pa
    import pyarrow.parquet as pq

    from spark_examples_tpu.ingest.parquet import ParquetSource

    # Metadata-only table: no sample columns.
    meta_only = str(tmp_path / "meta.parquet")
    pq.write_table(pa.table({
        "contig": pa.array(["chr1"] * 4),
        "position": pa.array(np.arange(4, dtype=np.int64)),
    }), meta_only)
    with pytest.raises(ValueError, match="no sample columns"):
        ParquetSource(meta_only).sample_ids

    # Range filtering without contig/position columns.
    from spark_examples_tpu.ingest.parquet import write_parquet

    g = random_genotypes(rng, n=4, v=16, missing_rate=0.0)
    bare = str(tmp_path / "bare.parquet")
    write_parquet(bare, g, contig=None)
    src = ParquetSource(bare,
                        references=[ReferenceRange("chr1", 0, 10)])
    with pytest.raises(ValueError, match="filtering needs"):
        list(src.blocks(8))
    # Without a filter the bare table streams fine (contig-less).
    got = np.concatenate(
        [b for b, _ in ParquetSource(bare).blocks(8)], axis=1
    )
    np.testing.assert_array_equal(got, g)


def test_packed_sidecar_schema_version(tmp_path, genotypes):
    """save_packed stamps the sidecar; load_packed mirrors load_model's
    ModelFormatError treatment — pre-versioning, future, truncated, and
    field-missing sidecars all get a PackedFormatError naming the cause
    (a long-lived job must be able to diagnose a bad store dir from the
    exception alone)."""
    import json
    import os

    from spark_examples_tpu.ingest.packed import (
        PACKED_SCHEMA_VERSION,
        PackedFormatError,
        save_packed,
    )

    path = str(tmp_path / "store")
    save_packed(path, genotypes)
    meta_path = os.path.join(path, "meta.json")
    meta = json.load(open(meta_path))
    assert meta["schema_version"] == PACKED_SCHEMA_VERSION
    load_packed(path)  # current version loads

    # pre-versioning (retroactively version 1) -> re-pack to upgrade
    legacy = dict(meta)
    del legacy["schema_version"]
    json.dump(legacy, open(meta_path, "w"))
    with pytest.raises(PackedFormatError, match="pre-versioning"):
        load_packed(path)

    # a NEWER build's store must not be guessed at
    future = dict(meta, schema_version=PACKED_SCHEMA_VERSION + 1)
    json.dump(future, open(meta_path, "w"))
    with pytest.raises(PackedFormatError, match="newer than this build"):
        load_packed(path)

    # missing required field, named
    broken = dict(meta)
    del broken["n_variants"]
    json.dump(broken, open(meta_path, "w"))
    with pytest.raises(PackedFormatError, match="n_variants"):
        load_packed(path)

    # truncated sidecar
    open(meta_path, "w").write(json.dumps(meta)[:20])
    with pytest.raises(PackedFormatError, match="unreadable"):
        load_packed(path)

    # not a store at all
    with pytest.raises(PackedFormatError, match="no meta.json"):
        load_packed(str(tmp_path / "nowhere"))

    # sidecar fine but the genotype payload is gone (interrupted pack)
    json.dump(meta, open(meta_path, "w"))
    os.remove(os.path.join(path, "genotypes.2bit.npy"))
    with pytest.raises(PackedFormatError, match="genotypes.2bit.npy"):
        load_packed(path)

"""KING-robust kinship (--metric king): matmul reformulation vs the
independent per-pair oracle, planted-relatedness recovery, and the
streaming/packed paths."""

import numpy as np
import pytest

from spark_examples_tpu.ingest.bitpack import pack_dosages
from spark_examples_tpu.ops import distances, gram
from spark_examples_tpu.utils import oracle
from tests.conftest import random_genotypes


def _phi(g):
    acc = gram.update(gram.init(g.shape[0], "king"), g, "king")
    return np.asarray(distances.finalize(acc, "king")["similarity"])


def test_king_matches_naive_oracle(rng):
    g = random_genotypes(rng, n=18, v=600, missing_rate=0.15)
    np.testing.assert_allclose(_phi(g), oracle.naive_king(g), atol=1e-6)


def test_king_diagonal_is_half(rng):
    g = random_genotypes(rng, n=10, v=400, missing_rate=0.05)
    # sample 0: fully homozygous (inbred-line / haploid 0-2 coding) —
    # its zero het count must NOT demote self-kinship to "unrelated";
    # a nonzero self-distance would poison downstream Gower centering
    g[0] = np.where(g[0] == 1, 2, g[0])
    phi = _phi(g)
    np.testing.assert_allclose(np.diagonal(phi), 0.5, atol=1e-7)
    acc = gram.update(gram.init(10, "king"), g, "king")
    d = np.asarray(distances.finalize(acc, "king")["distance"])
    np.testing.assert_allclose(np.diagonal(d), 0.0, atol=1e-7)


def test_king_recovers_planted_relatedness(rng):
    """Duplicate (MZ-twin analog) ~0.5; parent-child ~0.25; unrelated
    ~0, on allele-level simulated genotypes."""
    v = 20_000
    p = rng.uniform(0.2, 0.8, v)
    # unrelated founders as explicit allele pairs
    a = (rng.random((4, v)) < p).astype(np.int8)
    b = (rng.random((4, v)) < p).astype(np.int8)
    founders = a + b
    # child of founders 0 and 1: one transmitted allele from each
    child = (
        np.where(rng.random(v) < 0.5, a[0], b[0])
        + np.where(rng.random(v) < 0.5, a[1], b[1])
    ).astype(np.int8)
    cohort = np.concatenate(
        [founders, child[None, :], founders[0:1].copy()], axis=0
    )  # rows: f0 f1 f2 f3 child dup(f0)
    phi = _phi(cohort)
    assert abs(phi[0, 5] - 0.5) < 0.02   # duplicate pair
    assert abs(phi[0, 4] - 0.25) < 0.03  # parent-child
    assert abs(phi[4, 1] - 0.25) < 0.03  # other parent
    assert abs(phi[2, 3]) < 0.03         # unrelated founders
    assert abs(phi[0, 2]) < 0.03


def test_king_streaming_and_packed_match_single_block(rng):
    g = random_genotypes(rng, n=12, v=512, missing_rate=0.1)
    whole = _phi(g)
    acc = gram.init(12, "king")
    for s in range(0, 512, 128):
        acc = gram.update(acc, g[:, s : s + 128], "king")
    np.testing.assert_allclose(
        np.asarray(distances.finalize(acc, "king")["similarity"]),
        whole, atol=1e-7,
    )
    pacc = gram.update_packed(
        gram.init(12, "king"), pack_dosages(g), "king"
    )
    np.testing.assert_allclose(
        np.asarray(distances.finalize(pacc, "king")["similarity"]),
        whole, atol=1e-7,
    )


def test_king_pipeline_job(rng, tmp_path):
    """similarity job surface with --metric king writes the phi matrix."""
    from spark_examples_tpu.core.config import (
        ComputeConfig, IngestConfig, JobConfig,
    )
    from spark_examples_tpu.ingest.source import ArraySource
    from spark_examples_tpu.pipelines.runner import run_similarity

    g = random_genotypes(rng, n=14, v=300, missing_rate=0.1)
    job = JobConfig(
        ingest=IngestConfig(block_variants=64),
        compute=ComputeConfig(metric="king"),
    )
    res = run_similarity(job, source=ArraySource(g))
    np.testing.assert_allclose(
        res.similarity, oracle.naive_king(g), atol=1e-6
    )
    assert res.metric == "king"


def test_cross_kinship_matches_symmetric_blocks(rng):
    """The cross-cohort phi between cohorts A and B must equal the
    off-diagonal block of the symmetric KING matrix over [A; B]."""
    from spark_examples_tpu.core.config import IngestConfig, JobConfig
    from spark_examples_tpu.ingest.source import ArraySource
    from spark_examples_tpu.pipelines.project import cross_kinship_job

    g = random_genotypes(rng, n=20, v=600, missing_rate=0.1)
    a, b = g[:8], g[8:]
    job = JobConfig(ingest=IngestConfig(block_variants=128))
    res = cross_kinship_job(job, source_new=ArraySource(a),
                            source_ref=ArraySource(b))
    full = oracle.naive_king(g)
    np.testing.assert_allclose(res.similarity, full[:8, 8:], atol=1e-6)


def test_cross_kinship_finds_planted_duplicates_and_relatives(rng):
    from spark_examples_tpu.core.config import IngestConfig, JobConfig
    from spark_examples_tpu.ingest.source import ArraySource
    from spark_examples_tpu.pipelines.project import cross_kinship_job

    v = 20_000
    p = rng.uniform(0.2, 0.8, v)
    al = (rng.random((6, v)) < p).astype(np.int8)
    bl = (rng.random((6, v)) < p).astype(np.int8)
    panel = al + bl  # 6 founders
    child = (
        np.where(rng.random(v) < 0.5, al[0], bl[0])
        + np.where(rng.random(v) < 0.5, al[1], bl[1])
    ).astype(np.int8)
    new = np.stack([panel[2].copy(), child,
                    ((rng.random(v) < p).astype(np.int8)
                     + (rng.random(v) < p).astype(np.int8))])
    job = JobConfig(ingest=IngestConfig(block_variants=4096))
    res = cross_kinship_job(job, source_new=ArraySource(new),
                            source_ref=ArraySource(panel))
    phi = res.similarity
    assert abs(phi[0, 2] - 0.5) < 0.02   # duplicate of founder 2
    assert abs(phi[1, 0] - 0.25) < 0.03  # child-parent
    assert abs(phi[1, 1] - 0.25) < 0.03  # child-other-parent
    assert abs(phi[2, 3]) < 0.03         # unrelated new sample


def test_cross_matrix_rejected_by_square_reader(rng, tmp_path):
    """A persisted cross-cohort matrix must not flow into the square
    pcoa --matrix-path handoff (rows/columns index different cohorts)."""
    from spark_examples_tpu.pipelines import io as pio

    path = str(tmp_path / "x.tsv")
    pio.write_matrix(path, ["a", "b"], np.zeros((2, 3)),
                     kind="similarity", col_ids=["r0", "r1", "r2"])
    with pytest.raises(ValueError, match="rectangular"):
        pio.read_matrix(path)

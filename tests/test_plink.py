"""PLINK .bed/.bim/.fam ingest: round-trips, code semantics, chromosome
boundaries, resume, and the full pipeline over a fileset."""

import numpy as np
import pytest

from spark_examples_tpu.ingest.plink import PlinkSource, write_plink
from tests.conftest import random_genotypes


def _materialize(src, bv, start=0):
    blocks = [b for b, _ in src.blocks(bv, start)]
    return np.concatenate(blocks, axis=1) if blocks else None


@pytest.mark.parametrize("n", [4, 7, 13])  # exercise sample-axis padding
def test_plink_roundtrip(rng, tmp_path, n):
    g = random_genotypes(rng, n=n, v=101, missing_rate=0.2)
    prefix = str(tmp_path / "c")
    write_plink(prefix, g, sample_ids=[f"X{i}" for i in range(n)])
    src = PlinkSource(prefix)
    assert src.n_samples == n and src.n_variants == 101
    assert src.sample_ids[0] == "X0"
    np.testing.assert_array_equal(_materialize(src, 17), g)


def test_plink_accepts_bed_path(rng, tmp_path):
    g = random_genotypes(rng, n=5, v=8)
    prefix = str(tmp_path / "c")
    write_plink(prefix, g)
    np.testing.assert_array_equal(
        _materialize(PlinkSource(prefix + ".bed"), 8), g
    )


def test_plink_code_semantics(tmp_path):
    """Raw byte-level check against the PLINK spec: 00=A1/A1(2),
    01=missing, 10=het(1), 11=A2/A2(0), LSB pair first."""
    prefix = str(tmp_path / "c")
    with open(prefix + ".bed", "wb") as f:
        #                       s0=00 s1=01 s2=10 s3=11 -> one variant
        f.write(bytes([0x6C, 0x1B, 0x01, 0b11_10_01_00]))
    with open(prefix + ".fam", "w") as f:
        for i in range(4):
            f.write(f"F{i} S{i} 0 0 0 -9\n")
    with open(prefix + ".bim", "w") as f:
        f.write("1\trs0\t0\t100\tA\tC\n")
    out = _materialize(PlinkSource(prefix), 4)
    np.testing.assert_array_equal(out[:, 0], [2, -1, 1, 0])


def test_plink_rejects_bad_files(tmp_path, rng):
    bad = str(tmp_path / "bad")
    with open(bad + ".bed", "wb") as f:
        f.write(b"\x00\x00\x00")
    with pytest.raises(ValueError, match="bad magic"):
        PlinkSource(bad)
    short = str(tmp_path / "short")
    with open(short + ".bed", "wb") as f:
        f.write(bytes([0x6C, 0x1B]))  # magic only, truncated
    with pytest.raises(ValueError, match="bad magic"):
        PlinkSource(short)
    sm = str(tmp_path / "sm")
    with open(sm + ".bed", "wb") as f:
        f.write(bytes([0x6C, 0x1B, 0x00]))
    with pytest.raises(ValueError, match="sample-major"):
        PlinkSource(sm)


def test_plink_chromosome_boundary_flush(rng, tmp_path):
    """Blocks never span a chromosome; BlockMeta.contig is exact."""
    g = random_genotypes(rng, n=6, v=20)
    prefix = str(tmp_path / "c")
    write_plink(prefix, g, chroms=["1"] * 7 + ["2"] * 13)
    metas = [m for _, m in PlinkSource(prefix).blocks(5)]
    assert [(m.start, m.stop, m.contig) for m in metas] == [
        (0, 5, "1"), (5, 7, "1"), (7, 12, "2"), (12, 17, "2"), (17, 20, "2")
    ]
    np.testing.assert_array_equal(_materialize(PlinkSource(prefix), 5), g)


def test_plink_resume_matches(rng, tmp_path):
    g = random_genotypes(rng, n=5, v=64)
    prefix = str(tmp_path / "c")
    write_plink(prefix, g)
    src = PlinkSource(prefix)
    full = list(src.blocks(16))
    resumed = list(src.blocks(16, start_variant=full[2][1].stop))
    assert [m.start for _, m in resumed] == [m.start for _, m in full[3:]]
    np.testing.assert_array_equal(resumed[0][0], full[3][0])


def test_plink_resume_on_chromosome_irregular_grid(rng, tmp_path):
    """Chromosome flushes break the fixed block grid, so resume must
    compare actual block stops — a ceil(start/bv) block count would
    re-emit (double-accumulate) the flushed blocks."""
    g = random_genotypes(rng, n=4, v=2400)
    prefix = str(tmp_path / "c")
    write_plink(prefix, g, chroms=[str(1 + j // 600) for j in range(2400)])
    src = PlinkSource(prefix)
    full = list(src.blocks(1000))
    # blocks: (0,600),(600,1200),(1200,1800),(1800,2400)
    assert [m.stop for _, m in full] == [600, 1200, 1800, 2400]
    resumed = list(src.blocks(1000, start_variant=1800))
    assert [(m.start, m.stop) for _, m in resumed] == [(1800, 2400)]
    np.testing.assert_array_equal(resumed[0][0], full[3][0])


def test_plink_references_filter(rng, tmp_path):
    """--references chr:start:end semantics (VcfSource parity): only
    in-range variants stream; ordinals index the filtered stream."""
    from spark_examples_tpu.core.config import ReferenceRange

    g = random_genotypes(rng, n=5, v=30)
    prefix = str(tmp_path / "c")
    write_plink(prefix, g, chroms=["1"] * 15 + ["2"] * 15,
                positions=np.arange(100, 130))
    refs = (ReferenceRange("1", 105, 110),  # variants 5..9
            ReferenceRange("2", 120, 125))  # variants 20..24
    src = PlinkSource(prefix, references=refs)
    assert src.n_variants == 10
    blocks = list(src.blocks(4))
    out = np.concatenate([b for b, _ in blocks], axis=1)
    np.testing.assert_array_equal(
        out, np.concatenate([g[:, 5:10], g[:, 20:25]], axis=1)
    )
    # ordinals are filtered-stream ordinals; contigs stay exact
    assert [(m.start, m.stop, m.contig) for _, m in blocks] == [
        (0, 4, "1"), (4, 5, "1"), (5, 9, "2"), (9, 10, "2")
    ]
    assert list(blocks[1][1].positions) == [109]
    # resume over the filtered stream
    resumed = list(src.blocks(4, start_variant=5))
    np.testing.assert_array_equal(resumed[0][0], blocks[2][0])


def test_partitioned_plink_pipeline_parity(rng, tmp_path):
    """--splits-per-contig routes PLINK through PartitionedSource (the
    FixedContigSplits successor) and matches the unsplit ingest."""
    from spark_examples_tpu.core.config import (
        ComputeConfig, IngestConfig, JobConfig, ReferenceRange,
    )
    from spark_examples_tpu.pipelines import runner

    g = random_genotypes(rng, n=10, v=400, missing_rate=0.1)
    prefix = str(tmp_path / "c")
    write_plink(prefix, g, chroms=["1"] * 400,
                positions=np.arange(1000, 1400))
    base = dict(source="plink", path=prefix,
                references=[ReferenceRange("1", 0, 10_000)],
                block_variants=64)
    r_seq = runner.run_similarity(JobConfig(
        ingest=IngestConfig(**base), compute=ComputeConfig(metric="ibs")))
    r_par = runner.run_similarity(JobConfig(
        ingest=IngestConfig(**base, splits_per_contig=3, ingest_workers=2),
        compute=ComputeConfig(metric="ibs")))
    np.testing.assert_array_equal(r_seq.similarity, r_par.similarity)
    assert r_seq.n_variants == r_par.n_variants == 400


def test_plink_pcoa_pipeline(rng, tmp_path):
    """End to end: PLINK fileset -> packed transport -> IBS PCoA matches
    the same cohort ingested as a dense array."""
    from spark_examples_tpu.core.config import (
        ComputeConfig, IngestConfig, JobConfig,
    )
    from spark_examples_tpu.ingest.source import ArraySource
    from spark_examples_tpu.pipelines.jobs import pcoa_job

    g = random_genotypes(rng, n=24, v=300, missing_rate=0.1)
    prefix = str(tmp_path / "c")
    write_plink(prefix, g)
    job = JobConfig(
        ingest=IngestConfig(source="plink", path=prefix, block_variants=64),
        compute=ComputeConfig(metric="ibs", num_pc=4),
    )
    out = pcoa_job(job)
    ref = pcoa_job(job, source=ArraySource(g))
    np.testing.assert_allclose(
        np.abs(out.coords), np.abs(ref.coords), atol=1e-4
    )

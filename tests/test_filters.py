"""Variant QC stream filter (--maf / --max-missing): mask semantics,
re-chunking, contig boundaries, resume, and CLI wiring."""

import numpy as np
import pytest

from spark_examples_tpu.ingest.filters import FilteredSource, qc_mask
from spark_examples_tpu.ingest.source import ArraySource
from tests.conftest import random_genotypes


def _materialize(src, bv, start=0):
    blocks = [b for b, _ in src.blocks(bv, start)]
    return (np.concatenate(blocks, axis=1) if blocks
            else np.empty((src.n_samples, 0), np.int8))


def _expected(g, maf, max_missing):
    return g[:, qc_mask(g, maf, max_missing)]


def test_qc_mask_semantics():
    g = np.array([
        [0, 2, -1, 1, -1],
        [0, 2, -1, 1, 0],
        [0, 2, -1, 0, 0],
        [0, 2, -1, 0, 0],
    ], np.int8)
    # col0: p=0 (monomorphic ref); col1: p=1 (monomorphic alt);
    # col2: all missing; col3: p=0.25; col4: 1/4 missing, p=0
    keep = qc_mask(g, maf=0.05, max_missing=0.5)
    np.testing.assert_array_equal(keep, [False, False, False, True, False])
    keep = qc_mask(g, maf=0.0, max_missing=0.3)
    np.testing.assert_array_equal(keep, [True, True, False, True, True])


@pytest.mark.parametrize("bv", [16, 64, 256])
def test_filter_block_size_invariance(rng, bv):
    g = random_genotypes(rng, n=20, v=700, missing_rate=0.3)
    src = FilteredSource(ArraySource(g), maf=0.1, max_missing=0.25)
    out = _materialize(src, bv)
    np.testing.assert_array_equal(out, _expected(g, 0.1, 0.25))
    # ordinals are contiguous over the filtered stream
    metas = [m for _, m in src.blocks(bv)]
    assert metas[0].start == 0
    for a, b in zip(metas, metas[1:]):
        assert b.start == a.stop
    assert src.n_variants == out.shape[1]


def test_filter_preserves_contig_boundaries(rng, tmp_path):
    from spark_examples_tpu.ingest.plink import PlinkSource, write_plink

    g = random_genotypes(rng, n=8, v=60, missing_rate=0.2)
    prefix = str(tmp_path / "c")
    write_plink(prefix, g, chroms=["1"] * 25 + ["2"] * 35,
                positions=np.arange(60))
    src = FilteredSource(PlinkSource(prefix), max_missing=0.3)
    blocks = list(src.blocks(16))
    for b, m in blocks:
        assert m.contig in ("1", "2")
        assert b.shape[1] == m.stop - m.start
    # positions survive filtering and match the kept columns
    keep = qc_mask(g, 0.0, 0.3)
    kept_pos = np.arange(60)[keep]
    got_pos = np.concatenate([m.positions for _, m in blocks])
    np.testing.assert_array_equal(got_pos, kept_pos)
    np.testing.assert_array_equal(_materialize(src, 16), g[:, keep])


def test_filter_resume(rng):
    g = random_genotypes(rng, n=10, v=500, missing_rate=0.2)
    src = FilteredSource(ArraySource(g), maf=0.05)
    full = list(src.blocks(64))
    cursor = full[2][1].stop
    resumed = list(src.blocks(64, cursor))
    assert [m.start for _, m in resumed] == [m.start for _, m in full[3:]]
    np.testing.assert_array_equal(resumed[0][0], full[3][0])


def test_filter_pipeline_and_cli(rng, tmp_path, capsys):
    from spark_examples_tpu.cli.main import main
    from spark_examples_tpu.ingest.vcf import write_vcf

    g = random_genotypes(rng, n=15, v=400, missing_rate=0.3)
    path = str(tmp_path / "c.vcf")
    write_vcf(path, g)
    want = _expected(g, 0.1, 0.2)
    assert main(["similarity", "--source", "vcf", "--path", path,
                 "--maf", "0.1", "--max-missing", "0.2",
                 "--block-variants", "64"]) == 0
    cap = capsys.readouterr()
    assert f"over {want.shape[1]} variants" in cap.out
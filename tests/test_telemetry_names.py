"""Names lint (tier-1): every span/counter/gauge/histogram/event name
used at a telemetry call site in the codebase must be declared in the
canonical registry (core/telemetry.py NAMES) — a typo'd metric name
would otherwise silently fork a timeline into two series nobody ever
joins back together. The same contract covers fault sites: every
literal site string passed to ``faults.fire`` must be declared in
``faults.SITES`` — an undeclared site would be unarm-able from the env
grammar (FaultSpec rejects unknown sites), i.e. a recovery path the
chaos harness can never reach.

Since the graftlint PR these three lints run on the AST engine
(tools/graftlint rules ``telemetry-name`` / ``fault-site``) instead of
the original regex walkers: the AST rules additionally see through
import aliasing (``from ... import telemetry as t``), string
concatenation, and multi-line calls the regexes missed. Test names and
failure-message contracts are unchanged."""

import pathlib
import re

from spark_examples_tpu.core import faults, telemetry
from tools import graftlint

REPO = pathlib.Path(__file__).resolve().parent.parent


def _loc(finding) -> str:
    return f"{pathlib.PurePosixPath(finding.path).name}:{finding.line}"


def test_every_used_name_is_declared():
    undeclared = []
    fstring_sites = []
    for f in graftlint.run(rules=["telemetry-name"]):
        if f.rule != "telemetry-name":
            continue
        if f.data.get("dynamic"):
            # An f-string name can't be statically checked — the
            # registry's families + runtime check exist for dynamic
            # names; literal sites must stay literal.
            fstring_sites.append(f"{_loc(f)}: f-string name")
        else:
            undeclared.append(f"{_loc(f)}: {f.data['name']!r}")
    assert not undeclared, (
        "telemetry names used but not declared in telemetry.NAMES "
        "(add them to the canonical registry): " + "; ".join(undeclared)
    )
    assert not fstring_sites, (
        "telemetry call sites must pass literal names (use attrs for "
        "the dynamic part): " + "; ".join(fstring_sites)
    )


def test_every_fault_site_is_declared():
    """Every literal site fired in production code is in faults.SITES
    (and dynamic names are banned outright: a site must be a greppable
    constant for the harness's docs and specs to reference it)."""
    undeclared = []
    fstring_sites = []
    dead: set[str] = set()
    for f in graftlint.run(rules=["fault-site"]):
        if f.rule != "fault-site":
            continue
        if f.data.get("dead"):
            dead = set(f.data["dead"])
        elif f.data.get("dynamic"):
            fstring_sites.append(f"{_loc(f)}: f-string site")
        else:
            undeclared.append(f"{_loc(f)}: {f.data['site']!r}")
    assert not undeclared, (
        "fault sites fired but not declared in faults.SITES (declare "
        "them so specs can arm them): " + "; ".join(undeclared)
    )
    assert not fstring_sites, (
        "faults.fire sites must be literal strings: "
        + "; ".join(fstring_sites)
    )
    # The inverse direction: a declared site nothing fires is a dead
    # registry entry — the docs would promise an injection point the
    # harness can't hit (the rule's finalize pass, full-tree runs only).
    assert not dead, f"declared fault sites never fired in code: {dead}"


def test_every_fault_site_is_armed_by_a_test():
    """Every site in faults.SITES must be ARMED by at least one test —
    a spec string ``site:kind`` somewhere under tests/ (faults.armed or
    an env-armed subprocess). A site that is fired in production code
    but never armed in a test is a recovery path the chaos harness has
    never actually reached; it rots exactly like untested code because
    it IS untested code. Spec strings are collected from the tests'
    ASTs (every string constant, f-string fragments included) rather
    than regexed from raw text."""
    constants = graftlint.collect_string_constants([REPO / "tests"])
    unarmed = [
        site for site in faults.SITES
        if not any(f"{site}:{kind}" in s
                   for s in constants for kind in faults.KINDS)
    ]
    assert not unarmed, (
        "fault sites declared in faults.SITES but never armed by any "
        "test (add a test injecting at them): " + ", ".join(unarmed)
    )


def test_registry_is_well_formed():
    assert telemetry.NAMES, "registry emptied"
    for name, entry in telemetry.NAMES.items():
        kind, desc = entry
        assert kind in telemetry.KINDS, (name, kind)
        assert isinstance(desc, str) and len(desc) > 10, (
            f"{name}: a registry entry without a real description is a "
            "glossary hole")
        assert re.fullmatch(r"[a-z0-9_.]+(\.\*)?", name), name
        if name.endswith(".*"):
            assert len(name) > 2, name


_GLOSSARY_HEADER = "### Exported telemetry metrics (glossary)"
_TOKEN = re.compile(r"`([a-z0-9_.<>*]+)`")


def _glossary_tokens():
    """Names documented in BASELINE.md's glossary table.

    Returns (expanded, first_cells): ``expanded`` is every backticked
    token from the name/kind/description cells with the table's
    shorthand resolved — ``phase.<name>`` -> the ``phase.*`` family,
    and slash rows like ``checkpoint.save`/`write`` expand the bare
    tail against the previous dotted token's prefix; ``first_cells``
    is the same but name-cells only (held to the stricter
    every-token-declared contract — description prose may mention
    files and APIs that are not metrics)."""
    text = (REPO / "BASELINE.md").read_text()
    start = text.index(_GLOSSARY_HEADER)
    section = text[start:]
    nxt = section.find("\n### ", 1)
    if nxt != -1:
        section = section[:nxt]
    expanded: set[str] = set()
    first_cells: set[str] = set()
    for line in section.splitlines():
        if not line.startswith("| `"):
            continue
        cells = [c.strip() for c in line.strip("|").split("|")]
        for i, cell in enumerate(cells[:3]):
            prefix = None
            for tok in _TOKEN.findall(cell):
                tok = tok.replace("<name>", "*")
                if "." in tok:
                    prefix = tok.rsplit(".", 1)[0]
                elif prefix is not None:
                    tok = f"{prefix}.{tok}"
                expanded.add(tok)
                if i == 0:
                    first_cells.add(tok)
    return expanded, first_cells


def test_every_registry_name_has_a_glossary_row_and_vice_versa():
    """Satellite lint: PRs 4-7 hand-maintained the BASELINE.md metric
    glossary next to telemetry.NAMES; catch the drift mechanically in
    BOTH directions — a registered name missing from the glossary is
    an undocumented export, and a glossary row naming something
    undeclared documents a metric that does not exist."""
    expanded, first_cells = _glossary_tokens()
    missing = [
        name for name in telemetry.NAMES
        if name not in expanded
    ]
    assert not missing, (
        "telemetry.NAMES entries without a BASELINE.md glossary row "
        "(add one to 'Exported telemetry metrics'): "
        + ", ".join(sorted(missing))
    )
    phantom = [
        tok for tok in sorted(first_cells)
        if not (tok in telemetry.NAMES or telemetry.is_declared(tok)
                or tok.endswith(".*"))
    ]
    assert not phantom, (
        "BASELINE.md glossary rows naming metrics that are not in "
        "telemetry.NAMES (registry and glossary must move together): "
        + ", ".join(phantom)
    )


def test_core_names_present():
    # The instrumentation contract of this PR — removing one of these
    # silently un-instruments a subsystem.
    for name in (
        "gram.block",
        "multihost.consensus",
        "prefetch.queue_depth",
        "prefetch.put_wait_s",
        "prefetch.get_wait_s",
        "ingest.retries",
        "checkpoint.save",
        "checkpoint.fallback",
        "faults.fired",
        "hard_sync.fallback",
        "stream.snapshot",
        "phase.*",
        # serving subsystem (registered from day one — the satellite)
        "serve.latency_s",
        "serve.enqueue_wait_s",
        "serve.batch_rows",
        "serve.device_step",
        "serve.assemble",
        "serve.drain",
        "serve.shed",
        "serve.requests",
        "serve.cache_hits",
        "serve.cache_misses",
        "serve.deadline_expired",
        "serve.in_flight",
        # dataset-store subsystem (registered from day one)
        "store.compact",
        "store.chunk_read",
        "store.compact_bytes",
        "store.cache_hits",
        "store.cache_misses",
        "store.verify_failures",
        "store.quarantined",
        "store.cache_bytes",
        # parallel ingest engine + readahead + K-deep device feed
        # (this PR's instrumentation contract)
        "ingest.parallel_shards",
        "ingest.reassembly_wait_s",
        "prefetch.stage_wait_s",
        "prefetch.transfer_wait_s",
        "prefetch.transfers_in_flight",
        "store.readahead.scheduled",
        "store.readahead.hits",
        "store.readahead.errors",
        "store.readahead.wait_s",
        "store.readahead.in_flight",
        # supervision / self-healing / serve availability (this PR's
        # instrumentation contract)
        "supervisor.restarts",
        "supervisor.stalls",
        "supervisor.heartbeats",
        "store.healed",
        "store.heal",
        "serve.health",
        "serve.worker_restarts",
        "serve.breaker_open",
        # streaming sketch solver (registered from day one — the
        # CI/tooling satellite of the solvers PR)
        "solver.pass",
        "solver.solve",
        "solver.passes",
        "solver.rung",
        "solver.rank",
        "solver.state_bytes",
        "solver.nxn_bytes_avoided",
        # similarity-kernel registry: the dual-sketch (ratio metric)
        # solve path
        "solver.dual",
        "solver.dual_den_defect",
        # fleet serving: warm pool, priority admission, hedging (the
        # fleet PR's instrumentation contract)
        "fleet.stage",
        "fleet.restage_total",
        "fleet.evictions",
        "fleet.routes",
        "fleet.pool_bytes",
        "fleet.pool_pressure",
        "fleet.route.*",
        "fleet.cache_namespace_evictions",
        "fleet.hedge_launched",
        "fleet.hedge_wins",
        "serve.priority.preemptions",
        "serve.priority.depth_interactive",
        "serve.priority.depth_batch",
        "serve.priority.shed_interactive",
        "serve.priority.shed_batch",
        # live telemetry plane + trend tracking (this PR's
        # instrumentation contract)
        "live.flush",
        "live.flushes",
        "live.flush_errors",
        "live.requests",
        "live.proxy_requests",
        "live.proxy_stale",
        "trend.metrics_checked",
        "trend.regressions",
        # fleet control plane: the controller loop's evidence trail
        # (ISSUE 16's instrumentation contract)
        "controller.step",
        "controller.spawn",
        "controller.scrapes",
        "controller.scrape_stale",
        "controller.respawns",
        "controller.scale_ups",
        "controller.retires",
        "controller.preemptions",
        "controller.incidents",
        "controller.replicas",
        "controller.ready",
        "controller.flap_breaker_open",
        "serve.drain_abandoned",
        "fleet.failovers",
        # fleet flight recorder: request traces, the timeline ring,
        # SLO burn signals (ISSUE 17's instrumentation contract)
        "trace.request",
        "trace.queue",
        "trace.compute",
        "trace.hedge",
        "trace.sampled",
        "trace.export_errors",
        "trace.exemplars",
        "timeline.rounds",
        "timeline.markers",
        "timeline.compactions",
        "timeline.write_errors",
        "timeline.bytes",
        "timeline.fleet_p99_s",
        "timeline.fleet_queue_depth",
        "timeline.fleet_shed_rate",
        "timeline.route.*",
        "slo.breaches",
        "slo.ok",
        "slo.*",
        "controller.ledger_rotations",
        "neighbors.candidate_pairs",
        "neighbors.filter_frac",
        "neighbors.bucket_overflows",
        "neighbors.evaluated_pairs",
        "neighbors.requests",
        # fused packed gram lowering (this PR's instrumentation
        # contract): the auto choice and its per-block evidence
        "gram.lowering",
        "gram.fused_blocks",
    ):
        assert name in telemetry.NAMES, name
    assert telemetry.is_declared("phase.gram")  # family resolution
    assert not telemetry.is_declared("phasegram")
    assert telemetry.is_declared("timeline.route.r-ibs.p99_s")
    assert telemetry.is_declared("slo.r-ibs.fast_burn")

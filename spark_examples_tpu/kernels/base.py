"""The similarity-kernel registry: one declarative object per metric.

The reference repo's whole point is a *family* of similarity matrices
computed in one pass over variants — but until this module the family
was frozen into ``if metric ==`` chains spread across ``ops/gram.py``,
``ops/distances.py``, ``parallel/gram_sharded.py`` and
``pipelines/runner.py`` (ROADMAP item 1). A :class:`Kernel` gathers
everything a metric is into one object:

- **accumulator schema** — which leaves the streaming pass accumulates
  (raw int32 matmul products for the counting family, custom f32 leaves
  for the float family), and which of them are scalars (replicated, not
  tiled, under a tile2d plan);
- **per-tile update** — counting kernels ride the shared int8-operand
  matmul machinery (``ops/genotype.py``) on both the dense and the
  2-bit-packed transport; float kernels (GRM) supply their own update
  and tile2d body;
- **finalize** — accumulated statistics -> ``{"similarity",
  "distance"}``, in BOTH the jax form (``finalize``) and the NumPy
  oracle mirror (``np_finalize``) so the two can never drift apart
  silently (the kernel lint asserts both exist);
- **int32 overflow budget** — the worst per-variant increment feeding
  the runner's exactness guard, with ``value_scaled_budget`` for
  kernels whose increment scales with the table's max value;
- **FLOPs model** — ``flops(n, v)`` matmul work per block, for GFLOPS
  reporting and the bench kernel sweep;
- **sketch streamability** — a :class:`FactorSketch` when the centered
  solve operator is an exact Gram of per-block streamable features
  (the PR-7 construction), or a :class:`DualSketch` when the metric is
  a *ratio*: numerator and pair-count denominator streamed as TWO
  low-rank sketches in the same variant pass (arXiv:1911.04200's
  communication-efficient sketching direction), lifting ratio metrics
  out of the old hard-coded rejection;
- **cross-cohort projectability** — a :class:`CrossSpec` makes a
  fitted PCoA model of this kernel servable: the cross statistics to
  stream and the squared-distance finalize the projection applies.

The registry is the single source of truth consumed by ``ops/gram.py``
(init/update/combine/flops), ``ops/distances.py`` (finalize),
``parallel/gram_sharded.py`` (accumulator shardings, tile2d body),
``pipelines/runner.py`` (pack-stream auto selection, int32 budget,
table-path dispatch), ``core/config.py`` (validation messages,
computed ``SKETCH_METRICS``), ``solvers/`` (streamability gates) and
``pipelines/project.py`` / ``serve/`` (projectability). Adding a
kernel is ONE registration in ``kernels/builtin.py`` — no consumer
changes.

This module (and the registrations) import NO jax at module scope:
``core/config.py`` pulls the registry in for validation, and the
supervised CLI parent must parse configs without ever initializing a
device (core/supervisor.py). Every jax-touching callable on a kernel
imports lazily at call time.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable


@dataclass(frozen=True)
class FactorSketch:
    """Single-factor streamability: the metric's centered solve operator
    is ``B = (J A)(J A)^T / denom`` for per-block streamable features
    ``A_b = features(block)`` — the PR-7 sketch construction.

    ``features(block, precise) -> (a, kept)``: the (N, v) f32 feature
    columns for one dosage block plus the kept-variant count feeding
    the denominator (0 when unused). ``uses_nvar``: divide the
    finalized operator by the accumulated kept count (GRM).
    """

    features: Callable
    uses_nvar: bool = False
    # The factor IS the PCA driver's similarity (S = A A^T with no
    # denominator, the shared-alt convention): a sketch-rung fit of it
    # can be saved as a factorized PCA model and projected with the
    # exact route's centering formula. Factor metrics without this flag
    # (grm, dot, euclidean) have no cross-projection machinery at all,
    # so their sketch fits stay unservable.
    pca_family: bool = False


@dataclass(frozen=True)
class DualSketch:
    """Ratio-metric streamability: similarity ``S = NUM ⊘ DEN`` with
    both NUM and the pair-count denominator DEN sums of cross-products
    of per-block streamable feature columns. The solver streams
    ``NUM @ Q`` and ``DEN @ Q`` as two sketches in the SAME variant
    pass, extracts the dominant (Perron) rank-1 factor ``a a^T`` of DEN
    from its sketch, and solves the eigenproblem of the *scaled*
    operator ``B = J diag(1/a) NUM diag(1/a) J`` — exact whenever DEN
    is rank-1 (e.g. IBS pair counts with no missing calls), and a
    controlled approximation otherwise (solvers/driver.py documents
    the geometry).

    ``operands(block) -> {name: (N, v) f32}``; ``num_terms`` /
    ``den_terms`` are ``(left, right, weight)`` triples meaning
    ``sum_b w * L_b R_b^T``. ``num_psd``: NUM is positive
    semi-definite, enabling the single-pass Nystrom rung; kernels with
    an indefinite numerator are corrected-rung-only.
    """

    operands: Callable
    num_terms: tuple[tuple[str, str, float], ...]
    den_terms: tuple[tuple[str, str, float], ...]
    num_psd: bool = True


@dataclass(frozen=True)
class CrossSpec:
    """Out-of-sample projectability of a fitted PCoA model: ``stats``
    are the :data:`ops.genotype.CROSS_STATS` names to stream between
    the query cohort and the reference panel; ``d2(acc)`` finalizes the
    accumulated (A, N_ref) statistics into SQUARED cross distances in
    the kernel's own distance convention (jax, called under jit)."""

    stats: tuple[str, ...]
    d2: Callable
    # Ratio kernels only: ``num(acc)`` finalizes the accumulated cross
    # statistics into the similarity NUMERATOR (query x panel), the
    # quantity a factorized dual model scales by 1/(a_q a_j) to project
    # without the dense panel. None = no factorized projection path.
    num: Callable | None = None


@dataclass(frozen=True)
class PairSpec:
    """Sparse pairwise evaluability (the neighbors subsystem): ``stats``
    are :data:`ops.genotype.CROSS_STATS` names accumulated PER PAIR
    (both orientations spelled out — e.g. ``sn``/``sr`` rather than a
    transposed dense half); ``sim(acc)`` maps the accumulated int64
    per-pair statistic vectors to SIMILARITIES (NumPy, elementwise over
    the pair axis), mirroring ``np_finalize``'s off-diagonal values
    bitwise. Declaring a PairSpec does NOT make a kernel projectable —
    that stays ``cross`` — it makes it top-k-able."""

    stats: tuple[str, ...]
    sim: Callable


@dataclass(frozen=True)
class Kernel:
    """One similarity kernel, declaratively. See the module docstring
    for the field-by-field contract; ``family`` is:

    - ``"count"`` — int32 raw-product accumulation over the shared
      int8 matmul operands (the IBS family, jaccard, king, dot, ...);
    - ``"float"`` — custom f32 accumulators and update (GRM);
    - ``"table"`` — not a gram-path kernel at all: a dense-table
      pipeline with its own runner (braycurtis).
    """

    name: str
    summary: str
    family: str = "count"
    # count family: raw products accumulated / stats finalize consumes.
    pieces: tuple[str, ...] = ()
    stats: tuple[str, ...] = ()
    finalize: Callable | None = None      # stats -> {"similarity","distance"} (jax)
    np_finalize: Callable | None = None   # NumPy oracle mirror
    # 2-bit packable under --pack-stream auto (inputs are dosages by
    # definition); False keeps arbitrary-int8-table kernels dense.
    pack_auto: bool = True
    # int32 exactness guard: worst per-variant accumulator increment
    # (None = exempt, e.g. f32 accumulation); value_scaled_budget
    # scales it by the observed max table value squared (dot/euclidean).
    max_increment: int | None = None
    value_scaled_budget: bool = False
    flops: Callable | None = None         # (n, v) -> matmul FLOPs per block
    # Fused Pallas lowering (count family only): (packed_rows,
    # packed_cols) -> {piece: int32 tile}, decode + mask + contract in
    # one pass on the 2-bit bytes (ops/pallas/packed_gram.py) — the
    # drop-in twin of slice-unpack-tile_products, bit-identical by the
    # parity suites. None = reference XLA lowering only.
    fused_body: Callable | None = None
    sketch: FactorSketch | DualSketch | None = None
    cross: CrossSpec | None = None
    pair: PairSpec | None = None
    # float family hooks (all lazy-importing; None for count/table).
    acc_leaves_: tuple[str, ...] | None = None
    scalar_leaves: tuple[str, ...] = ()   # replicated (not tiled) leaves
    init: Callable | None = None          # n -> fresh accumulator dict
    update_impl: Callable | None = None   # (packed) -> (acc, block, precise) -> acc
    tile_body: Callable | None = None     # tile2d shard_map body hook
    oracle_similarity: Callable | None = None  # cpu-reference route
    # table family hook: (job, source, timer) -> SimilarityResult.
    table_runner: Callable | None = None

    @property
    def is_gram(self) -> bool:
        """Rides the streaming gram accumulator (count or float)."""
        return self.family in ("count", "float")

    @property
    def acc_leaves(self) -> tuple[str, ...]:
        """Accumulator leaf names (checkpoint schema, shardings)."""
        return self.acc_leaves_ if self.acc_leaves_ is not None else self.pieces


_REGISTRY: dict[str, Kernel] = {}


def register(kernel: Kernel) -> Kernel:
    """Add a kernel to the registry, validating the family contract up
    front — a half-declared kernel must die at import, not as a
    KeyError deep inside a streaming job."""
    if kernel.name in _REGISTRY:
        raise ValueError(f"kernel {kernel.name!r} is already registered")
    if kernel.family not in ("count", "float", "table"):
        raise ValueError(
            f"kernel {kernel.name!r}: unknown family {kernel.family!r} "
            "(count | float | table)"
        )
    if kernel.flops is None:
        raise ValueError(
            f"kernel {kernel.name!r} declares no FLOPs model — every "
            "kernel must be benchmarkable (flops=(n, v) -> float)"
        )
    if kernel.family == "count":
        missing = [f for f in ("pieces", "stats", "finalize", "np_finalize")
                   if not getattr(kernel, f)]
        if missing or kernel.max_increment is None:
            raise ValueError(
                f"count kernel {kernel.name!r} is missing "
                f"{missing + (['max_increment'] if kernel.max_increment is None else [])}"
            )
    if kernel.family == "float":
        missing = [f for f in ("init", "update_impl", "tile_body",
                               "finalize", "np_finalize", "acc_leaves_")
                   if getattr(kernel, f) is None]
        if missing:
            raise ValueError(
                f"float kernel {kernel.name!r} is missing {missing}")
    if kernel.family == "table" and kernel.table_runner is None:
        raise ValueError(
            f"table kernel {kernel.name!r} declares no table_runner")
    if kernel.fused_body is not None and not (
            kernel.family == "count" and kernel.pack_auto):
        raise ValueError(
            f"kernel {kernel.name!r} declares a fused_body but is not a "
            "pack_auto count kernel — the fused Pallas lowering consumes "
            "2-bit packed dosage bytes, which only the dosage-defined "
            "count family streams"
        )
    if isinstance(kernel.sketch, DualSketch):
        declared = _dual_operand_names(kernel.sketch)
        for side in (kernel.sketch.num_terms, kernel.sketch.den_terms):
            for left, right, _w in side:
                if left not in declared or right not in declared:
                    raise ValueError(
                        f"kernel {kernel.name!r}: dual-sketch term "
                        f"({left!r}, {right!r}) names an operand the "
                        f"spec never declares ({sorted(declared)})"
                    )
    _REGISTRY[kernel.name] = kernel
    return kernel


def _dual_operand_names(spec: DualSketch) -> set[str]:
    """Operand names a dual spec's terms may reference — declared as
    ``spec.operand_names`` metadata on the operands callable (set by
    the registration helper) so validation never has to call the
    jax-touching builder at import time."""
    return set(getattr(spec.operands, "operand_names", ())) or {
        l for terms in (spec.num_terms, spec.den_terms)
        for (l, r, _w) in terms for l in (l, r)
    }


def unregister(name: str) -> None:
    """Remove a kernel (test scaffolding for registration machinery)."""
    _REGISTRY.pop(name, None)


def maybe_get(name: str) -> Kernel | None:
    """The non-raising lookup (dispatch sites that build their own
    error message)."""
    return _REGISTRY.get(name)


def get(name: str) -> Kernel:
    try:
        return _REGISTRY[name]
    except KeyError:
        raise ValueError(
            f"unknown metric {name!r}; registered kernels: "
            f"{' | '.join(sorted(_REGISTRY))}"
        ) from None


def all_kernels() -> tuple[Kernel, ...]:
    return tuple(_REGISTRY.values())


def names() -> tuple[str, ...]:
    """Every registered kernel name, in registration order."""
    return tuple(_REGISTRY)


def gram_names() -> tuple[str, ...]:
    """Kernels riding the streaming gram accumulator."""
    return tuple(k.name for k in _REGISTRY.values() if k.is_gram)


def factor_sketch_names() -> tuple[str, ...]:
    """Kernels streamable as a single-factor sketch (PR-7 form)."""
    return tuple(k.name for k in _REGISTRY.values()
                 if isinstance(k.sketch, FactorSketch))


def dual_sketch_names() -> tuple[str, ...]:
    """Ratio kernels streamable as a num/den dual sketch."""
    return tuple(k.name for k in _REGISTRY.values()
                 if isinstance(k.sketch, DualSketch))


def fused_names() -> tuple[str, ...]:
    """Kernels with a fused packed Pallas lowering (--gram-lowering)."""
    return tuple(k.name for k in _REGISTRY.values()
                 if k.fused_body is not None)


def resolve_lowering(requested: str, platform: str, fused: str,
                     reference: str) -> str:
    """THE auto-lowering decision, shared by every kernel family:
    ``auto`` resolves to the fused/accelerated lowering on real TPU
    hardware and the portable reference lowering everywhere else (the
    Pallas interpreter is for correctness, not speed); an explicit
    request passes through. One tiny pure function so the gram fused
    path (ops/gram.py) and braycurtis's method pick
    (pipelines/runner.py) can never drift — and so the decision is
    testable without a device."""
    if requested == "auto":
        return fused if platform == "tpu" else reference
    return requested


def check_fused_lowering(metric: str, packed: bool) -> None:
    """Raise (with the registry-derived fix named) unless ``metric`` on
    this transport can run the fused packed Pallas lowering. The one
    gate shared by config-time validation (core/config.py) and the
    runtime dispatch (ops/gram.py, parallel/gram_sharded.py) — one text
    builder, no drift."""
    kern = _REGISTRY.get(metric)
    if kern is None or kern.fused_body is None:
        raise ValueError(
            f"--gram-lowering fused does not support --metric {metric}: "
            "no fused Pallas lowering is registered for it — fused "
            f"kernels: {' | '.join(fused_names())}; use --gram-lowering "
            "auto|reference for the others"
        )
    if not packed:
        raise ValueError(
            f"--gram-lowering fused consumes the 2-bit packed transport "
            f"directly, but --metric {metric} is resolving to a dense "
            "stream — use --pack-stream auto|packed (or --gram-lowering "
            "auto|reference)"
        )


def pairable_names() -> tuple[str, ...]:
    """Kernels whose similarity can be evaluated per candidate pair
    (declared a PairSpec) — the metrics the neighbors engine serves."""
    return tuple(k.name for k in _REGISTRY.values() if k.pair is not None)


def unsketchable_names() -> tuple[str, ...]:
    """Gram kernels with no declared streamability (exact rung only)."""
    return tuple(k.name for k in _REGISTRY.values()
                 if k.is_gram and k.sketch is None)


def unsketchable_metric_error(metric: str, solver: str) -> str:
    """THE rejection text for a metric the sketch ladder cannot run —
    derived from the registry (never a stale hand-listed string),
    shared by config-time validation and the solvers' runtime gate."""
    kern = _REGISTRY.get(metric)
    if kern is not None and isinstance(kern.sketch, DualSketch):
        # Reachable only for a dual kernel whose numerator is not PSD:
        # the single-pass Nystrom rung needs a PSD core.
        return (
            f"--solver {solver} does not support --metric {metric}: its "
            "dual-sketch numerator is not PSD, so the single-pass "
            "Nystrom rung is unavailable — use --solver corrected "
            "(streamed subspace iteration handles indefinite operators)"
        )
    return (
        f"--solver {solver} does not support --metric {metric}: the "
        "sketch streams an exact Gram factor per block, which exists "
        f"for {' | '.join(factor_sketch_names())}; ratio metrics "
        f"({' | '.join(dual_sketch_names())}) stream numerator + "
        "pair-count denominator as a dual sketch; metrics declaring "
        f"neither ({' | '.join(unsketchable_names())}) require the "
        "materialized N x N — use --solver exact for them"
    )


def check_sketchable(metric: str, solver: str) -> None:
    """Raise (with the registry-derived fix named) unless ``metric``
    can run the ``solver`` rung. The one gate shared by config-time
    validation (core/config.py) and the runtime driver
    (solvers/sketch.py) — one text builder, no drift."""
    kern = _REGISTRY.get(metric)
    spec = kern.sketch if kern is not None else None
    if spec is None:
        raise ValueError(unsketchable_metric_error(metric, solver))
    if (isinstance(spec, DualSketch) and solver == "sketch"
            and not spec.num_psd):
        raise ValueError(unsketchable_metric_error(metric, solver))


def factorized_savable_names() -> tuple[str, ...]:
    """Metrics whose sketch-rung fits can be saved as a factorized
    model: pca-family factor kernels on either rung, dual kernels with
    a cross numerator on the corrected rung."""
    return tuple(
        k.name for k in _REGISTRY.values()
        if (isinstance(k.sketch, FactorSketch) and k.sketch.pca_family)
        or (isinstance(k.sketch, DualSketch) and k.cross is not None
            and k.cross.num is not None)
    )


def check_factorized_savable(metric: str | None, solver: str,
                             kind: str | None = None) -> None:
    """Raise unless a ``--save-model`` fit of ``metric`` on the sketch
    ladder rung ``solver`` can produce a servable factorized model.
    Shared by config-time validation (``kind`` unknown there — a
    JobConfig serves pcoa, pca, and similarity alike, so only combos
    invalid for EVERY kind are rejected) and the run-time driver gate
    (``kind`` known; the kind-specific rows resolve). ``exact`` never
    reaches the factorized path and always passes."""
    if solver == "exact":
        return
    if metric is None:
        if kind is None:
            return  # defer: the driver default resolves at run time
        metric = "shared-alt" if kind == "pca" else "ibs"
    kern = _REGISTRY.get(metric)
    spec = kern.sketch if kern is not None else None
    savable = " | ".join(factorized_savable_names())
    if isinstance(spec, DualSketch):
        if kern.cross is None or kern.cross.num is None:
            raise ValueError(
                f"--save-model with --solver {solver}: --metric {metric} "
                "declares no cross numerator, so a factorized model of "
                f"it cannot project queries — savable sketch metrics: "
                f"{savable}, or fit with --solver exact"
            )
        if solver != "corrected":
            raise ValueError(
                f"--save-model with --metric {metric}: the dual "
                "centering statistics stream only in the corrected "
                "rung's scaled power passes (the denominator scale "
                "does not exist during pass 0) — use --solver "
                "corrected, or fit with --solver exact"
            )
        return
    if isinstance(spec, FactorSketch):
        if not spec.pca_family:
            raise ValueError(
                f"--save-model with --solver {solver}: --metric {metric} "
                "has no factorized projection path (its factor is not "
                "the PCA similarity and it declares no cross spec) — "
                f"savable sketch metrics: {savable}, or fit with "
                "--solver exact"
            )
        if kind == "pcoa":
            raise ValueError(
                f"--save-model with --solver {solver}: a pcoa fit of "
                f"--metric {metric} serves the Gower geometry, which "
                "the factorized artifact stores only for ratio "
                f"metrics ({' | '.join(dual_sketch_names())}) — save "
                "the pca fit instead, or fit with --solver exact"
            )
        return
    # No sketch spec at all: check_sketchable already rejects the rung
    # itself with the registry-derived text; repeat it here so this
    # gate is safe to call first.
    raise ValueError(unsketchable_metric_error(metric, solver))

"""Similarity-kernel registry (see kernels/base.py for the contract).

Importing the package registers the built-in kernels; the public
surface is the registry accessors. jax-free at import time — safe for
``core/config.py`` and the supervised CLI parent.
"""

from spark_examples_tpu.kernels.base import (  # noqa: F401
    CrossSpec,
    DualSketch,
    FactorSketch,
    Kernel,
    PairSpec,
    all_kernels,
    check_factorized_savable,
    check_fused_lowering,
    check_sketchable,
    dual_sketch_names,
    factor_sketch_names,
    factorized_savable_names,
    fused_names,
    get,
    gram_names,
    maybe_get,
    names,
    pairable_names,
    register,
    resolve_lowering,
    unregister,
    unsketchable_metric_error,
    unsketchable_names,
)
from spark_examples_tpu.kernels import builtin  # noqa: F401  (registers)

"""Built-in kernel registrations: the seven pre-existing metrics
re-registered through the registry with pinned bit-identity (their
finalize bodies are the exact code that used to live in
``ops/distances.py`` / ``utils/oracle.py`` — tests pin the outputs
byte-identical), plus **jaccard**, the first genuinely new workload the
registry ships (carrier-set similarity for duplicate detection and
cohort dedup), and **braycurtis** as the table-family registration of
the existing dense-table pipeline.

No jax at module scope (see kernels/base.py) — every jax-touching
callable imports lazily at call time.
"""

from __future__ import annotations

from spark_examples_tpu.kernels.base import (
    CrossSpec,
    DualSketch,
    FactorSketch,
    Kernel,
    PairSpec,
    register,
)


def _np_gower(sim):
    """NumPy twin of ``ops.distances.similarity_to_distance`` — the
    Gower transform ``d = sqrt(s_ii + s_jj - 2 s_ij)`` clamped at 0.
    ONE definition for every np_finalize below, so a clamp/dtype fix
    can never drift between kernels (the jax side has the same single
    definition)."""
    import numpy as np

    diag = np.diagonal(sim)
    return np.sqrt(np.maximum(diag[:, None] + diag[None, :] - 2 * sim, 0.0))


def _fused_count_body(pieces: tuple[str, ...]):
    """The fused packed Pallas lowering for a counting kernel: decode +
    mask + contract in one pass on the 2-bit bytes
    (ops/pallas/packed_gram.py), bit-identical to
    slice-unpack-``tile_products`` by the parity suites. Lazy import —
    this closure only touches jax when a fused update actually traces."""

    def fused_body(packed_rows, packed_cols):
        from spark_examples_tpu.ops.pallas.packed_gram import (
            fused_tile_products,
        )

        return fused_tile_products(packed_rows, packed_cols, pieces)

    return fused_body


def _count_flops(pieces: tuple[str, ...]):
    """Matmul FLOPs per block for a counting kernel: one matmul per
    int8-split term of each accumulated product (the radix-128 ``qc``
    lowering makes euclidean 3, not 2)."""

    def flops(n: int, v: int) -> float:
        from spark_examples_tpu.ops import genotype

        n_matmuls = sum(
            len(genotype._INT8_SPLIT.get(p, (None,))) for p in pieces
        )
        return 2.0 * n * n * v * n_matmuls

    return flops


# --------------------------------------------------------------- ibs

def _ibs_finalize(stats):
    import jax.numpy as jnp

    m = stats["m"]
    dist = jnp.where(m > 0, stats["d1"] / (2.0 * m), 0.0)
    return {"similarity": 1.0 - dist, "distance": dist}


def _ibs_np_finalize(acc):
    import numpy as np

    with np.errstate(invalid="ignore", divide="ignore"):
        dist = np.where(acc["m"] > 0, acc["d1"] / (2.0 * acc["m"]), 0.0)
    return {"similarity": 1.0 - dist, "distance": dist}


def _ibs_dual_operands(block):
    import jax.numpy as jnp

    valid = block >= 0
    c = valid.astype(jnp.float32)
    t1 = (block >= 1).astype(jnp.float32)
    t2 = (block >= 2).astype(jnp.float32)
    return {"c": c, "t1": t1, "t2": t2, "y": t1 + t2}


_ibs_dual_operands.operand_names = ("c", "t1", "t2", "y")


def _ibs_cross_d2(acc):
    import jax.numpy as jnp

    m = acc["m"]
    dist = jnp.where(m > 0, acc["d1"].astype(jnp.float32) / (2.0 * m), 0.0)
    return dist * dist


def _ibs_cross_num(acc):
    import jax.numpy as jnp

    # The dual sketch's similarity numerator NUM = 2m - d1 between a
    # query row and each panel sample — the same quantity the fit
    # streamed as sum_v c_i c_j (2 - |a-b|), from the cross statistics.
    return (2.0 * acc["m"] - acc["d1"]).astype(jnp.float32)


def _ibs_pair_sim(acc):
    import numpy as np

    # Mirrors _ibs_np_finalize off-diagonal bitwise: dist 0 (sim 1)
    # when a pair shares no complete variants.
    with np.errstate(invalid="ignore", divide="ignore"):
        return np.where(acc["m"] > 0,
                        1.0 - acc["d1"] / (2.0 * acc["m"]), 1.0)


register(Kernel(
    name="ibs",
    summary="PLINK-convention identity-by-state over pairwise-complete "
            "variants: dist = sum|a-b| / (2m)",
    family="count",
    pieces=("cc", "yc", "t1t1", "t2t2"),
    stats=("m", "d1"),
    finalize=_ibs_finalize,
    np_finalize=_ibs_np_finalize,
    pack_auto=True,
    max_increment=2,  # yc with y <= 2
    flops=_count_flops(("cc", "yc", "t1t1", "t2t2")),
    fused_body=_fused_count_body(("cc", "yc", "t1t1", "t2t2")),
    # Dual sketch: similarity numerator NUM = 2m - d1 =
    # sum_v c_i c_j (2 - |a-b|) — a PSD kernel matrix per variant
    # ([[2,1,0],[1,2,1],[0,1,2]] is PSD and masking is a congruence) —
    # over the pair-count denominator DEN = 2m (exactly rank-1 when no
    # calls are missing, so the scaled operator is then exact).
    sketch=DualSketch(
        operands=_ibs_dual_operands,
        num_terms=(("c", "c", 2.0), ("y", "c", -1.0), ("c", "y", -1.0),
                   ("t1", "t1", 2.0), ("t2", "t2", 2.0)),
        den_terms=(("c", "c", 2.0),),
        num_psd=True,
    ),
    cross=CrossSpec(stats=("m", "d1"), d2=_ibs_cross_d2,
                    num=_ibs_cross_num),
    pair=PairSpec(stats=("m", "d1"), sim=_ibs_pair_sim),
))


# -------------------------------------------------------------- ibs2

def _ibs2_finalize(stats):
    import jax.numpy as jnp

    m = stats["m"]
    sim = jnp.where(m > 0, stats["ibs2"] / (1.0 * m), 1.0)
    return {"similarity": sim, "distance": 1.0 - sim}


def _ibs2_np_finalize(acc):
    import numpy as np

    with np.errstate(invalid="ignore", divide="ignore"):
        sim = np.where(acc["m"] > 0, acc["ibs2"] / acc["m"], 1.0)
    return {"similarity": sim, "distance": 1.0 - sim}


register(Kernel(
    name="ibs2",
    summary="fraction of pairwise-complete variants with identical "
            "genotype",
    family="count",
    pieces=("cc", "t1c", "t1t1", "t1t2", "t2t2"),
    stats=("m", "ibs2"),
    finalize=_ibs2_finalize,
    np_finalize=_ibs2_np_finalize,
    pack_auto=True,
    max_increment=2,  # t1c-family indicator sums
    flops=_count_flops(("cc", "t1c", "t1t1", "t1t2", "t2t2")),
    fused_body=_fused_count_body(("cc", "t1c", "t1t1", "t1t2", "t2t2")),
))


# --------------------------------------------------------- shared-alt

def _shared_alt_finalize(stats):
    import jax.numpy as jnp

    from spark_examples_tpu.ops.distances import similarity_to_distance

    s = stats["s"].astype(jnp.float32)
    return {"similarity": s, "distance": similarity_to_distance(s)}


def _shared_alt_np_finalize(acc):
    return {"similarity": acc["s"], "distance": _np_gower(acc["s"])}


def _shared_alt_features(block, precise):
    import jax.numpy as jnp

    a = (block >= 1).astype(jnp.float32)
    return a, jnp.float32(0.0)  # denominator unused


register(Kernel(
    name="shared-alt",
    summary="raw shared-alt-carrier counts (the PCA driver's "
            "similarity)",
    family="count",
    pieces=("t1t1",),
    stats=("s",),
    finalize=_shared_alt_finalize,
    np_finalize=_shared_alt_np_finalize,
    pack_auto=True,
    max_increment=1,
    flops=_count_flops(("t1t1",)),
    fused_body=_fused_count_body(("t1t1",)),
    # pca_family: the factor IS the PCA similarity (S = T1 T1^T, no
    # denominator), so a sketch-rung fit saves as a factorized PCA
    # model served with the exact route's centering formula.
    sketch=FactorSketch(features=_shared_alt_features, pca_family=True),
))


# ---------------------------------------------------------- euclidean

def _euclidean_finalize(stats):
    import jax.numpy as jnp

    d = jnp.sqrt(jnp.maximum(stats["e2"].astype(jnp.float32), 0.0))
    return {"similarity": -d, "distance": d}


def _euclidean_np_finalize(acc):
    import numpy as np

    d = np.sqrt(np.maximum(acc["e2"], 0.0))
    return {"similarity": -d, "distance": d}


def _raw_value_features(block, precise):
    import jax.numpy as jnp

    a = jnp.where(block >= 0, block, 0).astype(jnp.float32)
    return a, jnp.float32(0.0)


register(Kernel(
    name="euclidean",
    summary="exact raw-value euclidean distance for arbitrary int8 "
            "tables",
    family="count",
    pieces=("qc", "yy"),
    stats=("e2",),
    finalize=_euclidean_finalize,
    np_finalize=_euclidean_np_finalize,
    pack_auto=False,  # arbitrary int8 values, not 2-bit representable
    max_increment=4,  # qc/yy at dosage values; m^2 in general
    value_scaled_budget=True,
    flops=_count_flops(("qc", "yy")),
    sketch=FactorSketch(features=_raw_value_features),
))


# ---------------------------------------------------------------- dot

def _dot_finalize(stats):
    import jax.numpy as jnp

    from spark_examples_tpu.ops.distances import similarity_to_distance

    dot = stats["dot"].astype(jnp.float32)
    return {"similarity": dot, "distance": similarity_to_distance(dot)}


def _dot_np_finalize(acc):
    return {"similarity": acc["dot"], "distance": _np_gower(acc["dot"])}


register(Kernel(
    name="dot",
    summary="raw-value inner products for arbitrary int8 tables",
    family="count",
    pieces=("yy",),
    stats=("dot",),
    finalize=_dot_finalize,
    np_finalize=_dot_np_finalize,
    pack_auto=False,
    max_increment=4,
    value_scaled_budget=True,
    flops=_count_flops(("yy",)),
    sketch=FactorSketch(features=_raw_value_features),
))


# --------------------------------------------------------------- king

def _king_finalize(stats):
    import jax.numpy as jnp

    # KING-robust kinship (Manichaikul 2010, between-family form):
    # phi = (N_AaAa - 2 * N_AA,aa) / (N_Aa(i) + N_Aa(j)), hets counted
    # over pairwise-complete variants. Pairs sharing no het variants
    # are uninformative -> phi 0 (unrelated); the diagonal is pinned to
    # self-kinship 0.5 even for samples with zero het calls (inbred
    # lines, haploid 0/2 coding) — a nonzero self-distance would poison
    # the Gower centering every downstream PCoA applies.
    den = (stats["hc"] + stats["hc"].T).astype(jnp.float32)
    num = (stats["hh"] - 2 * stats["opp"]).astype(jnp.float32)
    phi = jnp.where(den > 0, num / den, 0.0)
    n = phi.shape[0]
    phi = jnp.where(jnp.eye(n, dtype=bool), 0.5, phi)
    return {"similarity": phi,
            "distance": jnp.maximum(0.5 - phi, 0.0)}


def _king_np_finalize(acc):
    import numpy as np

    den = acc["hc"] + acc["hc"].T
    with np.errstate(invalid="ignore", divide="ignore"):
        phi = np.where(den > 0, (acc["hh"] - 2 * acc["opp"]) / den, 0.0)
    np.fill_diagonal(phi, 0.5)  # self-kinship even with zero hets
    return {"similarity": phi,
            "distance": np.maximum(0.5 - phi, 0.0)}


def _king_pair_sim(acc):
    import numpy as np

    # Per-pair het-count denominator = hcn + hcr (the two orientations
    # of the hc statistic), matching hc + hc^T off-diagonal bitwise.
    # The diagonal's 0.5 pin never applies: candidate pairs are i < j.
    den = acc["hcn"] + acc["hcr"]
    with np.errstate(invalid="ignore", divide="ignore"):
        return np.where(den > 0, (acc["hh"] - 2 * acc["opp"]) / den, 0.0)


register(Kernel(
    name="king",
    summary="KING-robust kinship (relatedness QC: dup ~0.5, "
            "parent-child ~0.25)",
    family="count",
    pieces=("t1c", "t2c", "t1t1", "t1t2", "t2t2"),
    stats=("hh", "opp", "hc"),
    finalize=_king_finalize,
    np_finalize=_king_np_finalize,
    pack_auto=True,
    max_increment=2,  # finalize sums hc + hc^T / hh - 2*opp in int32
    flops=_count_flops(("t1c", "t2c", "t1t1", "t1t2", "t2t2")),
    fused_body=_fused_count_body(("t1c", "t2c", "t1t1", "t1t2", "t2t2")),
    # No sketch spec: phi's numerator (hh - 2*opp) is indefinite AND
    # its het-count denominator is far from rank-1 (zero-het samples),
    # so neither sketch form applies — exact rung only, and the
    # registry-derived rejection says so. No cross spec either (a
    # PairSpec deliberately does not make king PROJECTABLE), but the
    # per-pair statistics exist, so top-k relatedness screening works.
    pair=PairSpec(stats=("hh", "opp", "hcn", "hcr"), sim=_king_pair_sim),
))


# ------------------------------------------------------------ jaccard

def _jaccard_finalize(stats):
    import jax.numpy as jnp

    from spark_examples_tpu.ops.distances import similarity_to_distance

    # Carrier-set Jaccard over pairwise-complete variants: intersection
    # = shared-alt count, union = sc + sc^T - s with sc[i, j] = #(i
    # carries alt AND j's call is valid). Pairs with an empty union
    # (neither carries anything) cannot be distinguished from identical
    # -> similarity 1, the same spirit as ibs's zero-overlap convention.
    # The diagonal is exactly 1 (union_ii == inter_ii == carrier
    # count), so the Gower distance is sqrt(2(1-J)) — itself a metric.
    s = stats["s"]
    union = stats["sc"] + stats["sc"].T - s
    sim = jnp.where(union > 0, s / union, 1.0)
    return {"similarity": sim, "distance": similarity_to_distance(sim)}


def _jaccard_np_finalize(acc):
    import numpy as np

    s = acc["s"]
    union = acc["sc"] + acc["sc"].T - s
    with np.errstate(invalid="ignore", divide="ignore"):
        sim = np.where(union > 0, s / union, 1.0)
    return {"similarity": sim, "distance": _np_gower(sim)}


def _jaccard_dual_operands(block):
    import jax.numpy as jnp

    c = (block >= 0).astype(jnp.float32)
    t1 = (block >= 1).astype(jnp.float32)
    return {"c": c, "t1": t1}


_jaccard_dual_operands.operand_names = ("c", "t1")


def _jaccard_cross_num(acc):
    import jax.numpy as jnp

    # The dual sketch's numerator is the raw intersection count
    # NUM = T1 T1^T — for a query row, exactly the streamed ``s``.
    return acc["s"].astype(jnp.float32)


def _jaccard_cross_d2(acc):
    import jax.numpy as jnp

    # Cross union between a query row and a panel column: each side's
    # carrier count over pairwise-complete variants, minus the shared
    # carriers. Self-similarity is exactly 1 on both sides (see the
    # symmetric finalize), so the Gower squared distance is 2 - 2J.
    s = acc["s"].astype(jnp.float32)
    union = (acc["sn"] + acc["sr"]).astype(jnp.float32) - s
    sim = jnp.where(union > 0, s / union, 1.0)
    return jnp.maximum(2.0 - 2.0 * sim, 0.0)


def _jaccard_pair_sim(acc):
    import numpy as np

    # Per-pair union = sn + sr - s (both orientations of the sc
    # statistic spelled out) — the same integers _jaccard_np_finalize
    # gets from sc + sc^T - s on the dense route, so the similarity
    # matches bitwise.
    union = acc["sn"] + acc["sr"] - acc["s"]
    with np.errstate(invalid="ignore", divide="ignore"):
        return np.where(union > 0, acc["s"] / union, 1.0)


register(Kernel(
    name="jaccard",
    summary="carrier-set Jaccard similarity over pairwise-complete "
            "variants (duplicate detection / cohort dedup)",
    family="count",
    pieces=("t1c", "t1t1"),
    stats=("s", "sc"),
    finalize=_jaccard_finalize,
    np_finalize=_jaccard_np_finalize,
    pack_auto=True,
    # The accumulated products are indicator sums (increment 1), but
    # finalize computes union = sc + sc^T - s in int32 — the effective
    # per-variant increment is 2, same reason ibs2/king register 2.
    max_increment=2,
    flops=_count_flops(("t1c", "t1t1")),
    fused_body=_fused_count_body(("t1c", "t1t1")),
    # Dual sketch: NUM = intersection counts T1 T1^T (PSD by
    # construction — both rungs available); DEN = the union pair
    # counts, whose Perron rank-1 factor the solver extracts from the
    # den sketch. arXiv:1911.04200's communication-efficient Jaccard
    # sketching, recast onto the streaming range-sketch machinery.
    sketch=DualSketch(
        operands=_jaccard_dual_operands,
        num_terms=(("t1", "t1", 1.0),),
        den_terms=(("t1", "c", 1.0), ("c", "t1", 1.0),
                   ("t1", "t1", -1.0)),
        num_psd=True,
    ),
    cross=CrossSpec(stats=("s", "sn", "sr"), d2=_jaccard_cross_d2,
                    num=_jaccard_cross_num),
    pair=PairSpec(stats=("s", "sn", "sr"), sim=_jaccard_pair_sim),
))


# -------------------------------------------------------- pc-invariant

def _pc_invariant_finalize(stats):
    import jax.numpy as jnp

    # Piecewise-constant invariant similarity (arXiv:2404.07183): the
    # per-variant pair contribution is an arbitrary piecewise-constant
    # function W(a, b) of the two dosages, assembled from indicator
    # cross-products. This registration instantiates the canonical
    # relatedness-flavored table
    #     W = [[+1, 0, -1], [0, +1, 0], [-1, 0, +1]]
    # (+1 identical genotype, -1 opposite homozygotes, 0 otherwise)
    # over pairwise-complete variants, normalized by the valid-pair
    # count m: s = (ibs2 - opp) / m in [-1, 1]. The numerator is
    # exactly the existing integer statistics recombined — the paper's
    # point, and the registry's declared extension contract: ANY such
    # table is one registration in the pieces/stats algebra, no new
    # matmuls. Pairs sharing no complete variants score 1 (the
    # indistinguishable-from-identical convention ibs/jaccard use), so
    # the diagonal is exactly 1 and the distance (1 - s) / 2 in [0, 1]
    # has an exactly-zero self-distance — safe under Gower centering.
    m = stats["m"].astype(jnp.float32)
    num = (stats["ibs2"] - stats["opp"]).astype(jnp.float32)
    sim = jnp.where(m > 0, num / m, 1.0)
    return {"similarity": sim, "distance": (1.0 - sim) / 2.0}


def _pc_invariant_np_finalize(acc):
    import numpy as np

    with np.errstate(invalid="ignore", divide="ignore"):
        sim = np.where(acc["m"] > 0,
                       (acc["ibs2"] - acc["opp"]) / acc["m"], 1.0)
    return {"similarity": sim, "distance": (1.0 - sim) / 2.0}


register(Kernel(
    name="pc-invariant",
    summary="piecewise-constant invariant similarity (arXiv:2404.07183"
            " construction): per-variant table +1 identical genotype, "
            "-1 opposite homozygotes, normalized per complete pair",
    family="count",
    pieces=("cc", "t1c", "t2c", "t1t1", "t1t2", "t2t2"),
    stats=("m", "ibs2", "opp"),
    finalize=_pc_invariant_finalize,
    np_finalize=_pc_invariant_np_finalize,
    pack_auto=True,
    # ibs2's combine sums indicator products with coefficient 2 (the
    # same reason ibs2/king register 2); the finalize's ibs2 - opp
    # stays within that per-variant budget.
    max_increment=2,
    flops=_count_flops(("cc", "t1c", "t2c", "t1t1", "t1t2", "t2t2")),
    fused_body=_fused_count_body(
        ("cc", "t1c", "t2c", "t1t1", "t1t2", "t2t2")),
    # No sketch spec: the table is indefinite (the -1 off-diagonal
    # blocks), so neither the exact-Gram factor form nor the PSD dual
    # numerator applies — exact rung only, like king.
))


# ---------------------------------------------------------------- grm

def _grm_finalize(stats):
    import jax.numpy as jnp

    from spark_examples_tpu.ops.distances import similarity_to_distance

    g = stats["zz"] / jnp.maximum(stats["nvar"], 1.0)
    return {"similarity": g, "distance": similarity_to_distance(g)}


def _grm_np_finalize(acc):
    import numpy as np

    g = acc["zz"] / np.maximum(acc["nvar"], 1.0)
    return {"similarity": g, "distance": _np_gower(g)}


def _grm_init(n):
    import jax.numpy as jnp

    return {
        "zz": jnp.zeros((n, n), jnp.float32),
        "nvar": jnp.zeros((), jnp.float32),
    }


def _grm_update_impl(packed: bool):
    from spark_examples_tpu.ops import gram

    return gram._update_grm_packed_impl if packed else gram._update_grm_impl


def _grm_tile_body(acc, block, i, j, tn, tm, precise):
    """The GRM tile2d contribution: standardization statistics come
    from the FULL block (per-variant, over all N samples — replicated
    work, identical on every device), then only the tile's slices hit
    the MXU."""
    import jax
    import jax.numpy as jnp

    from spark_examples_tpu.ops import gram as gram_ops

    z, keep = gram_ops.grm_standardize(block, precise)
    zr = jax.lax.dynamic_slice_in_dim(z, i * tn, tn, axis=0)
    zc = jax.lax.dynamic_slice_in_dim(z, j * tm, tm, axis=0)
    zz = jax.lax.dot_general(
        zr, zc, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    return {"zz": acc["zz"] + zz, "nvar": acc["nvar"] + keep.sum()}


def _grm_features(block, precise):
    import jax.numpy as jnp

    from spark_examples_tpu.ops import gram as gram_ops

    # Same standardization as the exact route; the sketch's matmuls
    # then run f32 regardless of grm_precise (they are ~N/r cheaper
    # than the dense update, so there is no rate to buy back).
    a, keep = gram_ops.grm_standardize(block, precise)
    return a.astype(jnp.float32), keep.sum().astype(jnp.float32)


def _grm_oracle(x):
    from spark_examples_tpu.utils import oracle

    return oracle.naive_grm(x)


register(Kernel(
    name="grm",
    summary="VanRaden/GCTA genomic relationship matrix (f32 "
            "accumulation, within-block allele frequencies)",
    family="float",
    finalize=_grm_finalize,
    np_finalize=_grm_np_finalize,
    pack_auto=True,
    max_increment=None,  # f32 accumulation: rounding, not wraparound
    flops=lambda n, v: 2.0 * n * n * v,  # one Z Z^T matmul per block
    sketch=FactorSketch(features=_grm_features, uses_nvar=True),
    acc_leaves_=("zz", "nvar"),
    scalar_leaves=("nvar",),
    init=_grm_init,
    update_impl=_grm_update_impl,
    tile_body=_grm_tile_body,
    oracle_similarity=_grm_oracle,
))


# ---------------------------------------------------------- braycurtis

def _braycurtis_runner(job, source, timer):
    from spark_examples_tpu.pipelines import runner

    return runner._run_braycurtis(job, source, timer)


register(Kernel(
    name="braycurtis",
    summary="abundance-table Bray-Curtis dissimilarity (dense-table "
            "path, not the gram accumulator)",
    family="table",
    pack_auto=False,
    # Elementwise |a-b| / (a+b) over all pairs: ~3 N^2 F VPU ops for
    # the exact lowering (the matmul/pallas lowerings trade this for
    # MXU work; see ops/distances.py).
    flops=lambda n, f: 3.0 * n * n * f,
    table_runner=_braycurtis_runner,
))

from spark_examples_tpu.cli.main import main

raise SystemExit(main())

"""Distance / similarity finalization and non-Gram pairwise metrics.

Finalization consumes the accumulated Gram pieces
(:mod:`spark_examples_tpu.ops.gram`) and produces the matrices the
reference's job surface exposed: the SimilarityMatrix entrypoint's
pairwise IBS matrix and the distance matrix the PCoA entrypoint consumes
(SURVEY.md §3.2–3.3). Bray-Curtis — the alternate metric named by
benchmark config 3 (BASELINE.md) — is not a bilinear form, so it gets a
blocked elementwise path (and later a Pallas kernel) instead of matmuls.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax


def finalize(acc: dict, metric: str) -> dict[str, jnp.ndarray]:
    """Accumulators -> {"similarity", "distance"} (N, N) f32 matrices.

    IBS semantics follow the PLINK convention the reference family used:
    over pairwise-complete variants, ``distance = sum|a-b| / (2 * m)`` and
    ``similarity = 1 - distance``; pairs with zero shared valid variants
    get distance 0 (they cannot be distinguished from identical — the
    oracle encodes the same choice so parity tests pin it down).
    """
    if metric == "ibs":
        m = acc["m"]
        dist = jnp.where(m > 0, acc["d1"] / (2.0 * m), 0.0)
        return {"similarity": 1.0 - dist, "distance": dist}
    if metric == "ibs2":
        m = acc["m"]
        sim = jnp.where(m > 0, acc["ibs2"] / m, 1.0)
        return {"similarity": sim, "distance": 1.0 - sim}
    if metric == "shared-alt":
        # The reference PCA driver's similarity: raw shared-alt-carrier
        # counts (centering happens downstream, SURVEY.md §3.1).
        s = acc["s"]
        return {"similarity": s, "distance": similarity_to_distance(s)}
    if metric == "euclidean":
        d = jnp.sqrt(jnp.maximum(acc["e2"], 0.0))
        return {"similarity": -d, "distance": d}
    if metric == "grm":
        g = acc["zz"] / jnp.maximum(acc["nvar"], 1.0)
        return {"similarity": g, "distance": similarity_to_distance(g)}
    if metric == "dot":
        return {"similarity": acc["dot"],
                "distance": similarity_to_distance(acc["dot"])}
    raise ValueError(f"unknown metric {metric!r}")


def similarity_to_distance(s: jnp.ndarray) -> jnp.ndarray:
    """Gower transform: d_ij = sqrt(s_ii + s_jj - 2 s_ij) (>= 0 for PSD s)."""
    diag = jnp.diagonal(s)
    d2 = diag[:, None] + diag[None, :] - 2.0 * s
    return jnp.sqrt(jnp.maximum(d2, 0.0))


@partial(jax.jit, static_argnames=("row_tile", "feat_tile"))
def pairwise_manhattan(
    x: jnp.ndarray, row_tile: int = 128, feat_tile: int = 128
) -> jnp.ndarray:
    """Blocked sum_f |x_i - x_j|: (N, F) -> (N, N).

    Double-tiled so peak memory is ``row_tile * N * feat_tile`` elements
    regardless of F — the feature axis streams exactly like the variant
    axis does in the Gram path. Runs on the VPU (elementwise), not the
    MXU; the Pallas kernel in ops.pallas targets the same contraction.
    """
    n, f = x.shape
    n_pad = -(-n // row_tile) * row_tile
    f_pad = -(-f // feat_tile) * feat_tile
    xp = jnp.pad(x.astype(jnp.float32), ((0, n_pad - n), (0, f_pad - f)))
    k = f_pad // feat_tile
    # (k, N_pad, feat_tile) feature chunks of the full matrix
    cols = xp.reshape(n_pad, k, feat_tile).transpose(1, 0, 2)

    def row_block(rb):  # rb: (row_tile, f_pad)
        a_chunks = rb.reshape(row_tile, k, feat_tile).transpose(1, 0, 2)

        def feat_step(acc, ab):
            a, b = ab  # (row_tile, ft), (n_pad, ft)
            acc = acc + jnp.abs(a[:, None, :] - b[None, :, :]).sum(-1)
            return acc, None

        acc0 = jnp.zeros((row_tile, n_pad), jnp.float32)
        acc, _ = lax.scan(feat_step, acc0, (a_chunks, cols))
        return acc

    blocks = lax.map(row_block, xp.reshape(n_pad // row_tile, row_tile, f_pad))
    return blocks.reshape(n_pad, n_pad)[:n, :n]


@partial(jax.jit, static_argnames=("row_tile", "feat_tile"))
def braycurtis(
    x: jnp.ndarray, row_tile: int = 128, feat_tile: int = 128
) -> jnp.ndarray:
    """Bray-Curtis dissimilarity on a nonnegative (N, F) abundance table.

    BC_ij = sum_f |x_i - x_j| / sum_f (x_i + x_j), the metric of benchmark
    config 3 (10k-sample OTU table, BASELINE.md). Zero-total pairs get 0.
    """
    num = pairwise_manhattan(x, row_tile=row_tile, feat_tile=feat_tile)
    totals = x.astype(jnp.float32).sum(axis=1)
    den = totals[:, None] + totals[None, :]
    return jnp.where(den > 0, num / den, 0.0)

"""Distance / similarity finalization and non-Gram pairwise metrics.

Finalization consumes the accumulated Gram pieces
(:mod:`spark_examples_tpu.ops.gram`) and produces the matrices the
reference's job surface exposed: the SimilarityMatrix entrypoint's
pairwise IBS matrix and the distance matrix the PCoA entrypoint consumes
(SURVEY.md §3.2–3.3). Bray-Curtis — the alternate metric named by
benchmark config 3 (BASELINE.md) — is not a bilinear form, so it gets a
blocked elementwise path (and later a Pallas kernel) instead of matmuls.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax


def finalize(acc: dict, metric: str) -> dict[str, jnp.ndarray]:
    """Raw-product accumulators -> {"similarity", "distance"} (N, N) f32.

    Combines the streamed int32 matmul products into named statistics
    (integer-exact — :func:`spark_examples_tpu.ops.gram.combine`), then
    applies the metric's ratio/transform. IBS semantics follow the PLINK
    convention the reference family used: over pairwise-complete
    variants, ``distance = sum|a-b| / (2 * m)`` and ``similarity = 1 -
    distance``; pairs with zero shared valid variants get distance 0
    (they cannot be distinguished from identical — the oracle encodes the
    same choice so parity tests pin it down).
    """
    from spark_examples_tpu.ops import gram

    stats = gram.combine(acc, metric)
    if metric == "ibs":
        m = stats["m"]
        dist = jnp.where(m > 0, stats["d1"] / (2.0 * m), 0.0)
        return {"similarity": 1.0 - dist, "distance": dist}
    if metric == "ibs2":
        m = stats["m"]
        sim = jnp.where(m > 0, stats["ibs2"] / (1.0 * m), 1.0)
        return {"similarity": sim, "distance": 1.0 - sim}
    if metric == "shared-alt":
        # The reference PCA driver's similarity: raw shared-alt-carrier
        # counts (centering happens downstream, SURVEY.md §3.1).
        s = stats["s"].astype(jnp.float32)
        return {"similarity": s, "distance": similarity_to_distance(s)}
    if metric == "euclidean":
        d = jnp.sqrt(jnp.maximum(stats["e2"].astype(jnp.float32), 0.0))
        return {"similarity": -d, "distance": d}
    if metric == "grm":
        g = stats["zz"] / jnp.maximum(stats["nvar"], 1.0)
        return {"similarity": g, "distance": similarity_to_distance(g)}
    if metric == "dot":
        dot = stats["dot"].astype(jnp.float32)
        return {"similarity": dot, "distance": similarity_to_distance(dot)}
    if metric == "king":
        # KING-robust kinship (Manichaikul 2010, between-family form):
        # phi = (N_AaAa - 2 * N_AA,aa) / (N_Aa(i) + N_Aa(j)), hets
        # counted over pairwise-complete variants. The diagonal lands on
        # 0.5 by construction (hc_ii == hh_ii). Pairs sharing no het
        # variants are uninformative -> phi 0 (unrelated), same spirit
        # as ibs's zero-overlap convention.
        den = (stats["hc"] + stats["hc"].T).astype(jnp.float32)
        num = (stats["hh"] - 2 * stats["opp"]).astype(jnp.float32)
        phi = jnp.where(den > 0, num / den, 0.0)
        # Pin the diagonal to self-kinship 0.5 even for samples with
        # zero het calls (inbred lines, haploid 0/2 coding), whose
        # den_ii = 0 would otherwise fall into the "unrelated" branch —
        # and a nonzero self-distance would poison the Gower centering
        # every downstream PCoA applies.
        n = phi.shape[0]
        phi = jnp.where(jnp.eye(n, dtype=bool), 0.5, phi)
        # Kinship distance: 0.5 - phi (0 for self/MZ, ~0.5 unrelated,
        # clipped: sampling noise can push phi past the 0.5 bound).
        return {"similarity": phi,
                "distance": jnp.maximum(0.5 - phi, 0.0)}
    raise ValueError(f"unknown metric {metric!r}")


def similarity_to_distance(s: jnp.ndarray) -> jnp.ndarray:
    """Gower transform: d_ij = sqrt(s_ii + s_jj - 2 s_ij) (>= 0 for PSD s)."""
    diag = jnp.diagonal(s)
    d2 = diag[:, None] + diag[None, :] - 2.0 * s
    return jnp.sqrt(jnp.maximum(d2, 0.0))


@partial(jax.jit, static_argnames=("row_tile", "feat_tile"))
def pairwise_manhattan(
    x: jnp.ndarray, row_tile: int = 128, feat_tile: int = 128
) -> jnp.ndarray:
    """Blocked sum_f |x_i - x_j|: (N, F) -> (N, N).

    Double-tiled so peak memory is ``row_tile * N * feat_tile`` elements
    regardless of F — the feature axis streams exactly like the variant
    axis does in the Gram path. Runs on the VPU (elementwise), not the
    MXU; the Pallas kernel in ops.pallas targets the same contraction.
    """
    n, f = x.shape
    n_pad = -(-n // row_tile) * row_tile
    f_pad = -(-f // feat_tile) * feat_tile
    xp = jnp.pad(x.astype(jnp.float32), ((0, n_pad - n), (0, f_pad - f)))
    k = f_pad // feat_tile
    # (k, N_pad, feat_tile) feature chunks of the full matrix
    cols = xp.reshape(n_pad, k, feat_tile).transpose(1, 0, 2)

    def row_block(rb):  # rb: (row_tile, f_pad)
        a_chunks = rb.reshape(row_tile, k, feat_tile).transpose(1, 0, 2)

        def feat_step(acc, ab):
            a, b = ab  # (row_tile, ft), (n_pad, ft)
            acc = acc + jnp.abs(a[:, None, :] - b[None, :, :]).sum(-1)
            return acc, None

        acc0 = jnp.zeros((row_tile, n_pad), jnp.float32)
        acc, _ = lax.scan(feat_step, acc0, (a_chunks, cols))
        return acc

    blocks = lax.map(row_block, xp.reshape(n_pad // row_tile, row_tile, f_pad))
    return blocks.reshape(n_pad, n_pad)[:n, :n]


@partial(jax.jit, static_argnames=("row_tile", "feat_tile"))
def braycurtis(
    x: jnp.ndarray, row_tile: int = 128, feat_tile: int = 128
) -> jnp.ndarray:
    """Bray-Curtis dissimilarity on a nonnegative (N, F) abundance table.

    BC_ij = sum_f |x_i - x_j| / sum_f (x_i + x_j), the metric of benchmark
    config 3 (10k-sample OTU table, BASELINE.md). Zero-total pairs get 0.
    Exact, VPU-bound; for large N use :func:`braycurtis_matmul`.
    """
    num = pairwise_manhattan(x, row_tile=row_tile, feat_tile=feat_tile)
    return bc_from_manhattan(num, jnp.asarray(x, jnp.float32).sum(axis=1))


def bc_from_manhattan(num: jnp.ndarray, totals: jnp.ndarray) -> jnp.ndarray:
    """Shared Bray-Curtis finalization: Manhattan numerator + row totals
    -> BC matrix. Pins the zero-total-pair -> 0 convention once for every
    lowering (exact VPU, MXU threshold, Pallas)."""
    den = totals[:, None] + totals[None, :]
    return jnp.where(den > 0, num / den, 0.0)


@partial(jax.jit, static_argnames=("levels", "precise"))
def braycurtis_matmul(
    x: jnp.ndarray, levels: int = 256, precise: bool = False
) -> jnp.ndarray:
    """Bray-Curtis via threshold-decomposed MXU matmuls (TPU-first path).

    The min-sum is not bilinear, but its threshold decomposition is:

        min(a, b) = sum_t  w_t * [a >= v_t] * [b >= v_t]

    Per-feature normalisation to [0, 1] puts every feature on a shared
    ``levels``-point grid; the per-feature scale folds symmetrically into
    the indicators as sqrt(scale/levels), so

        sum_f min = sum_t A_t A_t^T,   A_t = [x_n >= (t+.5)/L] * sqrt(w)

    — ``levels`` (N, F) matmuls that tile onto the MXU at full rate,
    replacing a VPU-bound elementwise pass ~50-100x slower at scale.
    Then BC = (den - 2*minsum) / den with den = totals_i + totals_j.

    Accuracy: quantisation error per feature is at most scale_f / (2L)
    (exact when each feature takes <= L distinct evenly spaced values,
    e.g. integer counts with max < L), plus ~0.4% relative bf16 rounding
    on the folded weights (``precise=True`` runs f32 matmuls at half MXU
    rate to remove the latter).
    """
    if levels < 1:
        raise ValueError(f"braycurtis levels must be >= 1, got {levels}")
    dt = jnp.float32 if precise else jnp.bfloat16
    x = jnp.maximum(x, 0).astype(jnp.float32)
    n, f = x.shape
    scale = x.max(axis=0)
    xn = jnp.where(scale > 0, x / jnp.maximum(scale, 1e-30), 0.0)
    sw = jnp.sqrt(scale / levels).astype(dt)

    # Batch CHUNK thresholds into one matmul: K = F * CHUNK keeps the MXU
    # fed with fat contractions instead of `levels` skinny ones. The grid
    # is padded to a chunk multiple with sentinel thresholds > 1 whose
    # indicators are identically zero, so a ragged tail contributes 0.
    chunk = max(1, min(8, levels))
    n_iters = -(-levels // chunk)
    thr_grid = (jnp.arange(n_iters * chunk, dtype=jnp.float32) + 0.5) / levels
    thr_grid = jnp.where(thr_grid < 1.0, thr_grid, 2.0)

    def body(c, acc):
        thr = jax.lax.dynamic_slice(thr_grid, (c * chunk,), (chunk,))
        # (N, F, CHUNK) indicators, folded weights, flattened to (N, F*CHUNK)
        a = (xn[:, :, None] >= thr[None, None, :]).astype(dt)
        a = (a * sw[None, :, None]).reshape(n, f * chunk)
        return acc + jax.lax.dot_general(
            a, a, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )

    minsum = jax.lax.fori_loop(0, n_iters, body, jnp.zeros((n, n), jnp.float32))
    totals = x.sum(axis=1)
    den = totals[:, None] + totals[None, :]
    num = jnp.maximum(den - 2.0 * minsum, 0.0)
    return bc_from_manhattan(num, totals)

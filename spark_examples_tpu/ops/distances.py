"""Distance / similarity finalization and non-Gram pairwise metrics.

Finalization consumes the accumulated Gram pieces
(:mod:`spark_examples_tpu.ops.gram`) and produces the matrices the
reference's job surface exposed: the SimilarityMatrix entrypoint's
pairwise IBS matrix and the distance matrix the PCoA entrypoint consumes
(SURVEY.md §3.2–3.3). Bray-Curtis — the alternate metric named by
benchmark config 3 (BASELINE.md) — is not a bilinear form, so it gets a
blocked elementwise path (and later a Pallas kernel) instead of matmuls.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax


def finalize(acc: dict, metric: str) -> dict[str, jnp.ndarray]:
    """Raw-product accumulators -> {"similarity", "distance"} (N, N) f32.

    Combines the streamed int32 matmul products into named statistics
    (integer-exact — :func:`spark_examples_tpu.ops.gram.combine`), then
    applies the kernel's declared finalize (its ratio/transform —
    spark_examples_tpu/kernels, each registration documents its
    conventions; e.g. IBS follows the PLINK convention the reference
    family used: ``distance = sum|a-b| / (2 * m)`` over pairwise-
    complete variants, zero-overlap pairs -> distance 0, and the CPU
    oracle mirrors the same choices via the kernel's ``np_finalize``).
    """
    from spark_examples_tpu import kernels
    from spark_examples_tpu.ops import gram

    kern = kernels.maybe_get(metric)
    if kern is None or kern.finalize is None:
        raise ValueError(
            f"unknown metric {metric!r}; finalizable kernels: "
            f"{' | '.join(sorted(kernels.gram_names()))}"
        )
    stats = gram.combine(acc, metric)
    return kern.finalize(stats)


def similarity_to_distance(s: jnp.ndarray) -> jnp.ndarray:
    """Gower transform: d_ij = sqrt(s_ii + s_jj - 2 s_ij) (>= 0 for PSD s)."""
    diag = jnp.diagonal(s)
    d2 = diag[:, None] + diag[None, :] - 2.0 * s
    return jnp.sqrt(jnp.maximum(d2, 0.0))


@partial(jax.jit, static_argnames=("row_tile", "feat_tile"))
def pairwise_manhattan(
    x: jnp.ndarray, row_tile: int = 128, feat_tile: int = 128
) -> jnp.ndarray:
    """Blocked sum_f |x_i - x_j|: (N, F) -> (N, N).

    Double-tiled so peak memory is ``row_tile * N * feat_tile`` elements
    regardless of F — the feature axis streams exactly like the variant
    axis does in the Gram path. Runs on the VPU (elementwise), not the
    MXU; the Pallas kernel in ops.pallas targets the same contraction.
    """
    n, f = x.shape
    n_pad = -(-n // row_tile) * row_tile
    f_pad = -(-f // feat_tile) * feat_tile
    xp = jnp.pad(x.astype(jnp.float32), ((0, n_pad - n), (0, f_pad - f)))
    k = f_pad // feat_tile
    # (k, N_pad, feat_tile) feature chunks of the full matrix
    cols = xp.reshape(n_pad, k, feat_tile).transpose(1, 0, 2)

    def row_block(rb):  # rb: (row_tile, f_pad)
        a_chunks = rb.reshape(row_tile, k, feat_tile).transpose(1, 0, 2)

        def feat_step(acc, ab):
            a, b = ab  # (row_tile, ft), (n_pad, ft)
            acc = acc + jnp.abs(a[:, None, :] - b[None, :, :]).sum(-1)
            return acc, None

        acc0 = jnp.zeros((row_tile, n_pad), jnp.float32)
        acc, _ = lax.scan(feat_step, acc0, (a_chunks, cols))
        return acc

    blocks = lax.map(row_block, xp.reshape(n_pad // row_tile, row_tile, f_pad))
    return blocks.reshape(n_pad, n_pad)[:n, :n]


@partial(jax.jit, static_argnames=("row_tile", "feat_tile"))
def braycurtis(
    x: jnp.ndarray, row_tile: int = 128, feat_tile: int = 128
) -> jnp.ndarray:
    """Bray-Curtis dissimilarity on a nonnegative (N, F) abundance table.

    BC_ij = sum_f |x_i - x_j| / sum_f (x_i + x_j), the metric of benchmark
    config 3 (10k-sample OTU table, BASELINE.md). Zero-total pairs get 0.
    Exact, VPU-bound; for large N use :func:`braycurtis_matmul`.
    """
    num = pairwise_manhattan(x, row_tile=row_tile, feat_tile=feat_tile)
    return bc_from_manhattan(num, jnp.asarray(x, jnp.float32).sum(axis=1))


def bc_from_manhattan(num: jnp.ndarray, totals: jnp.ndarray) -> jnp.ndarray:
    """Shared Bray-Curtis finalization: Manhattan numerator + row totals
    -> BC matrix. Pins the zero-total-pair -> 0 convention once for every
    lowering (exact VPU, MXU threshold, Pallas)."""
    den = totals[:, None] + totals[None, :]
    return jnp.where(den > 0, num / den, 0.0)


@partial(jax.jit, static_argnames=("levels", "precise"))
def braycurtis_matmul(
    x: jnp.ndarray, levels: int = 256, precise: bool = False
) -> jnp.ndarray:
    """Bray-Curtis via threshold-decomposed MXU matmuls (TPU-first path).

    The min-sum is not bilinear, but its threshold decomposition is:

        min(a, b) = sum_t  w_t * [a >= v_t] * [b >= v_t]

    Per-feature normalisation to [0, 1] puts every feature on a shared
    ``levels``-point grid; the per-feature scale folds symmetrically into
    the indicators as sqrt(scale/levels), so

        sum_f min = sum_t A_t A_t^T,   A_t = [x_n >= (t+.5)/L] * sqrt(w)

    — ``levels`` (N, F) matmuls that tile onto the MXU at full rate,
    replacing a VPU-bound elementwise pass ~50-100x slower at scale.
    Then BC = (den - 2*minsum) / den with den = totals_i + totals_j.

    Accuracy: quantisation error per feature is at most scale_f / (2L)
    (exact when each feature takes <= L distinct evenly spaced values,
    e.g. integer counts with max < L), plus ~0.4% relative bf16 rounding
    on the folded weights (``precise=True`` runs f32 matmuls at half MXU
    rate to remove the latter).
    """
    if levels < 1:
        raise ValueError(f"braycurtis levels must be >= 1, got {levels}")
    dt = jnp.float32 if precise else jnp.bfloat16
    x = jnp.maximum(x, 0).astype(jnp.float32)
    n, f = x.shape
    scale = x.max(axis=0)
    xn = jnp.where(scale > 0, x / jnp.maximum(scale, 1e-30), 0.0)
    sw = jnp.sqrt(scale / levels).astype(dt)

    # Batch CHUNK thresholds into one matmul: K = F * CHUNK keeps the MXU
    # fed with fat contractions instead of `levels` skinny ones. The grid
    # is padded to a chunk multiple with sentinel thresholds > 1 whose
    # indicators are identically zero, so a ragged tail contributes 0.
    chunk = max(1, min(8, levels))
    n_iters = -(-levels // chunk)
    thr_grid = (jnp.arange(n_iters * chunk, dtype=jnp.float32) + 0.5) / levels
    thr_grid = jnp.where(thr_grid < 1.0, thr_grid, 2.0)

    def body(c, acc):
        thr = jax.lax.dynamic_slice(thr_grid, (c * chunk,), (chunk,))
        # (N, F, CHUNK) indicators, folded weights, flattened to (N, F*CHUNK)
        a = (xn[:, :, None] >= thr[None, None, :]).astype(dt)
        a = (a * sw[None, :, None]).reshape(n, f * chunk)
        return acc + jax.lax.dot_general(
            a, a, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )

    minsum = jax.lax.fori_loop(0, n_iters, body, jnp.zeros((n, n), jnp.float32))
    totals = x.sum(axis=1)
    den = totals[:, None] + totals[None, :]
    num = jnp.maximum(den - 2.0 * minsum, 0.0)
    return bc_from_manhattan(num, totals)

from spark_examples_tpu.ops import centering, distances, eigh, genotype, gram  # noqa: F401

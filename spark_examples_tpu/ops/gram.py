"""Blocked, streaming Gram/similarity accumulation — the reference's
shuffle stage, rebuilt as FMA into resident accumulators.

Reference semantics (SURVEY.md §3.1): per-variant pair emission →
``reduceByKey`` over the netty shuffle → N x N similarity assembled on the
driver. The associativity that made reduceByKey work is the same property
exploited here: every pairwise statistic is a sum over variants, so the
driver streams (N, v_blk) dosage blocks through the chip and adds each
block's raw matmul products
(:func:`~spark_examples_tpu.ops.genotype.gram_products`) into **int32**
accumulators resident in HBM. The combination algebra (Manhattan sums,
IBS2 expansion — anything involving transposes or subtractions) runs once
at finalize (:func:`combine`), not per block, so the hot loop is pure
matmul + integer add: bit-exact for < 2^29 variants on dosage inputs
(worst per-variant increment is 4; arbitrary int8 tables have a m^2
increment bound the runner checks) and free of per-block N x N
relayouts. The 40M-variant axis never materialises on device — only
one block plus the N x N state (SURVEY.md §5 "Long-context").

Two block transforms live here:

- :func:`update` / :func:`update_packed` — per-kernel accumulation:
  raw products for the counting family (IBS / shared-alt / euclidean /
  IBS2 families, all pairwise-complete over missing data), the kernel's
  declared float update for the float family (GRM: VanRaden/GCTA form —
  per-variant allele frequency estimated *within the block*, dosages
  centered by 2p and scaled by 1/sqrt(2p(1-p)), missing mean-imputed to
  zero contribution, accumulated as Z Z^T in f32).
"""

from __future__ import annotations

from functools import lru_cache, partial

import jax
import jax.numpy as jnp

from spark_examples_tpu import kernels
from spark_examples_tpu.core.dtypes import COMPUTE_DTYPE
from spark_examples_tpu.ops import genotype

# Which raw matmul products each counting metric accumulates — DERIVED
# from the kernel registry (spark_examples_tpu/kernels), the single
# source of truth. Each product is one int8 x int8 -> int32 dot; the
# per-metric statistic is assembled from them once, in combine().
# NOTE: these module-level dicts are an import-time VIEW for
# introspection (tests, bench, shardings); the dispatch functions below
# read the live registry through _check_metric, so a kernel registered
# after this module imported still routes correctly.
PIECES_FOR_METRIC: dict[str, tuple[str, ...]] = {
    k.name: k.pieces for k in kernels.all_kernels() if k.family == "count"
}

# Statistics (genotype.combine_products names) each metric's finalize needs.
STATS_FOR_METRIC: dict[str, tuple[str, ...]] = {
    k.name: k.stats for k in kernels.all_kernels() if k.family == "count"
}

GRAM_METRICS = kernels.gram_names()

# Metrics whose inputs are genotype dosages *by definition* — safe to ship
# 2-bit packed under pack_stream="auto" (the kernel's pack_auto flag).
# dot/euclidean compute exact raw-value products for arbitrary int8
# tables (values >= 0; negatives are missing), which the 2-bit codec
# cannot represent, so auto keeps them on the dense transport.
DOSAGE_METRICS = tuple(
    k.name for k in kernels.all_kernels() if k.is_gram and k.pack_auto
)

# int32 accumulator budget: worst per-variant increment by metric, for
# the runner's exactness guard (increment * n_variants must stay < 2^31).
# Kernels with value_scaled_budget (dot/euclidean) depend on the table's
# max value m (bound m^2); the registered value is the dosage-domain
# bound, the runner scales it by the observed max when the stream is
# dense. Float-accumulating kernels (grm) are exempt (absent here).
MAX_INCREMENT: dict[str, int] = {
    k.name: k.max_increment for k in kernels.all_kernels()
    if k.max_increment is not None
}


def flops_per_block(n: int, v: int, metric: str) -> float:
    """Matmul FLOPs one block contributes (for GFLOPS reporting) — the
    kernel's declared FLOPs model (for counting kernels: one matmul per
    ``genotype._INT8_SPLIT`` term of each product, so euclidean is 3,
    not 2). ``v`` is the TRUE streamed variant span (meta.stop -
    meta.start), not the padded device width: pad lanes — packed-byte
    round-up, shard-grid padding — are missing calls that credit no
    work, so reference and fused lowerings divide by the same honest
    denominator in every throughput column."""
    kern = kernels.maybe_get(metric)
    if kern is None or kern.flops is None:
        return 2.0 * n * n * v  # one plain matmul (legacy fallback)
    return kern.flops(n, v)


def _check_metric(metric: str) -> "kernels.Kernel":
    kern = kernels.maybe_get(metric)
    if kern is None or not kern.is_gram:
        raise ValueError(
            f"unknown gram metric {metric!r}; valid: {sorted(GRAM_METRICS)} "
            "(braycurtis runs via distances.braycurtis, not the gram path)"
        )
    return kern


def acc_leaves(metric: str) -> tuple[str, ...]:
    """Accumulator leaf names for a gram metric (checkpoint schema)."""
    return _check_metric(metric).acc_leaves


def init(n: int, metric: str) -> dict[str, jnp.ndarray]:
    """Fresh zero accumulators for ``metric`` on the default device."""
    kern = _check_metric(metric)
    if kern.family == "float":
        return kern.init(n)
    return {k: jnp.zeros((n, n), jnp.int32) for k in kern.pieces}


def _update_impl(acc, block, pieces: tuple[str, ...]):
    g = genotype.gram_products(block, pieces)
    return {k: acc[k] + g[k] for k in pieces}


def _update_packed_impl(acc, packed, pieces: tuple[str, ...]):
    """Same contribution from a 2-bit packed (N, v_blk/4) uint8 block.

    The shift/mask unpack (ingest/bitpack.py) fuses into the indicator
    thresholds under jit; shipping packed blocks quarters host→device
    traffic — the binding constraint at the 40M-variant north star.
    """
    from spark_examples_tpu.ingest.bitpack import unpack_dosages

    return _update_impl(acc, unpack_dosages(packed), pieces)


def _update_fused_impl(acc, packed, metric: str):
    """Fused-lowering twin of :func:`_update_packed_impl`: the kernel's
    registered Pallas body consumes the 2-bit bytes directly (decode +
    mask + contract in one VMEM pass — ops/pallas/packed_gram.py), so
    no u8 dosage or indicator operand materialises in HBM. Bit-identical
    to the reference path for the int32 accumulators (asserted per
    kernel/transport by the tier-1 parity suites)."""
    kern = _check_metric(metric)
    prods = kern.fused_body(packed, packed)
    return {k: acc[k] + prods[k] for k in kern.pieces}


def fused_capable(metric: str, packed: bool) -> bool:
    """Can this metric/transport pair run the fused Pallas lowering?"""
    kern = kernels.maybe_get(metric)
    return bool(packed and kern is not None and kern.is_gram
                and kern.fused_body is not None)


def resolve_gram_lowering(requested: str, metric: str, packed: bool,
                          n_devices: int = 1,
                          plan_mode: str = "replicated",
                          platform: str | None = None) -> str:
    """Resolve ``--gram-lowering`` to the lowering actually run.

    ``auto`` follows the shared :func:`kernels.resolve_lowering` rule
    (fused on real TPU hardware, reference elsewhere) and silently
    downgrades to reference when the combination cannot run fused (no
    registered fused_body, dense stream, or a multi-device variant-mode
    plan — the SPMD partitioner cannot split a pallas_call, so fused
    tiles run per device inside the tile2d shard_map only). An explicit
    ``fused`` raises instead, naming the blocker and the fix.
    """
    variant_multi = plan_mode == "variant" and n_devices > 1
    if requested == "fused":
        kernels.check_fused_lowering(metric, packed)
        if variant_multi:
            raise ValueError(
                "--gram-lowering fused runs the Pallas tile kernel per "
                "device inside the tile2d shard_map; a multi-device "
                "variant-mode plan partitions ONE jitted update across "
                "chips, which cannot split a pallas_call — use "
                "--gram-mode tile2d (or a single-device mesh), or "
                "--gram-lowering auto|reference"
            )
        return "fused"
    if platform is None:
        platform = jax.default_backend()
    choice = kernels.resolve_lowering(requested, platform, "fused",
                                      "reference")
    if choice == "fused" and (not fused_capable(metric, packed)
                              or variant_multi):
        return "reference"
    return choice


def grm_standardize(block: jnp.ndarray, precise: bool = False):
    """VanRaden standardization of one dosage block: ``(z, keep)``.

    Per-variant allele frequency estimated *within the block*, dosages
    centered by 2p and scaled by 1/sqrt(2p(1-p)), missing mean-imputed
    to zero contribution; ``keep`` masks variants with usable
    frequencies (kept count feeds the GRM denominator). The single
    definition shared by the dense update here and the tile2d shard_map
    body (parallel/gram_sharded) — the two must never diverge.

    ``precise``: emit f32 ``z`` instead of bf16 — bf16 rounds GRM
    entries at ~1e-3 relative (the standardized dosages are continuous,
    unlike the exact {0,1} indicators of the counting metrics); f32
    matmuls run at roughly half MXU rate.
    """
    p, cnt, y, valid = genotype.af_stats(block)
    denom = 2.0 * p * (1.0 - p)
    keep = (denom > 1e-8) & (cnt > 1)
    scale = jnp.where(keep, jax.lax.rsqrt(jnp.maximum(denom, 1e-8)), 0.0)
    dt = jnp.float32 if precise else COMPUTE_DTYPE
    z = jnp.where(valid, (y - 2.0 * p) * scale, 0.0).astype(dt)
    return z, keep


def _update_grm_impl(acc: dict, block: jnp.ndarray, precise: bool = False) -> dict:
    """VanRaden-form GRM accumulation (see :func:`grm_standardize`)."""
    z, keep = grm_standardize(block, precise)
    zz = jax.lax.dot_general(
        z, z, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    )
    return {"zz": acc["zz"] + zz, "nvar": acc["nvar"] + keep.sum()}


def _update_grm_packed_impl(acc: dict, packed, precise: bool = False) -> dict:
    from spark_examples_tpu.ingest.bitpack import unpack_dosages

    return _update_grm_impl(acc, unpack_dosages(packed), precise)


def impl_for(metric: str, packed: bool, grm_precise: bool = False,
             lowering: str = "reference"):
    """The one dispatch point: unjitted ``(acc, block) -> acc`` for a
    metric/transport/lowering triple, pieces already bound. Every
    jitted wrapper (here and the sharded planner) derives from this.

    ``grm_precise``: run the GRM's Z Z^T in f32 instead of bf16 (half
    MXU rate, ~1e-3 better relative accuracy); ignored by the exact
    integer metrics.

    ``lowering``: already RESOLVED (:func:`resolve_gram_lowering`) —
    "fused" routes the packed count-family update through the kernel's
    registered Pallas body; float-family kernels ignore it (grm has no
    fused lowering; auto never resolves to one for it).
    """
    kern = _check_metric(metric)
    if kern.family == "float":
        return partial(kern.update_impl(packed), precise=grm_precise)
    if lowering == "fused":
        kernels.check_fused_lowering(metric, packed)
        return partial(_update_fused_impl, metric=metric)
    impl = _update_packed_impl if packed else _update_impl
    return partial(impl, pieces=kern.pieces)


_update = partial(jax.jit, static_argnames=("pieces",), donate_argnums=(0,))(
    _update_impl
)
_update_packed = partial(
    jax.jit, static_argnames=("pieces",), donate_argnums=(0,)
)(_update_packed_impl)
_update_fused = partial(
    jax.jit, static_argnames=("metric",), donate_argnums=(0,)
)(_update_fused_impl)
@lru_cache(maxsize=32)
def _float_update_jit(metric: str, packed: bool):
    """Jitted, donating convenience update for a float-family kernel —
    built from the kernel's declared impl, so a second float kernel
    gets the same jit/donation treatment as grm with no literal here."""
    return partial(jax.jit, static_argnames=("precise",),
                   donate_argnums=(0,))(_check_metric(metric)
                                        .update_impl(packed))


def update(acc: dict, block: jnp.ndarray, metric: str) -> dict:
    """Add one (N, v_blk) int8 dosage block's contribution to ``acc``."""
    kern = _check_metric(metric)
    if kern.family == "float":
        return _float_update_jit(metric, False)(acc, block)
    return _update(acc, block, kern.pieces)


def update_packed(acc: dict, packed: jnp.ndarray, metric: str) -> dict:
    """Packed-block twin of :func:`update`."""
    kern = _check_metric(metric)
    if kern.family == "float":
        return _float_update_jit(metric, True)(acc, packed)
    return _update_packed(acc, packed, kern.pieces)


def update_fused(acc: dict, packed: jnp.ndarray, metric: str) -> dict:
    """Fused-lowering twin of :func:`update_packed`: the kernel's
    registered Pallas body contracts the 2-bit bytes directly —
    bit-identical int32 accumulators, no HBM dosage expansion."""
    kernels.check_fused_lowering(metric, True)
    return _update_fused(acc, packed, metric=metric)


def combine(acc: dict, metric: str) -> dict[str, jnp.ndarray]:
    """Accumulated raw products -> the named statistics ``finalize``
    consumes (integer-exact; runs once per job). Float-family kernels'
    accumulators (GRM) pass through unchanged (already in statistic
    form)."""
    kern = _check_metric(metric)
    if kern.family == "float":
        return acc
    return genotype.combine_products(acc, kern.stats)

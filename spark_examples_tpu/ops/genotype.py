"""Genotype-block transforms: the matmul reformulation of pair counting.

This module is the heart of the parity story. The reference built its
pairwise similarity by *pair emission + reduceByKey*: for each variant,
emit a count for every pair of samples sharing a genotype state, shuffle,
and sum (SURVEY.md §3.1 HOT LOOP #2 — O(variants x carriers^2) pair
emission). That shape is hostile to an MXU. The TPU-native reformulation
turns the same counts into a handful of matmuls.

For a dosage block ``G`` of shape (N, V) with values {0, 1, 2, -1=missing},
define int indicator matrices:

    C  = [G >= 0]   valid (non-missing) call
    T1 = [G >= 1]   carries at least one alt allele
    T2 = [G >= 2]   homozygous alt

plus the derived operands Y = T1 + T2 (masked dosage, {0,1,2}) and
Q = T1 + 3 T2 (masked squared dosage, {0,1,4}) that fold multiple
indicator products into one matmul. Every pairwise co-occurrence count
the reference's reduceByKey produced is a bilinear form in these
operands; the *raw products* (``cc``, ``yc``, ``t1t1``, …) are what gets
accumulated across blocks, and the final statistics (valid-pair count M,
Manhattan sum D1, IBS2 count, squared euclidean, …) are assembled ONCE in
:func:`combine_products` — not per block. Two wins:

- the hot loop is pure matmul + add (no per-block N x N transposes or
  combination algebra on the accumulators);
- products of {0,1}/{0..4} int8 operands accumulate in **int32**, so
  every count is *bit-exact* out to at least 2^29 variants (the worst
  per-variant increment is 4, from yy/qc) — ~13x past the 40M-variant
  north star, where f32 accumulators would round (f32 mantissa is
  24 bits ≈ 1.7e7).

The 40M-long variant axis streams through in blocks and never
materialises on device (SURVEY.md §5 "Long-context").
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

# raw product name -> (left operand, right operand); each is one
# ``A B^T`` dot_general with int32 accumulation.
PRODUCT_OPERANDS: dict[str, tuple[str, str]] = {
    "cc": ("c", "c"),
    "t1c": ("t1", "c"),
    "yc": ("y", "c"),
    "qc": ("q", "c"),
    "yy": ("y", "y"),
    "t1t1": ("t1", "t1"),
    "t1t2": ("t1", "t2"),
    "t2t2": ("t2", "t2"),
}

# statistic -> raw products it needs (mirrored by the CPU oracle).
PIECE_PRODUCTS: dict[str, tuple[str, ...]] = {
    "m": ("cc",),
    "s": ("t1t1",),
    "d1": ("yc", "t1t1", "t2t2"),
    "ibs2": ("cc", "t1c", "t1t1", "t1t2", "t2t2"),
    "dot": ("yy",),
    "e2": ("qc", "yy"),
}


def operands(block: jnp.ndarray, dtype=jnp.int8) -> dict[str, jnp.ndarray]:
    """(N, V) int8 dosages -> the five matmul operands, int8.

    Missing (-1) contributes zero to every operand, which is what gives
    the pairwise-complete semantics: a pair's statistics at a variant
    count only when *both* calls are valid (product of indicators).
    """
    c = (block >= 0).astype(dtype)
    t1 = (block >= 1).astype(dtype)
    t2 = (block >= 2).astype(dtype)
    return {"c": c, "t1": t1, "t2": t2, "y": t1 + t2, "q": t1 + 3 * t2}


def _xxt(a: jnp.ndarray, b: jnp.ndarray, accum_dtype) -> jnp.ndarray:
    """``a @ b^T`` with on-MXU accumulation — one (N, V) x (V, N) dot."""
    return jax.lax.dot_general(
        a,
        b,
        dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=accum_dtype,
    )


def gram_products(
    block: jnp.ndarray,
    products: tuple[str, ...],
    accum_dtype=jnp.int32,
) -> dict[str, jnp.ndarray]:
    """Per-block raw products: int8 operands, int32 (N, N) outputs.

    Only the requested products' matmuls are emitted — IBS costs exactly
    4 (cc, yc, t1t1, t2t2), shared-alt 1, euclidean 2. Each product is
    additive across variant blocks, so the streaming driver FMAs them
    into resident int32 accumulators — exact to >= 2^29 variants (worst
    per-variant increment is 4, from yy/qc).

    The optimization barrier materialises each operand once: without it,
    XLA fuses the threshold computation into every dot's operand read, so
    each indicator is recomputed by every matmul that consumes it and the
    VPU work throttles the MXU pipeline (measured ~30% throughput loss on
    the 4-product IBS update).
    """
    ops = operands(block)
    used = sorted({o for p in products for o in PRODUCT_OPERANDS[p]})
    ops = dict(zip(used, jax.lax.optimization_barrier(
        tuple(ops[o] for o in used)
    )))
    return {
        p: _xxt(ops[PRODUCT_OPERANDS[p][0]], ops[PRODUCT_OPERANDS[p][1]],
                accum_dtype)
        for p in products
    }


def combine_products(
    prod: dict[str, jnp.ndarray], pieces: tuple[str, ...]
) -> dict[str, jnp.ndarray]:
    """Accumulated raw products -> named pairwise statistics.

    Runs ONCE per job (inside finalize), in integer arithmetic — the
    subtractions (e.g. D1 = YC + YC^T − 2(T1T1 + T2T2)) are exact, no
    cancellation error. Each statistic:

      ``m``   — valid-pair counts            C C^T
      ``s``   — shared-alt counts            T1 T1^T
      ``d1``  — Manhattan (sum |a-b|)        YC + YC^T − 2(T1T1 + T2T2)
                (|a−b| = a+b−2·min(a,b); min-sum = T1T1^T + T2T2^T)
      ``ibs2``— exact-match counts           Σ_g X_g X_g^T expanded into
                indicator products (X0 = C−T1, X1 = T1−T2, X2 = T2)
      ``dot`` — dosage inner products        Y Y^T
      ``e2``  — squared euclidean            QC + QC^T − 2 Y Y^T
    """
    out = {}
    for piece in pieces:
        if piece == "m":
            out["m"] = prod["cc"]
        elif piece == "s":
            out["s"] = prod["t1t1"]
        elif piece == "d1":
            p = prod["t1t1"] + prod["t2t2"]
            out["d1"] = prod["yc"] + _t(prod["yc"]) - 2 * p
        elif piece == "ibs2":
            out["ibs2"] = (
                prod["cc"] - prod["t1c"] - _t(prod["t1c"])
                + 2 * prod["t1t1"] - prod["t1t2"] - _t(prod["t1t2"])
                + 2 * prod["t2t2"]
            )
        elif piece == "dot":
            out["dot"] = prod["yy"]
        elif piece == "e2":
            out["e2"] = prod["qc"] + _t(prod["qc"]) - 2 * prod["yy"]
        else:
            raise ValueError(f"unknown gram piece {piece!r}")
    return out


def _t(a):
    """Transpose that works for both jnp and np arrays."""
    return a.T if hasattr(a, "T") else jnp.transpose(a)


def gram_pieces(block: jnp.ndarray, accum_dtype=jnp.int32) -> dict[str, jnp.ndarray]:
    """One-shot per-block statistics (all six) — test/oracle convenience;
    the streaming path uses :func:`gram_products` + a single deferred
    :func:`combine_products` instead."""
    pieces = tuple(PIECE_PRODUCTS)
    needed = tuple(
        sorted({p for piece in pieces for p in PIECE_PRODUCTS[piece]})
    )
    return combine_products(gram_products(block, needed, accum_dtype), pieces)

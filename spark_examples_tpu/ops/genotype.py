"""Genotype-block transforms: the matmul reformulation of pair counting.

This module is the heart of the parity story. The reference built its
pairwise similarity by *pair emission + reduceByKey*: for each variant,
emit a count for every pair of samples sharing a genotype state, shuffle,
and sum (SURVEY.md §3.1 HOT LOOP #2 — O(variants x carriers^2) pair
emission). That shape is hostile to an MXU. The TPU-native reformulation
turns the same counts into three matmuls.

For a dosage block ``G`` of shape (N, V) with values {0, 1, 2, -1=missing},
define int indicator matrices (computed in :func:`thresholds`):

    C  = [G >= 0]   valid (non-missing) call
    T1 = [G >= 1]   carries at least one alt allele
    T2 = [G >= 2]   homozygous alt

Every pairwise co-occurrence count the reference's reduceByKey produced is
a bilinear form in {C, T1, T2} (one-hot states are X0 = C - T1,
X1 = T1 - T2, X2 = T2):

    valid pair count        M    = C  C^T
    shared-alt count        S    = T1 T1^T            (the reference PCA
                                   driver's similarity: #variants where
                                   both samples carry >=1 alt)
    sum of dosages a+b      A+A^T with A = (T1+T2) C^T
    sum of min(a, b)        P    = T1 T1^T + T2 T2^T
    Manhattan sum |a-b|     D1   = A + A^T - 2 P      (|a-b| = a+b-2min)
    IBS2 count (a == b)     sum_g X_g X_g^T  — expands into the six
                            products of {C, T1, T2}

so a *single* stacked matmul ``Z Z^T`` with ``Z = concat([C, T1, T2])``
(or the six unique pairwise products in blocked form) yields every
statistic. All downstream metrics (ops.distances) consume these Gram
pieces; the full-matrix algebra never touches per-variant state again —
exactly the associative-accumulation property the reference exploited via
reduceByKey, now exploited via blocked FMA into an N x N accumulator
(SURVEY.md §5 "Long-context": the 40M-variant axis is streamed).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from spark_examples_tpu.core.dtypes import COMPUTE_DTYPE


def thresholds(block: jnp.ndarray, dtype=COMPUTE_DTYPE):
    """(N, V) int8 dosages -> stacked (3, N, V) indicators [C, T1, T2].

    Missing (-1) contributes zero to every indicator, which is what gives
    the pairwise-complete semantics: a pair's statistics at a variant
    count only when *both* calls are valid (product of indicators).
    """
    c = (block >= 0).astype(dtype)
    t1 = (block >= 1).astype(dtype)
    t2 = (block >= 2).astype(dtype)
    return jnp.stack([c, t1, t2])


def _xxt(a: jnp.ndarray, b: jnp.ndarray, accum_dtype) -> jnp.ndarray:
    """``a @ b^T`` with f32 MXU accumulation — one (N, V) x (V, N) dot."""
    return jax.lax.dot_general(
        a,
        b,
        dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=accum_dtype,
    )


def gram_pieces(block: jnp.ndarray, accum_dtype=jnp.float32) -> dict[str, jnp.ndarray]:
    """Per-block contributions to the named pairwise statistics.

    Returns a dict of (N, N) f32 arrays:
      ``m``   — valid-pair counts            C C^T
      ``s``   — shared-alt counts            T1 T1^T
      ``d1``  — Manhattan (sum |a-b|)        A + A^T - 2 P
      ``ibs2``— exact-match counts           sum_g X_g X_g^T
      ``dot`` — dosage inner products        Y Y^T (Y = masked dosage)
      ``e2``  — squared euclidean over valid pairs

    Dots are taken against *derived operands* where that saves MXU work:
    Y = T1 + T2 (masked dosage) and Q = T1 + 3 T2 (masked squared dosage)
    fold what would be two or three indicator products into one matmul —
    e.g. sum of dosages over valid pairs is one Y C^T dot, and the
    squared-euclidean piece is Q C^T + C Q^T - 2 Y Y^T, two dots total.
    Every product is a separate ``dot_general`` so that, under ``jit``,
    products feeding only unselected pieces are dead-code-eliminated:
    IBS compiles to exactly 4 matmuls (C C^T, Y C^T, T1 T1^T, T2 T2^T),
    euclidean to 2, the dosage Gram to 1.

    Each piece is additive across variant blocks, so the streaming driver
    just FMAs them into resident accumulators.
    """
    c, t1, t2 = thresholds(block)
    y = t1 + t2  # masked dosage: {0, 1, 2}, missing -> 0
    q = t1 + 3.0 * t2  # masked squared dosage: {0, 1, 4}

    cc = _xxt(c, c, accum_dtype)
    yc = _xxt(y, c, accum_dtype)
    qc = _xxt(q, c, accum_dtype)
    yy = _xxt(y, y, accum_dtype)
    t1c = _xxt(t1, c, accum_dtype)
    t1t1 = _xxt(t1, t1, accum_dtype)
    t1t2 = _xxt(t1, t2, accum_dtype)
    t2t2 = _xxt(t2, t2, accum_dtype)

    p = t1t1 + t2t2  # sum of min(a, b) over valid pairs
    d1 = yc + yc.T - 2.0 * p
    # IBS2 = sum over one-hot states; expand (C-T1)(C-T1)^T + (T1-T2)(T1-T2)^T
    # + T2 T2^T in indicator products.
    ibs2 = (
        cc - t1c.T - t1c + 2.0 * t1t1 - t1t2 - t1t2.T + 2.0 * t2t2
    )
    e2 = qc + qc.T - 2.0 * yy
    return {"m": cc, "s": t1t1, "d1": d1, "ibs2": ibs2, "dot": yy, "e2": e2}

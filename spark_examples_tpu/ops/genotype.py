"""Genotype-block transforms: the matmul reformulation of pair counting.

This module is the heart of the parity story. The reference built its
pairwise similarity by *pair emission + reduceByKey*: for each variant,
emit a count for every pair of samples sharing a genotype state, shuffle,
and sum (SURVEY.md §3.1 HOT LOOP #2 — O(variants x carriers^2) pair
emission). That shape is hostile to an MXU. The TPU-native reformulation
turns the same counts into a handful of matmuls.

For a dosage block ``G`` of shape (N, V) with values {0, 1, 2, -1=missing},
define int indicator matrices:

    C  = [G >= 0]   valid (non-missing) call
    T1 = [G >= 1]   carries at least one alt allele
    T2 = [G >= 2]   homozygous alt

plus derived operands: Y = T1 + T2 (clipped dosage, {0,1,2} — used only
by the dosage-defined IBS family), YR = the *raw* masked value (exact for
arbitrary int8 tables, e.g. count matrices fed to ``dot``/``euclidean``)
and QR = YR^2 (int16; up to 127^2). Every pairwise co-occurrence count
the reference's reduceByKey produced is a bilinear form in these
operands; the *raw products* (``cc``, ``yc``, ``t1t1``, …) are what gets
accumulated across blocks, and the final statistics (valid-pair count M,
Manhattan sum D1, IBS2 count, squared euclidean, …) are assembled ONCE in
:func:`combine_products` — not per block. Two wins:

- the hot loop is pure matmul + add (no per-block N x N transposes or
  combination algebra on the accumulators);
- int8 operand products accumulate in **int32**, so every count is
  *bit-exact* while ``max_increment * n_variants < 2^31``: for dosage
  inputs the worst per-variant increment is 4 (yy/qc on {0,1,2}), i.e.
  exact for **< 2^29 variants** — ~13x past the 40M-variant north star,
  where f32 accumulators would round (f32 mantissa is 24 bits ≈ 1.7e7).
  For arbitrary int8 tables with max value m the increment bound is m^2;
  the streaming runner warns when a stream outruns its budget.

The int16 QR operand never reaches the MXU directly: integer-accumulated
paths split it radix-128 into two int8 halves (``qh``/``ql``) so the
``qc`` product stays two full-rate int8 matmuls (see
:func:`gram_products`).

The 40M-long variant axis streams through in blocks and never
materialises on device (SURVEY.md §5 "Long-context").
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

# raw product name -> (left operand, right operand); each is one
# ``A B^T`` dot_general with int32 accumulation.
PRODUCT_OPERANDS: dict[str, tuple[str, str]] = {
    "cc": ("c", "c"),
    "t1c": ("t1", "c"),
    "t2c": ("t2", "c"),
    "yc": ("y", "c"),
    "qc": ("qr", "c"),
    "yy": ("yr", "yr"),
    "t1t1": ("t1", "t1"),
    "t1t2": ("t1", "t2"),
    "t2t2": ("t2", "t2"),
}

# Integer-path lowering of products whose left operand exceeds int8:
# product -> weighted sum of int8-operand matmuls. qc = 128*(qh c^T)
# + (ql c^T) with qh = qr >> 7, ql = qr & 127 keeps the MXU on int8.
_INT8_SPLIT: dict[str, tuple[tuple[tuple[str, str], int], ...]] = {
    "qc": ((("qh", "c"), 128), (("ql", "c"), 1)),
}

# statistic -> raw products it needs (mirrored by the CPU oracle).
PIECE_PRODUCTS: dict[str, tuple[str, ...]] = {
    "m": ("cc",),
    "s": ("t1t1",),
    "sc": ("t1c",),
    "d1": ("yc", "t1t1", "t2t2"),
    "ibs2": ("cc", "t1c", "t1t1", "t1t2", "t2t2"),
    "dot": ("yy",),
    "e2": ("qc", "yy"),
    # KING-robust kinship components (het = T1 - T2, homref = C - T1):
    "hh": ("t1t1", "t1t2", "t2t2"),
    "opp": ("t2c", "t1t2"),
    "hc": ("t1c", "t2c"),
}


def af_stats(block: jnp.ndarray):
    """Missing-aware per-variant allele statistics of an int8 dosage
    block: ``(p, cnt, y, valid)`` — alt-allele frequency over CALLED
    genotypes, call counts, zero-masked dosages, and the valid mask.
    The single definition of this subtle arithmetic, shared by the GRM
    update and the cross-cohort AF-concordance check."""
    valid = (block >= 0)
    y = jnp.where(valid, block, 0).astype(jnp.float32)
    cnt = valid.sum(axis=0).astype(jnp.float32)
    p = jnp.where(cnt > 0, y.sum(axis=0) / (2.0 * cnt), 0.0)
    return p, cnt, y, valid


def operands(block: jnp.ndarray, dtype=jnp.int8) -> dict[str, jnp.ndarray]:
    """(N, V) int8 values -> the matmul operands.

    Missing (any negative value) contributes zero to every operand, which
    is what gives the pairwise-complete semantics: a pair's statistics at
    a variant count only when *both* calls are valid (product of
    indicators).

    ``y`` (clipped dosage, T1+T2) serves the dosage-defined IBS family;
    ``yr``/``qr`` carry the *raw* masked value and its square so that
    ``dot``/``euclidean`` are exact for arbitrary int8 tables (counts up
    to 127), not just dosages. ``qr`` is int16 on the integer path
    (127^2 > int8); :func:`gram_products` splits it radix-128 back into
    int8 before the MXU.
    """
    valid = block >= 0
    c = valid.astype(dtype)
    t1 = (block >= 1).astype(dtype)
    t2 = (block >= 2).astype(dtype)
    yr = (valid * block).astype(dtype)
    if np.issubdtype(np.dtype(dtype), np.integer):
        qr = yr.astype(np.int16) ** 2
    else:
        qr = yr * yr
    return {"c": c, "t1": t1, "t2": t2, "y": t1 + t2, "yr": yr, "qr": qr}


def _xxt(a: jnp.ndarray, b: jnp.ndarray, accum_dtype) -> jnp.ndarray:
    """``a @ b^T`` with on-MXU accumulation — one (N, V) x (V, N) dot."""
    return jax.lax.dot_general(
        a,
        b,
        dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=accum_dtype,
    )


def _weighted_products(
    spec: dict[str, tuple[tuple[tuple[str, str], int], ...]],
    ops_l: dict[str, jnp.ndarray],
    ops_r: dict[str, jnp.ndarray],
    accum_dtype,
) -> dict[str, jnp.ndarray]:
    """name -> sum_w w * (opL @ opR^T), shared by the symmetric and
    cross-cohort paths.

    The optimization barrier materialises each operand once: without it,
    XLA fuses the threshold computation into every dot's operand read,
    so each indicator is recomputed by every matmul that consumes it and
    the VPU work throttles the MXU pipeline (measured ~30% throughput
    loss on the 4-product IBS update). For the symmetric case pass the
    same dict for both sides — each operand is then barriered once.
    """
    used_l = sorted({l for terms in spec.values() for (l, _), _ in terms})
    used_r = sorted({r for terms in spec.values() for (_, r), _ in terms})
    if ops_l is ops_r:
        used = sorted(set(used_l) | set(used_r))
        vals = jax.lax.optimization_barrier(tuple(ops_l[o] for o in used))
        ops_l = ops_r = dict(zip(used, vals))
    else:
        vals = jax.lax.optimization_barrier(
            tuple(ops_l[o] for o in used_l)
            + tuple(ops_r[o] for o in used_r)
        )
        ops_l = dict(zip(used_l, vals[: len(used_l)]))
        ops_r = dict(zip(used_r, vals[len(used_l):]))
    out = {}
    for p, terms in spec.items():
        acc = None
        for (l, r), w in terms:
            prod = _xxt(ops_l[l], ops_r[r], accum_dtype)
            prod = prod * w if w != 1 else prod
            acc = prod if acc is None else acc + prod
        out[p] = acc
    return out


def gram_products(
    block: jnp.ndarray,
    products: tuple[str, ...],
    accum_dtype=jnp.int32,
) -> dict[str, jnp.ndarray]:
    """Per-block raw products: int8 operands, int32 (N, N) outputs.

    Only the requested products' matmuls are emitted — IBS costs exactly
    4 (cc, yc, t1t1, t2t2), shared-alt 1, euclidean 3 (qc is two int8
    matmuls on the integer path — see ``_INT8_SPLIT``). Each product is
    additive across variant blocks, so the streaming driver FMAs them
    into resident int32 accumulators — exact while the per-variant
    increment times the stream length stays under 2^31 (< 2^29 variants
    for dosage inputs, whose worst increment is 4).
    """
    ops = _prepped_operands(block, accum_dtype)
    spec = _product_spec(products, accum_dtype)
    return _weighted_products(spec, ops, ops, accum_dtype)


def _prepped_operands(block, accum_dtype) -> dict[str, jnp.ndarray]:
    """Operands ready for the MXU: radix-128 split of ``qr`` on the
    integer path (keeps every operand int8), accumulator-dtype cast on
    the float path. Shared by the symmetric and tile product builders."""
    ops = operands(block)
    if np.issubdtype(np.dtype(accum_dtype), np.integer):
        sq = ops.pop("qr")
        ops["qh"] = (sq >> 7).astype(jnp.int8)
        ops["ql"] = (sq & 127).astype(jnp.int8)
    else:
        dt = np.dtype(accum_dtype)
        ops = {k: v.astype(dt) for k, v in ops.items()}
    return ops


def _product_spec(products: tuple[str, ...], accum_dtype):
    """product -> weighted operand-pair terms, honoring the int8 split."""
    if np.issubdtype(np.dtype(accum_dtype), np.integer):
        return {
            p: _INT8_SPLIT.get(p, ((PRODUCT_OPERANDS[p], 1),))
            for p in products
        }
    return {p: ((PRODUCT_OPERANDS[p], 1),) for p in products}


def tile_products(
    block_rows: jnp.ndarray,
    block_cols: jnp.ndarray,
    products: tuple[str, ...],
    accum_dtype=jnp.int32,
) -> dict[str, jnp.ndarray]:
    """:func:`gram_products` for one (rows, cols) tile of the pair
    matrix: left operands from the row samples' slice of the block,
    right operands from the column samples' — product[p] =
    opL(rows) @ opR(cols)^T. The per-device building block of the
    replicated-transport tile2d update (parallel/gram_sharded), where
    each chip owns an (N/p_i, N/p_j) tile and slices both operand sets
    locally out of the same on-device block. Feeding the same slice for
    both sides reproduces ``gram_products`` exactly (pinned by
    tests/test_genotype_ops.py)."""
    return _weighted_products(
        _product_spec(products, accum_dtype),
        _prepped_operands(block_rows, accum_dtype),
        _prepped_operands(block_cols, accum_dtype),
        accum_dtype,
    )


def combine_products(
    prod: dict[str, jnp.ndarray], pieces: tuple[str, ...]
) -> dict[str, jnp.ndarray]:
    """Accumulated raw products -> named pairwise statistics.

    Runs ONCE per job (inside finalize), in integer arithmetic — the
    subtractions (e.g. D1 = YC + YC^T − 2(T1T1 + T2T2)) are exact, no
    cancellation error. Each statistic:

      ``m``   — valid-pair counts            C C^T
      ``s``   — shared-alt counts            T1 T1^T
      ``d1``  — Manhattan (sum |a-b|)        YC + YC^T − 2(T1T1 + T2T2)
                (|a−b| = a+b−2·min(a,b); min-sum = T1T1^T + T2T2^T)
      ``ibs2``— exact-match counts           Σ_g X_g X_g^T expanded into
                indicator products (X0 = C−T1, X1 = T1−T2, X2 = T2)
      ``dot`` — raw-value inner products     YR YR^T
      ``e2``  — squared euclidean            QC + QC^T − 2 YR YR^T
                (QC built from QR = YR^2, so both are exact for
                arbitrary int8 values, not just dosages)
    """
    out = {}
    for piece in pieces:
        if piece == "m":
            out["m"] = prod["cc"]
        elif piece == "s":
            out["s"] = prod["t1t1"]
        elif piece == "sc":
            # sc[i, j] = # variants where i carries alt AND j's call is
            # valid (non-symmetric; the jaccard union is sc + sc^T - s)
            out["sc"] = prod["t1c"]
        elif piece == "d1":
            p = prod["t1t1"] + prod["t2t2"]
            out["d1"] = prod["yc"] + _t(prod["yc"]) - 2 * p
        elif piece == "ibs2":
            out["ibs2"] = (
                prod["cc"] - prod["t1c"] - _t(prod["t1c"])
                + 2 * prod["t1t1"] - prod["t1t2"] - _t(prod["t1t2"])
                + 2 * prod["t2t2"]
            )
        elif piece == "dot":
            out["dot"] = prod["yy"]
        elif piece == "e2":
            out["e2"] = prod["qc"] + _t(prod["qc"]) - 2 * prod["yy"]
        elif piece == "hh":
            # het-het co-occurrence: H H^T with H = T1 - T2
            out["hh"] = (
                prod["t1t1"] - prod["t1t2"] - _t(prod["t1t2"])
                + prod["t2t2"]
            )
        elif piece == "opp":
            # opposite-homozygote counts, both directions:
            # X0 X2^T + X2 X0^T with X0 = C - T1 (hom-ref), X2 = T2;
            # X0 X2^T = (T2 C^T)^T - T1 T2^T.
            out["opp"] = (
                prod["t2c"] + _t(prod["t2c"])
                - prod["t1t2"] - _t(prod["t1t2"])
            )
        elif piece == "hc":
            # hc[i, j] = # variants where i is het AND j's call is valid
            # (non-symmetric; the KING denominator uses hc + hc^T)
            out["hc"] = prod["t1c"] - prod["t2c"]
        else:
            raise ValueError(f"unknown gram piece {piece!r}")
    return out


def _t(a):
    """Transpose that works for both jnp and np arrays."""
    return a.T if hasattr(a, "T") else jnp.transpose(a)


# Cross-cohort statistics (out-of-sample projection, cross-kinship):
# operand-pair lists per metric statistic. Unlike the symmetric case,
# the mirrored products (e.g. C_new Y_ref^T vs Y_new C_ref^T) are NOT
# each other's transposes, so each orientation is its own matmul. Each
# entry: stat -> ((left operand of NEW cohort, right operand of REF),
# weight). The KING pieces expand H = T1 - T2 and X0 = C - T1 into
# indicator products exactly like the symmetric combine (ops/gram.py
# "king"), with both orientations explicit:
#   hh   = H_n H_r^T                 (het-het co-occurrence)
#   opp  = X0_n T2_r^T + T2_n X0_r^T (opposite homozygotes, both ways)
#   hcn  = H_n C_r^T                 (new-side het over complete pairs)
#   hcr  = C_n H_r^T                 (ref-side het over complete pairs)
CROSS_STATS: dict[str, tuple[tuple[tuple[str, str], int], ...]] = {
    "m": ((("c", "c"), 1),),
    "d1": ((("y", "c"), 1), (("c", "y"), 1),
           (("t1", "t1"), -2), (("t2", "t2"), -2)),
    "s": ((("t1", "t1"), 1),),
    "hh": ((("t1", "t1"), 1), (("t1", "t2"), -1),
           (("t2", "t1"), -1), (("t2", "t2"), 1)),
    "opp": ((("c", "t2"), 1), (("t1", "t2"), -1),
            (("t2", "c"), 1), (("t2", "t1"), -1)),
    "hcn": ((("t1", "c"), 1), (("t2", "c"), -1)),
    "hcr": ((("c", "t1"), 1), (("c", "t2"), -1)),
    # jaccard union sides: each cohort's carrier count over pairwise-
    # complete variants (union = sn + sr - s).
    "sn": ((("t1", "c"), 1),),
    "sr": ((("c", "t1"), 1),),
}


def cross_stats(
    block_new: jnp.ndarray,
    block_ref: jnp.ndarray,
    stats: tuple[str, ...],
    accum_dtype=jnp.int32,
) -> dict[str, jnp.ndarray]:
    """Cross-cohort pairwise statistics over one shared variant block.

    ``block_new`` (A, V) vs ``block_ref`` (N, V), SAME variants in the
    same order — yields (A, N) int32 statistics, additive across blocks
    exactly like the symmetric path: ``m`` valid-pair counts, ``d1``
    Manhattan sums (the IBS numerator), ``s`` shared-alt counts. This is
    the accumulation the Nystrom/out-of-sample PCoA projection streams
    (pipelines/project.py).
    """
    return _weighted_products(
        {s: CROSS_STATS[s] for s in stats},
        operands(block_new),
        operands(block_ref),
        accum_dtype,
    )


def gram_pieces(block: jnp.ndarray, accum_dtype=jnp.int32) -> dict[str, jnp.ndarray]:
    """One-shot per-block statistics (all six) — test/oracle convenience;
    the streaming path uses :func:`gram_products` + a single deferred
    :func:`combine_products` instead."""
    pieces = tuple(PIECE_PRODUCTS)
    needed = tuple(
        sorted({p for piece in pieces for p in PIECE_PRODUCTS[piece]})
    )
    return combine_products(gram_products(block, needed, accum_dtype), pieces)

"""Double-centering — step 3 of the reference pipeline.

Reference: PCoA entrypoint computes B = -1/2 J D^2 J (J = I - 11^T/n) and
the PCA driver centers its similarity matrix by row/col/grand means before
eigendecomposition (SURVEY.md §3.1, §3.3). J is never materialised here:
centering is rank-1 row/col mean subtraction, which XLA fuses into a
couple of reductions + one elementwise kernel — O(N^2) reads, no matmul.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


@jax.jit
def center_matrix(a: jnp.ndarray) -> jnp.ndarray:
    """J A J: subtract row means, col means, add grand mean."""
    row = a.mean(axis=1, keepdims=True)
    col = a.mean(axis=0, keepdims=True)
    grand = a.mean()
    return a - row - col + grand


@jax.jit
def gower_center(distance: jnp.ndarray) -> jnp.ndarray:
    """B = -1/2 J D^2 J from a distance matrix D (classical MDS / PCoA)."""
    return -0.5 * center_matrix(distance * distance)


@jax.jit
def gower_center_from_squared(d2: jnp.ndarray) -> jnp.ndarray:
    """Same, when the squared distances are already at hand."""
    return -0.5 * center_matrix(d2)

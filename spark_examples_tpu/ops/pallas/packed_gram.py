"""Fused Pallas TPU kernel for the packed count-family contraction.

The reference lowering of a packed gram update is unpack-then-contract:
``unpack_dosages`` expands the 2-bit codes into a full-width int8 dosage
block, the indicator thresholds (ops/genotype.py) follow, and only then
do the int8 matmuls run. Under jit the threshold math fuses, but the
expanded block (4x the packed bytes) and each indicator operand still
round-trip through HBM between the unpack and the MXU — on the packed
transport, unpack bandwidth, not the MXU, bounds the count family.

This kernel fuses all three stages into one ``pallas_call`` per output
tile: the packed bytes land in VMEM once, the 2-bit decode and the
missingness/piece indicators are formed in registers, and the int32
tile contraction accumulates across the byte-chunk grid sweep — no u8
dosage or indicator operand ever materialises in HBM.

Bit-identity contract: the packed layout interleaves variants across
bit planes (variant ``v`` = byte ``v // 4``, plane ``2 * (v % 4)`` —
ingest/bitpack.py), so the kernel decodes PER PLANE and sums four
plane-restricted int8 dots per product. Integer addition is exact under
reordering, so the plane-summed int32 tile equals the reference
full-width dot bit-for-bit — the same property the ring transport's
shard-order summation relies on (parallel/gram_sharded.py). The parity
suites (tests/test_kernel_registry.py, tests/test_parallel.py) assert
exact equality on every transport, via the interpreter on CPU.

Tiles: TI x TW packed bytes for the row block, TJ x TW for the column
block, TI x TJ int32 output per product. TW = 512 bytes = 2048 variants
per chunk keeps the operand VMEM footprint ~1 MB at the int8 (32, 128)
tiling, and the worst-case 6-product output set (pc-invariant) stays
under 384 KB of int32 tiles.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from spark_examples_tpu.ops.genotype import PRODUCT_OPERANDS

TI = 128  # row samples per program
TJ = 128  # column samples per program
TW = 512  # packed bytes per chunk (4 variants each)

# Operands the 2-bit decode can form in registers. qr/yr (raw values,
# dot/euclidean) are excluded by construction: those kernels accept
# arbitrary int8 tables the codec cannot represent (pack_auto=False).
_PACKABLE_OPERANDS = frozenset({"c", "t1", "t2", "y"})


def check_fusable(products: tuple[str, ...]) -> None:
    """Raise unless every product's operands decode from 2-bit codes."""
    for p in products:
        ops = PRODUCT_OPERANDS.get(p)
        if ops is None or not set(ops) <= _PACKABLE_OPERANDS:
            raise ValueError(
                f"product {p!r} is not lowerable by the fused packed "
                f"kernel: its operands {ops} are not all 2-bit "
                f"decodable ({sorted(_PACKABLE_OPERANDS)})"
            )


def _plane_operands(packed, shift: int, names) -> dict:
    """Decode one bit plane's indicator operands, in registers.

    ``codes = (packed >> shift) & 3`` holds every 4th variant;
    the indicators mirror ops.genotype.operands exactly:
    c = [code != 3] (valid), t1 = [code in {1, 2}] (alt carrier),
    t2 = [code == 2] (hom alt), y = t1 + t2 (clipped dosage).
    """
    codes = (packed >> shift) & jnp.uint8(3)
    valid = codes != jnp.uint8(3)
    ops = {}
    if "c" in names:
        ops["c"] = valid.astype(jnp.int8)
    if "t1" in names or "y" in names:
        t1 = (valid & (codes >= jnp.uint8(1))).astype(jnp.int8)
        if "t1" in names:
            ops["t1"] = t1
    if "t2" in names or "y" in names:
        t2 = (codes == jnp.uint8(2)).astype(jnp.int8)
        if "t2" in names:
            ops["t2"] = t2
    if "y" in names:
        ops["y"] = t1 + t2
    return ops


def _make_kernel(products: tuple[str, ...]):
    left = {PRODUCT_OPERANDS[p][0] for p in products}
    right = {PRODUCT_OPERANDS[p][1] for p in products}

    def kernel(rows_ref, cols_ref, *out_refs):
        @pl.when(pl.program_id(2) == 0)
        def _():
            for o in out_refs:
                o[:] = jnp.zeros_like(o)

        rows = rows_ref[:]
        cols = cols_ref[:]
        # Four plane-restricted dots per product, summed into the int32
        # output tile — bit-identical to the reference full-width dot
        # (int32 addition is exact under reordering; see module doc).
        for shift in (0, 2, 4, 6):
            lops = _plane_operands(rows, shift, left)
            rops = _plane_operands(cols, shift, right)
            for p, o in zip(products, out_refs):
                l, r = PRODUCT_OPERANDS[p]
                o[:] += jax.lax.dot_general(
                    lops[l], rops[r], (((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.int32,
                )

    return kernel


def fused_tile_products(
    packed_rows: jnp.ndarray,
    packed_cols: jnp.ndarray,
    products: tuple[str, ...],
    interpret: bool | None = None,
) -> dict[str, jnp.ndarray]:
    """Fused twin of :func:`ops.genotype.tile_products` on PACKED bytes:
    ``(tn, W) x (tm, W) uint8 -> {product: (tn, tm) int32}``, decode +
    mask + contract in one Pallas pass. Feeding the same slice for both
    sides reproduces the full symmetric update.

    Pads the sample axes to the (TI, TJ) program grid and the byte axis
    to TW with 0xFF — four missing codes per byte, which decode to
    all-zero operands and contribute nothing to any product (the same
    semantically-free padding the whole packed transport uses); the
    padded output rows/cols are sliced off. Not jitted here — it traces
    inside the caller's jit (ops/gram.py) or shard_map body
    (parallel/gram_sharded.py). ``interpret`` defaults to the Pallas
    interpreter off-TPU (Mosaic is TPU-only), the braycurtis kernel's
    convention, so tier-1 covers every fused kernel without hardware.
    """
    products = tuple(products)
    check_fusable(products)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    pr = jnp.asarray(packed_rows, jnp.uint8)
    pc = jnp.asarray(packed_cols, jnp.uint8)
    (nr, w), (nc, wc) = pr.shape, pc.shape
    if w != wc:
        raise ValueError(
            f"row/col packed widths disagree: {w} vs {wc} bytes"
        )
    nr_p = -(-nr // TI) * TI
    nc_p = -(-nc // TJ) * TJ
    w_p = -(-w // TW) * TW
    pr = jnp.pad(pr, ((0, nr_p - nr), (0, w_p - w)), constant_values=0xFF)
    pc = jnp.pad(pc, ((0, nc_p - nc), (0, w_p - w)), constant_values=0xFF)
    outs = pl.pallas_call(
        _make_kernel(products),
        grid=(nr_p // TI, nc_p // TJ, w_p // TW),
        in_specs=[
            pl.BlockSpec((TI, TW), lambda i, j, k: (i, k)),
            pl.BlockSpec((TJ, TW), lambda i, j, k: (j, k)),
        ],
        out_specs=[
            pl.BlockSpec((TI, TJ), lambda i, j, k: (i, j))
            for _ in products
        ],
        out_shape=[
            jax.ShapeDtypeStruct((nr_p, nc_p), jnp.int32)
            for _ in products
        ],
        interpret=interpret,
    )(pr, pc)
    return {p: o[:nr, :nc] for p, o in zip(products, outs)}

from spark_examples_tpu.ops.pallas import braycurtis_kernel  # noqa: F401

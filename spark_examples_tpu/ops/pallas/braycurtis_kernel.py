"""Pallas TPU kernel for the pairwise Manhattan contraction.

Bray-Curtis (BASELINE.md config 3) needs ``num[i,j] = sum_f |x_i - x_j|``
— not a bilinear form, so it can't ride the MXU. The stock XLA lowering
(ops.distances.pairwise_manhattan) materialises (row_tile, N, feat_tile)
broadcast intermediates in HBM between scan steps; this kernel keeps the
entire contraction in VMEM: grid (i, j, f) over output tiles and feature
chunks, an f32 accumulator tile that lives in the output block across the
f-sweep, and an inner row loop whose (TJ, TF) broadcast temp never leaves
the chip.

Tiles: TI x TF inputs for the row block, TJ x TF for the column block,
TI x TJ f32 output — all aligned to the (8, 128) f32 tiling. The body
is ONE vectorized (TI, TJ, TF) broadcast-abs-reduce per program: the
16 MB temp fits VMEM, and replacing the earlier per-row ``fori_loop``
(whose dynamic sublane indexing lowers poorly in Mosaic) with the flat
3-D op measured 6.0x on the config-3 shape — 0.36 s vs 2.13 s at
N=10k, F=4096, which also beats the threshold-matmul MXU lowering
(1.28 s) while staying exact.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

TI = 16  # rows per program
TJ = 256  # columns per program
TF = 1024  # feature chunk; (TI, TJ, TF) f32 temp = 16 MB of VMEM


def _kernel(xi_ref, xj_ref, out_ref):
    @pl.when(pl.program_id(2) == 0)
    def _():
        out_ref[:] = jnp.zeros_like(out_ref)

    # (TI, 1, TF) vs (1, TJ, TF) -> reduce feature axis -> (TI, TJ).
    out_ref[:] += jnp.abs(
        xi_ref[:][:, None, :] - xj_ref[:][None, :, :]
    ).sum(axis=2)


@functools.partial(jax.jit, static_argnames=("interpret",))
def pairwise_manhattan_pallas(x: jnp.ndarray, interpret: bool = False) -> jnp.ndarray:
    """(N, F) f32 -> (N, N) sum|x_i - x_j| via the fused VMEM kernel.

    Pads N up to max(TI, TJ) and F up to TF with zeros (pad rows produce
    garbage distances against real rows, but only inside padded rows/cols
    which are sliced off; zero-padding the feature axis adds |0-0| = 0).
    """
    n, f = x.shape
    n_pad = -(-n // max(TI, TJ)) * max(TI, TJ)
    f_pad = -(-f // TF) * TF
    xp = jnp.pad(x.astype(jnp.float32), ((0, n_pad - n), (0, f_pad - f)))
    grid = (n_pad // TI, n_pad // TJ, f_pad // TF)
    out = pl.pallas_call(
        _kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((TI, TF), lambda i, j, k: (i, k)),
            pl.BlockSpec((TJ, TF), lambda i, j, k: (j, k)),
        ],
        out_specs=pl.BlockSpec((TI, TJ), lambda i, j, k: (i, j)),
        out_shape=jax.ShapeDtypeStruct((n_pad, n_pad), jnp.float32),
        interpret=interpret,
    )(xp, xp)
    return out[:n, :n]


def braycurtis_pallas(x: jnp.ndarray, interpret: bool = False) -> jnp.ndarray:
    """Bray-Curtis via the fused kernel (see ops.distances.braycurtis for
    the metric's definition and conventions)."""
    from spark_examples_tpu.ops.distances import bc_from_manhattan

    num = pairwise_manhattan_pallas(x, interpret=interpret)
    return bc_from_manhattan(num, jnp.asarray(x, jnp.float32).sum(axis=1))

"""Pallas TPU kernel for the pairwise Manhattan contraction.

Bray-Curtis (BASELINE.md config 3) needs ``num[i,j] = sum_f |x_i - x_j|``
— not a bilinear form, so it can't ride the MXU. The stock XLA lowering
(ops.distances.pairwise_manhattan) materialises (row_tile, N, feat_tile)
broadcast intermediates in HBM between scan steps; this kernel keeps the
entire contraction in VMEM: grid (i, j, f) over output tiles and feature
chunks, an f32 accumulator tile that lives in the output block across the
f-sweep, and an inner row loop whose (TJ, TF) broadcast temp never leaves
the chip.

Tiles: TI x TF inputs for the row block, TJ x TF for the column block,
TI x TJ f32 output — all aligned to the (8, 128) f32 tiling. The inner
``fori_loop`` walks the TI rows so the live temp is (TJ, TF) not
(TI, TJ, TF).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

TI = 8  # rows per program (sublane-aligned)
TJ = 256  # columns per program
TF = 512  # feature chunk


def _kernel(xi_ref, xj_ref, out_ref):
    @pl.when(pl.program_id(2) == 0)
    def _():
        out_ref[:] = jnp.zeros_like(out_ref)

    xj = xj_ref[:]  # (TJ, TF)

    def row(a, _):
        # (1, TF) vs (TJ, TF) -> reduce to (TJ,): stays on-chip; row
        # writes go straight to the output ref (dynamic ref stores lower
        # natively; value-level scatter does not).
        d = jnp.abs(xi_ref[a, :][None, :] - xj).sum(axis=1)
        out_ref[a, :] += d
        return 0

    jax.lax.fori_loop(0, TI, row, 0)


@functools.partial(jax.jit, static_argnames=("interpret",))
def pairwise_manhattan_pallas(x: jnp.ndarray, interpret: bool = False) -> jnp.ndarray:
    """(N, F) f32 -> (N, N) sum|x_i - x_j| via the fused VMEM kernel.

    Pads N up to max(TI, TJ) and F up to TF with zeros (pad rows produce
    garbage distances against real rows, but only inside padded rows/cols
    which are sliced off; zero-padding the feature axis adds |0-0| = 0).
    """
    n, f = x.shape
    n_pad = -(-n // max(TI, TJ)) * max(TI, TJ)
    f_pad = -(-f // TF) * TF
    xp = jnp.pad(x.astype(jnp.float32), ((0, n_pad - n), (0, f_pad - f)))
    grid = (n_pad // TI, n_pad // TJ, f_pad // TF)
    out = pl.pallas_call(
        _kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((TI, TF), lambda i, j, k: (i, k)),
            pl.BlockSpec((TJ, TF), lambda i, j, k: (j, k)),
        ],
        out_specs=pl.BlockSpec((TI, TJ), lambda i, j, k: (i, j)),
        out_shape=jax.ShapeDtypeStruct((n_pad, n_pad), jnp.float32),
        interpret=interpret,
    )(xp, xp)
    return out[:n, :n]


def braycurtis_pallas(x: jnp.ndarray, interpret: bool = False) -> jnp.ndarray:
    """Bray-Curtis via the fused kernel (see ops.distances.braycurtis for
    the metric's definition and conventions)."""
    from spark_examples_tpu.ops.distances import bc_from_manhattan

    num = pairwise_manhattan_pallas(x, interpret=interpret)
    return bc_from_manhattan(num, jnp.asarray(x, jnp.float32).sum(axis=1))

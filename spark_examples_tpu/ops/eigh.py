"""Symmetric eigendecomposition — step 4 of the reference pipeline.

The reference collected the N x N matrix to the Spark driver and ran
LAPACK via MLlib ``RowMatrix.computePrincipalComponents`` — its scaling
wall (SURVEY.md §3.1 HOT LOOP #3). Here the matrix is already on device:

- :func:`top_k_eigh` — full dense ``jax.numpy.linalg.eigh`` (XLA's
  on-device QDWH/tridiagonal path), then slice the top k. Right answer up
  to N in the tens of thousands on one chip.
- :func:`randomized_eigh` — randomized subspace iteration (Halko-style;
  see PAPERS.md: arxiv 1612.08709, 2110.03423) for the large-N / sharded
  regime: k + p probes, a few power iterations, small host-side eigh of
  the Rayleigh quotient. Only needs B through matvec-blocks (matmul
  shaped, MXU friendly) — this is the path the 76k-exome benchmark config
  uses, and the building block for the streaming rank-k updates.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from spark_examples_tpu.core.config import (
    EIGH_ITERS_DEFAULT,
    EIGH_OVERSAMPLE_DEFAULT,
)


@partial(jax.jit, static_argnames=("k",))
def top_k_eigh(b: jnp.ndarray, k: int) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Top-k eigenpairs of symmetric ``b``, eigenvalues descending.

    Returns (vals (k,), vecs (N, k)).
    """
    vals, vecs = jnp.linalg.eigh(b)  # ascending
    vals = vals[::-1][:k]
    vecs = vecs[:, ::-1][:, :k]
    return vals, vecs


def _subspace_iterate_impl(b, q, k: int, iters: int, select: str = "top"):
    def step(q, _):
        q, _ = jnp.linalg.qr(b @ q)
        return q, None

    q, _ = jax.lax.scan(step, q, None, length=iters)
    # Rayleigh quotient: small (p, p) symmetric problem.
    t = q.T @ (b @ q)
    t = 0.5 * (t + t.T)
    vals, s = jnp.linalg.eigh(t)
    if select == "abs":
        # Largest-|lambda| pairs — the PCA driver's ordering (power
        # iteration amplifies |lambda|, so the tracked subspace already
        # targets these; only the final selection differs from "top").
        order = jnp.argsort(-jnp.abs(vals))[:k]
        return vals[order], (q @ s)[:, order], q
    if select != "top":  # static arg: free at trace time, and a typo
        raise ValueError(  # must not silently pick the wrong spectrum
            f"unknown select {select!r}; valid: top | abs"
        )
    vals_k = vals[::-1][:k]
    vecs = (q @ s)[:, ::-1][:, :k]
    return vals_k, vecs, q


@partial(jax.jit, static_argnames=("k", "iters"))
def subspace_iterate(
    b: jnp.ndarray, q: jnp.ndarray, k: int, iters: int = 1
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """``iters`` power steps from an existing (N, p) subspace ``q``, then
    a Rayleigh solve: returns (vals (k,), vecs (N, k), q_new (N, p)).

    This is the rank-k *incremental* eig building block (BASELINE.md
    config 5): when ``b`` is a streaming accumulator that grows by a
    small relative delta between calls, warm-starting from the previous
    ``q`` needs only ``iters=1`` power step per refresh instead of a
    full cold solve — subspace tracking, all matmul-shaped (the B @ Q
    products tile onto the MXU and shard over the mesh like any Gram
    block).
    """
    return _subspace_iterate_impl(b, q, k, iters)


def init_probes(key: jax.Array, n: int, p: int, dtype=jnp.float32):
    """Random (N, p) Gaussian probe block — the cold-start subspace.

    ``p`` is clamped to N: a wider-than-square probe block would be
    collapsed to (N, N) by reduced QR, changing the scan carry shape
    mid-iteration (a crash, not an accuracy loss).
    """
    return jax.random.normal(key, (n, min(p, n)), dtype=dtype)


def coords_from_eigpairs(vals: jnp.ndarray, vecs: jnp.ndarray) -> jnp.ndarray:
    """coords_i = v_i * sqrt(max(lambda_i, 0)) — the PCoA convention:
    negative eigenvalues (non-Euclidean distances) become zero
    coordinate axes, matching scikit-bio's classical PCoA. The single
    definition every route (dense, sharded, streaming) shares."""
    return vecs * jnp.sqrt(jnp.maximum(vals, 0.0))[None, :]


@partial(jax.jit, static_argnames=("k", "oversample", "iters", "select"))
def randomized_eigh(
    b: jnp.ndarray,
    k: int,
    key: jax.Array,
    oversample: int = EIGH_OVERSAMPLE_DEFAULT,
    iters: int = EIGH_ITERS_DEFAULT,
    select: str = "top",
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Randomized top-k eigenpairs of symmetric ``b``.

    Subspace iteration with QR re-orthonormalisation each step. The
    only large-N operations are ``b @ q`` products — (N, N) x (N, k+p)
    matmuls that tile onto the MXU and shard cleanly over the mesh.
    Cold start of :func:`subspace_iterate` (iters + 1 power steps from
    random probes). ``select="abs"`` returns the largest-|lambda| pairs
    instead of the largest-value ones (the PCA driver's ordering).

    Accuracy on PCoA-class spectra, measured against an f64 oracle at
    the config-1 shape (BASELINE.md "Randomized-solver accuracy"): the
    defaults put every eigenvalue ABOVE the noise bulk at relerr
    <= ~3e-4 (the 1e-3 target with margin), at ~1/3 the dense solve's
    wall-clock and far below its ~9n^3 FLOPs. Eigenvalues INSIDE the
    bulk (a quasi-degenerate cluster — 0.4 % total spread at config 1,
    sitting 143x below the structure) converge only at a few percent:
    pushing a Ritz value to 1e-3 inside a cluster with ~1e-4 relative
    internal gaps needs O(1e4) power iterations and distinguishes
    nothing biological — which bulk direction wins is sampling noise.
    Normalized by lambda_1 (the scale that moves coordinates), bulk
    error is < 6e-4 at the defaults. Raising ``iters`` buys structure
    accuracy almost nothing (already float-limited) and bulk accuracy
    slowly (8.7 % -> 2.1 % from 4/16 to 16/64 iters/oversample).
    """
    q = init_probes(key, b.shape[0], k + oversample, b.dtype)  # p clamped to N
    vals, vecs, _ = _subspace_iterate_impl(b, q, k, iters + 1, select)
    return vals, vecs


def eigh_flops(
    n: int, method: str = "dense", k: int = 0,
    oversample: int = EIGH_OVERSAMPLE_DEFAULT,
    iters: int = EIGH_ITERS_DEFAULT,
) -> float:
    """FLOP estimate matching the solver actually run, for the
    eigh-GFLOPS/chip north-star metric (BASELINE.md).

    - ``dense``: ~9 n^3 (tridiagonalisation + QR iteration).
    - ``randomized``: the (iters + 2) B @ Q products at 2 n^2 p each,
      plus (iters + 1) QR factorisations at ~4 n p^2 and the small
      Rayleigh eigh (negligible) — crediting the dense count here would
      inflate the metric by orders of magnitude (the whole point of the
      randomized path is to do fewer FLOPs).
    - ``sketch`` (the streaming sketch solver, spark_examples_tpu/
      solvers): ONLY the solve-stage residue — one shifted CholeskyQR2
      (~6 n p^2) per BETWEEN-pass boundary (passes - 1 of them; the
      single-pass rung runs none) plus the terminal Nystrom/Rayleigh
      (~4 n p^2); its B @ Q products were streamed through the variant
      pass and are credited to gram_flops by the pass loop, so counting
      them here would double-bill. ``iters`` = passes, ``k + oversample``
      = the sketch rank.
    """
    if method == "randomized":
        p = k + oversample
        return (iters + 2) * 2.0 * n * n * p + (iters + 1) * 4.0 * n * p * p
    if method == "sketch":
        p = k + oversample
        return max(iters - 1, 0) * 6.0 * n * p * p + 4.0 * n * p * p
    return 9.0 * float(n) ** 3

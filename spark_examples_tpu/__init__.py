"""spark_examples_tpu — a TPU-native population-genomics analysis framework.

A from-scratch rebuild of the capability surface of
``StanfordBioinformatics/spark-examples`` (a Scala/Apache-Spark genomics
example stack: Genomics-API/BigQuery variant ingest → pairwise
similarity/IBS distance matrices → double-centering → eigendecomposition →
PCA/PCoA coordinates), re-designed TPU-first:

- the dense linear-algebra core (similarity/Gram accumulation, centering,
  symmetric eigendecomposition) is expressed as JAX/XLA programs, blocked
  for the MXU and sharded over a ``jax.sharding.Mesh`` via ``shard_map`` /
  ``jit`` — replacing the reference's Spark ``reduceByKey`` shuffle and
  MLlib ``RowMatrix`` path (reference call stack: SURVEY.md §3.1);
- the ingest layer keeps the reference's partitioned-streaming shape
  (``VariantsRDD`` + genomic-range partitioners, SURVEY.md §2.1) behind a
  :class:`~spark_examples_tpu.ingest.source.GenotypeSource` protocol;
- job entrypoints mirror the reference's driver surface
  (``VariantsPcaDriver``, ``SimilarityMatrix``, ``PCoA``,
  ``SearchVariantsExample*``) as CLI subcommands.

NOTE ON CITATIONS: the reference mount (``/root/reference``) contained zero
files in every session so far; reference citations in this package point to
SURVEY.md sections (the reconstruction of record) rather than file:line.
"""

from spark_examples_tpu.version import __version__

__all__ = ["__version__"]

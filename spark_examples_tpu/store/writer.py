"""Compaction: stream any GenotypeSource ONCE into the block store.

The ETL tier of the catalog (the reference's "load the cohort into the
BigQuery table once" job shape): every block the source yields becomes
one 2-bit-packed, entropy-coded chunk file named by the sha256 of its
STORED bytes, and the manifest — written last, atomically — records
the variant/contig/position index plus the per-chunk codec geometry
over them. Because the name IS the content:

- a re-run over identical data rewrites nothing (chunk writes are
  skipped when the address already exists — dedupe for free);
- a partially-written chunk can never be mistaken for a good one
  (files land via tmp + rename, and the reader re-hashes against the
  address on first touch anyway);
- a crashed compaction leaves no manifest, so the store simply does
  not exist yet — re-running is always safe.

Compression (store/codec.py) sits between the 2-bit pack and the hash:
the content address covers the stored (compressed) bytes, so all of
the above — and replica healing, quarantine bookkeeping, `store heal`
re-verification — hold for compressed chunks unchanged. The codec is
byte-deterministic by contract, so compaction at any worker count, a
killed-and-resumed compaction, and an origin heal all reproduce
identical stored bytes.

Chunks inherit the source's "blocks never span a contig" contract
(``source.blocks`` flushes at contig boundaries), so every catalog row
has an exact contig and the store can answer range queries without
touching data. That same contract is what makes the optional per-contig
preset dictionary (``--store-codec zlib-dict``) well-defined: the first
chunk of each contig trains the dictionary (a pure function of its
packed payload), every later chunk of the contig compresses against it,
and the dictionary itself lands content-addressed under ``dicts/``.
"""

from __future__ import annotations

import os
import threading

import numpy as np

from spark_examples_tpu.core import hashing, telemetry
from spark_examples_tpu.store import codec as codecmod
from spark_examples_tpu.store.manifest import (
    CHUNK_DIR,
    POSITIONS_NAME,
    ChunkRecord,
    StoreManifest,
)


class _DictBook:
    """Per-contig dictionary rendezvous for the compaction pool.

    The trainer (the worker holding a contig's FIRST chunk — tagged by
    the serial feed, so the claim is unambiguous) derives the
    dictionary from its own packed payload, writes it content-addressed
    under ``dicts/``, and publishes; every other worker of that contig
    waits on the publication before compressing. Deadlock-free by
    construction: the trainer's task is always submitted (and therefore
    scheduled, FIFO) before any waiter of the same contig, and trainers
    never wait on anything. The timeout is a belt for a crashed trainer
    — its error also surfaces at the ordered consumer, first.
    """

    TIMEOUT_S = 300.0

    def __init__(self, root: str):
        self.root = root
        self._lock = threading.Lock()
        self._entries: dict[str | None, tuple[threading.Event,
                                              list]] = {}

    def _entry(self, contig):
        with self._lock:
            e = self._entries.get(contig)
            if e is None:
                e = self._entries[contig] = (threading.Event(), [])
            return e

    def train_and_publish(self, contig, raw: bytes) -> tuple[str, bytes]:
        zdict = codecmod.train_dict(raw)
        digest = hashing.sha256_bytes(zdict)
        path = codecmod.dict_path(self.root, digest)
        try:
            fresh = os.path.getsize(path) != len(zdict)
        except OSError:
            fresh = True
        if fresh:
            tmp = path + f".tmp.{os.getpid()}.{threading.get_ident()}"
            with open(tmp, "wb") as f:
                f.write(zdict)
            os.replace(tmp, path)
        event, slot = self._entry(contig)
        slot.append(("ok", digest, zdict))
        event.set()
        return digest, zdict

    def poison(self, contig) -> None:
        """The trainer died before publishing: release its waiters with
        a marker instead of leaving them parked until the timeout (the
        trainer's own error, being earliest, still surfaces first at
        the ordered consumer)."""
        event, slot = self._entry(contig)
        if not slot:
            slot.append(("dead",))
        event.set()

    def wait(self, contig) -> tuple[str, bytes]:
        event, slot = self._entry(contig)
        if not event.wait(self.TIMEOUT_S) or not slot:
            raise RuntimeError(
                f"compaction dictionary for contig {contig!r} was never "
                "published — the trainer worker died; its error follows "
                "at the ordered consumer"
            )
        entry = slot[0]
        if entry[0] != "ok":
            raise RuntimeError(
                f"compaction dictionary trainer for contig {contig!r} "
                "failed — its error follows at the ordered consumer"
            )
        return entry[1], entry[2]


def _write_chunk(path: str, block: np.ndarray, base_codec: str,
                 book: "_DictBook | None",
                 first_of_contig: bool, contig) -> tuple[str, int, int,
                                                         str | None]:
    """Pack + compress + hash + (dedupe-aware) write one chunk; returns
    (digest, raw_size, stored_size, dict_digest). Runs in a pool worker
    under ``workers > 1`` — everything here (the native 2-bit pack, the
    deflate, sha256 over the stored bytes, the file write) releases the
    GIL, which is what makes stage B scale."""
    from spark_examples_tpu.ingest import bitpack

    dict_digest = zdict = None
    try:
        packed = bitpack.pack_dosages(np.ascontiguousarray(block))
        raw = packed.tobytes()
        if book is not None and first_of_contig:
            dict_digest, zdict = book.train_and_publish(contig, raw)
    except BaseException:
        if book is not None and first_of_contig:
            book.poison(contig)
        raise
    if book is not None and not first_of_contig:
        dict_digest, zdict = book.wait(contig)
    data = codecmod.compress(base_codec, raw, zdict)
    digest = hashing.sha256_bytes(data)
    fname = os.path.join(path, CHUNK_DIR, f"{digest}.bin")
    # Dedupe by content address — but a wrong-SIZED file under the
    # right name is a truncated write (or a quarantined chunk), and
    # re-running the compaction must heal it, not trust the name.
    # Same-size bit rot is the read path's job (first-touch digest
    # verify); healing it means deleting the quarantined file and
    # re-running this compaction.
    try:
        fresh = os.path.getsize(fname) != len(data)
    except OSError:
        fresh = True
    if fresh:
        tmp = fname + f".tmp.{os.getpid()}.{threading.get_ident()}"
        with open(tmp, "wb") as f:
            f.write(data)
        os.replace(tmp, fname)
        telemetry.count("store.compact_bytes", float(len(data)))
    telemetry.count("store.compact_chunks")
    telemetry.count("store.codec.raw_bytes", float(len(raw)))
    telemetry.count("store.codec.stored_bytes", float(len(data)))
    return digest, len(raw), len(data), dict_digest


def _tag_first_of_contig(block_iter):
    """(block, meta) -> (block, meta, first_of_contig), computed in the
    single serial feed so every worker agrees on which chunk trains a
    contig's dictionary."""
    seen: set = set()
    for block, meta in block_iter:
        first = meta.contig not in seen
        seen.add(meta.contig)
        yield block, meta, first


@telemetry.traced("store.compact", cat="store")
def compact(path: str, source, chunk_variants: int = 16384,
            workers: int = 1, origin: dict | None = None,
            codec: str | None = None) -> StoreManifest:
    """Stream ``source`` into a content-addressed store at ``path``.

    ``chunk_variants`` is the catalog granularity: the unit of range
    addressing, integrity verification, and decode caching. It must be
    divisible by 4 so full chunks stay byte-aligned on the 2-bit grid
    (which is what lets the reader hand out zero-copy packed slices of
    raw-codec chunks). Returns the committed manifest.

    ``workers > 1`` runs the parallel ingest engine (ingest/parallel.py)
    under the SAME output contract — byte-identical chunks and manifest:
    stage A fans the parse out where the source allows it (VCF byte
    ranges, exact-source block stripes), stage B packs + compresses +
    hashes + writes each chunk in a second bounded pool, both
    reassembled in order. The serial ``workers=1`` path below is the
    semantic reference.

    ``origin`` (an IngestConfig-shaped dict — build one with
    ``store.heal.origin_from_ingest``) is recorded in the manifest as
    the store's self-healing recipe: a later corrupt chunk can be
    re-compacted from the origin source in place (re-compressed with
    the chunk's recorded codec + dictionary) and verified against its
    content address (store/heal.py). None disables healing-from-origin
    for this store (replica healing still works).

    ``codec`` names the chunk payload codec (config.STORE_CODEC_SPECS;
    default "zlib"): "raw" writes the v1-era uncompressed payload,
    "zlib" deflates each chunk, "zlib-dict" additionally trains a
    shared preset dictionary per contig during this same single pass.
    """
    from spark_examples_tpu.ingest import bitpack

    if chunk_variants <= 0 or chunk_variants % bitpack.VARIANTS_PER_BYTE:
        raise ValueError(
            f"chunk_variants must be a positive multiple of "
            f"{bitpack.VARIANTS_PER_BYTE}, got {chunk_variants}"
        )
    workers = int(workers)
    if workers < 1:
        raise ValueError(f"compact workers must be >= 1, got {workers}")
    base_codec, with_dict = codecmod.parse_spec(
        codec or codecmod.DEFAULT_SPEC)
    n = source.n_samples
    os.makedirs(os.path.join(path, CHUNK_DIR), exist_ok=True)
    book = None
    if with_dict:
        os.makedirs(os.path.join(path, codecmod.DICT_DIR), exist_ok=True)
        book = _DictBook(path)

    if workers > 1:
        from spark_examples_tpu.ingest.parallel import (
            parallel_blocks, parallel_map_ordered,
        )

        block_iter = _tag_first_of_contig(
            parallel_blocks(source, chunk_variants, workers))

        def emit(item):
            block, meta, first = item
            return meta, _write_chunk(path, block, base_codec, book,
                                      first, meta.contig)

        emitted = parallel_map_ordered(block_iter, emit, workers,
                                       name="compact-chunk")
    else:
        emitted = (
            (meta, _write_chunk(path, block, base_codec, book, first,
                                meta.contig))
            for block, meta, first in _tag_first_of_contig(
                source.blocks(chunk_variants))
        )

    records: list[ChunkRecord] = []
    chunk_positions: list[np.ndarray | None] = []
    written = 0  # variants consumed from the stream
    for meta, (digest, raw_size, stored_size, dict_digest) in emitted:
        if meta.start != written:
            raise ValueError(
                f"non-contiguous block stream: expected start {written}, "
                f"got {meta.start}"
            )
        pos_lo = pos_hi = -1
        if meta.positions is not None and len(meta.positions):
            chunk_positions.append(np.asarray(meta.positions, np.int64))
            pos_lo = int(meta.positions[0])
            pos_hi = int(meta.positions[-1])
        else:
            chunk_positions.append(None)
        records.append(ChunkRecord(
            start=meta.start, stop=meta.stop, contig=meta.contig,
            digest=digest, pos_lo=pos_lo, pos_hi=pos_hi,
            codec=base_codec, raw_size=raw_size,
            stored_size=stored_size, dict_digest=dict_digest,
        ))
        written = meta.stop
    # The declared count is consulted AFTER the stream: a completed full
    # pass caches it on parse-counting sources (VcfSource), so the
    # compaction never pays the serial pre-scan pass the reader would
    # otherwise run up front — a pure serial term the parallel engine
    # could not have absorbed.
    v = source.n_variants
    if written != v:
        raise ValueError(
            f"source stream ended at {written} of {v} declared variants"
        )
    if not records:
        raise ValueError("source yielded no variants — nothing to compact")

    positions = np.full(v, -1, np.int64)
    for rec, cp in zip(records, chunk_positions):
        if cp is not None:
            positions[rec.start:rec.stop] = cp
    has_positions = bool((positions >= 0).all())
    positions_digest = None
    if has_positions:
        pos_path = os.path.join(path, POSITIONS_NAME)
        tmp = pos_path + f".tmp.{os.getpid()}"
        with open(tmp, "wb") as f:
            tee = hashing.TeeHashWriter(f)
            np.save(tee, positions)
        os.replace(tmp, pos_path)
        positions_digest = tee.sha256.hexdigest()

    manifest = StoreManifest(
        n_samples=n,
        n_variants=v,
        chunk_variants=chunk_variants,
        sample_hash=hashing.sample_hash(source.sample_ids),
        chunks=records,
        sample_ids=list(source.sample_ids),
        has_positions=has_positions,
        positions_digest=positions_digest,
        origin=origin,
    )
    manifest.save(path)  # the commit point
    return manifest

"""Compaction: stream any GenotypeSource ONCE into the block store.

The ETL tier of the catalog (the reference's "load the cohort into the
BigQuery table once" job shape): every block the source yields becomes
one 2-bit-packed chunk file named by the sha256 of its bytes, and the
manifest — written last, atomically — records the variant/contig/
position index over them. Because the name IS the content:

- a re-run over identical data rewrites nothing (chunk writes are
  skipped when the address already exists — dedupe for free);
- a partially-written chunk can never be mistaken for a good one
  (files land via tmp + rename, and the reader re-hashes against the
  address on first touch anyway);
- a crashed compaction leaves no manifest, so the store simply does
  not exist yet — re-running is always safe.

Chunks inherit the source's "blocks never span a contig" contract
(``source.blocks`` flushes at contig boundaries), so every catalog row
has an exact contig and the store can answer range queries without
touching data.
"""

from __future__ import annotations

import os
import threading

import numpy as np

from spark_examples_tpu.core import hashing, telemetry
from spark_examples_tpu.store.manifest import (
    CHUNK_DIR,
    POSITIONS_NAME,
    ChunkRecord,
    StoreManifest,
)


def _write_chunk(path: str, block: np.ndarray) -> tuple[str, int]:
    """Pack + hash + (dedupe-aware) write one chunk; returns (digest,
    width). Runs in a pool worker under ``workers > 1`` — everything
    here (the native 2-bit pack, sha256 over the packed bytes, the file
    write) releases the GIL, which is what makes stage B scale."""
    from spark_examples_tpu.ingest import bitpack

    packed = bitpack.pack_dosages(np.ascontiguousarray(block))
    data = packed.tobytes()
    digest = hashing.sha256_bytes(data)
    fname = os.path.join(path, CHUNK_DIR, f"{digest}.bin")
    # Dedupe by content address — but a wrong-SIZED file under the
    # right name is a truncated write (or a quarantined chunk), and
    # re-running the compaction must heal it, not trust the name.
    # Same-size bit rot is the read path's job (first-touch digest
    # verify); healing it means deleting the quarantined file and
    # re-running this compaction.
    try:
        fresh = os.path.getsize(fname) != len(data)
    except OSError:
        fresh = True
    if fresh:
        tmp = fname + f".tmp.{os.getpid()}.{threading.get_ident()}"
        with open(tmp, "wb") as f:
            f.write(data)
        os.replace(tmp, fname)
        telemetry.count("store.compact_bytes", float(len(data)))
    telemetry.count("store.compact_chunks")
    return digest, block.shape[1]


@telemetry.traced("store.compact", cat="store")
def compact(path: str, source, chunk_variants: int = 16384,
            workers: int = 1, origin: dict | None = None) -> StoreManifest:
    """Stream ``source`` into a content-addressed store at ``path``.

    ``chunk_variants`` is the catalog granularity: the unit of range
    addressing, integrity verification, and decode caching. It must be
    divisible by 4 so full chunks stay byte-aligned on the 2-bit grid
    (which is what lets the reader hand out zero-copy packed slices).
    Returns the committed manifest.

    ``workers > 1`` runs the parallel ingest engine (ingest/parallel.py)
    under the SAME output contract — byte-identical chunks and manifest:
    stage A fans the parse out where the source allows it (VCF byte
    ranges, exact-source block stripes), stage B packs + hashes + writes
    each chunk in a second bounded pool, both reassembled in order. The
    serial ``workers=1`` path below is the semantic reference.

    ``origin`` (an IngestConfig-shaped dict — build one with
    ``store.heal.origin_from_ingest``) is recorded in the manifest as
    the store's self-healing recipe: a later corrupt chunk can be
    re-compacted from the origin source in place and verified against
    its content address (store/heal.py). None disables healing-from-
    origin for this store (replica healing still works).
    """
    from spark_examples_tpu.ingest import bitpack

    if chunk_variants <= 0 or chunk_variants % bitpack.VARIANTS_PER_BYTE:
        raise ValueError(
            f"chunk_variants must be a positive multiple of "
            f"{bitpack.VARIANTS_PER_BYTE}, got {chunk_variants}"
        )
    workers = int(workers)
    if workers < 1:
        raise ValueError(f"compact workers must be >= 1, got {workers}")
    n = source.n_samples
    os.makedirs(os.path.join(path, CHUNK_DIR), exist_ok=True)

    if workers > 1:
        from spark_examples_tpu.ingest.parallel import (
            parallel_blocks, parallel_map_ordered,
        )

        block_iter = parallel_blocks(source, chunk_variants, workers)

        def emit(item):
            block, meta = item
            digest, _w = _write_chunk(path, block)
            return meta, digest

        emitted = parallel_map_ordered(block_iter, emit, workers,
                                       name="compact-chunk")
    else:
        emitted = (
            (meta, _write_chunk(path, block)[0])
            for block, meta in source.blocks(chunk_variants)
        )

    records: list[ChunkRecord] = []
    chunk_positions: list[np.ndarray | None] = []
    written = 0  # variants consumed from the stream
    for meta, digest in emitted:
        if meta.start != written:
            raise ValueError(
                f"non-contiguous block stream: expected start {written}, "
                f"got {meta.start}"
            )
        pos_lo = pos_hi = -1
        if meta.positions is not None and len(meta.positions):
            chunk_positions.append(np.asarray(meta.positions, np.int64))
            pos_lo = int(meta.positions[0])
            pos_hi = int(meta.positions[-1])
        else:
            chunk_positions.append(None)
        records.append(ChunkRecord(
            start=meta.start, stop=meta.stop, contig=meta.contig,
            digest=digest, pos_lo=pos_lo, pos_hi=pos_hi,
        ))
        written = meta.stop
    # The declared count is consulted AFTER the stream: a completed full
    # pass caches it on parse-counting sources (VcfSource), so the
    # compaction never pays the serial pre-scan pass the reader would
    # otherwise run up front — a pure serial term the parallel engine
    # could not have absorbed.
    v = source.n_variants
    if written != v:
        raise ValueError(
            f"source stream ended at {written} of {v} declared variants"
        )
    if not records:
        raise ValueError("source yielded no variants — nothing to compact")

    positions = np.full(v, -1, np.int64)
    for rec, cp in zip(records, chunk_positions):
        if cp is not None:
            positions[rec.start:rec.stop] = cp
    has_positions = bool((positions >= 0).all())
    positions_digest = None
    if has_positions:
        pos_path = os.path.join(path, POSITIONS_NAME)
        tmp = pos_path + f".tmp.{os.getpid()}"
        with open(tmp, "wb") as f:
            tee = hashing.TeeHashWriter(f)
            np.save(tee, positions)
        os.replace(tmp, pos_path)
        positions_digest = tee.sha256.hexdigest()

    manifest = StoreManifest(
        n_samples=n,
        n_variants=v,
        chunk_variants=chunk_variants,
        sample_hash=hashing.sample_hash(source.sample_ids),
        chunks=records,
        sample_ids=list(source.sample_ids),
        has_positions=has_positions,
        positions_digest=positions_digest,
        origin=origin,
    )
    manifest.save(path)  # the commit point
    return manifest

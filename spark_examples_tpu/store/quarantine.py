"""Quarantine bookkeeping: the store's append-only corruption ledger.

``quarantine.json`` is the operator-facing record of every chunk whose
bytes stopped matching their content address. It is written by readers
(possibly several readahead workers at once, possibly several processes
sharing the store directory), read by the healer, and trimmed when a
chunk is healed — so the file discipline matters more than the format:

- **Atomic.** Every write lands via tmp + ``os.replace`` (the tmp name
  carries pid + thread id, so concurrent writers never collide on it);
  a reader can never observe a torn ledger.
- **Idempotent.** Entries are keyed by chunk digest: two readahead
  workers quarantining the same chunk in the same millisecond produce
  ONE entry, and re-quarantining an already-recorded chunk is a no-op.
- **Locked in-process.** A process-wide lock per (realpath'd) store
  root serializes the read-modify-write, so concurrent in-process
  writers cannot lose each other's updates. Cross-process writers are
  protected by the rename atomicity (last writer wins on the FILE, but
  each writer re-reads first, so a lost update needs two processes
  racing within one read-modify-write window — and the healer re-checks
  the chunk bytes themselves, never trusting the ledger alone).
"""

from __future__ import annotations

import json
import os
import threading
import warnings

from spark_examples_tpu.store.manifest import QUARANTINE_NAME

_locks: dict[str, threading.Lock] = {}
_locks_guard = threading.Lock()


def _lock_for(root: str) -> threading.Lock:
    key = os.path.realpath(root)
    with _locks_guard:
        lock = _locks.get(key)
        if lock is None:
            lock = _locks[key] = threading.Lock()
        return lock


def _path(root: str) -> str:
    return os.path.join(root, QUARANTINE_NAME)


def load(root: str) -> list[dict]:
    """The current ledger ([] when absent or unreadable — a torn ledger
    must never block the read path that is trying to report damage)."""
    try:
        with open(_path(root)) as f:
            entries = json.load(f)
        return entries if isinstance(entries, list) else []
    except (OSError, ValueError):
        return []


def _write(root: str, entries: list[dict]) -> None:
    qpath = _path(root)
    tmp = qpath + f".tmp.{os.getpid()}.{threading.get_ident()}"
    with open(tmp, "w") as f:
        json.dump(entries, f)
    os.replace(tmp, qpath)


def record(root: str, entry: dict) -> bool:
    """Append ``entry`` unless its digest is already recorded. Returns
    True when the ledger changed. Never raises: a full disk must not
    mask the corruption error the caller is about to raise."""
    with _lock_for(root):
        try:
            entries = load(root)
            if any(e.get("digest") == entry.get("digest") for e in entries):
                return False
            entries.append(entry)
            _write(root, entries)
            return True
        except OSError as e:
            warnings.warn(
                f"store: could not record quarantined chunk in "
                f"{_path(root)} ({e}) — the corruption error still "
                "stands",
                RuntimeWarning, stacklevel=3,
            )
            return False


def remove(root: str, digest: str) -> bool:
    """Drop the entry for ``digest`` (a healed chunk). Returns True when
    an entry was removed."""
    with _lock_for(root):
        try:
            entries = load(root)
            kept = [e for e in entries if e.get("digest") != digest]
            if len(kept) == len(entries):
                return False
            if kept:
                _write(root, kept)
            else:
                # An empty ledger is represented by NO file (the healthy
                # state a fresh store starts in).
                try:
                    os.remove(_path(root))
                except FileNotFoundError:
                    pass
            return True
        except OSError as e:
            warnings.warn(
                f"store: could not update quarantine ledger at "
                f"{_path(root)} ({e})",
                RuntimeWarning, stacklevel=3,
            )
            return False

"""The store catalog: one JSON manifest describing every chunk.

The manifest is the analog of the reference's BigQuery table metadata +
genomic-range partitioners in one document: which variants exist, in
what order, on which contig, at which positions, and — because chunk
files are content-addressed — exactly which bytes hold them. It is
written LAST by the compaction writer (tmp + rename), so a store either
has a complete, verifiable manifest or does not exist; a crashed
compaction can never present a half-catalog.

Layout on disk::

    <store>/
      manifest.json        the catalog (this module)
      chunks/<sha256>.bin  raw (N, ceil(w/4)) uint8 rows, one per chunk
      positions.npy        optional per-variant int64 positions
      quarantine.json      reader-appended record of corrupt chunks

Loading mirrors ``load_model()``'s :class:`ModelFormatError` treatment:
every way a manifest can be unusable — missing, truncated, pre-
versioning, from a newer build, a required field absent — raises a
:class:`StoreFormatError` naming the cause, never a raw ``KeyError``.
"""

from __future__ import annotations

import bisect
import json
import os
from dataclasses import dataclass, field

from spark_examples_tpu.core.sidecar import load_versioned_sidecar
from spark_examples_tpu.ingest import bitpack

# Bump when a field is added/renamed/re-semanticized. Version 2 added
# the optional ``origin`` record (how the store was compacted — the
# self-healing recipe); version-1 manifests load fine with origin=None.
# Version 3 added per-chunk payload codecs (store/codec.py): chunk
# rows grew codec / raw_size / stored_size / dict_digest columns, and
# the content address became the sha256 of the STORED (possibly
# compressed) bytes — which for v1/v2 rows (codec "raw") is the same
# bytes it always was, so older stores read back untouched. load()
# refuses files from NEWER builds, files without a version, and chunk
# rows naming a codec this build does not know, rather than guessing.
STORE_SCHEMA_VERSION = 3

MANIFEST_NAME = "manifest.json"
CHUNK_DIR = "chunks"
POSITIONS_NAME = "positions.npy"
QUARANTINE_NAME = "quarantine.json"

_REQUIRED = ("schema_version", "n_samples", "n_variants",
             "chunk_variants", "sample_hash", "chunks")


class StoreFormatError(ValueError):
    """A store/manifest that cannot be safely interpreted: missing or
    truncated manifest, pre-versioning or future schema, or a required
    field absent — always with the offending cause named."""


class StoreCorruptError(ValueError):
    """A chunk whose bytes no longer match their content address (or a
    truncated chunk file). Carries the resume cursor (``.cursor``, the
    chunk's first global variant) so a job can resume from a checkpoint
    once the chunk is recovered. A ValueError on purpose: the retry
    layer (ingest/resilient.py) treats it as damage, not weather — it
    is never retried and never silently skipped."""

    def __init__(self, msg: str, cursor: int = 0):
        super().__init__(msg)
        self.cursor = cursor


@dataclass(frozen=True)
class ChunkRecord:
    """One chunk's catalog row: where its variants sit in the global
    order (``[start, stop)``), which contig they belong to (chunks never
    span one), the position range they cover (-1 when the source carried
    none), the sha256 content address of its STORED bytes, and how the
    stored bytes encode the packed payload: ``codec`` (store/codec.py),
    ``raw_size`` (packed payload bytes — redundant with the geometry,
    recorded as a decode cross-check), ``stored_size`` (on-disk bytes;
    the truncation check compression took away from the mmap shape),
    and ``dict_digest`` (the shared preset dictionary, when one was
    trained). v1/v2 rows load as codec "raw" with sizes derived from
    the geometry — stored bytes == packed payload, as always."""

    start: int
    stop: int
    contig: str | None
    digest: str
    pos_lo: int = -1
    pos_hi: int = -1
    codec: str = "raw"
    raw_size: int = -1       # -1 = derive from geometry (v1/v2 rows)
    stored_size: int = -1    # -1 = raw_size (uncompressed)
    dict_digest: str | None = None

    @property
    def width(self) -> int:
        return self.stop - self.start

    def n_bytes(self, n_samples: int) -> int:
        """Packed payload bytes (the decoded-from-disk size)."""
        return n_samples * bitpack.packed_width(self.width)

    def payload_size(self, n_samples: int) -> int:
        return self.raw_size if self.raw_size >= 0 else self.n_bytes(n_samples)

    def disk_size(self, n_samples: int) -> int:
        """Expected on-disk size of the stored chunk file."""
        if self.stored_size >= 0:
            return self.stored_size
        return self.payload_size(n_samples)

    def filename(self) -> str:
        return os.path.join(CHUNK_DIR, f"{self.digest}.bin")


@dataclass
class StoreManifest:
    n_samples: int
    n_variants: int
    chunk_variants: int
    sample_hash: str
    chunks: list[ChunkRecord]
    sample_ids: list[str] | None = None
    has_positions: bool = False
    positions_digest: str | None = None
    # How this store was compacted (an IngestConfig-shaped dict — see
    # store/heal.py): with it, a corrupt chunk can be re-compacted from
    # the origin source IN PLACE (content addressing makes the repair
    # verifiable: the rebuilt bytes must hash to the chunk's name).
    # None (and every version-1 manifest) means "no healing recipe".
    origin: dict | None = None
    schema_version: int = STORE_SCHEMA_VERSION
    # Derived indexes (built once in __post_init__, not serialized).
    _starts: list[int] = field(default_factory=list, repr=False)
    _runs: list[tuple[str | None, int]] = field(default_factory=list,
                                                repr=False)

    def __post_init__(self):
        self._starts = [c.start for c in self.chunks]
        self._runs = []
        for c in self.chunks:
            if not self._runs or self._runs[-1][0] != c.contig:
                self._runs.append((c.contig, c.start))

    # -- catalog queries ---------------------------------------------------

    @property
    def contig_runs(self) -> list[tuple[str | None, int]]:
        """[(contig, first_variant), ...] in stream order — run i spans
        [start_i, start_{i+1})."""
        return list(self._runs)

    def segment_bounds(self) -> list[int]:
        """Variant boundaries dense blocks must not cross (the "blocks
        never span a contig" contract every file source keeps)."""
        return [s for _c, s in self._runs] + [self.n_variants]

    def contig_span(self, contig: str) -> tuple[int, int]:
        """Global variant range [lo, hi) of ``contig`` (empty (0, 0)
        when the store has no such contig — the same "filter matched
        nothing" semantics as the VCF region filter)."""
        bounds = self.segment_bounds()
        for i, (c, s) in enumerate(self._runs):
            if c == contig:
                return s, bounds[i + 1]
        return 0, 0

    def chunks_for_range(self, lo: int, hi: int) -> list[tuple[int, ChunkRecord]]:
        """(index, record) of every chunk overlapping variants [lo, hi),
        by bisection over the catalog — a range query touches only the
        chunks that hold it, never the whole store."""
        if hi <= lo:
            return []
        i = bisect.bisect_right(self._starts, lo) - 1
        i = max(i, 0)
        out = []
        while i < len(self.chunks) and self.chunks[i].start < hi:
            if self.chunks[i].stop > lo:
                out.append((i, self.chunks[i]))
            i += 1
        return out

    # -- (de)serialization -------------------------------------------------

    def to_json(self) -> dict:
        return {
            "schema_version": self.schema_version,
            "n_samples": self.n_samples,
            "n_variants": self.n_variants,
            "chunk_variants": self.chunk_variants,
            "sample_hash": self.sample_hash,
            "sample_ids": self.sample_ids,
            "has_positions": self.has_positions,
            "positions_digest": self.positions_digest,
            "origin": self.origin,
            "chunks": [
                [c.start, c.stop, c.contig, c.digest, c.pos_lo, c.pos_hi,
                 c.codec, c.raw_size, c.stored_size, c.dict_digest]
                for c in self.chunks
            ],
        }

    def save(self, root: str) -> None:
        """Atomic write — the manifest landing IS the store's commit."""
        path = os.path.join(root, MANIFEST_NAME)
        tmp = path + f".tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(self.to_json(), f)
        os.replace(tmp, path)

    @classmethod
    def load(cls, root: str) -> "StoreManifest":
        path = os.path.join(root, MANIFEST_NAME)
        raw = load_versioned_sidecar(
            path,
            current_version=STORE_SCHEMA_VERSION,
            required=_REQUIRED,
            error_cls=StoreFormatError,
            noun="store manifest",
            missing_msg=(
                f"{root!r} is not a dataset store: no {MANIFEST_NAME} "
                "(compact one with `ingest --output-path <dir>`; a "
                "missing manifest after a crash means the compaction "
                "never committed — re-run it)"
            ),
            repair="re-run the compaction",
        )
        version = raw["schema_version"]
        try:
            chunks = []
            for row in raw["chunks"]:
                if len(row) == 6:  # v1/v2 rows: stored bytes == payload
                    s, t, c, d, pl, ph = row
                    chunks.append(ChunkRecord(int(s), int(t), c, d,
                                              int(pl), int(ph)))
                else:
                    s, t, c, d, pl, ph, codec, rs, ss, dd = row
                    chunks.append(ChunkRecord(
                        int(s), int(t), c, d, int(pl), int(ph),
                        codec=str(codec), raw_size=int(rs),
                        stored_size=int(ss), dict_digest=dd,
                    ))
        except (TypeError, ValueError) as e:
            raise StoreFormatError(
                f"store manifest {path!r}: malformed chunk record ({e})"
            ) from None
        # Unknown-codec rejection belongs HERE, not at first read: a
        # store written by a newer build with a codec this build cannot
        # inflate must fail like a future schema — loudly, up front —
        # never as a mid-stream decode error at chunk 40 000.
        from spark_examples_tpu.store.codec import CODECS

        for i, c in enumerate(chunks):
            if c.codec not in CODECS:
                raise StoreFormatError(
                    f"store manifest {path!r}: chunk {i} uses unknown "
                    f"codec {c.codec!r} (this build decodes "
                    f"{' / '.join(CODECS)}) — the store was written by "
                    "a newer build; upgrade, or re-compact with a "
                    "supported --store-codec"
                )
        return cls(
            n_samples=int(raw["n_samples"]),
            n_variants=int(raw["n_variants"]),
            chunk_variants=int(raw["chunk_variants"]),
            sample_hash=raw["sample_hash"],
            chunks=chunks,
            sample_ids=raw.get("sample_ids"),
            has_positions=bool(raw.get("has_positions", False)),
            positions_digest=raw.get("positions_digest"),
            origin=raw.get("origin"),
            schema_version=version,
        )

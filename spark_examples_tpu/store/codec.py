"""Chunk payload codecs: entropy coding between the 2-bit pack and disk.

The streamed end-to-end path is feed-bound ~100-400x vs chip compute
(BENCH_r02-r05): every byte a chunk does NOT occupy on disk is a byte
the link never ships and the verifier never hashes. Genotype dosage
data is extremely low-entropy (long runs of homozygous-reference
codes), so a per-chunk deflate pass shrinks the already-4x-packed
payload several-fold more — and the content address stays the sha256
of the *stored* (compressed) bytes, so dedupe, quarantine, replica
heal, and `store heal` re-verification are untouched by compression.

Codec registry (per-chunk, recorded in the manifest's v3 rows):

- ``raw``   — the stored bytes ARE the packed payload (v1/v2 stores,
  and ``--store-codec raw``). Zero-copy mmap reads survive.
- ``zlib``  — per-chunk deflate at a FIXED level/strategy (the codec
  name pins the parameters: compression must be byte-deterministic so
  parallel compaction, kill/resume re-compaction, and origin healing
  all reproduce identical stored bytes). An optional preset
  dictionary — trained during ``compact()`` from the first chunk of
  each contig and shared by that contig's chunks (``zlib-dict``) —
  rides along as a content-addressed ``dicts/<sha256>.zdict`` file,
  with the digest recorded per chunk.

Decode has two implementations, pinned bit-identical:

- **native** — ``store_decode_chunk`` in native/codec.cpp: one
  GIL-released C call that inflates AND 2-bit-unpacks straight into a
  caller-provided slab (arbitrary column offset/row stride),
  collapsing the decompress -> Python bytes -> unpack -> copy-to-slab
  hop chain of the pure-Python route into zero intermediate buffers;
- **Python** — :func:`decompress` + ``bitpack.unpack_dosages_np`` +
  a slice copy. Selected when the native library (or the symbol — a
  stale binary) is absent, counted once per process as
  ``store.codec.fallback`` and warned about, so a build problem
  degrades loudly instead of silently running the slow path.

Corrupt compressed bytes behave exactly like corrupt raw bytes: the
sha256 first-touch verify catches bit rot/truncation before any
inflate runs, and an inflate/size failure that slips past a disabled
verify raises :class:`StoreDecodeError`, which the reader routes
through the same heal -> quarantine path as a digest mismatch.
"""

from __future__ import annotations

import os
import threading
import warnings
import zlib

import numpy as np

from spark_examples_tpu.core import telemetry
from spark_examples_tpu.core.config import STORE_CODEC_SPECS

RAW = "raw"
ZLIB = "zlib"
#: Codecs a chunk record may name. A manifest naming anything else is
#: rejected at load time with a StoreFormatError (store/manifest.py).
CODECS = (RAW, ZLIB)
#: --store-codec spellings (config.STORE_CODEC_SPECS is the source of
#: truth; "zlib-dict" = the zlib codec + the per-contig dictionary).
SPECS = STORE_CODEC_SPECS
DEFAULT_SPEC = ZLIB

# The deflate parameters ARE part of the codec's identity: stored
# bytes must be reproducible bit-for-bit by a later re-compaction
# (dedupe, kill/resume idempotence, origin healing). Changing any of
# these requires a NEW codec name, never a quiet retune.
ZLIB_LEVEL = 6
_ZLIB_WBITS = 15
_ZLIB_MEMLEVEL = 8

# zlib's deflate window is 32 KiB — a longer preset dictionary would
# be silently ignored past that.
DICT_MAX_BYTES = 32768
DICT_DIR = "dicts"

# native/codec.cpp store_decode_chunk codec ids.
CODEC_IDS = {RAW: 0, ZLIB: 1}


class StoreDecodeError(ValueError):
    """Stored chunk bytes that cannot be decoded (inflate failure or a
    decompressed size that contradicts the catalog). With verification
    on this is unreachable for disk damage — sha256 catches it first —
    so the reader treats it exactly like a digest mismatch: heal if a
    route exists, else quarantine."""


def parse_spec(spec: str) -> tuple[str, bool]:
    """``--store-codec`` spelling -> (base codec, train per-contig
    dictionary). Raises with the flag named (config-time convention)."""
    if spec == "zlib-dict":
        return ZLIB, True
    if spec in CODECS:
        return spec, False
    raise ValueError(
        f"bad ingest config: store_codec={spec!r} — expected one of "
        f"{' | '.join(SPECS)} (raw = no compression, zlib = per-chunk "
        "deflate, zlib-dict = deflate with a per-contig dictionary "
        "trained during compaction)"
    )


def train_dict(raw: bytes) -> bytes:
    """Deterministic preset dictionary from a contig's first chunk's
    packed payload: its trailing window (deflate scores matches near
    the dictionary's END highest, and any slice of real genotype rows
    is representative). Pure function of the bytes — a re-compaction
    or an origin heal re-derives the identical dictionary."""
    return bytes(raw[-DICT_MAX_BYTES:])


def dict_path(root: str, digest: str) -> str:
    return os.path.join(root, DICT_DIR, f"{digest}.zdict")


def compress(codec: str, raw: bytes, zdict: bytes | None = None) -> bytes:
    """Packed payload -> stored bytes (identity for ``raw``)."""
    if codec == RAW:
        return raw
    if codec == ZLIB:
        if zdict:
            c = zlib.compressobj(ZLIB_LEVEL, zlib.DEFLATED, _ZLIB_WBITS,
                                 _ZLIB_MEMLEVEL, zlib.Z_DEFAULT_STRATEGY,
                                 zdict)
        else:
            c = zlib.compressobj(ZLIB_LEVEL, zlib.DEFLATED, _ZLIB_WBITS,
                                 _ZLIB_MEMLEVEL, zlib.Z_DEFAULT_STRATEGY)
        return c.compress(raw) + c.flush()
    raise ValueError(f"unknown store codec {codec!r}")


def decompress(codec: str, stored, raw_size: int,
               zdict: bytes | None = None) -> bytes:
    """Stored bytes -> packed payload (the Python reference path; the
    zlib module wraps the same libz the native entry links, so the two
    accept exactly the same streams)."""
    if codec == RAW:
        data = bytes(stored)
        if len(data) != raw_size:
            raise StoreDecodeError(
                f"raw chunk payload is {len(data)} bytes, catalog says "
                f"{raw_size}"
            )
        return data
    if codec == ZLIB:
        d = (zlib.decompressobj(_ZLIB_WBITS, zdict=zdict) if zdict
             else zlib.decompressobj(_ZLIB_WBITS))
        try:
            out = d.decompress(bytes(stored), raw_size + 1)
            out += d.flush()
        except zlib.error as e:
            raise StoreDecodeError(
                f"zlib inflate failed ({e}) — stored bytes are not a "
                "valid deflate stream for this chunk"
            ) from None
        if len(out) != raw_size or not d.eof:
            raise StoreDecodeError(
                f"zlib chunk decompressed to {len(out)} bytes "
                f"(eof={d.eof}), catalog says {raw_size}"
            )
        return out
    raise ValueError(f"unknown store codec {codec!r}")


# ---------------------------------------------------------------------------
# Decode-to-slab: one call from stored bytes to dense dosages.

_fallback_lock = threading.Lock()
_fallback_warned = False


def _note_fallback() -> None:
    """The Python decode path was selected because the native entry is
    unavailable: count it (once — `store.codec.fallback` is a selection
    flag, not a per-call rate) and warn once per process, EXCEPT under
    SPARK_TPU_NO_NATIVE, where the fallback is a deliberate test pin."""
    global _fallback_warned
    with _fallback_lock:
        # check-then-count must sit under the lock: the readahead
        # pool's first decodes land here concurrently, and two threads
        # passing the ==0 check would break the once-per-process flag.
        if telemetry.counter_value("store.codec.fallback") == 0:
            telemetry.count("store.codec.fallback")
        if os.environ.get("SPARK_TPU_NO_NATIVE") or _fallback_warned:
            return
        _fallback_warned = True
    warnings.warn(
        "store: native decode-to-slab entry (store_decode_chunk) is "
        "unavailable — a stale libsparktpu build or no g++; store reads "
        "run the pure-Python decode path (bit-identical, measurably "
        "slower). Rebuild the native library to restore the fast path.",
        RuntimeWarning, stacklevel=3,
    )


def native_decode_available() -> bool:
    from spark_examples_tpu import native

    return native.has_store_decode()


def decode_into(stored, codec: str, zdict: bytes | None, n: int,
                w_bytes: int, v0: int, v1: int, out: np.ndarray,
                col_off: int = 0) -> None:
    """Decode variants ``[v0, v1)`` of one stored chunk into
    ``out[:, col_off : col_off + (v1 - v0)]``.

    ``stored`` is the chunk file's bytes (any uint8 buffer — typically
    the verified mmap); ``n`` x ``w_bytes`` is the packed payload
    geometry from the catalog. ``out`` must be C-contiguous int8 with
    at least ``col_off + (v1 - v0)`` columns — a decode-cache entry, a
    read_range destination, or a prefetch staging-ring slab. Native
    when available (one GIL-released decompress+unpack, no
    intermediate buffers), Python otherwise — bit-identical either
    way. Raises :class:`StoreDecodeError` on undecodable bytes."""
    from spark_examples_tpu import native

    rc = native.store_decode_chunk(stored, CODEC_IDS[codec], zdict,
                                   n, w_bytes, v0, v1, out, col_off)
    if rc is None:
        _note_fallback()
        payload = decompress(codec, stored, n * w_bytes, zdict)
        from spark_examples_tpu.ingest import bitpack

        dense = bitpack.unpack_dosages_np(
            np.frombuffer(payload, np.uint8).reshape(n, w_bytes)
        )
        out[:, col_off:col_off + (v1 - v0)] = dense[:, v0:v1]
        return
    if rc:
        raise StoreDecodeError({
            1: f"native decode: unknown codec id for {codec!r}",
            2: "native decode: zlib inflate failed — stored bytes are "
               "not a valid deflate stream for this chunk",
            3: "native decode: decompressed size contradicts the "
               "catalog geometry",
            4: "native decode: payload buffer allocation failed",
        }.get(rc, f"native decode failed (rc={rc})"))

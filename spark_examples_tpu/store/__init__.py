"""Content-addressed genotype block store — the ingest-once catalog.

The reference fork's answer to "parse once, query forever" was a
BigQuery variant table fronted by genomic-range partitioners; every
job after the initial load read columnar slices, never the source
files. This package is the TPU-native successor: ``compact`` streams
any :class:`~spark_examples_tpu.ingest.source.GenotypeSource` ONCE
into 2-bit-packed chunk files whose names ARE their sha256 content
digests, plus a JSON manifest (the catalog: schema version, sample
ids, per-chunk variant/contig/position index, digests). ``open_store``
returns a :class:`~spark_examples_tpu.store.reader.StoreSource` that
drops into every job surface unchanged — mmap zero-copy reads, a
bounded host-RAM decode cache, contig/position range queries, resume
cursors, and read-time digest verification with corrupt-chunk
quarantine (provable under the ``store.read`` fault site).
"""

from spark_examples_tpu.store.cache import DecodeCache  # noqa: F401
from spark_examples_tpu.store.codec import (  # noqa: F401
    StoreDecodeError,
)
# NOTE: the heal FUNCTION stays addressed as store.heal.heal — binding
# it here would shadow the submodule under the same attribute name.
from spark_examples_tpu.store.heal import (  # noqa: F401
    HealError,
    heal_chunk,
    origin_from_ingest,
    recover_dict,
)
from spark_examples_tpu.store.manifest import (  # noqa: F401
    STORE_SCHEMA_VERSION,
    ChunkRecord,
    StoreCorruptError,
    StoreFormatError,
    StoreManifest,
)
from spark_examples_tpu.store.reader import (  # noqa: F401
    StoreRangeSource,
    StoreSource,
    open_store,
)
from spark_examples_tpu.store.writer import compact  # noqa: F401

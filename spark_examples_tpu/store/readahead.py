"""Background readahead for the store read path: decode ahead of the
cursor so the store-cold tier runs at store-hit throughput.

Cold store reads pay mmap + first-touch sha256 verify + 2-bit decode
per chunk, serialized on the consumer thread while the chip (or the
next pipeline stage) waits. The readahead pool moves that work off the
critical path: as the streaming loops (``StoreSource.blocks`` /
``packed_blocks`` / range sources) advance, the next ``depth`` chunks
are decoded+verified by a small worker pool into the existing
:class:`~spark_examples_tpu.store.cache.DecodeCache`, so by the time
the cursor arrives the read is a cache hit. sha256 and the NumPy
unpack both release the GIL, so warming genuinely overlaps consumer
work (and other warms).

Error contract — workers never swallow and never crash a thread
silently: an exception raised while warming chunk ``i`` (an injected
``store.read`` fault, a real flaky read, a digest mismatch) is held and
**re-raised in the consumer thread when the cursor reaches chunk i** —
in order, with the chunk's own resume cursor — so it flows through the
exact same retry/fail-fast boundary (`ingest/resilient.py`) a
synchronous read would: transient ``IOError`` s get retried/reopened,
:class:`~spark_examples_tpu.store.manifest.StoreCorruptError` fails
fast with the quarantine recorded.
"""

from __future__ import annotations

import math
import threading
import time
from concurrent.futures import Future, ThreadPoolExecutor

from spark_examples_tpu.core import faults, telemetry

# Decode workers per pool: enough to overlap verify+decode with the
# consumer, few enough that a fleet of open stores doesn't breed
# threads. Depth (how far ahead to warm) is the operator's knob
# (--readahead-chunks, adaptively raised toward --readahead-chunks-max
# below); this is plumbing width, not policy.
MAX_WORKERS = 4

# Cadence/latency EWMA smoothing: ~4 samples of memory — fast enough
# to follow a phase change (compute-heavy stretch ends, consumer
# speeds up), slow enough that one hiccup does not saw the depth.
_EWMA_ALPHA = 0.25


class ReadaheadPool:
    """A bounded chunk-warming pool for one store reader, with
    cadence-adaptive depth.

    ``schedule(key, fn)`` submits ``fn`` (the decode/verify of one
    chunk) unless that key is already scheduled; ``consume(key)`` is
    called by the consumer on a cache miss — it waits out an in-flight
    warm of the same chunk (double-decoding would double-fire the
    ``store.read`` fault site and waste the work) and returns its
    value, re-raising the worker's exception if it failed. Keys never
    scheduled return None and the caller decodes inline. Keys are
    ``(transport, chunk_index)`` tuples: the dense and packed
    transports warm different artifacts (a cached decode vs a verified
    payload) and must never collide on a bare index.

    **Adaptive depth.** ``depth`` (how far ahead the reader schedules)
    breathes with the measured feed, driven by two signals. Ground
    truth first: a ``consume()`` that actually had to block on an
    unfinished warm means the window is too shallow, and the next
    retire deepens it by one (the EWMA ratio is distorted exactly
    then — a starved consumer's measured retire interval absorbs the
    decode wait, which would otherwise suppress deepening when it is
    most needed). Wait-free rounds settle toward the EWMA target: the
    consumer's PER-CHUNK retire cadence (``note_retire`` receives the
    cursor's chunk index, so the interval normalizes whatever the
    block grid — blocks finer than a chunk accumulate until a chunk
    boundary is crossed, coarser blocks divide by the chunks they
    retired) against the per-chunk warm latency (timed around every
    worker body); the target is the latency/cadence ratio plus one,
    clamped to [floor, max_depth], stepped down at most one per retire
    so the window breathes instead of sawing. A compute-bound consumer
    keeps the window — and the host RAM it pins — at the floor.
    ``max_depth <= floor`` disables adaptation (the pre-adaptive fixed
    behavior). The live depth is exported as the
    ``store.readahead.depth`` gauge so the supervisor and the live
    plane can watch the feed breathe: pinned at the ceiling really
    does mean the feed is decode/disk-bound (the consumer keeps
    arriving before the warms finish).
    """

    def __init__(self, depth: int, workers: int | None = None,
                 max_depth: int = 0):
        self.floor = max(1, int(depth))
        self.max_depth = max(self.floor, int(max_depth))
        self._depth = self.floor
        self._ex = ThreadPoolExecutor(
            max_workers=workers or min(self.max_depth, MAX_WORKERS),
            thread_name_prefix="store-readahead",
        )
        self._futures: dict[tuple, Future] = {}
        self._lock = threading.Lock()
        self._closed = False
        self._retire_ewma: float | None = None
        self._decode_ewma: float | None = None
        self._last_retire: float | None = None
        self._last_idx: int | None = None
        self._waited = False
        telemetry.gauge_set("store.readahead.depth", float(self._depth))

    @property
    def depth(self) -> int:
        """The current (possibly adapted) scheduling depth."""
        return self._depth

    @staticmethod
    def _ewma(old: float | None, sample: float) -> float:
        if old is None:
            return sample
        return old + _EWMA_ALPHA * (sample - old)

    @staticmethod
    def _target_depth(decode_s: float | None, retire_s: float | None,
                      floor: int, max_depth: int) -> int:
        """Pure policy: chunks the consumer retires per decode latency,
        plus one of slack, clamped — split out so the adaptation curve
        is unit-testable without threads or clocks."""
        if decode_s is None or retire_s is None:
            return floor
        target = 1 + math.ceil(decode_s / max(retire_s, 1e-9))
        return max(floor, min(max_depth, target))

    def note_retire(self, chunk_idx: int | None = None) -> None:
        """Consumer-cadence sample: called once per consumed block (the
        reader's ``_schedule_ahead``), with the cursor's chunk index so
        the interval normalizes to per-CHUNK cadence whatever the block
        grid. Re-targets the depth (see the class docstring)."""
        now = time.perf_counter()
        with self._lock:
            advance = 1
            if chunk_idx is not None:
                advance = (0 if self._last_idx is None
                           else max(chunk_idx - self._last_idx, 0))
                self._last_idx = chunk_idx
            if self._last_retire is None:
                self._last_retire = now
            elif advance > 0:
                self._retire_ewma = self._ewma(
                    self._retire_ewma, (now - self._last_retire) / advance)
                self._last_retire = now
            waited, self._waited = self._waited, False
            if self.max_depth <= self.floor:
                return
            if waited:
                new = min(self.max_depth, self._depth + 1)
            else:
                tgt = self._target_depth(self._decode_ewma,
                                         self._retire_ewma,
                                         self.floor, self.max_depth)
                new = tgt if tgt > self._depth else max(tgt, self._depth - 1)
            changed = new != self._depth
            self._depth = new
        if changed:
            telemetry.gauge_set("store.readahead.depth", float(new))

    def _warm(self, fn):
        """The worker body: the chaos site fires FIRST so an armed spec
        fails/stalls the warm inside the pool thread — proving the
        held-and-re-raised-at-the-cursor error contract (and that a
        worker death can never leak past `consume` silently). The whole
        body is timed into the decode-latency EWMA — an injected delay
        is indistinguishable from a slow disk, which is the point."""
        t0 = time.perf_counter()
        try:
            faults.fire("store.readahead.decode")
            return fn()
        finally:
            dt = time.perf_counter() - t0
            with self._lock:
                self._decode_ewma = self._ewma(self._decode_ewma, dt)

    def schedule(self, key: tuple, fn) -> None:
        with self._lock:
            if self._closed or key in self._futures:
                return
            if len(self._futures) >= 2 * self.depth:
                # Backstop against a consumer that skips chunks (range
                # queries): never hold more than 2x depth of warmed-but-
                # unconsumed chunks alive.
                return
            self._futures[key] = self._ex.submit(self._warm, fn)
            telemetry.gauge_set("store.readahead.in_flight",
                                float(len(self._futures)))
        telemetry.count("store.readahead.scheduled")

    def consume(self, key: tuple):
        """The consumer's rendezvous for one warm: the warmed value,
        the worker's re-raised exception, or None (never scheduled)."""
        with self._lock:
            fut = self._futures.pop(key, None)
            telemetry.gauge_set("store.readahead.in_flight",
                                float(len(self._futures)))
        if fut is None:
            return None
        if not fut.done():
            # The consumer is about to block on an unfinished warm —
            # ground truth that the window is too shallow; the next
            # retire deepens it (see the class docstring).
            with self._lock:
                self._waited = True
        t0 = time.perf_counter()
        try:
            value = fut.result()
        except BaseException:
            telemetry.count("store.readahead.errors")
            raise
        finally:
            telemetry.observe("store.readahead.wait_s",
                              time.perf_counter() - t0)
        telemetry.count("store.readahead.hits")
        return value

    def discard(self, key: tuple) -> None:
        """Drop a pending warm without waiting (a failed-and-retried
        stream re-schedules from its reopened reader)."""
        with self._lock:
            fut = self._futures.pop(key, None)
        if fut is not None:
            fut.cancel()

    def close(self) -> None:
        with self._lock:
            self._closed = True
            self._futures.clear()
        self._ex.shutdown(wait=False, cancel_futures=True)

"""Background readahead for the store read path: decode ahead of the
cursor so the store-cold tier runs at store-hit throughput.

Cold store reads pay mmap + first-touch sha256 verify + 2-bit decode
per chunk, serialized on the consumer thread while the chip (or the
next pipeline stage) waits. The readahead pool moves that work off the
critical path: as the streaming loops (``StoreSource.blocks`` /
``packed_blocks`` / range sources) advance, the next ``depth`` chunks
are decoded+verified by a small worker pool into the existing
:class:`~spark_examples_tpu.store.cache.DecodeCache`, so by the time
the cursor arrives the read is a cache hit. sha256 and the NumPy
unpack both release the GIL, so warming genuinely overlaps consumer
work (and other warms).

Error contract — workers never swallow and never crash a thread
silently: an exception raised while warming chunk ``i`` (an injected
``store.read`` fault, a real flaky read, a digest mismatch) is held and
**re-raised in the consumer thread when the cursor reaches chunk i** —
in order, with the chunk's own resume cursor — so it flows through the
exact same retry/fail-fast boundary (`ingest/resilient.py`) a
synchronous read would: transient ``IOError`` s get retried/reopened,
:class:`~spark_examples_tpu.store.manifest.StoreCorruptError` fails
fast with the quarantine recorded.
"""

from __future__ import annotations

import threading
import time
from concurrent.futures import Future, ThreadPoolExecutor

from spark_examples_tpu.core import faults, telemetry

# Decode workers per pool: enough to overlap verify+decode with the
# consumer, few enough that a fleet of open stores doesn't breed
# threads. Depth (how far ahead to warm) is the operator's knob
# (--readahead-chunks); this is plumbing width, not policy.
MAX_WORKERS = 4


class ReadaheadPool:
    """A bounded chunk-warming pool for one store reader.

    ``schedule(key, fn)`` submits ``fn`` (the decode/verify of one
    chunk) unless that key is already scheduled; ``consume(key)`` is
    called by the consumer on a cache miss — it waits out an in-flight
    warm of the same chunk (double-decoding would double-fire the
    ``store.read`` fault site and waste the work) and returns its
    value, re-raising the worker's exception if it failed. Keys never
    scheduled return None and the caller decodes inline. Keys are
    ``(transport, chunk_index)`` tuples: the dense and packed
    transports warm different artifacts (a cached decode vs a verified
    byte map) and must never collide on a bare index.
    """

    def __init__(self, depth: int, workers: int | None = None):
        self.depth = max(1, int(depth))
        self._ex = ThreadPoolExecutor(
            max_workers=workers or min(self.depth, MAX_WORKERS),
            thread_name_prefix="store-readahead",
        )
        self._futures: dict[tuple, Future] = {}
        self._lock = threading.Lock()
        self._closed = False

    @staticmethod
    def _warm(fn):
        """The worker body: the chaos site fires FIRST so an armed spec
        fails/stalls the warm inside the pool thread — proving the
        held-and-re-raised-at-the-cursor error contract (and that a
        worker death can never leak past `consume` silently)."""
        faults.fire("store.readahead.decode")
        return fn()

    def schedule(self, key: tuple, fn) -> None:
        with self._lock:
            if self._closed or key in self._futures:
                return
            if len(self._futures) >= 2 * self.depth:
                # Backstop against a consumer that skips chunks (range
                # queries): never hold more than 2x depth of warmed-but-
                # unconsumed chunks alive.
                return
            self._futures[key] = self._ex.submit(self._warm, fn)
            telemetry.gauge_set("store.readahead.in_flight",
                                float(len(self._futures)))
        telemetry.count("store.readahead.scheduled")

    def consume(self, key: tuple):
        """The consumer's rendezvous for one warm: the warmed value,
        the worker's re-raised exception, or None (never scheduled)."""
        with self._lock:
            fut = self._futures.pop(key, None)
            telemetry.gauge_set("store.readahead.in_flight",
                                float(len(self._futures)))
        if fut is None:
            return None
        t0 = time.perf_counter()
        try:
            value = fut.result()
        except BaseException:
            telemetry.count("store.readahead.errors")
            raise
        finally:
            telemetry.observe("store.readahead.wait_s",
                              time.perf_counter() - t0)
        telemetry.count("store.readahead.hits")
        return value

    def discard(self, key: tuple) -> None:
        """Drop a pending warm without waiting (a failed-and-retried
        stream re-schedules from its reopened reader)."""
        with self._lock:
            fut = self._futures.pop(key, None)
        if fut is not None:
            fut.cancel()

    def close(self) -> None:
        with self._lock:
            self._closed = True
            self._futures.clear()
        self._ex.shutdown(wait=False, cancel_futures=True)

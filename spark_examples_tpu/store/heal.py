"""Self-healing for the content-addressed store: repair, don't retire.

A quarantined chunk used to be a dead end: the read failed fast and the
operator re-compacted the whole store. But the content address makes a
repair *verifiable* — the healed bytes must hash to the chunk's own
filename — and the manifest (schema v2) records two recovery routes:

- **Replica.** Chunk files are content-addressed, so any peer store
  directory holding ``chunks/<digest>.bin`` holds THE chunk; healing is
  a verified copy, no manifest surgery.
- **Origin.** The manifest's ``origin`` record (an IngestConfig-shaped
  dict written by ``compact(..., origin=...)``) names the source the
  store was compacted from. Each catalog row is an origin *span*
  (``[start, stop)`` on the compaction's own block grid), so one chunk
  is re-compacted by re-streaming exactly that span — deterministic
  sources (synthetic, packed, VCF, another store) reproduce it bit for
  bit, and the digest check proves they did.

Both routes write tmp + rename and re-verify before the quarantine
entry is dropped, so a failed heal can never replace damage with
different damage. The reader (store/reader.py) calls :func:`heal_chunk`
inline on a verify failure — degradation instead of fail-fast whenever
a route is available — and the ``store heal`` CLI verb runs
:func:`heal` over the whole ledger for offline repair.
"""

from __future__ import annotations

import os
import shutil
import threading

from spark_examples_tpu.core import hashing, telemetry
from spark_examples_tpu.store import codec as codecmod
from spark_examples_tpu.store import quarantine
from spark_examples_tpu.store.manifest import ChunkRecord, StoreManifest


class HealError(RuntimeError):
    """No route could repair the chunk (no replica holds it, no origin
    is recorded, or the origin stream no longer reproduces the recorded
    digest). The original corruption error should follow."""


# IngestConfig fields that define the compacted stream (the healing
# recipe). Deliberately a closed list: transport/perf knobs (prefetch
# depth, worker counts, caches) cannot change the bytes and are not
# recorded.
_ORIGIN_FIELDS = (
    "source", "path", "n_samples", "n_variants", "n_populations", "seed",
    "maf", "max_missing", "ld_r2", "ld_window", "ld_carry",
)


def origin_from_ingest(cfg, chunk_variants: int) -> dict:
    """The manifest ``origin`` record for a compaction driven by
    ``cfg`` (an IngestConfig): every field that determines the stream's
    bytes, plus the chunk grid the spans were cut on. The source path
    is absolutized — a heal (or ``store heal``) runs from whatever
    working directory the LATER job happens to have, not the
    compaction's."""
    rec = {k: getattr(cfg, k) for k in _ORIGIN_FIELDS}
    if rec.get("path"):
        rec["path"] = os.path.abspath(rec["path"])
    rec["references"] = [str(r) for r in cfg.references]
    rec["chunk_variants"] = int(chunk_variants)
    return rec


def build_origin_source(origin: dict):
    """Rebuild the origin GenotypeSource from a manifest record."""
    from spark_examples_tpu.core.config import IngestConfig, ReferenceRange
    from spark_examples_tpu.pipelines.runner import build_source

    kw = {k: origin[k] for k in _ORIGIN_FIELDS if k in origin}
    kw["references"] = [ReferenceRange.parse(r)
                        for r in origin.get("references", [])]
    return build_source(IngestConfig(**kw))


def _raw_span_from_origin(rec: ChunkRecord, origin: dict,
                          source=None) -> bytes:
    """Re-compact one chunk span from the origin stream into its RAW
    packed payload (pre-compression); the caller re-compresses with the
    chunk's recorded codec and verifies the digest before installing."""
    import numpy as np

    from spark_examples_tpu.ingest import bitpack

    if source is None:
        source = build_origin_source(origin)
    chunk_variants = int(origin.get("chunk_variants", 16384))
    for block, meta in source.blocks(chunk_variants, start_variant=rec.start):
        if meta.start != rec.start or meta.stop != rec.stop:
            raise HealError(
                f"origin stream no longer matches the catalog: asked for "
                f"span [{rec.start}, {rec.stop}), got "
                f"[{meta.start}, {meta.stop}) — the origin changed since "
                "compaction; re-compact the store"
            )
        return bitpack.pack_dosages(np.ascontiguousarray(block)).tobytes()
    raise HealError(
        f"origin stream is shorter than the catalog (no block at "
        f"variant {rec.start}) — the origin changed since compaction"
    )


def _dict_trainer_record(manifest: StoreManifest,
                         dict_digest: str) -> ChunkRecord:
    """The chunk that trained ``dict_digest``: the FIRST chunk (stream
    order) carrying that digest — by the writer's construction, the
    first chunk of the dictionary's contig (store/writer.py
    _tag_first_of_contig)."""
    for rec in manifest.chunks:
        if rec.dict_digest == dict_digest:
            return rec
    raise HealError(
        f"dictionary {dict_digest[:16]}... is not referenced by any "
        "catalog row — a stale dicts/ file, nothing to rebuild"
    )


def recover_dict(root: str, manifest: StoreManifest, dict_digest: str,
                 replicas=(), origin_source=None) -> bytes:
    """Recover a missing/corrupt ``dicts/<digest>.zdict`` file in
    place: a digest-verified copy from a replica, else re-derivation
    from the origin (the dictionary is a pure function of its trainer
    chunk's raw payload — store/codec.py train_dict). Returns the
    dictionary bytes; raises :class:`HealError` when no route works."""
    errors: list[str] = []
    data = None
    for rep in replicas:
        cand = codecmod.dict_path(rep, dict_digest)
        try:
            with open(cand, "rb") as f:
                got = f.read()
        except OSError as e:
            errors.append(f"replica {rep!r}: {e}")
            continue
        if hashing.sha256_bytes(got) == dict_digest:
            data = got
            break
        errors.append(f"replica {rep!r}: dictionary bytes do not hash "
                      "to the content address")
    if data is None:
        if manifest.origin is None:
            raise HealError(
                "no replica holds the dictionary and the manifest "
                "records no origin"
                + (": " + "; ".join(errors) if errors else "")
            )
        trainer = _dict_trainer_record(manifest, dict_digest)
        try:
            raw = _raw_span_from_origin(trainer, manifest.origin,
                                        source=origin_source)
        except (OSError, ValueError) as e:
            raise HealError(
                f"origin re-derivation of the dictionary failed: {e}"
                + ("; " + "; ".join(errors) if errors else "")
            ) from e
        data = codecmod.train_dict(raw)
        if hashing.sha256_bytes(data) != dict_digest:
            raise HealError(
                "re-derived dictionary does not hash to "
                f"{dict_digest[:16]}... — the origin changed since "
                "compaction; re-compact the store"
            )
    path = codecmod.dict_path(root, dict_digest)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    tmp = path + f".heal.{os.getpid()}.{threading.get_ident()}"
    with open(tmp, "wb") as f:
        f.write(data)
    os.replace(tmp, path)
    return data


def _dict_bytes_for_heal(root: str, manifest: StoreManifest,
                         rec: ChunkRecord, replicas=(),
                         origin_source=None) -> bytes | None:
    """The dictionary an origin re-compression of ``rec`` needs —
    loaded from the store (digest-verified), else recovered through
    :func:`recover_dict`."""
    if rec.dict_digest is None:
        return None
    path = codecmod.dict_path(root, rec.dict_digest)
    try:
        with open(path, "rb") as f:
            data = f.read()
        if hashing.sha256_bytes(data) == rec.dict_digest:
            return data
    except OSError:
        pass
    return recover_dict(root, manifest, rec.dict_digest,
                        replicas=replicas, origin_source=origin_source)


def _install(root: str, rec: ChunkRecord, data: bytes, how: str) -> None:
    """Digest-check + tmp/rename the healed bytes into place."""
    got = hashing.sha256_bytes(data)
    if got != rec.digest:
        raise HealError(
            f"healed bytes from {how} hash to {got[:16]}..., not the "
            f"chunk's content address {rec.digest[:16]}... — refusing to "
            "install a different chunk under this name"
        )
    path = os.path.join(root, rec.filename())
    tmp = path + f".heal.{os.getpid()}.{threading.get_ident()}"
    with open(tmp, "wb") as f:
        f.write(data)
    os.replace(tmp, path)


def heal_chunk(root: str, manifest: StoreManifest, rec: ChunkRecord,
               replicas=(), origin_source=None) -> str:
    """Repair one chunk in place; returns how ("replica:<dir>" or
    "origin"). Raises :class:`HealError` when no route works. On
    success the chunk's quarantine entry (if any) is dropped and
    ``store.healed`` is counted."""
    with telemetry.span("store.heal", cat="store", digest=rec.digest[:16]):
        errors: list[str] = []
        for rep in replicas:
            cand = os.path.join(rep, rec.filename())
            try:
                with open(cand, "rb") as f:
                    data = f.read()
                _install(root, rec, data, how=f"replica {rep!r}")
            except (OSError, HealError) as e:
                errors.append(f"replica {rep!r}: {e}")
                continue
            how = f"replica:{rep}"
            break
        else:
            if manifest.origin is None:
                raise HealError(
                    "no replica holds the chunk and the manifest records "
                    "no origin (compacted before schema v2, or origin "
                    "recording disabled)"
                    + (": " + "; ".join(errors) if errors else "")
                )
            try:
                raw = _raw_span_from_origin(rec, manifest.origin,
                                            source=origin_source)
                # Re-compression with the chunk's recorded codec and
                # dictionary: the codec is byte-deterministic by
                # contract, so the stored bytes — and therefore the
                # digest _install checks — reproduce exactly.
                data = codecmod.compress(
                    rec.codec, raw,
                    _dict_bytes_for_heal(root, manifest, rec,
                                         replicas=replicas,
                                         origin_source=origin_source))
                _install(root, rec, data, how="origin re-compaction")
            except (OSError, ValueError) as e:
                raise HealError(
                    f"origin re-compaction failed: {e}"
                    + ("; " + "; ".join(errors) if errors else "")
                ) from e
            how = "origin"
    telemetry.count("store.healed")
    quarantine.remove(root, rec.digest)
    return how


def heal(root: str, replicas=(), verify_all: bool = False) -> dict:
    """Repair every damaged chunk in the store at ``root`` — the
    ``store heal`` CLI verb.

    Walks the quarantine ledger (plus, with ``verify_all``, a full
    re-hash of every chunk file against its content address) and runs
    :func:`heal_chunk` on each damaged chunk. Returns a report::

        {"checked": n, "damaged": n, "healed": [{digest, how}, ...],
         "failed": [{digest, error}, ...], "stale_cleared": n}

    The ledger is never trusted alone: a quarantined chunk whose file
    verifies clean (the operator restored it by hand) just clears its
    entry (reported with ``how="already-intact"``), and entries whose
    digest no longer appears in the manifest (the store was
    re-compacted since the incident) are cleared and counted as
    ``stale_cleared`` — leaving either would alarm on phantom chunks
    forever. A chunk healed from origin is re-compacted through ONE
    origin source shared across chunks (the origin stream is opened
    once).
    """
    manifest = StoreManifest.load(root)
    by_digest: dict[str, ChunkRecord] = {}
    for rec in manifest.chunks:
        by_digest.setdefault(rec.digest, rec)

    damaged: dict[str, ChunkRecord] = {}
    stale_cleared = 0
    intact: list[dict] = []
    for entry in quarantine.load(root):
        digest = entry.get("digest", "")
        rec = by_digest.get(digest)
        if rec is None:
            if quarantine.remove(root, digest):
                stale_cleared += 1
            continue
        # Never trust the ledger alone: an operator may have already
        # restored the file (the recovery path the quarantine error
        # names — content addressing needs no manifest surgery). A
        # chunk that verifies clean just clears its entry.
        try:
            if hashing.sha256_file(
                    os.path.join(root, rec.filename())) == digest:
                quarantine.remove(root, digest)
                intact.append({"digest": digest, "start": rec.start,
                               "stop": rec.stop,
                               "how": "already-intact"})
                continue
        except OSError:
            pass  # unreadable/missing: genuinely damaged
        damaged[rec.digest] = rec
    checked = len(damaged) + len(intact)
    if verify_all:
        for digest, rec in by_digest.items():
            if digest in damaged:
                continue
            checked += 1
            path = os.path.join(root, rec.filename())
            try:
                if hashing.sha256_file(path) == digest:
                    continue
            except OSError:
                pass
            damaged[digest] = rec

    origin_source = None
    if manifest.origin is not None and damaged:
        try:
            origin_source = build_origin_source(manifest.origin)
        except (OSError, ValueError):
            origin_source = None  # per-chunk heals will name the error

    healed, failed = list(intact), []
    for digest, rec in sorted(damaged.items(), key=lambda kv: kv[1].start):
        try:
            how = heal_chunk(root, manifest, rec, replicas=replicas,
                             origin_source=origin_source)
            healed.append({"digest": digest, "start": rec.start,
                           "stop": rec.stop, "how": how})
        except HealError as e:
            failed.append({"digest": digest, "start": rec.start,
                           "stop": rec.stop, "error": str(e)})
    return {"checked": checked, "damaged": len(damaged),
            "healed": healed, "failed": failed,
            "stale_cleared": stale_cleared}


def _copy_tree_chunks(src_root: str, dst_root: str) -> int:  # pragma: no cover
    """Convenience for tests/ops: copy every chunk file from one store
    into another (content addressing makes this safe — names can only
    collide on identical bytes). Returns the number copied."""
    from spark_examples_tpu.store.manifest import CHUNK_DIR

    src = os.path.join(src_root, CHUNK_DIR)
    dst = os.path.join(dst_root, CHUNK_DIR)
    os.makedirs(dst, exist_ok=True)
    n = 0
    for name in os.listdir(src):
        if not name.endswith(".bin"):
            continue
        if not os.path.exists(os.path.join(dst, name)):
            shutil.copy2(os.path.join(src, name), os.path.join(dst, name))
            n += 1
    return n

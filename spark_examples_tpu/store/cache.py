"""Tier 2 of the store read path: a bounded host-RAM decode cache.

The read path is tiered — disk (mmap of the stored chunk file, the
cold tier the OS page cache sits under) → this cache (the chunk's
DECODED form: the dense int8 decode at ~4x the packed bytes, or — for
compressed chunks on the packed transport — the inflated 2-bit
payload) → the consumer. Decoding is the per-read cost the packed +
compressed format trades disk/IO for; jobs that pass over the cohort
more than once (streaming refreshes, serve panel staging, repeated
range queries) pay it once per chunk instead of once per read, bounded
by ``max_bytes`` so a 40M-variant store cannot eat the host.

Accounting charges each entry at its **decoded** (in-RAM ndarray)
size, never the on-disk chunk size: once chunks compress ~4x, a bound
derived from stored bytes would admit ~4x the RAM it claims to — the
``--store-cache-mb`` knob bounds what the host actually holds.

Every get/put is accounted (``store.cache_hits`` / ``store.cache_misses``
counters, ``store.cache_bytes`` gauge) so a bench or a telemetry export
can state the hit rate instead of guessing it.
"""

from __future__ import annotations

import threading
from collections import OrderedDict

import numpy as np

from spark_examples_tpu.core import telemetry


class DecodeCache:
    """Thread-safe byte-bounded LRU of decoded chunks.

    Keys are ``(form, chunk_ordinal)`` tuples — ``("dense", i)`` for
    int8 decodes, ``("packed", i)`` for inflated 2-bit payloads: the
    two decoded forms of one chunk are distinct entries that must
    never collide on a bare ordinal. Values are frozen (read-only) so
    a cached chunk handed to two consumers can never be mutated under
    either, and charged at ``value.nbytes`` — the decoded in-RAM size.
    ``max_bytes=0`` disables storage entirely (every get misses — the
    knob's documented "no cache" setting). A single value larger than
    the bound is not stored (storing it would immediately evict
    everything else for a chunk that can never be joined by a second
    one).
    """

    def __init__(self, max_bytes: int):
        self.max_bytes = max(0, int(max_bytes))
        self._data: OrderedDict = OrderedDict()
        self._bytes = 0
        self._hits = 0
        self._misses = 0
        self._evictions = 0
        self._lock = threading.Lock()

    def __len__(self) -> int:
        with self._lock:
            return len(self._data)

    def peek(self, key) -> np.ndarray | None:
        """``get`` without accounting or LRU promotion — the readahead
        pool's "already resident?" probe (a background warmer consulting
        the cache must not inflate the consumer-facing hit/miss stats
        or reorder the eviction queue)."""
        with self._lock:
            return self._data.get(key)

    def get(self, key) -> np.ndarray | None:
        with self._lock:
            value = self._data.get(key)
            if value is not None:
                self._data.move_to_end(key)
                self._hits += 1
            else:
                self._misses += 1
        if value is not None:
            telemetry.count("store.cache_hits")
        else:
            telemetry.count("store.cache_misses")
        return value

    def put(self, key, value: np.ndarray) -> None:
        if self.max_bytes == 0 or value.nbytes > self.max_bytes:
            return
        frozen = np.asarray(value)
        frozen.setflags(write=False)
        with self._lock:
            old = self._data.pop(key, None)
            if old is not None:
                self._bytes -= old.nbytes
            self._data[key] = frozen
            self._bytes += frozen.nbytes
            while self._bytes > self.max_bytes:
                _, dropped = self._data.popitem(last=False)
                self._bytes -= dropped.nbytes
                self._evictions += 1
            nbytes = self._bytes
        telemetry.gauge_set("store.cache_bytes", float(nbytes))

    def clear(self) -> None:
        with self._lock:
            self._data.clear()
            self._bytes = 0
        telemetry.gauge_set("store.cache_bytes", 0.0)

    def stats(self) -> dict:
        """Accounting snapshot (hits/misses/evictions/resident bytes) —
        the numbers `bench.py --store` reports as the cache hit rate."""
        with self._lock:
            return {
                "hits": self._hits,
                "misses": self._misses,
                "evictions": self._evictions,
                "bytes": self._bytes,
                "entries": len(self._data),
                "max_bytes": self.max_bytes,
            }
